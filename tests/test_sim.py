"""Deterministic network simulator (rlo_tpu/transport/sim.py) and the
membership scenarios it proves (docs/DESIGN.md §8).

The simulator owns ALL delivery order, delay, drop, duplication, and
partition decisions from one seeded RNG, and engines take their clock
from virtual time — so every run (including heartbeat timeouts, ARQ
retransmits, op deadlines, and JOIN probe cadence) replays
bit-for-bit from the seed. The acceptance scenarios:

  - split-brain partition + heal converges to one membership view with
    exactly-once delivery;
  - a killed rank restarts mid-broadcast, rejoins with a fresh
    incarnation, and receives the replayed recent-broadcast log;
  - a proposer isolated by a partition gets FAILED + an ABORT flood,
    and its pid is resubmittable after heal;
  - same seed => byte-identical event schedule (digest equality);
  - a mixed-epoch chaos soak (dup + loss + partition + restart) shows
    zero duplicate pickups while the quarantine visibly drops stale
    frames.
"""

import logging

import pytest

from rlo_tpu.engine import EngineManager, ProgressEngine, ReqState
from rlo_tpu.transport.sim import (SCENARIO_KINDS, Scenario, SimViolation,
                                   SimWorld, fuzz_sweep, make_scenario)
from rlo_tpu.wire import Tag

logging.getLogger("rlo_tpu").setLevel(logging.ERROR)


ENGINE_KW = dict(failure_timeout=6.0, heartbeat_interval=1.0,
                 arq_rto=1.5, arq_max_retries=6, op_deadline=60.0)


def build(ws=4, seed=0, **world_kw):
    world = SimWorld(ws, seed=seed, **world_kw)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              clock=world.clock, **ENGINE_KW)
               for r in range(ws)]
    return world, mgr, engines


def run_until(world, mgr, engines, t, sink=None):
    while world.now < t:
        world.step()
        mgr.progress_all()
        for r, e in enumerate(engines):
            if e is None:
                continue
            while (m := e.pickup_next()) is not None:
                if sink is not None:
                    sink.setdefault(r, []).append(m)


# ---------------------------------------------------------------------------
# Determinism: the replay contract
# ---------------------------------------------------------------------------

class TestDeterminism:
    def test_same_seed_byte_identical_schedule(self):
        a = make_scenario("partition", 3).run()
        b = make_scenario("partition", 3).run()
        assert a["digest"] == b["digest"]
        assert a["events"] == b["events"]
        assert a["epochs"] == b["epochs"]
        assert a["delivered"] == b["delivered"]

    def test_different_seeds_differ(self):
        a = make_scenario("partition", 0).run()
        b = make_scenario("partition", 1).run()
        assert a["digest"] != b["digest"]

    def test_virtual_time_only_advances_via_step(self):
        world, mgr, engines = build()
        t = world.now
        for _ in range(50):
            mgr.progress_all()  # polling never advances time
        assert world.now == t
        world.step()
        assert world.now > t

    def test_channel_fifo_preserved(self):
        world = SimWorld(2, seed=9, min_delay=0.001, max_delay=0.5)
        tr = world.transport(0)
        for i in range(64):
            tr.isend(1, int(Tag.DATA), bytes([i]))
        got = []
        while not world.quiescent():
            world.step()
            while (m := world.transport(1).poll()) is not None:
                got.append(m[2][0])
        assert got == list(range(64))

    def test_violation_carries_seed_and_replay_recipe(self):
        sc = Scenario(world_size=4, seed=77)
        with pytest.raises(SimViolation) as ei:
            sc._fail("synthetic")
        assert "seed 77" in str(ei.value)
        assert "replay: Scenario(" in str(ei.value)


# ---------------------------------------------------------------------------
# Acceptance scenarios (docs/DESIGN.md §8)
# ---------------------------------------------------------------------------

class TestScenarios:
    def test_split_brain_heal_converges_exactly_once(self):
        res = make_scenario("partition", 0).run()
        ws = 4
        want = tuple(range(ws))
        for r, view in res["views"].items():
            assert view == want, f"rank {r} diverged: {view}"
        # both sides declared each other dead, then healed by mutual
        # rejoin — admissions actually happened
        assert res["rejoins"] > 0
        assert len(set(res["epochs"].values())) == 1
        # exactly-once was checked inside run(); delivered is per-rank
        for r in range(ws):
            assert len(res["delivered"][r]) == \
                len(set(res["delivered"][r]))

    def test_restart_mid_broadcast_receives_replayed_log(self):
        victim = 3
        data_while_dead = b"sent-while-3-was-down"
        world, mgr, engines = build(seed=21)
        incarnation = 0
        sink = {}
        run_until(world, mgr, engines, 10.0, sink)
        world.kill_rank(victim)
        engines[victim].cleanup()
        engines[victim] = None
        run_until(world, mgr, engines, 20.0, sink)
        engines[0].bcast(data_while_dead)  # mid-broadcast restart
        run_until(world, mgr, engines, 25.0, sink)
        world.restart_rank(victim)
        incarnation += 1
        engines[victim] = ProgressEngine(
            world.transport(victim), manager=mgr, clock=world.clock,
            incarnation=incarnation, **ENGINE_KW)
        assert engines[victim]._awaiting_welcome  # joiner mode
        run_until(world, mgr, engines, 120.0, sink)
        assert not engines[victim]._awaiting_welcome
        assert engines[victim].rejoins == 1
        # the admitting proposer replayed its recent-broadcast log:
        # the frame broadcast while rank 3 was DEAD reached its new
        # incarnation
        got = [(m.origin, m.data) for m in sink.get(victim, [])
               if m.type == int(Tag.BCAST)]
        assert (0, data_while_dead) in got
        assert len(got) == len(set(got))  # and exactly once
        # membership converged to the full world on every rank
        for e in engines:
            assert sorted(e._alive) == [0, 1, 2, 3]

    def test_isolated_proposer_fails_aborts_and_resubmits(self):
        world, mgr, engines = build(seed=5)
        # the deadline must fire before the detector discounts every
        # unreachable voter (a sole survivor legitimately completes)
        for e in engines:
            e.op_deadline = 4.0
        sink = {}
        run_until(world, mgr, engines, 5.0, sink)
        world.partition([[0], [1, 2, 3]])
        engines[0].submit_proposal(b"doomed", pid=42)
        run_until(world, mgr, engines, 20.0, sink)
        p = engines[0].my_own_proposal
        assert p.state == ReqState.FAILED
        assert engines[0].ops_failed >= 1
        world.heal()
        run_until(world, mgr, engines, 150.0, sink)
        for e in engines:
            assert sorted(e._alive) == [0, 1, 2, 3]
        # the ABORT flood unparked the relays: the majority side
        # received the abort notice for pid 42
        for r in (1, 2, 3):
            assert 42 in [m.pid for m in sink.get(r, [])
                          if m.type == int(Tag.ABORT)]
        # the pid is free again and resolves on the healed membership
        engines[0].submit_proposal(b"second life", pid=42)
        run_until(world, mgr, engines, 220.0, sink)
        assert engines[0].my_own_proposal.state == ReqState.COMPLETED
        assert engines[0].my_own_proposal.vote == 1

    def test_mixed_epoch_soak_zero_duplicate_pickups(self):
        # dup injection + loss + partition + restart: stale-epoch
        # frames from pre-partition lives mix with post-admission
        # traffic, and the quarantine (not luck) keeps pickup
        # exactly-once — Scenario.run() raises on any duplicate
        total_quarantined = 0
        for seed in range(3):
            sc = make_scenario("mixed", seed)
            sc.dup_p = 0.05
            res = sc.run()
            total_quarantined += res["quarantined"]
        assert total_quarantined > 0  # the quarantine actually fired


# ---------------------------------------------------------------------------
# Fuzz sweeps (check.sh runs the 25-seed sweep; `slow` runs 500)
# ---------------------------------------------------------------------------

class TestFuzz:
    def test_fuzz_sweep_smoke(self):
        res = fuzz_sweep(range(2))
        assert res["runs"] == 2 * len(SCENARIO_KINDS)
        assert res["rejoins"] > 0

    @pytest.mark.slow
    def test_fuzz_sweep_500(self):
        # the long fixed-seed sweep: 125 seeds x 4 scenario kinds =
        # 500 fully deterministic runs; any property violation raises
        # SimViolation carrying the seed + replay recipe
        res = fuzz_sweep(range(125))
        assert res["runs"] == 500
