"""Paged KV cache + chunked prefill + radix prefix sharing
(docs/DESIGN.md §12) — the oracle is unchanged from test_serve: every
request's tokens equal its dense ``generate`` EXACTLY, for any stream
shape, because pages, chunking, prefix sharing and COW are layout and
scheduling changes, never numerics changes. On top of the parity
oracle: allocator/trie unit semantics, exhaustion backpressure,
page-leak freedom, interpret-mode parity for the paged pallas
kernels, and the fabric's kill-mid-decode exactly-once story over the
paged ModelBackend."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rlo_tpu.models.generate import generate
from rlo_tpu.models.serve import DecodeServer
from rlo_tpu.models.transformer import TransformerConfig, init_params
from rlo_tpu.serving.pages import (PageAllocator, PageError,
                                   PrefixTrie)
from rlo_tpu.utils.metrics import Registry

CFG = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                        d_ff=64, dtype="float32")


@pytest.fixture(scope="module")
def setup():
    return init_params(jax.random.PRNGKey(0), CFG)


def dense_oracle(params, cfg, prompt, max_new):
    out = generate(params, jnp.asarray(prompt, jnp.int32)[None, :],
                   cfg, max_new=max_new)
    return np.asarray(out)[0]


# ---------------------------------------------------------------------------
# allocator + trie units (rlo_tpu/serving/pages.py)
# ---------------------------------------------------------------------------

def test_allocator_lifecycle_and_errors():
    a = PageAllocator(5, 8)
    assert a.free_pages == 4           # page 0 is the null page
    p1, p2 = a.alloc(), a.alloc()
    assert (p1, p2) == (1, 2)          # LIFO hands out 1, 2, ...
    a.retain(p1)
    assert a.release(p1) is False      # still referenced
    assert a.release(p1) is True
    a.release(p2)
    assert a.free_pages == 4 and a.pages_in_use == 0
    # most recently freed is reused first
    assert a.alloc() == p2
    with pytest.raises(PageError):
        a.release(0)                   # the null page is untouchable
    with pytest.raises(PageError):
        a.retain(4)                    # free page
    a.release(p2)
    with pytest.raises(PageError):
        a.release(p2)                  # double free
    # exhaustion returns None and counts
    for _ in range(4):
        assert a.alloc() is not None
    assert a.alloc() is None and a.alloc_failures == 1


def test_trie_match_register_evict():
    a = PageAllocator(10, 4)
    t = PrefixTrie(4)
    prompt = list(range(10))           # 2 full pages + 2-token tail
    pages = [a.alloc() for _ in range(3)]
    assert t.register(prompt, 10, pages, a) == 3
    assert a.refcount(pages[0]) == 2   # trie holds its own reference
    # full prompt (and beyond) matches all three pages
    m, cov = t.match(prompt + [99])
    assert m == pages and cov == 10
    # a full-page-only prefix matches just the full pages
    m, cov = t.match(list(range(8)) + [77])
    assert m == pages[:2] and cov == 8
    # divergent first page: no match
    assert t.match([5, 1, 2, 3]) == ([], 0)
    # first-wins: re-registering identical chunks adds nothing
    assert t.register(prompt, 10, [7, 8, 9], a) == 0
    # release the request's own references; trie keeps pages alive
    for p in pages:
        a.release(p)
    assert a.pages_in_use == 3 == t.entries
    # eviction drops trie-only pages, leaf-most first
    assert t.evict(a, 99) == 3
    assert a.pages_in_use == 0 and t.entries == 0


def test_trie_partial_tail_longest_match():
    a = PageAllocator(10, 4)
    t = PrefixTrie(4)
    short, long_ = [1, 2, 3, 4, 5], [1, 2, 3, 4, 5, 6, 7]
    t.register(short, 5, [a.alloc(), a.alloc()], a)
    t.register(long_, 7, [t.match(short)[0][0], a.alloc()], a)
    # the longer stored partial wins when both prefix the prompt
    m, cov = t.match([1, 2, 3, 4, 5, 6, 7, 8])
    assert cov == 7
    m, cov = t.match([1, 2, 3, 4, 5, 9])
    assert cov == 5


# ---------------------------------------------------------------------------
# paged server == dense generate (the parity oracle)
# ---------------------------------------------------------------------------

def test_paged_stream_matches_dense(setup):
    """8 mixed requests through 3 slots over 8-token pages: prompts
    span 1-4 pages, slots are reused, and every result equals the
    dense generate bit-for-bit."""
    params = setup
    rng = np.random.default_rng(0)
    srv = DecodeServer(params, CFG, n_slots=3, max_len=96,
                       round_len=5, paged=True, page_size=8)
    reqs = []
    for _ in range(8):
        plen = int(rng.integers(3, 30))
        max_new = int(rng.integers(1, 20))
        prompt = rng.integers(0, CFG.vocab, (plen,))
        reqs.append((prompt, max_new))
        srv.submit(prompt, max_new)
    outs = srv.run()
    assert len(outs) == 8
    for (prompt, max_new), got in zip(reqs, outs):
        np.testing.assert_array_equal(
            got, dense_oracle(params, CFG, prompt, max_new))
    # everything was released: only the radix cache still holds pages
    assert srv.allocator.pages_in_use == srv.trie.entries


@pytest.mark.parametrize("variant", ["gqa_rope", "int8"])
def test_paged_variants(setup, variant):
    cfg = (dataclasses.replace(CFG, n_kv_heads=2, pos_encoding="rope")
           if variant == "gqa_rope"
           else dataclasses.replace(CFG, kv_cache_dtype="int8"))
    params = init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.default_rng(3)
    srv = DecodeServer(params, cfg, n_slots=2, max_len=64,
                       round_len=3, paged=True, page_size=8)
    reqs = [(rng.integers(0, cfg.vocab, (int(rng.integers(3, 20)),)),
             int(rng.integers(2, 10))) for _ in range(5)]
    for p, m in reqs:
        srv.submit(p, m)
    outs = srv.run()
    for (p, m), got in zip(reqs, outs):
        np.testing.assert_array_equal(got,
                                      dense_oracle(params, cfg, p, m))


def test_paged_eos_and_late_submission(setup):
    """eos early-exit frees pages mid-stream and late submissions
    join the running pool — both with exact dense parity."""
    params = setup
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, CFG.vocab, (7,))
    dense = dense_oracle(params, CFG, prompt, 16)
    eos = int(dense[3])
    srv = DecodeServer(params, CFG, n_slots=1, max_len=64,
                       round_len=4, paged=True, page_size=8)
    srv.submit(prompt, 16, eos_id=eos)
    srv.step_round()
    late = rng.integers(0, CFG.vocab, (6,))
    srv.submit(late, 5)
    outs = srv.run()
    want = dense[:list(dense).index(eos) + 1]
    np.testing.assert_array_equal(outs[0], want)
    np.testing.assert_array_equal(outs[1],
                                  dense_oracle(params, CFG, late, 5))


def test_prefix_shared_admission_matches_dense(setup):
    """Requests sharing a 16-token system prefix map the same
    physical pages (radix reuse: prefill skipped for the shared full
    pages) and still decode bit-identically to dense."""
    params = setup
    rng = np.random.default_rng(4)
    reg = Registry()
    srv = DecodeServer(params, CFG, n_slots=2, max_len=64,
                       round_len=4, paged=True, page_size=8,
                       metrics=reg)
    sys_p = rng.integers(0, CFG.vocab, (16,))
    reqs = [(np.concatenate([sys_p,
                             rng.integers(0, CFG.vocab, (t,))]), 8)
            for t in (5, 9, 3)]
    for p, m in reqs:
        srv.submit(p, m)
    outs = srv.run()
    for (p, m), got in zip(reqs, outs):
        np.testing.assert_array_equal(got,
                                      dense_oracle(params, CFG, p, m))
    snap = srv.stats()
    assert snap["counters"]["serve.prefix_hits"] >= 1
    # at least the two full prefix pages were served from the cache
    assert snap["counters"]["serve.prefix_tokens_shared"] >= 16


def test_exact_duplicate_prompt_cow(setup):
    """An exact resubmission takes the full radix hit (only the last
    prompt token recomputed) and its first decode write lands in a
    shared page — the copy-on-write path — with identical tokens."""
    params = setup
    rng = np.random.default_rng(5)
    reg = Registry()
    srv = DecodeServer(params, CFG, n_slots=1, max_len=64,
                       round_len=4, paged=True, page_size=8,
                       metrics=reg)
    prompt = rng.integers(0, CFG.vocab, (13,))
    srv.submit(prompt, 6)
    srv.run()
    srv.submit(prompt.copy(), 9)   # resubmission, different budget
    outs = srv.run()
    np.testing.assert_array_equal(
        outs[1], dense_oracle(params, CFG, prompt, 9))
    snap = srv.stats()
    assert snap["counters"]["serve.prefix_hits"] == 1
    assert snap["counters"]["serve.cow_copies"] >= 1
    # the duplicate's whole prompt except the last token was shared
    assert snap["counters"]["serve.prefix_tokens_shared"] == 12


def test_allocator_exhaustion_backpressure(setup):
    """A pool too small for every request at once: admission stalls
    (head-of-line, counted), decode drains, freed pages admit the
    rest — every request still completes with dense parity and no
    page leaks."""
    params = setup
    rng = np.random.default_rng(6)
    reg = Registry()
    # 8 usable pages; each request spans 4 (plen 8 + max_new 24 over
    # 8-token pages), so only two can ever be resident
    srv = DecodeServer(params, CFG, n_slots=3, max_len=64,
                       round_len=4, paged=True, page_size=8,
                       n_pages=9, metrics=reg)
    reqs = [(rng.integers(0, CFG.vocab, (8,)), 24) for _ in range(4)]
    for p, m in reqs:
        srv.submit(p, m)
    outs = srv.run()
    for (p, m), got in zip(reqs, outs):
        np.testing.assert_array_equal(got,
                                      dense_oracle(params, CFG, p, m))
    assert reg.snapshot()["counters"]["serve.admission_stalls"] >= 1
    assert srv.allocator.pages_in_use == srv.trie.entries


def test_oversized_request_rejected(setup):
    srv = DecodeServer(setup, CFG, n_slots=1, max_len=64,
                       round_len=4, paged=True, page_size=8,
                       n_pages=5)
    with pytest.raises(ValueError, match="max_len"):
        srv.submit(np.zeros(60, np.int32), 20)
    with pytest.raises(ValueError, match="pool"):
        # fits max_len but spans more pages than the pool holds
        srv.submit(np.zeros(30, np.int32), 20)
    # an empty prompt has no last token whose logits could seed the
    # first generation — rejected cleanly in BOTH modes (the paged
    # prefill would otherwise wedge at next=-1 forever)
    with pytest.raises(ValueError, match="empty"):
        srv.submit(np.zeros(0, np.int32), 4)
    dense = DecodeServer(setup, CFG, n_slots=1, max_len=64,
                         round_len=4, prompt_buckets=(8,))
    with pytest.raises(ValueError, match="empty"):
        dense.submit(np.zeros(0, np.int32), 4)


def test_prefill_budget_interleaves_chunks(setup):
    """A finite prefill budget spreads a long prompt's chunks across
    rounds (decode of other slots proceeds between them) without
    changing any tokens."""
    params = setup
    rng = np.random.default_rng(7)
    reg = Registry()
    srv = DecodeServer(params, CFG, n_slots=2, max_len=96,
                       round_len=3, paged=True, page_size=8,
                       prefill_budget=8, metrics=reg)
    short = (rng.integers(0, CFG.vocab, (4,)), 12)
    long_ = (rng.integers(0, CFG.vocab, (29,)), 6)   # 4 chunks
    srv.submit(short[0], short[1])
    srv.submit(long_[0], long_[1])
    outs = srv.run()
    for (p, m), got in zip((short, long_), outs):
        np.testing.assert_array_equal(got,
                                      dense_oracle(params, CFG, p, m))
    assert reg.snapshot()["counters"]["serve.prefill_chunks"] >= 4


def test_clipped_rounds_beat_dense_slot_steps(setup):
    """Budget-clipped rounds: the paged server spends strictly fewer
    slot-steps than the fixed-round dense server on a mixed-budget
    stream (the serve_bench poisson win, in miniature)."""
    params = setup
    rng = np.random.default_rng(8)
    reqs = [(rng.integers(0, CFG.vocab, (int(rng.integers(3, 12)),)),
             int(rng.integers(2, 15))) for _ in range(6)]
    dense = DecodeServer(params, CFG, n_slots=2, max_len=64,
                         round_len=5, prompt_buckets=(8, 16))
    paged = DecodeServer(params, CFG, n_slots=2, max_len=64,
                         round_len=5, paged=True, page_size=8)
    for p, m in reqs:
        dense.submit(p, m)
        paged.submit(p, m)
    outs_d = dense.run()
    outs_p = paged.run()
    for a, b in zip(outs_d, outs_p):
        np.testing.assert_array_equal(a, b)
    assert paged.steps_run < dense.steps_run


def test_paged_telemetry_surface(setup):
    """The §12 page-pool telemetry flows through the PR-2 registry:
    pages gauges, prefix/COW/chunk counters, and the allocator block
    in stats()."""
    params = setup
    rng = np.random.default_rng(9)
    reg = Registry()
    srv = DecodeServer(params, CFG, n_slots=2, max_len=64,
                       round_len=4, paged=True, page_size=8,
                       metrics=reg)
    p = rng.integers(0, CFG.vocab, (10,))
    srv.submit(p, 6)
    srv.run()
    srv.submit(p.copy(), 4)   # radix hit against the finished run
    srv.run()
    snap = srv.stats()
    assert snap["gauges"]["serve.pages_in_use"] == \
        srv.allocator.pages_in_use
    assert snap["gauges"]["serve.pages_free"] == \
        srv.allocator.free_pages
    for key in ("serve.prefix_hits", "serve.cow_copies",
                "serve.prefill_chunks"):
        assert key in snap["counters"]
    pages = snap["pages"]
    assert pages["page_size"] == 8
    assert pages["pages_in_use"] + pages["pages_free"] == \
        srv.n_pages - 1
    assert pages["trie_entries"] == srv.trie.entries


# ---------------------------------------------------------------------------
# paged pallas kernels (interpret mode — the TPU path's numerics twin)
# ---------------------------------------------------------------------------

def test_write_kv_page_row_kernel_matches_scatter():
    from rlo_tpu.pallas.decode import write_kv_page_row
    rng = np.random.default_rng(0)
    P, nkv, d, ps = 6, 2, 64, 128
    pool = jnp.asarray(rng.standard_normal((P, nkv, d, ps)),
                       jnp.float32)
    row = jnp.asarray(rng.standard_normal((3, nkv, d)), jnp.float32)
    page = jnp.asarray([2, 0, 5], jnp.int32)
    off = jnp.asarray([17, ps, 3], jnp.int32)   # ps = drop sentinel
    got = np.asarray(write_kv_page_row(pool, row, page, off,
                                       interpret=True))
    want = np.asarray(pool).copy()
    want[2, :, :, 17] = row[0]
    want[5, :, :, 3] = row[2]                   # row 1 dropped
    np.testing.assert_array_equal(got, want)


def test_write_kv_page_block_kernel_matches_slice():
    from rlo_tpu.pallas.decode import write_kv_page_block
    rng = np.random.default_rng(1)
    P, nkv, d, ps = 6, 2, 64, 128
    pool = jnp.asarray(rng.standard_normal((P, nkv, d, ps)),
                       jnp.float32)
    rows = jnp.asarray(rng.standard_normal((nkv, d, 32)), jnp.float32)
    got = np.asarray(write_kv_page_block(pool, rows, 4, 90, 20,
                                         interpret=True))
    want = np.asarray(pool).copy()
    want[4, :, :, 90:110] = np.asarray(rows)[:, :, :20]  # pads dropped
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("T", [1, 4])
def test_paged_flash_decode_matches_gather_einsum(T):
    from rlo_tpu.models.generate import _attend_cache_block
    from rlo_tpu.models.paged import paged_view
    from rlo_tpu.pallas.decode import paged_flash_decode
    rng = np.random.default_rng(2)
    P, nkv, d, ps, b, mp, nh = 7, 2, 64, 128, 3, 4, 4
    kp = jnp.asarray(rng.standard_normal((P, nkv, d, ps)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((P, nkv, d, ps)), jnp.float32)
    table = jnp.asarray(rng.integers(0, P, (b, mp)), jnp.int32)
    pos0 = jnp.asarray([200, 37, 410], jnp.int32)
    q = jnp.asarray(rng.standard_normal((b, T, nh, d)), jnp.float32)
    got = paged_flash_decode(q, kp, vp, table, pos0, 0.125,
                             interpret=True)
    kg, vg, _, _ = paged_view({"k": kp, "v": vp}, table)
    pos_q = pos0[:, None] + jnp.arange(T)[None, :]
    want = _attend_cache_block(q, kg, vg, pos_q, 0.125,
                               use_flash=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_paged_flash_decode_int8_scales():
    from rlo_tpu.models.generate import (_attend_cache_block,
                                         _quantize_kv)
    from rlo_tpu.models.paged import paged_view
    from rlo_tpu.pallas.decode import paged_flash_decode
    rng = np.random.default_rng(3)
    P, nkv, d, ps, b, mp, nh = 5, 2, 64, 128, 2, 3, 4
    kf = jnp.asarray(rng.standard_normal((P, nkv, ps, d)), jnp.float32)
    vf = jnp.asarray(rng.standard_normal((P, nkv, ps, d)), jnp.float32)
    kq, ks = _quantize_kv(kf)
    vq, vs = _quantize_kv(vf)
    kq, vq = kq.transpose(0, 1, 3, 2), vq.transpose(0, 1, 3, 2)
    table = jnp.asarray(rng.integers(0, P, (b, mp)), jnp.int32)
    pos0 = jnp.asarray([150, 40], jnp.int32)
    q = jnp.asarray(rng.standard_normal((b, 1, nh, d)), jnp.float32)
    got = paged_flash_decode(q, kq, vq, table, pos0, 0.125, ks, vs,
                             interpret=True)
    kg, vg, ksg, vsg = paged_view(
        {"k": kq, "v": vq, "ks": ks, "vs": vs}, table)
    want = _attend_cache_block(q, kg, vg, pos0[:, None], 0.125,
                               k_scale=ksg, v_scale=vsg,
                               use_flash=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-2, atol=3e-2)


# ---------------------------------------------------------------------------
# the paged stub backend + fabric scenarios (docs/DESIGN.md §11+§12)
# ---------------------------------------------------------------------------

def test_paged_stub_backend_accounting():
    from rlo_tpu.serving.backend import PagedStubBackend, stub_tokens
    be = PagedStubBackend(n_slots=3, round_len=8, n_pages=9,
                          page_size=8)
    # three 4-page requests through an 8-page pool: the third has a
    # slot but no pages — head-of-line backpressure
    keys = ["a", "b", "c"]
    for k in keys:
        be.submit(k, (1, 2, 3, 4, 5, 6, 7, 8), 24)
    done = {}
    for _ in range(30):
        for k, toks in be.step_round():
            done[k] = toks
        if not be.has_work():
            break
    assert set(done) == set(keys)
    for k in keys:
        assert done[k] == stub_tokens((1, 2, 3, 4, 5, 6, 7, 8), 24)
    assert be.stalls >= 1          # backpressure actually happened
    assert be.prefix_hits >= 1     # identical prompts share pages
    # drained: only the radix cache still references pages
    assert be.alloc.pages_in_use == be.trie.entries
    st = be.stats()
    assert st["backend"] == "paged_stub" and "pages" in st


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fabric_paged_scenario_kind(seed):
    """The fabric_paged chaos shape: kill mid-decode over paged stub
    backends with a tight pool and shared-prefix traffic — the
    scenario's own property checks (exactly-once, oracle tokens,
    drained, page-leak-free) are the assertions."""
    from rlo_tpu.transport.sim import make_scenario
    res = make_scenario("fabric_paged", seed).run()
    assert res["submitted"] > 0
    assert res["requeues"] >= 1    # the kill actually orphaned work


def test_fabric_kill_paged_model_backend_exactly_once(setup):
    """3-rank fabric over the REAL paged DecodeServer: the owner dies
    mid-decode, the re-queued request re-prefills (radix cache cold on
    the survivor) and completes exactly once with oracle tokens."""
    from rlo_tpu.engine import EngineManager, ProgressEngine
    from rlo_tpu.serving.backend import ModelBackend
    from rlo_tpu.serving.fabric import DecodeFabric
    from rlo_tpu.transport.sim import SimWorld

    params = setup
    n_ranks = 3
    world = SimWorld(n_ranks, seed=0)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              clock=world.clock, failure_timeout=6.0,
                              heartbeat_interval=1.0, arq_rto=1.5,
                              arq_max_retries=6, op_deadline=20.0)
               for r in range(n_ranks)]
    fabrics = [DecodeFabric(
        engines[r],
        ModelBackend(DecodeServer(params, CFG, n_slots=2, max_len=64,
                                  round_len=4, paged=True,
                                  page_size=8)),
        decode_interval=1.0) for r in range(n_ranks)]
    rng = np.random.default_rng(1)
    prompt = tuple(int(t) for t in rng.integers(0, CFG.vocab, (6,)))
    rid = fabrics[1].submit(prompt, 14)
    live = {0, 1, 2}
    killed = False
    while world.now < 90.0:
        if not killed and world.now >= 2.5:
            killed = True
            world.kill_rank(0)
            engines[0].cleanup()
            live.discard(0)
        world.step()
        mgr.progress_all()
        for r in sorted(live):
            fabrics[r].pump()
        if killed and all(fabrics[r].result(rid) is not None
                          for r in live):
            break
    assert killed
    want = tuple(int(t) for t in dense_oracle(params, CFG, prompt, 14))
    for r in sorted(live):
        assert fabrics[r].result(rid) == want, f"rank {r} diverged"
    # exactly-once client delivery despite the re-queue
    for r in sorted(live):
        assert fabrics[r].completions.count(rid) == 1


# ---------------------------------------------------------------------------
# the ARQ due-heap gate (ROADMAP item 2 starter, engine.py)
# ---------------------------------------------------------------------------

def test_arq_due_heap_gates_scan_and_preserves_retransmit():
    """The due-list gate: before the earliest deadline the tick is a
    pure heap peek (no retransmits); past it, the sweep fires exactly
    as before; an ACK turns heap entries stale and they are popped
    lazily without a scan."""
    from rlo_tpu.engine import EngineManager, ProgressEngine
    from rlo_tpu.transport.loopback import LoopbackWorld

    clock = [0.0]
    world = LoopbackWorld(2, latency=0, seed=3)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              arq_rto=1.0, clock=lambda: clock[0])
               for r in range(2)]
    e = engines[0]
    world.drop_next(0, 1)              # lose the first frame 0 -> 1
    e.bcast(b"hello")
    assert e.arq_unacked() >= 1 and len(e._arq_due) >= 1
    # not due: the gate short-circuits, nothing retransmitted
    clock[0] = 0.5
    e._arq_tick()
    assert e.arq_retransmits == 0
    # due: the sweep fires
    clock[0] = 1.5
    e._arq_tick()
    assert e.arq_retransmits >= 1
    # drain: ACKs flow, queues empty, stale heap entries get popped
    for _ in range(50):
        mgr.progress_all()
        if e.arq_unacked() == 0:
            break
    assert e.arq_unacked() == 0
    clock[0] = 10.0
    e._arq_tick()                      # pops stale entries, no sweep
    assert e._arq_wake(clock[0]) is False
    for eng in engines:
        eng.cleanup()
