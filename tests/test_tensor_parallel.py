"""Tensor parallelism: Megatron-style head/hidden sharding on the
flagship transformer (net-new capability; the reference has no model
code or parallelism strategies, SURVEY.md §5).

Oracle: a tp-sharded forward/train-step must match the unsharded
single-device computation on identical params — tensor parallelism is
an implementation detail, not a model change.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from rlo_tpu.models.transformer import (TransformerConfig, forward,
                                        init_params, loss_fn, param_pspecs,
                                        train_step)
from rlo_tpu.parallel.mesh import make_mesh, shard_jit

CFG = TransformerConfig(vocab=61, d_model=64, n_heads=8, n_layers=2,
                        d_ff=128, dtype="float32")


def _data(cfg=CFG, batch=2, seq=16):
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab, (batch, seq)),
                         jnp.int32)
    return params, tokens


class TestForwardParity:
    @pytest.mark.parametrize("tp", [2, 4, 8])
    def test_tp_forward_matches_unsharded(self, tp):
        params, tokens = _data()
        ref = forward(params, tokens, CFG)
        mesh = make_mesh((tp,), ("tp",))
        f = shard_jit(
            lambda p, t: forward(p, t, CFG, tp_axis="tp"),
            mesh, (param_pspecs(CFG, "tp"), P()), P())
        out = f(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_tp_ring_allreduce_variant(self):
        """The framework's manual ring allreduce in the tensor-parallel
        position. A ppermute-ring result cannot be TYPED invariant under
        vma (only psum is), so this inference-only variant runs with
        check_vma=False — numerics still must match exactly."""
        params, tokens = _data()
        ref = forward(params, tokens, CFG)
        mesh = make_mesh((4,), ("tp",))
        f = shard_jit(
            lambda p, t: forward(p, t, CFG, tp_axis="tp",
                                 tp_algorithm="ring"),
            mesh, (param_pspecs(CFG, "tp"), P()), P(), check_vma=False)
        np.testing.assert_allclose(np.asarray(f(params, tokens)),
                                   np.asarray(ref), rtol=2e-4, atol=2e-4)

    def test_tp_loss_identical_on_all_shards(self):
        params, tokens = _data()
        mesh = make_mesh((4,), ("tp",))
        f = shard_jit(
            lambda p, t: loss_fn(p, t, CFG, tp_axis="tp")[None],
            mesh, (param_pspecs(CFG, "tp"), P()), P("tp"))
        losses = np.asarray(f(params, tokens))
        assert losses.shape == (4,)
        np.testing.assert_allclose(losses, losses[0], rtol=1e-5)


class TestTrainParity:
    def test_tp_train_step_matches_unsharded(self):
        params, tokens = _data()
        ref_params, ref_loss = jax.jit(
            lambda p, t: train_step(p, t, CFG, lr=1e-2))(params, tokens)
        mesh = make_mesh((4,), ("tp",))
        specs = param_pspecs(CFG, "tp")
        step = shard_jit(
            lambda p, t: train_step(p, t, CFG, lr=1e-2, tp_axis="tp"),
            mesh, (specs, P()), (specs, P()))
        new_params, loss = step(params, tokens)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        for (ka, a), (kb, b) in zip(
                jax.tree_util.tree_flatten_with_path(new_params)[0],
                jax.tree_util.tree_flatten_with_path(ref_params)[0]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4,
                err_msg=jax.tree_util.keystr(ka))

    def test_dp_sp_tp_combined_mesh(self):
        """The full 3-D mesh: (dp, sp, tp) = (2, 2, 2) on 8 devices."""
        cfg = TransformerConfig(vocab=61, d_model=64, n_heads=4,
                                n_layers=2, d_ff=128, dtype="float32")
        params = init_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(1)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab, (4, 16)),
                             jnp.int32)
        ref_params, ref_loss = jax.jit(
            lambda p, t: train_step(p, t, cfg, lr=1e-2))(params, tokens)

        mesh = make_mesh((2, 2, 2), ("dp", "sp", "tp"))
        specs = param_pspecs(cfg, "tp")
        step = shard_jit(
            lambda p, t: train_step(p, t, cfg, lr=1e-2, sp_axis="sp",
                                    dp_axis="dp", tp_axis="tp"),
            mesh, (specs, P("dp", "sp")), (specs, P()))
        new_params, loss = step(params, tokens)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
        for (ka, a), (kb, b) in zip(
                jax.tree_util.tree_flatten_with_path(new_params)[0],
                jax.tree_util.tree_flatten_with_path(ref_params)[0]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4,
                err_msg=jax.tree_util.keystr(ka))


class TestSpecs:
    def test_param_pspecs_structure_matches_params(self):
        params = init_params(jax.random.PRNGKey(0), CFG)
        specs = param_pspecs(CFG, "tp")
        assert (jax.tree_util.tree_structure(params)
                == jax.tree_util.tree_structure(
                    specs, is_leaf=lambda x: isinstance(x, P)))

    def test_uneven_heads_rejected(self):
        cfg = TransformerConfig(vocab=16, d_model=24, n_heads=3,
                                n_layers=1, d_ff=64, dtype="float32")
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jnp.zeros((1, 8), jnp.int32)
        mesh = make_mesh((2,), ("tp",))
        with pytest.raises(AssertionError, match="divide"):
            shard_jit(lambda p, t: forward(p, t, cfg, tp_axis="tp"),
                      mesh, (param_pspecs(cfg, "tp"), P()), P())(
                          params, tokens)
