"""rlo-scope cost ledgers vs rlo-prover P2 (docs/DESIGN.md §21).

The ledger module replays the committed schedule generators with the
same token algebra rlo-prover P2 proves them with, additionally
recording per-step (src -> dst, bytes, cumulative mask) edges.  These
tests pin the three contracts the tentpole rests on:

  1. **Algebra parity**: for every schedule family and every n <= 64
     (power-of-2 only where the schedule requires it), the ledger's
     final token-algebra state equals the matching P2 simulator's
     return value VERBATIM — the ledger cannot drift from the proofs.

  2. **Cost-model parity**: ``Ledger.bytes_per_rank`` equals
     ``allreduce_cost``'s total_bytes for ring / recursive-doubling /
     halving-doubling, including ragged (element-padded) payloads —
     the byte figures bench.py and BENCH_collective.json consume are
     the proven ones.

  3. **Mutation sensitivity**: a perturbed schedule generator (the
     ``topo=`` substitution hook) cannot produce a ledger at all —
     construction raises :class:`LedgerError` where P2 would record a
     defect, so wrong byte predictions are unrepresentable.
"""

import types

import pytest

from rlo_tpu import topology
from rlo_tpu.observe.ledger import (ALGORITHMS, COMPOSITES, SCHEDULES,
                                    LedgerError, chunk_nbytes, ledger)
from rlo_tpu.ops.tpu_collectives import allreduce_cost
from rlo_tpu.tools import rlo_prover as P

NBYTES = 4096
POW2 = [n for n in range(2, 65) if n & (n - 1) == 0]
ALL_N = list(range(2, 65))


# ---------------------------------------------------------------------------
# 1. algebra parity vs rlo-prover P2, all n <= 64
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kind", ["binomial_bcast", "skip_ring_bcast"])
def test_bcast_ledger_matches_p2_all_n(kind):
    gen = (topology.binomial_bcast_schedule if kind == "binomial_bcast"
           else topology.skip_ring_bcast_schedule)
    for n in ALL_N:
        for origin in {0, n // 2, n - 1}:
            led = ledger(kind, n, NBYTES, origin=origin)
            sched = gen(n, origin)
            assert led.final == tuple(
                P.simulate_bcast(sched.rounds, n))
            assert led.final == (origin,) * n
            assert led.num_steps == len(sched.rounds)
            # every delivery is one full-vector send
            assert led.total_bytes == NBYTES * sum(
                len(r) for r in sched.rounds)


def test_ring_allreduce_ledger_matches_p2_all_n():
    for n in ALL_N:
        led = ledger("ring_allreduce", n, NBYTES)
        grid, defects = P.simulate_ring_allreduce(n, topology)
        assert defects == []
        assert led.final == tuple(tuple(row) for row in grid)
        assert led.num_steps == 2 * (n - 1)


def test_ring_all_gather_ledger_matches_p2_all_n():
    for n in ALL_N:
        led = ledger("ring_all_gather", n, NBYTES)
        grid, defects = P.simulate_ring_all_gather(n, topology)
        assert defects == []
        assert led.final == tuple(tuple(row) for row in grid)


def test_recursive_doubling_ledger_matches_p2_all_n():
    for n in POW2:
        led = ledger("recursive_doubling", n, NBYTES)
        acc, defects = P.simulate_rd_allreduce(n, topology)
        assert defects == []
        assert led.final == tuple(acc)
        assert led.num_steps == n.bit_length() - 1


def test_halving_doubling_ledger_matches_p2_all_n():
    for n in POW2:
        rs, defects = P.simulate_halving_reduce_scatter(n, topology)
        assert defects == []
        led_rs = ledger("halving_reduce_scatter", n, NBYTES)
        assert led_rs.final == tuple(rs)

        grid, defects = P.simulate_doubling_all_gather(n, rs, topology)
        assert defects == []
        led = ledger("halving_doubling", n, NBYTES)
        assert led.final == tuple(tuple(row) for row in grid)

        full = (1 << n) - 1
        led_ag = ledger("doubling_all_gather", n, NBYTES)
        grid2, defects = P.simulate_doubling_all_gather(
            n, [(r, full) for r in range(n)], topology)
        assert defects == []
        assert led_ag.final == tuple(tuple(row) for row in grid2)


# ---------------------------------------------------------------------------
# 2. cost-model parity (incl. ragged payloads)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("nbytes", [NBYTES, 1000, 4])
def test_bytes_per_rank_matches_allreduce_cost(nbytes):
    for n in (2, 3, 4, 7, 8, 16, 31, 64):
        led = ledger("ring_allreduce", n, nbytes)
        model = allreduce_cost("ring", n, nbytes)
        assert led.bytes_per_rank == model["total_bytes"], (n, nbytes)
        assert led.num_steps == model["steps"]
        # the ring is uniform: every rank pushes the same bytes
        assert set(led.sent_bytes_by_rank()) == {led.bytes_per_rank}
    for n in (2, 4, 8, 16, 32, 64):
        for alg in ("recursive_doubling", "halving_doubling"):
            led = ledger(alg, n, nbytes)
            model = allreduce_cost(alg, n, nbytes)
            assert led.bytes_per_rank == model["total_bytes"], (
                alg, n, nbytes)
            assert led.num_steps == model["steps"]


def test_ragged_payload_pads_at_element_granularity():
    # 1000 B f32 over n=3: 250 elems -> ceil to 84/chunk -> 336 B
    assert chunk_nbytes(3, 1000, 4) == 336
    led = ledger("ring_reduce_scatter", 3, 1000)
    assert all(e.nbytes == 336 for s in led.steps for e in s.edges)
    with pytest.raises(LedgerError):
        chunk_nbytes(4, 1001, 4)  # not a multiple of itemsize


def test_fleet_accounting_consistency():
    for sched in SCHEDULES:
        n = 8
        led = ledger(sched, n, NBYTES)
        assert sum(led.sent_bytes_by_rank()) == led.total_bytes
        assert led.total_bytes == sum(s.nbytes for s in led.steps)
        for s in led.steps:
            assert s.edge_nbytes == max(e.nbytes for e in s.edges)
    # broadcast is the non-uniform family: the origin forwards more
    led = ledger("binomial_bcast", 8, NBYTES)
    by_rank = led.sent_bytes_by_rank()
    assert by_rank[0] == max(by_rank) and min(by_rank) == 0


def test_trivial_and_invalid_ledgers():
    led = ledger("ring_allreduce", 1, NBYTES)
    assert led.steps == () and led.total_bytes == 0
    with pytest.raises(LedgerError):
        ledger("nope", 4, NBYTES)
    with pytest.raises(LedgerError):
        ledger("ring_allreduce", 0, NBYTES)
    with pytest.raises(LedgerError):
        ledger("binomial_bcast", 4, NBYTES, origin=4)
    with pytest.raises(LedgerError):
        ledger("ring_allreduce", 4, NBYTES + 1)  # itemsize misfit


def test_schedule_tables_are_closed():
    # Ev.STEP's ``a`` field indexes ALGORITHMS; composites expand to
    # atomic phases in execution order
    for name, phases in COMPOSITES.items():
        assert all(p in ALGORITHMS for p in phases)
        led = ledger(name, 8, NBYTES)
        seen = tuple(dict.fromkeys(s.algorithm for s in led.steps))
        assert seen == phases


# ---------------------------------------------------------------------------
# 3. digest determinism + mutation sensitivity
# ---------------------------------------------------------------------------

def test_digest_is_deterministic_and_input_sensitive():
    a = ledger("ring_allreduce", 8, NBYTES).digest()
    assert a == ledger("ring_allreduce", 8, NBYTES).digest()
    assert a != ledger("ring_allreduce", 16, NBYTES).digest()
    assert a != ledger("ring_allreduce", 8, 2 * NBYTES).digest()
    assert a != ledger("recursive_doubling", 8, NBYTES).digest()


def _perturbed(**overrides):
    """The mutation hook: rlo_tpu.topology with named generators
    replaced — a stand-in for a buggy schedule commit."""
    ns = types.SimpleNamespace()
    for name in dir(topology):
        if not name.startswith("_"):
            setattr(ns, name, getattr(topology, name))
    for name, fn in overrides.items():
        setattr(ns, name, fn)
    return ns


def test_perturbed_chunk_map_cannot_produce_a_ledger():
    # off-by-one chunk selection: reduce-scatter merges misalign
    bad = _perturbed(ring_reduce_scatter_chunk=lambda n, r, s:
                     (topology.ring_reduce_scatter_chunk(n, r, s) + 1)
                     % n)
    with pytest.raises(LedgerError, match="misalignment"):
        ledger("ring_allreduce", 8, NBYTES, topo=bad)


def test_perturbed_rd_rounds_cannot_produce_a_ledger():
    # dropping the last round leaves contribution sets incomplete
    bad = _perturbed(recursive_doubling_rounds=lambda n:
                     topology.recursive_doubling_rounds(n)[:-1])
    with pytest.raises(LedgerError):
        ledger("recursive_doubling", 8, NBYTES, topo=bad)


def test_perturbed_bcast_schedule_cannot_produce_a_ledger():
    real = topology.binomial_bcast_schedule

    def truncated(n, origin):
        sched = real(n, origin)
        return type(sched)(n, origin, sched.rounds[:-1])

    bad = _perturbed(binomial_bcast_schedule=truncated)
    with pytest.raises(LedgerError, match="does not deliver"):
        ledger("binomial_bcast", 8, NBYTES, topo=bad)


def test_perturbed_ring_perm_cannot_produce_a_ledger():
    # a non-permutation "ring" (two senders to one receiver)
    bad = _perturbed(ring_perm=lambda n, off=1: tuple(
        (s, 0) for s in range(n)))
    with pytest.raises(LedgerError, match="permutation"):
        ledger("ring_all_gather", 8, NBYTES, topo=bad)
