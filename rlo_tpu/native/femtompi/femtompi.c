/* femtompi implementation — see mpi.h for scope and purpose.
 *
 * Architecture: the femtompirun launcher creates one POSIX shm segment
 * holding a header plus ws*ws SPSC byte rings (ring (s,d) is written
 * only by rank s and read only by rank d, so head/tail need only
 * acquire/release atomics — the same discipline as the framework's own
 * SHM transport, rlo_shm.c). Every rank mmaps the segment at MPI_Init
 * via env FEMTOMPI_SHM/FEMTOMPI_RANK.
 *
 * Point-to-point is eager: MPI_Isend copies the payload into a
 * request-owned staging buffer, then pushes [len|tag|comm|payload] into
 * ring (me, dst) — immediately, or lazily from the progress loop when
 * the ring is momentarily full (per-destination FIFO order preserved).
 * Receivers pump every inbound ring into a local unexpected-message
 * queue; MPI_Iprobe/MPI_Recv/MPI_Irecv match on (comm, source, tag)
 * with MPI_ANY_SOURCE and MPI_ANY_TAG (>= 0 tags only) wildcards.
 *
 * Collectives ride the same rings on reserved NEGATIVE tags with a
 * per-communicator lockstep sequence number (all ranks enter
 * collectives in the same order — an MPI requirement). MPI_Iallreduce
 * is a genuinely nonblocking state machine advanced by MPI_Test: ranks
 * send contributions to rank 0, rank 0 reduces and fans the result
 * back out; it reports completion only after every result frame is in
 * a ring, so a fast rank exiting right after completion cannot strand
 * a slow rank.
 */
#include "mpi.h"

#include <fcntl.h>
#include <sched.h>
#include <stdatomic.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/time.h>
#include <unistd.h>

#define FMPI_MAGIC 0xf3a90de5u
#define FMPI_MAX_COMMS 64
#define FMPI_REC_HDR 12 /* [len:u32][tag:i32][comm:i32] */

typedef struct fmpi_ring {
    _Atomic uint64_t head; /* written by the ring's writer rank */
    _Atomic uint64_t tail; /* written by the ring's reader rank */
    uint8_t buf[];         /* hdr->ring_bytes data bytes */
} fmpi_ring;

typedef struct fmpi_hdr {
    uint32_t magic;
    int32_t ws;
    uint64_t ring_bytes;
    uint64_t slot_size; /* sizeof(fmpi_ring) + ring_bytes, 64-aligned */
    _Atomic int abort_flag;
} fmpi_hdr;

typedef struct unode { /* one unexpected (pumped, unmatched) message */
    struct unode *next;
    int src, tag, comm;
    uint32_t len;
    uint8_t data[];
} unode;

struct fmpi_req {
    struct fmpi_req *next;
    int kind; /* 1 send, 2 recv, 3 iallreduce */
    int done, cancelled;
    /* send */
    int dst, tag, comm;
    uint32_t len;
    uint8_t *sbuf;
    /* recv */
    void *rbuf;
    uint64_t rcap;
    int rsrc, rtag, rcomm;
    MPI_Status st;
    /* iallreduce */
    MPI_Op op;
    MPI_Datatype dt;
    int count, ctag, got, stage;
    void *arbuf;
    uint8_t *acc;
    struct fmpi_req **fan; /* rank 0: result sends, ws entries;
                              non-root: the 1 contribution send */
    int n_fan;             /* entries in fan (for req_free reclaim) */
    uint64_t fan_made;     /* bit r: fan[r] was successfully created —
                              distinguishes 'reclaimed' (NULL, made)
                              from 'creation failed, retry' (NULL, not
                              made); ws <= 64 enforced at init */
};

static struct {
    int inited, rank, ws;
    fmpi_hdr *hdr;
    uint8_t *base;
    int next_comm;
    int coll_seq[FMPI_MAX_COMMS];
    unode *uq_head, *uq_tail;
    struct fmpi_req *act_head, *act_tail; /* active requests, FIFO */
} G;

/* ---------------- rings ---------------- */

static fmpi_ring *ring_of(int src, int dst)
{
    return (fmpi_ring *)(G.base + sizeof(fmpi_hdr) +
                         G.hdr->slot_size *
                             ((uint64_t)src * (uint64_t)G.hdr->ws + dst));
}

static uint64_t align8(uint64_t n)
{
    return (n + 7) & ~7ull;
}

static void ring_write(fmpi_ring *r, uint64_t pos, const void *src,
                       uint64_t n)
{
    uint64_t cap = G.hdr->ring_bytes, off = pos % cap;
    uint64_t first = n < cap - off ? n : cap - off;
    memcpy(r->buf + off, src, first);
    if (n > first)
        memcpy(r->buf, (const uint8_t *)src + first, n - first);
}

static void ring_read(fmpi_ring *r, uint64_t pos, void *dst, uint64_t n)
{
    uint64_t cap = G.hdr->ring_bytes, off = pos % cap;
    uint64_t first = n < cap - off ? n : cap - off;
    memcpy(dst, r->buf + off, first);
    if (n > first)
        memcpy((uint8_t *)dst + first, r->buf, n - first);
}

/* try to push one record; 1 on success, 0 when the ring is full */
static int ring_push(int dst, int tag, int comm, const uint8_t *data,
                     uint32_t len)
{
    fmpi_ring *r = ring_of(G.rank, dst);
    uint64_t need = align8(FMPI_REC_HDR + (uint64_t)len);
    uint64_t head = atomic_load_explicit(&r->head, memory_order_relaxed);
    uint64_t tail = atomic_load_explicit(&r->tail, memory_order_acquire);
    if (G.hdr->ring_bytes - (head - tail) < need)
        return 0;
    uint8_t hdr[FMPI_REC_HDR];
    memcpy(hdr, &len, 4);
    memcpy(hdr + 4, &tag, 4);
    memcpy(hdr + 8, &comm, 4);
    ring_write(r, head, hdr, FMPI_REC_HDR);
    if (len)
        ring_write(r, head + FMPI_REC_HDR, data, len);
    atomic_store_explicit(&r->head, head + need, memory_order_release);
    return 1;
}

/* pop every available record from every inbound ring into the
 * unexpected queue */
static int fmpi_pump(void)
{
    for (int s = 0; s < G.ws; s++) {
        if (s == G.rank)
            continue;
        fmpi_ring *r = ring_of(s, G.rank);
        for (;;) {
            uint64_t tail =
                atomic_load_explicit(&r->tail, memory_order_relaxed);
            uint64_t head =
                atomic_load_explicit(&r->head, memory_order_acquire);
            if (head == tail)
                break;
            uint8_t hdr[FMPI_REC_HDR];
            ring_read(r, tail, hdr, FMPI_REC_HDR);
            uint32_t len;
            int tag, comm;
            memcpy(&len, hdr, 4);
            memcpy(&tag, hdr + 4, 4);
            memcpy(&comm, hdr + 8, 4);
            unode *n = (unode *)malloc(sizeof(*n) + len);
            if (!n)
                return MPI_ERR_OTHER;
            n->next = 0;
            n->src = s;
            n->tag = tag;
            n->comm = comm;
            n->len = len;
            if (len)
                ring_read(r, tail + FMPI_REC_HDR, n->data, len);
            atomic_store_explicit(&r->tail,
                                  tail + align8(FMPI_REC_HDR + len),
                                  memory_order_release);
            if (G.uq_tail)
                G.uq_tail->next = n;
            else
                G.uq_head = n;
            G.uq_tail = n;
        }
    }
    return MPI_SUCCESS;
}

/* match (and optionally remove) the first unexpected message for
 * (comm, src, tag); ANY_TAG matches only tags >= 0 (negative tags are
 * internal collective traffic) */
static unode *uq_match(int comm, int src, int tag, int remove)
{
    unode *prev = 0;
    for (unode *n = G.uq_head; n; prev = n, n = n->next) {
        if (n->comm != comm)
            continue;
        if (src != MPI_ANY_SOURCE && n->src != src)
            continue;
        if (tag == MPI_ANY_TAG ? n->tag < 0 : n->tag != tag)
            continue;
        if (remove) {
            if (prev)
                prev->next = n->next;
            else
                G.uq_head = n->next;
            if (G.uq_tail == n)
                G.uq_tail = prev;
            n->next = 0;
        }
        return n;
    }
    return 0;
}

/* ---------------- requests + progress ---------------- */

static void act_append(struct fmpi_req *q)
{
    q->next = 0;
    if (G.act_tail)
        G.act_tail->next = q;
    else
        G.act_head = q;
    G.act_tail = q;
}

static void act_remove(struct fmpi_req *q)
{
    struct fmpi_req *prev = 0;
    for (struct fmpi_req *n = G.act_head; n; prev = n, n = n->next) {
        if (n != q)
            continue;
        if (prev)
            prev->next = n->next;
        else
            G.act_head = n->next;
        if (G.act_tail == n)
            G.act_tail = prev;
        n->next = 0;
        return;
    }
}

static int dt_size(MPI_Datatype dt)
{
    switch (dt) {
    case MPI_BYTE: return 1;
    case MPI_INT: case MPI_FLOAT: return 4;
    case MPI_INT64_T: case MPI_DOUBLE: return 8;
    }
    return -1;
}

static void reduce_in(MPI_Datatype dt, MPI_Op op, void *acc,
                      const void *in, int count)
{
#define CASE(T)                                                         \
    do {                                                                \
        T *a = (T *)acc;                                                \
        const T *b = (const T *)in;                                     \
        for (int i = 0; i < count; i++)                                 \
            a[i] = op == MPI_SUM   ? a[i] + b[i]                        \
                   : op == MPI_MIN ? (b[i] < a[i] ? b[i] : a[i])        \
                                   : (b[i] > a[i] ? b[i] : a[i]);       \
    } while (0)
    switch (dt) {
    case MPI_INT: CASE(int32_t); break;
    case MPI_INT64_T: CASE(int64_t); break;
    case MPI_FLOAT: CASE(float); break;
    case MPI_DOUBLE: CASE(double); break;
    default: break; /* MPI_BYTE reduction unsupported */
    }
#undef CASE
}

static void req_free(struct fmpi_req *q);

/* Does a frame of `len` payload bytes fit the per-pair ring at all?
 * Collectives check this symmetrically on EVERY rank before any
 * traffic: the sender-side failure alone would leave the peers parked
 * in blocking waits with no timeout (review finding). */
static int frame_fits(uint64_t len)
{
    return align8(FMPI_REC_HDR + len) <= G.hdr->ring_bytes;
}

static struct fmpi_req *send_req_new(int dst, int tag, int comm,
                                     const void *buf, uint64_t len)
{
    /* capacity check for EVERY send path (Isend, Bcast, Reduce,
     * Iallreduce fans): an oversized frame can never leave the queue —
     * ring_push would fail forever and the rank would spin until the
     * launcher timeout instead of returning an error (round-2 advisor
     * finding; the check used to live only in MPI_Isend) */
    if (!frame_fits(len)) {
        fprintf(stderr,
                "femtompi: message of %llu bytes exceeds ring capacity "
                "%llu (raise femtompirun -r)\n",
                (unsigned long long)len,
                (unsigned long long)G.hdr->ring_bytes);
        return 0;
    }
    struct fmpi_req *q = (struct fmpi_req *)calloc(1, sizeof(*q));
    if (!q)
        return 0;
    q->kind = 1;
    q->dst = dst;
    q->tag = tag;
    q->comm = comm;
    q->len = (uint32_t)len;
    q->sbuf = (uint8_t *)malloc(len ? len : 1);
    if (!q->sbuf) {
        free(q);
        return 0;
    }
    if (len)
        memcpy(q->sbuf, buf, len);
    act_append(q);
    return q;
}

static void fmpi_progress(void)
{
    /* 1. flush queued sends, preserving per-destination FIFO order */
    uint64_t blocked = 0; /* dst bitmask (ws <= 64 enforced at init) */
    for (struct fmpi_req *q = G.act_head; q; q = q->next) {
        if (q->kind != 1 || q->done)
            continue;
        if (q->dst < 64 && (blocked >> q->dst) & 1)
            continue;
        if (ring_push(q->dst, q->tag, q->comm, q->sbuf, q->len)) {
            q->done = 1;
            free(q->sbuf);
            q->sbuf = 0;
        } else if (q->dst < 64) {
            blocked |= 1ull << q->dst;
        }
    }
    /* 2. pump inbound traffic */
    fmpi_pump();
    /* 3. advance recvs and allreduces */
    for (struct fmpi_req *q = G.act_head; q; q = q->next) {
        if (q->done)
            continue;
        if (q->kind == 2) {
            unode *n = uq_match(q->rcomm, q->rsrc, q->rtag, 1);
            if (!n)
                continue;
            uint32_t cp = n->len < q->rcap ? n->len : (uint32_t)q->rcap;
            if (cp)
                memcpy(q->rbuf, n->data, cp);
            q->st.MPI_SOURCE = n->src;
            q->st.MPI_TAG = n->tag;
            q->st.MPI_ERROR = MPI_SUCCESS;
            q->st._count = (int)n->len;
            free(n);
            q->done = 1;
        } else if (q->kind == 3) {
            int64_t bytes = (int64_t)q->count * dt_size(q->dt);
            if (G.rank != 0) {
                /* stage 0: contribution queued at post time; wait for
                 * the result from rank 0 */
                unode *n = uq_match(q->comm, 0, q->ctag, 1);
                if (!n)
                    continue;
                memcpy(q->arbuf, n->data, bytes);
                free(n);
                /* the contribution send must be done by now (rank 0
                 * reduced it); reclaim it */
                if (q->fan && q->fan[0]) {
                    req_free(q->fan[0]);
                    q->fan[0] = 0;
                }
                q->done = 1;
            } else {
                while (q->got < G.ws - 1) {
                    unode *n =
                        uq_match(q->comm, MPI_ANY_SOURCE, q->ctag, 1);
                    if (!n)
                        break;
                    reduce_in(q->dt, q->op, q->acc, n->data, q->count);
                    free(n);
                    q->got++;
                }
                if (q->got < G.ws - 1)
                    continue;
                if (q->stage == 0) { /* fan the result out once */
                    if (!q->fan) {
                        q->fan = (struct fmpi_req **)calloc(
                            (size_t)G.ws, sizeof(*q->fan));
                        if (!q->fan)
                            continue;
                        q->n_fan = G.ws;
                    }
                    /* retry creation until every result send exists:
                     * treating a failed creation like a reclaimed
                     * (delivered) send would report success while the
                     * peer waits forever (review finding) */
                    int missing = 0;
                    for (int r = 1; r < G.ws; r++) {
                        if (q->fan_made & (1ull << r))
                            continue;
                        q->fan[r] = send_req_new(r, q->ctag, q->comm,
                                                 q->acc,
                                                 (uint64_t)bytes);
                        if (q->fan[r])
                            q->fan_made |= 1ull << r;
                        else
                            missing = 1;
                    }
                    if (missing)
                        continue;
                    memcpy(q->arbuf, q->acc, bytes);
                    q->stage = 1;
                }
                /* complete only when every result frame is in a ring:
                 * a fast rank exiting right after completion must not
                 * strand a slow one. Reclaim fan sends as they land. */
                int all = 1;
                for (int r = 1; r < G.ws; r++) {
                    if (!q->fan[r])
                        continue;
                    if (q->fan[r]->done) {
                        req_free(q->fan[r]);
                        q->fan[r] = 0;
                    } else {
                        all = 0;
                    }
                }
                if (all)
                    q->done = 1;
            }
        }
    }
}

static void req_free(struct fmpi_req *q)
{
    act_remove(q);
    free(q->sbuf);
    free(q->acc);
    if (q->fan) {
        /* freeing an in-flight collective: release any still-active
         * fan sub-requests too, or they stay on the active list
         * forever (round-2 advisor finding) */
        for (int i = 0; i < q->n_fan; i++)
            if (q->fan[i])
                req_free(q->fan[i]);
        free(q->fan);
    }
    free(q);
}

/* ---------------- init / teardown ---------------- */

int MPI_Init(int *argc, char ***argv)
{
    (void)argc;
    (void)argv;
    if (G.inited)
        return MPI_ERR_OTHER;
    const char *name = getenv("FEMTOMPI_SHM");
    const char *rank = getenv("FEMTOMPI_RANK");
    if (!name || !rank) {
        fprintf(stderr,
                "femtompi: not launched under femtompirun "
                "(FEMTOMPI_SHM/FEMTOMPI_RANK unset)\n");
        return MPI_ERR_OTHER;
    }
    int fd = shm_open(name, O_RDWR, 0600);
    if (fd < 0)
        return MPI_ERR_OTHER;
    struct stat stbuf;
    if (fstat(fd, &stbuf) != 0) {
        close(fd);
        return MPI_ERR_OTHER;
    }
    void *m = mmap(0, (size_t)stbuf.st_size, PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
    close(fd);
    if (m == MAP_FAILED)
        return MPI_ERR_OTHER;
    G.hdr = (fmpi_hdr *)m;
    G.base = (uint8_t *)m;
    if (G.hdr->magic != FMPI_MAGIC || G.hdr->ws < 2 || G.hdr->ws > 64)
        return MPI_ERR_OTHER;
    G.rank = atoi(rank);
    G.ws = G.hdr->ws;
    G.next_comm = 1;
    G.inited = 1;
    return MPI_SUCCESS;
}

int MPI_Initialized(int *flag)
{
    *flag = G.inited;
    return MPI_SUCCESS;
}

int MPI_Finalize(void)
{
    if (!G.inited)
        return MPI_ERR_OTHER;
    MPI_Barrier(MPI_COMM_WORLD);
    G.inited = 0;
    return MPI_SUCCESS;
}

int MPI_Abort(MPI_Comm comm, int errorcode)
{
    (void)comm;
    if (G.hdr)
        atomic_store(&G.hdr->abort_flag, 1);
    _exit(errorcode ? errorcode : 1);
}

double MPI_Wtime(void)
{
    struct timeval tv;
    gettimeofday(&tv, 0);
    return (double)tv.tv_sec + (double)tv.tv_usec * 1e-6;
}

/* ---------------- communicators ---------------- */

int MPI_Comm_dup(MPI_Comm comm, MPI_Comm *newcomm)
{
    (void)comm;
    if (G.next_comm >= FMPI_MAX_COMMS)
        return MPI_ERR_OTHER;
    *newcomm = G.next_comm++; /* all ranks dup in the same order */
    return MPI_SUCCESS;
}

int MPI_Comm_free(MPI_Comm *comm)
{
    *comm = MPI_COMM_NULL;
    return MPI_SUCCESS;
}

int MPI_Comm_size(MPI_Comm comm, int *size)
{
    (void)comm;
    *size = G.ws;
    return MPI_SUCCESS;
}

int MPI_Comm_rank(MPI_Comm comm, int *rank)
{
    (void)comm;
    *rank = G.rank;
    return MPI_SUCCESS;
}

/* ---------------- point-to-point ---------------- */

int MPI_Isend(const void *buf, int count, MPI_Datatype dt, int dest,
              int tag, MPI_Comm comm, MPI_Request *req)
{
    int sz = dt_size(dt);
    if (!G.inited || sz < 0 || count < 0 || dest < 0 || dest >= G.ws ||
        dest == G.rank)
        return MPI_ERR_OTHER;
    uint64_t len = (uint64_t)count * (uint64_t)sz;
    struct fmpi_req *q = send_req_new(dest, tag, comm, buf, len);
    if (!q) /* includes the ring-capacity check (reported to stderr) */
        return MPI_ERR_OTHER;
    fmpi_progress(); /* often completes the push immediately */
    *req = q;
    return MPI_SUCCESS;
}

int MPI_Send(const void *buf, int count, MPI_Datatype dt, int dest,
             int tag, MPI_Comm comm)
{
    MPI_Request q;
    int rc = MPI_Isend(buf, count, dt, dest, tag, comm, &q);
    if (rc != MPI_SUCCESS)
        return rc;
    return MPI_Wait(&q, MPI_STATUS_IGNORE);
}

int MPI_Irecv(void *buf, int count, MPI_Datatype dt, int source, int tag,
              MPI_Comm comm, MPI_Request *req)
{
    int sz = dt_size(dt);
    if (!G.inited || sz < 0 || count < 0)
        return MPI_ERR_OTHER;
    struct fmpi_req *q = (struct fmpi_req *)calloc(1, sizeof(*q));
    if (!q)
        return MPI_ERR_OTHER;
    q->kind = 2;
    q->rbuf = buf;
    q->rcap = (uint64_t)count * (uint64_t)sz;
    q->rsrc = source;
    q->rtag = tag;
    q->rcomm = comm;
    act_append(q);
    fmpi_progress();
    *req = q;
    return MPI_SUCCESS;
}

int MPI_Recv(void *buf, int count, MPI_Datatype dt, int source, int tag,
             MPI_Comm comm, MPI_Status *status)
{
    MPI_Request q;
    int rc = MPI_Irecv(buf, count, dt, source, tag, comm, &q);
    if (rc != MPI_SUCCESS)
        return rc;
    return MPI_Wait(&q, status);
}

int MPI_Iprobe(int source, int tag, MPI_Comm comm, int *flag,
               MPI_Status *status)
{
    if (!G.inited)
        return MPI_ERR_OTHER;
    fmpi_progress();
    unode *n = uq_match(comm, source, tag, 0);
    *flag = n != 0;
    if (n && status) {
        status->MPI_SOURCE = n->src;
        status->MPI_TAG = n->tag;
        status->MPI_ERROR = MPI_SUCCESS;
        status->_count = (int)n->len;
    }
    return MPI_SUCCESS;
}

int MPI_Get_count(const MPI_Status *status, MPI_Datatype dt, int *count)
{
    int sz = dt_size(dt);
    if (!status || sz <= 0)
        return MPI_ERR_OTHER;
    *count = status->_count / sz;
    return MPI_SUCCESS;
}

int MPI_Test(MPI_Request *req, int *flag, MPI_Status *status)
{
    if (!req)
        return MPI_ERR_OTHER;
    if (*req == MPI_REQUEST_NULL) { /* null/inactive: complete */
        *flag = 1;
        return MPI_SUCCESS;
    }
    fmpi_progress();
    struct fmpi_req *q = *req;
    *flag = q->done || q->cancelled;
    if (*flag) {
        if (status)
            *status = q->st;
        req_free(q);
        *req = MPI_REQUEST_NULL;
    }
    return MPI_SUCCESS;
}

int MPI_Wait(MPI_Request *req, MPI_Status *status)
{
    int flag = 0;
    while (!flag) {
        int rc = MPI_Test(req, &flag, status);
        if (rc != MPI_SUCCESS)
            return rc;
        if (!flag)
            sched_yield();
    }
    return MPI_SUCCESS;
}

int MPI_Cancel(MPI_Request *req)
{
    if (!req || *req == MPI_REQUEST_NULL)
        return MPI_ERR_OTHER;
    (*req)->cancelled = 1; /* recvs only; sends are eager (always run) */
    return MPI_SUCCESS;
}

int MPI_Request_free(MPI_Request *req)
{
    if (req && *req != MPI_REQUEST_NULL) {
        req_free(*req);
        *req = MPI_REQUEST_NULL;
    }
    return MPI_SUCCESS;
}

/* ---------------- collectives ---------------- */

static int coll_tag(MPI_Comm comm)
{
    /* lockstep per-comm sequence -> unique negative tag per instance */
    if (comm < 0 || comm >= FMPI_MAX_COMMS)
        return MPI_ANY_TAG; /* unreachable for valid comms */
    return -2 - (G.coll_seq[comm]++ & 0x0fffffff);
}

int MPI_Iallreduce(const void *sendbuf, void *recvbuf, int count,
                   MPI_Datatype dt, MPI_Op op, MPI_Comm comm,
                   MPI_Request *req)
{
    int sz = dt_size(dt);
    if (!G.inited || sz <= 0 || count < 0)
        return MPI_ERR_OTHER;
    /* int64: count * sz overflows int for large counts (advisor) */
    int64_t bytes = (int64_t)count * sz;
    if (!frame_fits((uint64_t)bytes))
        return MPI_ERR_OTHER; /* symmetric: every rank rejects */
    struct fmpi_req *q = (struct fmpi_req *)calloc(1, sizeof(*q));
    if (!q)
        return MPI_ERR_OTHER;
    q->kind = 3;
    q->op = op;
    q->dt = dt;
    q->count = count;
    q->comm = comm;
    q->ctag = coll_tag(comm);
    q->arbuf = recvbuf;
    if (G.rank == 0) {
        q->acc = (uint8_t *)malloc((size_t)(bytes ? bytes : 1));
        if (!q->acc) {
            free(q);
            return MPI_ERR_OTHER;
        }
        memcpy(q->acc, sendbuf, (size_t)bytes);
        act_append(q);
    } else {
        q->fan = (struct fmpi_req **)calloc(1, sizeof(*q->fan));
        q->n_fan = 1;
        act_append(q);
        if (!q->fan ||
            !(q->fan[0] = send_req_new(0, q->ctag, comm, sendbuf,
                                       (uint64_t)bytes))) {
            act_remove(q);
            free(q->fan);
            free(q);
            return MPI_ERR_OTHER;
        }
    }
    fmpi_progress();
    *req = q;
    return MPI_SUCCESS;
}

int MPI_Allreduce(const void *sendbuf, void *recvbuf, int count,
                  MPI_Datatype dt, MPI_Op op, MPI_Comm comm)
{
    MPI_Request q;
    int rc = MPI_Iallreduce(sendbuf, recvbuf, count, dt, op, comm, &q);
    if (rc != MPI_SUCCESS)
        return rc;
    return MPI_Wait(&q, MPI_STATUS_IGNORE);
}

int MPI_Barrier(MPI_Comm comm)
{
    int in = 0, out = 0;
    return MPI_Allreduce(&in, &out, 1, MPI_INT, MPI_SUM, comm);
}

int MPI_Bcast(void *buf, int count, MPI_Datatype dt, int root,
              MPI_Comm comm)
{
    int sz = dt_size(dt);
    if (!G.inited || sz <= 0 || count < 0 || root < 0 || root >= G.ws)
        return MPI_ERR_OTHER;
    int tag = coll_tag(comm);
    int64_t bytes = (int64_t)count * sz;
    if (!frame_fits((uint64_t)bytes))
        return MPI_ERR_OTHER; /* symmetric: every rank rejects */
    if (G.rank == root) {
        for (int r = 0; r < G.ws; r++) {
            if (r == root)
                continue;
            struct fmpi_req *s =
                send_req_new(r, tag, comm, buf, (uint64_t)bytes);
            if (!s)
                return MPI_ERR_OTHER;
            while (!s->done) { /* block until in the ring */
                fmpi_progress();
                if (!s->done)
                    sched_yield();
            }
            req_free(s);
        }
        return MPI_SUCCESS;
    }
    return MPI_Recv(buf, count, dt, root, tag, comm, MPI_STATUS_IGNORE);
}

int MPI_Reduce(const void *sendbuf, void *recvbuf, int count,
               MPI_Datatype dt, MPI_Op op, int root, MPI_Comm comm)
{
    int sz = dt_size(dt);
    if (!G.inited || sz <= 0 || count < 0 || root < 0 || root >= G.ws)
        return MPI_ERR_OTHER;
    int tag = coll_tag(comm);
    int64_t bytes = (int64_t)count * sz;
    if (!frame_fits((uint64_t)bytes))
        return MPI_ERR_OTHER; /* symmetric: every rank rejects */
    if (G.rank != root) {
        struct fmpi_req *s =
            send_req_new(root, tag, comm, sendbuf, (uint64_t)bytes);
        if (!s)
            return MPI_ERR_OTHER;
        while (!s->done) {
            fmpi_progress();
            if (!s->done)
                sched_yield();
        }
        req_free(s);
        return MPI_SUCCESS;
    }
    memcpy(recvbuf, sendbuf, (size_t)bytes);
    for (int got = 0; got < G.ws - 1;) {
        fmpi_progress();
        unode *n = uq_match(comm, MPI_ANY_SOURCE, tag, 1);
        if (!n) {
            sched_yield();
            continue;
        }
        reduce_in(dt, op, recvbuf, n->data, count);
        free(n);
        got++;
    }
    return MPI_SUCCESS;
}
