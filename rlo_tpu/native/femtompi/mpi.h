/* femtompi — a FUNCTIONAL single-host MPI subset over POSIX shared
 * memory, with standard MPI-3 signatures.
 *
 * Purpose: the container has no MPI installation, but the framework's
 * MPI transport (rlo_mpi.c, compile-gated on RLO_HAVE_MPI) must be
 * EXECUTED, not just syntax-checked (BASELINE config 1 runs "testcases
 * via mpirun on CPU"; the reference's whole L0 is live MPI P2P,
 * /root/reference/rootless_ops.c:656,1123,1613). femtompi implements the
 * exact subset rlo_mpi.c and the demo benchmark cases use — eager
 * point-to-point over per-pair SPSC shared-memory rings, ANY_SOURCE/
 * ANY_TAG probing, nonblocking sends, a nonblocking allreduce, and the
 * classic blocking collectives — so `femtompirun -n 8 ./rlo_demo_mpi`
 * drives every rlo_mpi.c code path with real multi-process traffic.
 * The same sources compile unmodified against a real MPI (signatures
 * are standard); femtompi is the vehicle, not the destination.
 *
 * Scope notes (documented deviations, all safe for our callers):
 *   - MPI_ANY_TAG matches only tags >= 0; negative tags are reserved
 *     for femtompi's internal collective protocol messages.
 *   - Communicators are small integer ids; MPI_Comm_dup is collective
 *     only in the sense that all ranks must dup in the same order
 *     (true for rlo_mpi_world_new, and for ordinary MPI programs).
 *   - One process per rank, one host; rendezvous via the segment the
 *     femtompirun launcher creates (env FEMTOMPI_SHM/RANK/SIZE).
 */
#ifndef FEMTOMPI_MPI_H
#define FEMTOMPI_MPI_H

#ifdef __cplusplus
extern "C" {
#endif

typedef int MPI_Comm;
typedef struct fmpi_req *MPI_Request;
typedef struct {
    int MPI_SOURCE, MPI_TAG, MPI_ERROR;
    int _count; /* internal: payload bytes of the matched message */
} MPI_Status;
typedef int MPI_Datatype;
typedef int MPI_Op;

#define MPI_SUCCESS 0
#define MPI_ERR_OTHER 15
#define MPI_COMM_WORLD ((MPI_Comm)0)
#define MPI_COMM_NULL ((MPI_Comm)-1)
#define MPI_REQUEST_NULL ((MPI_Request)0)

#define MPI_BYTE ((MPI_Datatype)0)
#define MPI_INT ((MPI_Datatype)1)
#define MPI_INT64_T ((MPI_Datatype)2)
#define MPI_FLOAT ((MPI_Datatype)3)
#define MPI_DOUBLE ((MPI_Datatype)4)

#define MPI_SUM ((MPI_Op)0)
#define MPI_MIN ((MPI_Op)1)
#define MPI_MAX ((MPI_Op)2)

#define MPI_ANY_SOURCE (-2)
#define MPI_ANY_TAG (-1)
#define MPI_STATUS_IGNORE ((MPI_Status *)0)

int MPI_Init(int *argc, char ***argv);
int MPI_Initialized(int *flag);
int MPI_Finalize(void);
int MPI_Abort(MPI_Comm comm, int errorcode);
double MPI_Wtime(void);

int MPI_Comm_dup(MPI_Comm comm, MPI_Comm *newcomm);
int MPI_Comm_free(MPI_Comm *comm);
int MPI_Comm_size(MPI_Comm comm, int *size);
int MPI_Comm_rank(MPI_Comm comm, int *rank);

int MPI_Isend(const void *buf, int count, MPI_Datatype dt, int dest,
              int tag, MPI_Comm comm, MPI_Request *req);
int MPI_Send(const void *buf, int count, MPI_Datatype dt, int dest,
             int tag, MPI_Comm comm);
int MPI_Irecv(void *buf, int count, MPI_Datatype dt, int source, int tag,
              MPI_Comm comm, MPI_Request *req);
int MPI_Recv(void *buf, int count, MPI_Datatype dt, int source, int tag,
             MPI_Comm comm, MPI_Status *status);
int MPI_Iprobe(int source, int tag, MPI_Comm comm, int *flag,
               MPI_Status *status);
int MPI_Get_count(const MPI_Status *status, MPI_Datatype dt, int *count);
int MPI_Test(MPI_Request *req, int *flag, MPI_Status *status);
int MPI_Wait(MPI_Request *req, MPI_Status *status);
int MPI_Cancel(MPI_Request *req);
int MPI_Request_free(MPI_Request *req);

int MPI_Iallreduce(const void *sendbuf, void *recvbuf, int count,
                   MPI_Datatype dt, MPI_Op op, MPI_Comm comm,
                   MPI_Request *req);
int MPI_Allreduce(const void *sendbuf, void *recvbuf, int count,
                  MPI_Datatype dt, MPI_Op op, MPI_Comm comm);
int MPI_Reduce(const void *sendbuf, void *recvbuf, int count,
               MPI_Datatype dt, MPI_Op op, int root, MPI_Comm comm);
int MPI_Bcast(void *buf, int count, MPI_Datatype dt, int root,
              MPI_Comm comm);
int MPI_Barrier(MPI_Comm comm);

#ifdef __cplusplus
}
#endif

#endif /* FEMTOMPI_MPI_H */
