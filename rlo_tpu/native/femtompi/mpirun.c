/* femtompirun — the `mpirun -n N prog` launcher for femtompi.
 *
 * Creates the shared-memory segment (header + ws*ws SPSC rings), forks
 * N children with FEMTOMPI_SHM/FEMTOMPI_RANK/FEMTOMPI_SIZE set, execs
 * the program, and reaps: exit status 0 iff every rank exited 0. A
 * wall-clock timeout (default 300 s) kills the whole job — a hung rank
 * must fail the run, not wedge CI (the reference's `mpirun -n N ./demo`
 * has the same job-level contract, SURVEY.md §4).
 *
 * Usage: femtompirun [-n ranks] [-r ring_bytes] [-t timeout_s]
 *                    prog [args...]
 */
#define _GNU_SOURCE
#include <errno.h>
#include <fcntl.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/time.h>
#include <sys/wait.h>
#include <unistd.h>

#define FMPI_MAGIC 0xf3a90de5u

typedef struct fmpi_hdr { /* must match femtompi.c */
    uint32_t magic;
    int32_t ws;
    uint64_t ring_bytes;
    uint64_t slot_size;
    int abort_flag; /* _Atomic int in femtompi.c; layout-compatible */
} fmpi_hdr;

static uint64_t now_usec(void)
{
    struct timeval tv;
    gettimeofday(&tv, 0);
    return (uint64_t)tv.tv_sec * 1000000ull + (uint64_t)tv.tv_usec;
}

int main(int argc, char **argv)
{
    int ws = 2;
    uint64_t ring_bytes = 4ull << 20;
    int timeout_s = 300;
    int i = 1;
    for (; i < argc; i++) {
        if (!strcmp(argv[i], "-n") && i + 1 < argc)
            ws = atoi(argv[++i]);
        else if (!strcmp(argv[i], "-r") && i + 1 < argc)
            ring_bytes = strtoull(argv[++i], 0, 0);
        else if (!strcmp(argv[i], "-t") && i + 1 < argc)
            timeout_s = atoi(argv[++i]);
        else
            break;
    }
    if (i >= argc || ws < 2 || ws > 64 || ring_bytes < 4096) {
        fprintf(stderr,
                "usage: %s [-n ranks(2-64)] [-r ring_bytes] "
                "[-t timeout_s] prog [args...]\n",
                argv[0]);
        return 2;
    }

    char name[64];
    snprintf(name, sizeof name, "/fmpi.%d", (int)getpid());
    int fd = shm_open(name, O_RDWR | O_CREAT | O_EXCL, 0600);
    if (fd < 0) {
        perror("shm_open");
        return 1;
    }
    uint64_t ring_sz = sizeof(uint64_t) * 2 /* head+tail */ + ring_bytes;
    uint64_t slot = (ring_sz + 63) & ~63ull;
    uint64_t total = sizeof(fmpi_hdr) + slot * (uint64_t)ws * (uint64_t)ws;
    total = (total + 4095) & ~4095ull;
    if (ftruncate(fd, (off_t)total) != 0) {
        perror("ftruncate");
        shm_unlink(name);
        return 1;
    }
    fmpi_hdr *hdr = (fmpi_hdr *)mmap(0, sizeof(fmpi_hdr),
                                     PROT_READ | PROT_WRITE, MAP_SHARED,
                                     fd, 0);
    close(fd);
    if (hdr == MAP_FAILED) {
        perror("mmap");
        shm_unlink(name);
        return 1;
    }
    hdr->ws = ws;
    hdr->ring_bytes = ring_bytes;
    hdr->slot_size = slot;
    hdr->abort_flag = 0;
    hdr->magic = FMPI_MAGIC; /* last: children validate it */

    pid_t *pids = (pid_t *)calloc((size_t)ws, sizeof(pid_t));
    char envbuf[32];
    for (int r = 0; r < ws; r++) {
        pid_t pid = fork();
        if (pid < 0) {
            perror("fork");
            for (int k = 0; k < r; k++)
                kill(pids[k], SIGKILL);
            shm_unlink(name);
            return 1;
        }
        if (pid == 0) {
            setenv("FEMTOMPI_SHM", name, 1);
            snprintf(envbuf, sizeof envbuf, "%d", r);
            setenv("FEMTOMPI_RANK", envbuf, 1);
            snprintf(envbuf, sizeof envbuf, "%d", ws);
            setenv("FEMTOMPI_SIZE", envbuf, 1);
            execvp(argv[i], &argv[i]);
            perror("execvp");
            _exit(127);
        }
        pids[r] = pid;
    }

    uint64_t deadline = now_usec() + (uint64_t)timeout_s * 1000000ull;
    int live = ws, failures = 0;
    while (live > 0) {
        int st = 0;
        pid_t got = waitpid(-1, &st, WNOHANG);
        if (got > 0) {
            live--;
            /* forget reaped pids: the OS may recycle them, and a later
             * kill sweep must never signal an unrelated process */
            for (int r = 0; r < ws; r++)
                if (pids[r] == got)
                    pids[r] = 0;
            int bad = !WIFEXITED(st) || WEXITSTATUS(st) != 0;
            if (bad) {
                failures++;
                /* one rank failed: the job is lost; kill the rest so
                 * the run terminates promptly */
                for (int r = 0; r < ws; r++)
                    if (pids[r] > 0)
                        kill(pids[r], SIGKILL);
            }
            continue;
        }
        if (now_usec() > deadline) {
            fprintf(stderr, "femtompirun: timeout after %d s, killing\n",
                    timeout_s);
            for (int r = 0; r < ws; r++)
                if (pids[r] > 0)
                    kill(pids[r], SIGKILL);
            failures++;
            deadline = (uint64_t)-1; /* kill once, then reap */
        }
        usleep(2000);
    }
    shm_unlink(name);
    free(pids);
    return failures ? 1 : 0;
}
