/* Progress engine: cooperatively-polled state machine driving the rootless
 * broadcast and IAR leaderless-consensus ops.
 *
 * Native counterpart of rlo_tpu/engine.py; both mirror the reference
 * (struct progress_engine rootless_ops.c:202-253, make_progress_gen :551,
 * RLO_bcast_gen :1581, _bc_forward :1104, IAR handlers :668-932, pickup
 * :938-992) with the deliberate departures listed in rlo_core.h.
 */
#include "rlo_internal.h"

/* ---------------- intrusive message queue (reference queue_append/
 * queue_remove, rootless_ops.c:345-404) ---------------- */

typedef struct rlo_msg rlo_msg;

typedef struct rlo_queue {
    rlo_msg *head, *tail;
    int len;
} rlo_queue;

/* ---------------- per-proposal consensus bookkeeping (reference
 * Proposal_state, rootless_ops.c:184-194) ---------------- */

typedef struct rlo_prop {
    int pid;
    int recv_from; /* parent in the vote tree */
    int vote;
    int votes_needed, votes_recved;
    int state; /* enum rlo_state */
    uint8_t *payload;
    int64_t len;
    rlo_handle **decision_handles;
    int n_decision;
    int decision_pending;
} rlo_prop;

/* ---------------- in-flight message (reference RLO_msg_t,
 * rootless_ops.h:93-146) ---------------- */

struct rlo_msg {
    rlo_msg *prev, *next;
    int tag, src; /* src = immediate sender (~MPI_SOURCE) */
    int32_t origin, pid, vote;
    rlo_blob *frame;        /* the encoded frame (owned ref) */
    const uint8_t *payload; /* aliases frame->data past the header */
    int64_t len;
    rlo_handle **handles;
    int n_handles, cap_handles;
    int pickup_done, fwd_done;
    rlo_prop *ps; /* for relayed IAR proposals */
};

struct rlo_engine {
    rlo_world *w;
    int rank, ws, comm;
    int64_t msg_size_max;
    rlo_judge_cb judge;
    void *judge_ctx;
    rlo_action_cb action;
    void *action_ctx;
    int my_level;
    int init_targets[64];
    int n_init;
    rlo_queue q_wait, q_wait_pickup, q_pickup, q_iar_pending;
    int64_t sent_bcast, recved_bcast, total_pickup;
    rlo_prop own; /* my_own_proposal; own.payload = my proposal bytes */
    int err; /* sticky first protocol error */
    rlo_msg *peeked; /* message exposed by rlo_pickup_peek, not consumed */
};

/* ---------------- queue ops ---------------- */

static void q_append(rlo_queue *q, rlo_msg *m)
{
    m->next = 0;
    m->prev = q->tail;
    if (q->tail)
        q->tail->next = m;
    else
        q->head = m;
    q->tail = m;
    q->len++;
}

static void q_remove(rlo_queue *q, rlo_msg *m)
{
    if (m->prev)
        m->prev->next = m->next;
    else
        q->head = m->next;
    if (m->next)
        m->next->prev = m->prev;
    else
        q->tail = m->prev;
    m->prev = m->next = 0;
    q->len--;
}

/* ---------------- msg lifecycle ---------------- */

/* Encode one frame into a fresh blob (the single copy a send makes;
 * every fan-out edge then shares it by ref). */
static rlo_blob *frame_blob(int32_t origin, int32_t pid, int32_t vote,
                            const uint8_t *payload, int64_t len)
{
    rlo_blob *b = rlo_blob_new(RLO_HEADER_SIZE + len);
    if (!b)
        return 0;
    if (rlo_frame_encode(b->data, b->len, origin, pid, vote, payload,
                         len) < 0) {
        rlo_blob_unref(b);
        return 0;
    }
    return b;
}

/* Wrap a received or freshly-encoded frame blob into a message; STEALS
 * the caller's blob ref (unrefs it on failure, storing RLO_ERR_PROTO or
 * RLO_ERR_NOMEM in *err so callers report the true cause). */
static rlo_msg *msg_from_frame(int tag, int src, rlo_blob *frame, int *err)
{
    int32_t origin, pid, vote;
    const uint8_t *payload;
    int64_t plen = rlo_frame_decode(frame->data, frame->len, &origin,
                                    &pid, &vote, &payload);
    if (plen < 0) {
        if (err)
            *err = RLO_ERR_PROTO;
        rlo_blob_unref(frame);
        return 0;
    }
    rlo_msg *m = (rlo_msg *)calloc(1, sizeof(*m));
    if (!m) {
        if (err)
            *err = RLO_ERR_NOMEM;
        rlo_blob_unref(frame);
        return 0;
    }
    m->tag = tag;
    m->src = src;
    m->origin = origin;
    m->pid = pid;
    m->vote = vote;
    m->frame = frame;
    m->payload = payload;
    m->len = plen;
    return m;
}

static void prop_free(rlo_prop *p)
{
    if (!p)
        return;
    for (int i = 0; i < p->n_decision; i++)
        rlo_handle_unref(p->decision_handles[i]);
    free(p->decision_handles);
    free(p->payload);
    free(p);
}

static void msg_free(rlo_msg *m)
{
    if (!m)
        return;
    for (int i = 0; i < m->n_handles; i++)
        rlo_handle_unref(m->handles[i]);
    free(m->handles);
    rlo_blob_unref(m->frame);
    prop_free(m->ps);
    free(m);
}

static int msg_track(rlo_msg *m, rlo_handle *h)
{
    if (m->n_handles == m->cap_handles) {
        int cap = m->cap_handles ? m->cap_handles * 2 : 4;
        rlo_handle **p = (rlo_handle **)realloc(
            m->handles, (size_t)cap * sizeof(void *));
        if (!p)
            return RLO_ERR_NOMEM;
        m->handles = p;
        m->cap_handles = cap;
    }
    m->handles[m->n_handles++] = h;
    return RLO_OK;
}

static int msg_sends_done(const rlo_msg *m)
{
    for (int i = 0; i < m->n_handles; i++)
        if (!m->handles[i]->delivered)
            return 0;
    return 1;
}

/* ---------------- send helper ---------------- */

/* isend one already-encoded frame blob; when track_in != NULL the
 * completion handle is retained on that message (votes pass NULL — fire
 * and forget, but still reliable: the world owns the in-flight node). */
static int eng_isend_frame(rlo_engine *e, int dst, int tag,
                           rlo_blob *frame, rlo_msg *track_in)
{
    rlo_handle *h = 0;
    int rc = rlo_world_isend(e->w, e->rank, dst, e->comm, tag, frame,
                             track_in ? &h : 0);
    if (rc == RLO_OK && track_in)
        rc = msg_track(track_in, h);
    return rc;
}

/* Encode + send a one-off frame (votes). */
static int eng_isend(rlo_engine *e, int dst, int tag, int32_t origin,
                     int32_t pid, int32_t vote, const uint8_t *payload,
                     int64_t len, rlo_msg *track_in)
{
    rlo_blob *frame = frame_blob(origin, pid, vote, payload, len);
    if (!frame)
        return RLO_ERR_NOMEM;
    int rc = eng_isend_frame(e, dst, tag, frame, track_in);
    rlo_blob_unref(frame);
    return rc;
}

/* ---------------- engine create/free ---------------- */

rlo_engine *rlo_engine_new(rlo_world *w, int rank, int comm,
                           rlo_judge_cb judge, void *judge_ctx,
                           rlo_action_cb action, void *action_ctx,
                           int64_t msg_size_max)
{
    if (!w || rank < 0 || rank >= rlo_world_size(w))
        return 0;
    /* one-process-per-rank transports (shm/mpi) bind the world to a rank */
    if (rlo_world_my_rank(w) >= 0 && rank != rlo_world_my_rank(w))
        return 0;
    rlo_engine *e = (rlo_engine *)calloc(1, sizeof(*e));
    if (!e)
        return 0;
    e->w = w;
    e->rank = rank;
    e->ws = rlo_world_size(w);
    e->comm = comm;
    e->judge = judge;
    e->judge_ctx = judge_ctx;
    e->action = action;
    e->action_ctx = action_ctx;
    e->msg_size_max = msg_size_max > 0 ? msg_size_max : RLO_MSG_SIZE_MAX;
    e->my_level = rlo_level(e->ws, rank);
    e->n_init = rlo_initiator_targets(e->ws, rank, e->init_targets, 64);
    e->own.state = RLO_INVALID;
    e->own.pid = -1;
    if (e->n_init < 0 || rlo_world_register(w, e) != RLO_OK) {
        free(e);
        return 0;
    }
    return e;
}

static void q_free_all(rlo_queue *q)
{
    for (rlo_msg *m = q->head; m;) {
        rlo_msg *nm = m->next;
        msg_free(m);
        m = nm;
    }
    q->head = q->tail = 0;
    q->len = 0;
}

void rlo_engine_free(rlo_engine *e)
{
    if (!e)
        return;
    rlo_world_unregister(e->w, e);
    q_free_all(&e->q_wait);
    q_free_all(&e->q_wait_pickup);
    q_free_all(&e->q_pickup);
    q_free_all(&e->q_iar_pending);
    for (int i = 0; i < e->own.n_decision; i++)
        rlo_handle_unref(e->own.decision_handles[i]);
    free(e->own.decision_handles);
    free(e->own.payload);
    free(e);
}

/* ---------------- rootless broadcast ---------------- */

/* Initiate without progressing (handlers use this; the public entry
 * progresses after). Returns the tracking msg via *out. */
static int bcast_init(rlo_engine *e, int tag, int32_t pid, int32_t vote,
                      const uint8_t *payload, int64_t len, rlo_msg **out)
{
    if (len < 0 || len > e->msg_size_max)
        return RLO_ERR_TOO_BIG;
    /* encode ONCE; every fan-out edge shares the blob by ref */
    rlo_blob *frame = frame_blob(e->rank, pid, vote, payload, len);
    if (!frame)
        return RLO_ERR_NOMEM;
    int err = RLO_ERR_NOMEM;
    rlo_msg *m = msg_from_frame(tag, -1, frame, &err); /* steals the ref */
    if (!m)
        return err;
    for (int i = 0; i < e->n_init; i++) { /* furthest-first */
        int rc = eng_isend_frame(e, e->init_targets[i], tag, m->frame, m);
        if (rc != RLO_OK) {
            msg_free(m);
            return rc;
        }
    }
    q_append(&e->q_wait, m);
    e->sent_bcast++;
    rlo_trace_emit(e->rank, RLO_EV_BCAST_INIT, tag, (int)len);
    if (out)
        *out = m;
    return RLO_OK;
}

int rlo_bcast(rlo_engine *e, const uint8_t *payload, int64_t len)
{
    int rc = bcast_init(e, RLO_TAG_BCAST, -1, -1, payload, len, 0);
    if (rc == RLO_OK)
        rlo_progress_all(e->w);
    return rc;
}

/* Forward a received broadcast along the overlay (reference _bc_forward,
 * rootless_ops.c:1104-1225). Returns the number of forwards or <0. */
static int bc_forward(rlo_engine *e, rlo_msg *m)
{
    int targets[64];
    int n = rlo_fwd_targets(e->ws, e->rank, m->origin, m->src, targets, 64);
    if (n < 0)
        return n;
    for (int i = 0; i < n; i++) {
        /* zero-copy store-and-forward: every hop shares the one blob */
        int rc = eng_isend_frame(e, targets[i], m->tag, m->frame, m);
        if (rc != RLO_OK)
            return rc;
    }
    if (n > 0)
        rlo_trace_emit(e->rank, RLO_EV_BCAST_FWD, m->tag, n);
    if (m->tag == RLO_TAG_IAR_PROPOSAL) {
        /* proposals are engine-internal: parked for the decision, never
         * user-visible (make_progress_gen :591-596) */
        q_append(&e->q_iar_pending, m);
    } else if (m->tag == RLO_TAG_IAR_DECISION) {
        /* delivery handled by on_decision */
    } else if (n > 0) {
        q_append(&e->q_wait_pickup, m);
    } else {
        m->fwd_done = 1;
        q_append(&e->q_pickup, m);
    }
    return n;
}

/* ---------------- IAR consensus ---------------- */

static int eng_judge(rlo_engine *e, const uint8_t *payload, int64_t len,
                     int pid)
{
    int verdict = e->judge ? (e->judge(payload, len, e->judge_ctx) ? 1 : 0)
                           : 1;
    rlo_trace_emit(e->rank, RLO_EV_JUDGE, pid, verdict);
    return verdict;
}

/* Send my (merged) vote to the rank the proposal came from (reference
 * _vote_back :728-741; nonblocking here). */
static int vote_back(rlo_engine *e, const rlo_prop *ps, int vote)
{
    rlo_trace_emit(e->rank, RLO_EV_VOTE, ps->pid, vote);
    return eng_isend(e, ps->recv_from, RLO_TAG_IAR_VOTE, e->rank, ps->pid,
                     vote, 0, 0, 0);
}

static rlo_msg *find_proposal_msg(rlo_engine *e, int pid)
{
    for (rlo_msg *m = e->q_iar_pending.head; m; m = m->next)
        if (m->ps && m->ps->pid == pid)
            return m;
    return 0;
}

static void set_err(rlo_engine *e, int err)
{
    if (e->err == RLO_OK)
        e->err = err;
}

static void on_proposal(rlo_engine *e, rlo_msg *m)
{
    if (e->own.state == RLO_IN_PROGRESS && m->pid == e->own.pid) {
        /* pid collision with my active proposal — the reference only
         * printf-warns (:690-692) and corrupts vote accounting; fail
         * loudly instead (matches the Python engine) */
        set_err(e, RLO_ERR_PROTO);
        msg_free(m);
        return;
    }
    rlo_prop *ps = (rlo_prop *)calloc(1, sizeof(*ps));
    if (!ps) {
        set_err(e, RLO_ERR_NOMEM);
        msg_free(m);
        return;
    }
    ps->pid = m->pid;
    ps->recv_from = m->src;
    ps->vote = 1;
    ps->state = RLO_IN_PROGRESS;
    ps->votes_needed =
        rlo_fwd_send_cnt(e->ws, e->rank, m->origin, m->src);
    m->ps = ps;
    if (!eng_judge(e, m->payload, m->len, ps->pid)) {
        /* decline: NO to parent immediately, don't forward — the subtree
         * below only ever sees the decision */
        vote_back(e, ps, 0);
        msg_free(m); /* frees ps too */
        return;
    }
    int sent = bc_forward(e, m); /* parks m in q_iar_pending */
    if (sent < 0) {
        /* bc_forward only fails before queueing — reclaim the msg */
        set_err(e, sent);
        msg_free(m);
    } else if (sent == 0) {
        vote_back(e, ps, 1); /* leaf: nothing to wait for */
    }
}

static void decision_bcast(rlo_engine *e)
{
    rlo_prop *p = &e->own;
    rlo_msg *m = 0;
    int rc = bcast_init(e, RLO_TAG_IAR_DECISION, p->pid, p->vote, 0, 0, &m);
    if (rc != RLO_OK) {
        set_err(e, rc);
        return;
    }
    /* retain the decision sends: the proposal completes only once the
     * decision has fanned out (reference :554-566) */
    p->decision_handles = (rlo_handle **)malloc(
        (size_t)(m->n_handles ? m->n_handles : 1) * sizeof(void *));
    if (!p->decision_handles) {
        set_err(e, RLO_ERR_NOMEM);
        return;
    }
    p->n_decision = m->n_handles;
    for (int i = 0; i < m->n_handles; i++) {
        p->decision_handles[i] = m->handles[i];
        m->handles[i]->refs++;
    }
    p->decision_pending = 1;
    rlo_trace_emit(e->rank, RLO_EV_DECISION, p->pid, p->vote);
}

static void on_vote(rlo_engine *e, rlo_msg *m)
{
    int pid = m->pid, vote = m->vote;
    rlo_prop *p = &e->own;
    if (pid == p->pid && p->state == RLO_IN_PROGRESS) {
        p->votes_recved++;
        p->vote &= vote;
        if (p->votes_recved == p->votes_needed) {
            if (p->vote)
                /* re-judge: a competing proposal may have changed app
                 * state since submission (reference :773) */
                p->vote = eng_judge(e, p->payload, p->len, p->pid);
            decision_bcast(e);
        }
        msg_free(m);
        return;
    }
    rlo_msg *pm = find_proposal_msg(e, pid);
    if (!pm) {
        set_err(e, RLO_ERR_PROTO);
        msg_free(m);
        return;
    }
    pm->ps->vote &= vote;
    pm->ps->votes_recved++;
    if (pm->ps->votes_recved == pm->ps->votes_needed)
        vote_back(e, pm->ps, pm->ps->vote);
    msg_free(m);
}

static void on_decision(rlo_engine *e, rlo_msg *m)
{
    rlo_msg *pm = find_proposal_msg(e, m->pid);
    int rc = bc_forward(e, m); /* forward first; delivery below */
    if (rc < 0)
        set_err(e, rc);
    if (pm) {
        if (m->vote && e->action)
            e->action(pm->payload, pm->len, e->action_ctx);
        q_remove(&e->q_iar_pending, pm);
        msg_free(pm);
    }
    /* deliver the decision to the user either way (reference :852-854) */
    q_append(&e->q_pickup, m);
}

int rlo_submit_proposal(rlo_engine *e, const uint8_t *proposal, int64_t len,
                        int pid)
{
    rlo_prop *p = &e->own;
    if (p->state == RLO_IN_PROGRESS)
        return RLO_ERR_BUSY;
    if (len < 0 || len > e->msg_size_max)
        return RLO_ERR_TOO_BIG;
    free(p->payload);
    for (int i = 0; i < p->n_decision; i++)
        rlo_handle_unref(p->decision_handles[i]);
    free(p->decision_handles);
    memset(p, 0, sizeof(*p));
    p->pid = pid;
    p->vote = 1;
    p->votes_needed = e->n_init;
    p->state = RLO_IN_PROGRESS;
    p->len = len;
    if (len > 0) {
        p->payload = (uint8_t *)malloc((size_t)len);
        if (!p->payload)
            return RLO_ERR_NOMEM;
        memcpy(p->payload, proposal, (size_t)len);
    }
    rlo_trace_emit(e->rank, RLO_EV_PROPOSAL_SUBMIT, pid, 0);
    int rc = bcast_init(e, RLO_TAG_IAR_PROPOSAL, pid, 1, proposal, len, 0);
    if (rc != RLO_OK) {
        p->state = RLO_FAILED;
        return rc;
    }
    rlo_progress_all(e->w);
    if (p->state == RLO_COMPLETED)
        return p->vote;
    return -1;
}

int rlo_check_proposal_state(rlo_engine *e)
{
    rlo_progress_all(e->w);
    return e->own.state;
}

int rlo_vote_my_proposal(rlo_engine *e)
{
    rlo_progress_all(e->w);
    if (e->own.state != RLO_COMPLETED)
        return -1;
    return e->own.vote;
}

void rlo_proposal_reset(rlo_engine *e)
{
    rlo_prop *p = &e->own;
    free(p->payload);
    for (int i = 0; i < p->n_decision; i++)
        rlo_handle_unref(p->decision_handles[i]);
    free(p->decision_handles);
    memset(p, 0, sizeof(*p));
    p->pid = -1;
    p->vote = 1;
    p->state = RLO_INVALID;
}

/* ---------------- delivery ---------------- */

static int64_t copy_out(rlo_msg *m, int *tag, int *origin, int *pid,
                        int *vote, uint8_t *buf, int64_t cap)
{
    if (m->len > cap)
        return RLO_ERR_TOO_BIG;
    if (tag)
        *tag = m->tag;
    if (origin)
        *origin = m->origin;
    if (pid)
        *pid = m->pid;
    if (vote)
        *vote = m->vote;
    if (m->len > 0)
        memcpy(buf, m->payload, (size_t)m->len);
    return m->len;
}

/* Head deliverable message: still-forwarding messages are eligible
 * first (reference order, RLO_user_pickup_next :938-979). */
static rlo_msg *pickup_head(rlo_engine *e, int *from_wait)
{
    if (e->q_wait_pickup.head) {
        *from_wait = 1;
        return e->q_wait_pickup.head;
    }
    *from_wait = 0;
    return e->q_pickup.head;
}

/* Retire one deliverable message (shared by pickup_next and
 * peek/consume). */
static void pickup_retire(rlo_engine *e, rlo_msg *m, int from_wait)
{
    e->total_pickup++;
    rlo_trace_emit(e->rank, RLO_EV_DELIVER, m->tag, m->origin);
    if (m == e->peeked)
        e->peeked = 0;
    if (from_wait) {
        q_remove(&e->q_wait_pickup, m);
        m->pickup_done = 1;
        q_append(&e->q_wait, m); /* keep tracking its forwards */
    } else {
        q_remove(&e->q_pickup, m);
        msg_free(m);
    }
}

/* Which delivery queue currently holds `m` (a progress turn may have
 * moved it from wait_and_pickup to pickup when its forwards finished). */
static int in_wait_pickup(const rlo_engine *e, const rlo_msg *m)
{
    for (const rlo_msg *x = e->q_wait_pickup.head; x; x = x->next)
        if (x == m)
            return 1;
    return 0;
}

int64_t rlo_pickup_next(rlo_engine *e, int *tag, int *origin, int *pid,
                        int *vote, uint8_t *buf, int64_t cap)
{
    int from_wait;
    rlo_msg *m = pickup_head(e, &from_wait);
    if (!m)
        return -1;
    int64_t n = copy_out(m, tag, origin, pid, vote, buf, cap);
    if (n < 0)
        return n;
    pickup_retire(e, m, from_wait);
    return n;
}

int64_t rlo_pickup_peek(rlo_engine *e, int *tag, int *origin, int *pid,
                        int *vote, const uint8_t **payload)
{
    int from_wait;
    rlo_msg *m = pickup_head(e, &from_wait);
    if (!m)
        return -1;
    e->peeked = m;
    if (tag)
        *tag = m->tag;
    if (origin)
        *origin = m->origin;
    if (pid)
        *pid = m->pid;
    if (vote)
        *vote = m->vote;
    if (payload)
        *payload = m->payload;
    return m->len;
}

int rlo_pickup_consume(rlo_engine *e)
{
    /* retire exactly the peeked message — a progress turn between peek
     * and consume may have changed the queue heads (or moved the peeked
     * message between delivery queues), and retiring whatever is head
     * now would silently swallow an undelivered message */
    rlo_msg *m = e->peeked;
    if (!m)
        return RLO_ERR_ARG;
    pickup_retire(e, m, in_wait_pickup(e, m));
    return RLO_OK;
}

/* ---------------- the gear (reference make_progress_gen :551-641) ------ */

void rlo_engine_progress_once(rlo_engine *e)
{
    /* (a) my own decision fan-out completion -> proposal COMPLETED */
    rlo_prop *p = &e->own;
    if (p->state == RLO_IN_PROGRESS && p->decision_pending) {
        int done = 1;
        for (int i = 0; i < p->n_decision; i++)
            if (!p->decision_handles[i]->delivered)
                done = 0;
        if (done) {
            p->state = RLO_COMPLETED;
            p->decision_pending = 0;
        }
    }

    /* (b) drain the transport, dispatch on tag (:569-624) */
    for (;;) {
        rlo_wire_node *n = rlo_world_poll(e->w, e->rank, e->comm);
        if (!n)
            break;
        /* steal the node's frame ref into the message — no copy */
        int err = RLO_ERR_PROTO;
        rlo_msg *m = msg_from_frame(n->tag, n->src, n->frame, &err);
        rlo_handle_unref(n->handle);
        free(n);
        if (!m) {
            set_err(e, err);
            continue;
        }
        switch (m->tag) {
        case RLO_TAG_BCAST: {
            e->recved_bcast++;
            int rc = bc_forward(e, m);
            if (rc < 0) {
                /* bc_forward only fails before queueing — reclaim */
                set_err(e, rc);
                msg_free(m);
            }
            break;
        }
        case RLO_TAG_IAR_PROPOSAL:
            on_proposal(e, m);
            break;
        case RLO_TAG_IAR_VOTE:
            on_vote(e, m);
            break;
        case RLO_TAG_IAR_DECISION:
            e->recved_bcast++;
            on_decision(e, m);
            break;
        default:
            /* aux tags go straight to pickup */
            m->fwd_done = 1;
            q_append(&e->q_pickup, m);
            break;
        }
    }

    /* (c) wait_and_pickup sweep (:995-1013): forwards done -> deliverable */
    for (rlo_msg *m = e->q_wait_pickup.head; m;) {
        rlo_msg *nm = m->next;
        if (msg_sends_done(m)) {
            m->fwd_done = 1;
            q_remove(&e->q_wait_pickup, m);
            q_append(&e->q_pickup, m);
        }
        m = nm;
    }

    /* (d) wait-only sweep (:1015-1034): completed sends are released */
    for (rlo_msg *m = e->q_wait.head; m;) {
        rlo_msg *nm = m->next;
        if (msg_sends_done(m)) {
            m->fwd_done = 1;
            q_remove(&e->q_wait, m);
            msg_free(m);
        }
        m = nm;
    }
}

/* ---------------- introspection ---------------- */

int rlo_engine_idle(const rlo_engine *e)
{
    return e->q_wait.len == 0 && e->q_wait_pickup.len == 0 &&
           !e->own.decision_pending;
}

int rlo_engine_err(const rlo_engine *e)
{
    return e->err;
}

int64_t rlo_engine_total_pickup(const rlo_engine *e)
{
    return e->total_pickup;
}

int64_t rlo_engine_sent_bcast(const rlo_engine *e)
{
    return e->sent_bcast;
}

int64_t rlo_engine_recved_bcast(const rlo_engine *e)
{
    return e->recved_bcast;
}
