/* Progress engine: cooperatively-polled state machine driving the rootless
 * broadcast and IAR leaderless-consensus ops.
 *
 * Native counterpart of rlo_tpu/engine.py; both mirror the reference
 * (struct progress_engine rootless_ops.c:202-253, make_progress_gen :551,
 * RLO_bcast_gen :1581, _bc_forward :1104, IAR handlers :668-932, pickup
 * :938-992) with the deliberate departures listed in rlo_core.h.
 */
/* for clock_gettime(CLOCK_MONOTONIC) under -std=c11 (the profiler
 * clock, now_usec_f) — must precede every system header */
#define _POSIX_C_SOURCE 199309L

#include "rlo_internal.h"

#include <stdio.h>
#include <time.h>

/* depth of the recent-broadcast ring log re-flooded on view changes */
#define RLO_RECENT_LOG 64
/* settled consensus rounds remembered for decision dedup */
#define RLO_SETTLED_LOG 256
/* per-origin out-of-order dedup window (bits above the contiguous
 * watermark); reordering beyond this collapses to at-most-once */
#define RLO_SEEN_BITS 256
#define RLO_SEEN_WORDS (RLO_SEEN_BITS / 64)

/* ---------------- intrusive message queue (reference queue_append/
 * queue_remove, rootless_ops.c:345-404) ---------------- */

typedef struct rlo_msg rlo_msg;

typedef struct rlo_queue {
    rlo_msg *head, *tail;
    int len;
} rlo_queue;

/* ---------------- per-proposal consensus bookkeeping (reference
 * Proposal_state, rootless_ops.c:184-194) ---------------- */

typedef struct rlo_prop {
    int pid;
    int gen;       /* round generation (disambiguates pid reuse) */
    int recv_from; /* parent in the vote tree */
    int vote;
    int votes_needed, votes_recved;
    int state; /* enum rlo_state */
    uint8_t *payload;
    int64_t len;
    rlo_handle **decision_handles;
    int n_decision;
    int decision_pending;
    /* direct children whose votes are outstanding — lets the failure
     * detector discount a dead child (mirror of ProposalState.await_from
     * in rlo_tpu/engine.py) */
    int await_from[64];
    int n_await;
    /* additional vote-tree parents acquired from duplicate proposals
     * (re-formed overlay trees); they receive the SAME merged vote as
     * recv_from when the round resolves — an interim verdict could
     * lose a subtree veto still in flight (round-2 advisor finding).
     * Mirror of ProposalState.dup_parents/resolved in engine.py. */
    int dup_parents[8];
    int n_dup;
    int resolved; /* merged vote determined and sent up */
} rlo_prop;

/* ---------------- ARQ retransmit entry (net-new; mirror of the Python
 * engine's _ArqEntry — the reference has no loss recovery at all,
 * SURVEY.md §5) ---------------- */

typedef struct rlo_rtx {
    /* main queue: doubly-linked, insertion (newest-first) order — the
     * retransmit SWEEP walks this list, so its walk order is exactly
     * the historical one */
    struct rlo_rtx *next, *prev;
    /* per-destination chain: cumulative ACKs from one peer touch only
     * that peer's entries (the ack scan was O(all unacked) before) */
    struct rlo_rtx *dnext, *dprev;
    int dst, tag, retries;
    int32_t seq;
    uint64_t due;  /* next retransmit time (usec) */
    uint64_t sent; /* first-transmission time (RTT sampling) */
    rlo_blob *frame;
    /* zero-copy large-payload entry (docs/DESIGN.md S13): frame is a
     * ref on the SHARED unstamped fan-out blob and hdr[] carries the
     * per-edge stamped header — sends and retransmits go through
     * rlo_world_isend_hdr, so the payload is never copied into a
     * per-frame arena. split == 0 entries own a stamped private
     * clone (the historical small-frame path, kept byte for byte). */
    int split;
    uint8_t hdr[RLO_HEADER_SIZE];
} rlo_rtx;

/* ---------------- in-flight message (reference RLO_msg_t,
 * rootless_ops.h:93-146) ---------------- */

struct rlo_msg {
    rlo_msg *prev, *next;
    int tag, src; /* src = immediate sender (~MPI_SOURCE) */
    int32_t origin, pid, vote, seq;
    rlo_blob *frame;        /* the encoded frame (owned ref) */
    const uint8_t *payload; /* aliases frame->data past the header */
    int64_t len;
    rlo_handle **handles;
    int n_handles, cap_handles;
    int pickup_done, fwd_done;
    rlo_prop *ps; /* for relayed IAR proposals */
    /* metrics stamps (0 = metrics were off at the event): initiation
     * time of a locally-initiated bcast and receipt time of a
     * deliverable message (mirror of _Msg.born/arrived in engine.py) */
    uint64_t born, arrived;
    /* profiler stamps (0 = profiler off at init, docs/DESIGN.md S10):
     * bcast init time for the first-forward/all-delivered phase
     * timers, and whether the first fan-out completion was observed
     * (mirror of _Msg.p_born/first_fwd in engine.py) */
    double p_born;
    int first_fwd;
};

struct rlo_engine {
    rlo_world *w;
    int rank, ws, comm;
    int64_t msg_size_max;
    rlo_judge_cb judge;
    void *judge_ctx;
    rlo_action_cb action;
    void *action_ctx;
    int my_level;
    int init_targets[64];
    int n_init;
    int fanout; /* RLO_FANOUT_* — bcast/IAR spanning-tree shape */
    rlo_queue q_wait, q_wait_pickup, q_pickup, q_iar_pending;
    int64_t sent_bcast, recved_bcast, total_pickup;
    rlo_prop own; /* my_own_proposal; own.payload = my proposal bytes */
    int err; /* sticky first protocol error */
    rlo_msg *peeked; /* message exposed by rlo_pickup_peek, not consumed */
    /* failure detection + elastic recovery (0 timeout = disabled;
     * mirror of the Python engine's failure_timeout machinery) */
    uint64_t fd_timeout, fd_interval;
    int gen_counter; /* per-engine round counter (see submit_proposal) */
    uint64_t hb_last_sent;
    uint64_t *hb_seen;  /* per rank: last heartbeat usec (0 = unseen) */
    uint8_t *failed;    /* per rank */
    int n_failed;
    int suspected_self;
    /* exactly-once broadcast (mirror of engine.py's _bcast_seq /
     * _seen_bcast / _recent_bcasts): every initiated BCAST frame is
     * stamped with a per-origin sequence number in the vote field;
     * receivers dedup on (origin, seq) before forwarding or
     * delivering, and on every adopted view change survivors re-flood
     * their recent-frame log point-to-point so a dead relay's
     * forwarding holes are plugged (dedup absorbs the duplication) */
    int32_t bcast_seq;
    int64_t *seen_contig;   /* per origin: all seqs <= contig seen */
    uint64_t *seen_mask;    /* per origin: 256-bit window above contig */
    rlo_blob *recent[RLO_RECENT_LOG];
    int recent_tag[RLO_RECENT_LOG]; /* BCAST or IAR_DECISION per entry */
    int recent_pos;
    /* settled consensus rounds (decision dedup across view changes) */
    struct { int32_t pid, gen; int used; } settled[RLO_SETTLED_LOG];
    int settled_pos;
    /* reliable delivery (ARQ; mirror of engine.py's arq_rto machinery,
     * net-new): per-dst link seq counters, a retransmit queue of
     * unacked frames, per-src receive dedup windows, and the per-src
     * "owes an ACK" flags flushed once per progress turn */
    uint64_t arq_rto; /* 0 = disabled */
    int arq_max_retries;
    int32_t *tx_seq;      /* per dst: next link seq */
    rlo_rtx *rtx_head;    /* unacked reliable frames (sweep order) */
    rlo_rtx **rtx_by_dst; /* per dst: that peer's chain (ack scans) */
    int64_t *rx_contig;   /* per src: all link seqs <= contig seen */
    uint64_t *rx_mask;    /* per src: window above contig */
    uint8_t *ack_due;     /* per src: cumulative ACK owed */
    /* per dst: highest given-up seq pending a SKIP notice (-1 none) +
     * its next-send time, and a per-tick scratch flag (see arq_tick) */
    int32_t *tx_skip;
    uint64_t *tx_skip_due;
    uint8_t *skip_hold;
    int64_t arq_retx, arq_dup, arq_gaveup, arq_unacked_cnt;
    /* lazy due-heap gating the retransmit sweep (docs/DESIGN.md S13;
     * C analogue of engine.py's _arq_due from PR 7): a binary
     * min-heap of wake-up times — one push per reliable send, per
     * retransmit re-arm, and per armed skip notice. Entries are PLAIN
     * DEADLINES (no identity): an acked frame's entry goes stale and
     * costs one empty sweep when it expires, which is what keeps the
     * hot path O(1) — arq_tick returns on a single heap peek while
     * the earliest deadline is in the future. INVARIANT: every live
     * retransmit entry and every armed skip notice has a heap entry
     * at or before its deadline, so the gate can never sleep past
     * real work. The sweep itself still walks the queue in insertion
     * order — wake-ups come from the heap, the walk order does not. */
    uint64_t *arq_heap;
    int arq_heap_len, arq_heap_cap;
    /* a wake-up was lost to a failed heap grow: the gate would sleep
     * past it, so sweeps run ungated until the queue fully drains and
     * the gate can re-arm from a clean slate */
    int arq_gate_degraded;
    int64_t arq_gated; /* sweeps skipped on the O(1) heap peek */
    /* lifetime frames polled off the transport (batched-progress
     * budget accounting; every polled frame counts, ACKs included) */
    int64_t frames_dispatched;
    /* metrics registry (mirror of engine.py's _mx_* machinery; see
     * rlo_core.h rlo_stats): per-peer link accounting + op-latency
     * histograms, collected only while metrics_on (one branch per
     * send/receive when off — the overhead contract) */
    int metrics_on;
    rlo_link_stats *links; /* ws entries; links[rank] stays zero */
    rlo_hist h_bcast, h_prop, h_pickup;
    uint64_t prop_born;
    /* in-engine phase profiler (docs/DESIGN.md S10; mirror of
     * engine.py's _prof_on/_ph machinery): per-stage log2 duration
     * histograms, collected only while profiler_on — one branch per
     * instrumented site when off (the overhead contract) */
    int profiler_on;
    rlo_phase_stats ph;
    double p_prop_born; /* submit stamp for the proposal phases (0=off) */
    /* membership-round watchdog: app op deadlines are Python-side,
     * but the ENGINE-initiated admission rounds need one here — a
     * round straddling a view change can park into a cyclic vote
     * tree (mixed old/new overlays) that never resolves, wedging the
     * own-proposal slot forever. 0 = unarmed. */
    uint64_t own_deadline;
    /* membership epochs + elastic rejoin (docs/DESIGN.md S8; mirror of
     * the Python engine's incarnation/epoch/JOIN machinery) */
    int32_t epoch;          /* monotone membership view counter */
    int64_t quarantined;    /* frames dropped by the epoch quarantine */
    int64_t rejoins_cnt;    /* admissions executed/adopted here */
    /* heal-cost counters (docs/DESIGN.md S17; mirror of engine.py's
     * view_changes/reflood_frames/... block — rlo-lint R2 pins the
     * rlo_stats schema): always-live plain counters */
    int64_t view_changes;   /* membership-view rebinds */
    int64_t reflood_frames; /* frames re-sent by the view-change flood */
    int64_t epoch_lag_max;  /* max(my epoch - accepted frame epoch) */
    int64_t quar_mid_rejoin, quar_failed_sender, quar_below_floor;
    int64_t admission_rounds; /* IAR admission rounds launched here */
    int64_t epoch_syncs;      /* MSYNC view adoptions (no full rejoin) */
    int64_t reflood_skipped;  /* advertised log entries already held */
    int64_t batched_admits;   /* joiners admitted in multi-joiner rounds */
    /* telemetry digest origination state (rlo_engine_telem_digest):
     * last-emitted sample (the delta base) + per-engine digest seq */
    int64_t telem_prev[RLO_TELEM_NKEYS];
    uint32_t telem_seq;
    int incarnation;        /* this engine's life at its rank */
    int awaiting_welcome;   /* joiner mode: quarantine + petition */
    int32_t welcome_epoch;  /* epoch of the last ADOPTED welcome */
    uint64_t join_last;     /* last JOIN probe burst (usec) */
    uint64_t join_interval; /* probe cadence (usec; 0 = default) */
    int32_t *epoch_floor;   /* per sender: min accepted link epoch
                             * (0 = no floor; floors are >= 1) */
    int32_t *link_epoch;    /* per dst: epoch of the edge's last
                             * link-state reset (the wire stamp) */
    int32_t *admit_epoch;   /* per joiner: highest admission epoch
                             * EXECUTED here (idempotence guard) */
    int32_t *admitted_inc;  /* per joiner: admitted incarnation (-1) */
    uint8_t *admitting;     /* joiners with an admission in flight */
    uint8_t *pending_join;  /* queued petitions awaiting the slot */
    int32_t *pending_inc;   /* petition incarnation per joiner */
    int32_t *pending_ep;    /* petition epoch per joiner */
    uint8_t *sub_excluded;  /* never probed/admitted (engine_new_sub) */
    uint8_t *gave_scratch;  /* per dst: ARQ give-up escalation flags */
    uint64_t *stale_probe_last; /* per src: last stale-sender nack */
    /* membership healing (docs/DESIGN.md S18): per member the CERTIFIED
     * link-reset epoch — set only when an admission executes HERE, so it
     * can seed third-party floors during MSYNC catch-up (the wholesale
     * welcome adoption cannot: it inflates admit_epoch for members whose
     * links were never reset) — plus the per-dst MSYNC request limiter */
    int32_t *reset_epoch;
    uint64_t *sync_req_last;
    int n_pending;          /* pending_join population */
    int n_excluded;         /* sub_excluded population */
};

/* Membership admission rounds live in the reserved pid namespace
 * pid <= RLO_MEMBER_PID_BASE (app pids are >= -1); pid =
 * BASE - (joiner * ws + proposer) keeps concurrent admissions of one
 * joiner by different proposers on distinct pids (a BATCHED round uses
 * the first joiner's pid). Record v2 (docs/DESIGN.md S18) admits k
 * queued joiners in ONE round: payload =
 * MAGIC + [new_epoch:i32][k:i32] + k x ([joiner:i32][incarnation:i32]).
 * Byte-identical to engine.py's MEMBER_MAGIC record. */
#define RLO_MEMBER_PID_BASE (-2)
#define RLO_MEMBER_MAGIC_LEN 5
static const uint8_t RLO_MEMBER_MAGIC[RLO_MEMBER_MAGIC_LEN] = {
    'R', 'L', 'O', 'J', 2};

/* MSYNC payload kind byte (first payload octet; mirrors engine.py's
 * MSYNC_REQ/RSP/AD/WANT constants — docs/DESIGN.md S18) */
#define RLO_MSYNC_REQ 0  /* <B><ii> requester epoch, incarnation */
#define RLO_MSYNC_RSP 1  /* <B><ii> epoch, n + n x <iii> + advert tail */
#define RLO_MSYNC_AD 2   /* <B><i> count + count x <iii> log idents */
#define RLO_MSYNC_WANT 3 /* <B><i> count + count x <iii> wanted idents */

static int32_t get_le32(const uint8_t *p)
{
    return (int32_t)((uint32_t)p[0] | ((uint32_t)p[1] << 8) |
                     ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24));
}

/* Decode a batched admission record (see RLO_MEMBER_MAGIC above):
 * returns k >= 1 with *new_epoch set and *recs pointing at the
 * k x [joiner:i32][inc:i32] body, or 0 on malformed/foreign payloads
 * (mirror of engine.py _member_decode). */
static int member_decode(const uint8_t *payload, int64_t len,
                         int32_t *new_epoch, const uint8_t **recs)
{
    if (!payload || len < RLO_MEMBER_MAGIC_LEN + 8 ||
        memcmp(payload, RLO_MEMBER_MAGIC, RLO_MEMBER_MAGIC_LEN))
        return 0;
    int k = get_le32(payload + RLO_MEMBER_MAGIC_LEN + 4);
    if (k < 1 || len < RLO_MEMBER_MAGIC_LEN + 8 + 8 * (int64_t)k)
        return 0;
    *new_epoch = get_le32(payload + RLO_MEMBER_MAGIC_LEN);
    *recs = payload + RLO_MEMBER_MAGIC_LEN + 8;
    return k;
}

/* ---------------- metrics helpers ---------------- */

static void hist_obs(rlo_hist *h, double v)
{
    int64_t iv = v <= 0 ? 0 : (int64_t)v;
    int b = 0;
    while (iv >> b)
        b++; /* bit_length */
    if (b > RLO_HIST_BUCKETS - 1)
        b = RLO_HIST_BUCKETS - 1;
    if (h->count == 0) {
        h->min = v;
        h->max = v;
    } else {
        if (v < h->min)
            h->min = v;
        if (v > h->max)
            h->max = v;
    }
    h->count++;
    h->sum += v;
    h->buckets[b]++;
}

/* ---------------- phase profiler (docs/DESIGN.md S10) ---------------- */

/* field indices into rlo_phase_stats — the ENGINE_PHASE_KEYS snapshot
 * order shared with the Python engine (and the Ev.PHASE a field) */
enum {
    RLO_PH_FRAME_ENCODE = 0,
    RLO_PH_FRAME_DECODE,
    RLO_PH_SEND,
    RLO_PH_ARQ_SCAN,
    RLO_PH_TAG_DISPATCH,
    RLO_PH_PICKUP_DRAIN,
    RLO_PH_BCAST_FIRST_FWD,
    RLO_PH_BCAST_ALL_DELIVERED,
    RLO_PH_PROP_VOTES_AGGREGATED,
    RLO_PH_PROP_DECISION,
};

/* profiler clock: monotonic, sub-usec resolution as double usec —
 * rlo_now_usec's 1 usec granularity would round most hot-path stages
 * (a header pack, one isend into an in-process ring) to zero */
static double now_usec_f(void)
{
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec * 1e6 + (double)ts.tv_nsec / 1e3;
}

/* Record one stage sample: duration since t0 into the phase histogram
 * (rlo_phase_stats is 10 contiguous rlo_hist fields, so indexing off
 * the first is well-defined), plus an RLO_EV_PHASE trace event when
 * the tracer is live. Callers gate on profiler_on — this is never
 * reached on the disabled path. */
static void ph_obs(rlo_engine *e, int idx, double t0)
{
    double dur = now_usec_f() - t0;
    hist_obs((rlo_hist *)&e->ph + idx, dur);
    if (rlo_trace_enabled())
        rlo_trace_emit(e->rank, RLO_EV_PHASE, idx,
                       dur >= 2147483647.0 ? 2147483647
                                           : (int)(dur < 0 ? 0 : dur),
                       0, 0);
}

static void rtt_sample(rlo_link_stats *ls, double usec)
{
    if (usec < 1.0)
        /* below clock resolution; clamp so a real sample can never
         * collide with the 0.0 "unmeasured" sentinel */
        usec = 1.0;
    if (ls->rtt_ewma_usec == 0.0)
        ls->rtt_ewma_usec = usec;
    else
        ls->rtt_ewma_usec += (usec - ls->rtt_ewma_usec) / 8.0;
}

/* correlation identity a trace event carries in its c field: the
 * per-origin exactly-once seq for BCAST frames (it travels in the
 * vote field), the pid for everything else */
static int32_t trace_ident(int tag, int32_t pid, int32_t vote)
{
    return tag == RLO_TAG_BCAST ? vote : pid;
}

/* ---------------- queue ops ---------------- */

/* rlo-sentinel: transfers(m) — the queue owns it until retired */
static void q_append(rlo_queue *q, rlo_msg *m)
{
    m->next = 0;
    m->prev = q->tail;
    if (q->tail)
        q->tail->next = m;
    else
        q->head = m;
    q->tail = m;
    q->len++;
}

static void q_remove(rlo_queue *q, rlo_msg *m)
{
    if (m->prev)
        m->prev->next = m->next;
    else
        q->head = m->next;
    if (m->next)
        m->next->prev = m->prev;
    else
        q->tail = m->prev;
    m->prev = m->next = 0;
    q->len--;
}

/* ---------------- msg lifecycle ---------------- */

/* Encode one frame into a fresh blob (the single copy a send makes;
 * every fan-out edge then shares it by ref; the ARQ path clones and
 * re-stamps per edge). */
static rlo_blob *frame_blob(rlo_world *w, int32_t origin, int32_t pid,
                            int32_t vote, const uint8_t *payload,
                            int64_t len)
{
    rlo_blob *b = rlo_blob_new_w(w, RLO_HEADER_SIZE + len);
    if (!b)
        return 0;
    if (rlo_frame_encode(b->data, b->len, origin, pid, vote, -1, payload,
                         len) < 0) {
        rlo_blob_unref(b);
        return 0;
    }
    return b;
}

/* Wrap a received or freshly-encoded frame blob into a message; STEALS
 * the caller's blob ref (unrefs it on failure, storing RLO_ERR_PROTO or
 * RLO_ERR_NOMEM in *err so callers report the true cause). */
/* rlo-sentinel: owns — returns a caller-owned message */
static rlo_msg *msg_from_frame(rlo_world *w, int tag, int src,
                               rlo_blob *frame, int *err)
{
    int32_t origin, pid, vote, seq;
    const uint8_t *payload;
    int64_t plen = rlo_frame_decode(frame->data, frame->len, &origin,
                                    &pid, &vote, &seq, &payload);
    if (plen < 0) {
        if (err)
            *err = RLO_ERR_PROTO;
        rlo_blob_unref(frame);
        return 0;
    }
    rlo_msg *m = (rlo_msg *)rlo_pool_alloc(w, sizeof(*m));
    if (m)
        memset(m, 0, sizeof(*m));
    if (!m) {
        if (err)
            *err = RLO_ERR_NOMEM;
        rlo_blob_unref(frame);
        return 0;
    }
    m->tag = tag;
    m->src = src;
    m->origin = origin;
    m->pid = pid;
    m->vote = vote;
    m->seq = seq;
    m->frame = frame;
    m->payload = payload;
    m->len = plen;
    return m;
}

/* rlo-sentinel: transfers(p) */
static void prop_free(rlo_prop *p)
{
    if (!p)
        return;
    for (int i = 0; i < p->n_decision; i++)
        rlo_handle_unref(p->decision_handles[i]);
    free(p->decision_handles);
    free(p->payload);
    free(p);
}

/* rlo-sentinel: transfers(m) */
static void msg_free(rlo_msg *m)
{
    if (!m)
        return;
    for (int i = 0; i < m->n_handles; i++)
        rlo_handle_unref(m->handles[i]);
    free(m->handles);
    rlo_blob_unref(m->frame);
    prop_free(m->ps);
    rlo_pool_free(m);
}

static int msg_track(rlo_msg *m, rlo_handle *h)
{
    if (m->n_handles == m->cap_handles) {
        int cap = m->cap_handles ? m->cap_handles * 2 : 4;
        rlo_handle **p = (rlo_handle **)realloc(
            m->handles, (size_t)cap * sizeof(void *));
        if (!p)
            return RLO_ERR_NOMEM;
        m->handles = p;
        m->cap_handles = cap;
    }
    m->handles[m->n_handles++] = h;
    return RLO_OK;
}

static int msg_sends_done(const rlo_msg *m)
{
    for (int i = 0; i < m->n_handles; i++)
        if (!m->handles[i]->delivered)
            return 0;
    return 1;
}

/* Any fan-out send completed (the profiler's first-forward phase
 * anchor); zero handles counts as none. */
static int msg_any_send_done(const rlo_msg *m)
{
    for (int i = 0; i < m->n_handles; i++)
        if (m->handles[i]->delivered)
            return 1;
    return 0;
}

/* ---------------- send helper ---------------- */

static void put_le32(uint8_t *dst, int v)
{
    dst[0] = (uint8_t)(v & 0xff);
    dst[1] = (uint8_t)((v >> 8) & 0xff);
    dst[2] = (uint8_t)((v >> 16) & 0xff);
    dst[3] = (uint8_t)((v >> 24) & 0xff);
}

static void arq_heap_push(rlo_engine *e, uint64_t due);

/* rlo-sentinel: transfers(rt) — the retransmit queue owns it */
static void rtx_link(rlo_engine *e, rlo_rtx *rt)
{
    rt->prev = 0;
    rt->next = e->rtx_head;
    if (e->rtx_head)
        e->rtx_head->prev = rt;
    e->rtx_head = rt;
    rt->dprev = 0;
    rt->dnext = e->rtx_by_dst[rt->dst];
    if (rt->dnext)
        rt->dnext->dprev = rt;
    e->rtx_by_dst[rt->dst] = rt;
    e->arq_unacked_cnt++;
}

/* Unlink from both lists and release; O(1). */
static void rtx_release(rlo_engine *e, rlo_rtx *rt)
{
    if (rt->prev)
        rt->prev->next = rt->next;
    else
        e->rtx_head = rt->next;
    if (rt->next)
        rt->next->prev = rt->prev;
    if (rt->dprev)
        rt->dprev->dnext = rt->dnext;
    else
        e->rtx_by_dst[rt->dst] = rt->dnext;
    if (rt->dnext)
        rt->dnext->dprev = rt->dprev;
    rlo_blob_unref(rt->frame);
    rlo_pool_free(rt);
    e->arq_unacked_cnt--;
}

/* Tags the ARQ layer neither stamps nor retransmits: heartbeats are
 * periodic by construction, and ACKs ack themselves by effect (a lost
 * ACK just costs one more retransmit, absorbed by the dedup). JOIN
 * probes repeat at their own cadence until answered, and a lost
 * WELCOME is replaced when the next probe arrives — both must also
 * work across the membership boundary where link state is reset. */
static int arq_exempt(int tag)
{
    return tag == RLO_TAG_HEARTBEAT || tag == RLO_TAG_ACK ||
           tag == RLO_TAG_JOIN || tag == RLO_TAG_JOIN_WELCOME ||
           tag == RLO_TAG_MSYNC;
}

/* isend one already-encoded frame blob; when track_in != NULL the
 * completion handle is retained on that message (votes pass NULL — fire
 * and forget; with ARQ enabled they are ALSO reliable: a dropped vote
 * retransmits until acked instead of wedging the consensus round).
 *
 * This is the one gate every engine frame leaves through: with ARQ on,
 * non-exempt frames are cloned, stamped with the next per-(src, dst)
 * link seq (the shared fan-out blob must not be mutated — each edge
 * carries a different seq), queued for retransmission, and only then
 * handed to the transport. */
/* rlo_world_isend with the profiler's send-stage timer (one branch on
 * the disabled path — the S10 overhead contract). */
static int isend_timed(rlo_engine *e, int dst, int tag, rlo_blob *frame,
                       rlo_handle **h)
{
    if (!e->profiler_on)
        return rlo_world_isend(e->w, e->rank, dst, e->comm, tag, frame,
                               h);
    double t0 = now_usec_f();
    int rc = rlo_world_isend(e->w, e->rank, dst, e->comm, tag, frame,
                             h);
    ph_obs(e, RLO_PH_SEND, t0);
    return rc;
}

/* Gather-send twin of isend_timed for the zero-copy ARQ path: the
 * stamped header travels as caller staging, the payload stays in the
 * shared fan-out blob (rlo_world_isend_hdr materializes a contiguous
 * copy only on transports without scatter-gather). */
static int isend_hdr_timed(rlo_engine *e, int dst, int tag,
                           const uint8_t *hdr, rlo_blob *frame,
                           rlo_handle **h)
{
    if (!e->profiler_on)
        return rlo_world_isend_hdr(e->w, e->rank, dst, e->comm, tag,
                                   hdr, frame, h);
    double t0 = now_usec_f();
    int rc = rlo_world_isend_hdr(e->w, e->rank, dst, e->comm, tag, hdr,
                                 frame, h);
    ph_obs(e, RLO_PH_SEND, t0);
    return rc;
}

static int eng_isend_frame(rlo_engine *e, int dst, int tag,
                           rlo_blob *frame, rlo_msg *track_in)
{
    rlo_handle *h = 0;
    int rc;
    if (e->metrics_on && dst >= 0 && dst < e->ws) {
        e->links[dst].tx_frames++;
        e->links[dst].tx_bytes += frame->len;
    }
    if (e->arq_rto && !arq_exempt(tag) && dst >= 0 && dst < e->ws) {
        rlo_rtx *rt = (rlo_rtx *)rlo_pool_alloc(e->w, sizeof(*rt));
        if (!rt)
            return RLO_ERR_NOMEM;
        memset(rt, 0, sizeof(*rt));
        /* large payloads take the zero-copy path (docs/DESIGN.md
         * S13): the per-edge seq/epoch is stamped into a 28-byte
         * header staging inside the retransmit entry and the SHARED
         * fan-out blob is ref'd as-is — no payload clone per edge.
         * Small frames keep the historical clone-and-stamp path.
         * Retransmits resend the same bytes either way. */
        int split = frame->len >= RLO_HEADER_SIZE + RLO_ZC_MIN_PAYLOAD;
        rlo_blob *stamped = 0;
        if (!split) {
            stamped = rlo_blob_new_w(e->w, frame->len);
            if (!stamped) {
                rlo_pool_free(rt);
                return RLO_ERR_NOMEM;
            }
            memcpy(stamped->data, frame->data, (size_t)frame->len);
        } else {
            memcpy(rt->hdr, frame->data, RLO_HEADER_SIZE);
        }
        uint8_t *stamp = split ? rt->hdr : stamped->data;
        int32_t seq = e->tx_seq[dst]++;
        put_le32(stamp + RLO_SEQ_OFFSET, seq);
        rlo_frame_set_epoch(stamp, e->link_epoch[dst]);
        rt->split = split;
        rt->dst = dst;
        rt->tag = tag;
        rt->seq = seq;
        rt->sent = rlo_now_usec();
        rt->due = rt->sent + e->arq_rto;
        rt->frame = rlo_blob_ref(split ? frame : stamped);
        rtx_link(e, rt);
        arq_heap_push(e, rt->due);
        rc = split ? isend_hdr_timed(e, dst, tag, rt->hdr, frame,
                                     track_in ? &h : 0)
                   : isend_timed(e, dst, tag, stamped,
                                 track_in ? &h : 0);
        rlo_blob_unref(stamped); /* NULL-safe on the split path */
    } else {
        /* link-epoch stamp (docs/DESIGN.md S8): the fan-out blob is
         * SHARED across edges and (zero-copy) with in-process
         * receivers, so when the edge's link epoch differs from what
         * the blob carries, stamp a private copy — with all link
         * epochs at 0 (no membership churn) this never copies */
        int32_t lep = (dst >= 0 && dst < e->ws) ? e->link_epoch[dst]
                                                : 0;
        if (frame->len >= RLO_HEADER_SIZE &&
            rlo_frame_epoch(frame->data) != lep) {
            rlo_blob *st = rlo_blob_new_w(e->w, frame->len);
            if (!st)
                return RLO_ERR_NOMEM;
            memcpy(st->data, frame->data, (size_t)frame->len);
            rlo_frame_set_epoch(st->data, lep);
            rc = isend_timed(e, dst, tag, st, track_in ? &h : 0);
            rlo_blob_unref(st);
        } else {
            rc = isend_timed(e, dst, tag, frame, track_in ? &h : 0);
        }
    }
    if (rc == RLO_OK && track_in)
        rc = msg_track(track_in, h);
    return rc;
}

/* Encode + send a one-off frame (votes). */
static int eng_isend(rlo_engine *e, int dst, int tag, int32_t origin,
                     int32_t pid, int32_t vote, const uint8_t *payload,
                     int64_t len, rlo_msg *track_in)
{
    rlo_blob *frame;
    if (e->profiler_on) {
        double t0 = now_usec_f();
        frame = frame_blob(e->w, origin, pid, vote, payload, len);
        ph_obs(e, RLO_PH_FRAME_ENCODE, t0);
    } else {
        frame = frame_blob(e->w, origin, pid, vote, payload, len);
    }
    if (!frame)
        return RLO_ERR_NOMEM;
    int rc = eng_isend_frame(e, dst, tag, frame, track_in);
    rlo_blob_unref(frame);
    return rc;
}

/* ---------------- engine create/free ---------------- */

rlo_engine *rlo_engine_new(rlo_world *w, int rank, int comm,
                           rlo_judge_cb judge, void *judge_ctx,
                           rlo_action_cb action, void *action_ctx,
                           int64_t msg_size_max)
{
    if (!w || rank < 0 || rank >= rlo_world_size(w))
        return 0;
    /* one-process-per-rank transports (shm/mpi) bind the world to a rank */
    if (rlo_world_my_rank(w) >= 0 && rank != rlo_world_my_rank(w))
        return 0;
    rlo_engine *e = (rlo_engine *)calloc(1, sizeof(*e));
    if (!e)
        return 0;
    e->w = w;
    e->rank = rank;
    e->ws = rlo_world_size(w);
    e->comm = comm;
    e->judge = judge;
    e->judge_ctx = judge_ctx;
    e->action = action;
    e->action_ctx = action_ctx;
    e->msg_size_max = msg_size_max > 0 ? msg_size_max : RLO_MSG_SIZE_MAX;
    e->my_level = rlo_level(e->ws, rank);
    e->n_init = rlo_initiator_targets(e->ws, rank, e->init_targets, 64);
    /* runtime schedule switch (net-new config surface, SURVEY.md §5):
     * RLO_FANOUT=flat makes every engine depth-1; the per-engine
     * setter overrides */
    {
        const char *fo = getenv("RLO_FANOUT");
        e->fanout = (fo && !strcmp(fo, "flat")) ? RLO_FANOUT_FLAT
                                                : RLO_FANOUT_SKIP_RING;
    }
    e->own.state = RLO_INVALID;
    e->own.pid = -1;
    /* always present so a FAILURE notice from a detecting peer is
     * adopted even when this engine's own detector is off */
    e->failed = (uint8_t *)calloc((size_t)e->ws, 1);
    e->hb_seen = (uint64_t *)calloc((size_t)e->ws, sizeof(uint64_t));
    e->seen_contig = (int64_t *)malloc((size_t)e->ws * sizeof(int64_t));
    e->seen_mask = (uint64_t *)calloc((size_t)e->ws * RLO_SEEN_WORDS,
                                      sizeof(uint64_t));
    e->tx_seq = (int32_t *)calloc((size_t)e->ws, sizeof(int32_t));
    e->rtx_by_dst =
        (rlo_rtx **)calloc((size_t)e->ws, sizeof(void *));
    e->rx_contig = (int64_t *)malloc((size_t)e->ws * sizeof(int64_t));
    e->rx_mask = (uint64_t *)calloc((size_t)e->ws * RLO_SEEN_WORDS,
                                    sizeof(uint64_t));
    e->ack_due = (uint8_t *)calloc((size_t)e->ws, 1);
    e->tx_skip = (int32_t *)malloc((size_t)e->ws * sizeof(int32_t));
    e->tx_skip_due =
        (uint64_t *)calloc((size_t)e->ws, sizeof(uint64_t));
    e->skip_hold = (uint8_t *)calloc((size_t)e->ws, 1);
    e->links = (rlo_link_stats *)calloc((size_t)e->ws,
                                        sizeof(rlo_link_stats));
    e->epoch_floor = (int32_t *)calloc((size_t)e->ws, sizeof(int32_t));
    e->link_epoch = (int32_t *)calloc((size_t)e->ws, sizeof(int32_t));
    e->admit_epoch = (int32_t *)calloc((size_t)e->ws, sizeof(int32_t));
    e->admitted_inc = (int32_t *)malloc((size_t)e->ws * sizeof(int32_t));
    e->admitting = (uint8_t *)calloc((size_t)e->ws, 1);
    e->pending_join = (uint8_t *)calloc((size_t)e->ws, 1);
    e->pending_inc = (int32_t *)calloc((size_t)e->ws, sizeof(int32_t));
    e->pending_ep = (int32_t *)calloc((size_t)e->ws, sizeof(int32_t));
    e->sub_excluded = (uint8_t *)calloc((size_t)e->ws, 1);
    e->gave_scratch = (uint8_t *)calloc((size_t)e->ws, 1);
    e->stale_probe_last =
        (uint64_t *)calloc((size_t)e->ws, sizeof(uint64_t));
    e->reset_epoch = (int32_t *)calloc((size_t)e->ws, sizeof(int32_t));
    e->sync_req_last =
        (uint64_t *)calloc((size_t)e->ws, sizeof(uint64_t));
    if (e->seen_contig)
        for (int r = 0; r < e->ws; r++)
            e->seen_contig[r] = -1;
    if (e->rx_contig)
        for (int r = 0; r < e->ws; r++)
            e->rx_contig[r] = -1;
    if (e->tx_skip)
        for (int r = 0; r < e->ws; r++)
            e->tx_skip[r] = -1;
    if (e->admitted_inc)
        for (int r = 0; r < e->ws; r++)
            e->admitted_inc[r] = -1;
    if (e->n_init < 0 || !e->failed || !e->hb_seen || !e->seen_contig ||
        !e->seen_mask || !e->tx_seq || !e->rtx_by_dst ||
        !e->rx_contig || !e->rx_mask ||
        !e->ack_due || !e->tx_skip || !e->tx_skip_due || !e->skip_hold ||
        !e->links || !e->epoch_floor || !e->link_epoch ||
        !e->admit_epoch || !e->admitted_inc || !e->admitting ||
        !e->pending_join || !e->pending_inc || !e->pending_ep ||
        !e->sub_excluded || !e->gave_scratch ||
        !e->stale_probe_last || !e->reset_epoch || !e->sync_req_last ||
        rlo_world_register(w, e) != RLO_OK) {
        free(e->failed);
        free(e->hb_seen);
        free(e->seen_contig);
        free(e->seen_mask);
        free(e->tx_seq);
        free(e->rtx_by_dst);
        free(e->rx_contig);
        free(e->rx_mask);
        free(e->ack_due);
        free(e->tx_skip);
        free(e->tx_skip_due);
        free(e->skip_hold);
        free(e->links);
        free(e->epoch_floor);
        free(e->link_epoch);
        free(e->admit_epoch);
        free(e->admitted_inc);
        free(e->admitting);
        free(e->pending_join);
        free(e->pending_inc);
        free(e->pending_ep);
        free(e->sub_excluded);
        free(e->gave_scratch);
        free(e->stale_probe_last);
        free(e->reset_epoch);
        free(e->sync_req_last);
        free(e);
        return 0;
    }
    return e;
}

rlo_engine *rlo_engine_new_sub(rlo_world *w, int rank, int comm,
                               const int *members, int n_members,
                               rlo_judge_cb judge, void *judge_ctx,
                               rlo_action_cb action, void *action_ctx,
                               int64_t msg_size_max)
{
    if (!members || n_members < 2 || n_members > rlo_world_size(w))
        return 0;
    int in_group = 0;
    for (int i = 0; i < n_members; i++) {
        if (members[i] < 0 || members[i] >= rlo_world_size(w))
            return 0;
        if (members[i] == rank)
            in_group = 1;
    }
    if (!in_group)
        return 0;
    rlo_engine *e = rlo_engine_new(w, rank, comm, judge, judge_ctx,
                                   action, action_ctx, msg_size_max);
    if (!e)
        return 0;
    /* subset = the elastic-reforming translation with the non-members
     * permanently excluded: every routed path (cur_init_targets,
     * cur_fwd_targets, ring_neighbors, reflood, discounting) already
     * consults the alive view (mirror of ProgressEngine(members=...)).
     * Excluded ranks are never probed or admitted (they are not
     * failed members — they were never members at all). */
    for (int r = 0; r < e->ws; r++)
        e->failed[r] = 1;
    for (int i = 0; i < n_members; i++)
        e->failed[members[i]] = 0;
    e->n_failed = 0;
    for (int r = 0; r < e->ws; r++) {
        e->n_failed += e->failed[r];
        e->sub_excluded[r] = e->failed[r];
    }
    e->n_excluded = e->n_failed;
    return e;
}

static void q_free_all(rlo_queue *q)
{
    for (rlo_msg *m = q->head; m;) {
        rlo_msg *nm = m->next;
        msg_free(m);
        m = nm;
    }
    q->head = q->tail = 0;
    q->len = 0;
}

void rlo_engine_free(rlo_engine *e)
{
    if (!e)
        return;
    rlo_world_unregister(e->w, e);
    q_free_all(&e->q_wait);
    q_free_all(&e->q_wait_pickup);
    q_free_all(&e->q_pickup);
    q_free_all(&e->q_iar_pending);
    for (int i = 0; i < e->own.n_decision; i++)
        rlo_handle_unref(e->own.decision_handles[i]);
    free(e->own.decision_handles);
    free(e->own.payload);
    free(e->failed);
    free(e->hb_seen);
    free(e->seen_contig);
    free(e->seen_mask);
    free(e->tx_seq);
    free(e->rx_contig);
    free(e->rx_mask);
    free(e->ack_due);
    free(e->tx_skip);
    free(e->tx_skip_due);
    free(e->skip_hold);
    free(e->links);
    free(e->epoch_floor);
    free(e->link_epoch);
    free(e->admit_epoch);
    free(e->admitted_inc);
    free(e->admitting);
    free(e->pending_join);
    free(e->pending_inc);
    free(e->pending_ep);
    free(e->sub_excluded);
    free(e->gave_scratch);
    free(e->stale_probe_last);
    free(e->reset_epoch);
    free(e->sync_req_last);
    while (e->rtx_head)
        rtx_release(e, e->rtx_head);
    free(e->rtx_by_dst);
    free(e->arq_heap);
    for (int i = 0; i < RLO_RECENT_LOG; i++)
        rlo_blob_unref(e->recent[i]);
    free(e);
}

/* ---------------- elastic topology (over the alive set) ------------
 * Mirror of the Python engine's _cur_initiator_targets/_fwd_targets:
 * identity to the static topology while nothing has failed; after a
 * failure, the skip-ring math runs on virtual ranks = indices into the
 * sorted alive set. */

static int vrank_of(const rlo_engine *e, int r)
{
    if (!e->n_failed)
        return r;
    if (e->failed[r])
        return -1;
    int v = 0;
    for (int i = 0; i < r; i++)
        if (!e->failed[i])
            v++;
    return v;
}

static int real_of(const rlo_engine *e, int v)
{
    if (!e->n_failed)
        return v;
    for (int r = 0; r < e->ws; r++)
        if (!e->failed[r] && v-- == 0)
            return r;
    return -1;
}

static int cur_init_targets(rlo_engine *e, int *out, int cap)
{
    if (e->fanout == RLO_FANOUT_FLAT) {
        /* flat spanning tree: the origin sends to every live member
         * directly; receivers are leaves. Depth-1 scheduling for
         * latency-bound cases where ONE rank should pay all sends.
         * Measured caveat (round-4 judge re-run, oversubscribed
         * 8-process host, 4 KB frames): flat was 1.22x native vs the
         * skip-ring's 1.10x — store-and-forward spreads the send
         * work over ranks and wins even there, so the skip-ring is
         * the default everywhere and case_nbcast races both each
         * run. Rootlessness, the (origin, seq) dedup, and IAR vote
         * accounting are schedule-independent — the proposer simply
         * awaits ws-1 leaf votes. */
        int n = 0;
        for (int r = 0; r < e->ws; r++) {
            if (r == e->rank || e->failed[r])
                continue;
            if (n >= cap)
                return RLO_ERR_ARG;
            out[n++] = r;
        }
        return n;
    }
    if (!e->n_failed) {
        int n = e->n_init < cap ? e->n_init : cap;
        memcpy(out, e->init_targets, (size_t)n * sizeof(int));
        return n;
    }
    int vws = e->ws - e->n_failed;
    if (vws < 2)
        return 0;
    int vt[64];
    int n = rlo_initiator_targets(vws, vrank_of(e, e->rank), vt, 64);
    if (n < 0 || n > cap)
        return RLO_ERR_ARG;
    for (int i = 0; i < n; i++)
        out[i] = real_of(e, vt[i]);
    return n;
}

static int cur_fwd_targets(rlo_engine *e, int origin, int src, int *out,
                           int cap)
{
    if (e->fanout == RLO_FANOUT_FLAT)
        return 0; /* flat: the origin reached everyone; deliver only */
    if (!e->n_failed)
        return rlo_fwd_targets(e->ws, e->rank, origin, src, out, cap);
    if (origin < 0 || origin >= e->ws || src < 0 || src >= e->ws ||
        e->failed[origin] || e->failed[src])
        return 0; /* stale pre-failure route: deliver-only */
    int vws = e->ws - e->n_failed;
    if (vws < 2)
        return 0;
    int vt[64];
    int n = rlo_fwd_targets(vws, vrank_of(e, e->rank),
                            vrank_of(e, origin), vrank_of(e, src), vt, 64);
    if (n < 0 || n > cap)
        return RLO_ERR_ARG;
    for (int i = 0; i < n; i++)
        out[i] = real_of(e, vt[i]);
    return n;
}

static int round_settled_peek(const rlo_engine *e, int32_t pid,
                              int32_t gen);
static int announce_failed(rlo_engine *e, int rank);
static void become_joiner(rlo_engine *e);
static int execute_admission(rlo_engine *e, int joiner, int inc,
                             int32_t new_epoch);
static void finish_member_round(rlo_engine *e);
static void request_sync(rlo_engine *e, int dst);
static void msync_serve(rlo_engine *e, int dst);
static void on_msync(rlo_engine *e, rlo_msg *m);

/* ---------------- exactly-once broadcast dedup -------------------- */

/* Shift the 256-bit window right by k bits (toward bit 0). */
static void seen_shift(uint64_t *m, int64_t k)
{
    while (k >= 64) {
        for (int i = 0; i < RLO_SEEN_WORDS - 1; i++)
            m[i] = m[i + 1];
        m[RLO_SEEN_WORDS - 1] = 0;
        k -= 64;
    }
    if (k > 0) {
        for (int i = 0; i < RLO_SEEN_WORDS; i++) {
            m[i] >>= k;
            if (i + 1 < RLO_SEEN_WORDS)
                m[i] |= m[i + 1] << (64 - k);
        }
    }
}

/* Record `seq` in a watermark+window dedup structure; returns 1 when it
 * was already seen. Bit i of the window is seq contig+1+i. Shared by
 * the app-level (origin, seq) broadcast dedup and the link-level
 * (sender, seq) ARQ dedup — same algorithm, different key spaces. */
static int window_record(int64_t *contig, uint64_t *mask, int64_t seq)
{
    if (seq <= *contig)
        return 1;
    int64_t off = seq - *contig - 1;
    if (off >= RLO_SEEN_BITS) {
        /* reorder beyond the window: absorb the oldest gaps as seen
         * (collapses to at-most-once for seqs that stale) */
        int64_t shift = off - (RLO_SEEN_BITS - 1);
        if (shift >= RLO_SEEN_BITS) /* clamp: a huge gap clears all */
            memset(mask, 0, RLO_SEEN_WORDS * sizeof(uint64_t));
        else
            seen_shift(mask, shift);
        *contig += shift;
        off = RLO_SEEN_BITS - 1;
    }
    if (mask[off >> 6] & (1ull << (off & 63)))
        return 1;
    mask[off >> 6] |= 1ull << (off & 63);
    while (mask[0] & 1) { /* advance the contiguous watermark */
        seen_shift(mask, 1);
        (*contig)++;
    }
    return 0;
}

/* Check-only variant of window_record: never mutates the window, so it
 * is safe inside the MSYNC advert filter (have_log_entry) — recording
 * there would poison the dedup against the real frame that the WANT
 * round is about to fetch. */
static int window_peek(const int64_t *contig, const uint64_t *mask,
                       int64_t seq)
{
    if (seq <= *contig)
        return 1;
    int64_t off = seq - *contig - 1;
    if (off >= RLO_SEEN_BITS)
        return 0;
    return (mask[off >> 6] & (1ull << (off & 63))) != 0;
}

/* (origin, seq) receipt check for BCAST frames. The initiator never
 * delivers its own broadcast, so a re-flooded copy of my own frame is
 * also a duplicate. */
static int bcast_is_dup(rlo_engine *e, const rlo_msg *m)
{
    if (m->origin == e->rank)
        return 1;
    if (m->vote < 0 || m->origin < 0 || m->origin >= e->ws)
        return 0; /* unstamped (foreign/legacy frame): best-effort */
    return window_record(&e->seen_contig[m->origin],
                         &e->seen_mask[(size_t)m->origin * RLO_SEEN_WORDS],
                         m->vote);
}

/* ---------------- reliable delivery (ARQ) ---------------- */

/* Push one wake-up deadline onto the lazy due-heap. Allocation
 * failure degrades gracefully: heap_len 0 with a non-empty queue
 * makes arq_tick fall back to the ungated sweep. */
static void arq_heap_push(rlo_engine *e, uint64_t due)
{
    if (e->arq_heap_len == e->arq_heap_cap) {
        int cap = e->arq_heap_cap ? e->arq_heap_cap * 2 : 64;
        uint64_t *h = (uint64_t *)realloc(
            e->arq_heap, (size_t)cap * sizeof(uint64_t));
        if (!h) {
            /* the lost wake-up breaks the gate invariant: degrade to
             * ungated sweeps (arq_tick re-arms once the queue drains) */
            e->arq_gate_degraded = 1;
            return;
        }
        e->arq_heap = h;
        e->arq_heap_cap = cap;
    }
    int i = e->arq_heap_len++;
    uint64_t *h = e->arq_heap;
    while (i > 0 && h[(i - 1) / 2] > due) {
        h[i] = h[(i - 1) / 2];
        i = (i - 1) / 2;
    }
    h[i] = due;
}

/* Pop every deadline at or before `now` (they are consumed whether
 * live or stale: a sweep follows and re-arms whatever remains). */
static void arq_heap_pop_due(rlo_engine *e, uint64_t now)
{
    uint64_t *h = e->arq_heap;
    while (e->arq_heap_len && h[0] <= now) {
        uint64_t last = h[--e->arq_heap_len];
        int i = 0;
        for (;;) {
            int kid = 2 * i + 1;
            if (kid >= e->arq_heap_len)
                break;
            if (kid + 1 < e->arq_heap_len && h[kid + 1] < h[kid])
                kid++;
            if (h[kid] >= last)
                break;
            h[i] = h[kid];
            i = kid;
        }
        if (e->arq_heap_len)
            h[i] = last;
    }
}

/* Cumulative ACK from `src`: drop everything it covers from the
 * retransmit queue (and retire a pending SKIP notice the ACK proves
 * was absorbed). */
static void arq_on_ack(rlo_engine *e, int src, int32_t cum)
{
    uint64_t now = e->metrics_on ? rlo_now_usec() : 0;
    int32_t lo = INT32_MAX; /* lowest seq still held for src */
    if (e->tx_skip[src] >= 0 && cum >= e->tx_skip[src])
        e->tx_skip[src] = -1;
    for (rlo_rtx *rt = e->rtx_by_dst[src]; rt;) {
        rlo_rtx *nrt = rt->dnext;
        if (rt->seq <= cum) {
            if (e->metrics_on && rt->retries == 0 && now >= rt->sent)
                /* RTT from ack timing — never-retransmitted frames
                 * only (Karn's rule: a retransmitted frame's ack is
                 * ambiguous about which copy it answers). now >= sent
                 * guards a backwards wall-clock step (rlo_now_usec is
                 * gettimeofday): an underflowed delta would poison
                 * the EWMA for the process lifetime */
                rtt_sample(&e->links[src],
                           (double)(now - rt->sent));
            rtx_release(e, rt);
        } else if (rt->seq < lo) {
            lo = rt->seq;
        }
        rt = nrt;
    }
    /* unfillable hole: the receiver's watermark sits below seqs we no
     * longer hold (its window was reset by an admission/welcome while
     * ours carried on — tx seqs are monotone per lifetime). We can
     * never retransmit (cum, lo) — ACKs are FIFO per channel, so the
     * gap is permanent — so tell it to skip ahead now instead of
     * retransmitting the held frames to exhaustion (which would end
     * in a spurious half-dead-link FAILURE). */
    if (lo != INT32_MAX && lo > cum + 1 && lo - 1 > e->tx_skip[src]) {
        e->tx_skip[src] = lo - 1;
        e->tx_skip_due[src] = 0; /* send at the next tick */
    }
    /* any ACK that leaves a notice armed wakes the gated sweep NOW:
     * it may have just released the lower-seq entry that was HOLDING
     * the notice back, and the notice's own wake could be a full rto
     * away (review finding: the pre-gate code sent it next tick) */
    if (e->tx_skip[src] >= 0)
        arq_heap_push(e, 0);
}

/* SKIP notice from a SENDER: it gave up on everything <= upto; advance
 * the receive watermark over the permanent hole so cumulative ACKs for
 * later frames are unblocked. */
static void arq_rx_skip(rlo_engine *e, int src, int32_t upto)
{
    if ((int64_t)upto <= e->rx_contig[src])
        return;
    uint64_t *mask = &e->rx_mask[(size_t)src * RLO_SEEN_WORDS];
    int64_t shift = (int64_t)upto - e->rx_contig[src];
    if (shift >= RLO_SEEN_BITS)
        memset(mask, 0, RLO_SEEN_WORDS * sizeof(uint64_t));
    else
        seen_shift(mask, shift);
    e->rx_contig[src] = upto;
    while (mask[0] & 1) { /* holes below upto may now be contiguous */
        seen_shift(mask, 1);
        e->rx_contig[src]++;
    }
    e->ack_due[src] = 1; /* tell the sender the new cum */
}

/* Drop every retransmit entry addressed to a (now dead) rank. */
static void arq_drop_dst(rlo_engine *e, int dst)
{
    for (rlo_rtx *rt = e->rtx_by_dst[dst]; rt;) {
        rlo_rtx *nrt = rt->dnext;
        rtx_release(e, rt);
        rt = nrt;
    }
}

/* Retransmit sweep: resend overdue unacked frames with exponential
 * backoff; give up after max_retries (a peer that silent is the
 * failure detector's problem, not ARQ's). Every give-up arms a SKIP
 * notice (ACK frame, vote = -2 sentinel, pid = abandoned seq) so the
 * receiver's watermark advances over the permanent hole — sent only
 * once no lower seq is still being retried (an advanced watermark
 * would misread those retransmits as duplicates), repeating at rto
 * cadence until an ACK at/past the skipped seq retires it
 * (mirror of ProgressEngine._arq_tick). */
static void arq_tick(rlo_engine *e)
{
    uint64_t now = rlo_now_usec();
    int armed = 0;
    /* lazy due-heap gate (PR 7's Python _arq_wake, docs/DESIGN.md
     * S13): while the earliest armed wake-up is in the future nothing
     * anywhere can be due, so the common idle tick is one heap peek.
     * Stale entries (acked / re-timed frames) pop when they expire
     * and cost one empty sweep — laziness is the O(1) deal. An empty
     * heap with a non-empty queue (a failed heap allocation) falls
     * back to the ungated sweep. */
    if (e->arq_gate_degraded) {
        /* a wake-up was lost to a failed heap grow: sweep ungated
         * until everything armed has drained, then reset the gate
         * from a clean slate (all future wakes get fresh pushes) */
        if (!e->rtx_head) {
            int armed_skip = 0;
            for (int d = 0; d < e->ws; d++)
                if (e->tx_skip[d] >= 0)
                    armed_skip = 1;
            if (!armed_skip) {
                e->arq_gate_degraded = 0;
                e->arq_heap_len = 0; /* stale entries, wholesale */
                e->arq_gated++;
                return;
            }
        }
    } else {
        if (e->arq_heap_len && e->arq_heap[0] > now) {
            e->arq_gated++;
            return;
        }
        if (!e->arq_heap_len && !e->rtx_head) {
            /* nothing unacked and no wake-ups armed (armed skip
             * notices always hold a heap entry, so none starve here) */
            e->arq_gated++;
            return;
        }
    }
    arq_heap_pop_due(e, now);
    for (rlo_rtx **pp = &e->rtx_head; *pp;) {
        rlo_rtx *rt = *pp;
        if (rt->due > now) {
            pp = &rt->next;
            continue;
        }
        if (rt->retries >= e->arq_max_retries ||
            (rt->dst >= 0 && rt->dst < e->ws && e->failed[rt->dst])) {
            if (rt->dst >= 0 && rt->dst < e->ws &&
                !e->failed[rt->dst]) {
                /* retries exhausted on a LIVE peer (a dead peer's
                 * entries are dropped, not given up on — mirror of
                 * the Python tick's failed-dst clear). A give-up is a
                 * half-dead link: escalate to the failure detector
                 * after the sweep (announce_failed mutates this
                 * queue) */
                e->arq_gaveup++;
                rlo_trace_emit(e->rank, RLO_EV_ARQ_GIVEUP, rt->dst,
                               rt->retries, 0, 0);
                if (!e->awaiting_welcome)
                    e->gave_scratch[rt->dst] = 1;
                if (rt->seq > e->tx_skip[rt->dst]) {
                    e->tx_skip[rt->dst] = rt->seq;
                    e->tx_skip_due[rt->dst] = now; /* send now */
                }
            }
            /* rtx_release unlinks by writing rt->prev->next — the
             * very field *pp aliases — so *pp is now rt's successor
             * and the walk continues without advancing pp */
            rtx_release(e, rt);
            continue;
        }
        rt->retries++;
        /* clamped shift: retries is bounded by enable_arq, but keep
         * the backoff well-defined for any config */
        rt->due = now + (e->arq_rto
                         << (rt->retries < 32 ? rt->retries : 32));
        arq_heap_push(e, rt->due); /* re-arm the gate */
        e->arq_retx++;
        if (e->metrics_on && rt->dst >= 0 && rt->dst < e->ws) {
            e->links[rt->dst].retransmits++;
            e->links[rt->dst].tx_frames++;
            e->links[rt->dst].tx_bytes += rt->frame->len;
        }
        /* same bytes, same seq: the receiver dedups the retransmit */
        if (rt->split)
            isend_hdr_timed(e, rt->dst, rt->tag, rt->hdr, rt->frame, 0);
        else
            isend_timed(e, rt->dst, rt->tag, rt->frame, 0);
        pp = &rt->next;
    }
    for (int d = 0; d < e->ws; d++) {
        e->skip_hold[d] = 0;
        if (e->tx_skip[d] >= 0)
            armed = 1;
    }
    if (!armed)
        return;
    /* hold a notice back while a lower seq is still in the queue */
    for (rlo_rtx *rt = e->rtx_head; rt; rt = rt->next)
        if (e->tx_skip[rt->dst] >= 0 && rt->seq <= e->tx_skip[rt->dst])
            e->skip_hold[rt->dst] = 1;
    for (int d = 0; d < e->ws; d++) {
        if (e->tx_skip[d] < 0 || e->skip_hold[d] ||
            now < e->tx_skip_due[d] || e->failed[d] || d == e->rank)
            continue;
        eng_isend(e, d, RLO_TAG_ACK, e->rank, e->tx_skip[d], -2, 0, 0,
                  0);
        e->tx_skip_due[d] = now + e->arq_rto;
    }
    /* re-arm the gate for every notice still armed (just sent, held
     * behind a lower seq, or not yet due): the heap invariant needs a
     * wake at or before each notice's next action time */
    for (int d = 0; d < e->ws; d++)
        if (e->tx_skip[d] >= 0)
            arq_heap_push(e, e->tx_skip_due[d] > now
                                 ? e->tx_skip_due[d]
                                 : now + e->arq_rto);
}

/* ARQ give-up escalation, AFTER the retransmit sweep: a peer that
 * swallowed max_retries retransmits is a half-dead link — declared
 * FAILED exactly like a silent heartbeat predecessor (mirror of the
 * Python tick's gave_up_on epilogue). */
static void arq_escalate_gaveup(rlo_engine *e)
{
    for (int d = 0; d < e->ws; d++) {
        if (!e->gave_scratch[d])
            continue;
        e->gave_scratch[d] = 0;
        if (e->failed[d] || e->awaiting_welcome)
            continue;
        if (!getenv("RLO_QUIET"))
            fprintf(stderr,
                    "rlo_tpu: rank %d declaring rank %d FAILED: ARQ "
                    "gave up after %d retries (half-dead link)\n",
                    e->rank, d, e->arq_max_retries);
        rlo_trace_emit(e->rank, RLO_EV_FAILURE, d, 1, 0, 0);
        announce_failed(e, d);
    }
}

/* Flush the cumulative ACKs this turn's receipts owe (at most one per
 * sender per turn; ACKs are themselves unreliable). */
static void arq_flush_acks(rlo_engine *e)
{
    for (int src = 0; src < e->ws; src++) {
        if (!e->ack_due[src])
            continue;
        e->ack_due[src] = 0;
        if (src == e->rank || e->failed[src])
            continue;
        eng_isend(e, src, RLO_TAG_ACK, e->rank, -1,
                  (int32_t)e->rx_contig[src], 0, 0, 0);
    }
}

/* Remember a BCAST or IAR_DECISION frame for view-change re-flooding.
 * Decisions ride the same log: one lost in a view-change window would
 * otherwise leave parent-died relayed rounds parked forever (the
 * settled (pid, gen) ring absorbs the flood like (origin, seq) does
 * for broadcasts). */
static void recent_log_push(rlo_engine *e, rlo_blob *frame, int tag)
{
    rlo_blob_unref(e->recent[e->recent_pos]);
    e->recent[e->recent_pos] = rlo_blob_ref(frame);
    e->recent_tag[e->recent_pos] = tag;
    e->recent_pos = (e->recent_pos + 1) % RLO_RECENT_LOG;
}

/* (tag, a, b) wire identity of one recent-log entry — the coordinates
 * the MSYNC advert/WANT pair exchanges instead of payloads (mirror of
 * engine.py _log_entry_ident). Returns 0 for entries with no
 * recoverable identity. The C log holds BCAST/IAR_DECISION/FAILURE
 * entries only (no ABORT receive path — see abort_own_round). */
static int log_entry_ident(const rlo_engine *e, int idx, int32_t *t,
                           int32_t *a, int32_t *b)
{
    rlo_blob *blob = e->recent[idx];
    if (!blob)
        return 0;
    int tag = e->recent_tag[idx];
    int32_t origin, pid, vote;
    const uint8_t *pl;
    int64_t plen = rlo_frame_decode(blob->data, blob->len, &origin,
                                    &pid, &vote, 0, &pl);
    if (plen < 0)
        return 0;
    if (tag == RLO_TAG_BCAST) {
        *t = tag;
        *a = origin; /* (origin, bcast seq) */
        *b = vote;
        return 1;
    }
    if (tag == RLO_TAG_IAR_DECISION || tag == RLO_TAG_ABORT) {
        if (plen < 4)
            return 0;
        *t = tag;
        *a = pid; /* (pid, gen) */
        *b = get_le32(pl);
        return *b >= 0;
    }
    if (tag == RLO_TAG_FAILURE) {
        *t = tag;
        *a = pid; /* (failed rank, declarer epoch) */
        *b = vote;
        return 1;
    }
    return 0;
}

/* Build the MSYNC_AD payload ([kind:u8][count:i32] + count x
 * [tag:i32][a:i32][b:i32]) for the current recent log into `out`
 * (cap >= 5 + 12 * RLO_RECENT_LOG); returns the payload length, or 0
 * when the log holds nothing advertisable. */
static int64_t advert_payload(const rlo_engine *e, uint8_t *out)
{
    int cnt = 0;
    int64_t pos = 5;
    for (int i = 0; i < RLO_RECENT_LOG; i++) {
        int32_t t, a, b;
        if (!log_entry_ident(e, i, &t, &a, &b))
            continue;
        put_le32(out + pos, t);
        put_le32(out + pos + 4, a);
        put_le32(out + pos + 8, b);
        pos += 12;
        cnt++;
    }
    if (!cnt)
        return 0;
    out[0] = RLO_MSYNC_AD;
    put_le32(out + 1, cnt);
    return pos;
}

/* Does this rank provably already hold the advertised entry? Reads
 * exactly the dedup state that would have dropped the old blast's
 * duplicate (mirror of engine.py _have_log_entry) — an entry this
 * returns 1 for would have been a wasted re-flood frame (counted in
 * reflood_skipped). Check-only: window_peek and round_settled_peek
 * never record. */
static int have_log_entry(const rlo_engine *e, int32_t t, int32_t a,
                          int32_t b)
{
    if (t == RLO_TAG_BCAST) {
        if (a == e->rank || b < 0 || a < 0 || a >= e->ws)
            return 1; /* my own, or unstamped (not recoverable) */
        return window_peek(&e->seen_contig[a],
                           &e->seen_mask[(size_t)a * RLO_SEEN_WORDS],
                           b);
    }
    if (t == RLO_TAG_IAR_DECISION || t == RLO_TAG_ABORT) {
        if (t == RLO_TAG_IAR_DECISION && a <= RLO_MEMBER_PID_BASE)
            /* membership decisions are never WANTed: the welcome /
             * sync-response member records are the authoritative
             * channel, and a stale admission about a since-re-failed
             * rank must not resurrect it (the same rule replay_recent
             * applies) */
            return 1;
        return b < 0 || round_settled_peek(e, a, b);
    }
    if (t == RLO_TAG_FAILURE) {
        if (a < 0 || a >= e->ws)
            return 1;
        /* a = failed rank, b = declarer epoch: already adopted, about
         * myself (heal probes cover self-failure learning), or stale
         * against an admission executed since */
        return a == e->rank || e->failed[a] || b < e->admit_epoch[a];
    }
    return 1;
}

/* Plug forwarding holes a dead relay left — digest-scoped
 * (docs/DESIGN.md S18). The pre-PR-16 heal re-sent every recent
 * frame point-to-point to every alive rank on every view change:
 * O(n^2 * ring) frames per churn episode, the dominant term of the
 * measured rejoin cascade. Now each view change sends one MSYNC
 * advert per alive peer carrying only the log entries' IDENTITIES; a
 * peer answers with a WANT naming exactly the entries it provably
 * misses, and only those payloads are re-sent. An empty log sends
 * nothing at all — kill-only fleets heal for free. Delivery
 * exactly-once composes the same way: the WANT check reads the same
 * dedup state that would have dropped the blast's duplicates.
 * Adverts are best-effort (ARQ-exempt): every later view change
 * re-adverts, and the admission replay / welcome path covers the
 * rejoin side independently. */
static void reflood_recent(rlo_engine *e)
{
    uint8_t ad[5 + 12 * RLO_RECENT_LOG];
    int64_t n = advert_payload(e, ad);
    if (!n)
        return;
    for (int dst = 0; dst < e->ws; dst++)
        if (dst != e->rank && !e->failed[dst])
            eng_isend(e, dst, RLO_TAG_MSYNC, e->rank, -1, -1, ad, n,
                      0);
}

/* ---------------- rootless broadcast ---------------- */

/* Initiate without progressing (handlers use this; the public entry
 * progresses after). Returns the tracking msg via *out. */
static int bcast_init(rlo_engine *e, int tag, int32_t pid, int32_t vote,
                      const uint8_t *payload, int64_t len, rlo_msg **out)
{
    if (len < 0 || len > e->msg_size_max)
        return RLO_ERR_TOO_BIG;
    /* encode ONCE; every fan-out edge shares the blob by ref */
    rlo_blob *frame;
    if (e->profiler_on) {
        double t0 = now_usec_f();
        frame = frame_blob(e->w, e->rank, pid, vote, payload, len);
        ph_obs(e, RLO_PH_FRAME_ENCODE, t0);
    } else {
        frame = frame_blob(e->w, e->rank, pid, vote, payload, len);
    }
    if (!frame)
        return RLO_ERR_NOMEM;
    int err = RLO_ERR_NOMEM;
    rlo_msg *m = msg_from_frame(e->w, tag, -1, frame,
                                &err); /* steals the ref */
    if (!m)
        return err;
    int targets[64];
    int nt = cur_init_targets(e, targets, 64);
    if (nt < 0) {
        msg_free(m);
        return nt;
    }
    for (int i = 0; i < nt; i++) { /* furthest-first */
        int rc = eng_isend_frame(e, targets[i], tag, m->frame, m);
        if (rc != RLO_OK) {
            msg_free(m);
            return rc;
        }
    }
    q_append(&e->q_wait, m);
    e->sent_bcast++;
    rlo_trace_emit(e->rank, RLO_EV_BCAST_INIT, tag, (int)len,
                   trace_ident(tag, pid, vote), 0);
    if (out)
        *out = m;
    return RLO_OK;
}

int rlo_bcast(rlo_engine *e, const uint8_t *payload, int64_t len)
{
    /* stamp the exactly-once sequence number in the (otherwise unused)
     * vote field; log the frame for view-change re-flooding. The seq is
     * consumed BEFORE sending (matching engine.py): a partial-send
     * failure may have leaked the seq to some peers, and reusing it
     * would make them silently drop the next broadcast as a duplicate.
     * A burnt seq just leaves a gap the dedup window absorbs. */
    rlo_msg *m = 0;
    int rc = bcast_init(e, RLO_TAG_BCAST, -1, e->bcast_seq++, payload,
                        len, &m);
    if (rc == RLO_OK) {
        if (e->metrics_on)
            m->born = rlo_now_usec();
        if (e->profiler_on)
            m->p_born = now_usec_f();
        recent_log_push(e, m->frame, RLO_TAG_BCAST);
        rlo_progress_all(e->w);
    }
    return rc;
}

/* Forward a received broadcast along the overlay (reference _bc_forward,
 * rootless_ops.c:1104-1225). Returns the number of forwards or <0. */
/* rlo-sentinel: transfers(m) — queued on success; on rc<0 nothing
 * was queued and the CALLER reclaims (progress dispatch) */
static int bc_forward(rlo_engine *e, rlo_msg *m)
{
    int targets[64];
    int n = cur_fwd_targets(e, m->origin, m->src, targets, 64);
    if (n < 0)
        return n;
    for (int i = 0; i < n; i++) {
        /* zero-copy store-and-forward: every hop shares the one blob */
        int rc = eng_isend_frame(e, targets[i], m->tag, m->frame, m);
        if (rc != RLO_OK)
            return rc;
    }
    /* receipt+forward step — emitted even for leaf receipts (zero
     * targets) so the timeline merger always has a receive-side
     * anchor carrying (origin, identity, immediate sender) */
    rlo_trace_emit(e->rank, RLO_EV_BCAST_FWD, m->tag, m->origin,
                   trace_ident(m->tag, m->pid, m->vote), m->src);
    if (m->tag == RLO_TAG_IAR_PROPOSAL) {
        /* proposals are engine-internal: parked for the decision, never
         * user-visible (make_progress_gen :591-596) */
        q_append(&e->q_iar_pending, m);
    } else if (m->tag == RLO_TAG_IAR_DECISION) {
        /* delivery handled by on_decision */
    } else if (n > 0) {
        q_append(&e->q_wait_pickup, m);
    } else {
        m->fwd_done = 1;
        q_append(&e->q_pickup, m);
    }
    return n;
}

/* ---------------- IAR consensus ---------------- */

static int eng_judge(rlo_engine *e, const uint8_t *payload, int64_t len,
                     int pid)
{
    int verdict;
    if (len >= RLO_MEMBER_MAGIC_LEN && payload &&
        !memcmp(payload, RLO_MEMBER_MAGIC, RLO_MEMBER_MAGIC_LEN))
        /* internal membership admission round (docs/DESIGN.md S8):
         * the engine judges it itself — the app's judge never sees
         * protocol-internal rounds */
        verdict = 1;
    else
        verdict = e->judge ? (e->judge(payload, len, e->judge_ctx) ? 1 : 0)
                           : 1;
    rlo_trace_emit(e->rank, RLO_EV_JUDGE, pid, verdict, 0, 0);
    return verdict;
}

/* Send my (merged) vote to the rank the proposal came from (reference
 * _vote_back :728-741; nonblocking here). The payload echoes the round
 * generation so a stale vote from an earlier same-pid round can never
 * be counted into a later one. */
static int vote_back(rlo_engine *e, const rlo_prop *ps, int vote)
{
    uint8_t genb[4];
    put_le32(genb, ps->gen);
    rlo_trace_emit(e->rank, RLO_EV_VOTE, ps->pid, vote, ps->gen, 0);
    return eng_isend(e, ps->recv_from, RLO_TAG_IAR_VOTE, e->rank, ps->pid,
                     vote, genb, 4, 0);
}

/* The relay's merged vote is final: send it to the vote-tree parent
 * AND to every duplicate parent from re-formed overlay trees — one
 * merged verdict everywhere, so a subtree veto survives even when the
 * original parent is the dead rank that triggered the view change
 * (mirror of ProgressEngine._resolve_relay). */
static int resolve_relay(rlo_engine *e, rlo_prop *ps)
{
    ps->resolved = 1;
    int rc = vote_back(e, ps, ps->vote);
    for (int i = 0; i < ps->n_dup && rc == RLO_OK; i++) {
        rlo_prop vb = {0};
        vb.pid = ps->pid;
        vb.gen = ps->gen;
        vb.recv_from = ps->dup_parents[i];
        rc = vote_back(e, &vb, ps->vote);
    }
    ps->n_dup = 0;
    return rc;
}

static int vote_gen(const rlo_msg *m)
{
    if (m->len < 4)
        return -1;
    return (int)((uint32_t)m->payload[0] |
                 ((uint32_t)m->payload[1] << 8) |
                 ((uint32_t)m->payload[2] << 16) |
                 ((uint32_t)m->payload[3] << 24));
}

/* Matched on (pid, generation) so rounds reusing a pid never shadow
 * each other in the pending queue (~_find_proposal_msg :1036-1053). */
static rlo_msg *find_proposal_msg(rlo_engine *e, int pid, int gen)
{
    for (rlo_msg *m = e->q_iar_pending.head; m; m = m->next)
        if (m->ps && m->ps->pid == pid && m->ps->gen == gen)
            return m;
    return 0;
}

static void set_err(rlo_engine *e, int err)
{
    if (e->err == RLO_OK)
        e->err = err;
}

/* Forward a duplicate store-and-forward frame along the overlay with
 * no local processing; parked in the wait-only queue until the sends
 * complete. */
/* rlo-sentinel: transfers(m) */
static void bc_forward_only(rlo_engine *e, rlo_msg *m)
{
    int targets[64];
    int n = cur_fwd_targets(e, m->origin, m->src, targets, 64);
    if (n < 0) {
        set_err(e, n);
        msg_free(m);
        return;
    }
    for (int i = 0; i < n; i++) {
        int rc = eng_isend_frame(e, targets[i], m->tag, m->frame, m);
        if (rc != RLO_OK) {
            set_err(e, rc);
            msg_free(m);
            return;
        }
    }
    q_append(&e->q_wait, m);
}

/* rlo-sentinel: transfers(m) */
static void on_proposal(rlo_engine *e, rlo_msg *m)
{
    if (m->origin == e->rank) {
        /* my own proposal echoed back around a re-formed overlay
         * cycle (mixed views while membership converges): the
         * proposer holds no relay state and must not re-forward */
        msg_free(m);
        return;
    }
    /* duplicate across a view change (mixed old/new overlay trees):
     * never re-judge or re-park — a second proposal state voting to a
     * second parent would corrupt the vote accounting. Forward for
     * coverage. A PENDING duplicate's sender is a live relay awaiting
     * my vote, but my subtree's veto may still be in flight, so an
     * interim verdict could approve a round a live rank vetoed:
     * resolved rounds send the final merged vote now, unresolved ones
     * record the sender as a duplicate parent for resolve_relay.
     * A SETTLED duplicate needs no vote — the decision already
     * broadcast, and on_decision frees the sender's pending state. */
    rlo_msg *dup = find_proposal_msg(e, m->pid, m->vote);
    if (dup || (m->vote >= 0 && round_settled_peek(e, m->pid, m->vote))) {
        if (dup && m->src != dup->ps->recv_from) {
            rlo_prop *dps = dup->ps;
            int known = 0;
            for (int i = 0; i < dps->n_dup; i++)
                if (dps->dup_parents[i] == m->src)
                    known = 1;
            if (!known) {
                if (dps->resolved) {
                    rlo_prop vb = {0};
                    vb.pid = m->pid;
                    vb.gen = m->vote;
                    vb.recv_from = m->src;
                    vote_back(e, &vb, dps->vote);
                } else if (dps->n_dup <
                           (int)(sizeof(dps->dup_parents) /
                                 sizeof(dps->dup_parents[0]))) {
                    dps->dup_parents[dps->n_dup++] = m->src;
                } else {
                    /* 8 concurrent re-formed trees mid-round: out of
                     * slots — vote the interim verdict rather than
                     * deadlock the sender (degraded, bounded) */
                    rlo_prop vb = {0};
                    vb.pid = m->pid;
                    vb.gen = m->vote;
                    vb.recv_from = m->src;
                    vote_back(e, &vb, dps->vote);
                }
            }
        }
        bc_forward_only(e, m);
        return;
    }
    if (e->own.state == RLO_IN_PROGRESS && m->pid == e->own.pid) {
        /* pid collision with my active proposal — the reference only
         * printf-warns (:690-692) and corrupts vote accounting; fail
         * loudly instead (matches the Python engine) */
        set_err(e, RLO_ERR_PROTO);
        msg_free(m);
        return;
    }
    rlo_prop *ps = (rlo_prop *)calloc(1, sizeof(*ps));
    if (!ps) {
        set_err(e, RLO_ERR_NOMEM);
        msg_free(m);
        return;
    }
    ps->pid = m->pid;
    ps->gen = m->vote; /* round generation (see rlo_submit_proposal) */
    ps->recv_from = m->src;
    ps->vote = 1;
    ps->state = RLO_IN_PROGRESS;
    /* equal to bc_forward's target list by construction, including
     * after elastic re-forming */
    ps->n_await = cur_fwd_targets(e, m->origin, m->src,
                                  ps->await_from, 64);
    if (ps->n_await < 0) {
        set_err(e, ps->n_await);
        m->ps = ps;
        msg_free(m);
        return;
    }
    ps->votes_needed = ps->n_await;
    m->ps = ps;
    if (!eng_judge(e, m->payload, m->len, ps->pid)) {
        /* decline: NO to parent immediately, don't forward — the
         * subtree below only ever sees the decision. Parked anyway
         * (resolved, vote 0) so duplicates from re-formed trees find
         * the verdict instead of re-judging, and an approved decision
         * (possible when this veto was discounted with a dead subtree)
         * still fires the action callback here like everywhere else */
        ps->vote = 0;
        ps->votes_needed = 0;
        ps->n_await = 0;
        resolve_relay(e, ps);
        q_append(&e->q_iar_pending, m);
        return;
    }
    int sent = bc_forward(e, m); /* parks m in q_iar_pending */
    if (sent < 0) {
        /* bc_forward only fails before queueing — reclaim the msg */
        set_err(e, sent);
        msg_free(m);
    } else if (sent == 0) {
        resolve_relay(e, ps); /* leaf: merged vote == my own */
    }
}

static void decision_bcast(rlo_engine *e)
{
    rlo_prop *p = &e->own;
    rlo_msg *m = 0;
    /* decision in the vote field, round generation in the payload.
     * Membership rounds append the admission record (MAGIC + joiner/
     * incarnation/epoch) so every member can execute the admission
     * from the decision alone, even if it never saw the proposal
     * (generation readers only unpack the first 4 bytes). */
    uint8_t genb[4 + RLO_MEMBER_MAGIC_LEN + 12];
    int64_t plen = 4;
    put_le32(genb, p->gen);
    if (p->pid <= RLO_MEMBER_PID_BASE && p->payload &&
        p->len <= (int64_t)sizeof(genb) - 4) {
        memcpy(genb + 4, p->payload, (size_t)p->len);
        plen += p->len;
    }
    int rc = bcast_init(e, RLO_TAG_IAR_DECISION, p->pid, p->vote, genb,
                        plen, &m);
    if (rc != RLO_OK) {
        set_err(e, rc);
        return;
    }
    recent_log_push(e, m->frame, RLO_TAG_IAR_DECISION);
    /* retain the decision sends: the proposal completes only once the
     * decision has fanned out (reference :554-566) */
    p->decision_handles = (rlo_handle **)malloc(
        (size_t)(m->n_handles ? m->n_handles : 1) * sizeof(void *));
    if (!p->decision_handles) {
        set_err(e, RLO_ERR_NOMEM);
        return;
    }
    p->n_decision = m->n_handles;
    for (int i = 0; i < m->n_handles; i++) {
        p->decision_handles[i] = m->handles[i];
        m->handles[i]->refs++;
    }
    p->decision_pending = 1;
    rlo_trace_emit(e->rank, RLO_EV_DECISION, p->pid, p->vote, p->gen, 0);
}

/* Drop src from the awaited-children list; 0 if it was not awaited. */
static int await_remove(rlo_prop *p, int src)
{
    for (int i = 0; i < p->n_await; i++)
        if (p->await_from[i] == src) {
            p->await_from[i] = p->await_from[--p->n_await];
            return 1;
        }
    return 0;
}

static void complete_own(rlo_engine *e)
{
    rlo_prop *p = &e->own;
    if (e->p_prop_born != 0)
        /* S10 prop_votes_aggregated: submit -> every awaited vote
         * merged (or discounted); the decision fan-out starts here */
        ph_obs(e, RLO_PH_PROP_VOTES_AGGREGATED, e->p_prop_born);
    if (p->vote)
        /* re-judge: a competing proposal may have changed app state
         * since submission (reference :773) */
        p->vote = eng_judge(e, p->payload, p->len, p->pid);
    decision_bcast(e);
    if (p->pid <= RLO_MEMBER_PID_BASE)
        /* membership round: the admitting proposer executes the
         * admission right after fanning the decision out (the
         * decision itself was routed over the PRE-admission
         * member-only overlay), then welcomes + replays to the
         * joiner (docs/DESIGN.md S8) */
        finish_member_round(e);
}

/* rlo-sentinel: transfers(m) */
static void on_vote(rlo_engine *e, rlo_msg *m)
{
    int pid = m->pid, vote = m->vote;
    int gen = vote_gen(m);
    rlo_prop *p = &e->own;
    /* claim the vote for my own proposal ONLY while it is in progress
     * AND the generations match: a later proposer may legitimately
     * reuse this pid (pid collisions are only forbidden between
     * CONCURRENT proposals, on_proposal errors on those), and a stale
     * vote from an earlier same-pid round must never merge into a
     * newer one */
    if (pid == p->pid && p->state == RLO_IN_PROGRESS && gen == p->gen) {
        /* only votes from still-awaited children count: a vote from a
         * discounted (suspected-dead) child must not advance the count
         * past a live child's pending veto */
        if (await_remove(p, m->src)) {
            p->votes_recved++;
            p->vote &= vote;
            if (p->votes_recved == p->votes_needed)
                complete_own(e);
        }
        msg_free(m);
        return;
    }
    rlo_msg *pm = find_proposal_msg(e, pid, gen);
    if (!pm) {
        if ((pid == p->pid && p->state != RLO_INVALID) ||
            round_settled_peek(e, pid, gen) ||
            e->fd_timeout || e->n_failed)
            ; /* stale round, settled/aborted round, or a membership
                 change; drop */
        else
            set_err(e, RLO_ERR_PROTO);
        msg_free(m);
        return;
    }
    if (!await_remove(pm->ps, m->src)) {
        msg_free(m); /* late/duplicate vote from a discounted child */
        return;
    }
    pm->ps->vote &= vote;
    pm->ps->votes_recved++;
    if (pm->ps->votes_recved == pm->ps->votes_needed)
        resolve_relay(e, pm->ps);
    msg_free(m);
}

/* settled-round dedup: a decision forwarded by a mix of old- and new-
 * topology trees during a view change can reach a rank twice; record
 * (pid, gen) of delivered decisions in a ring and drop repeats — the
 * IAR analogue of the (origin, seq) broadcast dedup. Returns 1 when
 * the round was already settled. */
/* Non-recording membership test of the settled-round ring. */
static int round_settled_peek(const rlo_engine *e, int32_t pid,
                              int32_t gen)
{
    for (int i = 0; i < RLO_SETTLED_LOG; i++)
        if (e->settled[i].pid == pid && e->settled[i].gen == gen &&
            e->settled[i].used)
            return 1;
    return 0;
}

static int round_settled(rlo_engine *e, int32_t pid, int32_t gen)
{
    if (gen < 0)
        return 0; /* ungenerated (foreign/legacy) frame: best-effort */
    if (round_settled_peek(e, pid, gen))
        return 1;
    e->settled[e->settled_pos].pid = pid;
    e->settled[e->settled_pos].gen = gen;
    e->settled[e->settled_pos].used = 1;
    e->settled_pos = (e->settled_pos + 1) % RLO_SETTLED_LOG;
    return 0;
}

/* rlo-sentinel: transfers(m) */
static void on_decision(rlo_engine *e, rlo_msg *m)
{
    if (m->origin == e->rank) {
        /* a re-flooded copy of my own decision (the proposer learns
         * its decision from the vote merge, never from the wire) */
        msg_free(m);
        return;
    }
    if (round_settled(e, m->pid, vote_gen(m))) {
        /* duplicate across a view change: deliver exactly once, but
         * STILL forward — a descendant reachable only through this
         * second tree (its old-view parent died) has no other way to
         * learn the decision. */
        bc_forward_only(e, m);
        return;
    }
    /* first sight: log for view-change re-flooding (parked parent-died
     * rounds depend on the decision surviving any one relay's death) */
    recent_log_push(e, m->frame, RLO_TAG_IAR_DECISION);
    rlo_msg *pm = find_proposal_msg(e, m->pid, vote_gen(m));
    int rc = bc_forward(e, m); /* forward first; delivery below */
    if (rc < 0)
        set_err(e, rc);
    if (m->pid <= RLO_MEMBER_PID_BASE) {
        /* membership round: engine-internal. Execute the admission
         * from the decision's embedded record (works even when this
         * rank never saw the proposal), unpark any relayed round
         * WITHOUT the app action, and never deliver to pickup — but
         * keep tracking the forward handles (docs/DESIGN.md S8). */
        if (pm) {
            pm->ps->state = m->vote ? RLO_COMPLETED : RLO_FAILED;
            q_remove(&e->q_iar_pending, pm);
            msg_free(pm);
        }
        int32_t new_epoch;
        const uint8_t *recs;
        int k = m->len >= 4 ? member_decode(m->payload + 4, m->len - 4,
                                            &new_epoch, &recs)
                            : 0;
        for (int j = 0; j < k; j++) {
            int joiner = get_le32(recs + 8 * j);
            int inc = get_le32(recs + 8 * j + 4);
            if (joiner < 0 || joiner >= e->ws)
                continue;
            e->admitting[joiner] = 0;
            if (e->pending_join[joiner]) {
                e->pending_join[joiner] = 0;
                e->n_pending--;
            }
            if (m->vote &&
                execute_admission(e, joiner, inc, new_epoch) && k > 1)
                e->batched_admits++;
        }
        q_append(&e->q_wait, m);
        return;
    }
    if (pm) {
        if (m->vote && e->action)
            e->action(pm->payload, pm->len, e->action_ctx);
        q_remove(&e->q_iar_pending, pm);
        msg_free(pm);
    }
    /* deliver the decision to the user either way (reference :852-854) */
    q_append(&e->q_pickup, m);
}

int rlo_submit_proposal(rlo_engine *e, const uint8_t *proposal, int64_t len,
                        int pid)
{
    rlo_prop *p = &e->own;
    if (p->state == RLO_IN_PROGRESS)
        return RLO_ERR_BUSY;
    if (len < 0 || len > e->msg_size_max)
        return RLO_ERR_TOO_BIG;
    free(p->payload);
    for (int i = 0; i < p->n_decision; i++)
        rlo_handle_unref(p->decision_handles[i]);
    free(p->decision_handles);
    memset(p, 0, sizeof(*p));
    e->own_deadline = 0; /* the watchdog never outlives its round */
    p->pid = pid;
    /* rank-qualified (counter * world_size + rank) so two proposers
     * reusing one pid can never collide on generation either, with no
     * overflow for any realistic rank or round count */
    p->gen = (++e->gen_counter) * e->ws + e->rank;
    p->vote = 1;
    p->n_await = cur_init_targets(e, p->await_from, 64);
    if (p->n_await < 0)
        return p->n_await;
    p->votes_needed = p->n_await;
    p->state = RLO_IN_PROGRESS;
    p->len = len;
    if (len > 0) {
        p->payload = (uint8_t *)malloc((size_t)len);
        if (!p->payload)
            return RLO_ERR_NOMEM;
        memcpy(p->payload, proposal, (size_t)len);
    }
    if (e->metrics_on)
        e->prop_born = rlo_now_usec();
    if (e->profiler_on)
        e->p_prop_born = now_usec_f();
    rlo_trace_emit(e->rank, RLO_EV_PROPOSAL_SUBMIT, pid, 0, p->gen, 0);
    /* the proposal frame's vote field carries the round generation */
    int rc = bcast_init(e, RLO_TAG_IAR_PROPOSAL, pid, p->gen, proposal,
                        len, 0);
    if (rc != RLO_OK) {
        p->state = RLO_FAILED;
        return rc;
    }
    if (p->votes_needed == 0)
        /* no awaited voters (sole survivor after elastic re-forming):
         * nothing will ever call on_vote — complete immediately */
        complete_own(e);
    rlo_progress_all(e->w);
    if (p->state == RLO_COMPLETED)
        return p->vote;
    return -1;
}

int rlo_check_proposal_state(rlo_engine *e)
{
    rlo_progress_all(e->w);
    return e->own.state;
}

int rlo_vote_my_proposal(rlo_engine *e)
{
    rlo_progress_all(e->w);
    if (e->own.state != RLO_COMPLETED)
        return -1;
    return e->own.vote;
}

void rlo_proposal_reset(rlo_engine *e)
{
    rlo_prop *p = &e->own;
    free(p->payload);
    for (int i = 0; i < p->n_decision; i++)
        rlo_handle_unref(p->decision_handles[i]);
    free(p->decision_handles);
    memset(p, 0, sizeof(*p));
    p->pid = -1;
    p->vote = 1;
    p->state = RLO_INVALID;
}

/* ---------------- failure detection + elastic recovery --------------
 * Mirror of rlo_tpu/engine.py's failure machinery (see rlo_core.h for
 * the contract). Membership changes are not view-synchronous, but
 * BCAST delivery is exactly-once across them for any initiator that
 * survived: (origin, seq) dedup makes twice impossible and the
 * view-change re-flood (reflood_recent) makes zero impossible — for
 * broadcasts within the RLO_RECENT_LOG most recent frames a survivor
 * holds (older evicted frames degrade to at-most-once, as does
 * traffic whose initiator died before handing any survivor a copy). */

static void ring_neighbors(const rlo_engine *e, int *succ, int *pred)
{
    int ws = e->ws;
    int s = -1, p = -1;
    for (int d = 1; d < ws; d++) {
        int r = (e->rank + d) % ws;
        if (!e->failed[r]) {
            s = r;
            break;
        }
    }
    for (int d = 1; d < ws; d++) {
        int r = (e->rank - d % ws + ws) % ws;
        if (!e->failed[r]) {
            p = r;
            break;
        }
    }
    *succ = s;
    *pred = p;
}

static void discount_failed_voter(rlo_engine *e, int rank)
{
    rlo_prop *p = &e->own;
    if (p->state == RLO_IN_PROGRESS && !p->decision_pending &&
        await_remove(p, rank)) {
        p->votes_needed--;
        if (p->votes_recved == p->votes_needed)
            complete_own(e);
    }
    for (rlo_msg *pm = e->q_iar_pending.head; pm; pm = pm->next) {
        if (pm->ps && await_remove(pm->ps, rank)) {
            pm->ps->votes_needed--;
            if (pm->ps->votes_recved == pm->ps->votes_needed)
                resolve_relay(e, pm->ps);
        }
    }
}

static void abort_orphaned_proposals(rlo_engine *e, int rank)
{
    /* relays whose PROPOSER died can never resolve (no decision will
     * ever broadcast): unpark and drop them. Rounds whose vote-tree
     * PARENT died stay parked: the surviving proposer discounts the
     * dead subtree and its decision still reaches this rank through
     * the re-formed overlay, clearing the round (and firing the
     * action) like a healthy one — and the child votes already merged
     * stay live for duplicate parents (mirror of the Python engine's
     * _abort_orphaned_proposals; round-2 advisor finding). */
    for (rlo_msg *pm = e->q_iar_pending.head; pm;) {
        rlo_msg *nm = pm->next;
        if (pm->ps && pm->origin == rank) {
            pm->ps->state = RLO_FAILED;
            q_remove(&e->q_iar_pending, pm);
            msg_free(pm);
        }
        pm = nm;
    }
}

/* Adopt a failure; returns 1 when newly learned (idempotent). */
static int mark_failed(rlo_engine *e, int rank)
{
    if (!e->failed || rank == e->rank || rank < 0 || rank >= e->ws ||
        e->failed[rank])
        return 0;
    int old_succ, old_pred;
    ring_neighbors(e, &old_succ, &old_pred);
    e->failed[rank] = 1;
    e->n_failed++;
    e->view_changes++;
    e->hb_seen[rank] = 0;
    /* every failure adoption bumps the membership epoch; the edge's
     * floor/link-epoch bookkeeping is obsolete — the failed-sender
     * quarantine now covers the rank entirely (docs/DESIGN.md S8) */
    e->epoch++;
    e->epoch_floor[rank] = 0;
    e->link_epoch[rank] = 0;
    /* the certified link-reset record dies with the link: a sync
     * response must never vouch floors for a failed member (S18) */
    e->reset_epoch[rank] = 0;
    if (e->pending_join[rank]) {
        e->pending_join[rank] = 0;
        e->n_pending--;
    }
    /* ARQ: a dead peer will never ack — stop retransmitting at it */
    arq_drop_dst(e, rank);
    e->ack_due[rank] = 0;
    e->tx_skip[rank] = -1;
    if (e->fd_timeout && e->ws - e->n_failed >= 2) {
        int succ, pred;
        ring_neighbors(e, &succ, &pred);
        /* fresh grace only when my predecessor actually changed */
        if (pred >= 0 && pred != old_pred)
            e->hb_seen[pred] = rlo_now_usec();
    }
    discount_failed_voter(e, rank);
    abort_orphaned_proposals(e, rank);
    reflood_recent(e);
    return 1;
}

/* Adopt + announce a failure THIS rank detected (heartbeat silence or
 * ARQ give-up): mark, then tell the world — overlay broadcast AND
 * point-to-point to every alive rank (overlay forwarding can have
 * holes while views are converging; receivers suppress duplicates).
 * The notice's vote field carries the DECLARER's epoch at declaration
 * time: unlike the header link epoch it is immutable through
 * re-floods, so receivers can recognize a stale notice about a rank
 * readmitted since. Returns 0 when the failure was already known. */
static int announce_failed(rlo_engine *e, int rank)
{
    if (!mark_failed(e, rank))
        return 0;
    rlo_msg *fm = 0;
    int rc = bcast_init(e, RLO_TAG_FAILURE, rank, e->epoch, 0, 0, &fm);
    if (rc != RLO_OK)
        set_err(e, rc);
    else if (fm)
        /* declarations join the re-flood log (docs/DESIGN.md S8);
         * admission purges stale notices about the readmitted rank */
        recent_log_push(e, fm->frame, RLO_TAG_FAILURE);
    for (int dst = 0; dst < e->ws; dst++) {
        if (dst == e->rank || e->failed[dst])
            continue;
        rc = eng_isend(e, dst, RLO_TAG_FAILURE, e->rank, rank,
                       e->epoch, 0, 0, 0);
        if (rc != RLO_OK)
            set_err(e, rc);
    }
    return 1;
}

static void declare_failed(rlo_engine *e, int rank)
{
    /* capture the evidence BEFORE mark_failed clears the slot: the
     * last-seen heartbeat age is what makes a false-positive
     * declaration diagnosable after the fact */
    uint64_t now = rlo_now_usec();
    uint64_t age = (rank >= 0 && rank < e->ws && e->hb_seen[rank] &&
                    now > e->hb_seen[rank])
                       ? now - e->hb_seen[rank]
                       : (uint64_t)INT32_MAX;
    if (age > (uint64_t)INT32_MAX)
        age = (uint64_t)INT32_MAX;
    if (!announce_failed(e, rank))
        return;
    if (!getenv("RLO_QUIET"))
        /* suppressible like the Python twin's logging.Logger route */
        fprintf(stderr,
                "rlo_tpu: rank %d declaring rank %d FAILED: no "
                "heartbeat for %.1f ms (timeout %.1f ms, interval "
                "%.1f ms)\n",
                e->rank, rank, (double)age / 1e3,
                (double)e->fd_timeout / 1e3,
                (double)e->fd_interval / 1e3);
    rlo_trace_emit(e->rank, RLO_EV_FAILURE, rank, 1, (int)age, 0);
}

/* rlo-sentinel: transfers(m) */
static void on_failure(rlo_engine *e, rlo_msg *m)
{
    int rank = m->pid;
    int32_t declared = m->vote; /* declarer's epoch (-1 on legacy) */
    if (rank == e->rank) {
        if (declared >= 0 && declared < e->welcome_epoch) {
            msg_free(m); /* pre-rejoin leftover about my old life */
            return;
        }
        /* somebody declared me failed: the group re-formed without me
         * and quarantines my traffic — record the suspicion AND
         * petition for readmission (docs/DESIGN.md S8; rejoin
         * replaces the old "no un-fail protocol" dead end) */
        if (e->suspected_self) {
            msg_free(m); /* duplicate */
            return;
        }
        e->suspected_self = 1;
        int rc0 = bc_forward(e, m);
        if (rc0 < 0) {
            set_err(e, rc0);
            msg_free(m);
        }
        /* rlo-model: edge failure->joiner */
        become_joiner(e);
        return;
    }
    if (declared >= 0 && rank >= 0 && rank < e->ws &&
        declared < e->admit_epoch[rank]) {
        /* stale notice (declared before an admission we already
         * executed): adopting it would flap the fresh member out */
        msg_free(m);
        return;
    }
    if (!mark_failed(e, rank)) {
        msg_free(m); /* already known: suppress the duplicate */
        return;
    }
    rlo_trace_emit(e->rank, RLO_EV_FAILURE, rank, 0, 0, 0);
    int rc = bc_forward(e, m); /* adopt-before-forward ordering */
    if (rc < 0) {
        set_err(e, rc);
        msg_free(m);
    }
}

static void failure_tick(rlo_engine *e)
{
    if (!e->fd_timeout || e->ws - e->n_failed < 2)
        return;
    uint64_t now = rlo_now_usec();
    int succ, pred;
    ring_neighbors(e, &succ, &pred);
    if (succ >= 0 && now - e->hb_last_sent >= e->fd_interval) {
        /* piggyback the cumulative link ACK for the successor: even
         * with no reverse data traffic, its retransmit queue to us
         * drains at heartbeat cadence */
        uint8_t ackb[4];
        int64_t n_ack = 0;
        if (e->arq_rto) {
            put_le32(ackb, (int)e->rx_contig[succ]);
            n_ack = 4;
        }
        eng_isend(e, succ, RLO_TAG_HEARTBEAT, e->rank, -1, -1, ackb,
                  n_ack, 0);
        e->hb_last_sent = now;
        rlo_trace_emit(e->rank, RLO_EV_HEARTBEAT, succ, 0, 0, 0);
    }
    if (pred < 0)
        return;
    if (e->hb_seen[pred] == 0) {
        e->hb_seen[pred] = now; /* grace on first watch */
        return;
    }
    /* hb_seen may sit in the FUTURE for a freshly admitted joiner
     * (admission grace, docs/DESIGN.md S18) — the unsigned subtraction
     * must not underflow into an instant re-declaration */
    if (now > e->hb_seen[pred] && now - e->hb_seen[pred] > e->fd_timeout)
        declare_failed(e, pred);
}

int rlo_engine_enable_failure_detection(rlo_engine *e,
                                        uint64_t timeout_usec,
                                        uint64_t interval_usec)
{
    if (!e || !timeout_usec)
        return RLO_ERR_ARG;
    e->fd_timeout = timeout_usec;
    e->fd_interval = interval_usec ? interval_usec : timeout_usec / 4;
    return RLO_OK;
}

int rlo_engine_enable_arq(rlo_engine *e, uint64_t rto_usec,
                          int max_retries)
{
    /* max_retries capped at 32: the backoff shift must stay defined
     * (and 2^32 * rto is already far beyond any useful horizon) */
    if (!e || !rto_usec || max_retries < 0 || max_retries > 32)
        return RLO_ERR_ARG;
    e->arq_rto = rto_usec;
    e->arq_max_retries = max_retries;
    return RLO_OK;
}

int64_t rlo_engine_arq_retransmits(const rlo_engine *e)
{
    return e->arq_retx;
}

int64_t rlo_engine_arq_dup_drops(const rlo_engine *e)
{
    return e->arq_dup;
}

int64_t rlo_engine_arq_unacked(const rlo_engine *e)
{
    return e->arq_unacked_cnt;
}

int64_t rlo_engine_arq_heap_len(const rlo_engine *e)
{
    return e->arq_heap_len;
}

int64_t rlo_engine_arq_scan_gated(const rlo_engine *e)
{
    return e->arq_gated;
}

int64_t rlo_engine_frames_dispatched(const rlo_engine *e)
{
    return e->frames_dispatched;
}

int64_t rlo_engine_arq_gave_up(const rlo_engine *e)
{
    return e->arq_gaveup;
}

/* ---------------- metrics registry (see rlo_core.h rlo_stats) ------- */

int rlo_engine_enable_metrics(rlo_engine *e, int on)
{
    if (!e)
        return RLO_ERR_ARG;
    e->metrics_on = on ? 1 : 0;
    return RLO_OK;
}

int rlo_engine_stats(const rlo_engine *e, rlo_stats *out)
{
    if (!e || !out)
        return RLO_ERR_ARG;
    memset(out, 0, sizeof(*out));
    out->sent_bcast = e->sent_bcast;
    out->recved_bcast = e->recved_bcast;
    out->total_pickup = e->total_pickup;
    out->ops_failed = 0; /* op deadlines are Python-side (schema parity) */
    out->arq_retransmits = e->arq_retx;
    out->arq_dup_drops = e->arq_dup;
    out->arq_gave_up = e->arq_gaveup;
    out->arq_unacked = e->arq_unacked_cnt;
    out->epoch = e->epoch;
    out->epoch_quarantined = e->quarantined;
    out->rejoins = e->rejoins_cnt;
    out->view_changes = e->view_changes;
    out->reflood_frames = e->reflood_frames;
    out->epoch_lag_max = e->epoch_lag_max;
    out->quar_mid_rejoin = e->quar_mid_rejoin;
    out->quar_failed_sender = e->quar_failed_sender;
    out->quar_below_floor = e->quar_below_floor;
    out->admission_rounds = e->admission_rounds;
    out->epoch_syncs = e->epoch_syncs;
    out->reflood_skipped = e->reflood_skipped;
    out->batched_admits = e->batched_admits;
    out->q_wait = e->q_wait.len;
    out->q_pickup = e->q_pickup.len;
    out->q_wait_and_pickup = e->q_wait_pickup.len;
    out->q_iar_pending = e->q_iar_pending.len;
    out->bcast_complete = e->h_bcast;
    out->proposal_resolve = e->h_prop;
    out->pickup_wait = e->h_pickup;
    return RLO_OK;
}

int rlo_engine_link_stats(const rlo_engine *e, rlo_link_stats *out,
                          int cap)
{
    if (!e || !out || cap < 0)
        return RLO_ERR_ARG;
    int n = cap < e->ws ? cap : e->ws; /* partial fill, per header */
    memcpy(out, e->links, (size_t)n * sizeof(rlo_link_stats));
    return e->ws;
}

/* Engine-originated telemetry digest (docs/DESIGN.md S17): sample the
 * engine's own telemetry into the wire.py TELEM_KEYS order — the
 * rlo_stats counter block, then the extras (link rollups, worst RTT
 * EWMA, queue depth, pickup backlog; the serving page keys are always
 * 0 here — the C engine hosts no paged server) — and delta-encode vs
 * the last digest THIS engine emitted (rlo_telem_encode). */
int64_t rlo_engine_telem_digest(rlo_engine *e, int full, uint8_t *buf,
                                int64_t cap)
{
    if (!e || !buf)
        return RLO_ERR_ARG;
    int64_t v[RLO_TELEM_NKEYS];
    int i = 0;
    v[i++] = e->sent_bcast;
    v[i++] = e->recved_bcast;
    v[i++] = e->total_pickup;
    v[i++] = 0; /* ops_failed: op deadlines are Python-side */
    v[i++] = e->arq_retx;
    v[i++] = e->arq_dup;
    v[i++] = e->arq_gaveup;
    v[i++] = e->arq_unacked_cnt;
    v[i++] = e->epoch;
    v[i++] = e->quarantined;
    v[i++] = e->rejoins_cnt;
    v[i++] = e->view_changes;
    v[i++] = e->reflood_frames;
    v[i++] = e->epoch_lag_max;
    v[i++] = e->quar_mid_rejoin;
    v[i++] = e->quar_failed_sender;
    v[i++] = e->quar_below_floor;
    v[i++] = e->admission_rounds;
    v[i++] = e->epoch_syncs;
    v[i++] = e->reflood_skipped;
    v[i++] = e->batched_admits;
    int64_t tx = 0, rx = 0;
    double rtt = 0.0;
    for (int r = 0; r < e->ws; r++) {
        tx += e->links[r].tx_frames;
        rx += e->links[r].rx_frames;
        if (e->links[r].rtt_ewma_usec > rtt)
            rtt = e->links[r].rtt_ewma_usec;
    }
    v[i++] = tx;
    v[i++] = rx;
    v[i++] = (int64_t)rtt;
    v[i++] = e->q_wait.len;
    v[i++] = e->q_pickup.len + e->q_wait_pickup.len;
    v[i++] = 0; /* pages_in_use */
    v[i++] = 0; /* pages_free */
    v[i++] = 0; /* serve_inflight: the serving fabric is Python-side */
    v[i++] = 0; /* ttft_p50_usec */
    v[i++] = 0; /* ttft_p99_usec */
    v[i++] = 0; /* e2e_p50_usec */
    v[i++] = 0; /* e2e_p99_usec */
    v[i++] = 0; /* coll_steps: tensor collectives are Python-side */
    v[i++] = 0; /* coll_bytes */
    v[i++] = 0; /* remedies_proposed: remediation is Python-side */
    v[i++] = 0; /* remedies_executed */
    v[i++] = 0; /* quarantined */
    v[i++] = 0; /* backpressure_level */
    /* digest seqs are incarnation-partitioned like the broadcast
     * seqs (mirror of TelemetryPlane): re-base on a bumped life and
     * re-anchor receivers with a full snapshot; the first digest of
     * any life is always full */
    uint32_t base = (uint32_t)e->incarnation << 20;
    if (e->telem_seq <= base) {
        if (e->telem_seq < base)
            e->telem_seq = base;
        full = 1;
    }
    /* full_every=8 cadence (mirror of TelemetryPlane's default): a
     * receiver that lost a delta parks the entry as `gap` and ONLY a
     * full snapshot heals it — without the cadence one lost digest
     * would stale this rank in every fleet view for the rest of the
     * run (the base is 8-aligned, so the mod matches Python's) */
    if ((e->telem_seq & 7u) == 0)
        full = 1;
    int64_t n = rlo_telem_encode(buf, cap, e->rank, e->epoch,
                                 e->telem_seq, full, v,
                                 full ? 0 : e->telem_prev);
    if (n < 0)
        return n;
    memcpy(e->telem_prev, v, sizeof(v));
    e->telem_seq++;
    return n;
}

int rlo_engine_enable_profiler(rlo_engine *e, int on)
{
    if (!e)
        return RLO_ERR_ARG;
    e->profiler_on = on ? 1 : 0;
    return RLO_OK;
}

int rlo_engine_phase_stats(const rlo_engine *e, rlo_phase_stats *out)
{
    if (!e || !out)
        return RLO_ERR_ARG;
    *out = e->ph;
    return RLO_OK;
}

int rlo_engine_rank_failed(const rlo_engine *e, int rank)
{
    return e->failed && rank >= 0 && rank < e->ws && e->failed[rank];
}

int rlo_engine_failed_count(const rlo_engine *e)
{
    return e->n_failed;
}

int rlo_engine_suspected_self(const rlo_engine *e)
{
    return e->suspected_self;
}

/* ---------------- membership epochs + elastic rejoin ----------------
 * Mirror of rlo_tpu/engine.py's membership machinery (docs/DESIGN.md
 * S8; see the protocol paragraph in rlo_core.h). Every rank carries a
 * monotone membership epoch; a failed-but-alive rank converges back
 * in by JOIN probes + an IAR admission round over the member set —
 * the rootless op voting on its own membership — finished by a
 * JOIN_WELCOME + recent-broadcast replay. */

static int member_pid(const rlo_engine *e, int joiner)
{
    return RLO_MEMBER_PID_BASE - (joiner * e->ws + e->rank);
}

/* Fail my own in-flight round deterministically (watchdog expiry or
 * entering joiner mode): free the slot, and for a membership round
 * clear the admitting flag so the joiner's next probe re-petitions.
 * Decision-pending rounds are left alone — their completion needs
 * only the local send handles, no inbound frame.
 *
 * Known divergence from the Python twin: no RLO_TAG_ABORT broadcast
 * (the C engine has no ABORT receive path — unknown tags go to app
 * pickup, and leaking engine-internal frames there would be worse).
 * Relays that parked the round are swept by the next successful
 * admission of the same joiner (execute_admission); only a joiner
 * that dies for good leaves its in-flight rounds parked, a bounded
 * retention (no new petitions => no new rounds). */
static void abort_own_round(rlo_engine *e)
{
    rlo_prop *p = &e->own;
    if (p->state != RLO_IN_PROGRESS || p->decision_pending)
        return;
    p->state = RLO_FAILED;
    e->prop_born = 0;
    e->p_prop_born = 0; /* phase timers track successes only */
    e->own_deadline = 0;
    rlo_trace_emit(e->rank, RLO_EV_DECISION, p->pid, -1, p->gen, 0);
    if (p->pid <= RLO_MEMBER_PID_BASE && p->payload) {
        /* aborted admission round: free every batched joiner for a
         * retry (their next JOIN probes re-petition) */
        int32_t new_epoch;
        const uint8_t *recs;
        int k = member_decode(p->payload, p->len, &new_epoch, &recs);
        for (int j = 0; j < k; j++) {
            int joiner = get_le32(recs + 8 * j);
            if (joiner >= 0 && joiner < e->ws)
                e->admitting[joiner] = 0;
        }
    }
}

static int min_alive(const rlo_engine *e)
{
    /* self always counts as alive (failed[rank] is never set) */
    for (int r = 0; r < e->ws; r++)
        if (r == e->rank || !e->failed[r])
            return r;
    return e->rank;
}

static uint64_t join_iv(const rlo_engine *e)
{
    if (e->join_interval)
        return e->join_interval;
    /* the failure detector's heartbeat interval when it is on, else a
     * conservative default for explicit rejoin on detector-less
     * engines (mirror of ProgressEngine.join_interval) */
    return e->fd_interval ? e->fd_interval : 500000;
}

/* Total order on membership views: higher epoch wins, then the side
 * containing the lower rank (disjoint split-brain views always differ
 * there); exact ties break by rank id. Returns 1 when MY view wins
 * against (ep, malive) as reported by `src`. */
static int view_wins(const rlo_engine *e, int32_t ep, int malive,
                     int src)
{
    int my_min = min_alive(e);
    if (e->epoch != ep)
        return e->epoch > ep;
    if (my_min != malive)
        return my_min < malive; /* -min_alive: lower base rank wins */
    return e->rank < src;
}

/* Enter joiner mode: quarantine everything except membership frames
 * and petition for readmission until a JOIN_WELCOME arrives. The
 * full-quarantine gate is what makes the admission's link-sequence
 * reset safe — no stale ACK or old-seq frame can touch the fresh
 * link state. */
static void become_joiner(rlo_engine *e)
{
    if (e->awaiting_welcome)
        return;
    /* my own in-flight round can never resolve once I quarantine
     * everything (its votes would be dropped unread): fail it now
     * and free the slot instead of wedging it forever */
    abort_own_round(e);
    e->awaiting_welcome = 1;
    e->join_last = 0; /* probe immediately */
}

/* (incarnation, epoch, min-alive-rank, petition, member): petition=1
 * marks a JOINER's plea (it has reset itself and quarantines
 * everything) vs a survivor's heal probe at a failed peer; member=1
 * tells dst it is ALIVE in the sender's view — a losing-view receiver
 * then catches up with a Tag.MSYNC view sync instead of a full rejoin
 * (docs/DESIGN.md S18). Old 4-field probes parse as member=0 (full
 * rejoin: status quo). */
static void send_join_probe(rlo_engine *e, int dst)
{
    uint8_t payload[20];
    put_le32(payload, e->incarnation);
    put_le32(payload + 4, e->epoch);
    put_le32(payload + 8, min_alive(e));
    put_le32(payload + 12, e->awaiting_welcome ? 1 : 0);
    put_le32(payload + 16,
             (e->awaiting_welcome ||
              (dst >= 0 && dst < e->ws && e->failed[dst]))
                 ? 0
                 : 1);
    eng_isend(e, dst, RLO_TAG_JOIN, e->rank, -1, -1, payload, 20, 0);
    rlo_trace_emit(e->rank, RLO_EV_JOIN, dst, 1, e->incarnation,
                   e->epoch);
}

/* Drop stale FAILURE notices about `keep`-flagged ranks from the
 * re-flood log: a re-flooded declaration about a readmitted rank
 * would kill the fresh incarnation. */
static void purge_stale_failures_impl(rlo_engine *e,
                                      const uint8_t *target, int rank)
{
    for (int i = 0; i < RLO_RECENT_LOG; i++) {
        rlo_blob *b = e->recent[i];
        if (!b || e->recent_tag[i] != RLO_TAG_FAILURE)
            continue;
        int32_t pid;
        if (rlo_frame_decode(b->data, b->len, 0, &pid, 0, 0, 0) < 0)
            continue;
        if (target ? (pid >= 0 && pid < e->ws && target[pid])
                   : pid == rank) {
            rlo_blob_unref(b);
            e->recent[i] = 0;
        }
    }
}

static void purge_stale_failures(rlo_engine *e, const uint8_t *target)
{
    purge_stale_failures_impl(e, target, -1);
}

static void purge_stale_failure_rank(rlo_engine *e, int rank)
{
    purge_stale_failures_impl(e, 0, rank);
}

/* Adopt an admission decision into the membership view (idempotent):
 * re-form the overlay to include the joiner, raise the epoch to the
 * agreed value, set the joiner's epoch floor (its dead incarnation's
 * frames all fall below it), and clear the RECEIVE-side ARQ window
 * toward the joiner — a restarted joiner's link seqs start at 0,
 * which the old window would misread as duplicates. The send-side
 * seq counter is never reset (monotone for this process's lifetime),
 * so a peer that keeps its window across our reset can never misread
 * our fresh frames as duplicates either. Returns 1 when the admission
 * actually executed (passed the idempotence guard). */
static int execute_admission(rlo_engine *e, int joiner, int inc,
                             int32_t new_epoch)
{
    if (joiner < 0 || joiner >= e->ws || joiner == e->rank ||
        e->sub_excluded[joiner])
        return 0;
    if (new_epoch <= e->admit_epoch[joiner])
        /* stale or duplicate admission artifact (an old decision
         * re-flooded out of a replaced view): executing it would
         * re-run the link reset ONE-SIDED and permanently desync the
         * ARQ windows on that edge */
        return 0;
    e->admit_epoch[joiner] = new_epoch;
    /* a CERTIFIED link-reset epoch (unlike the wholesale welcome
     * inflation of admit_epoch): sync responses built from it can
     * tell a laggard which floor is safe for this member (S18) */
    e->reset_epoch[joiner] = new_epoch;
    if (new_epoch > e->epoch)
        e->epoch = new_epoch;
    if (inc > e->admitted_inc[joiner])
        e->admitted_inc[joiner] = inc;
    e->epoch_floor[joiner] = new_epoch;
    e->link_epoch[joiner] = new_epoch;
    /* clear the receive window even when we never marked the joiner
     * failed ourselves (another member re-declared and re-admitted
     * it; the joiner reset its half at the welcome, so keeping ours
     * would swallow its fresh seqs as duplicates). Our tx seq counter
     * is NOT reset — seq spaces are monotone per process lifetime, so
     * the joiner's window (fresh or kept) never misreads our next
     * frames; the unfillable-hole rule in arq_on_ack re-syncs its
     * cumulative-ACK watermark in one round trip. App-level dedup
     * ((origin, seq) windows + the settled-round ring) keeps delivery
     * exactly-once across the reset. */
    arq_drop_dst(e, joiner);
    e->tx_skip[joiner] = -1;
    e->rx_contig[joiner] = -1;
    memset(&e->rx_mask[(size_t)joiner * RLO_SEEN_WORDS], 0,
           RLO_SEEN_WORDS * sizeof(uint64_t));
    e->ack_due[joiner] = 0;
    /* joiner-liveness grace (S18): a mid-rejoin joiner does not
     * heartbeat until its JOIN_WELCOME (or superseding sync) lands,
     * so a plain now-stamp re-declares it failed whenever the welcome
     * leg outlasts fd_timeout — the self-reinforcing half of the
     * rejoin cascade. Date the stamp into the future by half the
     * admission-round deadline; any accepted frame from the joiner
     * refreshes it to a live stamp. */
    {
        uint64_t grace = 2 * e->fd_timeout;
        uint64_t g2 = 10 * join_iv(e);
        if (g2 > grace)
            grace = g2;
        e->hb_seen[joiner] = rlo_now_usec() + grace;
    }
    /* abandoned concurrent admission rounds for this joiner (their
     * proposer's watchdog fired, or the round wedged in a mixed-view
     * tree) are settled by THIS admission: unpark their parked relays
     * so they don't accumulate across heal churn */
    for (rlo_msg *pm = e->q_iar_pending.head; pm;) {
        rlo_msg *nm = pm->next;
        if (pm->ps && pm->pid <= RLO_MEMBER_PID_BASE &&
            (RLO_MEMBER_PID_BASE - pm->pid) / e->ws == joiner) {
            pm->ps->state = RLO_FAILED;
            q_remove(&e->q_iar_pending, pm);
            msg_free(pm);
        }
        pm = nm;
    }
    purge_stale_failure_rank(e, joiner);
    if (!e->failed[joiner])
        return 1; /* view unchanged (concurrent admitting proposer) */
    e->failed[joiner] = 0;
    e->n_failed--;
    e->rejoins_cnt++;
    e->view_changes++;
    rlo_trace_emit(e->rank, RLO_EV_ADMIT, joiner, e->epoch, inc, 0);
    if (!getenv("RLO_QUIET"))
        fprintf(stderr,
                "rlo_tpu: rank %d admitted rank %d (incarnation %d, "
                "epoch %d)\n",
                e->rank, joiner, inc, (int)e->epoch);
    /* plug forwarding holes across the overlay re-form, exactly like
     * the failure path does */
    reflood_recent(e);
    return 1;
}

static void send_welcome(rlo_engine *e, int joiner, int inc,
                         int32_t new_epoch)
{
    int64_t cap = 12 + 4 * (int64_t)e->ws;
    uint8_t *payload = (uint8_t *)malloc((size_t)cap);
    if (!payload) {
        set_err(e, RLO_ERR_NOMEM);
        return;
    }
    int n = 0;
    for (int r = 0; r < e->ws; r++)
        if (r == e->rank || !e->failed[r])
            put_le32(payload + 12 + 4 * n++, r);
    put_le32(payload, new_epoch);
    put_le32(payload + 4, inc);
    put_le32(payload + 8, n);
    eng_isend(e, joiner, RLO_TAG_JOIN_WELCOME, e->rank, -1, -1, payload,
              12 + 4 * (int64_t)n, 0);
    free(payload);
}

/* Point-to-point replay of the recent-broadcast log to a freshly
 * admitted joiner so it converges on recent traffic (its (origin,
 * seq) dedup absorbs anything it already saw). FAILURE notices AND
 * membership decisions are skipped — the welcome's member list is
 * the authoritative view, and a stale admission decision about a
 * since-re-failed rank would pass the joiner's admit_epoch guard
 * (reset by the welcome) and resurrect the dead rank in its view. */
static void replay_recent(rlo_engine *e, int joiner)
{
    for (int i = 0; i < RLO_RECENT_LOG; i++) {
        rlo_blob *b = e->recent[i];
        if (!b || e->recent_tag[i] == RLO_TAG_FAILURE)
            continue;
        if (e->recent_tag[i] == RLO_TAG_IAR_DECISION) {
            int32_t pid;
            if (rlo_frame_decode(b->data, b->len, 0, &pid, 0, 0,
                                 0) >= 0 &&
                pid <= RLO_MEMBER_PID_BASE)
                continue;
        }
        eng_isend_frame(e, joiner, e->recent_tag[i], b, 0);
    }
}

/* Admitting proposer's epilogue: execute the batch of admissions,
 * then welcome + replay to each joiner. */
static void finish_member_round(rlo_engine *e)
{
    rlo_prop *p = &e->own;
    int32_t new_epoch;
    const uint8_t *recs;
    int k = p->payload
                ? member_decode(p->payload, p->len, &new_epoch, &recs)
                : 0;
    if (!k)
        return;
    for (int j = 0; j < k; j++) {
        int joiner = get_le32(recs + 8 * j);
        if (joiner < 0 || joiner >= e->ws)
            continue;
        e->admitting[joiner] = 0;
        if (e->pending_join[joiner]) {
            e->pending_join[joiner] = 0;
            e->n_pending--;
        }
    }
    if (!p->vote)
        return;
    for (int j = 0; j < k; j++) {
        int joiner = get_le32(recs + 8 * j);
        int inc = get_le32(recs + 8 * j + 4);
        if (joiner < 0 || joiner >= e->ws)
            continue;
        if (execute_admission(e, joiner, inc, new_epoch) && k > 1)
            e->batched_admits++;
        send_welcome(e, joiner, inc, new_epoch);
        replay_recent(e, joiner);
    }
}

/* A JOIN probe/petition arrived: compare view keys. If the sender's
 * view loses and it is failed here, petition to admit it (IAR over
 * the member set). If its view wins, become a joiner ourselves
 * (split-brain heal = mutual rejoin, higher epoch winning). If it
 * probes us while we hold the winning view but consider it alive,
 * answer with our own probe so it petitions us. Does NOT consume m. */
static void on_join(rlo_engine *e, rlo_msg *m)
{
    int src = m->src;
    if (src < 0 || src >= e->ws || src == e->rank ||
        e->sub_excluded[src] || m->len < 16)
        return;
    int inc = get_le32(m->payload);
    int32_t ep = get_le32(m->payload + 4);
    int malive = get_le32(m->payload + 8);
    int petition = get_le32(m->payload + 12);
    /* 5th field (PR-16): dst-is-a-member flag; absent on old 4-field
     * probes, which parse as 0 (full rejoin: status quo) */
    int member = m->len >= 20 ? get_le32(m->payload + 16) : 0;
    rlo_trace_emit(e->rank, RLO_EV_JOIN, src, 0, inc, ep);
    if (e->awaiting_welcome)
        return; /* mid-rejoin ourselves; the winning side sorts us */
    int mine_wins = view_wins(e, ep, malive, src);
    if (e->failed[src]) {
        if (!mine_wins) {
            if (member) {
                /* the winning view still holds me as a member: I am
                 * merely epoch-lagging, not excluded — catch up with
                 * a view-state sync instead of the full rejoin that
                 * used to strand every laggard (S18) */
                request_sync(e, src);
                return;
            }
            /* rlo-model: edge join->joiner */
            become_joiner(e);
            return;
        }
        if (inc < e->admitted_inc[src])
            return; /* stale probe from an already-replaced life */
        if (e->admitting[src] || e->pending_join[src])
            return; /* a round for it is already queued/in flight */
        e->pending_join[src] = 1;
        e->pending_inc[src] = inc;
        e->pending_ep[src] = ep;
        e->n_pending++;
    } else if (!mine_wins) {
        if (member) {
            request_sync(e, src);
            return;
        }
        /* rlo-model: edge join->joiner */
        become_joiner(e);
    } else if (petition) {
        if (inc < e->admitted_inc[src])
            return; /* stale petition from an already-replaced life */
        if (inc == e->admitted_inc[src] && e->reset_epoch[src]) {
            /* sync-supersedes-welcome (S18): this exact life was
             * already admitted here, so its JOIN_WELCOME was lost in
             * flight. The old answer — re-declare it failed and
             * re-admit — was the measured rejoin-cascade amplifier; a
             * view-state sync response carries everything the welcome
             * did and repeats for free on the petition cadence until
             * one lands. */
            msync_serve(e, src);
            return;
        }
        /* a rank we consider ALIVE is petitioning against our winning
         * view: it has reset itself and quarantines our traffic, so
         * it is effectively failed here — adopt + announce that, then
         * run the normal admission (without this, a lone stale-view
         * winner would answer petitions with probes forever and
         * nobody would ever admit anyone) */
        announce_failed(e, src);
        if (inc >= e->admitted_inc[src] && !e->admitting[src]) {
            if (!e->pending_join[src]) {
                e->pending_join[src] = 1;
                e->n_pending++;
            }
            e->pending_inc[src] = inc;
            e->pending_ep[src] = ep;
        }
    } else {
        /* the prober holds a losing view yet thinks we are alive
         * (asymmetric partition): show it the winning view */
        send_join_probe(e, src);
    }
}

/* Wholesale view adoption — the shared core of JOIN_WELCOME and the
 * sync-supersede path (docs/DESIGN.md S18): a certified admission of
 * THIS life at `new_epoch` whose notification reached us either as
 * the welcome itself or as a sync response after the welcome was
 * lost. `mem` is a ws-sized member-flag array (self included).
 * Adopts epoch, member list, fresh link state and heartbeat grace
 * everywhere, per-member epoch floors at the agreed epoch (members
 * only send to us AFTER executing the admission, so everything below
 * the floor is pre-partition leftovers). */
static void adopt_view(rlo_engine *e, int32_t new_epoch,
                       const uint8_t *mem, int inc, int src)
{
    e->awaiting_welcome = 0;
    e->suspected_self = 0;
    if (new_epoch > e->welcome_epoch)
        e->welcome_epoch = new_epoch;
    if (new_epoch > e->epoch)
        e->epoch = new_epoch;
    e->n_failed = 0;
    for (int r = 0; r < e->ws; r++) {
        if (mem[r] && r != e->rank && e->admit_epoch[r] < new_epoch)
            /* members of the adopted view are known-alive at this
             * epoch: FAILURE notices declared below it are stale */
            e->admit_epoch[r] = new_epoch;
        e->failed[r] = (!mem[r] || e->sub_excluded[r]) ? 1 : 0;
        if (r == e->rank)
            e->failed[r] = 0;
        e->n_failed += e->failed[r];
        /* fresh receive state everywhere (skip notices, windows,
         * floors); tx_seq is PRESERVED — seq spaces are monotone per
         * process lifetime, so a member whose matching admission
         * execution was suppressed as stale (its rx watermark intact)
         * still reads our next frames as fresh instead of silently
         * dup-dropping them into a half-dead-link deadlock */
        e->tx_skip[r] = -1;
        e->tx_skip_due[r] = 0;
        e->skip_hold[r] = 0;
        e->ack_due[r] = 0;
        e->rx_contig[r] = -1;
        e->hb_seen[r] = 0;
        int in_view = mem[r] && r != e->rank;
        e->epoch_floor[r] = in_view ? new_epoch : 0;
        e->link_epoch[r] = in_view ? new_epoch : 0;
        /* our pre-adoption link-reset certifications described a view
         * we just replaced wholesale; serving sync floors from them
         * would hand laggards one-sided floors (S18) */
        e->reset_epoch[r] = 0;
        e->sync_req_last[r] = 0;
    }
    memset(e->rx_mask, 0,
           (size_t)e->ws * RLO_SEEN_WORDS * sizeof(uint64_t));
    while (e->rtx_head)
        /* rtx_release keeps the per-dst ack chains and the unacked
         * counter consistent in one place — no companion bookkeeping
         * for the next editor to forget */
        rtx_release(e, e->rtx_head);
    e->hb_last_sent = 0;
    purge_stale_failures(e, mem);
    /* relayed rounds whose proposer is outside the adopted view can
     * never resolve here — unpark them as FAILED (the mirror of
     * abort_orphaned_proposals for the joiner side) */
    for (rlo_msg *pm = e->q_iar_pending.head; pm;) {
        rlo_msg *nm = pm->next;
        if (pm->ps &&
            (pm->origin < 0 || pm->origin >= e->ws || !mem[pm->origin])) {
            pm->ps->state = RLO_FAILED;
            q_remove(&e->q_iar_pending, pm);
            msg_free(pm);
        }
        pm = nm;
    }
    e->rejoins_cnt++;
    e->view_changes++;
    e->join_last = 0;
    /* advertise the log retained across the rejoin: this rank may be
     * the SOLE holder of its old life's entries (e.g. an abort
     * flooded while partitioned alone), and no later view change is
     * guaranteed to occur here — the WANT-side guards
     * (have_log_entry) make stale entries harmless */
    reflood_recent(e);
    rlo_trace_emit(e->rank, RLO_EV_ADMIT, e->rank, e->epoch, inc,
                   src);
    if (!getenv("RLO_QUIET"))
        fprintf(stderr,
                "rlo_tpu: rank %d rejoined at epoch %d (welcomed by "
                "rank %d)\n",
                e->rank, (int)e->epoch, src);
}

/* The admitting proposer's JOIN_WELCOME: validate + adopt its
 * membership view wholesale (adopt_view). The replay of the
 * proposer's recent-broadcast log follows on the same FIFO channel.
 * Does NOT consume m. */
static void on_welcome(rlo_engine *e, rlo_msg *m)
{
    if (m->len < 12)
        return;
    int32_t new_epoch = get_le32(m->payload);
    int inc = get_le32(m->payload + 4);
    int n = get_le32(m->payload + 8);
    if (inc != e->incarnation)
        return; /* welcome addressed to an older life of this rank */
    if (n < 0 || m->len < 12 + 4 * (int64_t)n)
        return;
    if (!e->awaiting_welcome && new_epoch <= e->welcome_epoch)
        /* duplicate/stale welcome (concurrent admitting proposers).
         * Deliberately compared against the last ADOPTED welcome
         * epoch, not e->epoch: our own epoch can outrun the round's
         * agreed epoch via local declarations, and rejecting the
         * welcome then would leave the admitting side's link-state
         * reset one-sided (a permanently desynced ARQ window) — the
         * exact mirror of the members' admit_epoch idempotence rule */
        return;
    uint8_t *mem = (uint8_t *)calloc((size_t)e->ws, 1);
    if (!mem) {
        set_err(e, RLO_ERR_NOMEM);
        return;
    }
    mem[e->rank] = 1;
    for (int i = 0; i < n; i++) {
        int r = get_le32(m->payload + 12 + 4 * i);
        if (r >= 0 && r < e->ws)
            mem[r] = 1;
    }
    /* rlo-model: edge welcome->member */
    adopt_view(e, new_epoch, mem, inc, m->src);
    free(mem);
}

/* -- Tag.MSYNC: view-state sync (docs/DESIGN.md S18) ----------------
 * Byte-compatible with engine.py's MSYNC_REQ/RSP/AD/WANT payloads;
 * ARQ- and epoch-exempt exactly like JOIN, so a lost frame costs
 * latency, never correctness. */

/* Ask an up-to-date peer for a view-state sync: the epoch catch-up
 * path that replaces the full rejoin a laggard used to be stranded
 * into. Rate-limited per destination at the join-probe cadence — the
 * probes that trigger it repeat on the peer's heal-probe cadence, so
 * one outstanding REQ per peer is enough and loss costs one cadence
 * interval, never progress. */
static void request_sync(rlo_engine *e, int dst)
{
    uint64_t now = rlo_now_usec();
    if (e->sync_req_last[dst] &&
        now - e->sync_req_last[dst] < join_iv(e))
        return;
    e->sync_req_last[dst] = now;
    uint8_t payload[9];
    payload[0] = RLO_MSYNC_REQ;
    put_le32(payload + 1, e->epoch);
    put_le32(payload + 5, e->incarnation);
    eng_isend(e, dst, RLO_TAG_MSYNC, e->rank, -1, -1, payload, 9, 0);
}

/* Build + send a MSYNC_RSP: epoch, member records, and the recent-log
 * advert. Per-member records carry only CERTIFIED link-reset epochs
 * (reset_epoch, set solely by execute_admission) — never the
 * wholesale welcome inflation of admit_epoch, which would hand the
 * laggard a one-sided floor for members whose links were never
 * actually reset (S18). */
static void msync_serve(rlo_engine *e, int dst)
{
    if (e->awaiting_welcome)
        return; /* mid-rejoin: nothing certifiable to serve */
    int64_t cap = 9 + 12 * (int64_t)e->ws + 5 + 12 * RLO_RECENT_LOG;
    uint8_t *payload = (uint8_t *)malloc((size_t)cap);
    if (!payload)
        return; /* best-effort: the next petition retries */
    int n = 0;
    int64_t pos = 9;
    for (int r = 0; r < e->ws; r++) {
        if (r != e->rank && e->failed[r])
            continue;
        put_le32(payload + pos, r);
        if (r == e->rank) {
            put_le32(payload + pos + 4, e->welcome_epoch);
            put_le32(payload + pos + 8, e->incarnation);
        } else {
            put_le32(payload + pos + 4, e->reset_epoch[r]);
            put_le32(payload + pos + 8, e->admitted_inc[r]);
        }
        pos += 12;
        n++;
    }
    payload[0] = RLO_MSYNC_RSP;
    put_le32(payload + 1, e->epoch);
    put_le32(payload + 5, n);
    /* embedded advert tail: same [count:i32] + triple body as a
     * standalone MSYNC_AD, minus its kind byte */
    uint8_t ad[5 + 12 * RLO_RECENT_LOG];
    int64_t adlen = advert_payload(e, ad);
    if (adlen > 0) {
        memcpy(payload + pos, ad + 1, (size_t)(adlen - 1));
        pos += adlen - 1;
    } else {
        put_le32(payload + pos, 0);
        pos += 4;
    }
    if (pos + 64 > e->msg_size_max) {
        /* view too large for one frame (pathological world_size):
         * fall back to the full-rejoin path rather than truncate */
        free(payload);
        send_join_probe(e, dst);
        return;
    }
    eng_isend(e, dst, RLO_TAG_MSYNC, e->rank, -1, -1, payload, pos, 0);
    free(payload);
}

/* MSYNC_AD body at `off`: [count:i32] + count x [tag][a][b]
 * recent-log identities. Answer with a WANT naming exactly the
 * entries this rank provably misses; each entry already held is a
 * re-flood frame the old blast would have wasted (reflood_skipped);
 * every read below is dominated by a length guard. */
static void msync_advert(rlo_engine *e, int src, const uint8_t *p,
                         int64_t plen, int64_t off)
{
    if (plen < off + 4)
        return;
    int cnt = get_le32(p + off);
    if (cnt < 0 || plen < off + 4 + 12 * (int64_t)cnt)
        return;
    uint8_t *out = (uint8_t *)malloc((size_t)(5 + 12 * (int64_t)cnt));
    if (!out)
        return;
    int nw = 0;
    for (int i = 0; i < cnt; i++) {
        int32_t t = get_le32(p + off + 4 + 12 * i);
        int32_t a = get_le32(p + off + 4 + 12 * i + 4);
        int32_t b = get_le32(p + off + 4 + 12 * i + 8);
        if (have_log_entry(e, t, a, b)) {
            e->reflood_skipped++;
        } else {
            put_le32(out + 5 + 12 * nw, t);
            put_le32(out + 5 + 12 * nw + 4, a);
            put_le32(out + 5 + 12 * nw + 8, b);
            nw++;
        }
    }
    if (nw) {
        out[0] = RLO_MSYNC_WANT;
        put_le32(out + 1, nw);
        eng_isend(e, src, RLO_TAG_MSYNC, e->rank, -1, -1, out,
                  5 + 12 * (int64_t)nw, 0);
    }
    free(out);
}

/* A WANT reply to our advert: re-send exactly the named recent-log
 * entries (through the ARQ gate, fresh link seqs — a new
 * transmission, not a retransmit; app-level dedup absorbs any
 * crossing duplicates). */
static void msync_want(rlo_engine *e, int src, const uint8_t *p,
                       int64_t plen)
{
    if (plen < 5)
        return;
    int cnt = get_le32(p + 1);
    if (cnt < 0 || plen < 5 + 12 * (int64_t)cnt)
        return;
    for (int i = 0; i < RLO_RECENT_LOG; i++) {
        int32_t t, a, b;
        if (!log_entry_ident(e, i, &t, &a, &b))
            continue;
        for (int j = 0; j < cnt; j++)
            if (get_le32(p + 5 + 12 * j) == t &&
                get_le32(p + 5 + 12 * j + 4) == a &&
                get_le32(p + 5 + 12 * j + 8) == b) {
                e->reflood_frames++;
                eng_isend_frame(e, src, e->recent_tag[i],
                                e->recent[i], 0);
                break;
            }
    }
}

/* A MSYNC_RSP arrived: catch up to the responder's view without a
 * full rejoin. Three cases: (1) the response certifies an admission
 * of THIS life we never saw the welcome for — wholesale adoption,
 * exactly as the welcome would have done (sync-supersedes-welcome);
 * (2) we are a mere epoch laggard — execute the certified per-member
 * admissions we missed and adopt the responder's failures; (3)
 * nothing certifiable heals the link to the responder — fall back to
 * a full rejoin, the pre-S18 status quo, so every sync exchange
 * strictly progresses. */
static void msync_adopt(rlo_engine *e, int src, const uint8_t *p,
                        int64_t plen)
{
    if (plen < 9)
        return;
    int32_t rsp_epoch = get_le32(p + 1);
    int n = get_le32(p + 5);
    if (n < 0 || plen < 9 + 12 * (int64_t)n)
        return;
    /* staleness, judged at ARRIVAL epoch (adoption below may raise
     * it): a response no newer than my view means I progressed past
     * the request in flight — I am not the laggard anymore */
    int stale = rsp_epoch <= e->epoch;
    int64_t ad_off = 9 + 12 * (int64_t)n;
    int32_t my_aep = 0, my_ainc = 0;
    int have_mine = 0;
    for (int i = 0; i < n; i++)
        if (get_le32(p + 9 + 12 * i) == e->rank) {
            my_aep = get_le32(p + 9 + 12 * i + 4);
            my_ainc = get_le32(p + 9 + 12 * i + 8);
            have_mine = 1;
            break;
        }
    if (!have_mine) {
        /* the responder's view does not hold me at all: if it wins,
         * only a full rejoin gets me back in */
        if (rsp_epoch > e->epoch)
            /* rlo-model: edge msync->joiner */
            become_joiner(e);
        return;
    }
    int adopted = 0;
    if (my_ainc == e->incarnation && my_aep > e->welcome_epoch) {
        /* lost-welcome supersede: the responder certifies THIS life
         * was admitted at my_aep but no welcome ever landed — adopt
         * the view wholesale with the welcome's exact semantics
         * (un-wedges awaiting_welcome) */
        uint8_t *mem = (uint8_t *)calloc((size_t)e->ws, 1);
        if (!mem)
            return;
        mem[e->rank] = 1;
        for (int i = 0; i < n; i++) {
            int r = get_le32(p + 9 + 12 * i);
            if (r >= 0 && r < e->ws)
                mem[r] = 1;
        }
        /* rlo-model: edge msync->member */
        adopt_view(e, my_aep, mem, e->incarnation, src);
        free(mem);
        if (rsp_epoch > e->epoch)
            e->epoch = rsp_epoch;
        adopted = 1;
    } else if (e->awaiting_welcome) {
        /* mid-rejoin and the response does not certify this life:
         * keep petitioning — only an admission can help now */
        return;
    } else {
        /* laggard catch-up: execute certified admissions (aep > 0
         * entries only; a zero means "no reset I can vouch for") */
        for (int i = 0; i < n; i++) {
            int r = get_le32(p + 9 + 12 * i);
            int32_t aep = get_le32(p + 9 + 12 * i + 4);
            int ainc = get_le32(p + 9 + 12 * i + 8);
            if (r != e->rank && aep > 0 && r >= 0 && r < e->ws &&
                aep > e->admit_epoch[r] &&
                execute_admission(e, r, ainc, aep))
                adopted = 1;
        }
        if (rsp_epoch > e->epoch) {
            /* adopt the responder's failures: ranks alive here but
             * absent from its strictly-newer view, unless an
             * admission we already executed post-dates it */
            for (int r = 0; r < e->ws; r++) {
                if (r == e->rank || e->failed[r])
                    continue;
                int present = 0;
                for (int i = 0; i < n; i++)
                    if (get_le32(p + 9 + 12 * i) == r) {
                        present = 1;
                        break;
                    }
                if (!present && rsp_epoch > e->admit_epoch[r])
                    mark_failed(e, r);
            }
            if (rsp_epoch > e->epoch)
                e->epoch = rsp_epoch;
            adopted = 1;
        }
    }
    if (e->failed[src]) {
        /* a stale RSP (predates local progress) is dropped, not
         * acted on: becoming a joiner off stale state can wedge the
         * whole fleet in joiner mode (the last member self-demoting
         * leaves no admitter) — my frames at the responder trigger
         * ITS sync or rejoin instead */
        if (stale)
            return;
        /* progress fallback: nothing in the response re-certified
         * the responder's link, so the two views cannot converge by
         * sync alone — full rejoin (status quo ante) */
        /* rlo-model: edge msync->joiner */
        become_joiner(e);
        return;
    }
    if (adopted)
        e->epoch_syncs++;
    if (plen >= ad_off + 4)
        msync_advert(e, src, p, plen, ad_off);
}

/* Dispatch a Tag.MSYNC frame by kind byte. Does NOT consume m;
 * every payload read is dominated by a length guard. */
static void on_msync(rlo_engine *e, rlo_msg *m)
{
    int src = m->src;
    if (src < 0 || src >= e->ws || src == e->rank ||
        e->sub_excluded[src] || m->len < 1)
        return;
    int kind = m->payload[0];
    if (kind == RLO_MSYNC_REQ) {
        if (m->len < 9)
            return;
        if (e->failed[src]) {
            /* can't certify link state toward a rank this view holds
             * failed: show it the winning view so it petitions for
             * readmission instead */
            send_join_probe(e, src);
            return;
        }
        if (get_le32(m->payload + 5) < e->admitted_inc[src])
            return; /* stale REQ from an already-replaced life */
        msync_serve(e, src);
    } else if (kind == RLO_MSYNC_RSP) {
        msync_adopt(e, src, m->payload, m->len);
    } else if (kind == RLO_MSYNC_AD) {
        /* a joiner's dedup state is mid-reset and a failed peer's
         * link is quarantined: neither side can exchange WANTs */
        if (!e->awaiting_welcome && !e->failed[src])
            msync_advert(e, src, m->payload, m->len, 1);
    } else if (kind == RLO_MSYNC_WANT) {
        if (!e->awaiting_welcome && !e->failed[src])
            msync_want(e, src, m->payload, m->len);
    }
}

/* Designated admitter's launch: drain EVERY servable queued petition
 * into one IAR round. Batched admissions (docs/DESIGN.md S18) —
 * under churn the petitions arrive in bursts (every victim of a
 * partition heals at once), and k sequential rounds were the
 * measured admission_rounds amplifier. */
static void launch_admission_round(rlo_engine *e, uint64_t now,
                                   uint64_t iv)
{
    int64_t cap = RLO_MEMBER_MAGIC_LEN + 8 + 8 * (int64_t)e->ws;
    uint8_t *payload = (uint8_t *)malloc((size_t)cap);
    if (!payload)
        return;
    int k = 0, first = -1;
    int32_t max_jep = e->epoch;
    for (int r = 0; r < e->ws; r++) {
        if (!e->pending_join[r])
            continue;
        e->pending_join[r] = 0;
        e->n_pending--;
        if (!e->failed[r] || e->admitting[r])
            continue;
        if (first < 0)
            first = r;
        e->admitting[r] = 1;
        put_le32(payload + RLO_MEMBER_MAGIC_LEN + 8 + 8 * k, r);
        put_le32(payload + RLO_MEMBER_MAGIC_LEN + 12 + 8 * k,
                 e->pending_inc[r]);
        if (e->pending_ep[r] > max_jep)
            max_jep = e->pending_ep[r];
        k++;
    }
    if (k) {
        /* the agreed post-admission epoch: above EVERY side's
         * view, so each joiner's fresh frames clear every
         * member's floor and their old lives' frames never
         * do. The round rides the FIRST joiner's pid slot. */
        int32_t new_epoch = max_jep + 1;
        memcpy(payload, RLO_MEMBER_MAGIC, RLO_MEMBER_MAGIC_LEN);
        put_le32(payload + RLO_MEMBER_MAGIC_LEN, new_epoch);
        put_le32(payload + RLO_MEMBER_MAGIC_LEN + 4, k);
        e->admission_rounds++;
        rlo_submit_proposal(e, payload,
                            RLO_MEMBER_MAGIC_LEN + 8 + 8 * (int64_t)k,
                            member_pid(e, first));
        /* arm the membership watchdog: if the round wedges
         * (mixed-view vote-tree cycle), fail it and let the
         * joiners' next probes retry on the settled view */
        if (e->own.state == RLO_IN_PROGRESS) {
            uint64_t budget = 4 * e->fd_timeout;
            if (20 * iv > budget)
                budget = 20 * iv;
            e->own_deadline = now + budget;
        }
    }
    free(payload);
}

/* Joiner side: petition every potential member at join_interval.
 * Survivor side: launch queued admission rounds once the (single)
 * own-proposal slot frees up, and probe failed-but-maybe-alive peers
 * so a healed partition or silent restart is discovered without any
 * out-of-band signal. */
static void membership_tick(rlo_engine *e)
{
    uint64_t now = rlo_now_usec();
    uint64_t iv = join_iv(e);
    if (e->awaiting_welcome) {
        if (now - e->join_last >= iv) {
            e->join_last = now;
            for (int dst = 0; dst < e->ws; dst++)
                if (dst != e->rank && !e->sub_excluded[dst])
                    send_join_probe(e, dst);
        }
        return;
    }
    /* thundering-herd damper (mirror of ProgressEngine._join_tick,
     * docs/DESIGN.md §14): only the DESIGNATED admitter — the lowest
     * alive rank in my view — launches admission rounds; everyone
     * else keeps the petition queued in case designation shifts. */
    if (e->n_pending && e->own.state != RLO_IN_PROGRESS &&
        min_alive(e) == e->rank)
        launch_admission_round(e, now, iv);
    int probe = 0;
    for (int r = 0; r < e->ws; r++)
        if (e->failed[r] && !e->sub_excluded[r])
            probe = 1;
    if (probe && now - e->join_last >= iv) {
        e->join_last = now;
        for (int r = 0; r < e->ws; r++)
            if (e->failed[r] && !e->sub_excluded[r])
                send_join_probe(e, r);
    }
}

int rlo_engine_set_incarnation(rlo_engine *e, int incarnation)
{
    /* bounded so the shifted base fits the int32 wire fields AFTER
     * the rank-qualification multiply in rlo_submit_proposal
     * (gen = gen_counter * ws + rank; mirror of engine.py's
     * _incarnation_cap — the plain INT32_MAX >> 20 bound would let
     * the multiply overflow signed int, which is UB) */
    if (!e || incarnation < 0 || incarnation < e->incarnation ||
        (int64_t)incarnation >
            ((int64_t)INT32_MAX / e->ws) >> 20)
        return RLO_ERR_ARG;
    e->incarnation = incarnation;
    /* re-partition the broadcast-seq and round-generation spaces so
     * peers' dedup windows never swallow the new life's frames */
    int32_t base = (int32_t)incarnation << 20;
    if (e->bcast_seq < base)
        e->bcast_seq = base;
    if (e->gen_counter < base)
        e->gen_counter = base;
    if (incarnation > 0)
        /* rlo-model: edge restart->joiner */
        become_joiner(e);
    return RLO_OK;
}

int rlo_engine_rejoin(rlo_engine *e)
{
    if (!e)
        return RLO_ERR_ARG;
    int rc = rlo_engine_set_incarnation(e, e->incarnation + 1);
    if (rc != RLO_OK)
        return rc;
    e->join_last = 0;
    rlo_progress_all(e->w);
    return e->incarnation;
}

int64_t rlo_engine_epoch(const rlo_engine *e)
{
    return e->epoch;
}

int64_t rlo_engine_epoch_quarantined(const rlo_engine *e)
{
    return e->quarantined;
}

int64_t rlo_engine_rejoins(const rlo_engine *e)
{
    return e->rejoins_cnt;
}

int rlo_engine_awaiting_welcome(const rlo_engine *e)
{
    return e->awaiting_welcome;
}

/* ---------------- delivery ---------------- */

static int64_t copy_out(rlo_msg *m, int *tag, int *origin, int *pid,
                        int *vote, uint8_t *buf, int64_t cap)
{
    if (m->len > cap)
        return RLO_ERR_TOO_BIG;
    if (tag)
        *tag = m->tag;
    if (origin)
        *origin = m->origin;
    if (pid)
        *pid = m->pid;
    if (vote)
        *vote = m->vote;
    if (m->len > 0)
        memcpy(buf, m->payload, (size_t)m->len);
    return m->len;
}

/* Head deliverable message: still-forwarding messages are eligible
 * first (reference order, RLO_user_pickup_next :938-979). */
static rlo_msg *pickup_head(rlo_engine *e, int *from_wait)
{
    if (e->q_wait_pickup.head) {
        *from_wait = 1;
        return e->q_wait_pickup.head;
    }
    *from_wait = 0;
    return e->q_pickup.head;
}

/* Retire one deliverable message (shared by pickup_next and
 * peek/consume). */
static void pickup_retire(rlo_engine *e, rlo_msg *m, int from_wait)
{
    e->total_pickup++;
    if (m->arrived) {
        /* clamp against a backwards wall-clock step (see arq_on_ack) */
        uint64_t now = rlo_now_usec();
        if (now >= m->arrived)
            hist_obs(&e->h_pickup, (double)(now - m->arrived));
    }
    rlo_trace_emit(e->rank, RLO_EV_DELIVER, m->tag, m->origin,
                   trace_ident(m->tag, m->pid, m->vote), m->src);
    /* span-stamped fabric record? emit the wire-hop span (b = -1
     * marks a hop receipt, not a stage boundary). The trailer check
     * runs only when tracing is on — zero cost on the disabled path. */
    if (rlo_trace_enabled() && m->len >= RLO_SPAN_CTX_SIZE) {
        int32_t gw, sq;
        int st, fl;
        if (rlo_span_decode(m->payload + m->len - RLO_SPAN_CTX_SIZE,
                            RLO_SPAN_CTX_SIZE, &gw, &sq, &st, &fl,
                            0) >= 0)
            rlo_trace_emit(e->rank, RLO_EV_SPAN, st, -1, sq, gw);
    }
    if (m == e->peeked)
        e->peeked = 0;
    if (from_wait) {
        q_remove(&e->q_wait_pickup, m);
        m->pickup_done = 1;
        q_append(&e->q_wait, m); /* keep tracking its forwards */
    } else {
        q_remove(&e->q_pickup, m);
        msg_free(m);
    }
}

/* Which delivery queue currently holds `m` (a progress turn may have
 * moved it from wait_and_pickup to pickup when its forwards finished). */
static int in_wait_pickup(const rlo_engine *e, const rlo_msg *m)
{
    for (const rlo_msg *x = e->q_wait_pickup.head; x; x = x->next)
        if (x == m)
            return 1;
    return 0;
}

int64_t rlo_pickup_next(rlo_engine *e, int *tag, int *origin, int *pid,
                        int *vote, uint8_t *buf, int64_t cap)
{
    double t0 = e->profiler_on ? now_usec_f() : 0;
    int from_wait;
    rlo_msg *m = pickup_head(e, &from_wait);
    if (!m)
        return -1;
    int64_t n = copy_out(m, tag, origin, pid, vote, buf, cap);
    if (n < 0)
        return n;
    pickup_retire(e, m, from_wait);
    if (e->profiler_on)
        ph_obs(e, RLO_PH_PICKUP_DRAIN, t0);
    return n;
}

int64_t rlo_pickup_peek(rlo_engine *e, int *tag, int *origin, int *pid,
                        int *vote, const uint8_t **payload)
{
    int from_wait;
    rlo_msg *m = pickup_head(e, &from_wait);
    if (!m)
        return -1;
    e->peeked = m;
    if (tag)
        *tag = m->tag;
    if (origin)
        *origin = m->origin;
    if (pid)
        *pid = m->pid;
    if (vote)
        *vote = m->vote;
    if (payload)
        *payload = m->payload;
    return m->len;
}

int rlo_pickup_consume(rlo_engine *e)
{
    /* retire exactly the peeked message — a progress turn between peek
     * and consume may have changed the queue heads (or moved the peeked
     * message between delivery queues), and retiring whatever is head
     * now would silently swallow an undelivered message */
    rlo_msg *m = e->peeked;
    if (!m)
        return RLO_ERR_ARG;
    /* the peek/consume pair is one delivery: time the retire leg (the
     * peek already handed the payload out zero-copy) */
    double t0 = e->profiler_on ? now_usec_f() : 0;
    pickup_retire(e, m, in_wait_pickup(e, m));
    if (e->profiler_on)
        ph_obs(e, RLO_PH_PICKUP_DRAIN, t0);
    return RLO_OK;
}

/* ---------------- the gear (reference make_progress_gen :551-641) ------ */

/* One progress turn. max_frames < 0 = unbounded (the historical
 * progress_once); >= 0 caps how many frames the transport drain may
 * poll this turn — the remainder stays queued in FIFO order for the
 * next turn, so budgeted and unbudgeted driving deliver identical
 * sequences. Returns frames polled (the batched entry points slice
 * their budget through this; every polled frame counts, ACKs and
 * quarantined frames included). */
int64_t rlo_engine_progress_budget(rlo_engine *e, int64_t max_frames)
{
    int64_t polled = 0;
    /* (a) my own decision fan-out completion -> proposal COMPLETED */
    rlo_prop *p = &e->own;
    if (p->state == RLO_IN_PROGRESS && p->decision_pending) {
        int done = 1;
        for (int i = 0; i < p->n_decision; i++)
            if (!p->decision_handles[i]->delivered)
                done = 0;
        if (done) {
            p->state = RLO_COMPLETED;
            p->decision_pending = 0;
            e->own_deadline = 0;
            if (e->prop_born) {
                uint64_t now = rlo_now_usec();
                if (now >= e->prop_born)
                    hist_obs(&e->h_prop,
                             (double)(now - e->prop_born));
                e->prop_born = 0;
            }
            if (e->p_prop_born != 0) {
                /* submit -> decision fan-out complete (S10 phase) */
                ph_obs(e, RLO_PH_PROP_DECISION, e->p_prop_born);
                e->p_prop_born = 0;
            }
        }
    }
    if (p->state == RLO_IN_PROGRESS && !p->decision_pending &&
        e->own_deadline && rlo_now_usec() > e->own_deadline)
        abort_own_round(e); /* membership watchdog expired */

    /* (b) drain the transport, dispatch on tag (:569-624) */
    for (;;) {
        if (max_frames >= 0 && polled >= max_frames)
            break; /* frame budget: the rest waits, FIFO intact */
        rlo_wire_node *n = rlo_world_poll(e->w, e->rank, e->comm);
        if (!n)
            break;
        polled++;
        e->frames_dispatched++;
        /* steal the node's frame ref into the message — no copy */
        int err = RLO_ERR_PROTO;
        rlo_msg *m;
        if (e->profiler_on) {
            double t0 = now_usec_f();
            m = msg_from_frame(e->w, n->tag, n->src, n->frame, &err);
            ph_obs(e, RLO_PH_FRAME_DECODE, t0);
        } else {
            m = msg_from_frame(e->w, n->tag, n->src, n->frame, &err);
        }
        rlo_handle_unref(n->handle);
        rlo_pool_free(n);
        if (!m) {
            set_err(e, err);
            continue;
        }
        if (e->metrics_on) {
            if (m->src >= 0 && m->src < e->ws) {
                e->links[m->src].rx_frames++;
                e->links[m->src].rx_bytes += m->frame->len;
            }
            m->arrived = rlo_now_usec();
        }
        /* membership frames cross the boundaries the quarantine below
         * enforces — dispatch them first (docs/DESIGN.md S8) */
        if (m->tag == RLO_TAG_JOIN) {
            on_join(e, m);
            msg_free(m);
            continue;
        }
        if (m->tag == RLO_TAG_JOIN_WELCOME) {
            on_welcome(e, m);
            msg_free(m);
            continue;
        }
        if (m->tag == RLO_TAG_MSYNC) {
            /* epoch-exempt like JOIN: a sync response must reach a
             * mid-rejoin laggard (sync-supersedes-welcome) and a REQ
             * must cross the failed-sender boundary; on_msync guards
             * per kind (docs/DESIGN.md S18) */
            on_msync(e, m);
            msg_free(m);
            continue;
        }
        /* stale-epoch / failed-sender quarantine, BEFORE ACK handling
         * and the ARQ dedup: a dead incarnation's traffic (and
         * everything while this rank is itself mid-rejoin) must not
         * touch link state, liveness, or app state */
        if (e->awaiting_welcome) {
            e->quarantined++;
            e->quar_mid_rejoin++;
            msg_free(m);
            continue;
        }
        if (m->src >= 0 && m->src < e->ws) {
            if (e->failed[m->src]) {
                e->quarantined++;
                e->quar_failed_sender++;
                msg_free(m);
                continue;
            }
            if (e->epoch_floor[m->src] &&
                rlo_frame_epoch(m->frame->data) <
                    e->epoch_floor[m->src]) {
                e->quarantined++;
                e->quar_below_floor++;
                /* stale-sender nack: an ALIVE sender stamping below
                 * our floor missed its one-shot JOIN_WELCOME — show
                 * it the winning view so it re-petitions (no heal
                 * probe fires at it: neither side holds the other
                 * failed). Rate-limited at the probe cadence. */
                uint64_t snow = rlo_now_usec();
                if (snow - e->stale_probe_last[m->src] >= join_iv(e)) {
                    e->stale_probe_last[m->src] = snow;
                    send_join_probe(e, m->src);
                }
                msg_free(m);
                continue;
            }
            /* heal-cost signal (docs/DESIGN.md S17): how far my view
             * epoch has outrun the link-epoch stamp of frames I
             * still ACCEPT (mirror of engine.py's epoch_lag_max) */
            int64_t lag =
                (int64_t)e->epoch - rlo_frame_epoch(m->frame->data);
            if (lag > e->epoch_lag_max)
                e->epoch_lag_max = lag;
        }
        /* ANY accepted frame proves the sender alive — prevents
         * heartbeat starvation when membership views transiently
         * diverge */
        if (e->fd_timeout && m->src >= 0 && m->src < e->ws)
            e->hb_seen[m->src] = rlo_now_usec();
        if (m->tag == RLO_TAG_ACK) {
            if (m->src >= 0 && m->src < e->ws) {
                if (m->vote == -2 && m->pid >= 0)
                    arq_rx_skip(e, m->src, m->pid);
                else
                    arq_on_ack(e, m->src, m->vote);
            }
            msg_free(m);
            continue;
        }
        if (e->arq_rto && !arq_exempt(m->tag) && m->seq >= 0 &&
            m->src >= 0 && m->src < e->ws) {
            /* link-level exactly-once BEFORE tag dispatch: a
             * retransmitted frame must be idempotent everywhere, and
             * its receipt owes the sender a cumulative ACK either way */
            e->ack_due[m->src] = 1;
            if (window_record(&e->rx_contig[m->src],
                              &e->rx_mask[(size_t)m->src * RLO_SEEN_WORDS],
                              m->seq)) {
                e->arq_dup++;
                if (e->metrics_on)
                    e->links[m->src].dup_drops++;
                msg_free(m);
                continue;
            }
        }
        /* S10 tag_dispatch phase: dispatch + handler for one protocol
         * frame (quarantine/ACK/dedup exits above are not counted —
         * they never reach a handler) */
        double t_disp = e->profiler_on ? now_usec_f() : 0;
        switch (m->tag) {
        case RLO_TAG_BCAST: {
            e->recved_bcast++;
            if (bcast_is_dup(e, m)) {
                /* exactly-once: drop, don't re-forward or deliver.
                 * `continue` (not break): a dup drop is not a
                 * dispatch, so no tag_dispatch phase sample — keeps
                 * the profiler counts in lockstep with the Python
                 * engine's `continue` on this path */
                msg_free(m);
                continue;
            }
            recent_log_push(e, m->frame, RLO_TAG_BCAST);
            int rc = bc_forward(e, m);
            if (rc < 0) {
                /* bc_forward only fails before queueing — reclaim */
                set_err(e, rc);
                msg_free(m);
            }
            break;
        }
        case RLO_TAG_IAR_PROPOSAL:
            on_proposal(e, m);
            break;
        case RLO_TAG_IAR_VOTE:
            on_vote(e, m);
            break;
        case RLO_TAG_IAR_DECISION:
            e->recved_bcast++;
            on_decision(e, m);
            break;
        case RLO_TAG_HEARTBEAT:
            /* liveness already refreshed above for any frame; a
             * piggybacked cumulative ACK rides the payload */
            if (e->arq_rto && m->len >= 4 && m->src >= 0 &&
                m->src < e->ws)
                arq_on_ack(e, m->src, (int32_t)vote_gen(m));
            msg_free(m);
            break;
        case RLO_TAG_FAILURE:
            on_failure(e, m);
            break;
        default:
            /* aux tags go straight to pickup */
            m->fwd_done = 1;
            q_append(&e->q_pickup, m);
            break;
        }
        if (e->profiler_on)
            ph_obs(e, RLO_PH_TAG_DISPATCH, t_disp);
    }

    /* (b2) liveness: heartbeat my ring successor, watch my predecessor
     * — suspended while mid-rejoin (a joiner quarantines everything,
     * so its detector would only produce false declarations against
     * peers it cannot hear) */
    if (!e->awaiting_welcome)
        failure_tick(e);

    /* (b2b) membership: JOIN petitions (joiner side), heal probes at
     * failed-but-maybe-alive peers, and queued admission rounds
     * waiting for the own-proposal slot (docs/DESIGN.md S8) */
    if (e->awaiting_welcome || e->n_pending ||
        e->n_failed > e->n_excluded)
        membership_tick(e);

    /* (b3) reliable delivery: retransmit overdue unacked frames,
     * escalate give-ups to the failure detector, then flush the
     * cumulative ACKs this turn's receipts owe */
    if (e->arq_rto) {
        if (e->profiler_on) {
            double t0 = now_usec_f();
            arq_tick(e);
            ph_obs(e, RLO_PH_ARQ_SCAN, t0);
        } else {
            arq_tick(e);
        }
        arq_escalate_gaveup(e);
        arq_flush_acks(e);
    }

    /* (c) wait_and_pickup sweep (:995-1013): forwards done -> deliverable */
    for (rlo_msg *m = e->q_wait_pickup.head; m;) {
        rlo_msg *nm = m->next;
        if (msg_sends_done(m)) {
            m->fwd_done = 1;
            q_remove(&e->q_wait_pickup, m);
            q_append(&e->q_pickup, m);
        }
        m = nm;
    }

    /* (d) wait-only sweep (:1015-1034): completed sends are released */
    for (rlo_msg *m = e->q_wait.head; m;) {
        rlo_msg *nm = m->next;
        if (m->p_born != 0 && !m->first_fwd && msg_any_send_done(m)) {
            /* S10 bcast_first_fwd: init -> the FIRST fan-out send
             * completed; observed once per locally-initiated bcast */
            m->first_fwd = 1;
            ph_obs(e, RLO_PH_BCAST_FIRST_FWD, m->p_born);
        }
        if (msg_sends_done(m)) {
            m->fwd_done = 1;
            if (m->born) {
                /* locally-initiated bcast: init -> fan-out complete */
                uint64_t now = rlo_now_usec();
                if (now >= m->born)
                    hist_obs(&e->h_bcast, (double)(now - m->born));
            }
            if (m->p_born != 0)
                ph_obs(e, RLO_PH_BCAST_ALL_DELIVERED, m->p_born);
            q_remove(&e->q_wait, m);
            msg_free(m);
        }
        m = nm;
    }
    return polled;
}

void rlo_engine_progress_once(rlo_engine *e)
{
    rlo_engine_progress_budget(e, -1);
}

/* Batched single-engine progress (docs/DESIGN.md S13; contract in
 * rlo_core.h): loop turns in C until the budget fills, the deadline
 * expires, or — with no deadline — the first fruitless turn. The
 * world's stepping guard is held through each turn so a judge/action
 * callback initiating a broadcast re-enters as a no-op, exactly as it
 * does inside rlo_progress_all. */
int64_t rlo_engine_progress_n(rlo_engine *e, int64_t max_frames,
                              uint64_t deadline_usec)
{
    if (!e)
        return RLO_ERR_ARG;
    rlo_world *w = e->w;
    if (w->stepping)
        return 0; /* re-entered from a handler: no-op */
    uint64_t end = deadline_usec ? rlo_now_usec() + deadline_usec : 0;
    int64_t total = 0;
    for (;;) {
        w->stepping = 1;
        int64_t got = rlo_engine_progress_budget(
            e, max_frames > 0 ? max_frames - total : -1);
        w->stepping = 0;
        total += got;
        if (max_frames > 0 && total >= max_frames)
            break;
        if (got == 0 && !end)
            break; /* fruitless turn, no poll-wait requested */
        if (end && rlo_now_usec() >= end)
            break;
    }
    return total;
}

/* ---------------- snapshot/restore (see rlo_core.h) ---------------- */

int rlo_engine_state_get(const rlo_engine *e, rlo_engine_state *out)
{
    if (!e || !out)
        return RLO_ERR_ARG;
    if (!rlo_engine_idle(e) || e->own.state == RLO_IN_PROGRESS ||
        e->q_iar_pending.len || e->q_pickup.len || e->q_wait_pickup.len)
        return RLO_ERR_BUSY;
    out->rank = e->rank;
    out->world_size = e->ws;
    out->sent_bcast = e->sent_bcast;
    out->recved_bcast = e->recved_bcast;
    out->total_pickup = e->total_pickup;
    out->prop_pid = e->own.pid;
    out->prop_state = e->own.state;
    out->prop_vote = e->own.vote;
    out->prop_votes_needed = e->own.votes_needed;
    out->prop_votes_recved = e->own.votes_recved;
    out->gen_counter = e->gen_counter;
    out->bcast_seq = e->bcast_seq;
    return RLO_OK;
}

int rlo_engine_state_set(rlo_engine *e, const rlo_engine_state *in)
{
    if (!e || !in)
        return RLO_ERR_ARG;
    if (in->rank != e->rank || in->world_size != e->ws)
        return RLO_ERR_ARG;
    /* state_get can only ever emit settled states — an IN_PROGRESS (or
     * out-of-range) snapshot is corrupt and would wedge the engine */
    if (in->prop_state != RLO_COMPLETED && in->prop_state != RLO_FAILED &&
        in->prop_state != RLO_INVALID)
        return RLO_ERR_ARG;
    e->sent_bcast = in->sent_bcast;
    e->recved_bcast = in->recved_bcast;
    e->total_pickup = in->total_pickup;
    e->own.pid = in->prop_pid;
    e->own.state = in->prop_state;
    e->own.vote = in->prop_vote;
    e->own.votes_needed = in->prop_votes_needed;
    e->own.votes_recved = in->prop_votes_recved;
    /* never rewind below the incarnation base: a restarted process
     * that set a fresh incarnation BEFORE restoring a pre-crash
     * snapshot would otherwise reissue its dead life's (pid, gen)
     * and bcast seqs, which peers' dedup windows silently swallow */
    int32_t inc_base = (int32_t)e->incarnation << 20;
    e->gen_counter =
        in->gen_counter < inc_base ? inc_base : in->gen_counter;
    e->bcast_seq = in->bcast_seq < inc_base ? inc_base : in->bcast_seq;
    return RLO_OK;
}

/* ---------------- introspection ---------------- */

int rlo_engine_idle(const rlo_engine *e)
{
    /* with ARQ enabled, unacked reliable frames are outstanding work:
     * an idle engine's sends are acknowledged, not merely handed off */
    return e->q_wait.len == 0 && e->q_wait_pickup.len == 0 &&
           !e->own.decision_pending && e->rtx_head == 0;
}

int rlo_engine_err(const rlo_engine *e)
{
    return e->err;
}

int rlo_engine_set_fanout(rlo_engine *e, int mode)
{
    if (!e || (mode != RLO_FANOUT_SKIP_RING && mode != RLO_FANOUT_FLAT))
        return RLO_ERR_ARG;
    /* schedule switches only between settled rounds: frames already in
     * flight were routed (and their votes counted) under the old shape */
    if (!rlo_engine_idle(e) || e->own.state == RLO_IN_PROGRESS ||
        e->q_iar_pending.len)
        return RLO_ERR_BUSY;
    e->fanout = mode;
    return RLO_OK;
}

int64_t rlo_engine_total_pickup(const rlo_engine *e)
{
    return e->total_pickup;
}

int64_t rlo_engine_sent_bcast(const rlo_engine *e)
{
    return e->sent_bcast;
}

int64_t rlo_engine_recved_bcast(const rlo_engine *e)
{
    return e->recved_bcast;
}
