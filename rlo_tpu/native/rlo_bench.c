/* Wholly-native micro-benchmark entry points, driven by one ctypes call.
 *
 * The BASELINE.json config-1 comparison ("allreduce on the engine
 * substrate") must measure the C engines themselves, not the Python
 * driver's ctypes boundary copies — so the full workload (bcast-gather
 * allreduce over the rootless broadcast overlay, the NativeBackend
 * data-collective algorithm) runs inside the library: every rank
 * broadcasts its fp32 buffer, the world drains, every rank sums what it
 * picks up through the zero-copy peek/consume path. The reference's own
 * benchmark harnesses are likewise all-native timing loops
 * (/root/reference/testcases.c:71-98, rootless_ops.c:1675-1709).
 */
#include "rlo_internal.h"

#include <stdio.h>

/* Median usec per allreduce over `reps` runs of a bcast-gather fp32
 * allreduce of `count` floats per rank, world_size in-process loopback
 * ranks. Returns <0 (rlo_err) on failure or a wrong reduction result. */
double rlo_bench_allreduce(int world_size, int64_t count, int reps)
{
    if (world_size < 2 || count <= 0 || reps <= 0 || reps > 1000)
        return RLO_ERR_ARG;
    rlo_world *w = rlo_world_new(world_size, 0, 0);
    if (!w)
        return RLO_ERR_NOMEM;
    double rc = RLO_ERR_NOMEM;
    int64_t nbytes = count * (int64_t)sizeof(float);
    rlo_engine **engines = 0;
    float **bufs = 0;   /* per-rank payloads */
    float *acc = 0;
    double *times = 0;

    engines = (rlo_engine **)calloc((size_t)world_size, sizeof(void *));
    bufs = (float **)calloc((size_t)world_size, sizeof(void *));
    acc = (float *)malloc((size_t)nbytes);
    times = (double *)calloc((size_t)reps, sizeof(double));
    if (!engines || !bufs || !acc || !times)
        goto out;
    for (int r = 0; r < world_size; r++) {
        engines[r] = rlo_engine_new(w, r, 0, 0, 0, 0, 0, nbytes + 64);
        bufs[r] = (float *)malloc((size_t)nbytes);
        if (!engines[r] || !bufs[r])
            goto out;
        for (int64_t i = 0; i < count; i++)
            bufs[r][i] = (float)((r + 1) * ((i % 13) + 1));
    }

    for (int rep = 0; rep < reps; rep++) {
        uint64_t t0 = rlo_now_usec();
        for (int r = 0; r < world_size; r++) {
            int src = rlo_bcast(engines[r], (const uint8_t *)bufs[r],
                                nbytes);
            if (src != RLO_OK) {
                rc = src;
                goto out;
            }
        }
        int spun = rlo_drain(w, 1000000);
        if (spun < 0) {
            rc = spun;
            goto out;
        }
        for (int r = 0; r < world_size; r++) {
            memcpy(acc, bufs[r], (size_t)nbytes);
            for (int got = 0; got < world_size - 1; got++) {
                const uint8_t *payload = 0;
                int64_t n = rlo_pickup_peek(engines[r], 0, 0, 0, 0,
                                            &payload);
                if (n != nbytes) {
                    rc = RLO_ERR_PROTO;
                    goto out;
                }
                const float *f = (const float *)payload;
                for (int64_t i = 0; i < count; i++)
                    acc[i] += f[i];
                rlo_pickup_consume(engines[r]);
            }
        }
        times[rep] = (double)(rlo_now_usec() - t0);
        /* oracle: sum over ranks of (r+1)*k = k * ws*(ws+1)/2 */
        double want =
            (double)world_size * (world_size + 1) / 2.0 * ((0 % 13) + 1);
        if (acc[0] != (float)want) {
            rc = RLO_ERR_PROTO;
            goto out;
        }
    }
    /* median */
    for (int i = 0; i < reps; i++)
        for (int j = i + 1; j < reps; j++)
            if (times[j] < times[i]) {
                double t = times[i];
                times[i] = times[j];
                times[j] = t;
            }
    rc = times[reps / 2];

out:
    if (engines)
        for (int r = 0; r < world_size; r++)
            rlo_engine_free(engines[r]);
    if (bufs)
        for (int r = 0; r < world_size; r++)
            free(bufs[r]);
    free(engines);
    free(bufs);
    free(acc);
    free(times);
    rlo_world_free(w);
    return rc;
}
