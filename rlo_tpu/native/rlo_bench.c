/* Wholly-native micro-benchmark entry points, driven by one ctypes call.
 *
 * The BASELINE.json config-1 comparison ("allreduce on the engine
 * substrate") must measure the C engines themselves, not the Python
 * driver's ctypes boundary copies — so the full workload (bcast-gather
 * allreduce over the rootless broadcast overlay, the NativeBackend
 * data-collective algorithm) runs inside the library: every rank
 * broadcasts its fp32 buffer, the world drains, every rank sums what it
 * picks up through the zero-copy peek/consume path. The reference's own
 * benchmark harnesses are likewise all-native timing loops
 * (/root/reference/testcases.c:71-98, rootless_ops.c:1675-1709).
 */
#include "rlo_internal.h"

#include <stdio.h>

/* Median usec per allreduce over `reps` runs of a bcast-gather fp32
 * allreduce of `count` floats per rank, world_size in-process loopback
 * ranks. Returns <0 (rlo_err) on failure or a wrong reduction result. */
double rlo_bench_allreduce(int world_size, int64_t count, int reps)
{
    if (world_size < 2 || count <= 0 || reps <= 0 || reps > 1000)
        return RLO_ERR_ARG;
    rlo_world *w = rlo_world_new(world_size, 0, 0);
    if (!w)
        return RLO_ERR_NOMEM;
    double rc = RLO_ERR_NOMEM;
    int64_t nbytes = count * (int64_t)sizeof(float);
    rlo_engine **engines = 0;
    float **bufs = 0;   /* per-rank payloads */
    float *acc = 0;
    double *times = 0;

    engines = (rlo_engine **)calloc((size_t)world_size, sizeof(void *));
    bufs = (float **)calloc((size_t)world_size, sizeof(void *));
    acc = (float *)malloc((size_t)nbytes);
    times = (double *)calloc((size_t)reps, sizeof(double));
    if (!engines || !bufs || !acc || !times)
        goto out;
    for (int r = 0; r < world_size; r++) {
        engines[r] = rlo_engine_new(w, r, 0, 0, 0, 0, 0, nbytes + 64);
        bufs[r] = (float *)malloc((size_t)nbytes);
        if (!engines[r] || !bufs[r])
            goto out;
        for (int64_t i = 0; i < count; i++)
            bufs[r][i] = (float)((r + 1) * ((i % 13) + 1));
    }

    for (int rep = 0; rep < reps; rep++) {
        uint64_t t0 = rlo_now_usec();
        for (int r = 0; r < world_size; r++) {
            int src = rlo_bcast(engines[r], (const uint8_t *)bufs[r],
                                nbytes);
            if (src != RLO_OK) {
                rc = src;
                goto out;
            }
        }
        int spun = rlo_drain(w, 1000000);
        if (spun < 0) {
            rc = spun;
            goto out;
        }
        for (int r = 0; r < world_size; r++) {
            memcpy(acc, bufs[r], (size_t)nbytes);
            for (int got = 0; got < world_size - 1; got++) {
                const uint8_t *payload = 0;
                int64_t n = rlo_pickup_peek(engines[r], 0, 0, 0, 0,
                                            &payload);
                if (n != nbytes) {
                    rc = RLO_ERR_PROTO;
                    goto out;
                }
                const float *f = (const float *)payload;
                for (int64_t i = 0; i < count; i++)
                    acc[i] += f[i];
                rlo_pickup_consume(engines[r]);
            }
        }
        times[rep] = (double)(rlo_now_usec() - t0);
        /* oracle: sum over ranks of (r+1)*k = k * ws*(ws+1)/2 */
        double want =
            (double)world_size * (world_size + 1) / 2.0 * ((0 % 13) + 1);
        if (acc[0] != (float)want) {
            rc = RLO_ERR_PROTO;
            goto out;
        }
    }
    /* median */
    for (int i = 0; i < reps; i++)
        for (int j = i + 1; j < reps; j++)
            if (times[j] < times[i]) {
                double t = times[i];
                times[i] = times[j];
                times[j] = t;
            }
    rc = times[reps / 2];

out:
    if (engines)
        for (int r = 0; r < world_size; r++)
            rlo_engine_free(engines[r]);
    if (bufs)
        for (int r = 0; r < world_size; r++)
            free(bufs[r]);
    free(engines);
    free(bufs);
    free(acc);
    free(times);
    rlo_world_free(w);
    return rc;
}

/* Median usec per SINGLE-ROOT broadcast (rank 0 -> all) of `nbytes`
 * over an in-process loopback world — the engine+wire machinery cost
 * of one overlay bcast with no transport contention and no scheduler:
 * every frame is a loopback queue hop (one memcpy) plus the engine's
 * serialize/demux/dedup/forward/pickup work. case_nbcast's floor
 * analysis divides this by (ws-1) frames to quantify the per-frame
 * engine CPU that the native MPI_Bcast path never pays (round-5
 * VERDICT item 7). Returns <0 (rlo_err) on failure. */
double rlo_bench_bcast_usec(int world_size, int64_t nbytes, int reps)
{
    if (world_size < 2 || nbytes <= 0 || reps <= 0 || reps > 10000)
        return RLO_ERR_ARG;
    rlo_world *w = rlo_world_new(world_size, 0, 0);
    if (!w)
        return RLO_ERR_NOMEM;
    double rc = RLO_ERR_NOMEM;
    rlo_engine **engines =
        (rlo_engine **)calloc((size_t)world_size, sizeof(void *));
    uint8_t *buf = (uint8_t *)malloc((size_t)nbytes);
    double *times = (double *)calloc((size_t)reps, sizeof(double));
    if (!engines || !buf || !times)
        goto out;
    memset(buf, 0x5a, (size_t)nbytes);
    for (int r = 0; r < world_size; r++) {
        engines[r] = rlo_engine_new(w, r, 0, 0, 0, 0, 0, nbytes + 64);
        if (!engines[r])
            goto out;
    }
    for (int rep = -2; rep < reps; rep++) { /* 2 warmup reps */
        uint64_t t0 = rlo_now_usec();
        int src = rlo_bcast(engines[0], buf, nbytes);
        if (src != RLO_OK) {
            rc = src;
            goto out;
        }
        int spun = rlo_drain(w, 1000000);
        if (spun < 0) {
            rc = spun;
            goto out;
        }
        for (int r = 1; r < world_size; r++) {
            const uint8_t *payload = 0;
            int64_t n = rlo_pickup_peek(engines[r], 0, 0, 0, 0,
                                        &payload);
            if (n != nbytes || payload[0] != 0x5a) {
                rc = RLO_ERR_PROTO;
                goto out;
            }
            rlo_pickup_consume(engines[r]);
        }
        if (rep >= 0)
            times[rep] = (double)(rlo_now_usec() - t0);
    }
    for (int i = 0; i < reps; i++)
        for (int j = i + 1; j < reps; j++)
            if (times[j] < times[i]) {
                double t = times[i];
                times[i] = times[j];
                times[j] = t;
            }
    rc = times[reps / 2];

out:
    if (engines)
        for (int r = 0; r < world_size; r++)
            rlo_engine_free(engines[r]);
    free(engines);
    free(buf);
    free(times);
    rlo_world_free(w);
    return rc;
}
