/* Wire format: one variable-size frame per message.
 *
 * Layout (little-endian, matching rlo_tpu/wire.py `<iiiiiQ>`):
 *   [origin:i32][pid:i32][vote:i32][seq:i32][epoch:i32][len:u64][payload]
 * The reference's pbuf (rootless_ops.c:1369-1410) carries the same logical
 * fields but always ships a fixed 32 KB buffer (:1588); frames here are
 * exactly header + payload. `seq` is the reliable-delivery layer's
 * per-(sender, receiver) link sequence number (-1 outside the ARQ path)
 * and `epoch` is the membership layer's LINK epoch for the edge (the
 * admission epoch of its last link-state reset, 0 on the original link;
 * receivers quarantine frames below their per-sender floor —
 * docs/DESIGN.md S8). Both are link state, not application fields.
 */
#include "rlo_core.h"

#include <string.h>

static void put_i32(uint8_t *p, int32_t v)
{
    p[0] = (uint8_t)(v & 0xff);
    p[1] = (uint8_t)((v >> 8) & 0xff);
    p[2] = (uint8_t)((v >> 16) & 0xff);
    p[3] = (uint8_t)((v >> 24) & 0xff);
}

static int32_t get_i32(const uint8_t *p)
{
    return (int32_t)((uint32_t)p[0] | ((uint32_t)p[1] << 8) |
                     ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24));
}

static void put_u64(uint8_t *p, uint64_t v)
{
    for (int i = 0; i < 8; i++)
        p[i] = (uint8_t)((v >> (8 * i)) & 0xff);
}

static uint64_t get_u64(const uint8_t *p)
{
    uint64_t v = 0;
    for (int i = 0; i < 8; i++)
        v |= (uint64_t)p[i] << (8 * i);
    return v;
}

int64_t rlo_frame_encode(uint8_t *dst, int64_t cap, int32_t origin,
                         int32_t pid, int32_t vote, int32_t seq,
                         const uint8_t *payload, int64_t len)
{
    if (len < 0 || cap < RLO_HEADER_SIZE + len)
        return RLO_ERR_ARG;
    put_i32(dst, origin);
    put_i32(dst + 4, pid);
    put_i32(dst + 8, vote);
    put_i32(dst + RLO_SEQ_OFFSET, seq);
    put_i32(dst + RLO_EPOCH_OFFSET, 0); /* stamped by the send gate */
    put_u64(dst + 20, (uint64_t)len);
    if (len > 0)
        memcpy(dst + RLO_HEADER_SIZE, payload, (size_t)len);
    return RLO_HEADER_SIZE + len;
}

int64_t rlo_frame_decode(const uint8_t *raw, int64_t rawlen, int32_t *origin,
                         int32_t *pid, int32_t *vote, int32_t *seq,
                         const uint8_t **payload)
{
    if (rawlen < RLO_HEADER_SIZE)
        return RLO_ERR_ARG;
    uint64_t n = get_u64(raw + 20);
    if ((int64_t)n > rawlen - RLO_HEADER_SIZE)
        return RLO_ERR_ARG; /* truncated frame */
    if (origin)
        *origin = get_i32(raw);
    if (pid)
        *pid = get_i32(raw + 4);
    if (vote)
        *vote = get_i32(raw + 8);
    if (seq)
        *seq = get_i32(raw + RLO_SEQ_OFFSET);
    if (payload)
        *payload = raw + RLO_HEADER_SIZE;
    return (int64_t)n;
}

int32_t rlo_frame_epoch(const uint8_t *raw)
{
    return get_i32(raw + RLO_EPOCH_OFFSET);
}

void rlo_frame_set_epoch(uint8_t *raw, int32_t epoch)
{
    put_i32(raw + RLO_EPOCH_OFFSET, epoch);
}
