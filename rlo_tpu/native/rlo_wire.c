/* Wire format: one variable-size frame per message.
 *
 * Layout (little-endian, matching rlo_tpu/wire.py `<iiiiiQ>`):
 *   [origin:i32][pid:i32][vote:i32][seq:i32][epoch:i32][len:u64][payload]
 * The reference's pbuf (rootless_ops.c:1369-1410) carries the same logical
 * fields but always ships a fixed 32 KB buffer (:1588); frames here are
 * exactly header + payload. `seq` is the reliable-delivery layer's
 * per-(sender, receiver) link sequence number (-1 outside the ARQ path)
 * and `epoch` is the membership layer's LINK epoch for the edge (the
 * admission epoch of its last link-state reset, 0 on the original link;
 * receivers quarantine frames below their per-sender floor —
 * docs/DESIGN.md S8). Both are link state, not application fields.
 */
#include "rlo_core.h"

#include <string.h>

static void put_i32(uint8_t *p, int32_t v)
{
    p[0] = (uint8_t)(v & 0xff);
    p[1] = (uint8_t)((v >> 8) & 0xff);
    p[2] = (uint8_t)((v >> 16) & 0xff);
    p[3] = (uint8_t)((v >> 24) & 0xff);
}

static int32_t get_i32(const uint8_t *p)
{
    return (int32_t)((uint32_t)p[0] | ((uint32_t)p[1] << 8) |
                     ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24));
}

static void put_u64(uint8_t *p, uint64_t v)
{
    for (int i = 0; i < 8; i++)
        p[i] = (uint8_t)((v >> (8 * i)) & 0xff);
}

static uint64_t get_u64(const uint8_t *p)
{
    uint64_t v = 0;
    for (int i = 0; i < 8; i++)
        v |= (uint64_t)p[i] << (8 * i);
    return v;
}

int64_t rlo_frame_encode(uint8_t *dst, int64_t cap, int32_t origin,
                         int32_t pid, int32_t vote, int32_t seq,
                         const uint8_t *payload, int64_t len)
{
    if (len < 0 || cap < RLO_HEADER_SIZE + len)
        return RLO_ERR_ARG;
    put_i32(dst, origin);
    put_i32(dst + 4, pid);
    put_i32(dst + 8, vote);
    put_i32(dst + RLO_SEQ_OFFSET, seq);
    put_i32(dst + RLO_EPOCH_OFFSET, 0); /* stamped by the send gate */
    put_u64(dst + 20, (uint64_t)len);
    if (len > 0)
        memcpy(dst + RLO_HEADER_SIZE, payload, (size_t)len);
    return RLO_HEADER_SIZE + len;
}

int64_t rlo_frame_decode(const uint8_t *raw, int64_t rawlen, int32_t *origin,
                         int32_t *pid, int32_t *vote, int32_t *seq,
                         const uint8_t **payload)
{
    if (rawlen < RLO_HEADER_SIZE)
        return RLO_ERR_ARG;
    uint64_t n = get_u64(raw + 20);
    if ((int64_t)n > rawlen - RLO_HEADER_SIZE)
        return RLO_ERR_ARG; /* truncated frame */
    if (origin)
        *origin = get_i32(raw);
    if (pid)
        *pid = get_i32(raw + 4);
    if (vote)
        *vote = get_i32(raw + 8);
    if (seq)
        *seq = get_i32(raw + RLO_SEQ_OFFSET);
    if (payload)
        *payload = raw + RLO_HEADER_SIZE;
    return (int64_t)n;
}

int32_t rlo_frame_epoch(const uint8_t *raw)
{
    return get_i32(raw + RLO_EPOCH_OFFSET);
}

void rlo_frame_set_epoch(uint8_t *raw, int32_t epoch)
{
    put_i32(raw + RLO_EPOCH_OFFSET, epoch);
}

/* ------------------------------------------------------------------ */
/* Telemetry digest codec (docs/DESIGN.md S17) — byte-identical to    */
/* wire.py encode_telem/decode_telem; parity asserted by              */
/* tests/test_observe.py. Layout:                                     */
/*   [magic:5][flags:u8][rank:i32][epoch:i32][seq:u32][mask:u64]      */
/*   [zigzag LEB128 varint per set mask bit, ascending]               */
/* ------------------------------------------------------------------ */

/* schema key names, mask-bit order: the rlo_stats counter fields
 * (ENGINE_COUNTER_KEYS) followed by the extras — rlo-lint R2 pins
 * this table against wire.py's TELEM_KEYS literal */
static const char *const k_telem_keys[RLO_TELEM_NKEYS] = {
    "sent_bcast", "recved_bcast", "total_pickup", "ops_failed",
    "arq_retransmits", "arq_dup_drops", "arq_gave_up", "arq_unacked",
    "epoch", "epoch_quarantined", "rejoins",
    "view_changes", "reflood_frames", "epoch_lag_max",
    "quar_mid_rejoin", "quar_failed_sender", "quar_below_floor",
    "admission_rounds",
    "epoch_syncs", "reflood_skipped", "batched_admits",
    "tx_frames", "rx_frames", "rtt_ewma_max_usec",
    "q_wait", "pickup_backlog", "pages_in_use", "pages_free",
    "serve_inflight", "ttft_p50_usec", "ttft_p99_usec",
    "e2e_p50_usec", "e2e_p99_usec",
    "coll_steps", "coll_bytes",
    "remedies_proposed", "remedies_executed",
    "quarantined", "backpressure_level",
};

const char *rlo_telem_key_name(int i)
{
    if (i < 0 || i >= RLO_TELEM_NKEYS)
        return 0;
    return k_telem_keys[i];
}

static void put_u32(uint8_t *p, uint32_t v)
{
    p[0] = (uint8_t)(v & 0xff);
    p[1] = (uint8_t)((v >> 8) & 0xff);
    p[2] = (uint8_t)((v >> 16) & 0xff);
    p[3] = (uint8_t)((v >> 24) & 0xff);
}

static uint32_t get_u32(const uint8_t *p)
{
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
           ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
}

int64_t rlo_telem_encode(uint8_t *dst, int64_t cap, int32_t rank,
                         int32_t epoch, uint32_t seq, int full,
                         const int64_t *vals, const int64_t *prev)
{
    if (!dst || !vals || cap < RLO_TELEM_HEADER_SIZE)
        return RLO_ERR_ARG;
    if (!prev)
        full = 1;
    memcpy(dst, RLO_TELEM_MAGIC, 5);
    dst[5] = full ? 1 : 0;
    put_i32(dst + 6, rank);
    put_i32(dst + 10, epoch);
    put_u32(dst + 14, seq);
    uint64_t mask = 0;
    int64_t pos = RLO_TELEM_HEADER_SIZE;
    for (int i = 0; i < RLO_TELEM_NKEYS; i++) {
        int64_t d = vals[i] - (full ? 0 : prev[i]);
        if (!full && d == 0)
            continue;
        mask |= (uint64_t)1 << i;
        /* zigzag, then LEB128 */
        uint64_t u = ((uint64_t)d << 1) ^ (uint64_t)(d >> 63);
        do {
            if (pos >= cap)
                return RLO_ERR_TOO_BIG;
            dst[pos++] = (uint8_t)((u & 0x7f) | (u >= 0x80 ? 0x80 : 0));
            u >>= 7;
        } while (u);
    }
    put_u64(dst + 18, mask);
    return pos;
}

int64_t rlo_telem_decode(const uint8_t *raw, int64_t rawlen,
                         int32_t *rank, int32_t *epoch, uint32_t *seq,
                         int *full, int64_t *deltas, uint64_t *mask)
{
    if (!raw || rawlen < RLO_TELEM_HEADER_SIZE ||
        memcmp(raw, RLO_TELEM_MAGIC, 5) != 0)
        return RLO_ERR_ARG;
    uint64_t m = get_u64(raw + 18);
    if (RLO_TELEM_NKEYS < 64 && (m >> RLO_TELEM_NKEYS))
        return RLO_ERR_ARG; /* mask bits beyond the schema */
    if (rank)
        *rank = get_i32(raw + 6);
    if (epoch)
        *epoch = get_i32(raw + 10);
    if (seq)
        *seq = get_u32(raw + 14);
    if (full)
        *full = raw[5] & 1;
    if (mask)
        *mask = m;
    int64_t pos = RLO_TELEM_HEADER_SIZE;
    for (int i = 0; i < RLO_TELEM_NKEYS; i++) {
        if (!(m & ((uint64_t)1 << i)))
            continue;
        uint64_t u = 0;
        int shift = 0;
        for (;;) {
            if (pos >= rawlen || shift > 63)
                return RLO_ERR_ARG; /* truncated/overlong varint */
            uint8_t b = raw[pos++];
            u |= (uint64_t)(b & 0x7f) << shift;
            shift += 7;
            if (!(b & 0x80))
                break;
        }
        if (deltas)
            deltas[i] = (int64_t)(u >> 1) ^ -(int64_t)(u & 1);
    }
    return pos;
}

/* ------------------------------------------------------------------ */
/* Span context codec (docs/DESIGN.md S19) — byte-identical to        */
/* wire.py encode_span_ctx/decode_span_ctx; parity asserted by        */
/* tests/test_spans.py. Layout:                                       */
/*   [magic:5][flags:u8][stage:u8][gateway:i32][seq:i32][t_usec:u64]  */
/* ------------------------------------------------------------------ */

int64_t rlo_span_encode(uint8_t *dst, int64_t cap, int32_t gateway,
                        int32_t seq, int stage, int flags,
                        uint64_t t_usec)
{
    if (!dst || cap < RLO_SPAN_CTX_SIZE)
        return RLO_ERR_ARG;
    memcpy(dst, RLO_SPAN_MAGIC, 5);
    dst[5] = (uint8_t)(flags & 0xff);
    dst[6] = (uint8_t)(stage & 0xff);
    put_i32(dst + 7, gateway);
    put_i32(dst + 11, seq & 0x7fffffff);
    put_u64(dst + 15, t_usec);
    return RLO_SPAN_CTX_SIZE;
}

int64_t rlo_span_decode(const uint8_t *raw, int64_t rawlen,
                        int32_t *gateway, int32_t *seq, int *stage,
                        int *flags, uint64_t *t_usec)
{
    if (!raw || rawlen < RLO_SPAN_CTX_SIZE ||
        memcmp(raw, RLO_SPAN_MAGIC, 5) != 0)
        return RLO_ERR_ARG;
    if (flags)
        *flags = raw[5];
    if (stage)
        *stage = raw[6];
    if (gateway)
        *gateway = get_i32(raw + 7);
    if (seq)
        *seq = get_i32(raw + 11);
    if (t_usec)
        *t_usec = get_u64(raw + 15);
    return RLO_SPAN_CTX_SIZE;
}
