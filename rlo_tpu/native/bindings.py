"""ctypes bindings for the native C core.

Python surface mirrors rlo_tpu.engine (ProgressEngine over the loopback
transport) so tests can run identical scenarios against both
implementations and compare outcomes. pybind11 is deliberately not used —
plain ctypes over the C ABI in rlo_core.h.
"""

from __future__ import annotations

import ctypes as C
from dataclasses import dataclass
from typing import Callable, List, Optional

import numpy as np

from rlo_tpu.native.build import build

# error codes (rlo_core.h enum rlo_err; -1 is the "nothing yet" sentinel)
OK = 0
ERR_ARG = -10
ERR_TOO_BIG = -11
ERR_BUSY = -12
ERR_PROTO = -13
ERR_NOMEM = -14
ERR_STALL = -15

# states (enum rlo_state)
COMPLETED = 0
IN_PROGRESS = 1
FAILED = 2
INVALID = 3

# spanning-tree shapes
FANOUT_SKIP_RING = 0  # rlo-lint: paired-with rlo_core.h:RLO_FANOUT_SKIP_RING
FANOUT_FLAT = 1  # rlo-lint: paired-with rlo_core.h:RLO_FANOUT_FLAT

from rlo_tpu.utils.metrics import ENGINE_COUNTER_KEYS, ENGINE_PHASE_KEYS
from rlo_tpu.wire import MSG_SIZE_MAX  # single shared engine-wide cap

_JUDGE_CB = C.CFUNCTYPE(C.c_int, C.POINTER(C.c_uint8), C.c_int64,
                        C.c_void_p)
_ACTION_CB = C.CFUNCTYPE(None, C.POINTER(C.c_uint8), C.c_int64, C.c_void_p)
# rlo_rank_fn (rlo_core.h): per-rank body run by the shm launcher
_RANK_FN = C.CFUNCTYPE(C.c_int, C.c_void_p, C.c_int, C.c_void_p)


class _EngineState(C.Structure):
    """Mirror of rlo_engine_state (rlo_core.h)."""
    _fields_ = [("rank", C.c_int32), ("world_size", C.c_int32),
                ("sent_bcast", C.c_int64), ("recved_bcast", C.c_int64),
                ("total_pickup", C.c_int64),
                ("prop_pid", C.c_int32), ("prop_state", C.c_int32),
                ("prop_vote", C.c_int32),
                ("prop_votes_needed", C.c_int32),
                ("prop_votes_recved", C.c_int32),
                ("gen_counter", C.c_int32),
                ("bcast_seq", C.c_int32)]


class _TraceEvent(C.Structure):
    """Mirror of rlo_trace_event (rlo_core.h)."""
    _fields_ = [("ts_usec", C.c_uint64), ("rank", C.c_int32),
                ("kind", C.c_int32), ("a", C.c_int32), ("b", C.c_int32),
                ("c", C.c_int32), ("d", C.c_int32)]


HIST_BUCKETS = 28  # mirror of RLO_HIST_BUCKETS (rlo_core.h)


class _Hist(C.Structure):
    """Mirror of rlo_hist (rlo_core.h) — same layout as the snapshot
    of rlo_tpu.utils.metrics.Histogram."""
    _fields_ = [("count", C.c_int64), ("sum", C.c_double),
                ("min", C.c_double), ("max", C.c_double),
                ("buckets", C.c_int64 * HIST_BUCKETS)]

    def to_dict(self) -> dict:
        return {"count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max,
                "buckets": list(self.buckets)}


class _LinkStats(C.Structure):
    """Mirror of rlo_link_stats (rlo_core.h)."""
    _fields_ = [("tx_frames", C.c_int64), ("tx_bytes", C.c_int64),
                ("rx_frames", C.c_int64), ("rx_bytes", C.c_int64),
                ("retransmits", C.c_int64), ("dup_drops", C.c_int64),
                ("rtt_ewma_usec", C.c_double)]

    def to_dict(self) -> dict:
        return {f: getattr(self, f) for f, _ in self._fields_}


class _PhaseStats(C.Structure):
    """Mirror of rlo_phase_stats (rlo_core.h) — the in-engine phase
    profiler's per-stage histograms; field order is the
    metrics.ENGINE_PHASE_KEYS snapshot order (rlo-lint R2 pins the
    pair)."""
    _fields_ = [("frame_encode", _Hist), ("frame_decode", _Hist),
                ("send", _Hist), ("arq_scan", _Hist),
                ("tag_dispatch", _Hist), ("pickup_drain", _Hist),
                ("bcast_first_fwd", _Hist),
                ("bcast_all_delivered", _Hist),
                ("prop_votes_aggregated", _Hist),
                ("prop_decision", _Hist)]


class _Stats(C.Structure):
    """Mirror of rlo_stats (rlo_core.h)."""
    _fields_ = [("sent_bcast", C.c_int64), ("recved_bcast", C.c_int64),
                ("total_pickup", C.c_int64), ("ops_failed", C.c_int64),
                ("arq_retransmits", C.c_int64),
                ("arq_dup_drops", C.c_int64),
                ("arq_gave_up", C.c_int64), ("arq_unacked", C.c_int64),
                ("epoch", C.c_int64), ("epoch_quarantined", C.c_int64),
                ("rejoins", C.c_int64),
                ("view_changes", C.c_int64),
                ("reflood_frames", C.c_int64),
                ("epoch_lag_max", C.c_int64),
                ("quar_mid_rejoin", C.c_int64),
                ("quar_failed_sender", C.c_int64),
                ("quar_below_floor", C.c_int64),
                ("admission_rounds", C.c_int64),
                ("epoch_syncs", C.c_int64),
                ("reflood_skipped", C.c_int64),
                ("batched_admits", C.c_int64),
                ("q_wait", C.c_int64), ("q_pickup", C.c_int64),
                ("q_wait_and_pickup", C.c_int64),
                ("q_iar_pending", C.c_int64),
                ("bcast_complete", _Hist), ("proposal_resolve", _Hist),
                ("pickup_wait", _Hist)]

_lib = None


def load() -> C.CDLL:
    """Build (if stale) and load the shared library, declaring signatures."""
    global _lib
    if _lib is not None:
        return _lib
    lib = C.CDLL(str(build()))

    def sig(name, restype, argtypes):
        fn = getattr(lib, name)
        fn.restype = restype
        fn.argtypes = argtypes

    p = C.c_void_p
    u8p = C.POINTER(C.c_uint8)
    sig("rlo_is_pow2", C.c_int, [C.c_int])
    sig("rlo_level", C.c_int, [C.c_int, C.c_int])
    sig("rlo_last_wall", C.c_int, [C.c_int, C.c_int])
    sig("rlo_send_list", C.c_int,
        [C.c_int, C.c_int, C.POINTER(C.c_int), C.c_int,
         C.POINTER(C.c_int)])
    sig("rlo_check_passed_origin", C.c_int,
        [C.c_int, C.c_int, C.c_int, C.c_int])
    sig("rlo_fwd_targets", C.c_int,
        [C.c_int, C.c_int, C.c_int, C.c_int, C.POINTER(C.c_int), C.c_int])
    sig("rlo_fwd_send_cnt", C.c_int, [C.c_int, C.c_int, C.c_int, C.c_int])
    sig("rlo_initiator_targets", C.c_int,
        [C.c_int, C.c_int, C.POINTER(C.c_int), C.c_int])
    sig("rlo_frame_encode", C.c_int64,
        [u8p, C.c_int64, C.c_int32, C.c_int32, C.c_int32, C.c_int32,
         u8p, C.c_int64])
    sig("rlo_frame_decode", C.c_int64,
        [u8p, C.c_int64, C.POINTER(C.c_int32), C.POINTER(C.c_int32),
         C.POINTER(C.c_int32), C.POINTER(C.c_int32), C.POINTER(u8p)])
    sig("rlo_frame_epoch", C.c_int32, [u8p])
    sig("rlo_frame_set_epoch", None, [u8p, C.c_int32])
    sig("rlo_world_new", p, [C.c_int, C.c_int, C.c_uint64])
    sig("rlo_world_free", None, [p])
    sig("rlo_world_size", C.c_int, [p])
    sig("rlo_world_my_rank", C.c_int, [p])
    sig("rlo_world_transport", C.c_char_p, [p])
    sig("rlo_world_failed", C.c_int, [p])
    sig("rlo_world_peer_alive", C.c_int, [p, C.c_int, C.c_uint64])
    sig("rlo_world_kill_rank", C.c_int, [p, C.c_int])
    sig("rlo_world_drop_next", C.c_int, [p, C.c_int, C.c_int, C.c_int])
    sig("rlo_world_dup_next", C.c_int, [p, C.c_int, C.c_int, C.c_int])
    sig("rlo_engine_enable_arq", C.c_int, [p, C.c_uint64, C.c_int])
    sig("rlo_engine_arq_retransmits", C.c_int64, [p])
    sig("rlo_engine_arq_dup_drops", C.c_int64, [p])
    sig("rlo_engine_arq_unacked", C.c_int64, [p])
    sig("rlo_engine_arq_gave_up", C.c_int64, [p])
    sig("rlo_engine_enable_metrics", C.c_int, [p, C.c_int])
    sig("rlo_engine_stats", C.c_int, [p, C.POINTER(_Stats)])
    sig("rlo_engine_enable_profiler", C.c_int, [p, C.c_int])
    sig("rlo_engine_phase_stats", C.c_int, [p, C.POINTER(_PhaseStats)])
    # telemetry digest codec + engine origination (docs/DESIGN.md §17)
    sig("rlo_telem_encode", C.c_int64,
        [u8p, C.c_int64, C.c_int32, C.c_int32, C.c_uint32, C.c_int,
         C.POINTER(C.c_int64), C.POINTER(C.c_int64)])
    sig("rlo_telem_decode", C.c_int64,
        [u8p, C.c_int64, C.POINTER(C.c_int32), C.POINTER(C.c_int32),
         C.POINTER(C.c_uint32), C.POINTER(C.c_int), C.POINTER(C.c_int64),
         C.POINTER(C.c_uint64)])
    sig("rlo_telem_key_name", C.c_char_p, [C.c_int])
    # span context codec (docs/DESIGN.md §19)
    sig("rlo_span_encode", C.c_int64,
        [u8p, C.c_int64, C.c_int32, C.c_int32, C.c_int, C.c_int,
         C.c_uint64])
    sig("rlo_span_decode", C.c_int64,
        [u8p, C.c_int64, C.POINTER(C.c_int32), C.POINTER(C.c_int32),
         C.POINTER(C.c_int), C.POINTER(C.c_int), C.POINTER(C.c_uint64)])
    sig("rlo_engine_telem_digest", C.c_int64, [p, C.c_int, u8p, C.c_int64])
    sig("rlo_engine_link_stats", C.c_int,
        [p, C.POINTER(_LinkStats), C.c_int])
    sig("rlo_engine_enable_failure_detection", C.c_int,
        [p, C.c_uint64, C.c_uint64])
    sig("rlo_engine_rank_failed", C.c_int, [p, C.c_int])
    sig("rlo_engine_failed_count", C.c_int, [p])
    sig("rlo_engine_suspected_self", C.c_int, [p])
    sig("rlo_world_partition", C.c_int, [p, C.POINTER(C.c_int), C.c_int])
    sig("rlo_world_revive_rank", C.c_int, [p, C.c_int])
    sig("rlo_engine_set_incarnation", C.c_int, [p, C.c_int])
    sig("rlo_engine_rejoin", C.c_int, [p])
    sig("rlo_engine_epoch", C.c_int64, [p])
    sig("rlo_engine_epoch_quarantined", C.c_int64, [p])
    sig("rlo_engine_rejoins", C.c_int64, [p])
    sig("rlo_engine_awaiting_welcome", C.c_int, [p])
    sig("rlo_engine_state_get", C.c_int, [p, C.POINTER(_EngineState)])
    sig("rlo_engine_state_set", C.c_int, [p, C.POINTER(_EngineState)])
    sig("rlo_engine_set_fanout", C.c_int, [p, C.c_int])
    sig("rlo_shm_launch", C.c_int, [C.c_int, C.c_int64, _RANK_FN, p])
    sig("rlo_shm_barrier", None, [p])
    sig("rlo_mpi_available", C.c_int, [])
    sig("rlo_mpi_world_new", p, [])
    sig("rlo_tcp_available", C.c_int, [])
    sig("rlo_tcp_world_new", p, [])
    sig("rlo_world_quiescent", C.c_int, [p])
    sig("rlo_world_sent_cnt", C.c_int64, [p])
    sig("rlo_world_delivered_cnt", C.c_int64, [p])
    # the batched drivers run for the call's whole duration with the
    # GIL released — rlo-sentinel S1 roots its per-world-ownership
    # call-graph scan here (docs/DESIGN.md §15)
    sig("rlo_engine_progress_n", C.c_int64,  # rlo-sentinel: gil-released
        [p, C.c_int64, C.c_uint64])
    sig("rlo_world_progress_all_n", C.c_int64,  # rlo-sentinel: gil-released
        [p, C.c_int64, C.c_uint64])
    sig("rlo_engine_frames_dispatched", C.c_int64, [p])
    sig("rlo_engine_arq_heap_len", C.c_int64, [p])
    sig("rlo_engine_arq_scan_gated", C.c_int64, [p])
    sig("rlo_engine_new", p,
        [p, C.c_int, C.c_int, _JUDGE_CB, p, _ACTION_CB, p, C.c_int64])
    sig("rlo_engine_new_sub", p,
        [p, C.c_int, C.c_int, C.POINTER(C.c_int), C.c_int, _JUDGE_CB, p,
         _ACTION_CB, p, C.c_int64])
    sig("rlo_engine_free", None, [p])
    sig("rlo_progress_all", None, [p])
    sig("rlo_bcast", C.c_int, [p, u8p, C.c_int64])
    sig("rlo_submit_proposal", C.c_int, [p, u8p, C.c_int64, C.c_int])
    sig("rlo_check_proposal_state", C.c_int, [p])
    sig("rlo_vote_my_proposal", C.c_int, [p])
    sig("rlo_proposal_reset", None, [p])
    sig("rlo_pickup_next", C.c_int64,
        [p, C.POINTER(C.c_int), C.POINTER(C.c_int), C.POINTER(C.c_int),
         C.POINTER(C.c_int), u8p, C.c_int64])
    sig("rlo_pickup_peek", C.c_int64,
        [p, C.POINTER(C.c_int), C.POINTER(C.c_int), C.POINTER(C.c_int),
         C.POINTER(C.c_int), C.POINTER(C.POINTER(C.c_uint8))])
    sig("rlo_pickup_consume", C.c_int, [p])
    sig("rlo_bench_allreduce", C.c_double, [C.c_int, C.c_int64, C.c_int])
    sig("rlo_bench_allreduce_ring", C.c_double,
        [C.c_int, C.c_int64, C.c_int])
    sig("rlo_bench_bcast_usec", C.c_double, [C.c_int, C.c_int64, C.c_int])
    sig("rlo_coll_new", p, [p, C.c_int, C.c_int])
    sig("rlo_coll_new_sub", p,
        [p, C.c_int, C.c_int, C.POINTER(C.c_int), C.c_int])
    sig("rlo_coll_free", None, [p])
    fp = C.POINTER(C.c_float)
    sig("rlo_coll_allreduce_f32_start", C.c_int,
        [p, fp, C.c_int64, C.c_int])
    sig("rlo_coll_reduce_scatter_f32_start", C.c_int,
        [p, fp, C.c_int64, fp, C.c_int])
    sig("rlo_coll_all_gather_start", C.c_int, [p, u8p, C.c_int64, u8p])
    sig("rlo_coll_all_to_all_start", C.c_int, [p, u8p, C.c_int64, u8p])
    sig("rlo_coll_barrier_start", C.c_int, [p])
    sig("rlo_coll_poll", C.c_int, [p])
    sig("rlo_coll_wait", C.c_int, [p, C.c_long])
    sig("rlo_engine_idle", C.c_int, [p])
    sig("rlo_engine_err", C.c_int, [p])
    sig("rlo_engine_total_pickup", C.c_int64, [p])
    sig("rlo_engine_sent_bcast", C.c_int64, [p])
    sig("rlo_engine_recved_bcast", C.c_int64, [p])
    sig("rlo_drain", C.c_int, [p, C.c_int])
    sig("rlo_world_barrier", None, [p])
    sig("rlo_world_inject", C.c_int,
        [p, C.c_int, C.c_int, C.c_int, C.c_int, u8p, C.c_int64])
    sig("rlo_now_usec", C.c_uint64, [])
    sig("rlo_trace_set", None, [C.c_int])
    sig("rlo_trace_enabled", C.c_int, [])
    sig("rlo_trace_emit", None, [C.c_int] * 6)
    sig("rlo_trace_drain", C.c_int, [C.POINTER(_TraceEvent), C.c_int])
    sig("rlo_trace_dropped", C.c_int64, [])
    sig("rlo_trace_capacity", C.c_int, [])
    sig("rlo_trace_clear", None, [])
    _lib = lib
    return lib


def _buf(data: bytes):
    return (C.c_uint8 * len(data)).from_buffer_copy(data) if data else None


@dataclass
class NativeUserMsg:
    """Mirror of rlo_tpu.engine.UserMsg for cross-implementation tests."""
    type: int
    origin: int
    pid: int = -1
    vote: int = -1
    data: bytes = b""


class NativeWorld:
    """Owns an rlo_world (in-process loopback transport)."""

    def __init__(self, world_size: int, latency: int = 0, seed: int = 1):
        self._lib = load()
        self._w = self._lib.rlo_world_new(world_size, latency, seed)
        if not self._w:
            raise ValueError(f"world_size must be >= 2, got {world_size}")
        self.world_size = world_size
        self.engines: List["NativeEngine"] = []
        #: NativeColl instances bound to this world — closed before the
        #: world is freed (pooled objects must never outlive the world
        #: that owns their freelists, rlo_internal.h pool rules)
        self.colls: List["NativeColl"] = []

    def progress_all(self) -> None:
        self._lib.rlo_progress_all(self._w)

    def progress_n(self, max_frames: int = 0,
                   deadline_usec: int = 0) -> int:
        """Batched progress (docs/DESIGN.md §13): loop progress sweeps
        INSIDE C until ``max_frames`` frames were processed (0 = no
        budget), ``deadline_usec`` microseconds elapsed (0 = no
        deadline), or — with no deadline — the first fruitless sweep
        with a quiescent transport. Returns frames processed. ctypes
        releases the GIL for the call's whole duration, so one Python
        crossing progresses thousands of frames (and with a deadline
        the call is a GIL-released poll-wait — the serving-pump
        shape). Re-entrant calls (from a judge/action callback) are
        no-ops returning 0."""
        rc = self._lib.rlo_world_progress_all_n(
            self._w, max_frames, deadline_usec)
        if rc < 0:
            raise RuntimeError(f"progress_n failed ({rc})")
        return rc

    def quiescent(self) -> bool:
        return bool(self._lib.rlo_world_quiescent(self._w))

    def failed(self) -> bool:
        """True when the world is dead — a peer process crashed (shm
        abort flag / tcp reset or mid-frame EOF). A graceful peer
        departure does NOT set it."""
        return bool(self._lib.rlo_world_failed(self._w))

    def peer_alive(self, rank: int, timeout_usec: int = 1_000_000) -> bool:
        """Net-new failure detection (SURVEY.md §5), transport-
        specific: shm = False when `rank` stamped no heartbeat slot
        for timeout_usec; tcp = False when `rank`'s connection is
        closed (graceful exit or crash — timeout_usec is ignored, and
        a hung-but-connected peer stays True: that is the engine-level
        heartbeat detector's job). Always True on transports without
        a liveness signal (in-process loopback)."""
        return bool(self._lib.rlo_world_peer_alive(self._w, rank,
                                                   timeout_usec))

    def kill_rank(self, rank: int) -> None:
        """Fault injection (loopback only): simulate `rank` crashing —
        mirror of LoopbackWorld.kill_rank."""
        rc = self._lib.rlo_world_kill_rank(self._w, rank)
        if rc != 0:
            raise RuntimeError(f"kill_rank failed ({rc})")

    def drop_next(self, src: int, dst: int, count: int = 1) -> None:
        """Fault injection (loopback only): silently drop the next
        ``count`` frames src -> dst — mirror of
        LoopbackWorld.drop_next."""
        rc = self._lib.rlo_world_drop_next(self._w, src, dst, count)
        if rc != 0:
            raise RuntimeError(f"drop_next failed ({rc})")

    def dup_next(self, src: int, dst: int, count: int = 1) -> None:
        """Fault injection (loopback only): deliver the next ``count``
        frames src -> dst twice — mirror of LoopbackWorld.dup_next."""
        rc = self._lib.rlo_world_dup_next(self._w, src, dst, count)
        if rc != 0:
            raise RuntimeError(f"dup_next failed ({rc})")

    def partition(self, groups) -> None:
        """Fault injection (loopback only): split the network into
        ``groups`` (sequences of ranks) — frames crossing the cut are
        dropped, including frames already in flight across it. Ranks
        not named fall into singleton groups. Mirror of
        SimWorld.partition."""
        gmap = {}
        for gi, g in enumerate(groups):
            for r in g:
                if not 0 <= r < self.world_size:
                    raise ValueError(f"bad rank {r} in partition")
                if r in gmap:
                    raise ValueError(f"rank {r} in two groups")
                gmap[r] = gi
        arr = (C.c_int * self.world_size)(
            *[gmap.get(r, len(groups) + r)
              for r in range(self.world_size)])
        rc = self._lib.rlo_world_partition(self._w, arr,
                                           self.world_size)
        if rc != 0:
            raise RuntimeError(f"partition failed ({rc})")

    def heal(self) -> None:
        """Remove the partition; traffic flows everywhere again."""
        rc = self._lib.rlo_world_partition(
            self._w, C.cast(None, C.POINTER(C.c_int)), 0)
        if rc != 0:
            raise RuntimeError(f"heal failed ({rc})")

    def revive_rank(self, rank: int) -> None:
        """Revive a killed rank's endpoint with an empty inbox (build a
        fresh engine with a bumped incarnation on top — mirror of
        SimWorld.restart_rank)."""
        rc = self._lib.rlo_world_revive_rank(self._w, rank)
        if rc != 0:
            raise RuntimeError(f"revive_rank failed ({rc})")

    @property
    def sent_cnt(self) -> int:
        return self._lib.rlo_world_sent_cnt(self._w)

    @property
    def delivered_cnt(self) -> int:
        return self._lib.rlo_world_delivered_cnt(self._w)

    def barrier(self) -> None:
        """Collective barrier across ranks (shm/mpi; no-op loopback)."""
        self._lib.rlo_world_barrier(self._w)

    def inject(self, src: int, dst: int, tag: int, raw: bytes,
               comm: int = 0) -> None:
        """Test support: place one raw frame on the (src, dst) channel
        as if src had sent it (duplicate/stale-frame scenarios)."""
        buf = (C.c_uint8 * len(raw)).from_buffer_copy(raw)
        rc = self._lib.rlo_world_inject(self._w, src, dst, comm, tag,
                                        buf, len(raw))
        if rc != 0:
            raise RuntimeError(f"inject failed ({rc})")

    def drain(self, max_spins: int = 100_000) -> int:
        rc = self._lib.rlo_drain(self._w, max_spins)
        if rc == ERR_STALL:
            raise RuntimeError("native drain did not reach quiescence")
        return rc

    def close(self) -> None:
        for e in list(self.engines):
            e.close()
        for c in list(getattr(self, "colls", [])):
            c.close()
        if self._w:
            self._lib.rlo_world_free(self._w)
            self._w = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


_COLL_OPS = {"sum": 0, "min": 1, "max": 2}


class NativeColl:
    """Engine-substrate ring data collectives (rlo_coll.c) — the C
    mirror of rlo_tpu/ops/collectives.py's coroutine Comm. Each op is a
    start/poll state machine; `blocking=True` helpers spin to
    completion (one-process-per-rank worlds), while in-process drivers
    round-robin `poll()` across ranks like run_collectives()."""

    MAX_SPINS = 200_000_000

    def __init__(self, world: "NativeWorld", rank: int, comm: int = 64,
                 members: Optional[List[int]] = None):
        """``members`` scopes the collectives to a rank subset (the
        data-collective face of sub-communicators); slot layouts are
        indexed by subset position."""
        self._lib = world._lib
        self.world = world
        self.rank = rank
        self.comm = comm  # must differ from every engine comm
        if members is None:
            self._c = self._lib.rlo_coll_new(world._w, rank, comm)
            self.group_size = world.world_size
            if not self._c:
                raise ValueError(f"bad rank {rank} for this world")
        else:
            ms = sorted(set(members))
            arr = (C.c_int * len(ms))(*ms)
            self._c = self._lib.rlo_coll_new_sub(world._w, rank, comm,
                                                 arr, len(ms))
            self.group_size = len(ms)
            if not self._c:
                raise ValueError(
                    f"bad subset for rank {rank}: members={ms} (need "
                    f"2..64 in-range members including this rank)")
        getattr(world, "colls", []).append(self)
        self._keep = None  # buffers pinned while an op is in flight

    def close(self) -> None:
        if self._c:
            self._lib.rlo_coll_free(self._c)
            self._c = None
        colls = getattr(self.world, "colls", None)
        if colls is not None and self in colls:
            colls.remove(self)

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass

    def poll(self) -> int:
        """1 done, 0 in progress (<0 raises)."""
        rc = self._lib.rlo_coll_poll(self._c)
        if rc < 0 and rc != -1:  # -1 RLO_ERR_ARG = nothing armed
            raise RuntimeError(f"coll poll failed ({rc})")
        return rc

    def _wait(self):
        rc = self._lib.rlo_coll_wait(self._c, self.MAX_SPINS)
        if rc != 0:
            raise RuntimeError(f"collective did not complete ({rc})")

    # -- fp32 ring ops -------------------------------------------------
    def allreduce_start(self, x: "np.ndarray", op: str = "sum"):
        """Arm an in-place ring allreduce; returns the output array
        (filled when poll() reports done)."""
        buf = np.ascontiguousarray(x, np.float32).reshape(-1).copy()
        rc = self._lib.rlo_coll_allreduce_f32_start(
            self._c, buf.ctypes.data_as(C.POINTER(C.c_float)), buf.size,
            _COLL_OPS[op])
        if rc != 0:
            raise RuntimeError(f"allreduce start failed ({rc})")
        self._keep = (buf,)
        return buf

    def allreduce(self, x: "np.ndarray", op: str = "sum"):
        out = self.allreduce_start(x, op)
        self._wait()
        return out.reshape(np.asarray(x).shape)

    def reduce_scatter_start(self, x: "np.ndarray", op: str = "sum"):
        buf = np.ascontiguousarray(x, np.float32).reshape(-1).copy()
        ws = self.group_size
        chunk = -(-buf.size // ws)
        out = np.empty(chunk, np.float32)
        rc = self._lib.rlo_coll_reduce_scatter_f32_start(
            self._c, buf.ctypes.data_as(C.POINTER(C.c_float)), buf.size,
            out.ctypes.data_as(C.POINTER(C.c_float)), _COLL_OPS[op])
        if rc != 0:
            raise RuntimeError(f"reduce_scatter start failed ({rc})")
        self._keep = (buf, out)
        return out

    def reduce_scatter(self, x, op: str = "sum"):
        out = self.reduce_scatter_start(x, op)
        self._wait()
        return out

    # -- byte ops ------------------------------------------------------
    def all_gather_start(self, data: bytes):
        ws = self.group_size
        src = np.frombuffer(bytes(data), np.uint8).copy()
        out = np.empty(ws * len(data), np.uint8)
        rc = self._lib.rlo_coll_all_gather_start(
            self._c, src.ctypes.data_as(C.POINTER(C.c_uint8)), len(data),
            out.ctypes.data_as(C.POINTER(C.c_uint8)))
        if rc != 0:
            raise RuntimeError(f"all_gather start failed ({rc})")
        self._keep = (src, out)
        return out

    def all_gather(self, data: bytes):
        """Returns [bytes per rank]."""
        out = self.all_gather_start(data)
        self._wait()
        n = len(out) // self.group_size
        raw = out.tobytes()
        return [raw[i * n:(i + 1) * n]
                for i in range(self.group_size)]

    def all_to_all_start(self, chunks):
        ws = self.group_size
        if len(chunks) != ws:
            raise ValueError(f"need {ws} chunks, got {len(chunks)}")
        n = len(chunks[0])
        if any(len(ch) != n for ch in chunks):
            raise ValueError("all chunks must be equal-sized")
        src = np.frombuffer(b"".join(bytes(ch) for ch in chunks),
                            np.uint8).copy()
        out = np.empty(ws * n, np.uint8)
        rc = self._lib.rlo_coll_all_to_all_start(
            self._c, src.ctypes.data_as(C.POINTER(C.c_uint8)), n,
            out.ctypes.data_as(C.POINTER(C.c_uint8)))
        if rc != 0:
            raise RuntimeError(f"all_to_all start failed ({rc})")
        self._keep = (src, out)
        return out

    def all_to_all(self, chunks):
        out = self.all_to_all_start(chunks)
        self._wait()
        ws = self.group_size
        n = len(out) // ws
        raw = out.tobytes()
        return [raw[i * n:(i + 1) * n] for i in range(ws)]

    def barrier_start(self):
        rc = self._lib.rlo_coll_barrier_start(self._c)
        if rc != 0:
            raise RuntimeError(f"barrier start failed ({rc})")

    def barrier(self):
        self.barrier_start()
        self._wait()


def run_colls(colls, starts, max_spins: int = 10_000_000):
    """Round-robin driver for in-process worlds: `starts[i]()` arms
    rank i's op, then every coll is polled until all complete — the C
    mirror of collectives.run_collectives()."""
    outs = [start() for start in starts]
    alive = set(range(len(colls)))
    for _ in range(max_spins):
        for i in list(alive):
            if colls[i].poll() == 1:
                alive.discard(i)
        if not alive:
            return outs
    raise RuntimeError("collective did not complete (deadlock?)")


class NativeEngine:
    """One rank's progress engine in a NativeWorld."""

    def __init__(self, world: NativeWorld, rank: int, comm: int = 0,
                 judge_cb: Optional[Callable[[bytes, object], int]] = None,
                 app_ctx: object = None,
                 action_cb: Optional[Callable[[bytes, object], None]] = None,
                 msg_size_max: int = MSG_SIZE_MAX,
                 members: Optional[List[int]] = None):
        """``members`` builds the engine over a rank subset (a
        sub-communicator: rlo_engine_new_sub; give it a distinct
        ``comm`` from any full-world engine on the same world)."""
        self._lib = load()
        self.world = world
        self.rank = rank
        self.world_size = world.world_size
        self.msg_size_max = msg_size_max
        self.app_ctx = app_ctx
        self.members = sorted(set(members)) if members is not None \
            else None

        # keep CFUNCTYPE wrappers alive for the engine's lifetime
        if judge_cb is not None:
            self._judge = _JUDGE_CB(
                lambda buf, n, _ctx: int(
                    judge_cb(bytes(C.cast(
                        buf, C.POINTER(C.c_uint8 * n)).contents) if n else
                        b"", app_ctx)))
        else:
            self._judge = C.cast(None, _JUDGE_CB)
        if action_cb is not None:
            self._action = _ACTION_CB(
                lambda buf, n, _ctx: action_cb(
                    bytes(C.cast(
                        buf, C.POINTER(C.c_uint8 * n)).contents) if n else
                    b"", app_ctx))
        else:
            self._action = C.cast(None, _ACTION_CB)

        if self.members is None:
            self._e = self._lib.rlo_engine_new(
                world._w, rank, comm, self._judge, None, self._action,
                None, msg_size_max)
        else:
            arr = (C.c_int * len(self.members))(*self.members)
            self._e = self._lib.rlo_engine_new_sub(
                world._w, rank, comm, arr, len(self.members),
                self._judge, None, self._action, None, msg_size_max)
        if not self._e:
            raise RuntimeError(f"engine creation failed (rank {rank})")
        world.engines.append(self)

    def _check(self, rc: int) -> int:
        if rc == ERR_BUSY:
            raise RuntimeError("proposal still in progress")
        if rc == ERR_TOO_BIG:
            raise ValueError("payload exceeds msg_size_max")
        if rc in (ERR_ARG, ERR_PROTO, ERR_NOMEM):
            raise RuntimeError(f"native error {rc}")
        return rc

    def bcast(self, payload: bytes) -> None:
        self._check(self._lib.rlo_bcast(
            self._e, _buf(payload), len(payload)))

    def submit_proposal(self, proposal: bytes, pid: int) -> int:
        return self._check(self._lib.rlo_submit_proposal(
            self._e, _buf(proposal), len(proposal), pid))

    def check_proposal_state(self) -> int:
        return self._lib.rlo_check_proposal_state(self._e)

    def vote_my_proposal(self) -> int:
        return self._lib.rlo_vote_my_proposal(self._e)

    def proposal_reset(self) -> None:
        self._lib.rlo_proposal_reset(self._e)

    def pickup_next(self) -> Optional[NativeUserMsg]:
        # zero-copy peek + consume: the single copy is string_at pulling
        # the payload out of the engine-owned frame blob into a Python
        # bytes (the engine's buffer is only valid until the next call)
        tag = C.c_int()
        origin = C.c_int()
        pid = C.c_int()
        vote = C.c_int()
        payload = C.POINTER(C.c_uint8)()
        n = self._lib.rlo_pickup_peek(
            self._e, C.byref(tag), C.byref(origin), C.byref(pid),
            C.byref(vote), C.byref(payload))
        if n < 0:
            if n == -1:
                return None
            self._check(int(n))
        data = C.string_at(payload, int(n)) if n else b""
        self._check(self._lib.rlo_pickup_consume(self._e))
        return NativeUserMsg(type=tag.value, origin=origin.value,
                             pid=pid.value, vote=vote.value, data=data)

    def progress(self, max_frames: int = 0,
                 deadline_usec: int = 0) -> int:
        """Batched single-engine progress (docs/DESIGN.md §13): loop
        THIS engine's progress turns inside C until the budget fills,
        the deadline expires, or — with no deadline — the first
        fruitless turn (it never spins on other engines' traffic, so
        one-frame-at-a-time stepping is ``progress(max_frames=1)``).
        Returns frames processed; the GIL is released throughout."""
        rc = self._lib.rlo_engine_progress_n(
            self._e, max_frames, deadline_usec)
        if rc < 0:
            raise RuntimeError(f"progress failed ({rc})")
        return rc

    @property
    def frames_dispatched(self) -> int:
        """Lifetime frames this engine polled off the transport (every
        polled frame counts: ACKs, heartbeats, duplicates)."""
        return self._lib.rlo_engine_frames_dispatched(self._e)

    @property
    def arq_heap_len(self) -> int:
        """Live population of the lazy ARQ due-heap (stale entries for
        acked frames linger until their deadline pops them)."""
        return self._lib.rlo_engine_arq_heap_len(self._e)

    @property
    def arq_scan_gated(self) -> int:
        """Retransmit sweeps skipped on the O(1) due-heap peek."""
        return self._lib.rlo_engine_arq_scan_gated(self._e)

    def enable_failure_detection(self, timeout_usec: int,
                                 interval_usec: int = 0) -> None:
        """Ring-heartbeat liveness detection + elastic survivor
        re-forming (mirror of ProgressEngine's failure_timeout)."""
        rc = self._lib.rlo_engine_enable_failure_detection(
            self._e, timeout_usec, interval_usec)
        if rc != 0:
            raise RuntimeError(f"enable_failure_detection failed ({rc})")

    def enable_arq(self, rto_usec: int, max_retries: int = 8) -> None:
        """Reliable delivery: per-(src, dst) link seqs, retransmit
        until acked with exponential backoff, receive-side dedup
        (mirror of ProgressEngine's arq_rto machinery)."""
        rc = self._lib.rlo_engine_enable_arq(self._e, rto_usec,
                                             max_retries)
        if rc != 0:
            raise RuntimeError(f"enable_arq failed ({rc})")

    @property
    def arq_retransmits(self) -> int:
        return self._lib.rlo_engine_arq_retransmits(self._e)

    @property
    def arq_dup_drops(self) -> int:
        return self._lib.rlo_engine_arq_dup_drops(self._e)

    @property
    def arq_unacked(self) -> int:
        return self._lib.rlo_engine_arq_unacked(self._e)

    @property
    def arq_gave_up(self) -> int:
        return self._lib.rlo_engine_arq_gave_up(self._e)

    def enable_metrics(self, on: bool = True) -> None:
        """Per-link frame/byte/RTT accounting + op-latency histograms
        (mirror of ProgressEngine.enable_metrics; one branch per
        send/receive when off)."""
        rc = self._lib.rlo_engine_enable_metrics(self._e, 1 if on else 0)
        if rc != 0:
            raise RuntimeError(f"enable_metrics failed ({rc})")

    def enable_profiler(self, on: bool = True) -> None:
        """In-engine phase profiler (docs/DESIGN.md §10): per-stage
        duration histograms over the ENGINE_PHASE_KEYS taxonomy
        (mirror of ProgressEngine.enable_profiler; one branch per
        instrumented site when off)."""
        rc = self._lib.rlo_engine_enable_profiler(self._e,
                                                  1 if on else 0)
        if rc != 0:
            raise RuntimeError(f"enable_profiler failed ({rc})")

    def metrics(self) -> dict:
        """Drain rlo_engine_stats / rlo_engine_link_stats into the
        SAME nested-dict schema as ProgressEngine.metrics() — counter
        keys, nesting, and histogram layout are identical by
        construction (asserted by the metrics-parity test)."""
        st = _Stats()
        rc = self._lib.rlo_engine_stats(self._e, C.byref(st))
        if rc != 0:
            raise RuntimeError(f"rlo_engine_stats failed ({rc})")
        ws = self.world_size
        arr = (_LinkStats * ws)()
        rc = self._lib.rlo_engine_link_stats(self._e, arr, ws)
        if rc < 0:
            raise RuntimeError(f"rlo_engine_link_stats failed ({rc})")
        ph = _PhaseStats()
        rc = self._lib.rlo_engine_phase_stats(self._e, C.byref(ph))
        if rc != 0:
            raise RuntimeError(f"rlo_engine_phase_stats failed ({rc})")
        return {
            # ENGINE_COUNTER_KEYS is the schema contract with the
            # Python engine (ProgressEngine.metrics builds from the
            # same tuple; the parity test asserts dict equality)
            "counters": {k: getattr(st, k)
                         for k in ENGINE_COUNTER_KEYS},
            "queues": {
                "wait": st.q_wait,
                "pickup": st.q_pickup,
                "wait_and_pickup": st.q_wait_and_pickup,
                "iar_pending": st.q_iar_pending,
            },
            # string peer keys: identical schema in memory and through
            # a JSON round-trip (mirror of ProgressEngine.metrics())
            "links": {str(peer): arr[peer].to_dict()
                      for peer in range(ws) if peer != self.rank},
            "op_latency_usec": {
                "bcast_complete": st.bcast_complete.to_dict(),
                "proposal_resolve": st.proposal_resolve.to_dict(),
                "pickup_wait": st.pickup_wait.to_dict(),
            },
            # ENGINE_PHASE_KEYS doubles as the rlo_phase_stats field
            # order (rlo-lint R2), so the same tuple drives both
            # engines' "phases" assembly
            "phases": {k: getattr(ph, k).to_dict()
                       for k in ENGINE_PHASE_KEYS},
        }

    def telem_digest(self, full: bool = False) -> bytes:
        """Originate one telemetry digest from the C engine's own
        telemetry (docs/DESIGN.md §17): delta-encoded vs the last
        digest this engine emitted, first call always a full
        snapshot. The bytes are a Tag.TELEM frame payload the
        telemetry plane (rlo_tpu/observe/) decodes and merges like
        any Python-originated digest."""
        from rlo_tpu.wire import TELEM_HEADER_SIZE, TELEM_KEYS
        cap = TELEM_HEADER_SIZE + 10 * len(TELEM_KEYS)
        buf = (C.c_uint8 * cap)()
        n = self._lib.rlo_engine_telem_digest(
            self._e, 1 if full else 0, buf, cap)
        if n < 0:
            raise RuntimeError(f"rlo_engine_telem_digest failed ({n})")
        return bytes(buf[:n])

    def set_fanout(self, mode: int) -> None:
        """Select the bcast/IAR spanning-tree shape (FANOUT_SKIP_RING /
        FANOUT_FLAT, rlo_core.h RLO_FANOUT_*) — only while the engine
        is idle between rounds; mirror of ProgressEngine(fanout=)."""
        rc = self._lib.rlo_engine_set_fanout(self._e, mode)
        if rc != 0:
            raise ValueError(f"set_fanout({mode}) failed ({rc}): bad "
                             f"mode or engine mid-round")

    def set_incarnation(self, incarnation: int) -> None:
        """Partition this engine's life at its rank: a RESTARTED
        process passes a fresh incarnation BEFORE any traffic;
        broadcast seqs and round generations re-base so peers' dedup
        windows never swallow the new life's frames. incarnation > 0
        also starts the engine in joiner mode (petitioning until
        welcomed) — mirror of ProgressEngine(incarnation=...)."""
        rc = self._lib.rlo_engine_set_incarnation(self._e, incarnation)
        if rc != 0:
            raise ValueError(
                f"set_incarnation({incarnation}) failed ({rc}): the "
                f"incarnation must not go backwards, be negative, or "
                f"exceed the world-size-qualified cap (the shifted "
                f"gen base must fit int32 after * world_size)")

    def rejoin(self) -> int:
        """Explicitly petition for readmission with a fresh
        incarnation (docs/DESIGN.md §8) — mirror of
        ProgressEngine.rejoin(). Returns the new incarnation."""
        rc = self._lib.rlo_engine_rejoin(self._e)
        if rc < 0:
            raise RuntimeError(f"rejoin failed ({rc})")
        return rc

    @property
    def epoch(self) -> int:
        return self._lib.rlo_engine_epoch(self._e)

    @property
    def epoch_quarantined(self) -> int:
        return self._lib.rlo_engine_epoch_quarantined(self._e)

    @property
    def rejoins(self) -> int:
        return self._lib.rlo_engine_rejoins(self._e)

    @property
    def awaiting_welcome(self) -> bool:
        return bool(self._lib.rlo_engine_awaiting_welcome(self._e))

    def rank_failed(self, rank: int) -> bool:
        return bool(self._lib.rlo_engine_rank_failed(self._e, rank))

    @property
    def failed_count(self) -> int:
        return self._lib.rlo_engine_failed_count(self._e)

    @property
    def suspected_self(self) -> bool:
        return bool(self._lib.rlo_engine_suspected_self(self._e))

    def state_dict(self) -> dict:
        """Quiesced-engine snapshot (~checkpoint.engine_state_dict for
        the C engine); raises if the engine has in-flight, pending, or
        undelivered work."""
        st = _EngineState()
        rc = self._lib.rlo_engine_state_get(self._e, C.byref(st))
        if rc != 0:
            raise RuntimeError(
                "engine busy: drain and pick up everything before "
                "snapshotting" if rc == ERR_BUSY else f"error {rc}")
        return {f: getattr(st, f) for f, _ in _EngineState._fields_}

    def load_state_dict(self, state: dict) -> None:
        st = _EngineState(**state)
        rc = self._lib.rlo_engine_state_set(self._e, C.byref(st))
        if rc != 0:
            raise ValueError(f"snapshot rejected ({rc}): rank/world "
                             f"mismatch or bad argument")

    def idle(self) -> bool:
        return bool(self._lib.rlo_engine_idle(self._e))

    @property
    def err(self) -> int:
        return self._lib.rlo_engine_err(self._e)

    @property
    def total_pickup(self) -> int:
        return self._lib.rlo_engine_total_pickup(self._e)

    @property
    def sent_bcast_cnt(self) -> int:
        return self._lib.rlo_engine_sent_bcast(self._e)

    @property
    def recved_bcast_cnt(self) -> int:
        return self._lib.rlo_engine_recved_bcast(self._e)

    def close(self) -> None:
        if self._e:
            self._lib.rlo_engine_free(self._e)
            self._e = None
        if self in self.world.engines:
            self.world.engines.remove(self)


# -- pure-function wrappers for parity tests --------------------------------

def level(ws: int, rank: int) -> int:
    return load().rlo_level(ws, rank)


def last_wall(ws: int, rank: int) -> int:
    return load().rlo_last_wall(ws, rank)


def send_list(ws: int, rank: int):
    out = (C.c_int * 64)()
    chan = C.c_int()
    n = load().rlo_send_list(ws, rank, out, 64, C.byref(chan))
    assert n >= 0
    return tuple(out[:n]), chan.value


def check_passed_origin(ws: int, my_rank: int, origin: int,
                        to_rank: int) -> bool:
    return bool(load().rlo_check_passed_origin(ws, my_rank, origin,
                                               to_rank))


def fwd_targets(ws: int, rank: int, origin: int, from_rank: int):
    out = (C.c_int * 64)()
    n = load().rlo_fwd_targets(ws, rank, origin, from_rank, out, 64)
    assert n >= 0
    return tuple(out[:n])


def fwd_send_cnt(ws: int, rank: int, origin: int, from_rank: int) -> int:
    return load().rlo_fwd_send_cnt(ws, rank, origin, from_rank)


def initiator_targets(ws: int, rank: int):
    out = (C.c_int * 64)()
    n = load().rlo_initiator_targets(ws, rank, out, 64)
    assert n >= 0
    return tuple(out[:n])


def frame_roundtrip(origin: int, pid: int, vote: int, payload: bytes,
                    seq: int = -1):
    """Encode then decode one frame through the C wire format."""
    from rlo_tpu.wire import HEADER_SIZE
    lib = load()
    cap = HEADER_SIZE + len(payload)
    raw = (C.c_uint8 * cap)()
    n = lib.rlo_frame_encode(raw, cap, origin, pid, vote, seq,
                             _buf(payload), len(payload))
    assert n == cap, n
    o = C.c_int32()
    p = C.c_int32()
    v = C.c_int32()
    s = C.c_int32()
    pp = C.POINTER(C.c_uint8)()
    m = lib.rlo_frame_decode(raw, n, C.byref(o), C.byref(p), C.byref(v),
                             C.byref(s), C.byref(pp))
    assert m >= 0, m
    data = bytes(C.cast(pp, C.POINTER(C.c_uint8 * m)).contents) if m else b""
    return o.value, p.value, v.value, data, bytes(raw), s.value


def frame_epoch(raw: bytes) -> int:
    """Read the link-epoch field of an encoded frame (C accessor —
    the parity twin of wire.Frame.decode(raw).epoch)."""
    from rlo_tpu.wire import HEADER_SIZE
    if len(raw) < HEADER_SIZE:
        raise ValueError(f"frame too short: {len(raw)} < {HEADER_SIZE}")
    return load().rlo_frame_epoch(_buf(raw))


def frame_set_epoch(raw: bytes, epoch: int) -> bytes:
    """Return ``raw`` with its link-epoch field restamped through the C
    send-gate accessor (parity twin of wire.restamp_epoch)."""
    from rlo_tpu.wire import HEADER_SIZE
    if len(raw) < HEADER_SIZE:
        raise ValueError(f"frame too short: {len(raw)} < {HEADER_SIZE}")
    buf = _buf(raw)
    load().rlo_frame_set_epoch(buf, epoch)
    return bytes(buf)


def telem_encode(rank: int, epoch: int, seq: int, values,
                 prev=None, full: bool = False) -> bytes:
    """Encode one telemetry digest through the C codec — the byte-
    parity twin of wire.encode_telem (docs/DESIGN.md §17). ``values``
    (and optional ``prev``) are sequences in wire.TELEM_KEYS order."""
    from rlo_tpu.wire import TELEM_HEADER_SIZE, TELEM_KEYS
    if len(values) != len(TELEM_KEYS):
        raise ValueError(f"need {len(TELEM_KEYS)} values, got "
                         f"{len(values)}")
    lib = load()
    cap = TELEM_HEADER_SIZE + 10 * len(TELEM_KEYS)
    buf = (C.c_uint8 * cap)()
    vals = (C.c_int64 * len(TELEM_KEYS))(*[int(v) for v in values])
    pv = None
    if prev is not None and not full:
        pv = (C.c_int64 * len(TELEM_KEYS))(*[int(v) for v in prev])
    n = lib.rlo_telem_encode(buf, cap, rank, epoch, seq,
                             1 if (full or prev is None) else 0,
                             vals, pv)
    if n < 0:
        raise ValueError(f"rlo_telem_encode failed ({n})")
    return bytes(buf[:n])


def telem_decode(raw: bytes):
    """Decode one digest through the C codec: ``(rank, epoch, seq,
    full, {key: delta})`` — the parity twin of wire.decode_telem."""
    from rlo_tpu.wire import TELEM_KEYS
    lib = load()
    rank = C.c_int32()
    epoch = C.c_int32()
    seq = C.c_uint32()
    full = C.c_int()
    deltas = (C.c_int64 * len(TELEM_KEYS))()
    mask = C.c_uint64()
    n = lib.rlo_telem_decode(_buf(raw), len(raw), C.byref(rank),
                             C.byref(epoch), C.byref(seq),
                             C.byref(full), deltas, C.byref(mask))
    if n < 0:
        raise ValueError(f"rlo_telem_decode failed ({n})")
    out = {k: deltas[i] for i, k in enumerate(TELEM_KEYS)
           if mask.value & (1 << i)}
    return rank.value, epoch.value, seq.value, bool(full.value), out


def telem_key_names():
    """The C codec's schema key table (rlo_wire.c k_telem_keys) — the
    runtime face of the rlo-lint R2 TELEM pin."""
    from rlo_tpu.wire import TELEM_KEYS
    lib = load()
    return tuple(lib.rlo_telem_key_name(i).decode()
                 for i in range(len(TELEM_KEYS)))


def span_encode(gateway: int, seq: int, stage: int, t_usec: int,
                flags: int = 1) -> bytes:
    """Encode one span-context trailer through the C codec — the
    byte-parity twin of wire.encode_span_ctx (docs/DESIGN.md §19)."""
    from rlo_tpu.wire import SPAN_CTX_SIZE
    lib = load()
    buf = (C.c_uint8 * SPAN_CTX_SIZE)()
    n = lib.rlo_span_encode(buf, SPAN_CTX_SIZE, gateway, seq, stage,
                            flags, t_usec)
    if n < 0:
        raise ValueError(f"rlo_span_encode failed ({n})")
    return bytes(buf[:n])


def span_decode(raw: bytes):
    """Decode a span context through the C codec: ``(flags, stage,
    gateway, seq, t_usec)`` or None when ``raw`` does not start with
    one — the parity twin of wire.decode_span_ctx."""
    lib = load()
    gateway = C.c_int32()
    seq = C.c_int32()
    stage = C.c_int()
    flags = C.c_int()
    t_usec = C.c_uint64()
    n = lib.rlo_span_decode(_buf(raw), len(raw), C.byref(gateway),
                            C.byref(seq), C.byref(stage),
                            C.byref(flags), C.byref(t_usec))
    if n < 0:
        return None
    return (flags.value, stage.value, gateway.value, seq.value,
            t_usec.value)


def run_judged_proposal(world_size: int, payload: bytes, proposer: int,
                        judge_for=None, action_cb=None, pid: int = None
                        ) -> int:
    """One complete IAR consensus round on a fresh in-process C world:
    rank `proposer` submits `payload`, every rank judges it with
    ``judge_for(rank)`` (None = approve), approving ranks fire
    ``action_cb(rank, payload)``; returns the decision (0/1).

    The shared plumbing behind NativeBackend.consensus and the hybrid
    bridge's propose_collective (~RLO_submit_proposal + callbacks,
    reference rootless_ops.c:876, :698, :842)."""
    if not 0 <= proposer < world_size:
        raise ValueError(f"proposer {proposer} out of range "
                         f"[0, {world_size})")
    world = NativeWorld(world_size)
    try:
        engines = [NativeEngine(
            world, r,
            judge_cb=(judge_for(r) if judge_for is not None else None),
            action_cb=(None if action_cb is None else
                       (lambda p, ctx, r=r: action_cb(r, p))))
            for r in range(world_size)]
        rc = engines[proposer].submit_proposal(
            payload, pid=proposer if pid is None else pid)
        if rc == -1:
            world.drain()
            rc = engines[proposer].vote_my_proposal()
        if rc not in (0, 1):
            raise RuntimeError(f"consensus incomplete ({rc})")
        world.drain()
        return int(rc)
    finally:
        world.close()


def bench_allreduce(world_size: int, count: int, reps: int = 5) -> float:
    """Median usec per wholly-native bcast-gather fp32 allreduce of
    `count` floats per rank (no Python in the measured loop); raises on
    native failure."""
    rc = load().rlo_bench_allreduce(world_size, count, reps)
    if rc < 0:
        raise RuntimeError(f"native bench failed ({int(rc)})")
    return float(rc)


def bench_allreduce_ring(world_size: int, count: int,
                         reps: int = 5) -> float:
    """Median usec per wholly-native RING fp32 allreduce (rlo_coll.c
    state machines round-robined in C) — the bandwidth-optimal
    comparison line against bench_allreduce's bcast-gather."""
    rc = load().rlo_bench_allreduce_ring(world_size, count, reps)
    if rc < 0:
        raise RuntimeError(f"native ring bench failed ({int(rc)})")
    return float(rc)


def bench_bcast_usec(world_size: int, nbytes: int, reps: int = 5) -> float:
    """Median usec per wholly-native rootless broadcast of `nbytes`
    (initiation to full delivery; rlo_demo's nbcast floor line)."""
    rc = load().rlo_bench_bcast_usec(world_size, nbytes, reps)
    if rc < 0:
        raise RuntimeError(f"native bcast bench failed ({int(rc)})")
    return float(rc)


def now_usec() -> int:
    return load().rlo_now_usec()


# -- native tracing (twin of rlo_tpu.utils.tracing) --------------------------

def trace_set(enabled: bool) -> None:
    load().rlo_trace_set(1 if enabled else 0)


def trace_clear() -> None:
    load().rlo_trace_clear()


def trace_dropped() -> int:
    return load().rlo_trace_dropped()


def trace_capacity() -> int:
    return load().rlo_trace_capacity()


def trace_emit(rank: int, kind: int, a: int = 0, b: int = 0,
               c: int = 0, d: int = 0) -> None:
    """Emit one event into the native ring (test support — the C
    engine emits its own protocol events)."""
    load().rlo_trace_emit(rank, int(kind), a, b, c, d)


def trace_drain(max_events: int = 65536):
    """Drain native trace events as dicts matching Event.to_dict() —
    the per-rank dump schema rlo_tpu/utils/timeline.py merges."""
    from rlo_tpu.utils.tracing import Ev
    buf = (_TraceEvent * max_events)()
    n = load().rlo_trace_drain(buf, max_events)
    return [{"ts_usec": buf[i].ts_usec, "rank": buf[i].rank,
             "kind": Ev(buf[i].kind).name, "a": buf[i].a, "b": buf[i].b,
             "c": buf[i].c, "d": buf[i].d}
            for i in range(n)]
