/* Standalone native self-test (the reference's `demo` binary analogue,
 * testcases.c:742-780). Built by `make selftest`, intended to run under
 * AddressSanitizer to prove the core is leak- and UAF-free:
 *   make selftest && ./rlo_selftest
 * Exercises bcast fan-out, latency fuzz, IAR consensus (approve + veto +
 * concurrent proposers), multi-comm multiplexing, and full teardown.
 */
#define _POSIX_C_SOURCE 200112L /* setenv/unsetenv under -std=c11 */
#include "rlo_core.h"

#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

static int failures;

#define CHECK(cond)                                                        \
    do {                                                                   \
        if (!(cond)) {                                                     \
            fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,        \
                    #cond);                                                \
            failures++;                                                    \
        }                                                                  \
    } while (0)

static int judge_veto(const uint8_t *p, int64_t n, void *ctx)
{
    (void)p;
    (void)n;
    return *(int *)ctx ? 0 : 1;
}

static void action_count(const uint8_t *p, int64_t n, void *ctx)
{
    (void)p;
    (void)n;
    (*(int *)ctx)++;
}

static void test_bcast(int ws, int latency)
{
    rlo_world *w = rlo_world_new(ws, latency, 42);
    rlo_engine *e[64];
    for (int r = 0; r < ws; r++)
        e[r] = rlo_engine_new(w, r, 0, 0, 0, 0, 0, 0);
    for (int r = 0; r < ws; r++) {
        char buf[32];
        int n = snprintf(buf, sizeof buf, "from-%d", r);
        CHECK(rlo_bcast(e[r], (const uint8_t *)buf, n) == RLO_OK);
    }
    CHECK(rlo_drain(w, 100000) >= 0);
    for (int r = 0; r < ws; r++) {
        uint8_t buf[64];
        int tag, origin, pid, vote, got = 0;
        while (rlo_pickup_next(e[r], &tag, &origin, &pid, &vote, buf,
                               sizeof buf) >= 0)
            got++;
        CHECK(got == ws - 1);
        CHECK(rlo_engine_err(e[r]) == RLO_OK);
    }
    for (int r = 0; r < ws; r++)
        rlo_engine_free(e[r]);
    rlo_world_free(w);
}

static void test_iar(int ws, int veto_rank, int expect)
{
    rlo_world *w = rlo_world_new(ws, 2, 7);
    rlo_engine *e[64];
    int veto[64] = {0}, actions[64] = {0};
    if (veto_rank >= 0)
        veto[veto_rank] = 1;
    for (int r = 0; r < ws; r++)
        e[r] = rlo_engine_new(w, r, 0, judge_veto, &veto[r], action_count,
                              &actions[r], 0);
    int rc = rlo_submit_proposal(e[0], (const uint8_t *)"prop", 4, 0);
    CHECK(rc == -1 || rc == expect);
    CHECK(rlo_drain(w, 100000) >= 0);
    CHECK(rlo_vote_my_proposal(e[0]) == expect);
    for (int r = 1; r < ws; r++)
        CHECK(actions[r] == (expect && r != veto_rank ? 1 : 0) ||
              /* veto rank never forwards, so it never acts */
              (r == veto_rank && actions[r] == 0));
    for (int r = 0; r < ws; r++)
        rlo_engine_free(e[r]);
    rlo_world_free(w);
}

static void test_concurrent_proposers(int ws)
{
    rlo_world *w = rlo_world_new(ws, 3, 13);
    rlo_engine *e[64];
    for (int r = 0; r < ws; r++)
        e[r] = rlo_engine_new(w, r, 0, 0, 0, 0, 0, 0);
    CHECK(rlo_submit_proposal(e[0], (const uint8_t *)"A", 1, 0) >= -1);
    CHECK(rlo_submit_proposal(e[ws / 2], (const uint8_t *)"B", 1, ws / 2) >=
          -1);
    CHECK(rlo_drain(w, 100000) >= 0);
    CHECK(rlo_vote_my_proposal(e[0]) == 1);
    CHECK(rlo_vote_my_proposal(e[ws / 2]) == 1);
    for (int r = 0; r < ws; r++)
        CHECK(rlo_engine_err(e[r]) == RLO_OK);
    for (int r = 0; r < ws; r++)
        rlo_engine_free(e[r]);
    rlo_world_free(w);
}

static void test_multiplex(void)
{
    int ws = 8;
    rlo_world *w = rlo_world_new(ws, 1, 5);
    rlo_engine *a[8], *b[8];
    for (int r = 0; r < ws; r++) {
        a[r] = rlo_engine_new(w, r, 0, 0, 0, 0, 0, 0);
        b[r] = rlo_engine_new(w, r, 1, 0, 0, 0, 0, 0);
    }
    CHECK(rlo_bcast(a[0], (const uint8_t *)"comm0", 5) == RLO_OK);
    CHECK(rlo_bcast(b[1], (const uint8_t *)"comm1", 5) == RLO_OK);
    CHECK(rlo_drain(w, 100000) >= 0);
    for (int r = 0; r < ws; r++) {
        uint8_t buf[32];
        int tag, origin, pid, vote;
        int na = 0, nb_ = 0;
        while (rlo_pickup_next(a[r], &tag, &origin, &pid, &vote, buf,
                               sizeof buf) >= 0) {
            CHECK(memcmp(buf, "comm0", 5) == 0);
            na++;
        }
        while (rlo_pickup_next(b[r], &tag, &origin, &pid, &vote, buf,
                               sizeof buf) >= 0) {
            CHECK(memcmp(buf, "comm1", 5) == 0);
            nb_++;
        }
        CHECK(na == (r == 0 ? 0 : 1));
        CHECK(nb_ == (r == 1 ? 0 : 1));
    }
    for (int r = 0; r < ws; r++) {
        rlo_engine_free(a[r]);
        rlo_engine_free(b[r]);
    }
    rlo_world_free(w);
}

/* teardown with undelivered traffic still queued: engine/world frees must
 * reclaim everything (ASan would flag leaks) */
static void test_dirty_teardown(void)
{
    rlo_world *w = rlo_world_new(8, 50, 3);
    rlo_engine *e[8];
    for (int r = 0; r < 8; r++)
        e[r] = rlo_engine_new(w, r, 0, 0, 0, 0, 0, 0);
    for (int r = 0; r < 8; r++)
        rlo_bcast(e[r], (const uint8_t *)"junk", 4);
    /* progress a little but do NOT drain or pick up */
    for (int i = 0; i < 3; i++)
        rlo_progress_all(w);
    for (int r = 0; r < 8; r++)
        rlo_engine_free(e[r]);
    rlo_world_free(w);
}

/* Failure detection + elastic recovery: kill a rank, let heartbeat
 * timeouts detect it, then verify broadcast and consensus still work
 * among the survivors on the re-formed overlay (mirror of
 * tests/test_failure.py on the Python engine). Uses real
 * timeouts, sized generously (200 ms) so CPU contention — other tier-1
 * tests, ASan overhead — cannot starve a heartbeat into a false
 * positive. */
static void test_elastic_recovery(int ws, int victim)
{
    rlo_world *w = rlo_world_new(ws, 0, 0);
    CHECK(w);
    rlo_engine *e[64];
    for (int r = 0; r < ws; r++) {
        e[r] = rlo_engine_new(w, r, 0, 0, 0, 0, 0, 0);
        CHECK(e[r]);
        CHECK(rlo_engine_enable_failure_detection(
                  e[r], 200 * 1000, 40 * 1000) == RLO_OK);
    }
    /* settle heartbeats */
    uint64_t t0 = rlo_now_usec();
    while (rlo_now_usec() - t0 < 300 * 1000)
        rlo_progress_all(w);
    /* crash the victim */
    CHECK(rlo_world_kill_rank(w, victim) == RLO_OK);
    rlo_engine_free(e[victim]);
    /* every survivor must learn of the failure */
    t0 = rlo_now_usec();
    int all = 0;
    while (!all && rlo_now_usec() - t0 < 8 * 1000 * 1000) {
        rlo_progress_all(w);
        all = 1;
        for (int r = 0; r < ws; r++)
            if (r != victim && !rlo_engine_rank_failed(e[r], victim))
                all = 0;
    }
    CHECK(all);
    if (!all)
        goto out;
    /* flush FAILURE notices */
    CHECK(rlo_drain(w, 10000000) >= 0);
    for (int r = 0; r < ws; r++) {
        if (r == victim)
            continue;
        uint8_t buf[64];
        while (rlo_pickup_next(e[r], 0, 0, 0, 0, buf, sizeof buf) >= 0)
            ;
    }
    /* elastic bcast: one delivery per survivor */
    int origin = victim == 0 ? 1 : 0;
    CHECK(rlo_bcast(e[origin], (const uint8_t *)"x", 1) == RLO_OK);
    CHECK(rlo_drain(w, 10000000) >= 0);
    for (int r = 0; r < ws; r++) {
        if (r == victim || r == origin)
            continue;
        uint8_t buf[64];
        int got = 0;
        while (rlo_pickup_next(e[r], 0, 0, 0, 0, buf, sizeof buf) >= 0)
            got++;
        CHECK(got == 1);
    }
    /* elastic consensus among survivors */
    int rc = rlo_submit_proposal(e[origin], (const uint8_t *)"p", 1, 77);
    t0 = rlo_now_usec();
    while (rc == -1 && rlo_now_usec() - t0 < 8 * 1000 * 1000) {
        rlo_progress_all(w);
        rc = rlo_vote_my_proposal(e[origin]);
    }
    CHECK(rc == 1);
    CHECK(rlo_drain(w, 10000000) >= 0);
out:
    for (int r = 0; r < ws; r++)
        if (r != victim)
            rlo_engine_free(e[r]);
    rlo_world_free(w);
}

/* A voter dies mid-consensus: the proposer must discount the dead
 * subtree and complete instead of waiting forever. */
static void test_mid_round_voter_death(int ws, int victim)
{
    rlo_world *w = rlo_world_new(ws, 0, 0);
    CHECK(w);
    rlo_engine *e[64];
    for (int r = 0; r < ws; r++) {
        e[r] = rlo_engine_new(w, r, 0, 0, 0, 0, 0, 0);
        CHECK(rlo_engine_enable_failure_detection(
                  e[r], 200 * 1000, 40 * 1000) == RLO_OK);
    }
    uint64_t t0 = rlo_now_usec();
    while (rlo_now_usec() - t0 < 300 * 1000)
        rlo_progress_all(w);
    /* kill BEFORE proposing, before detection: the proposal still
     * counts the dead subtree */
    CHECK(rlo_world_kill_rank(w, victim) == RLO_OK);
    rlo_engine_free(e[victim]);
    int rc = rlo_submit_proposal(e[0], (const uint8_t *)"m", 1, 3);
    t0 = rlo_now_usec();
    while (rc == -1 && rlo_now_usec() - t0 < 8 * 1000 * 1000) {
        rlo_progress_all(w);
        rc = rlo_vote_my_proposal(e[0]);
    }
    CHECK(rc == 1);
    CHECK(rlo_drain(w, 10000000) >= 0);
    for (int r = 0; r < ws; r++)
        if (r != victim)
            rlo_engine_free(e[r]);
    rlo_world_free(w);
}

/* A proposal with zero awaited voters (everyone else died) completes
 * immediately instead of polling -1 forever. */
static void test_sole_survivor_consensus(void)
{
    rlo_world *w = rlo_world_new(2, 0, 0);
    CHECK(w);
    rlo_engine *e0 = rlo_engine_new(w, 0, 0, 0, 0, 0, 0, 0);
    rlo_engine *e1 = rlo_engine_new(w, 1, 0, 0, 0, 0, 0, 0);
    CHECK(rlo_engine_enable_failure_detection(e0, 200 * 1000, 40 * 1000) ==
          RLO_OK);
    CHECK(rlo_engine_enable_failure_detection(e1, 200 * 1000, 40 * 1000) ==
          RLO_OK);
    uint64_t t0 = rlo_now_usec();
    while (rlo_now_usec() - t0 < 300 * 1000)
        rlo_progress_all(w);
    CHECK(rlo_world_kill_rank(w, 1) == RLO_OK);
    rlo_engine_free(e1);
    t0 = rlo_now_usec();
    while (!rlo_engine_rank_failed(e0, 1) &&
           rlo_now_usec() - t0 < 8 * 1000 * 1000)
        rlo_progress_all(w);
    CHECK(rlo_engine_rank_failed(e0, 1));
    int rc = rlo_submit_proposal(e0, (const uint8_t *)"s", 1, 5);
    t0 = rlo_now_usec();
    while (rc == -1 && rlo_now_usec() - t0 < 1000 * 1000) {
        rlo_progress_all(w);
        rc = rlo_vote_my_proposal(e0);
    }
    CHECK(rc == 1);
    rlo_engine_free(e0);
    rlo_world_free(w);
}

/* A pid may be reused by a LATER proposer (only concurrent collisions
 * are forbidden): a completed own round must not swallow the relayed
 * round's votes. Regression for a review-caught deadlock. */
static void test_pid_reuse_across_rounds(int ws)
{
    rlo_world *w = rlo_world_new(ws, 0, 0);
    CHECK(w);
    rlo_engine *e[64];
    for (int r = 0; r < ws; r++)
        e[r] = rlo_engine_new(w, r, 0, 0, 0, 0, 0, 0);
    for (int proposer = 0; proposer < ws; proposer++) {
        int rc = rlo_submit_proposal(e[proposer],
                                     (const uint8_t *)"r", 1, 7);
        for (long i = 0; rc == -1 && i < 100000; i++) {
            rlo_progress_all(w);
            rc = rlo_vote_my_proposal(e[proposer]);
        }
        CHECK(rc == 1);
        CHECK(rlo_drain(w, 10000000) >= 0);
        /* deliberately NO proposal_reset: past proposers keep pid 7 in
         * their completed own state — the exact swallow condition */
        uint8_t buf[64];
        for (int r = 0; r < ws; r++)
            while (rlo_pickup_next(e[r], 0, 0, 0, 0, buf,
                                   sizeof buf) >= 0)
                ;
    }
    for (int r = 0; r < ws; r++) {
        CHECK(rlo_engine_err(e[r]) == RLO_OK);
        rlo_engine_free(e[r]);
    }
    rlo_world_free(w);
}

/* ring data collectives (rlo_coll.c) under the sanitizers: allreduce /
 * reduce-scatter / all-gather / all-to-all / barrier, round-robin
 * driven in-process, with numeric oracles and back-to-back reuse */
static void test_coll(int ws)
{
    rlo_world *w = rlo_world_new(ws, 0, 0);
    CHECK(w);
    rlo_coll **c = (rlo_coll **)calloc((size_t)ws, sizeof(void *));
    float **buf = (float **)calloc((size_t)ws, sizeof(void *));
    const int64_t n = 37; /* ragged: forces identity padding */
    for (int r = 0; r < ws; r++) {
        c[r] = rlo_coll_new(w, r, 7);
        buf[r] = (float *)malloc((size_t)n * sizeof(float));
        CHECK(c[r] && buf[r]);
    }

#define DRIVE()                                                            \
    do {                                                                   \
        int done = 0;                                                      \
        for (long spin = 0; done < ws && spin < 10000000L; spin++) {       \
            done = 0;                                                      \
            for (int r = 0; r < ws; r++) {                                 \
                int pr = rlo_coll_poll(c[r]);                              \
                if (pr == 1 || pr == RLO_ERR_ARG)                          \
                    done++;                                                \
                else                                                       \
                    CHECK(pr >= 0);                                        \
            }                                                              \
        }                                                                  \
        CHECK(done == ws);                                                 \
    } while (0)

    for (int round = 0; round < 2; round++) { /* opid reuse */
        for (int r = 0; r < ws; r++) {
            for (int64_t i = 0; i < n; i++)
                buf[r][i] = (float)((r + 1) * (i + 1 + round));
            CHECK(rlo_coll_allreduce_f32_start(c[r], buf[r], n,
                                               RLO_COLL_SUM) == RLO_OK);
        }
        DRIVE();
        float want = (float)(ws * (ws + 1) / 2 * (1 + round));
        for (int r = 0; r < ws; r++)
            CHECK(buf[r][0] == want);
    }

    /* reduce-scatter: chunks reassemble to the full reduction */
    int64_t chunk = (n + ws - 1) / ws;
    float **rs = (float **)calloc((size_t)ws, sizeof(void *));
    for (int r = 0; r < ws; r++) {
        rs[r] = (float *)malloc((size_t)chunk * sizeof(float));
        for (int64_t i = 0; i < n; i++)
            buf[r][i] = (float)(r + 1);
        CHECK(rs[r] && rlo_coll_reduce_scatter_f32_start(
                           c[r], buf[r], n, rs[r],
                           RLO_COLL_SUM) == RLO_OK);
    }
    DRIVE();
    for (int r = 0; r < ws; r++)
        if ((int64_t)r * chunk < n)
            CHECK(rs[r][0] == (float)(ws * (ws + 1) / 2));

    /* all-gather + all-to-all on byte slots */
    uint8_t *slot = (uint8_t *)malloc(4);
    uint8_t **ag = (uint8_t **)calloc((size_t)ws, sizeof(void *));
    uint8_t **a2a_in = (uint8_t **)calloc((size_t)ws, sizeof(void *));
    uint8_t **a2a_out = (uint8_t **)calloc((size_t)ws, sizeof(void *));
    for (int r = 0; r < ws; r++) {
        memset(slot, r, 4);
        ag[r] = (uint8_t *)malloc((size_t)(4 * ws));
        CHECK(ag[r] && rlo_coll_all_gather_start(c[r], slot, 4,
                                                 ag[r]) == RLO_OK);
    }
    DRIVE();
    for (int r = 0; r < ws; r++)
        for (int s = 0; s < ws; s++)
            CHECK(ag[r][s * 4] == (uint8_t)s);
    for (int r = 0; r < ws; r++) {
        a2a_in[r] = (uint8_t *)malloc((size_t)(2 * ws));
        a2a_out[r] = (uint8_t *)malloc((size_t)(2 * ws));
        CHECK(a2a_in[r] && a2a_out[r]);
        for (int d = 0; d < ws; d++) {
            a2a_in[r][2 * d] = (uint8_t)(r * 8 + d);
            a2a_in[r][2 * d + 1] = (uint8_t)(r ^ d);
        }
        CHECK(rlo_coll_all_to_all_start(c[r], a2a_in[r], 2,
                                        a2a_out[r]) == RLO_OK);
    }
    DRIVE();
    for (int d = 0; d < ws; d++)
        for (int s = 0; s < ws; s++) {
            CHECK(a2a_out[d][2 * s] == (uint8_t)(s * 8 + d));
            CHECK(a2a_out[d][2 * s + 1] == (uint8_t)(s ^ d));
        }

    for (int r = 0; r < ws; r++)
        CHECK(rlo_coll_barrier_start(c[r]) == RLO_OK);
    DRIVE();
#undef DRIVE

    CHECK(rlo_world_quiescent(w));
    for (int r = 0; r < ws; r++) {
        rlo_coll_free(c[r]);
        free(buf[r]);
        free(rs[r]);
        free(ag[r]);
        free(a2a_in[r]);
        free(a2a_out[r]);
    }
    free(c);
    free(buf);
    free(rs);
    free(ag);
    free(a2a_in);
    free(a2a_out);
    free(slot);
    rlo_world_free(w);
}

/* Round-3: ring data collectives over a rank subset, interleaved with
 * a full-world context on another comm (ASan leg of rlo_coll_new_sub:
 * virtual-ring endpoints, subset slot layouts). */
static void test_coll_sub(void)
{
    int ws = 8;
    static const int members[3] = {1, 4, 6};
    int n_m = 3;
    rlo_world *w = rlo_world_new(ws, 0, 0);
    CHECK(w != 0);
    rlo_coll *cs[3];
    rlo_coll *cf[8];
    float bufs[3][10], buff[8][10];
    const int64_t n = 10;
    for (int i = 0; i < n_m; i++) {
        cs[i] = rlo_coll_new_sub(w, members[i], 70, members, n_m);
        CHECK(cs[i] != 0);
    }
    CHECK(!rlo_coll_new_sub(w, 0, 70, members, n_m)); /* non-member */
    for (int r = 0; r < ws; r++) {
        cf[r] = rlo_coll_new(w, r, 71);
        CHECK(cf[r] != 0);
    }
    for (int i = 0; i < n_m; i++) {
        for (int64_t j = 0; j < n; j++)
            bufs[i][j] = (float)(members[i] + 1);
        CHECK(rlo_coll_allreduce_f32_start(cs[i], bufs[i], n,
                                           RLO_COLL_SUM) == RLO_OK);
    }
    for (int r = 0; r < ws; r++) {
        for (int64_t j = 0; j < n; j++)
            buff[r][j] = 1.0f;
        CHECK(rlo_coll_allreduce_f32_start(cf[r], buff[r], n,
                                           RLO_COLL_SUM) == RLO_OK);
    }
    int done = 0;
    for (long spin = 0; done < n_m + ws && spin < 10000000L; spin++) {
        done = 0;
        for (int i = 0; i < n_m; i++)
            if (rlo_coll_poll(cs[i]) == 1 ||
                rlo_coll_poll(cs[i]) == RLO_ERR_ARG)
                done++;
        for (int r = 0; r < ws; r++)
            if (rlo_coll_poll(cf[r]) == 1 ||
                rlo_coll_poll(cf[r]) == RLO_ERR_ARG)
                done++;
    }
    CHECK(done == n_m + ws);
    float want = 0;
    for (int i = 0; i < n_m; i++)
        want += (float)(members[i] + 1);
    for (int i = 0; i < n_m; i++)
        CHECK(bufs[i][0] == want && bufs[i][n - 1] == want);
    for (int r = 0; r < ws; r++)
        CHECK(buff[r][0] == (float)ws);
    for (int i = 0; i < n_m; i++)
        rlo_coll_free(cs[i]);
    for (int r = 0; r < ws; r++)
        rlo_coll_free(cf[r]);
    rlo_world_free(w);
}

static int judge_count(const uint8_t *p, int64_t n, void *ctx)
{
    (void)p;
    (void)n;
    (*(int *)ctx)++;
    return 1;
}

/* Round-3: engine over a rank subset (sub-communicator, comm 1) with a
 * full-world engine set running interleaved traffic on comm 0 — the
 * ASan leg of rlo_engine_new_sub (pytest covers semantics; this proves
 * the subset paths are leak/UAF-free). */
static void test_subcomm(void)
{
    int ws = 8;
    rlo_world *w = rlo_world_new(ws, 2, 21);
    int members[3] = {0, 2, 7};
    rlo_engine *ef[8];
    rlo_engine *es[8] = {0};
    int veto[8] = {0}, actions[8] = {0};
    veto[7] = 1;
    for (int r = 0; r < ws; r++)
        ef[r] = rlo_engine_new(w, r, 0, 0, 0, 0, 0, 0);
    for (int i = 0; i < 3; i++) {
        int r = members[i];
        es[r] = rlo_engine_new_sub(w, r, 1, members, 3, judge_veto,
                                   &veto[r], action_count, &actions[r],
                                   0);
        CHECK(es[r] != 0);
    }
    CHECK(!rlo_engine_new_sub(w, 1, 1, members, 3, 0, 0, 0, 0, 0));
    CHECK(rlo_bcast(ef[3], (const uint8_t *)"full", 4) == RLO_OK);
    CHECK(rlo_bcast(es[2], (const uint8_t *)"sub", 3) == RLO_OK);
    int rc = rlo_submit_proposal(es[0], (const uint8_t *)"p", 1, 0);
    CHECK(rc == -1 || rc == 0);
    CHECK(rlo_drain(w, 100000) >= 0);
    CHECK(rlo_vote_my_proposal(es[0]) == 0); /* rank 7's veto won */
    for (int r = 0; r < ws; r++) {
        uint8_t buf[64];
        int tag, origin, pid, vote, got = 0;
        while (rlo_pickup_next(ef[r], &tag, &origin, &pid, &vote, buf,
                               sizeof buf) >= 0)
            got++;
        CHECK(got == (r == 3 ? 0 : 1)); /* full bcast scope */
        CHECK(rlo_engine_err(ef[r]) == RLO_OK);
    }
    for (int i = 0; i < 3; i++) {
        int r = members[i];
        uint8_t buf[64];
        int tag, origin, pid, vote, got_b = 0, got_d = 0;
        while (rlo_pickup_next(es[r], &tag, &origin, &pid, &vote, buf,
                               sizeof buf) >= 0) {
            if (tag == RLO_TAG_BCAST)
                got_b++;
            else if (tag == RLO_TAG_IAR_DECISION)
                got_d++;
        }
        CHECK(got_b == (r == 2 ? 0 : 1)); /* subset bcast scope */
        CHECK(got_d == (r == 0 ? 0 : 1)); /* declined decision */
        CHECK(rlo_engine_err(es[r]) == RLO_OK);
    }
    for (int r = 0; r < ws; r++) {
        rlo_engine_free(ef[r]);
        if (es[r])
            rlo_engine_free(es[r]);
    }
    rlo_world_free(w);
}

/* Round-3: deferred duplicate-parent vote — a relay with child votes
 * outstanding records a duplicate's sender instead of voting an
 * interim verdict; the round resolves when the (vetoing) child votes
 * arrive, and teardown with a still-parked neighbor round leaks
 * nothing (ASan leg of dup_parents/resolve_relay + the parked decline
 * path). */
static void test_deferred_dup_vote(void)
{
    int ws = 8;
    rlo_world *w = rlo_world_new(ws, 0, 3);
    int judged = 0;
    rlo_engine *e = rlo_engine_new(w, 2, 0, judge_count, &judged, 0, 0,
                                   0);
    CHECK(e != 0);
    int kids[8];
    int n_kids = rlo_fwd_targets(ws, 2, 0, 0, kids, 8);
    CHECK(n_kids >= 1); /* scenario needs an outstanding child */
    uint8_t frame[64];
    int64_t n = rlo_frame_encode(frame, sizeof frame, 0, 5, 777, -1,
                                 (const uint8_t *)"p", 1);
    CHECK(n > 0);
    CHECK(rlo_world_inject(w, 0, 2, 0, RLO_TAG_IAR_PROPOSAL, frame,
                           n) == RLO_OK);
    for (int i = 0; i < 200; i++)
        rlo_progress_all(w);
    CHECK(judged == 1);
    /* duplicate from a re-formed-tree parent: recorded, not answered */
    CHECK(rlo_world_inject(w, 6, 2, 0, RLO_TAG_IAR_PROPOSAL, frame,
                           n) == RLO_OK);
    for (int i = 0; i < 200; i++)
        rlo_progress_all(w);
    CHECK(judged == 1); /* never re-judged */
    /* child votes (gen 777 echoed LE32); the last one vetoes */
    uint8_t genb[4] = {(uint8_t)(777 & 0xff), (uint8_t)(777 >> 8), 0, 0};
    for (int i = 0; i < n_kids; i++) {
        uint8_t vf[64];
        int64_t vn = rlo_frame_encode(vf, sizeof vf, kids[i], 5,
                                      i == n_kids - 1 ? 0 : 1, -1, genb,
                                      4);
        CHECK(vn > 0);
        CHECK(rlo_world_inject(w, kids[i], 2, 0, RLO_TAG_IAR_VOTE, vf,
                               vn) == RLO_OK);
    }
    for (int i = 0; i < 400; i++)
        rlo_progress_all(w);
    CHECK(judged == 1);
    CHECK(rlo_engine_err(e) == RLO_OK);
    rlo_engine_free(e); /* still parked (no decision): must not leak */
    rlo_world_free(w);
}

/* ARQ: a dropped frame retransmits until delivered; a duplicated frame
 * delivers exactly once. Exercises the full ack/retransmit/dedup state
 * machine under the sanitizers (mirror of tests/test_reliability.py). */
static void test_arq_loss_and_dup(int ws)
{
    rlo_world *w = rlo_world_new(ws, 0, 11);
    CHECK(w);
    rlo_engine *e[64];
    for (int r = 0; r < ws; r++) {
        e[r] = rlo_engine_new(w, r, 0, 0, 0, 0, 0, 0);
        CHECK(rlo_engine_enable_arq(e[r], 500, 12) == RLO_OK);
    }
    /* drop the first two frames rank 0 sends to EVERY target, and
     * duplicate the next three frames on a couple of edges */
    for (int dst = 1; dst < ws; dst++)
        CHECK(rlo_world_drop_next(w, 0, dst, 2) == RLO_OK);
    CHECK(rlo_world_dup_next(w, 1, 0, 3) == RLO_OK);
    CHECK(rlo_world_dup_next(w, 0, 1, 3) == RLO_OK);
    for (int i = 0; i < 3; i++) {
        char buf[16];
        int n = snprintf(buf, sizeof buf, "m%d", i);
        CHECK(rlo_bcast(e[0], (const uint8_t *)buf, n) == RLO_OK);
    }
    /* drain spins until retransmits fill the holes and acks clear the
     * queues (rto 500 usec; real time) */
    CHECK(rlo_drain(w, 100000000) >= 0);
    int64_t retx = 0, dups = 0;
    for (int r = 0; r < ws; r++) {
        uint8_t buf[64];
        int got = 0;
        while (rlo_pickup_next(e[r], 0, 0, 0, 0, buf, sizeof buf) >= 0)
            got++;
        CHECK(got == (r == 0 ? 0 : 3)); /* exactly once each */
        CHECK(rlo_engine_err(e[r]) == RLO_OK);
        CHECK(rlo_engine_arq_unacked(e[r]) == 0);
        retx += rlo_engine_arq_retransmits(e[r]);
        dups += rlo_engine_arq_dup_drops(e[r]);
    }
    CHECK(retx >= 2);  /* the dropped frames really were retransmitted */
    CHECK(dups >= 3);  /* the injected duplicates really were dropped */
    for (int r = 0; r < ws; r++)
        rlo_engine_free(e[r]);
    rlo_world_free(w);
}

/* ARQ + IAR: a dropped VOTE frame no longer wedges the consensus round
 * (the acceptance scenario of the reliability issue). */
static void test_arq_dropped_vote(int ws)
{
    rlo_world *w = rlo_world_new(ws, 0, 17);
    CHECK(w);
    rlo_engine *e[64];
    for (int r = 0; r < ws; r++) {
        e[r] = rlo_engine_new(w, r, 0, 0, 0, 0, 0, 0);
        CHECK(rlo_engine_enable_arq(e[r], 500, 12) == RLO_OK);
    }
    /* rank 1 is a leaf child of rank 0's tree for every pow2-ish ws we
     * use; drop its first frame back to 0 — the vote */
    CHECK(rlo_world_drop_next(w, 1, 0, 1) == RLO_OK);
    int rc = rlo_submit_proposal(e[0], (const uint8_t *)"p", 1, 9);
    uint64_t t0 = rlo_now_usec();
    while (rc == -1 && rlo_now_usec() - t0 < 5 * 1000 * 1000) {
        rlo_progress_all(w);
        rc = rlo_vote_my_proposal(e[0]);
    }
    CHECK(rc == 1); /* completed despite the dropped vote */
    CHECK(rlo_drain(w, 100000000) >= 0);
    for (int r = 0; r < ws; r++) {
        CHECK(rlo_engine_err(e[r]) == RLO_OK);
        rlo_engine_free(e[r]);
    }
    rlo_world_free(w);
}

/* S13 batched progress: the same seeded workload driven one sweep per
 * call (rlo_progress_all) and batched (rlo_world_progress_all_n) must
 * produce byte-identical delivery order and identical engine counters
 * — batching changes how often the driver crosses into C, never what
 * the engines do. ARQ + metrics enabled so the ack/dedup machinery is
 * in the compared state. */
static void drive_parity_workload(int batched, rlo_stats *stats,
                                  int *order, int *order_n, int cap)
{
    int ws = 8;
    rlo_world *w = rlo_world_new(ws, 0, 77);
    CHECK(w);
    rlo_engine *e[8];
    for (int r = 0; r < ws; r++) {
        e[r] = rlo_engine_new(w, r, 0, 0, 0, 0, 0, 0);
        CHECK(e[r]);
        CHECK(rlo_engine_enable_arq(e[r], 60 * 1000 * 1000, 4) ==
              RLO_OK);
        CHECK(rlo_engine_enable_metrics(e[r], 1) == RLO_OK);
    }
    *order_n = 0;
    for (int round = 0; round < 4; round++) {
        for (int r = 0; r < ws; r++) {
            char msg[32];
            int n = snprintf(msg, sizeof msg, "r%d-%d", round, r);
            CHECK(rlo_bcast(e[r], (const uint8_t *)msg, n) == RLO_OK);
        }
        if (batched) {
            /* one crossing: sweeps until fruitless + quiescent */
            CHECK(rlo_world_progress_all_n(w, 0, 0) >= 0);
        } else {
            for (int i = 0; i < 100000 && !rlo_world_quiescent(w); i++)
                rlo_progress_all(w);
        }
        /* both modes settle the ack tail with the same sweep shape */
        CHECK(rlo_drain(w, 100000) >= 0);
        for (int r = 0; r < ws; r++) {
            uint8_t buf[64];
            int tag, origin, pid, vote;
            while (rlo_pickup_next(e[r], &tag, &origin, &pid, &vote,
                                   buf, sizeof buf) >= 0) {
                CHECK(*order_n < cap);
                if (*order_n < cap)
                    order[(*order_n)++] = (r << 8) | origin;
            }
        }
    }
    for (int r = 0; r < ws; r++) {
        CHECK(rlo_engine_stats(e[r], &stats[r]) == RLO_OK);
        CHECK(rlo_engine_err(e[r]) == RLO_OK);
        rlo_engine_free(e[r]);
    }
    rlo_world_free(w);
}

static void test_batched_parity(void)
{
    enum { CAP = 512 };
    static rlo_stats st_a[8], st_b[8];
    static int ord_a[CAP], ord_b[CAP];
    int na = 0, nb_ = 0;
    drive_parity_workload(0, st_a, ord_a, &na, CAP);
    drive_parity_workload(1, st_b, ord_b, &nb_, CAP);
    CHECK(na == nb_ && na == 4 * 8 * 7);
    CHECK(memcmp(ord_a, ord_b, (size_t)na * sizeof(int)) == 0);
    for (int r = 0; r < 8; r++) {
        CHECK(st_a[r].sent_bcast == st_b[r].sent_bcast);
        CHECK(st_a[r].recved_bcast == st_b[r].recved_bcast);
        CHECK(st_a[r].total_pickup == st_b[r].total_pickup);
        CHECK(st_a[r].arq_retransmits == st_b[r].arq_retransmits);
        CHECK(st_a[r].arq_dup_drops == st_b[r].arq_dup_drops);
        CHECK(st_a[r].arq_unacked == 0 && st_b[r].arq_unacked == 0);
    }
}

/* S13 frame budget: a budget of 1 processes exactly one frame per
 * call and the remainder survives in FIFO order — repeated budgeted
 * calls converge to the unbudgeted result. */
static void test_progress_budget(void)
{
    int ws = 8;
    rlo_world *w = rlo_world_new(ws, 0, 5);
    CHECK(w);
    rlo_engine *e[8];
    for (int r = 0; r < ws; r++)
        e[r] = rlo_engine_new(w, r, 0, 0, 0, 0, 0, 0);
    CHECK(rlo_bcast(e[0], (const uint8_t *)"b", 1) == RLO_OK);
    /* note rlo_bcast already progressed once; whatever remains must
     * arrive one frame per call */
    int64_t total = 0;
    for (int i = 0; i < 10000 && !rlo_world_quiescent(w); i++) {
        int64_t got = rlo_world_progress_all_n(w, 1, 0);
        CHECK(got >= 0 && got <= 1);
        total += got;
    }
    CHECK(rlo_world_quiescent(w));
    for (int r = 1; r < ws; r++) {
        uint8_t buf[16];
        int got = 0;
        while (rlo_pickup_next(e[r], 0, 0, 0, 0, buf, sizeof buf) >= 0)
            got++;
        CHECK(got == 1);
    }
    for (int r = 0; r < ws; r++)
        rlo_engine_free(e[r]);
    rlo_world_free(w);
}

/* S13 due-heap: with a long rto and no loss, every post-traffic tick
 * is gated on the O(1) heap peek; with loss injected, retransmits
 * still fire exactly as before (the gate wakes at the deadline). */
static void test_arq_due_heap(void)
{
    int ws = 4;
    rlo_world *w = rlo_world_new(ws, 0, 23);
    CHECK(w);
    rlo_engine *e[4];
    for (int r = 0; r < ws; r++) {
        e[r] = rlo_engine_new(w, r, 0, 0, 0, 0, 0, 0);
        CHECK(rlo_engine_enable_arq(e[r], 500, 12) == RLO_OK);
    }
    CHECK(rlo_world_drop_next(w, 0, 1, 1) == RLO_OK);
    CHECK(rlo_bcast(e[0], (const uint8_t *)"x", 1) == RLO_OK);
    CHECK(rlo_drain(w, 100000000) >= 0);
    int64_t retx = 0;
    for (int r = 0; r < ws; r++)
        retx += rlo_engine_arq_retransmits(e[r]);
    CHECK(retx >= 1); /* the dropped frame really was retransmitted */
    uint8_t buf[16];
    for (int r = 1; r < ws; r++) {
        int got = 0;
        while (rlo_pickup_next(e[r], 0, 0, 0, 0, buf, sizeof buf) >= 0)
            got++;
        CHECK(got == 1); /* exactly once despite the loss */
    }
    /* idle ticks now ride the O(1) gate (stale entries may cost a few
     * sweeps first; the gate must engage once they expire) */
    int64_t gated0 = rlo_engine_arq_scan_gated(e[0]);
    CHECK(rlo_bcast(e[0], (const uint8_t *)"y", 1) == RLO_OK);
    CHECK(rlo_drain(w, 100000000) >= 0);
    for (int i = 0; i < 50; i++)
        rlo_progress_all(w);
    CHECK(rlo_engine_arq_scan_gated(e[0]) > gated0);
    for (int r = 0; r < ws; r++) {
        CHECK(rlo_engine_err(e[r]) == RLO_OK);
        rlo_engine_free(e[r]);
    }
    rlo_world_free(w);
}

/* S13 TSan leg: two threads, each driving ITS OWN world through the
 * batched entry points concurrently — proves rlo_engine_progress_n /
 * rlo_world_progress_all_n touch no hidden shared state (the one
 * process-global, the trace ring, stays branch-guarded off). Each
 * thread reports failures through its own slot; main CHECKs after
 * joining so the shared failure counter is never raced. */
static void *progress_n_thread_body(void *arg)
{
    int *fails = (int *)arg;
    int ws = 4;
    rlo_world *w = rlo_world_new(ws, 0, 31);
    if (!w) {
        (*fails)++;
        return 0;
    }
    rlo_engine *e[4];
    for (int r = 0; r < ws; r++) {
        e[r] = rlo_engine_new(w, r, 0, 0, 0, 0, 0, 0);
        if (!e[r] || rlo_engine_enable_arq(e[r], 60 * 1000 * 1000, 4)
                         != RLO_OK)
            (*fails)++;
    }
    for (int round = 0; round < 10; round++) {
        for (int r = 0; r < ws; r++)
            if (rlo_bcast(e[r], (const uint8_t *)"t", 1) != RLO_OK)
                (*fails)++;
        if (rlo_world_progress_all_n(w, 0, 0) < 0)
            (*fails)++;
        /* engine-level batched face, with a short poll-wait deadline */
        if (rlo_engine_progress_n(e[0], 0, 200) < 0)
            (*fails)++;
    }
    if (rlo_drain(w, 10000000) < 0)
        (*fails)++;
    for (int r = 0; r < ws; r++) {
        uint8_t buf[16];
        int got = 0;
        while (rlo_pickup_next(e[r], 0, 0, 0, 0, buf, sizeof buf) >= 0)
            got++;
        if (got != 10 * (ws - 1))
            (*fails)++;
        if (rlo_engine_err(e[r]) != RLO_OK)
            (*fails)++;
        rlo_engine_free(e[r]);
    }
    rlo_world_free(w);
    return 0;
}

static void test_progress_n_threads(void)
{
    pthread_t t[2];
    int fails[2] = {0, 0};
    CHECK(pthread_create(&t[0], 0, progress_n_thread_body,
                         &fails[0]) == 0);
    CHECK(pthread_create(&t[1], 0, progress_n_thread_body,
                         &fails[1]) == 0);
    pthread_join(t[0], 0);
    pthread_join(t[1], 0);
    CHECK(fails[0] == 0);
    CHECK(fails[1] == 0);
}

/* Same two-worlds-two-threads shape WITH TRACING ON: the trace ring is
 * the one piece of process-global mutable state the GIL-released
 * batched drivers share across worlds (rlo-sentinel S1, round 15 —
 * the ring is mutex-protected for exactly this shape).  Before the
 * fix this case was a guaranteed TSan report: both threads emit
 * BCAST_FWD/DELIVER events concurrently.  Run under TSan via the
 * `tsan` target like its untraced twin. */
static void test_progress_n_threads_traced(void)
{
    rlo_trace_clear();
    rlo_trace_set(1);
    pthread_t t[2];
    int fails[2] = {0, 0};
    CHECK(pthread_create(&t[0], 0, progress_n_thread_body,
                         &fails[0]) == 0);
    CHECK(pthread_create(&t[1], 0, progress_n_thread_body,
                         &fails[1]) == 0);
    pthread_join(t[0], 0);
    pthread_join(t[1], 0);
    rlo_trace_set(0);
    CHECK(fails[0] == 0);
    CHECK(fails[1] == 0);
    /* both threads' events landed in the shared ring (drained events +
     * overflow drops account for every emit; exact counts depend on
     * interleaving, presence is the contract) */
    rlo_trace_event ev[256];
    int drained = 0, got;
    while ((got = rlo_trace_drain(ev, 256)) > 0)
        drained += got;
    CHECK(drained + rlo_trace_dropped() > 0);
    rlo_trace_clear();
}

/* S13 writev coalescing + partial-write resume + zero-copy path: a
 * 2-rank TCP world with SO_SNDBUF shrunk to its floor, shipping
 * large ARQ-stamped frames (the isend_hdr gather path) interleaved
 * with small ones. Every flush is a short write, so the resume path
 * runs constantly; the child verifies size, content, and FIFO order
 * and its exit code carries the verdict. */
#define WPR_ROUNDS 6
#define WPR_BIG (96 * 1024)

static int wpr_child(void)
{
    setenv("RLO_TCP_RANK", "1", 1);
    rlo_world *w = rlo_tcp_world_new();
    if (!w)
        return 2;
    rlo_engine *e = rlo_engine_new(w, 1, 0, 0, 0, 0, 0, 1 << 20);
    if (!e || rlo_engine_enable_arq(e, 60 * 1000 * 1000, 4) != RLO_OK)
        return 3;
    uint8_t *buf = (uint8_t *)malloc(WPR_BIG + 16);
    if (!buf)
        return 4;
    int bad = 0;
    for (int i = 0; i < 2 * WPR_ROUNDS; i++) {
        int tag = -1, origin = -1, pid, vote;
        int64_t n = -1;
        for (int spin = 0; spin < 200000 && n < 0; spin++) {
            rlo_engine_progress_n(e, 0, 1000); /* batched poll-wait */
            n = rlo_pickup_next(e, &tag, &origin, &pid, &vote, buf,
                                WPR_BIG + 16);
        }
        /* strict alternation big/small proves per-peer FIFO held
         * through batched partial flushes */
        int64_t want = (i % 2 == 0) ? WPR_BIG : 5;
        if (n != want || origin != 0)
            bad = 1;
        for (int64_t j = 0; j < n; j++)
            if (buf[j] != (uint8_t)(0x40 + i)) {
                bad = 1;
                break;
            }
    }
    free(buf);
    /* flush the local send queues (rlo_drain is COLLECTIVE on tcp —
     * the parent never enters it, so entering here would stall on the
     * control-ring timeout): once tcp_quiescent, every owed ACK is in
     * the kernel and the graceful close delivers it */
    for (int spin = 0; spin < 200000 && !rlo_world_quiescent(w); spin++)
        rlo_engine_progress_n(e, 0, 1000);
    rlo_engine_free(e);
    rlo_world_free(w);
    return bad ? 5 : 0;
}

static void test_writev_partial_resume(void)
{
    char port[16];
    snprintf(port, sizeof port, "%d", 21000 + (int)(getpid() % 20000));
    setenv("RLO_TCP_WORLD", "2", 1);
    setenv("RLO_TCP_PORT_BASE", port, 1);
    setenv("RLO_TCP_SNDBUF", "4096", 1); /* force short writes */
    pid_t kid = fork();
    CHECK(kid >= 0);
    if (kid == 0)
        _exit(wpr_child());
    setenv("RLO_TCP_RANK", "0", 1);
    rlo_world *w = rlo_tcp_world_new();
    CHECK(w);
    if (!w) {
        waitpid(kid, 0, 0);
        goto out_env;
    }
    {
        rlo_engine *e = rlo_engine_new(w, 0, 0, 0, 0, 0, 0, 1 << 20);
        CHECK(e);
        CHECK(rlo_engine_enable_arq(e, 60 * 1000 * 1000, 4) == RLO_OK);
        uint8_t *big = (uint8_t *)malloc(WPR_BIG);
        CHECK(big);
        for (int i = 0; i < 2 * WPR_ROUNDS; i++) {
            int64_t len = (i % 2 == 0) ? WPR_BIG : 5;
            memset(big, 0x40 + i, (size_t)len);
            /* even frames ride the zero-copy isend_hdr path (payload
             * >= RLO_ZC_MIN_PAYLOAD), odd ones the clone path — both
             * interleave in the same sendmsg batches */
            CHECK(rlo_bcast(e, big, len) == RLO_OK);
        }
        /* poll-wait until the child's cumulative ACK covers all of it
         * (proves every byte survived the short-write resumes) */
        for (int spin = 0;
             spin < 200000 && rlo_engine_arq_unacked(e) > 0; spin++)
            rlo_engine_progress_n(e, 0, 1000);
        CHECK(rlo_engine_arq_unacked(e) == 0);
        CHECK(rlo_engine_err(e) == RLO_OK);
        free(big);
        int status = 0;
        waitpid(kid, &status, 0);
        CHECK(WIFEXITED(status) && WEXITSTATUS(status) == 0);
        rlo_engine_free(e);
        rlo_world_free(w);
    }
out_env:
    unsetenv("RLO_TCP_RANK");
    unsetenv("RLO_TCP_WORLD");
    unsetenv("RLO_TCP_PORT_BASE");
    unsetenv("RLO_TCP_SNDBUF");
}

/* TCP peer death: the child rank connects then crashes without a clean
 * shutdown; the parent must observe peer_alive(child) == 0, have its
 * in-flight handles complete (failed, not hung), and keep isend to the
 * dead peer non-blocking (blackhole semantics). */
static void test_tcp_peer_death(void)
{
    char port[16];
    /* derived from the pid so parallel selftest runs can't collide */
    snprintf(port, sizeof port, "%d", 20000 + (int)(getpid() % 20000));
    setenv("RLO_TCP_WORLD", "2", 1);
    setenv("RLO_TCP_PORT_BASE", port, 1);
    pid_t kid = fork();
    CHECK(kid >= 0);
    if (kid == 0) {
        /* child = rank 1: handshake, then crash abruptly */
        setenv("RLO_TCP_RANK", "1", 1);
        rlo_world *cw = rlo_tcp_world_new();
        if (!cw)
            _exit(2);
        _exit(0); /* no clean drain/free: sockets die with the process */
    }
    setenv("RLO_TCP_RANK", "0", 1);
    rlo_world *w = rlo_tcp_world_new();
    CHECK(w);
    if (!w) {
        waitpid(kid, 0, 0);
        return;
    }
    int status = 0;
    waitpid(kid, &status, 0); /* child is gone; its sockets are closed */
    CHECK(WIFEXITED(status) && WEXITSTATUS(status) == 0);
    rlo_engine *e = rlo_engine_new(w, 0, 0, 0, 0, 0, 0, 0);
    CHECK(e);
    /* keep sending until the kernel surfaces the reset; the transport
     * must fail the handles rather than hang or error the engine */
    uint64_t t0 = rlo_now_usec();
    while (rlo_world_peer_alive(w, 1, 0) &&
           rlo_now_usec() - t0 < 5 * 1000 * 1000) {
        rlo_bcast(e, (const uint8_t *)"x", 1);
        rlo_progress_all(w);
    }
    CHECK(!rlo_world_peer_alive(w, 1, 0));
    /* post-mortem send: either the peer was already marked crashed
     * (EPIPE/reset — isend blackholes) or its FIN landed on a record
     * boundary (graceful close) and THIS send trips the dead socket;
     * both must complete the handles and leave the engine unwedged */
    CHECK(rlo_bcast(e, (const uint8_t *)"y", 1) == RLO_OK);
    for (int i = 0; i < 100; i++)
        rlo_progress_all(w);
    CHECK(rlo_world_failed(w)); /* crash-fast signal for collectives */
    CHECK(rlo_engine_idle(e)); /* nothing wedged on the dead peer */
    CHECK(rlo_engine_err(e) == RLO_OK);
    rlo_engine_free(e);
    rlo_world_free(w);
    unsetenv("RLO_TCP_RANK");
    unsetenv("RLO_TCP_WORLD");
    unsetenv("RLO_TCP_PORT_BASE");
}

int main(void)
{
    static const int sizes[] = {2, 3, 5, 8, 16, 23, 32};
    for (unsigned i = 0; i < sizeof sizes / sizeof *sizes; i++) {
        test_bcast(sizes[i], 0);
        test_bcast(sizes[i], 4);
        test_iar(sizes[i], -1, 1);
        test_iar(sizes[i], sizes[i] - 1, 0);
    }
    test_concurrent_proposers(8);
    test_concurrent_proposers(23);
    test_multiplex();
    test_dirty_teardown();
    test_elastic_recovery(6, 2);
    test_elastic_recovery(8, 7);
    test_elastic_recovery(5, 0);
    test_mid_round_voter_death(6, 4);
    test_mid_round_voter_death(8, 2);
    test_sole_survivor_consensus();
    test_pid_reuse_across_rounds(4);
    test_pid_reuse_across_rounds(8);
    test_coll(2);
    test_coll(5);
    test_coll(8);
    test_coll(13);
    test_subcomm();
    test_deferred_dup_vote();
    test_coll_sub();
    test_arq_loss_and_dup(4);
    test_arq_loss_and_dup(8);
    test_arq_dropped_vote(8);
    test_batched_parity();
    test_progress_budget();
    test_arq_due_heap();
    test_progress_n_threads();
    test_progress_n_threads_traced();
    test_writev_partial_resume();
    test_tcp_peer_death();
    if (failures) {
        fprintf(stderr, "%d FAILURES\n", failures);
        return 1;
    }
    printf("rlo_selftest: all checks passed\n");
    return 0;
}
