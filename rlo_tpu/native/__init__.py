"""Native C core: the reference is a C11 library (SURVEY.md §2 — every
native component gets a native equivalent). C sources + Makefile live here;
`rlo_tpu.native.bindings` builds on demand and exposes ctypes wrappers
(NativeWorld / NativeEngine) mirroring the Python engine API.
"""

from rlo_tpu.native.build import build, lib_path  # noqa: F401
