"""On-demand build of the native core.

Compiles librlo_core.so from the C sources next to this file the first time
the bindings are imported (and whenever a source is newer than the built
library), so a fresh checkout needs no manual make step. Uses the plain C
toolchain only — no MPI, no pybind11 (bindings are ctypes).
"""

from __future__ import annotations

import os
import subprocess
from pathlib import Path

_DIR = Path(__file__).resolve().parent
SOURCES = ["rlo_topology.c", "rlo_wire.c", "rlo_trace.c",
           "rlo_world_common.c", "rlo_loopback.c", "rlo_shm.c",
           "rlo_mpi.c", "rlo_tcp.c", "rlo_engine.c", "rlo_coll.c",
           "rlo_bench.c"]
HEADERS = ["rlo_core.h", "rlo_internal.h"]
LIB_NAME = "librlo_core.so"
#: femtompi-linked variant: the MPI transport is live, rendezvous via
#: the femtompirun launcher (env FEMTOMPI_*). Built on demand when a
#: process launched under femtompirun imports the bindings.
MPI_LIB_NAME = "librlo_core_fmpi.so"


def under_femtompi() -> bool:
    return os.environ.get("FEMTOMPI_RANK") is not None


def lib_path() -> Path:
    return _DIR / (MPI_LIB_NAME if under_femtompi() else LIB_NAME)


def _stale(lib: Path) -> bool:
    if not lib.exists():
        return True
    lib_mtime = lib.stat().st_mtime
    # build.py itself is a dep: changing the source list must trigger
    # a rebuild (a stale lib otherwise masks missing symbols)
    deps = SOURCES + HEADERS + ["build.py"]
    if under_femtompi():
        deps = deps + ["femtompi/femtompi.c", "femtompi/mpi.h"]
    return any((_DIR / f).stat().st_mtime > lib_mtime for f in deps)


def _have_mpi(cc: str) -> bool:
    """True when a tiny MPI program compiles AND links — a header-only
    install must not break the whole native-core build with -lmpi."""
    probe = subprocess.run(
        [cc, "-xc", "-", "-lmpi", "-o", os.devnull],
        input="#include <mpi.h>\nint main(void){return MPI_Init(0,0);}\n",
        capture_output=True, text=True)
    return probe.returncode == 0


def _have_rt(cc: str) -> bool:
    """shm_open/shm_unlink live in librt on pre-2.34 glibc; a -shared
    link succeeds without it but dlopen then fails with an undefined
    symbol, so probe and link it when present (mirror of the Makefile's
    HAVE_RT)."""
    probe = subprocess.run(
        [cc, "-xc", "-", "-lrt", "-o", os.devnull],
        input="int main(void){return 0;}\n",
        capture_output=True, text=True)
    return probe.returncode == 0


def build(force: bool = False) -> Path:
    """Build (if needed) and return the shared-library path.

    Under femtompirun the femtompi-linked variant is built instead: the
    MPI transport compiles in against femtompi/mpi.h so MpiBackend runs
    for real (one process per rank). Otherwise a real MPI install is
    probed; absent both, rlo_mpi_available() reports 0.
    """
    lib = lib_path()
    if not force and not _stale(lib):
        return lib
    cc = os.environ.get("CC", "cc")
    srcs = [str(_DIR / s) for s in SOURCES]
    if under_femtompi():
        extra = ["-DRLO_HAVE_MPI", f"-I{_DIR / 'femtompi'}",
                 str(_DIR / "femtompi" / "femtompi.c")]
    else:
        extra = ["-DRLO_HAVE_MPI", "-lmpi"] if _have_mpi(cc) else []
    if _have_rt(cc):
        extra = extra + ["-lrt"]
    # build to a private temp then atomically rename: N ranks launched
    # together may all find the library stale and rebuild concurrently
    tmp = lib.with_suffix(f".so.tmp.{os.getpid()}")
    cmd = [cc, "-O2", "-g", "-std=c11", "-Wall", "-Wextra", "-fPIC",
           "-shared", "-o", str(tmp)] + srcs + extra
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        tmp.unlink(missing_ok=True)
        raise RuntimeError(
            f"native core build failed ({' '.join(cmd)}):\n{proc.stderr}")
    os.replace(tmp, lib)
    return lib
