/* MPI point-to-point transport — CPU-cluster parity with the reference.
 *
 * The reference hardwires nonblocking MPI P2P throughout rootless_ops.c
 * (MPI_Isend :1123/1152/1588, MPI_Irecv :656, MPI_Test :647); here the
 * same calls sit behind the transport vtable so the engine code is
 * shared with the loopback and SHM transports. Compile-gated on
 * RLO_HAVE_MPI (the build autodetects mpi.h): without MPI the stubs
 * below keep the library linkable and `rlo_mpi_available()` reports 0 so
 * the ROOTLESS_BACKEND=mpi switch can fail with a clear message instead
 * of an undefined symbol.
 *
 * Differences from the reference worth noting:
 *   - variable-size frames (MPI_Get_count sizes the receive) instead of
 *     fixed 32 KB sends (rootless_ops.c:1588);
 *   - engine `comm` ids are multiplexed into the MPI tag
 *     (mpi_tag = comm * 16 + rlo_tag) rather than one dup'ed MPI
 *     communicator per engine (:1461) — same isolation, no collective
 *     setup per engine;
 *   - termination detection generalizes the reference's
 *     MPI_Iallreduce-over-bcast-counts drain (:1613-1625): a nonblocking
 *     allreduce of [global sent, global delivered] must agree twice in a
 *     row while every local engine is idle.
 */
#include "rlo_internal.h"

#include <sched.h>
#include <stdio.h>

int rlo_mpi_available(void)
{
#ifdef RLO_HAVE_MPI
    return 1;
#else
    return 0;
#endif
}

#ifndef RLO_HAVE_MPI

rlo_world *rlo_mpi_world_new(void)
{
    return 0;
}

#else /* RLO_HAVE_MPI */

#include <mpi.h>

#define MPI_TAG_STRIDE 16 /* rlo tags occupy [0, 16) */

/* one outstanding MPI_Isend: the buffer must stay alive until tested
 * complete (the reference parks msgs in queue_wait for the same reason,
 * rootless_ops.c:1594) */
typedef struct mpi_send_node {
    struct mpi_send_node *next;
    MPI_Request req;
    rlo_handle *handle;
    rlo_blob *frame; /* ref held until MPI_Test reports completion */
} mpi_send_node;

typedef struct rlo_mpi_world {
    rlo_world base;
    MPI_Comm comm;
    mpi_send_node *sends; /* untested isends */
    rlo_wire_node *inbox_head, *inbox_tail; /* received, un-polled */
    int64_t sent_cnt, recv_cnt;
} rlo_mpi_world;

static void mpi_test_sends(rlo_mpi_world *w)
{
    mpi_send_node **pp = &w->sends;
    while (*pp) {
        mpi_send_node *n = *pp;
        int done = 0;
        MPI_Test(&n->req, &done, MPI_STATUS_IGNORE);
        if (done) {
            n->handle->delivered = 1;
            rlo_handle_unref(n->handle);
            rlo_blob_unref(n->frame);
            *pp = n->next;
            free(n);
        } else {
            pp = &n->next;
        }
    }
}

static int mpi_isend(rlo_world *base, int src, int dst, int comm, int tag,
                     rlo_blob *frame, rlo_handle **out)
{
    rlo_mpi_world *w = (rlo_mpi_world *)base;
    if (dst < 0 || dst >= base->world_size || !frame || frame->len < 0 ||
        src != base->my_rank)
        return RLO_ERR_ARG;
    int64_t len = frame->len;
    mpi_send_node *n = (mpi_send_node *)calloc(1, sizeof(*n));
    /* world ref + optional caller ref */
    rlo_handle *h = rlo_handle_new_w(base, out ? 2 : 1);
    if (!n || !h) {
        free(n);
        rlo_pool_free(h);
        return RLO_ERR_NOMEM;
    }
    /* zero-copy: MPI sends straight from the shared frame blob, whose
     * ref is held until MPI_Test reports completion */
    n->frame = rlo_blob_ref(frame);
    n->handle = h;
    if (MPI_Isend(frame->data, (int)len, MPI_BYTE, dst,
                  comm * MPI_TAG_STRIDE + tag, w->comm,
                  &n->req) != MPI_SUCCESS) {
        rlo_blob_unref(n->frame);
        free(n);
        rlo_pool_free(h);
        return RLO_ERR_PROTO;
    }
    n->next = w->sends;
    w->sends = n;
    w->sent_cnt++;
    if (out)
        *out = h;
    return RLO_OK;
}

/* move every probe-able incoming message into the local inbox */
static int mpi_pump(rlo_mpi_world *w)
{
    for (;;) {
        int flag = 0;
        MPI_Status st;
        MPI_Iprobe(MPI_ANY_SOURCE, MPI_ANY_TAG, w->comm, &flag, &st);
        if (!flag)
            return RLO_OK;
        int nbytes = 0;
        MPI_Get_count(&st, MPI_BYTE, &nbytes);
        rlo_wire_node *n =
            (rlo_wire_node *)rlo_pool_alloc(&w->base, sizeof(*n));
        rlo_blob *frame = rlo_blob_new_w(&w->base, nbytes);
        if (!n || !frame) {
            rlo_pool_free(n);
            rlo_blob_unref(frame);
            return RLO_ERR_NOMEM;
        }
        n->next = 0;
        n->src = st.MPI_SOURCE;
        n->dst = w->base.my_rank;
        n->tag = st.MPI_TAG % MPI_TAG_STRIDE;
        n->comm = st.MPI_TAG / MPI_TAG_STRIDE;
        n->due = 0;
        n->frame = frame;
        n->handle = rlo_handle_new_w(&w->base, 1);
        if (!n->handle) {
            rlo_pool_free(n);
            rlo_blob_unref(frame);
            return RLO_ERR_NOMEM;
        }
        n->handle->delivered = 1;
        MPI_Recv(frame->data, nbytes, MPI_BYTE, st.MPI_SOURCE, st.MPI_TAG,
                 w->comm, MPI_STATUS_IGNORE);
        w->recv_cnt++;
        if (w->inbox_tail)
            w->inbox_tail->next = n;
        else
            w->inbox_head = n;
        w->inbox_tail = n;
    }
}

static rlo_wire_node *mpi_poll(rlo_world *base, int rank, int comm)
{
    rlo_mpi_world *w = (rlo_mpi_world *)base;
    if (rank != base->my_rank)
        return 0;
    mpi_test_sends(w);
    mpi_pump(w);
    rlo_wire_node *prev = 0;
    for (rlo_wire_node *n = w->inbox_head; n; prev = n, n = n->next) {
        if (n->comm != comm)
            continue;
        if (prev)
            prev->next = n->next;
        else
            w->inbox_head = n->next;
        if (w->inbox_tail == n)
            w->inbox_tail = prev;
        n->next = 0;
        return n;
    }
    return 0;
}

static int mpi_quiescent(const rlo_world *base)
{
    const rlo_mpi_world *w = (const rlo_mpi_world *)base;
    /* local view only; global truth needs the drain protocol */
    return w->sends == 0 && w->inbox_head == 0;
}

static int64_t mpi_sent(const rlo_world *base)
{
    return ((const rlo_mpi_world *)base)->sent_cnt;
}

static int64_t mpi_delivered(const rlo_world *base)
{
    return ((const rlo_mpi_world *)base)->recv_cnt;
}

/* Drain: nonblocking allreduce of [sent, recvd]; terminate when the
 * global sums agree twice consecutively with all local engines idle
 * (generalizes reference rootless_ops.c:1613-1625). Collective. */
static int mpi_drain(rlo_world *base, int max_spins)
{
    rlo_mpi_world *w = (rlo_mpi_world *)base;
    int64_t prev_sum[2] = {-1, -2};
    for (int i = 0; i < max_spins; i++) {
        rlo_progress_all(base);
        int local_idle = 1;
        for (int j = 0; j < base->n_engines; j++)
            if (!rlo_engine_idle(base->engines[j]))
                local_idle = 0;
        if (!local_idle || !mpi_quiescent(base)) {
            if ((i & 7) == 7) /* oversubscribed cores: let peers run */
                sched_yield();
            continue;
        }
        int64_t local[2] = {w->sent_cnt, w->recv_cnt};
        int64_t sum[2] = {0, 0};
        MPI_Request req;
        MPI_Iallreduce(local, sum, 2, MPI_INT64_T, MPI_SUM, w->comm,
                       &req);
        int done = 0;
        for (long t = 0; !done; t++) {
            if (t > (long)max_spins * 1000L) {
                /* a peer never posted its matching Iallreduce (it
                 * stalled or died). The request cannot be cancelled
                 * portably; leaking it is the least-bad option on this
                 * already-fatal path. */
                return RLO_ERR_STALL;
            }
            MPI_Test(&req, &done, MPI_STATUS_IGNORE);
            rlo_progress_all(base); /* keep draining while reducing */
            if (!done && (t & 7) == 7)
                sched_yield(); /* peers must reach their Iallreduce */
        }
        if (sum[0] == sum[1] && sum[0] == prev_sum[0] &&
            prev_sum[0] == prev_sum[1])
            return i;
        prev_sum[0] = sum[0];
        prev_sum[1] = sum[1];
    }
    return RLO_ERR_STALL;
}

static void mpi_free(rlo_world *base)
{
    rlo_mpi_world *w = (rlo_mpi_world *)base;
    mpi_test_sends(w);
    for (mpi_send_node *n = w->sends; n;) {
        mpi_send_node *nn = n->next;
        /* Never MPI_Cancel a send: Open MPI >= 4 aborts on it and a
         * cancel that no-ops would leave MPI_Wait blocking on a dead
         * receiver. Real-time deadline; on timeout leak the request AND
         * the buffer (MPI may still be reading it) — this path is only
         * reachable after a failed drain, where the job is lost anyway. */
        int done = 0;
        uint64_t deadline = rlo_now_usec() + 5 * 1000 * 1000;
        while (!done && rlo_now_usec() < deadline)
            MPI_Test(&n->req, &done, MPI_STATUS_IGNORE);
        rlo_handle_unref(n->handle);
        if (done) {
            rlo_blob_unref(n->frame);
            free(n);
        }
        n = nn;
    }
    for (rlo_wire_node *n = w->inbox_head; n;) {
        rlo_wire_node *nn = n->next;
        rlo_handle_unref(n->handle);
        rlo_blob_unref(n->frame);
        rlo_pool_free(n);
        n = nn;
    }
    MPI_Comm_free(&w->comm);
    free(base->engines);
    rlo_pool_drain(base);
    free(w);
}

static void mpi_barrier(rlo_world *base)
{
    MPI_Barrier(((rlo_mpi_world *)base)->comm);
}

static const rlo_transport_ops MPI_OPS = {
    .name = "mpi",
    .isend = mpi_isend,
    .poll = mpi_poll,
    .quiescent = mpi_quiescent,
    .sent_cnt = mpi_sent,
    .delivered_cnt = mpi_delivered,
    .drain = mpi_drain,
    .barrier = mpi_barrier,
    .free_ = mpi_free,
};

rlo_world *rlo_mpi_world_new(void)
{
    int inited = 0;
    MPI_Initialized(&inited);
    if (!inited)
        MPI_Init(0, 0);
    rlo_mpi_world *w = (rlo_mpi_world *)calloc(1, sizeof(*w));
    if (!w)
        return 0;
    w->base.ops = &MPI_OPS;
    /* isolated traffic, like the reference's dup at bcomm_init :1461 */
    if (MPI_Comm_dup(MPI_COMM_WORLD, &w->comm) != MPI_SUCCESS) {
        free(w);
        return 0;
    }
    MPI_Comm_size(w->comm, &w->base.world_size);
    MPI_Comm_rank(w->comm, &w->base.my_rank);
    if (w->base.world_size < 2) {
        MPI_Comm_free(&w->comm);
        free(w);
        return 0;
    }
    return &w->base;
}

#endif /* RLO_HAVE_MPI */
