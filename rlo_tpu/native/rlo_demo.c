/* `demo` — multi-process scenario runner over the SHM transport.
 *
 * The analogue of the reference's one test binary driven by
 * `mpirun -n N ./demo` (reference Makefile:5, testcases.c:742-780): each
 * rank is a real OS process; scenarios replicate the reference suite
 * (SURVEY.md §4) with its behavior-level oracles:
 *
 *   bcast    ~ test_gen_bcast (testcases.c:59-108): one root broadcasts
 *              `cnt` messages, every other rank spin-picks-up exactly cnt
 *   wrapper  ~ test_wrapper_bcast (:699-724): every rank roots in turn
 *   hacky    ~ hacky_sack_progress_engine (:638-697): random ball
 *              passing; every catch triggers a new broadcast; per-rank
 *              pickup-count oracle
 *   iar      ~ test_IAllReduce_single_proposal (:243-332): one proposer,
 *              optional dissenting rank; decision verified on every rank
 *   iar2     ~ test_concurrent_iar_single_proposal (:110-241): two
 *              engines on one world, concurrent proposals, both verified
 *   multi    ~ test_iar_multi_proposal (:401-486): several simultaneous
 *              proposers; every rank counts the expected decisions
 *   multi2   ~ test_concurrent_iar_multi_proposal (:488-594): engine
 *              multiplexing x several simultaneous proposers per
 *              engine, with pid reuse across two sequential rounds
 *   fail     net-new (no reference analogue): one rank crashes; the
 *              others detect it through shm heartbeat staleness
 *              (rlo_world_peer_alive) instead of hanging in a drain
 *   efail    net-new: full engine-level elastic recovery across real
 *              processes — heartbeat detection, FAILURE broadcast,
 *              survivor overlay re-forming, and a working bcast after
 *
 * Usage: ./rlo_demo [-n ranks] [-c case|all] [-m msgs] [-v]
 * Exit status 0 iff every rank's oracle held.
 */
#include "rlo_core.h"

#include <sched.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

typedef struct demo_cfg {
    int msgs;       /* bcast count / hacky rounds / bench reps */
    int veto;       /* iar: rank that votes NO (-1 = none) */
    int verbose;
    int64_t bytes;  /* bench payload bytes per rank */
} demo_cfg;

#define RCHECK(cond)                                                       \
    do {                                                                   \
        if (!(cond)) {                                                     \
            fprintf(stderr, "rank %d FAIL %s:%d: %s\n", rank, __FILE__,    \
                    __LINE__, #cond);                                      \
            return 1;                                                      \
        }                                                                  \
    } while (0)

#define DRAIN_SPINS 50000000

/* spin progress until pickup returns something, a peer rank dies, or the
 * budget runs out. Progress is BATCHED (docs/DESIGN.md S13): each
 * iteration lets the C loop run sweeps until the currently flowing
 * work is done, so the demo exercises rlo_world_progress_all_n on
 * every transport (shm rings, the tcp sendmsg coalescing, femtompi). */
static int64_t pickup_spin(rlo_world *w, rlo_engine *e, int *tag,
                           int *origin, int *pid, int *vote, uint8_t *buf,
                           int64_t cap)
{
    for (long i = 0; i < 200000000L; i++) {
        int64_t n = rlo_pickup_next(e, tag, origin, pid, vote, buf, cap);
        if (n >= 0)
            return n;
        if (rlo_world_failed(w))
            return -1;
        /* bounded deadline: on shm the no-deadline world call would
         * spin its fruitless fuse whenever the GLOBAL in-flight count
         * is nonzero because of OTHER ranks' traffic; 200 usec per
         * crossing keeps every local engine co-progressing (a rank
         * may host several) without hogging an oversubscribed core */
        if (rlo_world_progress_all_n(w, 0, 200) == 0)
            sched_yield(); /* nothing for us: let the sender run */
    }
    return -1;
}

/* spin until my own proposal leaves IN_PROGRESS; 0 on success */
static int proposal_spin(rlo_world *w, rlo_engine *e)
{
    for (long i = 0; i < 200000000L; i++) {
        if (rlo_check_proposal_state(e) != RLO_IN_PROGRESS)
            return 0;
        if (rlo_world_failed(w))
            return -1;
        if ((i & 63) == 63)
            sched_yield();
    }
    return -1;
}

/* ---- bcast: root broadcasts cnt msgs; others expect exactly cnt ---- */
static int case_bcast(rlo_world *w, int rank, void *vcfg)
{
    const demo_cfg *cfg = (const demo_cfg *)vcfg;
    int ws = rlo_world_size(w);
    int cnt = cfg->msgs;
    rlo_engine *e = rlo_engine_new(w, rank, 0, 0, 0, 0, 0, 0);
    RCHECK(e);
    uint64_t t0 = rlo_now_usec();
    if (rank == 0) {
        for (int i = 0; i < cnt; i++) {
            char buf[64];
            int n = snprintf(buf, sizeof buf, "bcast-%d", i);
            RCHECK(rlo_bcast(e, (const uint8_t *)buf, n) == RLO_OK);
        }
    } else {
        for (int i = 0; i < cnt; i++) {
            uint8_t buf[64];
            int tag, origin, pid, vote;
            int64_t n = pickup_spin(w, e, &tag, &origin, &pid, &vote, buf,
                                    sizeof buf);
            RCHECK(n >= 0);
            RCHECK(origin == 0 && tag == RLO_TAG_BCAST);
        }
    }
    RCHECK(rlo_drain(w, DRAIN_SPINS) >= 0);
    RCHECK(rlo_engine_total_pickup(e) == (rank == 0 ? 0 : cnt));
    RCHECK(rlo_engine_err(e) == RLO_OK);
    if (cfg->verbose && rank == 0)
        fprintf(stderr, "bcast: %d msgs x %d ranks in %llu usec\n", cnt,
                ws, (unsigned long long)(rlo_now_usec() - t0));
    rlo_engine_free(e);
    return 0;
}

/* ---- wrapper: every rank roots one round in turn ---- */
static int case_wrapper(rlo_world *w, int rank, void *vcfg)
{
    const demo_cfg *cfg = (const demo_cfg *)vcfg;
    (void)cfg;
    int ws = rlo_world_size(w);
    rlo_engine *e = rlo_engine_new(w, rank, 0, 0, 0, 0, 0, 0);
    RCHECK(e);
    for (int root = 0; root < ws; root++) {
        if (rank == root) {
            char buf[64];
            int n = snprintf(buf, sizeof buf, "round-%d", root);
            RCHECK(rlo_bcast(e, (const uint8_t *)buf, n) == RLO_OK);
        } else {
            uint8_t buf[64];
            int tag, origin, pid, vote;
            int64_t n = pickup_spin(w, e, &tag, &origin, &pid, &vote, buf,
                                    sizeof buf);
            RCHECK(n >= 0);
            RCHECK(origin == root);
        }
        RCHECK(rlo_drain(w, DRAIN_SPINS) >= 0);
        rlo_shm_barrier(w); /* keep rounds from bleeding into oracles */
    }
    RCHECK(rlo_engine_total_pickup(e) == ws - 1);
    RCHECK(rlo_engine_err(e) == RLO_OK);
    rlo_engine_free(e);
    return 0;
}

/* ---- hacky sack: every catch triggers a new broadcast ----
 * Ball payload = {round, holder}. Rank 0 throws round 0; whoever the
 * ball names as holder throws the next round until `msgs` rounds are
 * out. Oracle (reference :691-692 adapted): pickups == rounds_total -
 * my_throws, since a rank sees every ball but its own. */
static int case_hacky(rlo_world *w, int rank, void *vcfg)
{
    const demo_cfg *cfg = (const demo_cfg *)vcfg;
    int ws = rlo_world_size(w);
    int rounds = cfg->msgs;
    rlo_engine *e = rlo_engine_new(w, rank, 0, 0, 0, 0, 0, 0);
    RCHECK(e);
    uint64_t t0 = rlo_now_usec();
    int my_throws = 0;
    int32_t ball[2];
    if (rank == 0) { /* round 0 */
        ball[0] = 0;
        ball[1] = (int32_t)(1 % ws);
        RCHECK(rlo_bcast(e, (const uint8_t *)ball, sizeof ball) == RLO_OK);
        my_throws++;
    }
    int seen = 0;
    /* every rank sees rounds 0..rounds-1 except the ones it threw */
    while (seen + my_throws < rounds) {
        uint8_t buf[64];
        int tag, origin, pid, vote;
        int64_t n = pickup_spin(w, e, &tag, &origin, &pid, &vote, buf,
                                sizeof buf);
        RCHECK(n == sizeof ball);
        memcpy(ball, buf, sizeof ball);
        seen++;
        int rnd = ball[0], holder = ball[1];
        if (holder == rank && rnd + 1 < rounds) {
            /* deterministic "random" next holder, never myself */
            int32_t nxt = (int32_t)((rank + rnd * 2654435761u) % ws);
            if (nxt == rank)
                nxt = (int32_t)((nxt + 1) % ws);
            int32_t nb[2] = {(int32_t)(rnd + 1), nxt};
            RCHECK(rlo_bcast(e, (const uint8_t *)nb, sizeof nb) == RLO_OK);
            my_throws++;
        }
    }
    RCHECK(rlo_drain(w, DRAIN_SPINS) >= 0);
    /* a final sweep: nothing further may arrive */
    RCHECK(rlo_engine_total_pickup(e) + my_throws == rounds);
    RCHECK(rlo_engine_err(e) == RLO_OK);
    if (cfg->verbose && rank == 0)
        fprintf(stderr, "hacky: %d rounds x %d ranks in %llu usec\n",
                rounds, ws, (unsigned long long)(rlo_now_usec() - t0));
    rlo_engine_free(e);
    return 0;
}

/* ---- IAR single proposal (veto rank optional) ---- */
typedef struct iar_ctx {
    int veto;
    int actions;
} iar_ctx;

static int judge_cb(const uint8_t *p, int64_t n, void *vc)
{
    (void)p;
    (void)n;
    return ((iar_ctx *)vc)->veto ? 0 : 1;
}

static void action_cb(const uint8_t *p, int64_t n, void *vc)
{
    (void)p;
    (void)n;
    ((iar_ctx *)vc)->actions++;
}

static int case_iar(rlo_world *w, int rank, void *vcfg)
{
    const demo_cfg *cfg = (const demo_cfg *)vcfg;
    int ws = rlo_world_size(w);
    int expect = cfg->veto >= 0 && cfg->veto < ws ? 0 : 1;
    iar_ctx ctx = {.veto = rank == cfg->veto, .actions = 0};
    rlo_engine *e =
        rlo_engine_new(w, rank, 0, judge_cb, &ctx, action_cb, &ctx, 0);
    RCHECK(e);
    if (rank == 0) {
        int rc = rlo_submit_proposal(e, (const uint8_t *)"move-x", 6, 0);
        RCHECK(rc == -1 || rc == expect);
        /* poll to completion (reference spin on check_proposal_state,
         * testcases.c:262-266) */
        RCHECK(proposal_spin(w, e) == 0);
        RCHECK(rlo_vote_my_proposal(e) == expect);
    } else {
        /* every non-proposer must see the decision in its pickup */
        uint8_t buf[64];
        int tag, origin, pid, vote;
        int64_t n = pickup_spin(w, e, &tag, &origin, &pid, &vote, buf,
                                sizeof buf);
        RCHECK(n >= 0);
        RCHECK(tag == RLO_TAG_IAR_DECISION);
        RCHECK(pid == 0 && vote == expect);
        /* approved proposals ran the action exactly once — except on a
         * vetoing rank, which never forwards and never acts */
        RCHECK(ctx.actions == (expect && !ctx.veto ? 1 : 0));
    }
    RCHECK(rlo_drain(w, DRAIN_SPINS) >= 0);
    RCHECK(rlo_engine_err(e) == RLO_OK);
    rlo_engine_free(e);
    return 0;
}

/* ---- two engines on one world, concurrent proposals ---- */
static int case_iar2(rlo_world *w, int rank, void *vcfg)
{
    const demo_cfg *cfg = (const demo_cfg *)vcfg;
    (void)cfg;
    int ws = rlo_world_size(w);
    rlo_engine *a = rlo_engine_new(w, rank, 0, 0, 0, 0, 0, 0);
    rlo_engine *b = rlo_engine_new(w, rank, 1, 0, 0, 0, 0, 0);
    RCHECK(a && b);
    int pa = 0, pb = 1 % ws; /* proposer ranks per engine */
    if (rank == pa)
        RCHECK(rlo_submit_proposal(a, (const uint8_t *)"on-A", 4, pa) >=
               -1);
    if (rank == pb)
        RCHECK(rlo_submit_proposal(b, (const uint8_t *)"on-B", 4, pb) >=
               -1);
    /* both engines progress each other through the shared world */
    if (rank == pa) {
        RCHECK(proposal_spin(w, a) == 0);
        RCHECK(rlo_vote_my_proposal(a) == 1);
    } else {
        uint8_t buf[64];
        int tag, origin, pid, vote;
        RCHECK(pickup_spin(w, a, &tag, &origin, &pid, &vote, buf,
                           sizeof buf) >= 0);
        RCHECK(tag == RLO_TAG_IAR_DECISION && pid == pa && vote == 1);
    }
    if (rank == pb) {
        RCHECK(proposal_spin(w, b) == 0);
        RCHECK(rlo_vote_my_proposal(b) == 1);
    } else {
        uint8_t buf[64];
        int tag, origin, pid, vote;
        RCHECK(pickup_spin(w, b, &tag, &origin, &pid, &vote, buf,
                           sizeof buf) >= 0);
        RCHECK(tag == RLO_TAG_IAR_DECISION && pid == pb && vote == 1);
    }
    RCHECK(rlo_drain(w, DRAIN_SPINS) >= 0);
    RCHECK(rlo_engine_err(a) == RLO_OK && rlo_engine_err(b) == RLO_OK);
    rlo_engine_free(a);
    rlo_engine_free(b);
    return 0;
}

/* ---- several simultaneous proposers on one engine ---- */
static int case_multi(rlo_world *w, int rank, void *vcfg)
{
    const demo_cfg *cfg = (const demo_cfg *)vcfg;
    (void)cfg;
    int ws = rlo_world_size(w);
    rlo_engine *e = rlo_engine_new(w, rank, 0, 0, 0, 0, 0, 0);
    RCHECK(e);
    /* proposers: rank 1 plus every rank = 0 mod 4 (reference active_1 +
     * active_2_mod pattern, testcases.c:401-486); pid = rank */
    int am_proposer = rank == 1 % ws || rank % 4 == 0;
    int n_prop = 0;
    for (int r = 0; r < ws; r++)
        if (r == 1 % ws || r % 4 == 0)
            n_prop++;
    if (am_proposer)
        RCHECK(rlo_submit_proposal(e, (const uint8_t *)"multi", 5, rank) >=
               -1);
    /* expect decisions for every proposal but my own via pickup */
    int want = n_prop - (am_proposer ? 1 : 0);
    int seen[256] = {0};
    for (int i = 0; i < want; i++) {
        uint8_t buf[64];
        int tag, origin, pid, vote;
        int64_t n = pickup_spin(w, e, &tag, &origin, &pid, &vote, buf,
                                sizeof buf);
        RCHECK(n >= 0);
        RCHECK(tag == RLO_TAG_IAR_DECISION && vote == 1);
        RCHECK(pid >= 0 && pid < 256 && !seen[pid]);
        seen[pid] = 1;
    }
    if (am_proposer) {
        RCHECK(proposal_spin(w, e) == 0);
        RCHECK(rlo_vote_my_proposal(e) == 1);
    }
    RCHECK(rlo_drain(w, DRAIN_SPINS) >= 0);
    RCHECK(rlo_engine_err(e) == RLO_OK);
    rlo_engine_free(e);
    return 0;
}

/* ---- concurrent multi-proposal on TWO engines ----
 * Reference test_concurrent_iar_multi_proposal (testcases.c:488-594):
 * the product of engine multiplexing (iar2) and several simultaneous
 * proposers (multi), plus pid reuse across two sequential rounds (each
 * proposer reuses pid=rank; the round generation disambiguates). */
static int case_multi2(rlo_world *w, int rank, void *vcfg)
{
    (void)vcfg;
    int ws = rlo_world_size(w);
    rlo_engine *a = rlo_engine_new(w, rank, 0, 0, 0, 0, 0, 0);
    rlo_engine *b = rlo_engine_new(w, rank, 1, 0, 0, 0, 0, 0);
    RCHECK(a && b);
    int am_proposer = rank == 1 % ws || rank % 4 == 0;
    int n_prop = 0;
    for (int r = 0; r < ws; r++)
        if (r == 1 % ws || r % 4 == 0)
            n_prop++;
    for (int round = 0; round < 2; round++) {
        if (am_proposer) {
            RCHECK(rlo_submit_proposal(a, (const uint8_t *)"mA", 2,
                                       rank) >= -1);
            RCHECK(rlo_submit_proposal(b, (const uint8_t *)"mB", 2,
                                       rank) >= -1);
        }
        /* decision-count oracle per engine: one decision per foreign
         * proposal, each pid exactly once, all approved */
        int want = n_prop - (am_proposer ? 1 : 0);
        for (int ei = 0; ei < 2; ei++) {
            rlo_engine *e = ei ? b : a;
            int seen[256] = {0};
            for (int i = 0; i < want; i++) {
                uint8_t buf[64];
                int tag, origin, pid, vote;
                int64_t n = pickup_spin(w, e, &tag, &origin, &pid, &vote,
                                        buf, sizeof buf);
                RCHECK(n >= 0);
                RCHECK(tag == RLO_TAG_IAR_DECISION && vote == 1);
                RCHECK(pid >= 0 && pid < 256 && !seen[pid]);
                seen[pid] = 1;
            }
        }
        if (am_proposer) {
            RCHECK(proposal_spin(w, a) == 0);
            RCHECK(rlo_vote_my_proposal(a) == 1);
            RCHECK(proposal_spin(w, b) == 0);
            RCHECK(rlo_vote_my_proposal(b) == 1);
        }
        RCHECK(rlo_drain(w, DRAIN_SPINS) >= 0);
        /* the drain is collective but its EXIT is not simultaneous: a
         * fast rank submitting round r+1 immediately would regenerate
         * traffic and keep a slow rank's drain from ever observing
         * global idle. Barrier between rounds closes that race. */
        rlo_world_barrier(w);
    }
    RCHECK(rlo_engine_err(a) == RLO_OK && rlo_engine_err(b) == RLO_OK);
    rlo_engine_free(a);
    rlo_engine_free(b);
    return 0;
}

/* ---- bench: engine-substrate fp32 allreduce timing ----
 * BASELINE config 1 ("float32 allreduce, 8 MPI ranks, 1 MB buffer,
 * testcases via mpirun on CPU"): the bcast-gather allreduce over the
 * rootless overlay — every rank broadcasts its buffer, drains, and
 * sums everything through the zero-copy peek/consume path. Runs on any
 * multi-process transport (shm or MPI), one real process per rank; the
 * in-process variant is rlo_bench.c. Rank 0 prints median usec. */
static int case_bench(rlo_world *w, int rank, void *vcfg)
{
    const demo_cfg *cfg = (const demo_cfg *)vcfg;
    int ws = rlo_world_size(w);
    int64_t nbytes = cfg->bytes > 0 ? cfg->bytes : 1 << 20;
    int64_t count = nbytes / (int64_t)sizeof(float);
    int reps = cfg->msgs > 0 && cfg->msgs <= 100 ? cfg->msgs : 5;
    nbytes = count * (int64_t)sizeof(float);
    rlo_engine *e = rlo_engine_new(w, rank, 0, 0, 0, 0, 0, nbytes + 64);
    RCHECK(e);
    float *buf = (float *)malloc((size_t)nbytes);
    float *acc = (float *)malloc((size_t)nbytes);
    double *times = (double *)calloc((size_t)reps, sizeof(double));
    RCHECK(buf && acc && times);
    for (int64_t i = 0; i < count; i++)
        buf[i] = (float)((rank + 1) * ((i % 13) + 1));
    rlo_world_barrier(w);
    for (int rep = 0; rep < reps; rep++) {
        uint64_t t0 = rlo_now_usec();
        RCHECK(rlo_bcast(e, (const uint8_t *)buf, nbytes) == RLO_OK);
        RCHECK(rlo_drain(w, DRAIN_SPINS) >= 0);
        memcpy(acc, buf, (size_t)nbytes);
        for (int got = 0; got < ws - 1; got++) {
            const uint8_t *payload = 0;
            int64_t n = rlo_pickup_peek(e, 0, 0, 0, 0, &payload);
            RCHECK(n == nbytes);
            const float *f = (const float *)payload;
            for (int64_t i = 0; i < count; i++)
                acc[i] += f[i];
            rlo_pickup_consume(e);
        }
        times[rep] = (double)(rlo_now_usec() - t0);
        /* oracle: sum over ranks of (r+1)*k at i=0 (k=1) */
        RCHECK(acc[0] == (float)(ws * (ws + 1) / 2));
        rlo_world_barrier(w);
    }
    for (int i = 0; i < reps; i++)
        for (int j = i + 1; j < reps; j++)
            if (times[j] < times[i]) {
                double t = times[i];
                times[i] = times[j];
                times[j] = t;
            }
    if (rank == 0)
        printf("bench[%s]: engine allreduce (bcast-gather) %lld B x %d "
               "ranks: median %.0f usec\n",
               rlo_world_transport(w), (long long)nbytes, ws,
               times[reps / 2]);
    fflush(stdout);

    /* ring allreduce over the same transport (rlo_coll.c) — the
     * bandwidth-optimal schedule, one real process per rank */
    rlo_coll *coll = rlo_coll_new(w, rank, 64);
    RCHECK(coll);
    for (int rep = 0; rep < reps; rep++) {
        for (int64_t i = 0; i < count; i++)
            buf[i] = (float)((rank + 1) * ((i % 13) + 1));
        rlo_world_barrier(w);
        uint64_t t0 = rlo_now_usec();
        RCHECK(rlo_coll_allreduce_f32_start(coll, buf, count,
                                            RLO_COLL_SUM) == RLO_OK);
        RCHECK(rlo_coll_wait(coll, 2000000000L) == RLO_OK);
        times[rep] = (double)(rlo_now_usec() - t0);
        RCHECK(buf[0] == (float)(ws * (ws + 1) / 2));
        rlo_world_barrier(w);
    }
    for (int i = 0; i < reps; i++)
        for (int j = i + 1; j < reps; j++)
            if (times[j] < times[i]) {
                double t = times[i];
                times[i] = times[j];
                times[j] = t;
            }
    if (rank == 0)
        printf("bench[%s]: ring allreduce (rlo_coll) %lld B x %d ranks: "
               "median %.0f usec\n",
               rlo_world_transport(w), (long long)nbytes, ws,
               times[reps / 2]);
    fflush(stdout);
    rlo_coll_free(coll);
    free(buf);
    free(acc);
    free(times);
    RCHECK(rlo_engine_err(e) == RLO_OK);
    rlo_engine_free(e);
    return 0;
}

#ifdef RLO_HAVE_MPI
#include <mpi.h>

/* ---- nbcast: overlay bcast vs native MPI_Bcast ----
 * Reference native_benchmark_single_point_bcast
 * (/root/reference/rootless_ops.c:1675-1709): time `msgs` rootless
 * broadcasts from rank 0 over the overlay vs the same traffic as
 * native MPI_Bcast calls. MPI builds only (needs direct MPI calls).
 *
 * Protocol (round 4): on the oversubscribed single-core launch the
 * scheduler drifts by whole timeslices between windows, so a
 * single overlay-window/native-window comparison swings 0.7x-2.7x run
 * to run. Like bench.py's paired-ratio protocol, the two sides are
 * timed in ADJACENT per-block windows and the reported ratio is the
 * MEDIAN of per-block ratios — common-mode scheduler phases cancel,
 * asymmetric spikes are rejected. */
#define NB_BLOCKS 7
static int case_nbcast(rlo_world *w, int rank, void *vcfg)
{
    const demo_cfg *cfg = (const demo_cfg *)vcfg;
    int64_t nbytes = cfg->bytes > 0 ? cfg->bytes : 4096;
    int reps = cfg->msgs > 0 ? cfg->msgs : 16;
    rlo_engine *e = rlo_engine_new(w, rank, 0, 0, 0, 0, 0, nbytes + 64);
    RCHECK(e);
    uint8_t *buf = (uint8_t *)malloc((size_t)nbytes);
    RCHECK(buf);
    memset(buf, rank == 0 ? 0x5a : 0, (size_t)nbytes);
    /* per block, THREE adjacent windows — skip-ring overlay, flat
     * overlay (depth-1, rlo_engine_set_fanout), native MPI_Bcast —
     * in an order rotated per block so no side systematically pays a
     * first-window warmup */
    double r_skip[NB_BLOCKS], r_flat[NB_BLOCKS];
    double us[3][NB_BLOCKS];
    for (int b = 0; b < NB_BLOCKS; b++) {
        uint64_t t_side[3] = {0, 0, 0};
        for (int s = 0; s < 3; s++) {
            int side = (s + b) % 3;
            if (side < 2)
                RCHECK(rlo_engine_set_fanout(
                           e, side == 0 ? RLO_FANOUT_SKIP_RING
                                        : RLO_FANOUT_FLAT) == RLO_OK);
            rlo_world_barrier(w);
            uint64_t t0 = rlo_now_usec();
            if (side < 2) {
                /* overlay: rank 0 broadcasts; others pick up; the
                 * window ends at settlement — every rank idle (all
                 * reps consumed and forwarded) + one barrier, the
                 * SAME end semantics as the native side's
                 * MPI_Barrier. (The full termination-detection drain
                 * would cost ~3 extra collective rounds the native
                 * side never pays; it is for when the recipient set
                 * is unknown.) */
                for (int i = 0; i < reps; i++) {
                    if (rank == 0)
                        RCHECK(rlo_bcast(e, buf, nbytes) == RLO_OK);
                    else {
                        const uint8_t *payload = 0;
                        int64_t n = -1;
                        for (long spin = 0;
                             spin < 200000000L && n < 0; spin++) {
                            n = rlo_pickup_peek(e, 0, 0, 0, 0,
                                                &payload);
                            if (n < 0) {
                                rlo_progress_all(w);
                                /* hand the CPU to the feeding rank
                                 * promptly (most of the round-2 19x
                                 * gap) */
                                if ((spin & 7) == 7)
                                    sched_yield();
                            }
                        }
                        RCHECK(n == nbytes && payload[0] == 0x5a);
                        rlo_pickup_consume(e);
                    }
                }
                for (long spin = 0; !rlo_engine_idle(e); spin++) {
                    RCHECK(spin < 200000000L);
                    rlo_progress_all(w);
                    if ((spin & 7) == 7)
                        sched_yield();
                }
                rlo_world_barrier(w);
            } else {
                /* native window; ends at a barrier — the settlement
                 * analogue (root-side send timing alone would flatter
                 * the native side) */
                for (int i = 0; i < reps; i++)
                    RCHECK(MPI_Bcast(buf, (int)nbytes, MPI_BYTE, 0,
                                     MPI_COMM_WORLD) == MPI_SUCCESS);
                RCHECK(buf[0] == 0x5a);
                MPI_Barrier(MPI_COMM_WORLD);
            }
            t_side[side] = rlo_now_usec() - t0;
        }
        for (int side = 0; side < 3; side++)
            us[side][b] = (double)t_side[side] / reps;
        double tn = t_side[2] ? (double)t_side[2] : 1.0;
        r_skip[b] = (double)t_side[0] / tn;
        r_flat[b] = (double)t_side[1] / tn;
    }
    rlo_world_barrier(w);
    if (rank == 0) {
        /* medians by insertion sort (NB_BLOCKS is tiny) */
        double *arrs[5] = {r_skip, r_flat, us[0], us[1], us[2]};
        for (int a = 0; a < 5; a++)
            for (int i = 1; i < NB_BLOCKS; i++)
                for (int j = i;
                     j > 0 && arrs[a][j] < arrs[a][j - 1]; j--) {
                    double t = arrs[a][j];
                    arrs[a][j] = arrs[a][j - 1];
                    arrs[a][j - 1] = t;
                }
        int m = NB_BLOCKS / 2;
        printf("nbcast: %dx%d x %lld B: overlay skip-ring %.1f / flat "
               "%.1f / MPI_Bcast %.1f usec/bcast (medians of %d "
               "3-window blocks: skip-ring/native %.2fx, flat/native "
               "%.2fx; skip",
               NB_BLOCKS, reps, (long long)nbytes, us[0][m], us[1][m],
               us[2][m], NB_BLOCKS, r_skip[m], r_flat[m]);
        for (int b = 0; b < NB_BLOCKS; b++)
            printf(" %.2f", r_skip[b]);
        printf("; flat");
        for (int b = 0; b < NB_BLOCKS; b++)
            printf(" %.2f", r_flat[b]);
        printf(")\n");
        /* ---- floor analysis (round-5 VERDICT item 7) ----
         * Why the overlay cannot reach 1.00x here: both sides move
         * the same ws-1 frames through the same femtompi rings on one
         * oversubscribed core, so the overlay's extra cost is the
         * engine machinery those frames pass through (wire header
         * serialize/parse, (origin, seq) dedup, queue ops, pickup
         * API) that a bare MPI_Bcast never runs. Quantify it on an
         * in-process loopback world — same engine code, no scheduler,
         * no transport contention — and report how much of the
         * overlay-native gap the serialized engine CPU accounts for. */
        double lb = rlo_bench_bcast_usec(rlo_world_size(w), nbytes,
                                         64);
        if (lb >= 0) {
            int frames = rlo_world_size(w) - 1;
            double gap = us[0][m] - us[2][m];
            printf("nbcast floor: loopback overlay %.2f usec/bcast "
                   "(%d frames, %.2f usec/frame engine+wire CPU); "
                   "overlay-native gap %.2f usec -> engine CPU "
                   "accounts for %.0f%%\n",
                   lb, frames, lb / frames, gap,
                   gap > 0 ? 100.0 * (lb < gap ? lb / gap : 1.0)
                           : 100.0);
        }
    }
    fflush(stdout);
    free(buf);
    RCHECK(rlo_engine_err(e) == RLO_OK);
    rlo_engine_free(e);
    return 0;
}
#endif /* RLO_HAVE_MPI */

#ifdef RLO_HAVE_MPI
/* ---- toobig: oversized collectives fail symmetrically ----
 * A frame larger than the femtompi per-pair ring can never be
 * delivered; every rank must get MPI_ERR_OTHER promptly instead of
 * the sender erroring alone while peers park in blocking waits until
 * the launcher timeout (the round-3 review finding). */
static int case_toobig(rlo_world *w, int rank, void *vcfg)
{
    (void)vcfg;
    (void)w;
    /* far above any configured ring (femtompirun default 4 MB) */
    int count = 256 << 20;
    static uint8_t tiny[1]; /* never touched: the size check fires
                               before any buffer access */
    uint64_t t0 = rlo_now_usec();
    RCHECK(MPI_Bcast(tiny, count, MPI_BYTE, 0, MPI_COMM_WORLD) ==
           MPI_ERR_OTHER);
    RCHECK(MPI_Reduce(tiny, tiny, count, MPI_BYTE, MPI_SUM, 0,
                      MPI_COMM_WORLD) == MPI_ERR_OTHER);
    MPI_Request req;
    RCHECK(MPI_Iallreduce(tiny, tiny, count / 4, MPI_INT, MPI_SUM,
                          MPI_COMM_WORLD, &req) == MPI_ERR_OTHER);
    /* symmetric + prompt: nobody blocked on a peer */
    RCHECK(rlo_now_usec() - t0 < 5 * 1000 * 1000ull);
    MPI_Barrier(MPI_COMM_WORLD); /* everyone got here: no hang */
    return 0;
}
#endif /* RLO_HAVE_MPI */

/* ---- subcomm: engine over a rank subset (sub-communicator) ----
 * Reference parity: RLO_progress_engine_new on any MPI_Comm — an
 * engine spanning ranks {0,2,ws-1} (rootless_ops.c:467, 1461) — while
 * a full-world engine runs interleaved traffic on comm 0. Oracles:
 * subset bcast/IAR deliveries span exactly the member set, the
 * bystander full-world broadcast is undisturbed, and the subset
 * decision reflects a member's veto. */
static int case_subcomm(rlo_world *w, int rank, void *vcfg)
{
    (void)vcfg;
    int ws = rlo_world_size(w);
    /* members {0, 2, ws-1} when the world is big enough for true
     * bystanders; degenerate {0, ws-1} pair otherwise (ws 2-3) */
    int members[3], n_m;
    if (ws >= 4) {
        members[0] = 0; members[1] = 2; members[2] = ws - 1;
        n_m = 3;
    } else {
        members[0] = 0; members[1] = ws - 1;
        n_m = 2;
    }
    int is_member = 0;
    for (int i = 0; i < n_m; i++)
        if (members[i] == rank)
            is_member = 1;
    int sub_bcaster = members[1];
    rlo_engine *ef = rlo_engine_new(w, rank, 0, 0, 0, 0, 0, 0);
    RCHECK(ef);
    iar_ctx ctx = {.veto = rank == ws - 1, .actions = 0};
    rlo_engine *es = 0;
    if (is_member) {
        es = rlo_engine_new_sub(w, rank, 1, members, n_m, judge_cb,
                                &ctx, action_cb, &ctx, 0);
        RCHECK(es);
    } else {
        /* a non-member must be rejected */
        RCHECK(!rlo_engine_new_sub(w, rank, 1, members, n_m, 0, 0, 0, 0,
                                   0));
    }
    /* interleaved: rank 1 broadcasts on the full comm, member
     * sub_bcaster on the subset, member 0 proposes (ws-1 vetoes) */
    if (rank == 1)
        RCHECK(rlo_bcast(ef, (const uint8_t *)"full", 4) == RLO_OK);
    if (rank == sub_bcaster)
        RCHECK(rlo_bcast(es, (const uint8_t *)"sub", 3) == RLO_OK);
    if (rank == 0) {
        int rc = rlo_submit_proposal(es, (const uint8_t *)"p", 1, 0);
        RCHECK(rc == -1 || rc == 0);
        RCHECK(proposal_spin(w, es) == 0);
        RCHECK(rlo_vote_my_proposal(es) == 0); /* the veto won */
    }
    /* full comm: everyone but the initiator picks up "full" */
    if (rank != 1) {
        uint8_t buf[64];
        int tag, origin, pid, vote;
        int64_t n = pickup_spin(w, ef, &tag, &origin, &pid, &vote, buf,
                                sizeof buf);
        RCHECK(n == 4 && origin == 1 && tag == RLO_TAG_BCAST);
    }
    /* subset comm: members pick up the subset bcast (except its
     * initiator) and the declined decision (except the proposer),
     * arrival order unknown */
    if (is_member) {
        int want = (rank == sub_bcaster ? 0 : 1) + (rank == 0 ? 0 : 1);
        int got_b = 0, got_d = 0;
        for (int i = 0; i < want; i++) {
            uint8_t buf[64];
            int tag, origin, pid, vote;
            int64_t n = pickup_spin(w, es, &tag, &origin, &pid, &vote,
                                    buf, sizeof buf);
            RCHECK(n >= 0);
            if (tag == RLO_TAG_BCAST) {
                RCHECK(n == 3 && origin == sub_bcaster);
                got_b++;
            } else {
                RCHECK(tag == RLO_TAG_IAR_DECISION && pid == 0 &&
                       vote == 0);
                got_d++;
            }
        }
        RCHECK(got_b == (rank == sub_bcaster ? 0 : 1));
        RCHECK(got_d == (rank == 0 ? 0 : 1));
        RCHECK(ctx.actions == 0); /* declined round: no actions */
    }
    RCHECK(rlo_drain(w, DRAIN_SPINS) >= 0);
    RCHECK(rlo_engine_err(ef) == RLO_OK);
    if (es)
        RCHECK(rlo_engine_err(es) == RLO_OK);
    rlo_engine_free(ef);
    if (es)
        rlo_engine_free(es);
    return 0;
}

/* ---- fail: a rank dies; survivors detect it via shm heartbeats ----
 * Net-new failure detection (the reference defines RLO_FAILED,
 * rootless_ops.h:66, but never assigns it; no timeouts or rank-failure
 * handling anywhere — SURVEY.md §5). The victim (last rank) exits right
 * after the start barrier without draining, simulating a crash: its
 * heartbeat slot goes stale. Survivors spin progress (which pumps rings
 * and stamps their own heartbeats) until rlo_world_peer_alive reports
 * the victim dead, while confirming no false positive on launch-fresh
 * peers. No global drain — that is the point: a dead rank would hang
 * the reference's MPI_Iallreduce-style drain forever. */
static int case_fail(rlo_world *w, int rank, void *vcfg)
{
    const demo_cfg *cfg = (const demo_cfg *)vcfg;
    int ws = rlo_world_size(w);
    int victim = ws - 1;
    const uint64_t timeout_usec = 300 * 1000;
    /* everyone is up and launch-stamped: no peer may look dead yet
     * against a generous window */
    for (int r = 0; r < ws; r++)
        RCHECK(rlo_world_peer_alive(w, r, 60 * 1000 * 1000));
    rlo_shm_barrier(w);
    if (rank == victim)
        return 0; /* "crash": stop pumping, heartbeat goes stale */
    rlo_engine *e = rlo_engine_new(w, rank, 0, 0, 0, 0, 0, 0);
    RCHECK(e);
    uint64_t t0 = rlo_now_usec();
    int detected = 0;
    while (rlo_now_usec() - t0 < 30ull * 1000 * 1000) {
        rlo_progress_all(w); /* pumps rings -> stamps my heartbeat */
        if (!rlo_world_peer_alive(w, victim, timeout_usec)) {
            detected = 1;
            break;
        }
    }
    RCHECK(detected);
    RCHECK(rlo_world_peer_alive(w, rank, timeout_usec)); /* self alive */
    if (cfg->verbose)
        fprintf(stderr, "rank %d: victim %d detected dead in %llu usec\n",
                rank, victim,
                (unsigned long long)(rlo_now_usec() - t0));
    rlo_engine_free(e);
    return 0;
}

/* ---- efail: engine-level elastic recovery across real processes ----
 * The full failure story on the multi-process transport: every rank
 * runs a progress engine with heartbeat detection; the victim crashes
 * after the start barrier; survivors detect it through missed ENGINE
 * heartbeats (not just transport staleness), adopt the FAILURE
 * broadcast, re-form the overlay, and complete a broadcast among
 * themselves — all without a global drain, which a dead rank would
 * stall forever. */
static int case_efail(rlo_world *w, int rank, void *vcfg)
{
    const demo_cfg *cfg = (const demo_cfg *)vcfg;
    int ws = rlo_world_size(w);
    int victim = ws - 1;
    int origin = 0;
    rlo_shm_barrier(w);
    if (rank == victim)
        return 0; /* crash: no drain, no goodbye */
    rlo_engine *e = rlo_engine_new(w, rank, 0, 0, 0, 0, 0, 0);
    RCHECK(e);
    RCHECK(rlo_engine_enable_failure_detection(e, 100 * 1000,
                                               20 * 1000) == RLO_OK);
    uint64_t t0 = rlo_now_usec();
    while (!rlo_engine_rank_failed(e, victim)) {
        rlo_progress_all(w);
        RCHECK(rlo_now_usec() - t0 < 30ull * 1000 * 1000);
    }
    if (cfg->verbose)
        fprintf(stderr, "rank %d: engine detected %d dead (%llu usec)\n",
                rank, victim,
                (unsigned long long)(rlo_now_usec() - t0));
    /* give every survivor time to adopt before re-using the overlay
     * (no pickup flush here: an early-arriving broadcast would be
     * swallowed; the receive loop below skips FAILURE notices instead) */
    t0 = rlo_now_usec();
    while (rlo_now_usec() - t0 < 300ull * 1000)
        rlo_progress_all(w);
    uint8_t buf[256];
    if (rank == origin)
        RCHECK(rlo_bcast(e, (const uint8_t *)"elastic", 7) == RLO_OK);
    if (rank != origin) {
        /* straggler FAILURE notices (duplicated during the view
         * transition) may still arrive — skip them, wait for the bcast */
        for (;;) {
            int tag = -1, org = -1, pid, vote;
            int64_t n = pickup_spin(w, e, &tag, &org, &pid, &vote, buf,
                                    sizeof buf);
            RCHECK(n >= 0);
            if (tag == RLO_TAG_FAILURE)
                continue;
            RCHECK(n == 7 && org == origin && tag == RLO_TAG_BCAST);
            break;
        }
    }
    /* settle outstanding forwards without a global drain */
    t0 = rlo_now_usec();
    while (rlo_now_usec() - t0 < 300ull * 1000)
        rlo_progress_all(w);
    rlo_engine_free(e);
    return 0;
}

/* ------------------------------------------------------------------ */

typedef struct demo_case {
    const char *name;
    rlo_rank_fn fn;
} demo_case;

static const demo_case CASES[] = {
    {"bcast", case_bcast},   {"wrapper", case_wrapper},
    {"hacky", case_hacky},   {"iar", case_iar},
    {"iar2", case_iar2},     {"multi", case_multi},
    {"multi2", case_multi2}, {"bench", case_bench},
    {"subcomm", case_subcomm},
#ifdef RLO_HAVE_MPI
    {"nbcast", case_nbcast},
    {"toobig", case_toobig},
#endif
    {"fail", case_fail},     {"efail", case_efail},
};
#define N_CASES (int)(sizeof CASES / sizeof *CASES)

/* cases that need shm-specific machinery (process-crash injection,
 * shared heartbeat slots) and cannot run over the MPI/TCP transports */
static int shm_only(const char *name)
{
    return !strcmp(name, "fail") || !strcmp(name, "efail");
}

/* Under the TCP launcher (tcprun / RLO_TCP_RANK env): one rank per
 * process over a real socket mesh — the transport that crosses host
 * boundaries (round-4 VERDICT; reference deploys on any MPI cluster,
 * rootless_ops.c:1123). nbcast/toobig additionally need an MPI
 * library and stay mpirun-only. */
static int tcp_main(const char *which, demo_cfg *cfg)
{
    rlo_world *w = rlo_tcp_world_new();
    if (!w) {
        fprintf(stderr, "rlo_tcp_world_new failed (env/ports?)\n");
        return 1;
    }
    int rank = rlo_world_my_rank(w);
    int ws = rlo_world_size(w);
    int failures = 0, matched = 0;
    for (int c = 0; c < N_CASES; c++) {
        if (strcmp(which, "all") && strcmp(which, CASES[c].name))
            continue;
        matched++;
        if (shm_only(CASES[c].name) ||
            !strcmp(CASES[c].name, "nbcast") ||
            !strcmp(CASES[c].name, "toobig")) {
            if (rank == 0)
                printf("%-8s n=%-3d SKIP (%s)\n", CASES[c].name, ws,
                       shm_only(CASES[c].name) ? "shm-only"
                                               : "mpirun-only");
            fflush(stdout);
            continue;
        }
        uint64_t t0 = rlo_now_usec();
        int rc = CASES[c].fn(w, rank, cfg);
        rlo_world_barrier(w);
        if (rank == 0)
            printf("%-8s n=%-3d %s (%llu usec) [tcp]\n", CASES[c].name,
                   ws, rc == 0 ? "PASS" : "FAIL",
                   (unsigned long long)(rlo_now_usec() - t0));
        fflush(stdout);
        if (rc != 0)
            failures++;
    }
    if (!matched && rank == 0)
        fprintf(stderr, "unknown case '%s'\n", which);
    rlo_world_free(w);
    return failures || !matched ? 1 : 0;
}

#ifdef RLO_HAVE_MPI
/* Under mpirun (femtompirun or a real MPI launcher) the demo runs ONE
 * rank per process over the MPI transport — `mpirun -n N ./rlo_demo_mpi
 * -c case`, the reference's own run shape (SURVEY.md §4). */
static int mpi_main(const char *which, demo_cfg *cfg)
{
    rlo_world *w = rlo_mpi_world_new();
    if (!w) {
        fprintf(stderr, "rlo_mpi_world_new failed\n");
        return 1;
    }
    int rank = rlo_world_my_rank(w);
    int ws = rlo_world_size(w);
    int failures = 0, matched = 0;
    for (int c = 0; c < N_CASES; c++) {
        if (strcmp(which, "all") && strcmp(which, CASES[c].name))
            continue;
        matched++;
        if (shm_only(CASES[c].name)) {
            if (rank == 0)
                printf("%-8s n=%-3d SKIP (shm-only)\n", CASES[c].name,
                       ws);
            continue;
        }
        uint64_t t0 = rlo_now_usec();
        int rc = CASES[c].fn(w, rank, cfg);
        rlo_world_barrier(w);
        if (rank == 0)
            printf("%-8s n=%-3d %s (%llu usec) [mpi]\n", CASES[c].name,
                   ws, rc == 0 ? "PASS" : "FAIL",
                   (unsigned long long)(rlo_now_usec() - t0));
        if (rc != 0)
            failures++;
    }
    if (!matched && rank == 0)
        fprintf(stderr, "unknown case '%s'\n", which);
    rlo_world_free(w);
    return failures || !matched ? 1 : 0;
}
#endif /* RLO_HAVE_MPI */

int main(int argc, char **argv)
{
    int ws = 8;
    const char *which = "all";
    demo_cfg cfg = {.msgs = 16, .veto = -1, .verbose = 0, .bytes = 0};
    for (int i = 1; i < argc; i++) {
        if (!strcmp(argv[i], "-n") && i + 1 < argc)
            ws = atoi(argv[++i]);
        else if (!strcmp(argv[i], "-c") && i + 1 < argc)
            which = argv[++i];
        else if (!strcmp(argv[i], "-m") && i + 1 < argc)
            cfg.msgs = atoi(argv[++i]);
        else if (!strcmp(argv[i], "-b") && i + 1 < argc)
            cfg.bytes = atoll(argv[++i]);
        else if (!strcmp(argv[i], "-veto") && i + 1 < argc)
            cfg.veto = atoi(argv[++i]);
        else if (!strcmp(argv[i], "-v"))
            cfg.verbose = 1;
        else {
            fprintf(stderr,
                    "usage: %s [-n ranks] [-c case|all] [-m msgs] "
                    "[-b bytes] [-veto rank] [-v]\ncases:",
                    argv[0]);
            for (int c = 0; c < N_CASES; c++)
                fprintf(stderr, " %s", CASES[c].name);
            fprintf(stderr, "\n");
            return 2;
        }
    }
    /* launched under tcprun? run one rank over the socket mesh */
    if (getenv("RLO_TCP_RANK"))
        return tcp_main(which, &cfg);
#ifdef RLO_HAVE_MPI
    /* launched under mpirun? run one rank over the MPI transport */
    if (getenv("FEMTOMPI_RANK") || getenv("OMPI_COMM_WORLD_RANK") ||
        getenv("PMI_RANK"))
        return mpi_main(which, &cfg);
#endif
    int failures = 0, matched = 0;
    for (int c = 0; c < N_CASES; c++) {
        if (strcmp(which, "all") && strcmp(which, CASES[c].name))
            continue;
        matched++;
#ifdef RLO_HAVE_MPI
        if (!strcmp(CASES[c].name, "nbcast") ||
            !strcmp(CASES[c].name, "toobig")) {
            /* needs a live MPI runtime: only valid under an mpirun
             * launcher (mpi_main); calling MPI_Bcast from the shm
             * children without MPI_Init would abort */
            printf("%-8s n=%-3d SKIP (mpirun-only)\n", CASES[c].name,
                   ws);
            fflush(stdout);
            continue;
        }
#endif
        /* iar additionally runs the dissent variant (reference
         * parameterized agree/disagree, testcases.c:243-332) */
        int reps = !strcmp(CASES[c].name, "iar") && cfg.veto < 0 ? 2 : 1;
        for (int rep = 0; rep < reps; rep++) {
            demo_cfg run = cfg;
            if (reps == 2 && rep == 1)
                run.veto = ws - 1;
            /* the bench case ships full payload frames through the
             * rings; size them to hold several in flight */
            int64_t ring = 0;
            if (!strcmp(CASES[c].name, "bench")) {
                int64_t payload = run.bytes > 0 ? run.bytes : 1 << 20;
                ring = 4 * payload + (64 << 10);
            }
            uint64_t t0 = rlo_now_usec();
            int rc = rlo_shm_launch(ws, ring, CASES[c].fn, &run);
            printf("%-8s n=%-3d %s (%llu usec)%s\n", CASES[c].name, ws,
                   rc == 0 ? "PASS" : "FAIL",
                   (unsigned long long)(rlo_now_usec() - t0),
                   reps == 2 && rep == 1 ? " [veto]" : "");
            /* flush BEFORE the next fork: children inherit the stdio
             * buffer, and their own flushes would replay it */
            fflush(stdout);
            if (rc != 0)
                failures++;
        }
    }
    if (!matched) {
        fprintf(stderr, "unknown case '%s'\n", which);
        return 2;
    }
    if (failures)
        fprintf(stderr, "%d case(s) FAILED\n", failures);
    return failures ? 1 : 0;
}
