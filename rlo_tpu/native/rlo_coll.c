/* Engine-substrate ring data collectives over the transport vtable.
 *
 * C counterpart of the Python coroutine collectives
 * (rlo_tpu/ops/collectives.py:183-259): ring reduce-scatter +
 * all-gather allreduce (bandwidth-optimal, 2*(ws-1) rounds of
 * 1/ws-sized chunks), the ring halves exposed directly, a rotation
 * all-to-all, and a dissemination barrier — generalizing the
 * reference's single-bit vote merge (vote &= v, rootless_ops.c:1060)
 * to tensor payloads, as BASELINE.json's config-1 op set requires.
 * These replace the O(ws^2) every-rank-broadcasts-everything
 * data-collective fallback in the Native/Mpi backend facades.
 *
 * Execution model mirrors the Python generators: since C has no
 * coroutines, each op is an explicit state machine — `*_start` arms
 * it, `rlo_coll_poll` advances one bounded slice (send at most one
 * frame, consume at most one arrival) and returns 1 when complete.
 * One process per rank spins its own poll (shm/mpi transports); a
 * single-process driver (loopback worlds, rlo_bench) round-robins
 * polls across ranks exactly like run_collectives().
 *
 * Message matching is the Python scheme verbatim: every phase draws a
 * fresh op id (frame pid) and stamps the round in the frame vote;
 * out-of-order arrivals park in a per-coll pending list until their
 * (src, opid, round) is awaited. A coll object owns a transport comm
 * id — it must differ from every engine's comm on the same world (the
 * world inbox is demultiplexed by comm).
 */
#include "rlo_internal.h"

#include <sched.h>
#include <string.h>

typedef struct coll_pend {
    struct coll_pend *next;
    int src;
    int32_t pid, vote;
    rlo_blob *frame;       /* owned ref */
    const uint8_t *payload;
    int64_t len;
} coll_pend;

/* op kinds */
enum {
    COLL_NONE = 0,
    COLL_ALLREDUCE,
    COLL_REDUCE_SCATTER,
    COLL_ALL_GATHER,
    COLL_ALL_TO_ALL,
    COLL_BARRIER,
};

/* phases of the ring ops */
enum { PH_RS = 0, PH_AG, PH_ROT, PH_DONE };

struct rlo_coll {
    rlo_world *w;
    int rank, ws, comm;
    /* sub-communicator support: ring/slot math runs on VIRTUAL ranks
     * 0..ws-1 (vrank = this rank's ring position); for subsets
     * (sub=1, <= 64 members) transport endpoints map through real[],
     * full-world contexts use identity arithmetic at ANY world size */
    int vrank;
    int sub;
    int real[64];
    int next_opid;
    coll_pend *pend;

    /* armed op state */
    int kind, op, phase, step, sent;
    int opid;               /* opid of the current phase */
    int64_t count;          /* caller elements (fp32 ops) */
    int64_t chunk;          /* elements per ring chunk (padded) */
    float *fbuf;            /* ws*chunk staging (fp32 ops) */
    float *fout;            /* caller output (allreduce: in-place) */
    int64_t blen;           /* bytes per slot (byte ops) */
    uint8_t *bbuf;          /* ws*blen staging (byte ops) */
    uint8_t *bout;          /* caller output (byte ops) */
};

rlo_coll *rlo_coll_new(rlo_world *w, int rank, int comm)
{
    if (!w || rank < 0 || rank >= rlo_world_size(w))
        return 0;
    if (rlo_world_my_rank(w) >= 0 && rank != rlo_world_my_rank(w))
        return 0;
    rlo_coll *c = (rlo_coll *)calloc(1, sizeof(*c));
    if (!c)
        return 0;
    c->w = w;
    c->rank = rank;
    c->ws = rlo_world_size(w);
    c->comm = comm;
    c->vrank = rank; /* full-world: endpoints are identity (endp) */
    return c;
}

/* virtual ring position -> real transport endpoint */
static int endp(const rlo_coll *c, int v)
{
    return c->sub ? c->real[v] : v;
}

rlo_coll *rlo_coll_new_sub(rlo_world *w, int rank, int comm,
                           const int *members, int n_members)
{
    if (!members || n_members < 2 || n_members > 64 ||
        n_members > rlo_world_size(w))
        return 0;
    int vr = -1;
    for (int i = 0; i < n_members; i++) {
        if (members[i] < 0 || members[i] >= rlo_world_size(w))
            return 0;
        for (int j = 0; j < i; j++)
            if (members[j] == members[i])
                return 0; /* duplicate member: the ring could never
                             complete (two positions, one rank) */
        if (members[i] == rank)
            vr = i;
    }
    if (vr < 0)
        return 0;
    rlo_coll *c = rlo_coll_new(w, rank, comm);
    if (!c)
        return 0;
    c->ws = n_members;
    c->vrank = vr;
    c->sub = 1;
    for (int i = 0; i < n_members; i++)
        c->real[i] = members[i];
    return c;
}

void rlo_coll_free(rlo_coll *c)
{
    if (!c)
        return;
    for (coll_pend *p = c->pend; p;) {
        coll_pend *np = p->next;
        rlo_blob_unref(p->frame);
        free(p);
        p = np;
    }
    free(c->fbuf);
    free(c->bbuf);
    free(c);
}

/* ---------------- plumbing ---------------- */

static int coll_send(rlo_coll *c, int dst, int32_t opid, int32_t rnd,
                     const void *data, int64_t len)
{
    rlo_blob *b = rlo_blob_new(RLO_HEADER_SIZE + len);
    if (!b)
        return RLO_ERR_NOMEM;
    if (rlo_frame_encode(b->data, b->len, c->rank, opid, rnd, -1,
                         (const uint8_t *)data, len) < 0) {
        rlo_blob_unref(b);
        return RLO_ERR_PROTO;
    }
    int rc = rlo_world_isend(c->w, c->rank, dst, c->comm, RLO_TAG_DATA,
                             b, 0);
    rlo_blob_unref(b);
    return rc;
}

/* pump at most one inbound frame into the pending list */
static int coll_pump(rlo_coll *c)
{
    rlo_wire_node *n = rlo_world_poll(c->w, c->rank, c->comm);
    if (!n)
        return 0;
    coll_pend *p = (coll_pend *)malloc(sizeof(*p));
    if (!p) {
        rlo_handle_unref(n->handle);
        rlo_blob_unref(n->frame);
        rlo_pool_free(n);
        return RLO_ERR_NOMEM;
    }
    int32_t origin = -1;
    p->len = rlo_frame_decode(n->frame->data, n->frame->len, &origin,
                              &p->pid, &p->vote, 0, &p->payload);
    rlo_handle_unref(n->handle);
    if (p->len < 0) {
        /* drop the undecodable frame BEFORE linking: a parked node
         * with garbage (src, pid, vote) and negative len could later
         * match a coll_take and memcpy from junk (advisor finding) */
        rlo_blob_unref(n->frame);
        rlo_pool_free(n);
        free(p);
        return RLO_ERR_PROTO;
    }
    p->src = n->src >= 0 ? n->src : origin;
    p->frame = n->frame; /* steal the ref */
    p->next = c->pend;
    c->pend = p;
    rlo_pool_free(n);
    return 1;
}

/* take a parked (src, opid, rnd) arrival; NULL if not yet here */
static coll_pend *coll_take(rlo_coll *c, int src, int32_t opid,
                            int32_t rnd)
{
    coll_pend **pp = &c->pend;
    while (*pp) {
        coll_pend *p = *pp;
        if (p->src == src && p->pid == opid && p->vote == rnd) {
            *pp = p->next;
            p->next = 0;
            return p;
        }
        pp = &p->next;
    }
    return 0;
}

static void reduce_f32(int op, float *acc, const float *in, int64_t n)
{
    switch (op) {
    case RLO_COLL_SUM:
        for (int64_t i = 0; i < n; i++)
            acc[i] += in[i];
        break;
    case RLO_COLL_MIN:
        for (int64_t i = 0; i < n; i++)
            if (in[i] < acc[i])
                acc[i] = in[i];
        break;
    case RLO_COLL_MAX:
        for (int64_t i = 0; i < n; i++)
            if (in[i] > acc[i])
                acc[i] = in[i];
        break;
    }
}

static float identity_f32(int op)
{
    switch (op) {
    case RLO_COLL_MIN: return 3.402823466e38f;  /* +FLT_MAX */
    case RLO_COLL_MAX: return -3.402823466e38f;
    default: return 0.0f;
    }
}

/* ---------------- arming ---------------- */

static int coll_busy(const rlo_coll *c)
{
    return c->kind != COLL_NONE;
}

/* stage caller fp32 data into a ws*chunk padded ring buffer */
static int stage_f32(rlo_coll *c, const float *data, int64_t count,
                     int op)
{
    c->count = count;
    c->chunk = (count + c->ws - 1) / c->ws;
    free(c->fbuf);
    c->fbuf = (float *)malloc((size_t)(c->ws * c->chunk) * sizeof(float));
    if (!c->fbuf)
        return RLO_ERR_NOMEM;
    memcpy(c->fbuf, data, (size_t)count * sizeof(float));
    float ident = identity_f32(op);
    for (int64_t i = count; i < c->ws * c->chunk; i++)
        c->fbuf[i] = ident;
    return RLO_OK;
}

int rlo_coll_allreduce_f32_start(rlo_coll *c, float *data, int64_t count,
                                 int op)
{
    if (!c || !data || count <= 0 || coll_busy(c))
        return RLO_ERR_ARG;
    int rc = stage_f32(c, data, count, op);
    if (rc != RLO_OK)
        return rc;
    c->kind = COLL_ALLREDUCE;
    c->op = op;
    c->fout = data;
    c->phase = c->ws > 1 ? PH_RS : PH_DONE;
    c->step = 0;
    c->sent = 0;
    c->opid = c->next_opid++;
    return RLO_OK;
}

int rlo_coll_reduce_scatter_f32_start(rlo_coll *c, const float *data,
                                      int64_t count, float *out, int op)
{
    if (!c || !data || !out || count <= 0 || coll_busy(c))
        return RLO_ERR_ARG;
    int rc = stage_f32(c, data, count, op);
    if (rc != RLO_OK)
        return rc;
    c->kind = COLL_REDUCE_SCATTER;
    c->op = op;
    c->fout = out;
    c->phase = c->ws > 1 ? PH_RS : PH_DONE;
    c->step = 0;
    c->sent = 0;
    c->opid = c->next_opid++;
    return RLO_OK;
}

int rlo_coll_all_gather_start(rlo_coll *c, const uint8_t *data,
                              int64_t len, uint8_t *out)
{
    if (!c || !data || !out || len <= 0 || coll_busy(c))
        return RLO_ERR_ARG;
    c->blen = len;
    free(c->bbuf);
    c->bbuf = (uint8_t *)malloc((size_t)(c->ws * len));
    if (!c->bbuf)
        return RLO_ERR_NOMEM;
    memcpy(c->bbuf + (size_t)c->vrank * len, data, (size_t)len);
    c->kind = COLL_ALL_GATHER;
    c->bout = out;
    c->phase = c->ws > 1 ? PH_AG : PH_DONE;
    c->step = 0;
    c->sent = 0;
    c->opid = c->next_opid++;
    return RLO_OK;
}

int rlo_coll_all_to_all_start(rlo_coll *c, const uint8_t *data,
                              int64_t len_per_rank, uint8_t *out)
{
    if (!c || !data || !out || len_per_rank <= 0 || coll_busy(c))
        return RLO_ERR_ARG;
    c->blen = len_per_rank;
    free(c->bbuf);
    c->bbuf = (uint8_t *)malloc((size_t)(c->ws * len_per_rank));
    if (!c->bbuf)
        return RLO_ERR_NOMEM;
    memcpy(c->bbuf, data, (size_t)(c->ws * len_per_rank));
    memcpy(out + (size_t)c->vrank * len_per_rank,
           data + (size_t)c->vrank * len_per_rank, (size_t)len_per_rank);
    c->kind = COLL_ALL_TO_ALL;
    c->bout = out;
    c->phase = c->ws > 1 ? PH_AG : PH_DONE;
    c->step = 1; /* round d in [1, ws) */
    c->sent = 0;
    c->opid = c->next_opid++;
    return RLO_OK;
}

int rlo_coll_barrier_start(rlo_coll *c)
{
    if (!c || coll_busy(c))
        return RLO_ERR_ARG;
    c->kind = COLL_BARRIER;
    c->phase = c->ws > 1 ? PH_AG : PH_DONE;
    c->step = 0; /* round k: distance 2^k */
    c->sent = 0;
    c->opid = c->next_opid++;
    return RLO_OK;
}

/* ---------------- the gear ---------------- */

static void coll_finish(rlo_coll *c)
{
    if (c->kind == COLL_ALLREDUCE)
        memcpy(c->fout, c->fbuf, (size_t)c->count * sizeof(float));
    else if (c->kind == COLL_REDUCE_SCATTER)
        memcpy(c->fout, c->fbuf + (size_t)c->vrank * c->chunk,
               (size_t)c->chunk * sizeof(float));
    else if (c->kind == COLL_ALL_GATHER)
        memcpy(c->bout, c->bbuf, (size_t)(c->ws * c->blen));
    c->kind = COLL_NONE;
}

/* Advance one bounded slice. Returns 1 when the armed op completed
 * (result delivered), 0 when still in progress, <0 on error. */
int rlo_coll_poll(rlo_coll *c)
{
    if (!c)
        return RLO_ERR_ARG;
    if (c->kind == COLL_NONE)
        return RLO_ERR_ARG;
    if (c->phase == PH_DONE) {
        coll_finish(c);
        return 1;
    }
    int ws = c->ws, rank = c->vrank; /* ring position */
    int nxt = endp(c, (rank + 1) % ws);       /* transport endpoints */
    int prv = endp(c, (rank - 1 + ws) % ws);
    int rc;

    switch (c->kind) {
    case COLL_ALLREDUCE:
    case COLL_REDUCE_SCATTER:
        if (c->phase == PH_RS) {
            /* ring reduce-scatter: step s sends chunk (rank-s), folds
             * the arrival into chunk (rank-s-1) (collectives.py:190) */
            if (!c->sent) {
                int64_t idx = ((rank - c->step) % ws + ws) % ws;
                rc = coll_send(c, nxt, c->opid, c->step,
                               c->fbuf + idx * c->chunk,
                               c->chunk * (int64_t)sizeof(float));
                if (rc != RLO_OK)
                    return rc;
                c->sent = 1;
            }
            coll_pend *p = coll_take(c, prv, c->opid, c->step);
            if (!p) {
                rc = coll_pump(c);
                if (rc < 0)
                    return rc;
                p = coll_take(c, prv, c->opid, c->step);
                if (!p)
                    return 0;
            }
            int64_t idx = ((rank - c->step - 1) % ws + ws) % ws;
            if (p->len != c->chunk * (int64_t)sizeof(float)) {
                rlo_blob_unref(p->frame);
                free(p);
                return RLO_ERR_PROTO;
            }
            reduce_f32(c->op, c->fbuf + idx * c->chunk,
                       (const float *)p->payload, c->chunk);
            rlo_blob_unref(p->frame);
            free(p);
            c->sent = 0;
            if (++c->step == ws - 1) {
                c->step = 0;
                c->opid = c->next_opid++;
                if (c->kind == COLL_ALLREDUCE) {
                    c->phase = PH_AG; /* own chunk = (rank+1) % ws */
                } else {
                    /* reduce-scatter: rank holds chunk (rank+1);
                     * rotate one hop so rank r returns chunk r */
                    c->phase = PH_ROT;
                }
            }
            return 0;
        }
        if (c->phase == PH_ROT) {
            if (!c->sent) {
                int64_t own = (rank + 1) % ws;
                rc = coll_send(c, nxt, c->opid, 0,
                               c->fbuf + own * c->chunk,
                               c->chunk * (int64_t)sizeof(float));
                if (rc != RLO_OK)
                    return rc;
                c->sent = 1;
            }
            coll_pend *p = coll_take(c, prv, c->opid, 0);
            if (!p) {
                rc = coll_pump(c);
                if (rc < 0)
                    return rc;
                p = coll_take(c, prv, c->opid, 0);
                if (!p)
                    return 0;
            }
            memcpy(c->fbuf + (size_t)rank * c->chunk, p->payload,
                   (size_t)c->chunk * sizeof(float));
            rlo_blob_unref(p->frame);
            free(p);
            c->phase = PH_DONE;
            coll_finish(c);
            return 1;
        }
        /* PH_AG: forward chunks around the ring; step s sends chunk
         * (own - s), the arrival is chunk (own - s - 1)
         * (collectives.py:206-219) */
        {
            int64_t own = (rank + 1) % ws;
            if (!c->sent) {
                int64_t idx = ((own - c->step) % ws + ws) % ws;
                rc = coll_send(c, nxt, c->opid, c->step,
                               c->fbuf + idx * c->chunk,
                               c->chunk * (int64_t)sizeof(float));
                if (rc != RLO_OK)
                    return rc;
                c->sent = 1;
            }
            coll_pend *p = coll_take(c, prv, c->opid, c->step);
            if (!p) {
                rc = coll_pump(c);
                if (rc < 0)
                    return rc;
                p = coll_take(c, prv, c->opid, c->step);
                if (!p)
                    return 0;
            }
            if (p->len != c->chunk * (int64_t)sizeof(float)) {
                rlo_blob_unref(p->frame);
                free(p);
                return RLO_ERR_PROTO;
            }
            int64_t idx = ((own - c->step - 1) % ws + ws) % ws;
            memcpy(c->fbuf + idx * c->chunk, p->payload,
                   (size_t)c->chunk * sizeof(float));
            rlo_blob_unref(p->frame);
            free(p);
            c->sent = 0;
            if (++c->step == ws - 1) {
                c->phase = PH_DONE;
                coll_finish(c);
                return 1;
            }
            return 0;
        }

    case COLL_ALL_GATHER: {
        /* ring all-gather of per-rank byte slots; own slot = rank */
        if (!c->sent) {
            int64_t idx = ((rank - c->step) % ws + ws) % ws;
            rc = coll_send(c, nxt, c->opid, c->step,
                           c->bbuf + idx * c->blen, c->blen);
            if (rc != RLO_OK)
                return rc;
            c->sent = 1;
        }
        coll_pend *p = coll_take(c, prv, c->opid, c->step);
        if (!p) {
            rc = coll_pump(c);
            if (rc < 0)
                return rc;
            p = coll_take(c, prv, c->opid, c->step);
            if (!p)
                return 0;
        }
        if (p->len != c->blen) {
            rlo_blob_unref(p->frame);
            free(p);
            return RLO_ERR_PROTO;
        }
        int64_t idx = ((rank - c->step - 1) % ws + ws) % ws;
        memcpy(c->bbuf + idx * c->blen, p->payload, (size_t)c->blen);
        rlo_blob_unref(p->frame);
        free(p);
        c->sent = 0;
        if (++c->step == ws - 1) {
            c->phase = PH_DONE;
            coll_finish(c);
            return 1;
        }
        return 0;
    }

    case COLL_ALL_TO_ALL: {
        /* rotation: round d sends slot (rank+d) to rank+d, receives
         * slot for me from rank-d (collectives.py:241-259); slots are
         * virtual positions, send/take endpoints are real ranks */
        int dst = (rank + c->step) % ws;
        int src = ((rank - c->step) % ws + ws) % ws;
        if (!c->sent) {
            rc = coll_send(c, endp(c, dst), c->opid, c->step,
                           c->bbuf + (size_t)dst * c->blen, c->blen);
            if (rc != RLO_OK)
                return rc;
            c->sent = 1;
        }
        coll_pend *p = coll_take(c, endp(c, src), c->opid, c->step);
        if (!p) {
            rc = coll_pump(c);
            if (rc < 0)
                return rc;
            p = coll_take(c, endp(c, src), c->opid, c->step);
            if (!p)
                return 0;
        }
        if (p->len != c->blen) {
            rlo_blob_unref(p->frame);
            free(p);
            return RLO_ERR_PROTO;
        }
        memcpy(c->bout + (size_t)src * c->blen, p->payload,
               (size_t)c->blen);
        rlo_blob_unref(p->frame);
        free(p);
        c->sent = 0;
        if (++c->step == ws) {
            c->phase = PH_DONE;
            c->kind = COLL_NONE;
            return 1;
        }
        return 0;
    }

    case COLL_BARRIER: {
        /* dissemination barrier: round k exchanges tokens at distance
         * 2^k (collectives.py:261-273) */
        int dist = 1 << c->step;
        if (!c->sent) {
            uint8_t token = 1;
            rc = coll_send(c, endp(c, (rank + dist) % ws), c->opid,
                           c->step, &token, 1);
            if (rc != RLO_OK)
                return rc;
            c->sent = 1;
        }
        int from = endp(c, ((rank - dist) % ws + ws) % ws);
        coll_pend *p = coll_take(c, from, c->opid, c->step);
        if (!p) {
            rc = coll_pump(c);
            if (rc < 0)
                return rc;
            p = coll_take(c, from, c->opid, c->step);
            if (!p)
                return 0;
        }
        rlo_blob_unref(p->frame);
        free(p);
        c->sent = 0;
        c->step++;
        if ((1 << c->step) >= ws) {
            c->phase = PH_DONE;
            c->kind = COLL_NONE;
            return 1;
        }
        return 0;
    }
    }
    return RLO_ERR_ARG;
}

/* Blocking convenience: spin poll to completion (one-process-per-rank
 * transports; single-process drivers must round-robin poll instead).
 * Yields the CPU periodically — ranks are commonly oversubscribed on
 * few cores, where a hot spin starves the very peer being awaited. */
int rlo_coll_wait(rlo_coll *c, long max_spins)
{
    for (long i = 0; i < max_spins; i++) {
        int rc = rlo_coll_poll(c);
        if (rc != 0)
            return rc < 0 ? rc : RLO_OK;
        if (rlo_world_failed(c->w))
            return RLO_ERR_STALL;
        if ((i & 63) == 63)
            sched_yield();
    }
    return RLO_ERR_STALL;
}

/* ------------------------------------------------------------------ */
/* In-process ring-allreduce benchmark: the config-1 comparison line    */
/* against rlo_bench_allreduce's bcast-gather (every-rank-broadcasts,   */
/* O(ws^2) bytes). The ring moves 2*(ws-1)/ws of the buffer per rank.   */
/* Same loopback world, same median-of-reps timing. Returns median      */
/* usec per allreduce, or <0 (rlo_err) on failure/wrong numerics.       */
/* ------------------------------------------------------------------ */
double rlo_bench_allreduce_ring(int world_size, int64_t count, int reps)
{
    if (world_size < 2 || count <= 0 || reps <= 0 || reps > 1000)
        return RLO_ERR_ARG;
    rlo_world *w = rlo_world_new(world_size, 0, 0);
    if (!w)
        return RLO_ERR_NOMEM;
    double rc = RLO_ERR_NOMEM;
    rlo_coll **colls = (rlo_coll **)calloc((size_t)world_size,
                                           sizeof(void *));
    float **bufs = (float **)calloc((size_t)world_size, sizeof(void *));
    double *times = (double *)calloc((size_t)reps, sizeof(double));
    if (!colls || !bufs || !times)
        goto out;
    for (int r = 0; r < world_size; r++) {
        colls[r] = rlo_coll_new(w, r, 0);
        bufs[r] = (float *)malloc((size_t)count * sizeof(float));
        if (!colls[r] || !bufs[r])
            goto out;
    }
    for (int rep = 0; rep < reps; rep++) {
        for (int r = 0; r < world_size; r++)
            for (int64_t i = 0; i < count; i++)
                bufs[r][i] = (float)((r + 1) * ((i % 13) + 1));
        uint64_t t0 = rlo_now_usec();
        for (int r = 0; r < world_size; r++) {
            int src = rlo_coll_allreduce_f32_start(colls[r], bufs[r],
                                                   count, RLO_COLL_SUM);
            if (src != RLO_OK) {
                rc = src;
                goto out;
            }
        }
        /* round-robin the state machines, run_collectives() style */
        int done = 0;
        for (long spin = 0; done < world_size && spin < 100000000L;
             spin++) {
            done = 0;
            for (int r = 0; r < world_size; r++) {
                int pr = rlo_coll_poll(colls[r]);
                if (pr < 0 && pr != RLO_ERR_ARG) {
                    rc = pr;
                    goto out;
                }
                if (pr == 1 || pr == RLO_ERR_ARG) /* ARG = already done */
                    done++;
            }
        }
        if (done < world_size) {
            rc = RLO_ERR_STALL;
            goto out;
        }
        times[rep] = (double)(rlo_now_usec() - t0);
        double want =
            (double)world_size * (world_size + 1) / 2.0 * ((0 % 13) + 1);
        if (bufs[0][0] != (float)want || bufs[1][0] != (float)want) {
            rc = RLO_ERR_PROTO;
            goto out;
        }
    }
    for (int i = 0; i < reps; i++)
        for (int j = i + 1; j < reps; j++)
            if (times[j] < times[i]) {
                double t = times[i];
                times[i] = times[j];
                times[j] = t;
            }
    rc = times[reps / 2];

out:
    if (colls)
        for (int r = 0; r < world_size; r++)
            rlo_coll_free(colls[r]);
    if (bufs)
        for (int r = 0; r < world_size; r++)
            free(bufs[r]);
    free(colls);
    free(bufs);
    free(times);
    rlo_world_free(w);
    return rc;
}
