/* rlo_core — native C core of the rlo_tpu framework.
 *
 * The reference (/root/reference/, "Rootless Operations for MPI") is a C11
 * library; this is its native-parity counterpart in the rebuild: skip-ring
 * overlay topology (reference rootless_ops.c:1412-1579), variable-size wire
 * frames (pbuf_serialize, rootless_ops.c:1369-1410 — minus the fixed 32 KB
 * frame flaw), intrusive message queues (rootless_ops.c:54-58, 345-404),
 * a cooperatively-polled progress engine (make_progress_gen,
 * rootless_ops.c:551-658), rootless broadcast (RLO_bcast_gen :1581,
 * _bc_forward :1104) and IAR leaderless consensus (:668-932), all over an
 * in-process loopback transport world (net-new: the reference can only run
 * under mpirun).
 *
 * Semantics are kept in lockstep with the Python engine
 * (rlo_tpu/engine.py) so the two implementations cross-check each other in
 * tests. Deliberate departures from the reference mirror the Python side:
 * nonblocking votes, variable-size frames, explicit state enums, and hard
 * errors instead of printf-warnings on protocol violations.
 *
 * Everything is single-threaded and cooperatively polled — there is no
 * background thread, matching the reference's documented model
 * (rootless_ops.h:216).
 */
#ifndef RLO_CORE_H
#define RLO_CORE_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---- message tags (reference RLO_COMM_TAGS, rootless_ops.h:50-61) ---- */
/* Tags without their own dispatch case go straight to pickup through
 * the progress switch's default label; rlo-lint R4 requires each such
 * tag to carry the `rlo-lint: default-route` annotation below (the
 * Python twin's annotations live on wire.py's Tag members). */
enum rlo_tag {
    RLO_TAG_BCAST = 0,
    RLO_TAG_JOB_DONE = 1,     /* rlo-lint: default-route */
    RLO_TAG_IAR_PROPOSAL = 2,
    RLO_TAG_IAR_VOTE = 3,
    RLO_TAG_IAR_DECISION = 4,
    RLO_TAG_BC_TEARDOWN = 5,  /* rlo-lint: default-route */
    RLO_TAG_IAR_TEARDOWN = 6, /* rlo-lint: default-route */
    RLO_TAG_P2P = 7,          /* rlo-lint: default-route */
    RLO_TAG_SYS = 8,          /* rlo-lint: default-route */
    RLO_TAG_DATA = 9,         /* rlo-lint: default-route */
    RLO_TAG_BARRIER = 10,     /* rlo-lint: default-route */
    RLO_TAG_HEARTBEAT = 11, /* point-to-point ring liveness probe */
    RLO_TAG_FAILURE = 12,   /* rootless failure notification */
    RLO_TAG_ACK = 13,       /* cumulative link ACK (ARQ); vote = seq */
    RLO_TAG_ABORT = 14,     /* rootless op-abort (deadline expiry);
                             * the C engine has no op deadlines, so a
                             * received ABORT delivers via pickup
                             * (documented divergence, rlo_engine.c).
                             * rlo-lint: default-route */
    RLO_TAG_JOIN = 15,      /* membership probe/petition: payload =
                             * (incarnation, epoch, min-alive, petition,
                             * member), 5 x le32 (docs/DESIGN.md S8/S18;
                             * member=1 tells the DESTINATION it is
                             * alive in the sender's view — catch up
                             * via MSYNC, not a full rejoin. Old
                             * 4-field probes parse as member=0) */
    RLO_TAG_JOIN_WELCOME = 16, /* admission notice: payload = (epoch,
                             * incarnation echo, n) + n member ranks;
                             * followed by a point-to-point replay of
                             * the recent-broadcast log */
    RLO_TAG_SERVE = 17,     /* serving-fabric point-to-point frame
                             * (load reports, docs/DESIGN.md S11):
                             * ARQ-stamped, epoch-gated, delivered
                             * straight to pickup.
                             * rlo-lint: default-route */
    RLO_TAG_TELEM = 18,     /* in-band telemetry digest (docs/DESIGN.md
                             * S17): ARQ-stamped, epoch-gated,
                             * delivered straight to pickup; payload =
                             * a delta-encoded digest (rlo_telem_encode
                             * below), consumed by the telemetry plane.
                             * rlo-lint: default-route */
    RLO_TAG_MSYNC = 19,     /* membership view-state sync (docs/
                             * DESIGN.md S18): payload = kind byte
                             * (0 REQ / 1 RSP / 2 AD / 3 WANT) +
                             * kind-specific body. ARQ- and epoch-
                             * exempt like JOIN: the catch-up channel
                             * must cross the quarantine it heals. */
};

/* ---- request/proposal states (reference RLO_Req_stat) ---- */
enum rlo_state {
    RLO_COMPLETED = 0,
    RLO_IN_PROGRESS = 1,
    RLO_FAILED = 2,
    RLO_INVALID = 3,
};

/* ---- error codes (negative returns) ----
 * Numbering starts at -10 so errors never collide with the -1 "nothing
 * yet / still pending" sentinel used by pickup and submit_proposal. */
enum rlo_err {
    RLO_OK = 0,
    RLO_ERR_ARG = -10,      /* bad argument */
    RLO_ERR_TOO_BIG = -11,  /* payload exceeds msg_size_max */
    RLO_ERR_BUSY = -12,     /* own proposal still in progress */
    RLO_ERR_PROTO = -13,    /* protocol violation (dup pid, unknown vote) */
    RLO_ERR_NOMEM = -14,
    RLO_ERR_STALL = -15,    /* drain did not reach quiescence */
};

/* default per-message payload cap (reference RLO_MSG_SIZE_MAX,
 * rootless_ops.h:49); frames themselves are variable-size */
#define RLO_MSG_SIZE_MAX 32768

/* ------------------------------------------------------------------ */
/* Topology: pure skip-ring math (reference rootless_ops.c:1412-1579). */
/* ------------------------------------------------------------------ */
int rlo_is_pow2(int n);
int rlo_level(int world_size, int rank);
int rlo_last_wall(int world_size, int rank);
/* Fills out[] with the raw send list, returns its length; *channel_cnt
 * (optional) receives the forwarding-channel count. cap must be >= 32. */
int rlo_send_list(int world_size, int rank, int *out, int cap,
                  int *channel_cnt);
int rlo_check_passed_origin(int world_size, int my_rank, int origin,
                            int to_rank);
/* Forward targets for a broadcast arriving at `rank` from `from_rank`
 * (furthest-first). Returns count. */
int rlo_fwd_targets(int world_size, int rank, int origin, int from_rank,
                    int *out, int cap);
int rlo_fwd_send_cnt(int world_size, int rank, int origin, int from_rank);
/* Targets the broadcast origin itself sends to (furthest-first). */
int rlo_initiator_targets(int world_size, int rank, int *out, int cap);

/* ------------------------------------------------------------------ */
/* Wire format: little-endian [origin:i32][pid:i32][vote:i32][seq:i32]  */
/* [epoch:i32][len:u64] header + payload (reference pbuf layout,        */
/* rootless_ops.c:64-73, extended with the ARQ link sequence number and */
/* the membership LINK epoch — both stamped by the sending engine per   */
/* (src, dst) edge; seq is -1 outside the reliable path, epoch is the   */
/* admission epoch of the edge's last link reset, 0 on the original     */
/* link. Matches rlo_tpu/wire.py `<iiiiiQ>` byte for byte.)             */
/* ------------------------------------------------------------------ */
#define RLO_HEADER_SIZE 28
/* byte offset of the seq field (the ARQ send path patches encoded
 * frames in place: one encode per broadcast, one stamp per edge) */
#define RLO_SEQ_OFFSET 12
/* byte offset of the link-epoch field (patched by the engine send
 * gate; receivers quarantine frames below their per-sender floor) */
#define RLO_EPOCH_OFFSET 16
/* Encodes into dst (cap >= RLO_HEADER_SIZE + len); returns frame size.
 * The epoch field is written as 0 — the send gate stamps it. */
int64_t rlo_frame_encode(uint8_t *dst, int64_t cap, int32_t origin,
                         int32_t pid, int32_t vote, int32_t seq,
                         const uint8_t *payload, int64_t len);
/* Decodes header; returns payload length or RLO_ERR_ARG on truncation.
 * *payload points into raw. */
int64_t rlo_frame_decode(const uint8_t *raw, int64_t rawlen, int32_t *origin,
                         int32_t *pid, int32_t *vote, int32_t *seq,
                         const uint8_t **payload);
/* Link-epoch accessors (raw must hold >= RLO_HEADER_SIZE bytes). */
int32_t rlo_frame_epoch(const uint8_t *raw);
void rlo_frame_set_epoch(uint8_t *raw, int32_t epoch);

/* ------------------------------------------------------------------ */
/* Loopback transport world: N in-process ranks, per-(src,dst,comm)     */
/* FIFO channels, optional seeded delivery latency in poll ticks.       */
/* ------------------------------------------------------------------ */
typedef struct rlo_world rlo_world;
typedef struct rlo_engine rlo_engine;

rlo_world *rlo_world_new(int world_size, int latency, uint64_t seed);
void rlo_world_free(rlo_world *w);
int rlo_world_size(const rlo_world *w);
/* bound rank for one-process-per-rank transports (shm/mpi); -1 when this
 * process hosts every rank (loopback) */
int rlo_world_my_rank(const rlo_world *w);
/* transport name: "loopback" / "shm" / "mpi" */
const char *rlo_world_transport(const rlo_world *w);
/* 1 when no frames are in flight or waiting in any inbox */
int rlo_world_quiescent(const rlo_world *w);
/* 1 when the world is dead (a peer rank's process failed/aborted);
 * always 0 for in-process transports. Spin loops should poll this. */
int rlo_world_failed(const rlo_world *w);
/* Liveness of one peer: 1 when `rank`'s process showed activity within
 * the last timeout_usec (net-new failure detection — the reference
 * defines RLO_FAILED, rootless_ops.h:66, but never assigns it and has no
 * timeouts or rank-failure handling, SURVEY.md §5). Transports without a
 * liveness signal (loopback: in-process) always return 1. On shm, every
 * rank stamps a shared heartbeat slot whenever it pumps its rings, so a
 * crashed or exited peer goes stale within one timeout. */
int rlo_world_peer_alive(const rlo_world *w, int rank,
                         uint64_t timeout_usec);
/* Fault injection (loopback only): simulate `rank`'s process dying —
 * its inbox is discarded, frames in flight to/from it are dropped
 * (handles complete), future traffic involving it is blackholed, its
 * polls return nothing. RLO_ERR_ARG on transports without injection.
 * Mirror of LoopbackWorld.kill_rank (rlo_tpu/transport/loopback.py). */
int rlo_world_kill_rank(rlo_world *w, int rank);
/* Fault injection (loopback only): silently drop / duplicate the next
 * `count` frames sent src -> dst — the loss/duplication legs of the
 * chaos harness (mirror of LoopbackWorld.drop_next / dup_next).
 * RLO_ERR_ARG on transports without injection. */
int rlo_world_drop_next(rlo_world *w, int src, int dst, int count);
int rlo_world_dup_next(rlo_world *w, int src, int dst, int count);
/* Fault injection (loopback only): network partition — frames whose
 * endpoints fall in different groups of group_of[0..n-1] (n ==
 * world_size; group_of[r] = r's group id) are silently dropped.
 * Passing NULL heals the partition. RLO_ERR_ARG where unsupported. */
int rlo_world_partition(rlo_world *w, const int *group_of, int n);
/* Fault injection (loopback only): revive a killed rank's endpoint
 * with an empty inbox (the harness then builds a fresh engine with a
 * bumped incarnation — the restart leg of the membership tests). */
int rlo_world_revive_rank(rlo_world *w, int rank);
int64_t rlo_world_sent_cnt(const rlo_world *w);
int64_t rlo_world_delivered_cnt(const rlo_world *w);
/* Collective barrier across all ranks (shm: sense-reversing spin;
 * mpi: MPI_Barrier; no-op on single-process transports). */
void rlo_world_barrier(rlo_world *w);
/* Test support (in-process worlds): inject one raw frame as if `src`
 * sent it — for duplicate/stale-frame scenarios. */
int rlo_world_inject(rlo_world *w, int src, int dst, int comm, int tag,
                     const uint8_t *raw, int64_t len);

/* ------------------------------------------------------------------ */
/* SHM transport: N real OS processes as ranks over a shared-memory     */
/* segment of SPSC ring channels — the `mpirun -n N` analogue           */
/* (reference Makefile:5). The launcher forks world_size children; each */
/* child receives a world bound to its rank and runs `fn`.              */
/* ------------------------------------------------------------------ */
typedef int (*rlo_rank_fn)(rlo_world *w, int rank, void *ctx);
/* Returns 0 when every rank returned 0, else the first nonzero child
 * status (or a negative rlo_err for setup failures). ring_bytes <= 0
 * picks a default (256 KB per src->dst channel). */
int rlo_shm_launch(int world_size, int64_t ring_bytes, rlo_rank_fn fn,
                   void *ctx);
/* Collective barrier across all ranks of an shm world (sense-reversing;
 * spins with sched_yield). No-op on other transports. */
void rlo_shm_barrier(rlo_world *w);

/* ------------------------------------------------------------------ */
/* MPI transport: CPU-cluster parity with the reference's backend       */
/* (nonblocking MPI P2P, rootless_ops.c passim). Compile-gated on       */
/* RLO_HAVE_MPI — rlo_mpi_available() reports whether this build has    */
/* it; without it rlo_mpi_world_new returns NULL. Requires a process    */
/* launched under mpirun; initializes MPI if the app hasn't.            */
/* ------------------------------------------------------------------ */
int rlo_mpi_available(void);
rlo_world *rlo_mpi_world_new(void);

/* ------------------------------------------------------------------ */
/* TCP transport: one process per rank over a full mesh of stream      */
/* sockets — the control plane crossing real host boundaries (the      */
/* reference's any-MPI-cluster deployment, rootless_ops.c:1123).       */
/* Endpoints from RLO_TCP_RANK/RLO_TCP_WORLD plus RLO_TCP_HOSTS        */
/* ("host:port,...", one per rank) or RLO_TCP_PORT_BASE on localhost.  */
/* ------------------------------------------------------------------ */
int rlo_tcp_available(void);
rlo_world *rlo_tcp_world_new(void);

/* ------------------------------------------------------------------ */
/* Progress engine (reference struct progress_engine + EngineManager).  */
/* ------------------------------------------------------------------ */
/* judgement callback: 1 approve / 0 decline (reference iar_cb_func_t,
 * rootless_ops.h:77) */
typedef int (*rlo_judge_cb)(const uint8_t *payload, int64_t len, void *ctx);
/* action callback: executed on every rank when a proposal is approved */
typedef void (*rlo_action_cb)(const uint8_t *payload, int64_t len,
                              void *ctx);

/* Engines on the same `comm` id across ranks form one communicator;
 * different comm ids on one world are fully isolated (the analogue of the
 * reference's dup'ed MPI comm per engine, rootless_ops.c:1461). */
rlo_engine *rlo_engine_new(rlo_world *w, int rank, int comm,
                           rlo_judge_cb judge, void *judge_ctx,
                           rlo_action_cb action, void *action_ctx,
                           int64_t msg_size_max);
/* Engine over a RANK SUBSET — the reference's engines-over-sub-
 * communicators capability (RLO_progress_engine_new on any MPI_Comm,
 * rootless_ops.c:467, 1461). bcast/IAR span exactly `members` (overlay
 * topology over virtual ranks 0..n_members-1); non-members never see
 * this engine's traffic. `rank` must be a member; create the engine on
 * member ranks only, with a `comm` distinct from any full-world
 * engine's on the same world. */
rlo_engine *rlo_engine_new_sub(rlo_world *w, int rank, int comm,
                               const int *members, int n_members,
                               rlo_judge_cb judge, void *judge_ctx,
                               rlo_action_cb action, void *action_ctx,
                               int64_t msg_size_max);
void rlo_engine_free(rlo_engine *e);

/* Step every engine in the world once (reference RLO_make_progress_all,
 * rootless_ops.c:538-549); re-entrant calls are no-ops. */
void rlo_progress_all(rlo_world *w);

/* ------------------------------------------------------------------ */
/* Batched progress (docs/DESIGN.md S13): loop progress turns INSIDE C */
/* so a driver (the ctypes bindings release the GIL for the call's     */
/* whole duration) pays one crossing for thousands of frames instead   */
/* of one per turn. Both entry points return the number of frames      */
/* polled off the transport (every frame counts: ACKs, heartbeats,     */
/* quarantined and duplicate frames included), or a negative rlo_err.  */
/*                                                                     */
/* Stop conditions (first one wins):                                   */
/*   - max_frames > 0 and that many frames were processed (the budget  */
/*     binds exactly: a turn stops polling mid-inbox, the remainder    */
/*     waits for the next call);                                       */
/*   - deadline_usec > 0 and that many MICROSECONDS have elapsed since */
/*     call entry: the call becomes a busy poll-wait that keeps        */
/*     progressing through idle periods — the serving-pump shape       */
/*     (GIL released, one wakeup per deadline window);                 */
/*   - with no deadline armed, the natural end of the currently        */
/*     flowing work: rlo_world_progress_all_n returns at the first     */
/*     fruitless sweep with the world quiescent (in-flight latency     */
/*     frames on the loopback keep it sweeping until delivered);       */
/*     rlo_engine_progress_n — the single-engine face for the          */
/*     one-process-per-rank transports (shm/tcp/mpi) — returns at the  */
/*     first fruitless turn (it must not spin a multi-engine world     */
/*     whose pending frames belong to other engines).                  */
/* Re-entrant calls (from a judge/action callback) are no-ops          */
/* returning 0, like rlo_progress_all.                                 */
int64_t rlo_engine_progress_n(rlo_engine *e, int64_t max_frames,
                              uint64_t deadline_usec);
int64_t rlo_world_progress_all_n(rlo_world *w, int64_t max_frames,
                                 uint64_t deadline_usec);
/* lifetime count of frames this engine polled off the transport */
int64_t rlo_engine_frames_dispatched(const rlo_engine *e);

/* Rootless broadcast from this rank (reference RLO_bcast_gen :1581). */
int rlo_bcast(rlo_engine *e, const uint8_t *payload, int64_t len);

/* IAR leaderless consensus (reference RLO_submit_proposal :876).
 * Returns the decision (0/1) if it completed within this call, else -1
 * (poll with rlo_check_proposal_state / rlo_vote_my_proposal), or a
 * negative rlo_err. pids must be unique across concurrent proposers. */
int rlo_submit_proposal(rlo_engine *e, const uint8_t *proposal, int64_t len,
                        int pid);
int rlo_check_proposal_state(rlo_engine *e);     /* enum rlo_state */
int rlo_vote_my_proposal(rlo_engine *e);         /* -1 / 0 / 1 */
void rlo_proposal_reset(rlo_engine *e);

/* Delivery (reference RLO_user_pickup_next/RLO_user_msg_recycle
 * :938-992). Copies the payload into buf (cap bytes) and returns its
 * length, filling tag/origin/pid/vote; returns -1 when nothing is
 * deliverable, RLO_ERR_TOO_BIG if cap is too small (message stays
 * queued). */
int64_t rlo_pickup_next(rlo_engine *e, int *tag, int *origin, int *pid,
                        int *vote, uint8_t *buf, int64_t cap);

/* Zero-copy delivery, the native analogue of the reference's
 * pickup-then-recycle pair (the payload stays in the engine's buffer
 * while the app reads it, like RLO_user_pickup_next handing out the
 * engine's own msg buffer until RLO_user_msg_recycle :981-992):
 * rlo_pickup_peek exposes the head deliverable message — fills the
 * fields, points *payload into engine-owned memory, returns the length —
 * without consuming it; rlo_pickup_consume (the `recycle`) then retires
 * exactly the message last peeked, even if progress turns ran in
 * between and changed the queue heads. The payload pointer is valid
 * only until the next call into the engine. peek returns -1 when
 * nothing is deliverable; consume without a pending peek is
 * RLO_ERR_ARG. */
int64_t rlo_pickup_peek(rlo_engine *e, int *tag, int *origin, int *pid,
                        int *vote, const uint8_t **payload);
int rlo_pickup_consume(rlo_engine *e);

/* ------------------------------------------------------------------ */
/* Failure detection + elastic recovery on the engine (net-new — the    */
/* reference defines RLO_FAILED but never assigns it, SURVEY.md §5;     */
/* mirror of the Python engine's failure_timeout machinery): ranks      */
/* heartbeat their ring successor every interval_usec and declare a     */
/* silent predecessor failed after timeout_usec, announce it with a     */
/* rootless FAILURE broadcast, and every survivor re-forms the overlay  */
/* over the alive set so bcast and consensus keep working (pending      */
/* consensus rounds discount dead voters; proposals orphaned by a dead  */
/* proposer or vote-tree parent are dropped). Disabled by default.      */
/* Unlike the Python engine, a late decision for a dropped orphaned     */
/* proposal delivers but does not re-run the action callback.           */
/* ------------------------------------------------------------------ */
int rlo_engine_enable_failure_detection(rlo_engine *e,
                                        uint64_t timeout_usec,
                                        uint64_t interval_usec);

/* ------------------------------------------------------------------ */
/* Reliable delivery (ARQ; net-new — the reference has no timeouts,    */
/* retries, or loss recovery, SURVEY.md §5; mirror of the Python       */
/* engine's arq_rto machinery): every engine frame except heartbeats   */
/* and ACKs carries a per-(src, dst) link sequence number and sits in  */
/* a retransmit queue until the destination's cumulative ACK covers    */
/* it; overdue frames retransmit with exponential backoff, giving up   */
/* after max_retries (a persistently silent peer is the failure        */
/* detector's job). Receivers dedup on (sender, seq) BEFORE tag        */
/* dispatch, so retransmits are idempotent through the                 */
/* store-and-forward broadcast path, and owe the sender a cumulative   */
/* ACK (flushed once per progress turn). Disabled by default.          */
/* ------------------------------------------------------------------ */
int rlo_engine_enable_arq(rlo_engine *e, uint64_t rto_usec,
                          int max_retries);
int64_t rlo_engine_arq_retransmits(const rlo_engine *e);
int64_t rlo_engine_arq_dup_drops(const rlo_engine *e);
/* outstanding reliable frames not yet covered by an ACK */
int64_t rlo_engine_arq_unacked(const rlo_engine *e);
/* frames the ARQ layer abandoned after max_retries (skip notices) */
int64_t rlo_engine_arq_gave_up(const rlo_engine *e);
/* due-heap introspection (docs/DESIGN.md S13; C analogue of the
 * Python engine's _arq_due lazy heap): live heap population (stale
 * entries for acked/re-timed frames linger until their deadline pops
 * them — lazy by design) and the count of O(1) gated retransmit
 * sweeps (ticks that returned on the heap peek alone) */
int64_t rlo_engine_arq_heap_len(const rlo_engine *e);
int64_t rlo_engine_arq_scan_gated(const rlo_engine *e);
/* 1 when this engine has marked `rank` failed */
int rlo_engine_rank_failed(const rlo_engine *e, int rank);
int rlo_engine_failed_count(const rlo_engine *e);
/* 1 when a FAILURE notice about THIS rank arrived (false positive) */
int rlo_engine_suspected_self(const rlo_engine *e);

/* ------------------------------------------------------------------ */
/* Membership epochs + elastic rejoin (net-new, docs/DESIGN.md S8;     */
/* mirror of the Python engine's incarnation/epoch/JOIN machinery).    */
/* Every rank carries a monotone membership epoch (bumped on every     */
/* failure adoption and admission); the send gate stamps the LINK      */
/* epoch of each edge into outgoing frames and receivers quarantine    */
/* (a) traffic from senders they consider failed, (b) frames below    */
/* the per-sender floor set at that sender's admission, (c)           */
/* everything while mid-rejoin. A failed-but-alive rank converges     */
/* back in via Tag.JOIN probes + an IAR admission round over the      */
/* member set, finished by a JOIN_WELCOME + recent-broadcast replay.  */
/* ------------------------------------------------------------------ */
/* Partition the engine's life at this rank: a RESTARTED process       */
/* passes a fresh incarnation BEFORE any traffic; broadcast seqs and   */
/* round generations start at incarnation << 20 so peers' dedup        */
/* windows never swallow the new life's frames. incarnation > 0 also   */
/* starts the engine in JOINER mode (petitioning until welcomed).      */
int rlo_engine_set_incarnation(rlo_engine *e, int incarnation);
/* Explicit rejoin: bump the incarnation, enter joiner mode, petition. */
int rlo_engine_rejoin(rlo_engine *e);
int64_t rlo_engine_epoch(const rlo_engine *e);
int64_t rlo_engine_epoch_quarantined(const rlo_engine *e);
int64_t rlo_engine_rejoins(const rlo_engine *e);
/* 1 while the engine is mid-rejoin (quarantining everything) */
int rlo_engine_awaiting_welcome(const rlo_engine *e);

/* ------------------------------------------------------------------ */
/* Metrics registry (rlo_stats) — native twin of ProgressEngine        */
/* metrics() (rlo_tpu/utils/metrics.py; docs/DESIGN.md §7). Counter    */
/* keys, nesting, and histogram layout are kept IDENTICAL across the   */
/* two engines (bindings.py assembles the same nested dict), asserted  */
/* by the metrics-parity test. Collection of per-link accounting and   */
/* latency histograms is opt-in (rlo_engine_enable_metrics); when off, */
/* the residual hot-path cost is one branch per send/receive — plain   */
/* counters (ARQ totals, bcast/pickup counts) are always live.         */
/* ------------------------------------------------------------------ */

/* log2 latency histogram: bucket i counts samples whose integer part
 * has bit_length i (i.e. [2^(i-1), 2^i) usec); bucket 0 is <= 0, the
 * last bucket absorbs overflow. Mirror of metrics.Histogram. */
#define RLO_HIST_BUCKETS 28
typedef struct rlo_hist {
    int64_t count;
    double sum, min, max;
    int64_t buckets[RLO_HIST_BUCKETS];
} rlo_hist;

/* per-peer link accounting: frames/bytes both ways, retransmits,
 * duplicate drops, and an RTT EWMA measured from ARQ ack timing
 * (first-transmission frames only — Karn's rule — smoothed 1/8).
 * Mirror of metrics.LinkStats. */
typedef struct rlo_link_stats {
    int64_t tx_frames, tx_bytes, rx_frames, rx_bytes;
    int64_t retransmits, dup_drops;
    double rtt_ewma_usec; /* 0 = unmeasured */
} rlo_link_stats;

/* engine-level snapshot: counters + live queue depths (q_pickup +
 * q_wait_and_pickup = the pickup backlog) + op-latency histograms
 * (bcast init -> fan-out complete, proposal submit -> decision,
 * frame receipt -> pickup). ops_failed is always 0 in the C engine
 * (op deadlines are Python-side); the key exists for schema parity. */
typedef struct rlo_stats {
    int64_t sent_bcast, recved_bcast, total_pickup, ops_failed;
    int64_t arq_retransmits, arq_dup_drops, arq_gave_up, arq_unacked;
    /* membership (docs/DESIGN.md S8): current view epoch, frames
     * dropped by the stale-epoch / failed-sender quarantine, and
     * admissions executed (or adopted, joiner side) */
    int64_t epoch, epoch_quarantined, rejoins;
    /* heal-cost block (docs/DESIGN.md S17): membership-view rebinds,
     * frames re-sent by the view-change re-flood, the high-water mark
     * of (my epoch - accepted frame's link epoch), the per-reason
     * breakdown of epoch_quarantined (the three sum to it), and IAR
     * admission rounds LAUNCHED here (designated-admitter side) */
    int64_t view_changes, reflood_frames, epoch_lag_max;
    int64_t quar_mid_rejoin, quar_failed_sender, quar_below_floor;
    int64_t admission_rounds;
    /* churn-proof healing (docs/DESIGN.md S18): epoch catch-ups
     * adopted via Tag.MSYNC (instead of full rejoins), advert entries
     * a re-flood receiver already held (frames the pre-S18 blast
     * would have wasted), and joiners admitted through multi-joiner
     * batched admission records */
    int64_t epoch_syncs, reflood_skipped, batched_admits;
    int64_t q_wait, q_pickup, q_wait_and_pickup, q_iar_pending;
    rlo_hist bcast_complete, proposal_resolve, pickup_wait;
} rlo_stats;

int rlo_engine_enable_metrics(rlo_engine *e, int on);
int rlo_engine_stats(const rlo_engine *e, rlo_stats *out);
/* Fills out[0..min(cap, world_size)-1] (out[rank] for this engine's
 * own rank stays zeroed); returns world_size or RLO_ERR_ARG. */
int rlo_engine_link_stats(const rlo_engine *e, rlo_link_stats *out,
                          int cap);

/* ------------------------------------------------------------------ */
/* In-engine phase profiler (docs/DESIGN.md S10) — native twin of the  */
/* Python engine's ENGINE_PHASE_KEYS schema (rlo_tpu/utils/metrics.py):*/
/* one log2 duration histogram (usec) per stage, FIELD ORDER IDENTICAL */
/* to the Python tuple (rlo-lint R2 pins the pair; the profiler parity */
/* test asserts snapshot equality). Hot-path stages: wire encode /     */
/* decode, one transport isend, one ARQ retransmit-window sweep, tag   */
/* dispatch + handler, one pickup delivery. Per-op protocol phases     */
/* (local observation points): bcast init -> first fan-out send done   */
/* -> all fan-out sends done; proposal submit -> all votes merged ->   */
/* decision fan-out done. Off by default; the disabled path costs one  */
/* predictable branch per instrumented site (no clock read) — the same */
/* overhead contract as the metrics registry. With tracing enabled,    */
/* every sample also emits RLO_EV_PHASE (a = field index, b = usec)    */
/* for the Chrome-timeline duration slices.                            */
/* ------------------------------------------------------------------ */
typedef struct rlo_phase_stats {
    rlo_hist frame_encode, frame_decode, send, arq_scan, tag_dispatch,
             pickup_drain, bcast_first_fwd, bcast_all_delivered,
             prop_votes_aggregated, prop_decision;
} rlo_phase_stats;

int rlo_engine_enable_profiler(rlo_engine *e, int on);
int rlo_engine_phase_stats(const rlo_engine *e, rlo_phase_stats *out);

/* ------------------------------------------------------------------ */
/* Telemetry digest codec (docs/DESIGN.md S17) — the C half of the    */
/* byte-pinned layout in rlo_tpu/wire.py (encode_telem/decode_telem): */
/*   [magic "RLOT\x01":5][flags:u8 bit0=FULL][rank:i32][epoch:i32]    */
/*   [seq:u32][mask:u64][zigzag-LEB128 delta per set mask bit]        */
/* Key order = wire.py TELEM_KEYS: the rlo_stats counter fields       */
/* (ENGINE_COUNTER_KEYS) followed by the extras in k_telem_keys       */
/* (rlo_wire.c) — rlo-lint R2 pins the three against each other.      */
/* ------------------------------------------------------------------ */
#define RLO_TELEM_MAGIC "RLOT\x01"
#define RLO_TELEM_HEADER_SIZE 26
#define RLO_TELEM_NKEYS 39
/* Pure codec (no engine): encode vals[RLO_TELEM_NKEYS] as a digest,
 * delta vs prev (NULL or full != 0 => full snapshot, deltas vs zero).
 * Returns bytes written or RLO_ERR_TOO_BIG/RLO_ERR_ARG. */
int64_t rlo_telem_encode(uint8_t *dst, int64_t cap, int32_t rank,
                         int32_t epoch, uint32_t seq, int full,
                         const int64_t *vals, const int64_t *prev);
/* Decode: fills deltas[RLO_TELEM_NKEYS] (unset keys stay untouched),
 * *mask says which. Returns bytes consumed or RLO_ERR_ARG. */
int64_t rlo_telem_decode(const uint8_t *raw, int64_t rawlen,
                         int32_t *rank, int32_t *epoch, uint32_t *seq,
                         int *full, int64_t *deltas, uint64_t *mask);
/* schema key name for mask bit i (NULL out of range) — the parity
 * surface rlo-lint R2 checks against wire.py's TELEM_KEYS */
const char *rlo_telem_key_name(int i);

/* ------------------------------------------------------------------ */
/* Span context codec (docs/DESIGN.md S19) — the C half of the        */
/* byte-pinned trailer in rlo_tpu/wire.py (encode_span_ctx):          */
/*   [magic "RLOS\x01":5][flags:u8 bit0=sampled][stage:u8]            */
/*   [gateway:i32][seq:i32][t_usec:u64 stage start, origin clock]     */
/* Appended as a TRAILER to fabric record payloads; SIZE % 4 == 3     */
/* makes it structurally unambiguous against i32-word record bodies.  */
/* The engine's pickup path decodes it to emit RLO_EV_SPAN wire-hop   */
/* events — zero cost when tracing is off.                            */
/* ------------------------------------------------------------------ */
#define RLO_SPAN_MAGIC "RLOS\x01"
#define RLO_SPAN_CTX_SIZE 23
/* Pure codec: write one span context into dst. Returns bytes written
 * (RLO_SPAN_CTX_SIZE) or RLO_ERR_ARG on a short buffer. */
int64_t rlo_span_encode(uint8_t *dst, int64_t cap, int32_t gateway,
                        int32_t seq, int stage, int flags,
                        uint64_t t_usec);
/* Decode a span context at raw[0..RLO_SPAN_CTX_SIZE): returns bytes
 * consumed or RLO_ERR_ARG when the bytes are not a span context
 * (absence is the common case, not corruption). */
int64_t rlo_span_decode(const uint8_t *raw, int64_t rawlen,
                        int32_t *gateway, int32_t *seq, int *stage,
                        int *flags, uint64_t *t_usec);
/* Engine-originated digest: samples the engine's own telemetry
 * (counters + link rollups + queue depths; the serving page keys are
 * always 0 in C), delta-encodes vs the last digest THIS call emitted,
 * bumps the per-engine digest seq, and writes the frame payload into
 * buf. full != 0 forces a full snapshot (the first call always is).
 * Returns bytes written or a negative rlo_err. */
int64_t rlo_engine_telem_digest(rlo_engine *e, int full, uint8_t *buf,
                                int64_t cap);

/* ------------------------------------------------------------------ */
/* Engine snapshot/restore (mirror of the checkpoint subsystem's        */
/* engine_state_dict, rlo_tpu/utils/checkpoint.py): a quiesced engine's */
/* durable identity — bcast/pickup counters and own-proposal            */
/* bookkeeping — captured into a flat struct and re-applied onto a      */
/* fresh engine after a process restart. state_get returns RLO_ERR_BUSY */
/* unless the engine is idle, not mid-consensus (own proposal awaiting  */
/* votes or relayed proposals pending), and fully picked up (unlike the */
/* Python snapshot, undelivered pickup messages are NOT captured —      */
/* drain them first). state_set rejects a rank/world mismatch.          */
/* ------------------------------------------------------------------ */
typedef struct rlo_engine_state {
    int32_t rank, world_size;
    int64_t sent_bcast, recved_bcast, total_pickup;
    int32_t prop_pid, prop_state, prop_vote;
    int32_t prop_votes_needed, prop_votes_recved;
    /* round-generation counter: a restored engine must never reissue a
     * pre-snapshot generation (stale in-flight votes could otherwise
     * match a post-restore round) */
    int32_t gen_counter;
    /* exactly-once broadcast sequence counter: a restored engine must
     * never reissue a pre-snapshot seq (peers remembering it as seen
     * would drop the fresh broadcast). The per-origin dedup window and
     * recent-frame log are NOT captured (this struct is a flat POD):
     * the C snapshot assumes whole-world restart, where peers restart
     * with fresh logs and nothing pre-snapshot is ever re-flooded.
     * The Python engine snapshot captures both (checkpoint.py). */
    int32_t bcast_seq;
} rlo_engine_state;
int rlo_engine_state_get(const rlo_engine *e, rlo_engine_state *out);
int rlo_engine_state_set(rlo_engine *e, const rlo_engine_state *in);

/* Spanning-tree shape for bcast/IAR (runtime-selectable; the skip-ring
 * is the reference's overlay, rootless_ops.c:1489; FLAT is depth-1 —
 * origin sends to every live member directly, receivers are leaves.
 * Env default RLO_FANOUT=flat; per-engine override below, only while
 * the engine is idle between rounds). Rootlessness, dedup, and vote
 * accounting are schedule-independent. */
#define RLO_FANOUT_SKIP_RING 0
#define RLO_FANOUT_FLAT 1
int rlo_engine_set_fanout(rlo_engine *e, int mode);

/* 1 when this engine has no outstanding forwards or pending decision */
int rlo_engine_idle(const rlo_engine *e);
int rlo_engine_err(const rlo_engine *e);         /* sticky first error */
int64_t rlo_engine_total_pickup(const rlo_engine *e);
int64_t rlo_engine_sent_bcast(const rlo_engine *e);
int64_t rlo_engine_recved_bcast(const rlo_engine *e);

/* Termination-detection drain (reference cleanup drain,
 * rootless_ops.c:1613-1625): progress until the world is quiescent and
 * every engine idle. Returns spins used, or RLO_ERR_STALL. Collective on
 * multi-process transports (every rank must call it, like the
 * reference's MPI_Iallreduce-based drain). */
int rlo_drain(rlo_world *w, int max_spins);

/* ------------------------------------------------------------------ */
/* Engine-substrate ring data collectives (rlo_coll.c) — the C mirror  */
/* of rlo_tpu/ops/collectives.py: ring reduce-scatter/all-gather       */
/* allreduce, rotation all-to-all, dissemination barrier, over the     */
/* same transport vtable. Explicit state machines: `*_start` arms an   */
/* op, rlo_coll_poll advances one slice (1 = done, 0 = in progress,    */
/* <0 = error). One op may be armed per coll at a time; every rank     */
/* must issue collectives in the same order. The coll's `comm` id      */
/* must differ from every engine comm on the same world.               */
/* Collectives are NOT failure-elastic (MPI-collective semantics): a   */
/* rank dying mid-op stalls the survivors' polls until their spin      */
/* budget (rlo_coll_wait returns RLO_ERR_STALL; on transports with a   */
/* failed() signal the wait aborts as soon as the world is dead). The  */
/* elastic path is the engine substrate: bcast/IAR survive failures    */
/* via the detector + re-formed overlay (rlo_engine.c).                */
/* ------------------------------------------------------------------ */
typedef struct rlo_coll rlo_coll;

enum rlo_coll_op { RLO_COLL_SUM = 0, RLO_COLL_MIN = 1, RLO_COLL_MAX = 2 };

rlo_coll *rlo_coll_new(rlo_world *w, int rank, int comm);
/* Data collectives over a RANK SUBSET (the collective face of
 * rlo_engine_new_sub): ring/rotation schedules run over virtual ranks
 * 0..n_members-1; slot layouts (all_gather / reduce_scatter /
 * all_to_all) are indexed by subset position. `rank` must be a member;
 * use a comm distinct from any full-world context on the same world. */
rlo_coll *rlo_coll_new_sub(rlo_world *w, int rank, int comm,
                           const int *members, int n_members);
void rlo_coll_free(rlo_coll *c);
/* in-place ring allreduce of count floats */
int rlo_coll_allreduce_f32_start(rlo_coll *c, float *data, int64_t count,
                                 int op);
/* rank r receives the r-th of ws equal chunks (identity-padded);
 * out must hold ceil(count/ws) floats */
int rlo_coll_reduce_scatter_f32_start(rlo_coll *c, const float *data,
                                      int64_t count, float *out, int op);
/* out must hold ws*len bytes; slot r = rank r's data */
int rlo_coll_all_gather_start(rlo_coll *c, const uint8_t *data,
                              int64_t len, uint8_t *out);
/* data/out are ws slots of len_per_rank bytes; out slot s = the chunk
 * rank s addressed to this rank */
int rlo_coll_all_to_all_start(rlo_coll *c, const uint8_t *data,
                              int64_t len_per_rank, uint8_t *out);
int rlo_coll_barrier_start(rlo_coll *c);
int rlo_coll_poll(rlo_coll *c);
/* spin poll to completion — one-process-per-rank transports only */
int rlo_coll_wait(rlo_coll *c, long max_spins);

/* ------------------------------------------------------------------ */
/* Wholly-native micro-benchmarks (rlo_bench.c / rlo_coll.c): median   */
/* usec per op on an in-process loopback world, no Python in the       */
/* measured loop. ctypes entry points for benchmarks/suite.py; also    */
/* linked by rlo_demo's nbcast floor analysis. Negative = rlo_err.     */
/* ------------------------------------------------------------------ */
/* bcast-gather fp32 allreduce over the engine substrate */
double rlo_bench_allreduce(int world_size, int64_t count, int reps);
/* ring fp32 allreduce (rlo_coll.c state machines round-robined in C) —
 * the bandwidth-optimal comparison line against bcast-gather */
double rlo_bench_allreduce_ring(int world_size, int64_t count, int reps);
/* one rootless broadcast of nbytes, initiation to full delivery */
double rlo_bench_bcast_usec(int world_size, int64_t nbytes, int reps);

/* ------------------------------------------------------------------ */
/* Timing utils (reference RLO_get_time_usec, rootless_ops.c:128-132).  */
/* ------------------------------------------------------------------ */
uint64_t rlo_now_usec(void);

/* ------------------------------------------------------------------ */
/* Structured event tracing. The reference has none beyond printf       */
/* tracepoints and an unused Log global (SURVEY.md §5). Event kinds and */
/* semantics are shared with the Python tracer                          */
/* (rlo_tpu/utils/tracing.py); disabled by default — one branch per     */
/* emit when off. Process-local ring; oldest events drop when full.     */
/* ------------------------------------------------------------------ */
/* Field semantics are shared with the Python tracer (tracing.Ev); the
 * c/d fields carry the correlation identity the cross-rank timeline
 * merger (rlo_tpu/utils/timeline.py) keys on: identity = the
 * per-origin exactly-once seq for BCAST frames, the pid for IAR /
 * FAILURE / ABORT traffic; d = the immediate sender (what turns
 * per-rank logs into send->recv flow edges). */
enum rlo_ev {
    RLO_EV_BCAST_INIT = 1, /* a = tag, b = payload len, c = seq/pid */
    RLO_EV_BCAST_FWD = 2,  /* receipt+forward step (emitted even for
                            * zero-target leaf receipts): a = tag,
                            * b = origin, c = seq/pid, d = sender */
    RLO_EV_DELIVER = 3,    /* a = tag, b = origin, c = seq/pid,
                            * d = sender */
    RLO_EV_PROPOSAL_SUBMIT = 4, /* a = pid, c = round generation */
    RLO_EV_JUDGE = 5,      /* a = pid of the judged proposal, b = verdict */
    RLO_EV_VOTE = 6,       /* a = pid, b = merged vote, c = generation */
    RLO_EV_DECISION = 7,   /* a = pid, b = decision, c = generation */
    RLO_EV_DRAIN = 8,      /* a = spins */
    RLO_EV_HEARTBEAT = 9,  /* a = destination rank */
    RLO_EV_FAILURE = 10,   /* a = failed rank, b = 1 local / 0 learned;
                            * c = last-seen heartbeat age (usec, clamped
                            * to int32) on local detections */
    RLO_EV_ARQ_GIVEUP = 11, /* ARQ exhausted its retries at a live peer
                             * (now declared failed): a = peer,
                             * b = retransmit count */
    RLO_EV_JOIN = 12,      /* membership probe: a = peer, b = 1 sent /
                            * 0 received, c = incarnation, d = epoch */
    RLO_EV_ADMIT = 13,     /* admission executed/adopted: a = joiner,
                            * b = new epoch, c = joiner incarnation */
    RLO_EV_PHASE = 14,     /* phase-profiler stage sample (docs/DESIGN.md
                            * S10): a = field index in rlo_phase_stats /
                            * ENGINE_PHASE_KEYS order, b = duration
                            * (usec, clamped to int32); the timeline
                            * merger renders a duration slice ENDING at
                            * ts_usec */
    RLO_EV_SPAN = 15,      /* request-scoped causal span (docs/DESIGN.md
                            * S19): a = stage id, b = duration (usec;
                            * -1 = wire-hop receipt of a span-stamped
                            * record), c = rid seq, d = rid gateway */
    RLO_EV_STEP = 16,      /* collective data-plane step (docs/DESIGN.md
                            * S21): a = schedule id (observe.ledger
                            * ALGORITHMS index), b = step duration
                            * (usec, clamped to int32), c = op id * 1024
                            * + step index, d = rank received from (-1
                            * for send-only steps). The C engine hosts
                            * no tensor collectives yet and never emits
                            * it; the id is reserved here so the merged
                            * timeline's numbering can't be reused. */
};

typedef struct rlo_trace_event {
    uint64_t ts_usec;
    int32_t rank;
    int32_t kind; /* enum rlo_ev */
    int32_t a, b, c, d;
} rlo_trace_event;

void rlo_trace_set(int enabled);
int rlo_trace_enabled(void);
void rlo_trace_emit(int rank, int kind, int a, int b, int c, int d);
/* Copies up to max oldest-first events into out and removes them;
 * returns the count. */
int rlo_trace_drain(rlo_trace_event *out, int max);
int64_t rlo_trace_dropped(void);
int rlo_trace_capacity(void);
void rlo_trace_clear(void);

#ifdef __cplusplus
}
#endif
#endif /* RLO_CORE_H */
