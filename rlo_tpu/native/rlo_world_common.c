/* Transport-independent world machinery: engine registry, cooperative
 * progress loop, and dispatch to the transport vtable.
 *
 * The registry + progress loop mirror the reference's EngineManager /
 * Active_Engines / RLO_make_progress_all (rootless_ops.c:33-47, 407-466,
 * 538-549): multiple engines may live on one world (each on its own comm
 * id — the analogue of the dup'ed MPI communicator per engine,
 * rootless_ops.c:1461), and one progress turn steps all of them so
 * engines co-progress each other (testcases.c:110-241 relies on this).
 */
#include "rlo_internal.h"

#include <string.h>

/* ---- small-object pool (rlo_internal.h has the design notes) ---- */

static const size_t POOL_CEILING[RLO_POOL_CLASSES] = {
    RLO_POOL_C0, RLO_POOL_C1, RLO_POOL_C2, RLO_POOL_C3};

void *rlo_pool_alloc(rlo_world *w, size_t size)
{
#ifdef RLO_POOL_PASSTHROUGH
    w = 0; /* sanitizer builds: every object is a fresh malloc */
#endif
    size_t cls = RLO_POOL_CLASSES;
    if (w)
        for (cls = 0; cls < RLO_POOL_CLASSES; cls++)
            if (size <= POOL_CEILING[cls])
                break;
    if (w && cls < RLO_POOL_CLASSES && w->pool_free[cls]) {
        rlo_pool_hdr *h = (rlo_pool_hdr *)w->pool_free[cls];
        w->pool_free[cls] = h->link;
        h->link = w;
        return h + 1;
    }
    rlo_pool_hdr *h = (rlo_pool_hdr *)malloc(
        sizeof(*h) +
        (cls < RLO_POOL_CLASSES ? POOL_CEILING[cls] : size));
    if (!h)
        return 0;
    h->link = cls < RLO_POOL_CLASSES ? (void *)w : 0;
    h->cls = cls;
    return h + 1;
}

void rlo_pool_free(void *p)
{
    if (!p)
        return;
    rlo_pool_hdr *h = (rlo_pool_hdr *)p - 1;
    rlo_world *w = (rlo_world *)h->link;
    if (!w || h->cls >= RLO_POOL_CLASSES) {
        free(h);
        return;
    }
    h->link = w->pool_free[h->cls];
    w->pool_free[h->cls] = h;
}

void rlo_pool_drain(rlo_world *w)
{
    for (int c = 0; c < RLO_POOL_CLASSES; c++) {
        for (void *p = w->pool_free[c]; p;) {
            void *next = ((rlo_pool_hdr *)p)->link;
            free(p);
            p = next;
        }
        w->pool_free[c] = 0;
    }
    free(w->sweep_scratch);
    w->sweep_scratch = 0;
    w->sweep_cap = 0;
}

int rlo_world_size(const rlo_world *w)
{
    return w->world_size;
}

int rlo_world_my_rank(const rlo_world *w)
{
    return w->my_rank;
}

const char *rlo_world_transport(const rlo_world *w)
{
    return w->ops->name;
}

int64_t rlo_world_sent_cnt(const rlo_world *w)
{
    return w->ops->sent_cnt(w);
}

int64_t rlo_world_delivered_cnt(const rlo_world *w)
{
    return w->ops->delivered_cnt(w);
}

int rlo_world_quiescent(const rlo_world *w)
{
    return w->ops->quiescent(w);
}

int rlo_world_failed(const rlo_world *w)
{
    return w->ops->failed ? w->ops->failed(w) : 0;
}

/* Test support: inject one raw frame as if `src` had sent it —
 * duplicate/stale-frame scenarios (e.g. a decision replayed by a
 * mixed-overlay forward during a view change) need a way to place
 * arbitrary wire bytes on a channel. In-process worlds only. */
int rlo_world_inject(rlo_world *w, int src, int dst, int comm, int tag,
                     const uint8_t *raw, int64_t len)
{
    if (!w || !raw || len < 0 || src < 0 || src >= w->world_size ||
        dst < 0 || dst >= w->world_size)
        return RLO_ERR_ARG;
    rlo_blob *b = rlo_blob_new(len);
    if (!b)
        return RLO_ERR_NOMEM;
    memcpy(b->data, raw, (size_t)len);
    /* prefer the transport's direct-delivery hook: it bypasses latency
     * and fault injection, so src may be a dead rank (mirror of
     * LoopbackWorld.inject — the stale-frame quarantine scenarios) */
    int rc = w->ops->inject
                 ? w->ops->inject(w, src, dst, comm, tag, b)
                 : rlo_world_isend(w, src, dst, comm, tag, b, 0);
    rlo_blob_unref(b);
    return rc;
}

void rlo_world_barrier(rlo_world *w)
{
    if (w->ops->barrier)
        w->ops->barrier(w);
}

int rlo_world_peer_alive(const rlo_world *w, int rank,
                         uint64_t timeout_usec)
{
    if (rank < 0 || rank >= w->world_size)
        return 0;
    if (!w->ops->peer_alive)
        return 1; /* no liveness signal: in-process peers can't die */
    return w->ops->peer_alive(w, rank, timeout_usec);
}

int rlo_world_kill_rank(rlo_world *w, int rank)
{
    if (!w->ops->kill_rank)
        return RLO_ERR_ARG;
    return w->ops->kill_rank(w, rank);
}

int rlo_world_drop_next(rlo_world *w, int src, int dst, int count)
{
    if (!w->ops->drop_next)
        return RLO_ERR_ARG;
    return w->ops->drop_next(w, src, dst, count);
}

int rlo_world_dup_next(rlo_world *w, int src, int dst, int count)
{
    if (!w->ops->dup_next)
        return RLO_ERR_ARG;
    return w->ops->dup_next(w, src, dst, count);
}

int rlo_world_partition(rlo_world *w, const int *group_of, int n)
{
    if (!w->ops->partition)
        return RLO_ERR_ARG;
    return w->ops->partition(w, group_of, n);
}

int rlo_world_revive_rank(rlo_world *w, int rank)
{
    if (!w->ops->revive)
        return RLO_ERR_ARG;
    return w->ops->revive(w, rank);
}

void rlo_world_free(rlo_world *w)
{
    if (!w)
        return;
    w->ops->free_(w);
}

int rlo_world_isend(rlo_world *w, int src, int dst, int comm, int tag,
                    rlo_blob *frame, rlo_handle **out)
{
    return w->ops->isend(w, src, dst, comm, tag, frame, out);
}

int rlo_world_isend_hdr(rlo_world *w, int src, int dst, int comm,
                        int tag, const uint8_t *hdr, rlo_blob *frame,
                        rlo_handle **out)
{
    if (frame->len < RLO_HEADER_SIZE)
        return RLO_ERR_ARG;
    if (w->ops->isend_hdr)
        return w->ops->isend_hdr(w, src, dst, comm, tag, hdr, frame,
                                 out);
    /* fallback: materialize the stamped header + shared payload into
     * one contiguous frame (one copy — the pre-S13 behavior for
     * transports without scatter-gather) */
    rlo_blob *b = rlo_blob_new_w(w, frame->len);
    if (!b)
        return RLO_ERR_NOMEM;
    memcpy(b->data, hdr, RLO_HEADER_SIZE);
    memcpy(b->data + RLO_HEADER_SIZE, frame->data + RLO_HEADER_SIZE,
           (size_t)(frame->len - RLO_HEADER_SIZE));
    int rc = w->ops->isend(w, src, dst, comm, tag, b, out);
    rlo_blob_unref(b);
    return rc;
}

/* rlo-sentinel: owns — the polled node belongs to the caller */
rlo_wire_node *rlo_world_poll(rlo_world *w, int rank, int comm)
{
    return w->ops->poll(w, rank, comm);
}

int rlo_world_register(rlo_world *w, rlo_engine *e)
{
    if (w->n_engines == w->cap_engines) {
        int cap = w->cap_engines ? w->cap_engines * 2 : 8;
        rlo_engine **p = (rlo_engine **)realloc(
            w->engines, (size_t)cap * sizeof(void *));
        if (!p)
            return RLO_ERR_NOMEM;
        w->engines = p;
        w->cap_engines = cap;
    }
    w->engines[w->n_engines++] = e;
    return RLO_OK;
}

void rlo_world_unregister(rlo_world *w, rlo_engine *e)
{
    for (int i = 0; i < w->n_engines; i++) {
        if (w->engines[i] == e) {
            memmove(&w->engines[i], &w->engines[i + 1],
                    (size_t)(w->n_engines - i - 1) * sizeof(void *));
            w->n_engines--;
            return;
        }
    }
}

/* One sweep: every engine gets one progress turn, sharing a frame
 * budget (budget < 0 = unbounded). Returns frames polled across the
 * sweep. Re-entrant calls are no-ops returning 0. */
static int64_t world_sweep(rlo_world *w, int64_t budget)
{
    /* handlers may initiate broadcasts (decision bcast inside the vote
     * handler) which re-enter; make nested turns no-ops (mirrors
     * EngineManager._stepping, rlo_tpu/engine.py) */
    if (w->stepping)
        return 0;
    w->stepping = 1;
    int64_t total = 0;
    /* step over a snapshot: callbacks may register/unregister engines
     * mid-turn (the Python side iterates a copy for the same reason).
     * The snapshot buffer is world-owned scratch, reused sweep to
     * sweep — the stepping guard rules out concurrent sweeps. */
    int n = w->n_engines;
    if (n > w->sweep_cap) {
        int cap = w->sweep_cap ? w->sweep_cap * 2 : 8;
        while (cap < n)
            cap *= 2;
        rlo_engine **s = (rlo_engine **)realloc(
            w->sweep_scratch, (size_t)cap * sizeof(void *));
        if (s) {
            w->sweep_scratch = s;
            w->sweep_cap = cap;
        }
    }
    rlo_engine **snap = w->sweep_scratch;
    if (snap && n <= w->sweep_cap) {
        if (n > 0) /* engines may be NULL pre-registration (UBSan) */
            memcpy(snap, w->engines, (size_t)n * sizeof(void *));
        for (int i = 0; i < n; i++) {
            if (budget >= 0 && total >= budget)
                break; /* the rest of the sweep waits for more budget */
            /* skip engines freed by an earlier engine's callback */
            int live = 0;
            for (int j = 0; j < w->n_engines; j++)
                if (w->engines[j] == snap[i])
                    live = 1;
            if (live)
                total += rlo_engine_progress_budget(
                    snap[i], budget >= 0 ? budget - total : -1);
        }
    }
    w->stepping = 0;
    return total;
}

void rlo_progress_all(rlo_world *w)
{
    world_sweep(w, -1);
}

/* Batched world progress (docs/DESIGN.md S13; contract in rlo_core.h):
 * sweep until the budget fills, the deadline expires, or — with no
 * deadline — the first fruitless sweep with a quiescent transport
 * (in-flight latency frames keep it sweeping: every loopback poll
 * advances the delivery clock, so a non-quiescent world always makes
 * progress toward the next due frame). A fruitless-sweep fuse bounds
 * the pathological case of in-flight frames no registered engine will
 * ever poll (a comm whose engine was freed mid-traffic). */
#define RLO_PROGRESS_FRUITLESS_FUSE 65536

int64_t rlo_world_progress_all_n(rlo_world *w, int64_t max_frames,
                                 uint64_t deadline_usec)
{
    if (!w)
        return RLO_ERR_ARG;
    if (w->stepping)
        return 0; /* re-entered from a handler: no-op */
    uint64_t end = deadline_usec ? rlo_now_usec() + deadline_usec : 0;
    int64_t total = 0;
    int64_t fruitless = 0;
    for (;;) {
        /* dead-time skip BEFORE each sweep: frames waiting out
         * injected latency jump straight to deliverable (the one-poll-
         * per-tick path would burn a sweep per dead tick); a no-op on
         * real-time transports and on latency-free worlds */
        int64_t moved = w->ops->advance ? w->ops->advance(w) : 0;
        int64_t got = world_sweep(
            w, max_frames > 0 ? max_frames - total : -1);
        total += got;
        if (max_frames > 0 && total >= max_frames)
            break;
        if (got == 0 && moved == 0) {
            if (!end && (rlo_world_quiescent(w) ||
                         ++fruitless >= RLO_PROGRESS_FRUITLESS_FUSE))
                break;
        } else {
            fruitless = 0;
        }
        if (end && rlo_now_usec() >= end)
            break;
    }
    return total;
}

int rlo_drain(rlo_world *w, int max_spins)
{
    return w->ops->drain(w, max_spins);
}

/* Shared single-process drain loop used by transports whose quiescent()
 * predicate is globally accurate from one process (loopback; MPI uses its
 * own collective protocol). */
int rlo_drain_local(rlo_world *w, int max_spins)
{
    for (int i = 0; i < max_spins; i++) {
        rlo_progress_all(w);
        if (rlo_world_quiescent(w)) {
            int idle = 1;
            for (int j = 0; j < w->n_engines; j++)
                if (!rlo_engine_idle(w->engines[j]))
                    idle = 0;
            if (idle)
                return i;
        }
    }
    return RLO_ERR_STALL;
}
