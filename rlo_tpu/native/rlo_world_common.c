/* Transport-independent world machinery: engine registry, cooperative
 * progress loop, and dispatch to the transport vtable.
 *
 * The registry + progress loop mirror the reference's EngineManager /
 * Active_Engines / RLO_make_progress_all (rootless_ops.c:33-47, 407-466,
 * 538-549): multiple engines may live on one world (each on its own comm
 * id — the analogue of the dup'ed MPI communicator per engine,
 * rootless_ops.c:1461), and one progress turn steps all of them so
 * engines co-progress each other (testcases.c:110-241 relies on this).
 */
#include "rlo_internal.h"

#include <string.h>

int rlo_world_size(const rlo_world *w)
{
    return w->world_size;
}

int rlo_world_my_rank(const rlo_world *w)
{
    return w->my_rank;
}

const char *rlo_world_transport(const rlo_world *w)
{
    return w->ops->name;
}

int64_t rlo_world_sent_cnt(const rlo_world *w)
{
    return w->ops->sent_cnt(w);
}

int64_t rlo_world_delivered_cnt(const rlo_world *w)
{
    return w->ops->delivered_cnt(w);
}

int rlo_world_quiescent(const rlo_world *w)
{
    return w->ops->quiescent(w);
}

int rlo_world_failed(const rlo_world *w)
{
    return w->ops->failed ? w->ops->failed(w) : 0;
}

/* Test support: inject one raw frame as if `src` had sent it —
 * duplicate/stale-frame scenarios (e.g. a decision replayed by a
 * mixed-overlay forward during a view change) need a way to place
 * arbitrary wire bytes on a channel. In-process worlds only. */
int rlo_world_inject(rlo_world *w, int src, int dst, int comm, int tag,
                     const uint8_t *raw, int64_t len)
{
    if (!w || !raw || len < 0 || src < 0 || src >= w->world_size ||
        dst < 0 || dst >= w->world_size)
        return RLO_ERR_ARG;
    rlo_blob *b = rlo_blob_new(len);
    if (!b)
        return RLO_ERR_NOMEM;
    memcpy(b->data, raw, (size_t)len);
    int rc = rlo_world_isend(w, src, dst, comm, tag, b, 0);
    rlo_blob_unref(b);
    return rc;
}

void rlo_world_barrier(rlo_world *w)
{
    if (w->ops->barrier)
        w->ops->barrier(w);
}

int rlo_world_peer_alive(const rlo_world *w, int rank,
                         uint64_t timeout_usec)
{
    if (rank < 0 || rank >= w->world_size)
        return 0;
    if (!w->ops->peer_alive)
        return 1; /* no liveness signal: in-process peers can't die */
    return w->ops->peer_alive(w, rank, timeout_usec);
}

int rlo_world_kill_rank(rlo_world *w, int rank)
{
    if (!w->ops->kill_rank)
        return RLO_ERR_ARG;
    return w->ops->kill_rank(w, rank);
}

int rlo_world_drop_next(rlo_world *w, int src, int dst, int count)
{
    if (!w->ops->drop_next)
        return RLO_ERR_ARG;
    return w->ops->drop_next(w, src, dst, count);
}

int rlo_world_dup_next(rlo_world *w, int src, int dst, int count)
{
    if (!w->ops->dup_next)
        return RLO_ERR_ARG;
    return w->ops->dup_next(w, src, dst, count);
}

int rlo_world_partition(rlo_world *w, const int *group_of, int n)
{
    if (!w->ops->partition)
        return RLO_ERR_ARG;
    return w->ops->partition(w, group_of, n);
}

int rlo_world_revive_rank(rlo_world *w, int rank)
{
    if (!w->ops->revive)
        return RLO_ERR_ARG;
    return w->ops->revive(w, rank);
}

void rlo_world_free(rlo_world *w)
{
    if (!w)
        return;
    w->ops->free_(w);
}

int rlo_world_isend(rlo_world *w, int src, int dst, int comm, int tag,
                    rlo_blob *frame, rlo_handle **out)
{
    return w->ops->isend(w, src, dst, comm, tag, frame, out);
}

rlo_wire_node *rlo_world_poll(rlo_world *w, int rank, int comm)
{
    return w->ops->poll(w, rank, comm);
}

int rlo_world_register(rlo_world *w, rlo_engine *e)
{
    if (w->n_engines == w->cap_engines) {
        int cap = w->cap_engines ? w->cap_engines * 2 : 8;
        rlo_engine **p = (rlo_engine **)realloc(
            w->engines, (size_t)cap * sizeof(void *));
        if (!p)
            return RLO_ERR_NOMEM;
        w->engines = p;
        w->cap_engines = cap;
    }
    w->engines[w->n_engines++] = e;
    return RLO_OK;
}

void rlo_world_unregister(rlo_world *w, rlo_engine *e)
{
    for (int i = 0; i < w->n_engines; i++) {
        if (w->engines[i] == e) {
            memmove(&w->engines[i], &w->engines[i + 1],
                    (size_t)(w->n_engines - i - 1) * sizeof(void *));
            w->n_engines--;
            return;
        }
    }
}

void rlo_progress_all(rlo_world *w)
{
    /* handlers may initiate broadcasts (decision bcast inside the vote
     * handler) which re-enter; make nested turns no-ops (mirrors
     * EngineManager._stepping, rlo_tpu/engine.py) */
    if (w->stepping)
        return;
    w->stepping = 1;
    /* step over a snapshot: callbacks may register/unregister engines
     * mid-turn (the Python side iterates a copy for the same reason) */
    int n = w->n_engines;
    rlo_engine **snap =
        (rlo_engine **)malloc((size_t)(n ? n : 1) * sizeof(void *));
    if (snap) {
        if (n > 0) /* engines may be NULL pre-registration (UBSan) */
            memcpy(snap, w->engines, (size_t)n * sizeof(void *));
        for (int i = 0; i < n; i++) {
            /* skip engines freed by an earlier engine's callback */
            int live = 0;
            for (int j = 0; j < w->n_engines; j++)
                if (w->engines[j] == snap[i])
                    live = 1;
            if (live)
                rlo_engine_progress_once(snap[i]);
        }
        free(snap);
    }
    w->stepping = 0;
}

int rlo_drain(rlo_world *w, int max_spins)
{
    return w->ops->drain(w, max_spins);
}

/* Shared single-process drain loop used by transports whose quiescent()
 * predicate is globally accurate from one process (loopback; MPI uses its
 * own collective protocol). */
int rlo_drain_local(rlo_world *w, int max_spins)
{
    for (int i = 0; i < max_spins; i++) {
        rlo_progress_all(w);
        if (rlo_world_quiescent(w)) {
            int idle = 1;
            for (int j = 0; j < w->n_engines; j++)
                if (!rlo_engine_idle(w->engines[j]))
                    idle = 0;
            if (idle)
                return i;
        }
    }
    return RLO_ERR_STALL;
}
