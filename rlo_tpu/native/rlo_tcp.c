/* TCP socket transport: one process per rank, a full mesh of
 * nonblocking stream sockets — the control plane genuinely crossing
 * host boundaries (round-4 VERDICT "What's missing" #2: every prior
 * executing transport was single-host; the reference deploys on any
 * MPI cluster, rootless_ops.c:1123 MPI_Isend across machines).
 *
 * Endpoints come from RLO_TCP_HOSTS ("host:port,host:port,..." — one
 * per rank, so ranks may live on different machines) or default to
 * 127.0.0.1 ports RLO_TCP_PORT_BASE+rank. Connection setup: rank r
 * listens, connects to every lower rank (with retry while peers boot),
 * accepts from every higher rank; a 4-byte hello identifies the
 * connector. After setup all sockets are nonblocking + TCP_NODELAY.
 *
 * Wire: [src:i32][tag:i32][comm:i32][pad:i32][len:i64] then the frame
 * bytes (dst is implied by the socket). Send semantics are buffered
 * like the SHM transport: the frame is queued per destination, flushed
 * opportunistically from isend/poll, and the completion handle reports
 * delivered once the kernel accepted every byte.
 *
 * Flushing is scatter-gather (docs/DESIGN.md S13): one sendmsg carries
 * up to TCP_IOV_BATCH iovecs spanning as many queued frames as fit, so
 * ACKs, heartbeats, and small broadcasts share a syscall instead of
 * paying one each. A short write leaves the first incomplete frame's
 * offset mid-node and the next flush resumes exactly there — per-peer
 * byte order is the queue order regardless of batching. The transport
 * also implements the optional isend_hdr gather op: a restamped
 * 28-byte frame header rides node-local staging while the payload goes
 * to the kernel straight from the engine's shared blob (zero-copy for
 * large ARQ-stamped messages). RLO_TCP_SNDBUF shrinks SO_SNDBUF —
 * selftest support for forcing partial writes deterministically.
 *
 * Termination detection (reference rootless_ops.c:1613-1625 drain,
 * generalized like the MPI transport's): when all local engines are
 * idle and the socket queues quiescent, a two-pass ring allreduce of
 * [global sent, global delivered] runs over transport-internal control
 * frames (comm TCP_CTRL_COMM, invisible to engines); the drain ends
 * when the sums agree twice in a row. The barrier is the same ring
 * token without payload. Both keep pumping data frames while waiting,
 * so a drain entered mid-traffic still converges.
 *
 * Failure signal: a peer's socket EOF/reset marks the world failed
 * (rlo_world_failed) — the net-new failure-detection surface the
 * reference lacks (SURVEY.md §5). */
#define _GNU_SOURCE
#include "rlo_internal.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sched.h>
#include <stdio.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <time.h>
#include <unistd.h>

#define TCP_MAX_RANKS 256
#define TCP_DEFAULT_PORT_BASE 29500
#define TCP_CONNECT_TIMEOUT_SEC 30
#define TCP_CTRL_TIMEOUT_SEC 120
#define TCP_MAX_FRAME (1ll << 30)
/* iovecs per sendmsg batch: 3 per frame worst case (transport header,
 * staged frame header, payload), comfortably under every platform's
 * IOV_MAX (Linux 1024) */
#define TCP_IOV_BATCH 64

#define TCP_CTRL_COMM 0x7ffffffe /* transport-internal frames */
/* ctrl tags */
#define CT_SUM_FWD 0  /* drain ring pass 1: accumulate */
#define CT_SUM_BCK 1  /* drain ring pass 2: broadcast total */
#define CT_BAR_FWD 2  /* barrier pass 1 */
#define CT_BAR_BCK 3  /* barrier pass 2 */

typedef struct tcp_hdr {
    int32_t src, tag, comm, pad;
    int64_t len;
} tcp_hdr;

typedef struct tcp_send_node {
    struct tcp_send_node *next;
    tcp_hdr hdr;
    /* isend_hdr gather nodes: the restamped frame header lives in
     * this staging and the payload stays in `frame` past body_off
     * (fhdr_len == 0 marks a whole-frame node — every wire byte after
     * the transport header comes from `frame` at offset 0). Both node
     * shapes emit exactly hdr.len == frame->len frame bytes, so the
     * receiver cannot tell them apart. */
    uint8_t fhdr[RLO_HEADER_SIZE];
    size_t fhdr_len; /* 0 or RLO_HEADER_SIZE */
    size_t body_off; /* first frame byte taken from frame->data */
    rlo_blob *frame;
    size_t off; /* bytes of (hdr+fhdr+body) already written */
    rlo_handle *handle;
} tcp_send_node;

/* wire bytes this node emits in total */
static size_t node_total(const tcp_send_node *n)
{
    return sizeof n->hdr + n->fhdr_len +
           ((size_t)n->frame->len - n->body_off);
}

typedef struct tcp_peer {
    int fd;                        /* -1 for self */
    int crashed;                   /* reset / EPIPE / mid-frame EOF */
    tcp_send_node *sq_head, *sq_tail;
    /* receive reassembly */
    tcp_hdr rhdr;
    size_t rhdr_got;
    rlo_blob *rframe;
    size_t rframe_got;
} tcp_peer;

typedef struct rlo_tcp_world {
    rlo_world base;
    tcp_peer peers[TCP_MAX_RANKS];
    rlo_wire_node *inbox_head, *inbox_tail; /* data frames, un-polled */
    rlo_wire_node *ctrl_head, *ctrl_tail;   /* control frames */
    int64_t sent_cnt, recv_cnt;
    int failed;
} rlo_tcp_world;

static uint64_t now_sec(void)
{
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec;
}

static void set_nonblock(int fd)
{
    int fl = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

static void set_nodelay(int fd)
{
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
}

static void tcp_peer_crashed(rlo_tcp_world *w, tcp_peer *p);

/* Flush as much of dst's queue as the kernel accepts right now: gather
 * up to TCP_IOV_BATCH iovecs across queued frames into one sendmsg
 * (the coalescing rules of docs/DESIGN.md S13 — frames already queued
 * when the syscall fires share it; nothing is delayed waiting for
 * company). A short write advances node offsets in queue order and
 * the next flush resumes at the first incomplete byte. */
static int tcp_flush_peer(rlo_tcp_world *w, int dst)
{
    tcp_peer *p = &w->peers[dst];
    while (p->sq_head) {
        struct iovec iov[TCP_IOV_BATCH];
        int niov = 0;
        size_t batch = 0;
        for (tcp_send_node *n = p->sq_head;
             n && niov + 3 <= TCP_IOV_BATCH; n = n->next) {
            size_t hdr_sz = sizeof n->hdr;
            size_t fhdr_end = hdr_sz + n->fhdr_len;
            size_t total = node_total(n);
            size_t off = n->off;
            if (off < hdr_sz) {
                iov[niov].iov_base = (uint8_t *)&n->hdr + off;
                iov[niov++].iov_len = hdr_sz - off;
                off = hdr_sz;
            }
            if (off < fhdr_end) {
                iov[niov].iov_base = n->fhdr + (off - hdr_sz);
                iov[niov++].iov_len = fhdr_end - off;
                off = fhdr_end;
            }
            if (off < total) {
                iov[niov].iov_base =
                    n->frame->data + n->body_off + (off - fhdr_end);
                iov[niov++].iov_len = total - off;
            }
            batch += total - n->off;
        }
        struct msghdr mh;
        memset(&mh, 0, sizeof mh);
        mh.msg_iov = iov;
        mh.msg_iovlen = (size_t)niov;
        ssize_t k = sendmsg(p->fd, &mh, MSG_NOSIGNAL);
        if (k < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK)
                return RLO_OK; /* kernel buffer full: try later */
            tcp_peer_crashed(w, p); /* EPIPE/reset: the peer died */
            return RLO_ERR_STALL;
        }
        size_t wrote = (size_t)k;
        /* consume the written bytes across the queue head (partial-
         * write resume: the first incomplete node keeps its offset) */
        size_t left = wrote;
        while (left > 0 && p->sq_head) {
            tcp_send_node *n = p->sq_head;
            size_t total = node_total(n);
            size_t take =
                left < total - n->off ? left : total - n->off;
            n->off += take;
            left -= take;
            if (n->off < total)
                break;
            p->sq_head = n->next;
            if (!p->sq_head)
                p->sq_tail = 0;
            if (n->handle) {
                n->handle->delivered = 1;
                rlo_handle_unref(n->handle);
            }
            rlo_blob_unref(n->frame);
            rlo_pool_free(n);
        }
        if (wrote < batch)
            return RLO_OK; /* kernel took a partial batch: try later */
    }
    return RLO_OK;
}

/* Queue one frame for dst. fhdr != NULL is the gather shape: fhdr's
 * RLO_HEADER_SIZE restamped bytes replace the frame blob's own header
 * on the wire and the payload is taken from the blob past the header
 * (the isend_hdr zero-copy path); fhdr == NULL ships the whole blob. */
static int tcp_enqueue(rlo_tcp_world *w, int dst, int comm, int tag,
                       const uint8_t *fhdr, rlo_blob *frame,
                       rlo_handle **out)
{
    tcp_peer *p = &w->peers[dst];
    tcp_send_node *n =
        (tcp_send_node *)rlo_pool_alloc(&w->base, sizeof(*n));
    rlo_handle *h = out ? rlo_handle_new_w(&w->base, 2) : 0;
    if (!n || (out && !h)) {
        rlo_pool_free(n);
        rlo_pool_free(h);
        return RLO_ERR_NOMEM;
    }
    memset(n, 0, sizeof(*n));
    n->hdr.src = w->base.my_rank;
    n->hdr.tag = tag;
    n->hdr.comm = comm;
    n->hdr.len = frame->len;
    if (fhdr) {
        memcpy(n->fhdr, fhdr, RLO_HEADER_SIZE);
        n->fhdr_len = RLO_HEADER_SIZE;
        n->body_off = RLO_HEADER_SIZE;
    }
    n->frame = rlo_blob_ref(frame);
    n->handle = h;
    if (p->sq_tail)
        p->sq_tail->next = n;
    else
        p->sq_head = n;
    p->sq_tail = n;
    if (out)
        *out = h;
    return tcp_flush_peer(w, dst);
}

static int tcp_send_common(rlo_world *base, int src, int dst, int comm,
                           int tag, const uint8_t *fhdr, rlo_blob *frame,
                           rlo_handle **out)
{
    rlo_tcp_world *w = (rlo_tcp_world *)base;
    if (dst < 0 || dst >= base->world_size || !frame || frame->len < 0 ||
        frame->len > TCP_MAX_FRAME ||  /* symmetric with the receiver's
                                          cap: error HERE, not by
                                          poisoning the peer's world */
        src != base->my_rank || dst == base->my_rank)
        return RLO_ERR_ARG;
    if (w->peers[dst].crashed) {
        /* blackhole, like loopback's kill_rank: the handle completes
         * done-but-failed so the sender's queues drain, and traffic to
         * LIVE peers keeps flowing — the engine-level failure detector
         * (not a sticky transport error) owns the recovery */
        if (out) {
            rlo_handle *h = rlo_handle_new_w(base, 1);
            if (!h)
                return RLO_ERR_NOMEM;
            h->delivered = 1;
            h->failed = 1;
            *out = h;
        }
        return RLO_OK;
    }
    int rc = tcp_enqueue(w, dst, comm, tag, fhdr, frame, out);
    if (rc == RLO_ERR_STALL && w->peers[dst].crashed)
        rc = RLO_OK; /* crash detected on this very flush: the handle
                        already fail-completed; not a caller error */
    if (rc == RLO_OK && comm != TCP_CTRL_COMM)
        w->sent_cnt++;
    return rc;
}

static int tcp_isend(rlo_world *base, int src, int dst, int comm, int tag,
                     rlo_blob *frame, rlo_handle **out)
{
    return tcp_send_common(base, src, dst, comm, tag, 0, frame, out);
}

/* Zero-copy gather op (rlo_internal.h isend_hdr): the caller's
 * restamped header is copied into node staging, the payload goes to
 * sendmsg straight from the shared blob. */
static int tcp_isend_hdr(rlo_world *base, int src, int dst, int comm,
                         int tag, const uint8_t *hdr, rlo_blob *frame,
                         rlo_handle **out)
{
    return tcp_send_common(base, src, dst, comm, tag, hdr, frame, out);
}

static void tcp_deliver(rlo_tcp_world *w, int src)
{
    tcp_peer *p = &w->peers[src];
    rlo_wire_node *n =
        (rlo_wire_node *)rlo_pool_alloc(&w->base, sizeof(*n));
    if (!n) {
        w->failed = 1;
        return;
    }
    n->next = 0;
    n->src = p->rhdr.src;
    n->dst = w->base.my_rank;
    n->tag = p->rhdr.tag;
    n->comm = p->rhdr.comm;
    n->due = 0;
    n->frame = p->rframe;
    n->handle = rlo_handle_new_w(&w->base, 1);
    if (!n->handle) {
        rlo_blob_unref(p->rframe);
        rlo_pool_free(n);
        w->failed = 1;
        p->rframe = 0;
        return;
    }
    n->handle->delivered = 1;
    p->rframe = 0;
    p->rhdr_got = 0;
    p->rframe_got = 0;
    if (n->comm == TCP_CTRL_COMM) {
        if (w->ctrl_tail)
            w->ctrl_tail->next = n;
        else
            w->ctrl_head = n;
        w->ctrl_tail = n;
        return;
    }
    w->recv_cnt++;
    if (w->inbox_tail)
        w->inbox_tail->next = n;
    else
        w->inbox_head = n;
    w->inbox_tail = n;
}


/* A peer-attributable failure (recv EOF mid-frame, send EPIPE/reset):
 * mark THE PEER dead, fail-complete every in-flight handle queued at
 * it (done-but-failed, never hung — the engine's tracking queues
 * drain and its ARQ entries stop mattering), drop its queue and any
 * half-assembled inbound frame, and close the socket so
 * tcp_peer_alive reports it dead. The world's failed flag is also set
 * (the crash-fast signal data collectives abort on); the engine-level
 * heartbeat detector feeds off the same silence — the peer stops
 * refreshing hb_seen, times out, and the survivors elastically
 * re-form exactly as on any other transport. */
static void tcp_peer_crashed(rlo_tcp_world *w, tcp_peer *p)
{
    w->failed = 1;
    p->crashed = 1;
    if (p->fd >= 0) {
        close(p->fd);
        p->fd = -1;
    }
    for (tcp_send_node *n = p->sq_head; n;) {
        tcp_send_node *nn = n->next;
        if (n->handle) {
            n->handle->delivered = 1;
            n->handle->failed = 1;
            rlo_handle_unref(n->handle);
        }
        rlo_blob_unref(n->frame);
        rlo_pool_free(n);
        n = nn;
    }
    p->sq_head = p->sq_tail = 0;
    rlo_blob_unref(p->rframe);
    p->rframe = 0;
    p->rhdr_got = 0;
    p->rframe_got = 0;
}

/* read whatever each socket has; assemble frames into the inboxes.
 * A clean EOF at a record boundary is a GRACEFUL peer exit (it
 * finished its drain and freed its world — the shutdown ring is
 * asymmetric, so the last rank may close while earlier ranks still
 * forward among themselves): close the fd, keep the world alive.
 * EOF mid-frame or a socket error is a crashed peer: world failed. */
static void tcp_pump(rlo_tcp_world *w)
{
    for (int r = 0; r < w->base.world_size; r++) {
        tcp_peer *p = &w->peers[r];
        if (p->fd < 0)
            continue;
        tcp_flush_peer(w, r);
        for (;;) {
            if (p->rhdr_got < sizeof p->rhdr) {
                ssize_t k = recv(p->fd,
                                 (uint8_t *)&p->rhdr + p->rhdr_got,
                                 sizeof p->rhdr - p->rhdr_got, 0);
                if (k == 0 && p->rhdr_got == 0) {
                    close(p->fd); /* graceful peer exit */
                    p->fd = -1;
                    break;
                }
                if (k == 0 || (k < 0 && errno != EAGAIN &&
                               errno != EWOULDBLOCK)) {
                    tcp_peer_crashed(w, p);
                    return;
                }
                if (k < 0)
                    break; /* EAGAIN */
                p->rhdr_got += (size_t)k;
                if (p->rhdr_got < sizeof p->rhdr)
                    break;
                if (p->rhdr.len < 0 || p->rhdr.len > TCP_MAX_FRAME ||
                    p->rhdr.src != r) {
                    /* len caps the allocation below; src is the
                     * engine's quarantine key and MUST match the
                     * socket's rank — a mis-stamped src would smuggle
                     * frames past the failed-sender/epoch quarantine
                     * as traffic "from nowhere" (rlo-sentinel S2) */
                    tcp_peer_crashed(w, p);
                    return;
                }
                p->rframe = rlo_blob_new_w(&w->base, p->rhdr.len);
                if (!p->rframe) {
                    w->failed = 1;
                    return;
                }
                p->rframe_got = 0;
                if (p->rhdr.len == 0) {
                    tcp_deliver(w, r);
                    continue;
                }
            }
            ssize_t k = recv(p->fd, p->rframe->data + p->rframe_got,
                             (size_t)p->rhdr.len - p->rframe_got, 0);
            if (k == 0 ||
                (k < 0 && errno != EAGAIN && errno != EWOULDBLOCK)) {
                tcp_peer_crashed(w, p);
                return;
            }
            if (k < 0)
                break;
            p->rframe_got += (size_t)k;
            if (p->rframe_got == (size_t)p->rhdr.len)
                tcp_deliver(w, r);
            else
                break;
        }
    }
}

static rlo_wire_node *tcp_poll(rlo_world *base, int rank, int comm)
{
    rlo_tcp_world *w = (rlo_tcp_world *)base;
    if (rank != base->my_rank)
        return 0;
    tcp_pump(w);
    rlo_wire_node *prev = 0;
    for (rlo_wire_node *n = w->inbox_head; n; prev = n, n = n->next) {
        if (n->comm != comm)
            continue;
        if (prev)
            prev->next = n->next;
        else
            w->inbox_head = n->next;
        if (w->inbox_tail == n)
            w->inbox_tail = prev;
        n->next = 0;
        return n;
    }
    return 0;
}

static int tcp_quiescent(const rlo_world *base)
{
    const rlo_tcp_world *w = (const rlo_tcp_world *)base;
    for (int r = 0; r < base->world_size; r++)
        if (w->peers[r].sq_head)
            return 0;
    return w->inbox_head == 0;
}

static int64_t tcp_sent(const rlo_world *base)
{
    return ((const rlo_tcp_world *)base)->sent_cnt;
}

static int64_t tcp_delivered(const rlo_world *base)
{
    return ((const rlo_tcp_world *)base)->recv_cnt;
}

static int tcp_failed(const rlo_world *base)
{
    return ((const rlo_tcp_world *)base)->failed;
}

/* Socket-level liveness: a peer is alive while its connection is
 * open. A graceful exit closes the fd (clean EOF in tcp_pump); a
 * crash is a reset/mid-frame EOF (world failed AND the fd closes).
 * A peer that is hung-but-connected stays "alive" here — that is
 * what the engine-level heartbeat detector is for; this signal is
 * the transport's crash-fast path (shm's heartbeat-slot analogue). */
static int tcp_peer_alive(const rlo_world *base, int rank,
                          uint64_t timeout_usec)
{
    (void)timeout_usec;
    const rlo_tcp_world *w = (const rlo_tcp_world *)base;
    if (rank == base->my_rank)
        return 1;
    if (rank < 0 || rank >= base->world_size)
        return 0;
    return w->peers[rank].fd >= 0;
}

/* send a control token; bounded-blocking (flush until accepted) */
static int ctrl_send(rlo_tcp_world *w, int dst, int tag,
                     const int64_t *payload, int n64)
{
    rlo_blob *b = rlo_blob_new_w(&w->base, (int64_t)n64 * 8);
    if (!b)
        return RLO_ERR_NOMEM;
    memcpy(b->data, payload, (size_t)n64 * 8);
    int rc = tcp_enqueue(w, dst, TCP_CTRL_COMM, tag, 0, b, 0);
    rlo_blob_unref(b);
    if (rc != RLO_OK)
        return rc;
    uint64_t deadline = now_sec() + TCP_CTRL_TIMEOUT_SEC;
    while (w->peers[dst].sq_head) {
        tcp_flush_peer(w, dst);
        tcp_pump(w);
        if (w->failed || now_sec() > deadline)
            return RLO_ERR_STALL;
    }
    return RLO_OK;
}

/* wait for the next control token with `tag`; keeps data + engines
 * progressing so a peer blocked on us cannot deadlock the ring */
static int ctrl_wait(rlo_tcp_world *w, int tag, int64_t *payload, int n64)
{
    uint64_t deadline = now_sec() + TCP_CTRL_TIMEOUT_SEC;
    for (;;) {
        rlo_wire_node *prev = 0;
        for (rlo_wire_node *n = w->ctrl_head; n; prev = n, n = n->next) {
            if (n->tag != tag)
                continue;
            if (prev)
                prev->next = n->next;
            else
                w->ctrl_head = n->next;
            if (w->ctrl_tail == n)
                w->ctrl_tail = prev;
            if (n->frame->len < (int64_t)n64 * 8) {
                rlo_handle_unref(n->handle);
                rlo_blob_unref(n->frame);
                rlo_pool_free(n);
                return RLO_ERR_PROTO;
            }
            memcpy(payload, n->frame->data, (size_t)n64 * 8);
            rlo_handle_unref(n->handle);
            rlo_blob_unref(n->frame);
            rlo_pool_free(n);
            return RLO_OK;
        }
        rlo_progress_all(&w->base); /* keep data + engine frames moving */
        tcp_pump(w);
        if (w->failed || now_sec() > deadline)
            return RLO_ERR_STALL;
        sched_yield();
    }
}

/* two-pass ring allreduce of n64 int64s over control frames.
 * Collective: every rank must enter. */
static int ctrl_ring_sum(rlo_tcp_world *w, int64_t *vals, int n64,
                         int tag_fwd, int tag_bck)
{
    int ws = w->base.world_size, me = w->base.my_rank, rc;
    int64_t buf[4];
    if (n64 > 4)
        return RLO_ERR_ARG;
    if (ws == 1)
        return RLO_OK;
    if (me == 0) {
        if ((rc = ctrl_send(w, 1, tag_fwd, vals, n64)) != RLO_OK)
            return rc;
        if ((rc = ctrl_wait(w, tag_bck, vals, n64)) != RLO_OK)
            return rc;
        if (ws > 2)
            return ctrl_send(w, 1, tag_bck, vals, n64);
        return RLO_OK;
    }
    if ((rc = ctrl_wait(w, tag_fwd, buf, n64)) != RLO_OK)
        return rc;
    for (int i = 0; i < n64; i++)
        vals[i] += buf[i];
    if (me < ws - 1) {
        if ((rc = ctrl_send(w, me + 1, tag_fwd, vals, n64)) != RLO_OK)
            return rc;
        if ((rc = ctrl_wait(w, tag_bck, vals, n64)) != RLO_OK)
            return rc;
        if (me + 1 < ws - 1)
            return ctrl_send(w, me + 1, tag_bck, vals, n64);
        return RLO_OK;
    }
    /* rank ws-1 holds the total: send it back around via rank 0 */
    return ctrl_send(w, 0, tag_bck, vals, n64);
}

static int tcp_drain(rlo_world *base, int max_spins)
{
    rlo_tcp_world *w = (rlo_tcp_world *)base;
    int64_t prev[2] = {-1, -2};
    for (int i = 0; i < max_spins; i++) {
        rlo_progress_all(base);
        tcp_pump(w);
        if (w->failed)
            return RLO_ERR_STALL;
        int idle = 1;
        for (int j = 0; j < base->n_engines; j++)
            if (!rlo_engine_idle(base->engines[j]))
                idle = 0;
        if (!idle || !tcp_quiescent(base)) {
            if ((i & 7) == 7)
                sched_yield();
            continue;
        }
        int64_t sums[2] = {w->sent_cnt, w->recv_cnt};
        int rc = ctrl_ring_sum(w, sums, 2, CT_SUM_FWD, CT_SUM_BCK);
        if (rc != RLO_OK)
            return rc;
        if (sums[0] == sums[1] && sums[0] == prev[0] &&
            prev[0] == prev[1])
            return i;
        prev[0] = sums[0];
        prev[1] = sums[1];
    }
    return RLO_ERR_STALL;
}

static void tcp_barrier(rlo_world *base)
{
    rlo_tcp_world *w = (rlo_tcp_world *)base;
    int64_t token[1] = {0};
    /* the vtable barrier returns void: a ring failure/timeout marks
     * the world failed so callers cannot proceed as if synchronized */
    if (ctrl_ring_sum(w, token, 1, CT_BAR_FWD, CT_BAR_BCK) != RLO_OK)
        w->failed = 1;
}

static void tcp_free(rlo_world *base)
{
    rlo_tcp_world *w = (rlo_tcp_world *)base;
    for (int r = 0; r < base->world_size; r++) {
        tcp_peer *p = &w->peers[r];
        for (tcp_send_node *n = p->sq_head; n;) {
            tcp_send_node *nn = n->next;
            rlo_handle_unref(n->handle);
            rlo_blob_unref(n->frame);
            rlo_pool_free(n);
            n = nn;
        }
        rlo_blob_unref(p->rframe);
        if (p->fd >= 0)
            close(p->fd);
    }
    for (rlo_wire_node *lists[2] = {w->inbox_head, w->ctrl_head}, **l =
             lists; l < lists + 2; l++)
        for (rlo_wire_node *n = *l; n;) {
            rlo_wire_node *nn = n->next;
            rlo_handle_unref(n->handle);
            rlo_blob_unref(n->frame);
            rlo_pool_free(n);
            n = nn;
        }
    free(base->engines);
    rlo_pool_drain(base);
    free(w);
}

static const rlo_transport_ops TCP_OPS = {
    .name = "tcp",
    .isend = tcp_isend,
    .poll = tcp_poll,
    .quiescent = tcp_quiescent,
    .sent_cnt = tcp_sent,
    .delivered_cnt = tcp_delivered,
    .drain = tcp_drain,
    .failed = tcp_failed,
    .peer_alive = tcp_peer_alive,
    .kill_rank = 0,
    .barrier = tcp_barrier,
    .free_ = tcp_free,
    .isend_hdr = tcp_isend_hdr,
};

/* parse "host:port" entry i of RLO_TCP_HOSTS, or default localhost */
static int endpoint_for(int rank, char *host, size_t hostsz, int *port)
{
    const char *hosts = getenv("RLO_TCP_HOSTS");
    const char *pb = getenv("RLO_TCP_PORT_BASE");
    int base_port = pb ? atoi(pb) : TCP_DEFAULT_PORT_BASE;
    if (!hosts || !*hosts) {
        snprintf(host, hostsz, "127.0.0.1");
        *port = base_port + rank;
        return 0;
    }
    const char *s = hosts;
    for (int i = 0; i < rank; i++) {
        s = strchr(s, ',');
        if (!s)
            return -1;
        s++;
    }
    const char *end = strchr(s, ',');
    size_t len = end ? (size_t)(end - s) : strlen(s);
    const char *colon = memchr(s, ':', len);
    if (!colon || (size_t)(colon - s) >= hostsz)
        return -1;
    memcpy(host, s, (size_t)(colon - s));
    host[colon - s] = 0;
    *port = atoi(colon + 1);
    return 0;
}

static int tcp_connect_to(int rank)
{
    char host[256];
    int port;
    if (endpoint_for(rank, host, sizeof host, &port))
        return -1;
    uint64_t deadline = now_sec() + TCP_CONNECT_TIMEOUT_SEC;
    for (;;) {
        struct addrinfo hints = {0}, *ai = 0;
        hints.ai_family = AF_UNSPEC;
        hints.ai_socktype = SOCK_STREAM;
        char portstr[16];
        snprintf(portstr, sizeof portstr, "%d", port);
        if (getaddrinfo(host, portstr, &hints, &ai) != 0 || !ai)
            return -1;
        int fd = socket(ai->ai_family, SOCK_STREAM, 0);
        if (fd >= 0 &&
            connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
            freeaddrinfo(ai);
            return fd;
        }
        if (fd >= 0)
            close(fd);
        freeaddrinfo(ai);
        if (now_sec() > deadline)
            return -1;
        struct timespec ts = {0, 50 * 1000 * 1000};
        nanosleep(&ts, 0);
    }
}

static int read_full(int fd, void *buf, size_t n)
{
    size_t got = 0;
    while (got < n) {
        ssize_t k = recv(fd, (uint8_t *)buf + got, n - got, 0);
        if (k == 0)
            return -1; /* EOF: peer closed mid-handshake (errno stale) */
        if (k < 0 && errno != EINTR)
            return -1;
        if (k > 0)
            got += (size_t)k;
    }
    return 0;
}

int rlo_tcp_available(void)
{
    return 1;
}

rlo_world *rlo_tcp_world_new(void)
{
    const char *er = getenv("RLO_TCP_RANK");
    const char *ew = getenv("RLO_TCP_WORLD");
    if (!er || !ew)
        return 0;
    int rank = atoi(er), ws = atoi(ew);
    if (ws < 2 || ws > TCP_MAX_RANKS || rank < 0 || rank >= ws)
        return 0;
    rlo_tcp_world *w = (rlo_tcp_world *)calloc(1, sizeof(*w));
    if (!w)
        return 0;
    w->base.ops = &TCP_OPS;
    w->base.world_size = ws;
    w->base.my_rank = rank;
    for (int r = 0; r < ws; r++)
        w->peers[r].fd = -1;

    char host[256];
    int port;
    if (endpoint_for(rank, host, sizeof host, &port))
        goto fail;
    int lfd = socket(AF_INET, SOCK_STREAM, 0);
    if (lfd < 0)
        goto fail;
    int one = 1;
    setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    struct sockaddr_in addr = {0};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons((uint16_t)port);
    if (bind(lfd, (struct sockaddr *)&addr, sizeof addr) != 0 ||
        listen(lfd, ws) != 0) {
        close(lfd);
        goto fail;
    }
    /* connect DOWN (peers 0..rank-1), announcing who we are */
    for (int r = 0; r < rank; r++) {
        int fd = tcp_connect_to(r);
        if (fd < 0) {
            close(lfd);
            goto fail;
        }
        int32_t hello = rank;
        if (send(fd, &hello, sizeof hello, MSG_NOSIGNAL) !=
            sizeof hello) {
            close(fd);
            close(lfd);
            goto fail;
        }
        w->peers[r].fd = fd;
    }
    /* accept UP (peers rank+1..ws-1, in whatever order they arrive).
     * Bounded: a peer that failed to boot (port clash, crash) must
     * fail this rank's setup, not hang it in accept() forever */
    struct timeval atv = {TCP_CONNECT_TIMEOUT_SEC, 0};
    setsockopt(lfd, SOL_SOCKET, SO_RCVTIMEO, &atv, sizeof atv);
    for (int need = ws - 1 - rank; need > 0; need--) {
        int fd = accept(lfd, 0, 0);
        int32_t hello = -1;
        if (fd < 0 || read_full(fd, &hello, sizeof hello) != 0 ||
            hello <= rank || hello >= ws || w->peers[hello].fd >= 0) {
            if (fd >= 0)
                close(fd);
            close(lfd);
            goto fail;
        }
        w->peers[hello].fd = fd;
    }
    close(lfd);
    /* RLO_TCP_SNDBUF: shrink the kernel send buffer (test support —
     * the writev partial-write-resume selftest forces short writes
     * deterministically this way; unset = kernel default) */
    const char *sb = getenv("RLO_TCP_SNDBUF");
    int sndbuf = sb ? atoi(sb) : 0;
    for (int r = 0; r < ws; r++)
        if (w->peers[r].fd >= 0) {
            set_nonblock(w->peers[r].fd);
            set_nodelay(w->peers[r].fd);
            if (sndbuf > 0)
                setsockopt(w->peers[r].fd, SOL_SOCKET, SO_SNDBUF,
                           &sndbuf, sizeof sndbuf);
        }
    /* everyone connected everywhere before any traffic */
    tcp_barrier(&w->base);
    return &w->base;
fail:
    for (int r = 0; r < ws; r++)
        if (w->peers[r].fd >= 0)
            close(w->peers[r].fd);
    free(w);
    return 0;
}
