/* POSIX shared-memory multi-process transport: N real OS processes as
 * ranks — the framework's `mpirun -n N ./demo` analogue (the reference
 * can only run multi-rank under mpirun, Makefile:5, SURVEY.md §4).
 *
 * Layout: one anonymous MAP_SHARED segment created by the launcher before
 * fork, holding a header (global sent/consumed counters, a sense-reversing
 * barrier, per-rank idle flags) and world_size^2 SPSC byte-ring channels,
 * one per (src, dst) pair. Writer = src process, reader = dst process, so
 * a release-store on head / acquire-load on tail is all the
 * synchronization a channel needs — the shared-memory analogue of the
 * one-sided remote-write transport the reference abandoned in
 * rma_util.c:29-62 (mailbag over MPI_Win_lock/MPI_Put epochs).
 *
 * Send semantics match MPI buffered isend: the frame is copied into the
 * ring, so the completion handle is delivered immediately (the reference
 * tests per-destination isend requests only to learn buffer reuse safety,
 * rootless_ops.c:319-325). When a ring is full the sender pumps its own
 * inbound rings into a local inbox (breaking send-send cycles) and
 * yields until space frees or a timeout trips RLO_ERR_STALL.
 *
 * Termination detection (reference rootless_ops.c:1613-1625 uses an
 * MPI_Iallreduce over bcast counts): non-blocking. One atomic global
 * `in_flight` counter (incremented before a frame enters a ring,
 * decremented when the destination engine polls it) plus per-rank idle
 * flags. A rank exits its drain when in_flight == 0 and every idle flag
 * is set, stable across a few sweeps. Safety: with in_flight == 0 and
 * all engines idle, no rank can ever send again — a new send requires
 * either an app call (excluded during drain, as in the reference's
 * cleanup) or a poll of an in-flight frame (none exist) — so ranks may
 * observe the condition at different times and exit independently
 * without a blocking barrier (which could livelock: a parked rank
 * cannot poll, holding in_flight above zero forever).
 */
#define _GNU_SOURCE
#include "rlo_internal.h"

#include <errno.h>
#include <sched.h>
#include <signal.h>
#include <stdatomic.h>
#include <stdio.h>
#include <sys/mman.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

#define SHM_DEFAULT_RING (256 * 1024)
#define SHM_MAX_RANKS 256
#define SHM_ALIGN 8
/* ring-full wait budget before declaring a stall */
#define SHM_FULL_TIMEOUT_SEC 30

/* per-channel SPSC byte ring; data[] follows the struct */
typedef struct shm_ring {
    _Atomic uint64_t head; /* bytes written (monotonic) */
    _Atomic uint64_t tail; /* bytes consumed (monotonic) */
    char pad[64 - 2 * sizeof(_Atomic uint64_t)];
} shm_ring;

/* record header inside a ring, 8-byte aligned */
typedef struct shm_rec {
    uint32_t size; /* total record bytes incl. header + padding */
    int32_t tag;
    int32_t comm;
    int32_t src;
    int64_t len; /* frame bytes that follow */
} shm_rec;

typedef struct shm_hdr {
    int world_size;
    int64_t ring_bytes;
    _Atomic int64_t sent_cnt;     /* frames entered a ring */
    _Atomic int64_t consumed_cnt; /* frames handed to an engine */
    _Atomic int64_t in_flight;    /* sent but not yet polled by an engine */
    _Atomic int barrier_cnt;
    _Atomic int barrier_gen;
    _Atomic int abort_flag; /* a rank hit a fatal error */
    _Atomic int idle_flag[SHM_MAX_RANKS];
    /* per-rank heartbeat (usec clock): stamped on every ring pump so a
     * crashed/exited peer goes stale within one failure timeout — the
     * net-new failure-detection signal (the reference has none,
     * SURVEY.md §5); read by rlo_world_peer_alive */
    _Atomic uint64_t hb_usec[SHM_MAX_RANKS];
} shm_hdr;

typedef struct rlo_shm_world {
    rlo_world base;
    shm_hdr *hdr;
    uint8_t *rings; /* world_size^2 rings, index src*ws + dst */
    size_t ring_stride;
    size_t seg_size;
    /* local inbox of frames already pumped out of my inbound rings
     * (holds frames for every comm; poll filters) */
    rlo_wire_node *inbox_head, *inbox_tail;
} rlo_shm_world;

static shm_ring *ring_at(const rlo_shm_world *w, int src, int dst)
{
    return (shm_ring *)(w->rings +
                        w->ring_stride *
                            ((size_t)src * (size_t)w->base.world_size +
                             (size_t)dst));
}

static uint8_t *ring_data(shm_ring *r)
{
    return (uint8_t *)(r + 1);
}

/* copy in/out with wraparound */
static void ring_write(shm_ring *r, int64_t cap, uint64_t at,
                       const void *src, size_t n)
{
    uint8_t *d = ring_data(r);
    size_t off = (size_t)(at % (uint64_t)cap);
    size_t first = (size_t)cap - off;
    if (first > n)
        first = n;
    memcpy(d + off, src, first);
    if (n > first)
        memcpy(d, (const uint8_t *)src + first, n - first);
}

static void ring_read(shm_ring *r, int64_t cap, uint64_t at, void *dst,
                      size_t n)
{
    const uint8_t *d = ring_data(r);
    size_t off = (size_t)(at % (uint64_t)cap);
    size_t first = (size_t)cap - off;
    if (first > n)
        first = n;
    memcpy(dst, d + off, first);
    if (n > first)
        memcpy((uint8_t *)dst + first, d, n - first);
}

static size_t rec_size(int64_t len)
{
    size_t n = sizeof(shm_rec) + (size_t)len;
    return (n + (SHM_ALIGN - 1)) & ~(size_t)(SHM_ALIGN - 1);
}

/* ---- pump: drain all my inbound rings into the local inbox ---- */

/* rlo-sentinel: transfers(n) — the inbox owns it until polled */
static void shm_inbox_push(rlo_shm_world *w, rlo_wire_node *n)
{
    n->next = 0;
    if (w->inbox_tail)
        w->inbox_tail->next = n;
    else
        w->inbox_head = n;
    w->inbox_tail = n;
}

static int shm_pump(rlo_shm_world *w)
{
    int moved = 0;
    int ws = w->base.world_size;
    int me = w->base.my_rank;
    atomic_store_explicit(&w->hdr->hb_usec[me], rlo_now_usec(),
                          memory_order_relaxed);
    int64_t cap = w->hdr->ring_bytes;
    for (int src = 0; src < ws; src++) {
        if (src == me)
            continue;
        shm_ring *r = ring_at(w, src, me);
        for (;;) {
            uint64_t tail = atomic_load_explicit(&r->tail,
                                                 memory_order_relaxed);
            uint64_t head = atomic_load_explicit(&r->head,
                                                 memory_order_acquire);
            if (head == tail)
                break;
            shm_rec rec;
            ring_read(r, cap, tail, &rec, sizeof(rec));
            /* rec is WIRE INPUT from a shared segment a crashed or
             * hostile peer may have scribbled over (rlo-sentinel S2):
             * every field that sizes an allocation/copy or advances
             * the consume cursor is validated against the ring
             * geometry before use — the TCP receive path applies the
             * same symmetric cap (tcp_pump), including the src pin:
             * each ring is per (src, me) and senders stamp their own
             * rank, so any other value is a scribble that would let
             * frames impersonate a healthy rank past the
             * failed-sender/epoch quarantine. A violation poisons the
             * world (abort_flag), it must never poison this process. */
            if (rec.len < 0 ||
                rec.len > cap - (int64_t)sizeof(shm_rec) ||
                rec.size != rec_size(rec.len) ||
                rec.src != src) {
                atomic_store(&w->hdr->abort_flag, 1);
                return RLO_ERR_PROTO;
            }
            rlo_wire_node *n = (rlo_wire_node *)rlo_pool_alloc(
                &w->base, sizeof(*n));
            rlo_blob *frame = rlo_blob_new_w(&w->base, rec.len);
            if (!n || !frame) {
                rlo_pool_free(n);
                rlo_blob_unref(frame);
                return RLO_ERR_NOMEM;
            }
            n->next = 0;
            n->src = rec.src;
            n->dst = me;
            n->tag = rec.tag;
            n->comm = rec.comm;
            n->due = 0;
            n->frame = frame;
            n->handle = rlo_handle_new_w(&w->base, 1);
            if (!n->handle) {
                rlo_pool_free(n);
                rlo_blob_unref(frame);
                return RLO_ERR_NOMEM;
            }
            n->handle->delivered = 1;
            if (rec.len > 0)
                ring_read(r, cap, tail + sizeof(rec), frame->data,
                          (size_t)rec.len);
            atomic_store_explicit(&r->tail, tail + rec.size,
                                  memory_order_release);
            shm_inbox_push(w, n);
            moved++;
        }
    }
    return moved;
}

/* ---- vtable ops ---- */

static int shm_isend(rlo_world *base, int src, int dst, int comm, int tag,
                     rlo_blob *frame, rlo_handle **out)
{
    rlo_shm_world *w = (rlo_shm_world *)base;
    if (dst < 0 || dst >= base->world_size || !frame ||
        src != base->my_rank)
        return RLO_ERR_ARG;
    const uint8_t *raw = frame->data;
    int64_t len = frame->len;
    if (len < 0)
        return RLO_ERR_ARG;
    if (dst == src)
        return RLO_ERR_ARG; /* overlay never self-sends */
    int64_t cap = w->hdr->ring_bytes;
    size_t need = rec_size(len);
    if ((int64_t)need > cap)
        return RLO_ERR_TOO_BIG;
    /* sending means this rank is active: take the idle flag down so no
     * peer's drain can conclude global quiescence around this send */
    atomic_store(&w->hdr->idle_flag[base->my_rank], 0);
    /* allocate the caller's completion handle before committing the
     * frame — a post-commit allocation failure would report a send that
     * actually happened */
    rlo_handle *h = 0;
    if (out) {
        h = rlo_handle_new_w(base, 1);
        if (!h)
            return RLO_ERR_NOMEM;
        h->delivered = 1; /* buffered-send semantics */
    }
    shm_ring *r = ring_at(w, src, dst);
    struct timespec t0, tn;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    for (;;) {
        uint64_t head = atomic_load_explicit(&r->head,
                                             memory_order_relaxed);
        uint64_t tail = atomic_load_explicit(&r->tail,
                                             memory_order_acquire);
        if ((uint64_t)cap - (head - tail) >= need) {
            shm_rec rec = {.size = (uint32_t)need,
                           .tag = tag,
                           .comm = comm,
                           .src = src,
                           .len = len};
            ring_write(r, cap, head, &rec, sizeof(rec));
            if (len > 0)
                ring_write(r, cap, head + sizeof(rec), raw, (size_t)len);
            /* in_flight rises before the frame becomes visible so an
             * observer can never see the frame without the count */
            atomic_fetch_add_explicit(&w->hdr->in_flight, 1,
                                      memory_order_relaxed);
            atomic_store_explicit(&r->head, head + need,
                                  memory_order_release);
            atomic_fetch_add_explicit(&w->hdr->sent_cnt, 1,
                                      memory_order_relaxed);
            break;
        }
        /* ring full: keep consuming my own inbound traffic so two
         * mutually-full ranks can't deadlock, then yield to the reader */
        if (atomic_load(&w->hdr->abort_flag)) {
            rlo_handle_unref(h);
            return RLO_ERR_STALL;
        }
        int rc = shm_pump(w);
        if (rc < 0) {
            rlo_handle_unref(h);
            return rc;
        }
        sched_yield();
        clock_gettime(CLOCK_MONOTONIC, &tn);
        if (tn.tv_sec - t0.tv_sec > SHM_FULL_TIMEOUT_SEC) {
            atomic_store(&w->hdr->abort_flag, 1);
            rlo_handle_unref(h);
            return RLO_ERR_STALL;
        }
    }
    if (out)
        *out = h;
    return RLO_OK;
}

static rlo_wire_node *shm_poll(rlo_world *base, int rank, int comm)
{
    rlo_shm_world *w = (rlo_shm_world *)base;
    if (rank != base->my_rank)
        return 0;
    shm_pump(w);
    rlo_wire_node *prev = 0;
    for (rlo_wire_node *n = w->inbox_head; n; prev = n, n = n->next) {
        if (n->comm != comm)
            continue;
        if (prev)
            prev->next = n->next;
        else
            w->inbox_head = n->next;
        if (w->inbox_tail == n)
            w->inbox_tail = prev;
        n->next = 0;
        /* handing a frame to an engine whose dispatch may send: the
         * idle flag must be observably down BEFORE in_flight can read 0,
         * or a peer's drain could conclude global quiescence in the
         * window between this decrement and the dispatch's own sends
         * (both seq_cst to keep the store ordered before the sub) */
        atomic_store(&w->hdr->idle_flag[base->my_rank], 0);
        atomic_fetch_add_explicit(&w->hdr->consumed_cnt, 1,
                                  memory_order_relaxed);
        atomic_fetch_sub(&w->hdr->in_flight, 1);
        return n;
    }
    return 0;
}

static int shm_quiescent(const rlo_world *base)
{
    const rlo_shm_world *w = (const rlo_shm_world *)base;
    return atomic_load(&w->hdr->in_flight) == 0;
}

static int64_t shm_sent(const rlo_world *base)
{
    return atomic_load(&((const rlo_shm_world *)base)->hdr->sent_cnt);
}

static int64_t shm_delivered(const rlo_world *base)
{
    return atomic_load(&((const rlo_shm_world *)base)->hdr->consumed_cnt);
}

/* Sense-reversing barrier. While spinning, keep pumping inbound rings
 * into the local inbox (not counted as consumed until poll) so a rank
 * still working outside the barrier can never block on a full ring whose
 * reader is parked here. */
static void shm_barrier_w(rlo_shm_world *w)
{
    shm_hdr *h = w->hdr;
    int ws = w->base.world_size;
    int gen = atomic_load(&h->barrier_gen);
    if (atomic_fetch_add(&h->barrier_cnt, 1) == ws - 1) {
        atomic_store(&h->barrier_cnt, 0);
        atomic_fetch_add(&h->barrier_gen, 1);
    } else {
        while (atomic_load(&h->barrier_gen) == gen) {
            if (atomic_load(&h->abort_flag)) {
                /* leave the barrier accounting consistent on abort */
                atomic_fetch_sub(&h->barrier_cnt, 1);
                return;
            }
            shm_pump(w);
            sched_yield();
        }
    }
}

void rlo_shm_barrier(rlo_world *base)
{
    if (!base || base->ops->quiescent != shm_quiescent)
        return; /* not an shm world */
    shm_barrier_w((rlo_shm_world *)base);
}

static int shm_local_idle(rlo_shm_world *w)
{
    for (int j = 0; j < w->base.n_engines; j++)
        if (!rlo_engine_idle(w->base.engines[j]))
            return 0;
    return w->inbox_head == 0;
}

static int shm_drain(rlo_world *base, int max_spins)
{
    rlo_shm_world *w = (rlo_shm_world *)base;
    shm_hdr *h = w->hdr;
    int me = base->my_rank;
    int stable = 0;
    for (int i = 0; i < max_spins; i++) {
        /* flag down while we might dispatch (a dispatch can send) */
        atomic_store(&h->idle_flag[me], 0);
        rlo_progress_all(base);
        if (atomic_load(&h->abort_flag))
            return RLO_ERR_STALL;
        if (!shm_local_idle(w) || atomic_load(&h->in_flight) != 0) {
            stable = 0;
            sched_yield();
            continue;
        }
        atomic_store(&h->idle_flag[me], 1);
        int ok = atomic_load(&h->in_flight) == 0;
        for (int r = 0; ok && r < base->world_size; r++)
            if (!atomic_load(&h->idle_flag[r]))
                ok = 0;
        stable = ok ? stable + 1 : 0;
        if (stable >= 3) {
            atomic_store(&h->idle_flag[me], 1); /* stay up for peers */
            return i;
        }
        sched_yield();
    }
    return RLO_ERR_STALL;
}

static void shm_free(rlo_world *base)
{
    rlo_shm_world *w = (rlo_shm_world *)base;
    for (rlo_wire_node *n = w->inbox_head; n;) {
        rlo_wire_node *nn = n->next;
        rlo_handle_unref(n->handle);
        rlo_blob_unref(n->frame);
        rlo_pool_free(n);
        n = nn;
    }
    /* the segment is unmapped at process exit; unmapping here would break
     * other engines still bound to it in this process */
    free(base->engines);
    rlo_pool_drain(base);
    free(w);
}

static int shm_failed(const rlo_world *base)
{
    return atomic_load(&((const rlo_shm_world *)base)->hdr->abort_flag);
}

static int shm_peer_alive(const rlo_world *base, int rank,
                          uint64_t timeout_usec)
{
    const rlo_shm_world *w = (const rlo_shm_world *)base;
    if (rank == base->my_rank)
        return 1;
    uint64_t last = atomic_load_explicit(&w->hdr->hb_usec[rank],
                                         memory_order_relaxed);
    uint64_t now = rlo_now_usec();
    return now < last || now - last <= timeout_usec;
}

static void shm_barrier_op(rlo_world *base)
{
    shm_barrier_w((rlo_shm_world *)base);
}

static const rlo_transport_ops SHM_OPS = {
    .name = "shm",
    .barrier = shm_barrier_op,
    .isend = shm_isend,
    .poll = shm_poll,
    .quiescent = shm_quiescent,
    .sent_cnt = shm_sent,
    .delivered_cnt = shm_delivered,
    .drain = shm_drain,
    .failed = shm_failed,
    .peer_alive = shm_peer_alive,
    .free_ = shm_free,
};

/* ---- launcher ---- */

static rlo_world *shm_world_bind(void *seg, size_t seg_size, int rank)
{
    shm_hdr *h = (shm_hdr *)seg;
    rlo_shm_world *w = (rlo_shm_world *)calloc(1, sizeof(*w));
    if (!w)
        return 0;
    w->base.ops = &SHM_OPS;
    w->base.world_size = h->world_size;
    w->base.my_rank = rank;
    w->hdr = h;
    w->ring_stride = sizeof(shm_ring) + (size_t)h->ring_bytes;
    w->rings = (uint8_t *)seg + sizeof(shm_hdr);
    w->seg_size = seg_size;
    return &w->base;
}

int rlo_shm_launch(int world_size, int64_t ring_bytes, rlo_rank_fn fn,
                   void *ctx)
{
    if (world_size < 2 || world_size > SHM_MAX_RANKS || !fn)
        return RLO_ERR_ARG;
    if (ring_bytes <= 0)
        ring_bytes = SHM_DEFAULT_RING;
    ring_bytes = (ring_bytes + (SHM_ALIGN - 1)) &
                 ~(int64_t)(SHM_ALIGN - 1);
    size_t stride = sizeof(shm_ring) + (size_t)ring_bytes;
    size_t seg_size = sizeof(shm_hdr) +
                      stride * (size_t)world_size * (size_t)world_size;
    void *seg = mmap(0, seg_size, PROT_READ | PROT_WRITE,
                     MAP_SHARED | MAP_ANONYMOUS, -1, 0);
    if (seg == MAP_FAILED)
        return RLO_ERR_NOMEM;
    shm_hdr *h = (shm_hdr *)seg;
    memset(h, 0, sizeof(*h));
    h->world_size = world_size;
    h->ring_bytes = ring_bytes;
    /* stamp every heartbeat slot now so no rank reads stale-at-birth */
    uint64_t now = rlo_now_usec();
    for (int r = 0; r < world_size; r++)
        atomic_store(&h->hb_usec[r], now);

    pid_t pids[SHM_MAX_RANKS];
    int nforked = 0;
    for (int r = 0; r < world_size; r++) {
        pid_t pid = fork();
        if (pid < 0) {
            atomic_store(&h->abort_flag, 1);
            for (int k = 0; k < nforked; k++)
                kill(pids[k], SIGKILL);
            for (int k = 0; k < nforked; k++)
                waitpid(pids[k], 0, 0);
            munmap(seg, seg_size);
            return RLO_ERR_NOMEM;
        }
        if (pid == 0) {
            rlo_world *w = shm_world_bind(seg, seg_size, r);
            if (!w)
                _exit(120);
            int rc = fn(w, r, ctx);
            rlo_world_free(w);
            _exit(rc < 0 || rc > 255 ? 119 : rc);
        }
        pids[nforked++] = pid;
    }

    /* reap in completion order: a rank that fails must raise the abort
     * flag immediately so peers parked in a barrier or full-ring spin
     * notice and exit instead of spinning forever */
    int status_out = 0;
    for (int k = 0; k < nforked; k++) {
        int st = 0;
        pid_t pid = waitpid(-1, &st, 0);
        if (pid < 0)
            break;
        int rc;
        if (WIFEXITED(st))
            rc = WEXITSTATUS(st);
        else
            rc = 128 + (WIFSIGNALED(st) ? WTERMSIG(st) : 0);
        if (rc != 0 && status_out == 0) {
            status_out = rc;
            /* wake ranks stuck in a barrier/full-ring spin */
            atomic_store(&h->abort_flag, 1);
        }
    }
    munmap(seg, seg_size);
    return status_out;
}
