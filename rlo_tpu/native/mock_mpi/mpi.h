/* Minimal MPI-3 declaration shim — COMPILE CHECKING ONLY.
 *
 * The container has no MPI installation, so the real rlo_mpi.c transport
 * path (#ifdef RLO_HAVE_MPI) would otherwise never be seen by a
 * compiler. `make mpicheck` (and tests/test_native_core.py) runs
 *   cc -fsyntax-only -DRLO_HAVE_MPI -Imock_mpi rlo_mpi.c
 * against this header to keep that path syntactically and
 * type-checkably valid. It declares exactly the subset rlo_mpi.c uses,
 * with standard MPI-3 signatures; it implements nothing and must never
 * be linked.
 */
#ifndef RLO_MOCK_MPI_H
#define RLO_MOCK_MPI_H

typedef struct rlo_mock_comm *MPI_Comm;
typedef struct rlo_mock_req *MPI_Request;
typedef struct { int MPI_SOURCE, MPI_TAG, MPI_ERROR; } MPI_Status;
typedef int MPI_Datatype;
typedef int MPI_Op;

#define MPI_SUCCESS 0
#define MPI_COMM_WORLD ((MPI_Comm)0)
#define MPI_BYTE ((MPI_Datatype)1)
#define MPI_INT64_T ((MPI_Datatype)2)
#define MPI_SUM ((MPI_Op)1)
#define MPI_ANY_SOURCE (-2)
#define MPI_ANY_TAG (-1)
#define MPI_STATUS_IGNORE ((MPI_Status *)0)

int MPI_Init(int *argc, char ***argv);
int MPI_Initialized(int *flag);
int MPI_Comm_dup(MPI_Comm comm, MPI_Comm *newcomm);
int MPI_Comm_free(MPI_Comm *comm);
int MPI_Comm_size(MPI_Comm comm, int *size);
int MPI_Comm_rank(MPI_Comm comm, int *rank);
int MPI_Isend(const void *buf, int count, MPI_Datatype dt, int dest,
              int tag, MPI_Comm comm, MPI_Request *req);
int MPI_Recv(void *buf, int count, MPI_Datatype dt, int source, int tag,
             MPI_Comm comm, MPI_Status *status);
int MPI_Iprobe(int source, int tag, MPI_Comm comm, int *flag,
               MPI_Status *status);
int MPI_Get_count(const MPI_Status *status, MPI_Datatype dt, int *count);
int MPI_Test(MPI_Request *req, int *flag, MPI_Status *status);
int MPI_Wait(MPI_Request *req, MPI_Status *status);
int MPI_Cancel(MPI_Request *req);
int MPI_Request_free(MPI_Request *req);
int MPI_Iallreduce(const void *sendbuf, void *recvbuf, int count,
                   MPI_Datatype dt, MPI_Op op, MPI_Comm comm,
                   MPI_Request *req);
int MPI_Barrier(MPI_Comm comm);
int MPI_Bcast(void *buf, int count, MPI_Datatype dt, int root,
              MPI_Comm comm);
double MPI_Wtime(void);

#endif /* RLO_MOCK_MPI_H */
