/* Skip-ring overlay topology — pure functions, no state.
 *
 * Native counterpart of rlo_tpu/topology.py; semantics match the reference
 * bcomm math (get_level rootless_ops.c:1427, last_wall :1444, send-list
 * construction in bcomm_init :1483-1515, check_passed_origin :1534,
 * fwd_send_cnt :1559) including the non-power-of-2 truncation rules.
 */
#include "rlo_core.h"

#include <sys/time.h>

int rlo_is_pow2(int n)
{
    return n > 0 && (n & (n - 1)) == 0;
}

static int floor_log2(int n)
{
    int l = -1;
    while (n > 0) {
        n >>= 1;
        l++;
    }
    return l;
}

int rlo_level(int world_size, int rank)
{
    if (rank == 0) {
        int l = floor_log2(world_size);
        return rlo_is_pow2(world_size) ? l - 1 : l;
    }
    /* count trailing zero bits */
    int l = 0;
    while (((rank >> l) & 1) == 0)
        l++;
    return l;
}

int rlo_last_wall(int world_size, int rank)
{
    if (rank == 0)
        return 1 << rlo_level(world_size, 0);
    return rank & (rank - 1); /* clear lowest set bit */
}

int rlo_send_list(int world_size, int rank, int *out, int cap,
                  int *channel_cnt)
{
    int lvl = rlo_level(world_size, rank);
    int chan = lvl;
    int n = 0;
    if (lvl + 1 > cap)
        return RLO_ERR_ARG;
    if (rlo_is_pow2(world_size)) {
        for (int i = 0; i <= lvl; i++)
            out[n++] = (rank + (1 << i)) % world_size;
    } else {
        for (int i = 0; i <= lvl; i++) {
            int dest = rank + (1 << i);
            if (dest >= world_size) {
                if (rank == world_size - 1) {
                    chan = 0;
                    out[0] = 0;
                    n = 1;
                } else {
                    chan = i;
                    out[i] = 0;
                    n = i + 1;
                }
                break;
            }
            out[n++] = dest;
        }
    }
    if (channel_cnt)
        *channel_cnt = chan;
    return n;
}

int rlo_check_passed_origin(int world_size, int my_rank, int origin,
                            int to_rank)
{
    (void)world_size;
    if (to_rank == origin)
        return 1;
    if (my_rank >= origin) {
        if (to_rank > my_rank)
            return 0;
        /* to_rank < my_rank: duplicate iff it wrapped into [0, origin) */
        return !(to_rank >= 0 && to_rank < origin);
    }
    /* my_rank < origin: safe only inside (my_rank, origin) */
    return !(my_rank < to_rank && to_rank < origin);
}

int rlo_fwd_targets(int world_size, int rank, int origin, int from_rank,
                    int *out, int cap)
{
    if (rlo_level(world_size, rank) == 0)
        return 0;
    int list[64];
    int chan;
    int len = rlo_send_list(world_size, rank, list, 64, &chan);
    if (len < 0)
        return len;
    int n = 0;
    if (from_rank > rlo_last_wall(world_size, rank)) {
        /* full fan-out, furthest-first */
        for (int j = len - 1; j >= 0; j--) {
            if (n >= cap)
                return RLO_ERR_ARG;
            out[n++] = list[j];
        }
        return n;
    }
    for (int j = chan - 1; j >= 0; j--) {
        if (!rlo_check_passed_origin(world_size, rank, origin, list[j])) {
            if (n >= cap)
                return RLO_ERR_ARG;
            out[n++] = list[j];
        }
    }
    return n;
}

int rlo_fwd_send_cnt(int world_size, int rank, int origin, int from_rank)
{
    int tmp[64];
    return rlo_fwd_targets(world_size, rank, origin, from_rank, tmp, 64);
}

int rlo_initiator_targets(int world_size, int rank, int *out, int cap)
{
    int list[64];
    int len = rlo_send_list(world_size, rank, list, 64, 0);
    if (len < 0)
        return len;
    if (len > cap)
        return RLO_ERR_ARG;
    for (int j = 0; j < len; j++)
        out[j] = list[len - 1 - j]; /* furthest-first */
    return len;
}

uint64_t rlo_now_usec(void)
{
    struct timeval tv;
    gettimeofday(&tv, 0);
    return (uint64_t)tv.tv_sec * 1000000u + (uint64_t)tv.tv_usec;
}
