/* Internal structures shared between transport worlds and the engine.
 *
 * The world is polymorphic — a transport vtable (SURVEY.md §7 "transport
 * vtable" design stance): the engine only ever talks through
 * rlo_world_isend / rlo_world_poll / rlo_world_register, and each
 * transport (in-process loopback, POSIX-SHM multi-process, compile-gated
 * MPI) supplies the ops. This is the seam the reference lacks — its MPI
 * calls are hard-wired throughout rootless_ops.c (SURVEY.md §2 C11).
 */
#ifndef RLO_INTERNAL_H
#define RLO_INTERNAL_H

#include "rlo_core.h"

#include <stdlib.h>
#include <string.h>

/* ------------------------------------------------------------------ */
/* Small-object pool (docs/DESIGN.md S13). The hot path allocates and */
/* frees a handful of tiny objects PER FRAME (wire node, completion   */
/* handle, message struct, ARQ entry, small frame blobs); under the   */
/* batched progress loop that malloc/free traffic dominated the       */
/* per-frame cost. Worlds own size-classed freelists; every pooled    */
/* object carries a one-pointer header naming its owning world (NULL  */
/* = plain malloc), so the type-blind unref/free helpers route each   */
/* object back where it came from. Single-threaded per world, like    */
/* every other world structure (the cooperative-polling model).       */
/*                                                                    */
/* Under ASan/TSan the pool compiles to plain malloc/free so the      */
/* sanitizers keep full poisoning/race precision — the sanitizer      */
/* gates verify the allocation DISCIPLINE, the pool only changes the  */
/* allocator behind it.                                               */
/*                                                                    */
/* LIFETIME RULE: a pooled object's free writes through its header    */
/* into the owning world's freelists, so every engine, coll, and      */
/* stray blob/handle/node ref MUST be released before rlo_world_free  */
/* (this was already the de-facto rule — engine_free dereferences     */
/* e->w — but the pool makes violations memory corruption instead of  */
/* a benign leak; the Python bindings close tracked engines AND colls */
/* in NativeWorld.close() for exactly this reason).                   */
/* ------------------------------------------------------------------ */
#if defined(__SANITIZE_ADDRESS__) || defined(__SANITIZE_THREAD__)
#define RLO_POOL_PASSTHROUGH 1
#endif
#if !defined(RLO_POOL_PASSTHROUGH) && defined(__has_feature)
#if __has_feature(address_sanitizer) || __has_feature(thread_sanitizer)
#define RLO_POOL_PASSTHROUGH 1
#endif
#endif

#define RLO_POOL_CLASSES 4
/* class ceilings: node/handle/ack-blob | msg/rtx | bench-size frame
 * blobs | anything small enough to be worth keeping */
#define RLO_POOL_C0 64
#define RLO_POOL_C1 192
#define RLO_POOL_C2 512
#define RLO_POOL_C3 2048

typedef struct rlo_pool_hdr {
    /* allocated: the owning world (NULL = plain malloc);
     * on a freelist: the next free chunk */
    void *link;
    size_t cls; /* size class, stable across reuse */
} rlo_pool_hdr;

void *rlo_pool_alloc(rlo_world *w, size_t size);
void rlo_pool_free(void *p);
/* world teardown: release every chunk parked on the freelists */
void rlo_pool_drain(rlo_world *w);

/* Refcounted send-completion handle (~MPI_Request tested by MPI_Test;
 * reference keeps per-destination isend req arrays, rootless_ops.c:296).
 * One ref is held by the in-flight wire node, one by the tracking message
 * (when the sender tracks completion at all — votes don't). */
typedef struct rlo_handle {
    int delivered;
    /* set alongside delivered when the send terminated WITHOUT
     * delivering (peer dead, frame dropped by fault injection) — the
     * MPI_ERR_*-in-status analogue; done-but-failed, never hung */
    int failed;
    int refs;
} rlo_handle;

/* Pool-aware constructor: handles are freed type-blind through
 * rlo_handle_unref -> rlo_pool_free, so EVERY handle must carry the
 * pool header — w == NULL just means the plain-malloc class. */
static inline rlo_handle *rlo_handle_new_w(rlo_world *w, int refs)
{
    rlo_handle *h = (rlo_handle *)rlo_pool_alloc(w, sizeof(*h));
    if (h) {
        memset(h, 0, sizeof(*h));
        h->refs = refs;
    }
    return h;
}

static inline rlo_handle *rlo_handle_new(int refs)
{
    return rlo_handle_new_w(0, refs);
}

static inline void rlo_handle_unref(rlo_handle *h)
{
    if (h && --h->refs == 0)
        rlo_pool_free(h);
}

/* Refcounted immutable frame blob. One encoded frame is shared across
 * every fan-out send, the engine's tracking message, and (for in-process
 * transports) the receiver — the native analogue of the Python engine
 * passing one immutable `bytes` to every isend, and the zero-copy spirit
 * of the one-sided remote-write transport the reference abandoned
 * (rma_util.c:29-62). Single-threaded refcounts (the engine model is
 * cooperative polling; rootless_ops.h:216). */
typedef struct rlo_blob {
    int refs;
    int64_t len;
    uint8_t data[];
} rlo_blob;

/* Pool-aware constructor (same rule as handles: unref routes through
 * rlo_pool_free, so every blob carries the header; small blobs from a
 * world-owning call site recycle through that world's freelists). */
static inline rlo_blob *rlo_blob_new_w(rlo_world *w, int64_t len)
{
    rlo_blob *b = (rlo_blob *)rlo_pool_alloc(
        w, sizeof(rlo_blob) + (size_t)(len > 0 ? len : 0));
    if (b) {
        b->refs = 1;
        b->len = len;
    }
    return b;
}

static inline rlo_blob *rlo_blob_new(int64_t len)
{
    return rlo_blob_new_w(0, len);
}

static inline rlo_blob *rlo_blob_ref(rlo_blob *b)
{
    b->refs++;
    return b;
}

static inline void rlo_blob_unref(rlo_blob *b)
{
    if (b && --b->refs == 0)
        rlo_pool_free(b);
}

/* One in-flight or delivered wire frame. Owned by the world until the
 * receiving engine polls it off its inbox; then owned by the engine
 * (which steals the frame ref). */
typedef struct rlo_wire_node {
    struct rlo_wire_node *next;
    int src, dst, tag, comm;
    uint64_t due; /* deliver-at tick (latency injection) */
    rlo_handle *handle;
    rlo_blob *frame; /* encoded frame bytes */
} rlo_wire_node;

/* ---- transport vtable ---- */
typedef struct rlo_transport_ops {
    const char *name;
    /* Send one encoded frame. The transport takes its own ref on `frame`
     * if it retains it (in-process delivery, pending MPI request);
     * cross-process transports may instead copy out of it. The caller
     * keeps its ref. */
    int (*isend)(rlo_world *w, int src, int dst, int comm, int tag,
                 rlo_blob *frame, rlo_handle **out);
    /* next frame addressed to (rank, comm), or NULL; caller owns it */
    rlo_wire_node *(*poll)(rlo_world *w, int rank, int comm);
    int (*quiescent)(const rlo_world *w);
    int64_t (*sent_cnt)(const rlo_world *w);
    int64_t (*delivered_cnt)(const rlo_world *w);
    /* transport-specific termination detection (reference cleanup drain,
     * rootless_ops.c:1613-1625); collective for multi-process transports */
    int (*drain)(rlo_world *w, int max_spins);
    /* 1 when the world is dead (a peer process failed); NULL = never */
    int (*failed)(const rlo_world *w);
    /* 1 when `rank` showed liveness within timeout_usec; NULL = the
     * transport has no liveness signal (peers always considered alive) */
    int (*peer_alive)(const rlo_world *w, int rank, uint64_t timeout_usec);
    /* fault injection: simulate `rank`'s process dying (in-process
     * transports only); NULL = unsupported */
    int (*kill_rank)(rlo_world *w, int rank);
    /* fault injection: drop / duplicate the next `count` frames sent
     * src -> dst (in-process transports only); NULL = unsupported */
    int (*drop_next)(rlo_world *w, int src, int dst, int count);
    int (*dup_next)(rlo_world *w, int src, int dst, int count);
    /* fault injection: group partition (NULL group_of = heal) and
     * killed-rank revival (in-process transports only);
     * NULL = unsupported */
    int (*partition)(rlo_world *w, const int *group_of, int n);
    int (*revive)(rlo_world *w, int rank);
    /* block until every rank reaches the barrier (multi-process
     * transports); NULL = no-op (single-process worlds need none) */
    void (*barrier)(rlo_world *w);
    void (*free_)(rlo_world *w);
    /* OPTIONAL zero-copy gather send (docs/DESIGN.md S13): transmit
     * `hdr` (exactly RLO_HEADER_SIZE bytes, copied out by the
     * transport — it is caller-stack staging) followed by `frame`'s
     * PAYLOAD bytes (frame->data + RLO_HEADER_SIZE, taken by ref) as
     * one wire frame of frame->len bytes. Lets the ARQ send gate
     * restamp the per-edge seq/epoch of a large message without
     * cloning the payload into a per-frame arena. NULL = unsupported
     * (rlo_world_isend_hdr materializes a contiguous copy instead). */
    int (*isend_hdr)(rlo_world *w, int src, int dst, int comm, int tag,
                     const uint8_t *hdr, rlo_blob *frame,
                     rlo_handle **out);
    /* OPTIONAL dead-time skip for the batched progress loop (docs/
     * DESIGN.md S13): jump the transport's virtual delivery clock
     * straight to the next due frame and make it pollable. Returns
     * the number of frames made deliverable (0 = nothing to
     * advance). rlo_world_progress_all_n MAY call this before any
     * sweep — the batched driver treats injected latency as dead
     * virtual time to be skipped, so relative ordering of deliveries
     * (due order per channel, pump walk order across channels) is
     * the contract, not the wall-time interleaving of in-flight
     * frames with engine activity (the one-sweep-per-call driver
     * keeps the historical tick-at-a-time pacing). Only meaningful
     * for transports with an injected-latency clock (loopback);
     * real-time transports leave it NULL. */
    int64_t (*advance)(rlo_world *w);
    /* OPTIONAL test-support direct delivery (rlo_world_inject): place
     * one frame in dst's inbox bypassing latency and fault injection —
     * the mirror of LoopbackWorld.inject, where src MAY be a dead rank
     * (a dead incarnation's stale frame arriving late is the point of
     * the quarantine scenarios). NULL = rlo_world_inject falls back to
     * ops->isend, which applies fault injection. */
    int (*inject)(rlo_world *w, int src, int dst, int comm, int tag,
                  rlo_blob *frame);
} rlo_transport_ops;

/* Payload size (bytes) at which the ARQ send gate switches from the
 * clone-and-stamp path to the header-staging zero-copy path. Small
 * frames keep the clone: a 28-byte-header gather costs more in
 * bookkeeping than a sub-page memcpy saves, and keeping the seeded
 * small-frame schedules on the historical path preserves them
 * byte for byte. */
#define RLO_ZC_MIN_PAYLOAD 4096

/* Base world: first member of every transport's world struct. */
struct rlo_world {
    const rlo_transport_ops *ops;
    int world_size;
    int my_rank; /* bound rank for one-process-per-rank transports; -1 =
                    this process hosts every rank (loopback) */
    rlo_engine **engines;
    int n_engines, cap_engines;
    int stepping; /* re-entrancy guard for rlo_progress_all */
    /* small-object freelists (see the pool block above); drained by
     * each transport's free_ right before it releases the struct */
    void *pool_free[RLO_POOL_CLASSES];
    /* world_sweep's engine snapshot, reused across sweeps (the
     * stepping guard makes one scratch per world safe) */
    rlo_engine **sweep_scratch;
    int sweep_cap;
};

/* World-side transport API used by the engine (dispatch wrappers in
 * rlo_world_common.c). */
int rlo_world_isend(rlo_world *w, int src, int dst, int comm, int tag,
                    rlo_blob *frame, rlo_handle **out);
/* Gather send: dispatches to ops->isend_hdr when the transport has
 * one, else materializes hdr + frame payload into a contiguous blob
 * and falls back to ops->isend (one copy — the pre-S13 behavior). */
int rlo_world_isend_hdr(rlo_world *w, int src, int dst, int comm,
                        int tag, const uint8_t *hdr, rlo_blob *frame,
                        rlo_handle **out);
rlo_wire_node *rlo_world_poll(rlo_world *w, int rank, int comm);
int rlo_world_register(rlo_world *w, rlo_engine *e);
void rlo_world_unregister(rlo_world *w, rlo_engine *e);

/* Engine-side hooks the world's progress loop drives. */
void rlo_engine_progress_once(rlo_engine *e);
/* One progress turn with a frame budget: the transport drain stops
 * after max_frames polled frames (the rest stay queued for the next
 * turn); max_frames < 0 = unbounded (progress_once). Returns frames
 * polled this turn. The batched entry points slice their budget
 * through this. */
int64_t rlo_engine_progress_budget(rlo_engine *e, int64_t max_frames);

/* Drain loop for transports whose quiescent() is globally accurate from
 * one process. */
int rlo_drain_local(rlo_world *w, int max_spins);

#endif /* RLO_INTERNAL_H */
