/* Internal structures shared between transport worlds and the engine.
 *
 * The world is polymorphic — a transport vtable (SURVEY.md §7 "transport
 * vtable" design stance): the engine only ever talks through
 * rlo_world_isend / rlo_world_poll / rlo_world_register, and each
 * transport (in-process loopback, POSIX-SHM multi-process, compile-gated
 * MPI) supplies the ops. This is the seam the reference lacks — its MPI
 * calls are hard-wired throughout rootless_ops.c (SURVEY.md §2 C11).
 */
#ifndef RLO_INTERNAL_H
#define RLO_INTERNAL_H

#include "rlo_core.h"

#include <stdlib.h>
#include <string.h>

/* Refcounted send-completion handle (~MPI_Request tested by MPI_Test;
 * reference keeps per-destination isend req arrays, rootless_ops.c:296).
 * One ref is held by the in-flight wire node, one by the tracking message
 * (when the sender tracks completion at all — votes don't). */
typedef struct rlo_handle {
    int delivered;
    /* set alongside delivered when the send terminated WITHOUT
     * delivering (peer dead, frame dropped by fault injection) — the
     * MPI_ERR_*-in-status analogue; done-but-failed, never hung */
    int failed;
    int refs;
} rlo_handle;

static inline rlo_handle *rlo_handle_new(int refs)
{
    rlo_handle *h = (rlo_handle *)calloc(1, sizeof(*h));
    if (h)
        h->refs = refs;
    return h;
}

static inline void rlo_handle_unref(rlo_handle *h)
{
    if (h && --h->refs == 0)
        free(h);
}

/* Refcounted immutable frame blob. One encoded frame is shared across
 * every fan-out send, the engine's tracking message, and (for in-process
 * transports) the receiver — the native analogue of the Python engine
 * passing one immutable `bytes` to every isend, and the zero-copy spirit
 * of the one-sided remote-write transport the reference abandoned
 * (rma_util.c:29-62). Single-threaded refcounts (the engine model is
 * cooperative polling; rootless_ops.h:216). */
typedef struct rlo_blob {
    int refs;
    int64_t len;
    uint8_t data[];
} rlo_blob;

static inline rlo_blob *rlo_blob_new(int64_t len)
{
    rlo_blob *b =
        (rlo_blob *)malloc(sizeof(*b) + (size_t)(len > 0 ? len : 0));
    if (b) {
        b->refs = 1;
        b->len = len;
    }
    return b;
}

static inline rlo_blob *rlo_blob_ref(rlo_blob *b)
{
    b->refs++;
    return b;
}

static inline void rlo_blob_unref(rlo_blob *b)
{
    if (b && --b->refs == 0)
        free(b);
}

/* One in-flight or delivered wire frame. Owned by the world until the
 * receiving engine polls it off its inbox; then owned by the engine
 * (which steals the frame ref). */
typedef struct rlo_wire_node {
    struct rlo_wire_node *next;
    int src, dst, tag, comm;
    uint64_t due; /* deliver-at tick (latency injection) */
    rlo_handle *handle;
    rlo_blob *frame; /* encoded frame bytes */
} rlo_wire_node;

/* ---- transport vtable ---- */
typedef struct rlo_transport_ops {
    const char *name;
    /* Send one encoded frame. The transport takes its own ref on `frame`
     * if it retains it (in-process delivery, pending MPI request);
     * cross-process transports may instead copy out of it. The caller
     * keeps its ref. */
    int (*isend)(rlo_world *w, int src, int dst, int comm, int tag,
                 rlo_blob *frame, rlo_handle **out);
    /* next frame addressed to (rank, comm), or NULL; caller owns it */
    rlo_wire_node *(*poll)(rlo_world *w, int rank, int comm);
    int (*quiescent)(const rlo_world *w);
    int64_t (*sent_cnt)(const rlo_world *w);
    int64_t (*delivered_cnt)(const rlo_world *w);
    /* transport-specific termination detection (reference cleanup drain,
     * rootless_ops.c:1613-1625); collective for multi-process transports */
    int (*drain)(rlo_world *w, int max_spins);
    /* 1 when the world is dead (a peer process failed); NULL = never */
    int (*failed)(const rlo_world *w);
    /* 1 when `rank` showed liveness within timeout_usec; NULL = the
     * transport has no liveness signal (peers always considered alive) */
    int (*peer_alive)(const rlo_world *w, int rank, uint64_t timeout_usec);
    /* fault injection: simulate `rank`'s process dying (in-process
     * transports only); NULL = unsupported */
    int (*kill_rank)(rlo_world *w, int rank);
    /* fault injection: drop / duplicate the next `count` frames sent
     * src -> dst (in-process transports only); NULL = unsupported */
    int (*drop_next)(rlo_world *w, int src, int dst, int count);
    int (*dup_next)(rlo_world *w, int src, int dst, int count);
    /* fault injection: group partition (NULL group_of = heal) and
     * killed-rank revival (in-process transports only);
     * NULL = unsupported */
    int (*partition)(rlo_world *w, const int *group_of, int n);
    int (*revive)(rlo_world *w, int rank);
    /* block until every rank reaches the barrier (multi-process
     * transports); NULL = no-op (single-process worlds need none) */
    void (*barrier)(rlo_world *w);
    void (*free_)(rlo_world *w);
} rlo_transport_ops;

/* Base world: first member of every transport's world struct. */
struct rlo_world {
    const rlo_transport_ops *ops;
    int world_size;
    int my_rank; /* bound rank for one-process-per-rank transports; -1 =
                    this process hosts every rank (loopback) */
    rlo_engine **engines;
    int n_engines, cap_engines;
    int stepping; /* re-entrancy guard for rlo_progress_all */
};

/* World-side transport API used by the engine (dispatch wrappers in
 * rlo_world_common.c). */
int rlo_world_isend(rlo_world *w, int src, int dst, int comm, int tag,
                    rlo_blob *frame, rlo_handle **out);
rlo_wire_node *rlo_world_poll(rlo_world *w, int rank, int comm);
int rlo_world_register(rlo_world *w, rlo_engine *e);
void rlo_world_unregister(rlo_world *w, rlo_engine *e);

/* Engine-side hooks the world's progress loop drives. */
void rlo_engine_progress_once(rlo_engine *e);

/* Drain loop for transports whose quiescent() is globally accurate from
 * one process. */
int rlo_drain_local(rlo_world *w, int max_spins);

#endif /* RLO_INTERNAL_H */
