/* Internal structures shared between the loopback world and the engine. */
#ifndef RLO_INTERNAL_H
#define RLO_INTERNAL_H

#include "rlo_core.h"

#include <stdlib.h>
#include <string.h>

/* Refcounted send-completion handle (~MPI_Request tested by MPI_Test;
 * reference keeps per-destination isend req arrays, rootless_ops.c:296).
 * One ref is held by the in-flight wire node, one by the tracking message
 * (when the sender tracks completion at all — votes don't). */
typedef struct rlo_handle {
    int delivered;
    int refs;
} rlo_handle;

static inline rlo_handle *rlo_handle_new(int refs)
{
    rlo_handle *h = (rlo_handle *)calloc(1, sizeof(*h));
    if (h)
        h->refs = refs;
    return h;
}

static inline void rlo_handle_unref(rlo_handle *h)
{
    if (h && --h->refs == 0)
        free(h);
}

/* One in-flight or delivered wire frame. Owned by the world until the
 * receiving engine polls it off its inbox; then owned by the engine. */
typedef struct rlo_wire_node {
    struct rlo_wire_node *next;
    int src, dst, tag, comm;
    uint64_t due; /* deliver-at tick (latency injection) */
    rlo_handle *handle;
    int64_t len;
    uint8_t data[]; /* encoded frame */
} rlo_wire_node;

/* World-side transport API used by the engine. */
int rlo_world_isend(rlo_world *w, int src, int dst, int comm, int tag,
                    const uint8_t *raw, int64_t len, rlo_handle **out);
rlo_wire_node *rlo_world_poll(rlo_world *w, int rank, int comm);
int rlo_world_register(rlo_world *w, rlo_engine *e);
void rlo_world_unregister(rlo_world *w, rlo_engine *e);

/* Engine-side hook the world's progress loop drives. */
void rlo_engine_progress_once(rlo_engine *e);

#endif /* RLO_INTERNAL_H */
