/* In-process loopback transport world: N ranks in one address space.
 *
 * Native counterpart of rlo_tpu/transport/loopback.py. The reference has no
 * equivalent — its tests need mpirun even on one host (SURVEY.md §4).
 * Guarantees mirror MPI and the Python loopback: per-(src,dst,comm) FIFO
 * order even under latency injection, reliable delivery, unspecified
 * cross-pair order (which the seeded latency deliberately perturbs).
 *
 * Single-threaded by design: the engine model is cooperative polling
 * (reference rootless_ops.h:216 documents thread-unsafety; we keep the
 * model and make it explicit).
 */
#include "rlo_internal.h"

/* per-(src,dst,comm) FIFO of frames still "in flight" */
typedef struct rlo_channel {
    struct rlo_channel *next;
    int src, dst, comm;
    rlo_wire_node *head, *tail;
} rlo_channel;

struct rlo_world {
    int world_size;
    int latency;
    uint64_t rng;
    uint64_t tick;
    int64_t sent_cnt, delivered_cnt;
    rlo_channel *channels;
    rlo_wire_node **inbox_head; /* per-rank delivered FIFO */
    rlo_wire_node **inbox_tail;
    rlo_engine **engines;
    int n_engines, cap_engines;
    int stepping; /* re-entrancy guard for rlo_progress_all */
};

static uint64_t xorshift64(uint64_t *s)
{
    uint64_t x = *s;
    x ^= x << 13;
    x ^= x >> 7;
    x ^= x << 17;
    return *s = x;
}

rlo_world *rlo_world_new(int world_size, int latency, uint64_t seed)
{
    if (world_size < 2) /* reference rejects at bcomm_init :1464 */
        return 0;
    rlo_world *w = (rlo_world *)calloc(1, sizeof(*w));
    if (!w)
        return 0;
    w->world_size = world_size;
    w->latency = latency;
    w->rng = seed ? seed : 0x9e3779b97f4a7c15ull;
    w->inbox_head =
        (rlo_wire_node **)calloc((size_t)world_size, sizeof(void *));
    w->inbox_tail =
        (rlo_wire_node **)calloc((size_t)world_size, sizeof(void *));
    if (!w->inbox_head || !w->inbox_tail) {
        free(w->inbox_head);
        free(w->inbox_tail);
        free(w);
        return 0;
    }
    return w;
}

static void free_node(rlo_wire_node *n)
{
    rlo_handle_unref(n->handle);
    free(n);
}

void rlo_world_free(rlo_world *w)
{
    if (!w)
        return;
    for (rlo_channel *c = w->channels; c;) {
        rlo_channel *nc = c->next;
        for (rlo_wire_node *n = c->head; n;) {
            rlo_wire_node *nn = n->next;
            free_node(n);
            n = nn;
        }
        free(c);
        c = nc;
    }
    for (int r = 0; r < w->world_size; r++) {
        for (rlo_wire_node *n = w->inbox_head[r]; n;) {
            rlo_wire_node *nn = n->next;
            free_node(n);
            n = nn;
        }
    }
    free(w->inbox_head);
    free(w->inbox_tail);
    free(w->engines);
    free(w);
}

int rlo_world_size(const rlo_world *w)
{
    return w->world_size;
}

int64_t rlo_world_sent_cnt(const rlo_world *w)
{
    return w->sent_cnt;
}

int64_t rlo_world_delivered_cnt(const rlo_world *w)
{
    return w->delivered_cnt;
}

int rlo_world_quiescent(const rlo_world *w)
{
    for (const rlo_channel *c = w->channels; c; c = c->next)
        if (c->head)
            return 0;
    for (int r = 0; r < w->world_size; r++)
        if (w->inbox_head[r])
            return 0;
    return 1;
}

static void inbox_push(rlo_world *w, rlo_wire_node *n)
{
    n->next = 0;
    if (w->inbox_tail[n->dst])
        w->inbox_tail[n->dst]->next = n;
    else
        w->inbox_head[n->dst] = n;
    w->inbox_tail[n->dst] = n;
    n->handle->delivered = 1;
    w->delivered_cnt++;
}

static rlo_channel *get_channel(rlo_world *w, int src, int dst, int comm)
{
    for (rlo_channel *c = w->channels; c; c = c->next)
        if (c->src == src && c->dst == dst && c->comm == comm)
            return c;
    rlo_channel *c = (rlo_channel *)calloc(1, sizeof(*c));
    if (!c)
        return 0;
    c->src = src;
    c->dst = dst;
    c->comm = comm;
    c->next = w->channels;
    w->channels = c;
    return c;
}

int rlo_world_isend(rlo_world *w, int src, int dst, int comm, int tag,
                    const uint8_t *raw, int64_t len, rlo_handle **out)
{
    if (dst < 0 || dst >= w->world_size || len < 0)
        return RLO_ERR_ARG;
    int caller_tracks = out != 0;
    rlo_handle *h = rlo_handle_new(caller_tracks ? 2 : 1);
    rlo_wire_node *n =
        (rlo_wire_node *)malloc(sizeof(*n) + (size_t)len);
    if (!h || !n) {
        free(h);
        free(n);
        return RLO_ERR_NOMEM;
    }
    n->next = 0;
    n->src = src;
    n->dst = dst;
    n->tag = tag;
    n->comm = comm;
    n->handle = h;
    n->len = len;
    if (len > 0)
        memcpy(n->data, raw, (size_t)len);
    w->sent_cnt++;
    if (w->latency <= 0) {
        inbox_push(w, n);
    } else {
        n->due = w->tick + xorshift64(&w->rng) % (uint64_t)(w->latency + 1);
        rlo_channel *c = get_channel(w, src, dst, comm);
        if (!c) {
            free_node(n);
            return RLO_ERR_NOMEM;
        }
        if (c->tail)
            c->tail->next = n;
        else
            c->head = n;
        c->tail = n;
        n->next = 0;
    }
    if (out)
        *out = h;
    return RLO_OK;
}

/* Move every due channel head to its inbox. Only heads can become due,
 * which preserves per-channel FIFO under latency injection. */
static void pump(rlo_world *w)
{
    w->tick++;
    for (rlo_channel *c = w->channels; c; c = c->next) {
        while (c->head && c->head->due <= w->tick) {
            rlo_wire_node *n = c->head;
            c->head = n->next;
            if (!c->head)
                c->tail = 0;
            inbox_push(w, n);
        }
    }
}

rlo_wire_node *rlo_world_poll(rlo_world *w, int rank, int comm)
{
    pump(w);
    rlo_wire_node *prev = 0;
    for (rlo_wire_node *n = w->inbox_head[rank]; n;
         prev = n, n = n->next) {
        if (n->comm != comm)
            continue;
        if (prev)
            prev->next = n->next;
        else
            w->inbox_head[rank] = n->next;
        if (w->inbox_tail[rank] == n)
            w->inbox_tail[rank] = prev;
        n->next = 0;
        return n;
    }
    return 0;
}

int rlo_world_register(rlo_world *w, rlo_engine *e)
{
    if (w->n_engines == w->cap_engines) {
        int cap = w->cap_engines ? w->cap_engines * 2 : 8;
        rlo_engine **p = (rlo_engine **)realloc(
            w->engines, (size_t)cap * sizeof(void *));
        if (!p)
            return RLO_ERR_NOMEM;
        w->engines = p;
        w->cap_engines = cap;
    }
    w->engines[w->n_engines++] = e;
    return RLO_OK;
}

void rlo_world_unregister(rlo_world *w, rlo_engine *e)
{
    for (int i = 0; i < w->n_engines; i++) {
        if (w->engines[i] == e) {
            memmove(&w->engines[i], &w->engines[i + 1],
                    (size_t)(w->n_engines - i - 1) * sizeof(void *));
            w->n_engines--;
            return;
        }
    }
}

void rlo_progress_all(rlo_world *w)
{
    /* handlers may initiate broadcasts (decision bcast inside the vote
     * handler) which re-enter; make nested turns no-ops (mirrors
     * EngineManager._stepping, rlo_tpu/engine.py) */
    if (w->stepping)
        return;
    w->stepping = 1;
    /* step over a snapshot: callbacks may register/unregister engines
     * mid-turn (the Python side iterates a copy for the same reason) */
    int n = w->n_engines;
    rlo_engine **snap =
        (rlo_engine **)malloc((size_t)(n ? n : 1) * sizeof(void *));
    if (snap) {
        memcpy(snap, w->engines, (size_t)n * sizeof(void *));
        for (int i = 0; i < n; i++) {
            /* skip engines freed by an earlier engine's callback */
            int live = 0;
            for (int j = 0; j < w->n_engines; j++)
                if (w->engines[j] == snap[i])
                    live = 1;
            if (live)
                rlo_engine_progress_once(snap[i]);
        }
        free(snap);
    }
    w->stepping = 0;
}

int rlo_drain(rlo_world *w, int max_spins)
{
    for (int i = 0; i < max_spins; i++) {
        rlo_progress_all(w);
        if (rlo_world_quiescent(w)) {
            int idle = 1;
            for (int j = 0; j < w->n_engines; j++)
                if (!rlo_engine_idle(w->engines[j]))
                    idle = 0;
            if (idle)
                return i;
        }
    }
    return RLO_ERR_STALL;
}
