/* In-process loopback transport world: N ranks in one address space.
 *
 * Native counterpart of rlo_tpu/transport/loopback.py. The reference has no
 * equivalent — its tests need mpirun even on one host (SURVEY.md §4).
 * Guarantees mirror MPI and the Python loopback: per-(src,dst,comm) FIFO
 * order even under latency injection, reliable delivery, unspecified
 * cross-pair order (which the seeded latency deliberately perturbs).
 *
 * Single-threaded by design: the engine model is cooperative polling
 * (reference rootless_ops.h:216 documents thread-unsafety; we keep the
 * model and make it explicit).
 */
#include "rlo_internal.h"

/* per-(src,dst,comm) FIFO of frames still "in flight" */
typedef struct rlo_channel {
    struct rlo_channel *next;      /* global list: pump/teardown order */
    struct rlo_channel *pair_next; /* per-(src,dst) lookup chain */
    int src, dst, comm;
    rlo_wire_node *head, *tail;
} rlo_channel;

typedef struct rlo_loop_world {
    rlo_world base;
    int latency;
    uint64_t rng;
    uint64_t tick;
    int64_t sent_cnt, delivered_cnt;
    /* frames currently in flight or waiting in an inbox — kept live
     * so quiescent() is O(1) (docs/DESIGN.md S13: the batched
     * progress loop and the drain spin consult it every sweep; the
     * historical walk was O(channels + ranks) per call) */
    int64_t pending;
    rlo_channel *channels;
    rlo_channel **pair_idx; /* ws*ws buckets: O(1) channel lookup
                             * (the linear scan of `channels` was the
                             * hottest line under batched progress) */
    rlo_wire_node **inbox_head; /* per-rank delivered FIFO */
    rlo_wire_node **inbox_tail;
    uint8_t *dead;  /* fault injection: killed ranks */
    int *drops;     /* fault injection: per (src*ws+dst) pending drops */
    int *dups;      /* fault injection: per (src*ws+dst) pending dups */
    int *pgroup;    /* fault injection: partition group per rank
                     * (NULL = no partition); frames crossing groups
                     * are dropped at send time */
} rlo_loop_world;

static uint64_t xorshift64(uint64_t *s)
{
    uint64_t x = *s;
    x ^= x << 13;
    x ^= x >> 7;
    x ^= x << 17;
    return *s = x;
}

/* rlo-sentinel: transfers(n) */
static void free_node(rlo_wire_node *n)
{
    rlo_handle_unref(n->handle);
    rlo_blob_unref(n->frame);
    rlo_pool_free(n);
}

static void loop_free(rlo_world *base)
{
    rlo_loop_world *w = (rlo_loop_world *)base;
    for (rlo_channel *c = w->channels; c;) {
        rlo_channel *nc = c->next;
        for (rlo_wire_node *n = c->head; n;) {
            rlo_wire_node *nn = n->next;
            free_node(n);
            n = nn;
        }
        free(c);
        c = nc;
    }
    for (int r = 0; r < base->world_size; r++) {
        for (rlo_wire_node *n = w->inbox_head[r]; n;) {
            rlo_wire_node *nn = n->next;
            free_node(n);
            n = nn;
        }
    }
    free(w->pair_idx);
    free(w->inbox_head);
    free(w->inbox_tail);
    free(w->dead);
    free(w->drops);
    free(w->dups);
    free(w->pgroup);
    free(base->engines);
    rlo_pool_drain(base);
    free(w);
}

static int64_t loop_sent(const rlo_world *base)
{
    return ((const rlo_loop_world *)base)->sent_cnt;
}

static int64_t loop_delivered(const rlo_world *base)
{
    return ((const rlo_loop_world *)base)->delivered_cnt;
}

static int loop_quiescent(const rlo_world *base)
{
    return ((const rlo_loop_world *)base)->pending == 0;
}

/* rlo-sentinel: transfers(n) — the inbox owns it until polled */
static void inbox_push(rlo_loop_world *w, rlo_wire_node *n)
{
    n->next = 0;
    if (w->inbox_tail[n->dst])
        w->inbox_tail[n->dst]->next = n;
    else
        w->inbox_head[n->dst] = n;
    w->inbox_tail[n->dst] = n;
    n->handle->delivered = 1;
    w->delivered_cnt++;
}

static rlo_channel *get_channel(rlo_loop_world *w, int src, int dst,
                                int comm)
{
    rlo_channel **bucket =
        &w->pair_idx[src * w->base.world_size + dst];
    for (rlo_channel *c = *bucket; c; c = c->pair_next)
        if (c->comm == comm)
            return c;
    rlo_channel *c = (rlo_channel *)calloc(1, sizeof(*c));
    if (!c)
        return 0;
    c->src = src;
    c->dst = dst;
    c->comm = comm;
    c->next = w->channels; /* same global order as the historical scan */
    w->channels = c;
    c->pair_next = *bucket;
    *bucket = c;
    return c;
}

static int loop_isend(rlo_world *base, int src, int dst, int comm, int tag,
                      rlo_blob *frame, rlo_handle **out)
{
    rlo_loop_world *w = (rlo_loop_world *)base;
    if (dst < 0 || dst >= base->world_size || !frame || frame->len < 0)
        return RLO_ERR_ARG;
    if (w->dead[src] || w->dead[dst] ||
        (w->pgroup && w->pgroup[src] != w->pgroup[dst]) ||
        w->drops[src * base->world_size + dst] > 0) {
        /* a dead host's packets never leave it; packets to a dead host
         * (or hit by loss injection) vanish — the handle completes
         * done-but-failed so the sender's queues drain */
        if (w->drops[src * base->world_size + dst] > 0)
            w->drops[src * base->world_size + dst]--;
        if (out) {
            rlo_handle *h = rlo_handle_new_w(base, 1);
            if (!h)
                return RLO_ERR_NOMEM;
            h->delivered = 1;
            h->failed = 1;
            *out = h;
        }
        return RLO_OK;
    }
    int dup = 0;
    if (w->dups[src * base->world_size + dst] > 0) {
        w->dups[src * base->world_size + dst]--;
        dup = 1; /* duplication injection: deliver this frame twice */
    }
    int caller_tracks = out != 0;
    rlo_handle *h = rlo_handle_new_w(base, caller_tracks ? 2 : 1);
    rlo_wire_node *n =
        (rlo_wire_node *)rlo_pool_alloc(base, sizeof(*n));
    if (!h || !n) {
        rlo_pool_free(h);
        rlo_pool_free(n);
        return RLO_ERR_NOMEM;
    }
    n->next = 0;
    n->src = src;
    n->dst = dst;
    n->tag = tag;
    n->comm = comm;
    n->handle = h;
    n->frame = rlo_blob_ref(frame); /* zero-copy in-process delivery */
    w->sent_cnt++;
    for (int copy = 0; copy <= dup; copy++) {
        if (copy == 1) {
            /* duplication injection: a second node sharing the frame
             * blob, with its own (untracked) completion handle */
            rlo_wire_node *n2 =
                (rlo_wire_node *)rlo_pool_alloc(base, sizeof(*n2));
            rlo_handle *h2 = rlo_handle_new_w(base, 1);
            if (!n2 || !h2) { /* injection is best-effort: skip */
                rlo_pool_free(n2);
                rlo_pool_free(h2);
                break;
            }
            *n2 = *n;
            n2->next = 0;
            n2->handle = h2;
            n2->frame = rlo_blob_ref(frame);
            n = n2;
        }
        if (w->latency <= 0) {
            inbox_push(w, n);
        } else {
            n->due =
                w->tick + xorshift64(&w->rng) % (uint64_t)(w->latency + 1);
            rlo_channel *c = get_channel(w, src, dst, comm);
            if (!c) {
                /* free_node drops the NODE's handle ref only; on this
                 * error return *out is never set, so the ref reserved
                 * for the caller must be dropped here too or a
                 * tracked send leaks its handle (rlo-sentinel S3
                 * audit, round 15) */
                free_node(n);
                if (caller_tracks)
                    rlo_handle_unref(h);
                return RLO_ERR_NOMEM;
            }
            if (c->tail)
                c->tail->next = n;
            else
                c->head = n;
            c->tail = n;
            n->next = 0;
        }
        w->pending++; /* enqueued (inbox or channel): in flight */
    }
    if (out)
        *out = h;
    /* rlo-sentinel: trusted — the copy loop runs at least once
     * (copy = 0 <= dup), so every node was pushed or freed above;
     * the zero-iteration path the CFG sees is infeasible */
    return RLO_OK;
}

static int loop_drop_next(rlo_world *base, int src, int dst, int count)
{
    rlo_loop_world *w = (rlo_loop_world *)base;
    if (src < 0 || src >= base->world_size || dst < 0 ||
        dst >= base->world_size || count < 0)
        return RLO_ERR_ARG;
    w->drops[src * base->world_size + dst] += count;
    return RLO_OK;
}

static int loop_dup_next(rlo_world *base, int src, int dst, int count)
{
    rlo_loop_world *w = (rlo_loop_world *)base;
    if (src < 0 || src >= base->world_size || dst < 0 ||
        dst >= base->world_size || count < 0)
        return RLO_ERR_ARG;
    w->dups[src * base->world_size + dst] += count;
    return RLO_OK;
}

/* Group partition: sends crossing the cut vanish (handles complete
 * done-but-failed); frames already in flight across the cut are
 * dropped too, like a link going dark. NULL group_of = heal. */
static int loop_partition(rlo_world *base, const int *group_of, int n)
{
    rlo_loop_world *w = (rlo_loop_world *)base;
    if (!group_of) {
        free(w->pgroup);
        w->pgroup = 0;
        return RLO_OK;
    }
    if (n != base->world_size)
        return RLO_ERR_ARG;
    if (!w->pgroup) {
        w->pgroup = (int *)malloc((size_t)n * sizeof(int));
        if (!w->pgroup)
            return RLO_ERR_NOMEM;
    }
    memcpy(w->pgroup, group_of, (size_t)n * sizeof(int));
    for (rlo_channel *c = w->channels; c; c = c->next) {
        if (w->pgroup[c->src] == w->pgroup[c->dst])
            continue;
        for (rlo_wire_node *nd = c->head; nd;) {
            rlo_wire_node *nn = nd->next;
            nd->handle->delivered = 1;
            nd->handle->failed = 1;
            free_node(nd);
            w->pending--;
            nd = nn;
        }
        c->head = c->tail = 0;
    }
    return RLO_OK;
}

/* Revive a killed rank's endpoint (empty inbox; the harness builds a
 * fresh engine with a bumped incarnation on top). */
static int loop_revive(rlo_world *base, int rank)
{
    rlo_loop_world *w = (rlo_loop_world *)base;
    if (rank < 0 || rank >= base->world_size)
        return RLO_ERR_ARG;
    w->dead[rank] = 0;
    for (rlo_wire_node *n = w->inbox_head[rank]; n;) {
        rlo_wire_node *nn = n->next;
        free_node(n);
        w->pending--;
        n = nn;
    }
    w->inbox_head[rank] = w->inbox_tail[rank] = 0;
    return RLO_OK;
}

/* Move every due channel head to its inbox. Only heads can become due,
 * which preserves per-channel FIFO under latency injection. */
static void pump(rlo_loop_world *w)
{
    w->tick++;
    for (rlo_channel *c = w->channels; c; c = c->next) {
        while (c->head && c->head->due <= w->tick) {
            rlo_wire_node *n = c->head;
            c->head = n->next;
            if (!c->head)
                c->tail = 0;
            inbox_push(w, n);
        }
    }
}

static int loop_kill_rank(rlo_world *base, int rank)
{
    rlo_loop_world *w = (rlo_loop_world *)base;
    if (rank < 0 || rank >= base->world_size)
        return RLO_ERR_ARG;
    w->dead[rank] = 1;
    /* drop frames in flight to or from the dead rank */
    for (rlo_channel *c = w->channels; c; c = c->next) {
        if (c->src != rank && c->dst != rank)
            continue;
        for (rlo_wire_node *n = c->head; n;) {
            rlo_wire_node *nn = n->next;
            n->handle->delivered = 1;
            n->handle->failed = 1;
            free_node(n);
            w->pending--;
            n = nn;
        }
        c->head = c->tail = 0;
    }
    for (rlo_wire_node *n = w->inbox_head[rank]; n;) {
        rlo_wire_node *nn = n->next;
        free_node(n);
        w->pending--;
        n = nn;
    }
    w->inbox_head[rank] = w->inbox_tail[rank] = 0;
    return RLO_OK;
}

/* Dead-time skip for the batched progress loop (rlo_internal.h
 * `advance`): jump the tick clock straight to the earliest due frame
 * and move every head due by then — identical per-channel FIFO and the
 * same cross-channel walk order as pump(), just without burning one
 * poll per empty tick. */
static int64_t loop_advance(rlo_world *base)
{
    rlo_loop_world *w = (rlo_loop_world *)base;
    uint64_t min_due = 0;
    int have = 0;
    for (rlo_channel *c = w->channels; c; c = c->next)
        if (c->head && (!have || c->head->due < min_due)) {
            min_due = c->head->due;
            have = 1;
        }
    if (!have)
        return 0;
    if (min_due > w->tick)
        w->tick = min_due;
    int64_t moved = 0;
    for (rlo_channel *c = w->channels; c; c = c->next) {
        while (c->head && c->head->due <= w->tick) {
            rlo_wire_node *n = c->head;
            c->head = n->next;
            if (!c->head)
                c->tail = 0;
            inbox_push(w, n);
            moved++;
        }
    }
    return moved;
}

/* Direct delivery for rlo_world_inject: bypasses latency and fault
 * injection so a DEAD rank can source a stale frame (the quarantine
 * scenarios) — only a dead destination rejects, its inbox is gone.
 * Mirrors LoopbackWorld.inject: delivered_cnt counts it, sent_cnt
 * does not (it never crossed a channel). */
static int loop_inject(rlo_world *base, int src, int dst, int comm,
                       int tag, rlo_blob *frame)
{
    rlo_loop_world *w = (rlo_loop_world *)base;
    if (w->dead[dst])
        return RLO_ERR_ARG;
    rlo_handle *h = rlo_handle_new_w(base, 1);
    rlo_wire_node *n =
        (rlo_wire_node *)rlo_pool_alloc(base, sizeof(*n));
    if (!h || !n) {
        rlo_pool_free(h);
        rlo_pool_free(n);
        return RLO_ERR_NOMEM;
    }
    n->next = 0;
    n->src = src;
    n->dst = dst;
    n->tag = tag;
    n->comm = comm;
    n->due = 0;
    n->handle = h;
    n->frame = rlo_blob_ref(frame);
    w->pending++;
    inbox_push(w, n);
    return RLO_OK;
}

static rlo_wire_node *loop_poll(rlo_world *base, int rank, int comm)
{
    rlo_loop_world *w = (rlo_loop_world *)base;
    if (w->dead[rank])
        return 0;
    pump(w);
    rlo_wire_node *prev = 0;
    for (rlo_wire_node *n = w->inbox_head[rank]; n;
         prev = n, n = n->next) {
        if (n->comm != comm)
            continue;
        if (prev)
            prev->next = n->next;
        else
            w->inbox_head[rank] = n->next;
        if (w->inbox_tail[rank] == n)
            w->inbox_tail[rank] = prev;
        n->next = 0;
        w->pending--; /* handed to the engine */
        return n;
    }
    return 0;
}

static const rlo_transport_ops LOOP_OPS = {
    .name = "loopback",
    .isend = loop_isend,
    .poll = loop_poll,
    .quiescent = loop_quiescent,
    .sent_cnt = loop_sent,
    .delivered_cnt = loop_delivered,
    .drain = rlo_drain_local,
    .kill_rank = loop_kill_rank,
    .drop_next = loop_drop_next,
    .dup_next = loop_dup_next,
    .partition = loop_partition,
    .revive = loop_revive,
    .free_ = loop_free,
    .advance = loop_advance,
    .inject = loop_inject,
};

rlo_world *rlo_world_new(int world_size, int latency, uint64_t seed)
{
    if (world_size < 2) /* reference rejects at bcomm_init :1464 */
        return 0;
    rlo_loop_world *w = (rlo_loop_world *)calloc(1, sizeof(*w));
    if (!w)
        return 0;
    w->base.ops = &LOOP_OPS;
    w->base.world_size = world_size;
    w->base.my_rank = -1; /* hosts every rank */
    w->latency = latency;
    w->rng = seed ? seed : 0x9e3779b97f4a7c15ull;
    w->inbox_head =
        (rlo_wire_node **)calloc((size_t)world_size, sizeof(void *));
    w->inbox_tail =
        (rlo_wire_node **)calloc((size_t)world_size, sizeof(void *));
    w->dead = (uint8_t *)calloc((size_t)world_size, 1);
    w->drops = (int *)calloc((size_t)world_size * world_size, sizeof(int));
    w->dups = (int *)calloc((size_t)world_size * world_size, sizeof(int));
    w->pair_idx = (rlo_channel **)calloc(
        (size_t)world_size * world_size, sizeof(void *));
    if (!w->inbox_head || !w->inbox_tail || !w->dead || !w->drops ||
        !w->dups || !w->pair_idx) {
        free(w->pair_idx);
        free(w->inbox_head);
        free(w->inbox_tail);
        free(w->dead);
        free(w->drops);
        free(w->dups);
        free(w);
        return 0;
    }
    return &w->base;
}
