/* Structured event tracing — native twin of rlo_tpu/utils/tracing.py.
 *
 * The reference's only observability is gettimeofday timestamps and
 * commented-out printf tracepoints (SURVEY.md §5); this replaces them
 * with a bounded process-local ring of typed events the engine emits at
 * every protocol step. Single-threaded like the rest of the core (the
 * engine model is cooperative polling, rlo_core.h header note).
 */
#include "rlo_internal.h"

#define TRACE_CAP 65536

static rlo_trace_event ring[TRACE_CAP];
static int head;    /* next write slot */
static int count;   /* live events */
static int enabled;
static int64_t dropped;

void rlo_trace_set(int on)
{
    enabled = on;
}

int rlo_trace_enabled(void)
{
    return enabled;
}

void rlo_trace_emit(int rank, int kind, int a, int b, int c, int d)
{
    if (!enabled)
        return;
    rlo_trace_event *e = &ring[head];
    e->ts_usec = rlo_now_usec();
    e->rank = rank;
    e->kind = kind;
    e->a = a;
    e->b = b;
    e->c = c;
    e->d = d;
    head = (head + 1) % TRACE_CAP;
    if (count < TRACE_CAP)
        count++;
    else
        dropped++;
}

int rlo_trace_capacity(void)
{
    return TRACE_CAP;
}

int rlo_trace_drain(rlo_trace_event *out, int max)
{
    int n = count < max ? count : max;
    int start = (head - count + TRACE_CAP) % TRACE_CAP;
    for (int i = 0; i < n; i++)
        out[i] = ring[(start + i) % TRACE_CAP];
    count -= n;
    return n;
}

int64_t rlo_trace_dropped(void)
{
    return dropped;
}

void rlo_trace_clear(void)
{
    head = count = 0;
    dropped = 0;
}
