/* Structured event tracing — native twin of rlo_tpu/utils/tracing.py.
 *
 * The reference's only observability is gettimeofday timestamps and
 * commented-out printf tracepoints (SURVEY.md §5); this replaces them
 * with a bounded process-local ring of typed events the engine emits at
 * every protocol step.
 *
 * Concurrency (docs/DESIGN.md §15, rlo-sentinel S1): the ring is the
 * ONE piece of process-global mutable state reachable from the
 * GIL-releasing batched progress entry points.  Each world is
 * single-threaded cooperative polling, but two app threads may drive
 * two DIFFERENT worlds concurrently (the PR-8 serving-pump shape), and
 * both emit into this ring — so it is mutex-protected.  The
 * enabled flag is a relaxed atomic: the disabled fast path stays one
 * branch + one relaxed load, no lock, preserving the "one predictable
 * branch per instrumented site" overhead contract of rlo_core.h.
 */
#include "rlo_internal.h"

#include <pthread.h>
#include <stdatomic.h>

#define TRACE_CAP 65536

/* every field below is read/written only under trace_mu (the enabled
 * flag is atomic; the mutex itself is a concurrency primitive and out
 * of S1 scope) */
static pthread_mutex_t trace_mu = PTHREAD_MUTEX_INITIALIZER;
/* rlo-sentinel: guarded-by(trace_mu) */
static rlo_trace_event ring[TRACE_CAP];
static int head;    /* next write slot; rlo-sentinel: guarded-by(trace_mu) */
static int count;   /* live events; rlo-sentinel: guarded-by(trace_mu) */
static atomic_int enabled;
static int64_t dropped; /* rlo-sentinel: guarded-by(trace_mu) */

void rlo_trace_set(int on)
{
    atomic_store_explicit(&enabled, on, memory_order_relaxed);
}

int rlo_trace_enabled(void)
{
    return atomic_load_explicit(&enabled, memory_order_relaxed);
}

void rlo_trace_emit(int rank, int kind, int a, int b, int c, int d)
{
    if (!atomic_load_explicit(&enabled, memory_order_relaxed))
        return;
    uint64_t now = rlo_now_usec();
    pthread_mutex_lock(&trace_mu);
    rlo_trace_event *e = &ring[head];
    e->ts_usec = now;
    e->rank = rank;
    e->kind = kind;
    e->a = a;
    e->b = b;
    e->c = c;
    e->d = d;
    head = (head + 1) % TRACE_CAP;
    if (count < TRACE_CAP)
        count++;
    else
        dropped++;
    pthread_mutex_unlock(&trace_mu);
}

int rlo_trace_capacity(void)
{
    return TRACE_CAP;
}

int rlo_trace_drain(rlo_trace_event *out, int max)
{
    pthread_mutex_lock(&trace_mu);
    int n = count < max ? count : max;
    int start = (head - count + TRACE_CAP) % TRACE_CAP;
    for (int i = 0; i < n; i++)
        out[i] = ring[(start + i) % TRACE_CAP];
    count -= n;
    pthread_mutex_unlock(&trace_mu);
    return n;
}

int64_t rlo_trace_dropped(void)
{
    pthread_mutex_lock(&trace_mu);
    int64_t d = dropped;
    pthread_mutex_unlock(&trace_mu);
    return d;
}

void rlo_trace_clear(void)
{
    pthread_mutex_lock(&trace_mu);
    head = count = 0;
    dropped = 0;
    pthread_mutex_unlock(&trace_mu);
}
