"""Mixture-of-experts FFN with expert parallelism over a mesh axis.

Net-new capability (the reference has no model code or parallelism
strategies — SURVEY.md §5); completes the framework's strategy set
(dp / sp / tp / ep) on the same collective substrate: expert dispatch
and return are the `all_to_all` collective (rlo_tpu.ops.tpu_collectives),
the one communication pattern the other strategies don't use.

Design (switch-style top-1 routing with static capacity, the
TPU-friendly formulation — everything is dense one-hot einsums, no
dynamic shapes, so XLA tiles it onto the MXU):

  - router: logits = h @ wr -> softmax gate; each token goes to its
    argmax expert, carrying the gate probability (the only path the
    gradient needs through the discrete choice);
  - capacity C = ceil(cap_factor * T / E) per expert per shard; tokens
    beyond an expert's capacity are dropped (output 0 for them, the
    residual stream carries them unchanged);
  - dispatch: one-hot (T, E, C) tensor; expert inputs are
    einsum('tec,td->ecd') — and the combine on the way back multiplies
    by the gate, so dropped slots vanish;
  - expert parallelism: experts are sharded over `ep_axis` (each shard
    owns E/ep experts); the (E, C, d) dispatch block reshapes to
    (ep, E_local, C, d) and one all_to_all ships every shard's slice of
    my experts to me; after the local expert FFNs, a second all_to_all
    ships results back;
  - aux load-balancing loss (Switch Transformer form):
    E * sum_e fraction_dispatched(e) * mean_gate_prob(e).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from rlo_tpu.ops import tpu_collectives as tc


def init_moe_params(rng: jax.Array, d_model: int, d_ff: int,
                    n_experts: int) -> dict:
    """Router + per-expert FFN weights. Expert-indexed leading axes are
    the ones `ep` shards (see transformer.param_pspecs)."""
    k1, k2, k3 = jax.random.split(rng, 3)
    scale_in = d_model ** -0.5
    scale_out = d_ff ** -0.5
    return {
        "wr": jax.random.normal(k1, (d_model, n_experts),
                                jnp.float32) * scale_in,
        "w1": jax.random.normal(k2, (n_experts, d_model, d_ff),
                                jnp.float32) * scale_in,
        "w2": jax.random.normal(k3, (n_experts, d_ff, d_model),
                                jnp.float32) * scale_out,
    }


def moe_ffn(params: dict, h, n_experts: int, *,
            capacity_factor: float = 2.0,
            ep_axis: Optional[str] = None,
            all_to_all_algorithm: str = "xla") -> Tuple[jax.Array,
                                                        jax.Array]:
    """Apply the MoE FFN to ``h`` (..., d). Returns (out, aux_loss).

    With ``ep_axis``: ``params['w1']/['w2']`` arrive sharded to this
    shard's E/ep experts; ``h`` is this shard's tokens. Tokens cross
    shards only inside the two all_to_all calls.
    """
    orig_shape = h.shape
    dt = h.dtype
    d = h.shape[-1]
    x = h.reshape(-1, d)
    t = x.shape[0]
    ep = lax.axis_size(ep_axis) if ep_axis is not None else 1
    e_local = params["w1"].shape[0]
    n_exp = n_experts
    assert e_local * ep == n_exp, (
        f"expert shards {e_local}x{ep} != n_experts {n_exp}")
    cap = max(1, math.ceil(capacity_factor * t / n_exp))

    # ---- router (float32 for a stable softmax) ----
    logits = x.astype(jnp.float32) @ params["wr"].astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)          # (T, E)
    expert = jnp.argmax(gates, axis=-1)              # (T,)
    prob = jnp.max(gates, axis=-1)                   # (T,)

    onehot = jax.nn.one_hot(expert, n_exp, dtype=jnp.float32)   # (T, E)
    # position of each token within its expert's queue
    pos = (jnp.cumsum(onehot, axis=0) - onehot) * onehot         # (T, E)
    keep = (pos < cap) * onehot                                  # (T, E)
    slot = jax.nn.one_hot(jnp.sum(pos, axis=-1).astype(jnp.int32), cap,
                          dtype=jnp.float32)                     # (T, C)
    dispatch = (keep[:, :, None] * slot[:, None, :]).astype(dt)  # (T,E,C)

    # aux load-balance loss: fraction routed vs mean gate mass per expert
    frac = jnp.mean(onehot, axis=0)
    mean_gate = jnp.mean(gates, axis=0)
    aux = n_exp * jnp.sum(frac * mean_gate)

    # the heavy einsums run in the activation dtype (bf16 on TPU — the
    # MXU path, like the dense FFN); only the router needed float32
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)           # (E,C,d)

    if ep_axis is not None:
        blocks = expert_in.reshape(ep, e_local, cap, d)
        # dispatch: shard s's slice for my experts arrives at row s
        blocks = tc.all_to_all(blocks, ep_axis,
                               algorithm=all_to_all_algorithm)
        xin = jnp.moveaxis(blocks, 0, 1).reshape(e_local, ep * cap, d)
    else:
        xin = expert_in                                          # (E,C,d)

    h1 = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xin,
                                params["w1"].astype(dt)))
    out_blocks = jnp.einsum("ecf,efd->ecd", h1, params["w2"].astype(dt))

    if ep_axis is not None:
        back = jnp.moveaxis(
            out_blocks.reshape(e_local, ep, cap, d), 1, 0)
        back = tc.all_to_all(back, ep_axis,
                             algorithm=all_to_all_algorithm)
        expert_out = back.reshape(n_exp, cap, d)
    else:
        expert_out = out_blocks

    combine = dispatch * prob[:, None, None].astype(dt)          # (T,E,C)
    out = jnp.einsum("tec,ecd->td", combine, expert_out,
                     preferred_element_type=jnp.float32)
    return out.reshape(orig_shape).astype(dt), aux
