"""Device side of the paged KV cache (docs/DESIGN.md §12).

The dense serving cache (models.generate.init_kv_cache) allocates one
(max_len)-long seq-minor row per slot; this module replaces it with a
GLOBAL pool of ``page_size``-token pages per layer plus a per-slot
int32 page table, so slots only pin the pages their live prefix
actually spans and identical prompt prefixes can map the same physical
pages (rlo_tpu.serving.pages owns who-maps-what; this module only
moves bytes).

Layout: each layer's pool is (n_pages, kv_heads, head_dim, page_size)
in the activation dtype — a page IS one 128-lane block of the dense
seq-minor cache (the round-5 layout), so the pallas decode kernels
need only an index indirection, not a new tiling: logical tile ik of
slot b lives at physical page table[b, ik]. int8 caches carry
(n_pages, kv_heads, page_size) f32 scale sidecar pools at the same
page indexes.

Three entry points mirror models.generate exactly (the layer math IS
apply_layer via the same attention-hook pattern, so paged decode can
never drift from dense decode by construction):

  - ``paged_decode_step``: one token per slot through all layers;
    writes go to page table[b, pos_b // ps] (inactive slots write
    nothing: the offset sentinel drops the scatter), attends gather
    through the table.
  - ``paged_prefill_chunk``: ≤ page_size prompt tokens of ONE slot in
    one forward (the chunked-prefill unit — a chunk never crosses a
    page boundary, so its writes touch exactly one page).
  - ``copy_page``: the COW primitive (dst := src across every layer's
    pools).

On TPU the attends run through ``pallas.decode.paged_flash_decode``
(page-table scalar prefetch; cache HBM traffic = the live pages'
stored bytes) and the writes through the aliased page-write kernels;
everywhere else a gather + the einsum block attend keeps the numerics
in the exact class of the dense path.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from rlo_tpu.models.generate import (_attend_cache_block, _decode_cfg,
                                     _quantize_kv)
from rlo_tpu.models.transformer import (TransformerConfig, apply_layer,
                                        embed_tokens, _rmsnorm)


def init_page_pool(cfg: TransformerConfig, n_pages: int,
                   page_size: int):
    """Zeroed per-layer page pools: a list of {"k","v"} arrays shaped
    (n_pages, kv_heads, head_dim, page_size) — the dense cache's
    seq-minor layout with the sequence axis cut into pages. Page 0 is
    the reserved null page (pages.NULL_PAGE). On TPU the page size
    must be a 128-lane multiple so a page is a legal cache block.
    ``cfg.kv_cache_dtype='int8'`` adds (n_pages, kv_heads, page_size)
    f32 scale sidecars at the same page indexes."""
    # rlo-prover: lane-pinned (a page IS one 128-lane cache block)
    if jax.default_backend() == "tpu" and page_size % 128:
        raise ValueError(
            f"TPU pages must be 128-lane multiples, got {page_size}")
    shape = (n_pages, cfg.kv_heads, cfg.head_dim, page_size)
    sshape = (n_pages, cfg.kv_heads, page_size)
    if cfg.kv_cache_dtype == "int8":
        return [{"k": jnp.zeros(shape, jnp.int8),
                 "v": jnp.zeros(shape, jnp.int8),
                 "ks": jnp.zeros(sshape, jnp.float32),
                 "vs": jnp.zeros(sshape, jnp.float32)}
                for _ in range(cfg.n_layers)]
    if cfg.kv_cache_dtype is not None:
        raise ValueError(
            f"unknown kv_cache_dtype {cfg.kv_cache_dtype!r}")
    return [{"k": jnp.zeros(shape, cfg.act_dtype),
             "v": jnp.zeros(shape, cfg.act_dtype)}
            for _ in range(cfg.n_layers)]


def paged_view(entry, table):
    """Gather a layer's logical per-slot caches out of its pool:
    ``table`` (b, mp) int32 -> (k, v, ks, vs) where k/v are
    (b, kv_heads, head_dim, mp*page_size) — the dense attend layout —
    and ks/vs are the matching scale views (None for plain caches).
    Unmapped table entries point at the null page (zeros)."""
    b, mp = table.shape

    def g(x):                              # (P, kvh, hd, ps)
        got = x[table]                     # (b, mp, kvh, hd, ps)
        got = jnp.moveaxis(got, 1, 3)      # (b, kvh, hd, mp, ps)
        return got.reshape(b, x.shape[1], x.shape[2],
                           mp * x.shape[3])

    def gs(x):                             # (P, kvh, ps)
        got = x[table]                     # (b, mp, kvh, ps)
        got = jnp.moveaxis(got, 1, 2)      # (b, kvh, mp, ps)
        return got.reshape(b, x.shape[1], mp * x.shape[2])

    ks = gs(entry["ks"]) if "ks" in entry else None
    vs = gs(entry["vs"]) if "vs" in entry else None
    return g(entry["k"]), g(entry["v"]), ks, vs


def paged_write_rows(entry, k_row, v_row, ks_new, vs_new, page, off):
    """Write one (kvh, hd) K/V row per slot into its pool page:
    ``page``/``off`` are (b,) int32, row b lands at
    [page_b, :, :, off_b]. An off of page_size (the DROP sentinel —
    inactive or masked slots) drops the write entirely. Slots never
    share a writable page (the COW invariant), so the scatter indexes
    are disjoint."""
    ps = entry["k"].shape[3]
    kvh, hd = entry["k"].shape[1], entry["k"].shape[2]
    quant = ks_new is not None
    store_dt = entry["k"].dtype
    if jax.default_backend() == "tpu" and ps % 128 == 0:
        from rlo_tpu.pallas.decode import write_kv_page_row
        kc = write_kv_page_row(entry["k"], k_row, page, off)
        vc = write_kv_page_row(entry["v"], v_row, page, off)
        out = {"k": kc, "v": vc}
        if quant:
            # sidecars (P, kvh, ps) ride the same kernel via the free
            # (P, kvh, 1, ps) view (the write_kv_row trick)
            out["ks"] = write_kv_page_row(
                entry["ks"][:, :, None, :], ks_new[:, :, None],
                page, off)[:, :, 0, :]
            out["vs"] = write_kv_page_row(
                entry["vs"][:, :, None, :], vs_new[:, :, None],
                page, off)[:, :, 0, :]
        return out
    heads = jnp.arange(kvh)[None, :, None]
    dims = jnp.arange(hd)[None, None, :]
    idx = (page[:, None, None], heads, dims, off[:, None, None])
    out = {"k": entry["k"].at[idx].set(k_row.astype(store_dt),
                                       mode="drop"),
           "v": entry["v"].at[idx].set(v_row.astype(store_dt),
                                       mode="drop")}
    if quant:
        sidx = (page[:, None], jnp.arange(kvh)[None, :],
                off[:, None])
        out["ks"] = entry["ks"].at[sidx].set(ks_new, mode="drop")
        out["vs"] = entry["vs"].at[sidx].set(vs_new, mode="drop")
    return out


def paged_write_chunk(entry, kt, vt, ks_new, vs_new, page, off0,
                      n_valid):
    """Write one slot's prefill chunk: ``kt``/``vt`` (kvh, hd, T)
    seq-minor, token t landing at [page, :, :, off0 + t] for
    t < n_valid (pads dropped). The chunk never crosses a page
    boundary (off0 + n_valid <= page_size, caller-scheduled), so ONE
    page takes every lane — which is what makes the aliased TPU block
    write legal (a single program owns the block)."""
    ps = entry["k"].shape[3]
    kvh = entry["k"].shape[1]
    T = kt.shape[2]
    store_dt = entry["k"].dtype
    quant = ks_new is not None
    if jax.default_backend() == "tpu" and ps % 128 == 0:
        from rlo_tpu.pallas.decode import write_kv_page_block
        kc = write_kv_page_block(entry["k"], kt, page, off0, n_valid)
        vc = write_kv_page_block(entry["v"], vt, page, off0, n_valid)
        out = {"k": kc, "v": vc}
        if quant:
            out["ks"] = write_kv_page_block(
                entry["ks"][:, :, None, :], ks_new[:, None, :],
                page, off0, n_valid)[:, :, 0, :]
            out["vs"] = write_kv_page_block(
                entry["vs"][:, :, None, :], vs_new[:, None, :],
                page, off0, n_valid)[:, :, 0, :]
        return out
    # the scatter path: T updates into one page, pads dropped via the
    # page_size offset sentinel
    t = jnp.arange(T)
    offs = jnp.where(t < n_valid, off0 + t, ps)         # (T,)
    pagev = jnp.full((T,), page)
    heads = jnp.arange(kvh)[None, :, None]
    dims = jnp.arange(entry["k"].shape[2])[None, None, :]
    idx = (pagev[:, None, None], heads, dims, offs[:, None, None])
    krows = jnp.moveaxis(kt, 2, 0)                      # (T, kvh, hd)
    vrows = jnp.moveaxis(vt, 2, 0)
    out = {"k": entry["k"].at[idx].set(krows.astype(store_dt),
                                       mode="drop"),
           "v": entry["v"].at[idx].set(vrows.astype(store_dt),
                                       mode="drop")}
    if quant:
        sidx = (pagev[:, None], jnp.arange(kvh)[None, :],
                offs[:, None])
        out["ks"] = entry["ks"].at[sidx].set(
            jnp.moveaxis(ks_new, 1, 0), mode="drop")
        out["vs"] = entry["vs"].at[sidx].set(
            jnp.moveaxis(vs_new, 1, 0), mode="drop")
    return out


def _paged_attend(q, entry, table, pos_q, scale):
    """q (b, T, nh, hd) against the table-mapped pages: query i of row
    b sits at position pos_q[b, i] and attends positions <= it
    (write-then-attend, exactly like the dense block attend). TPU
    takes the page-prefetch flash kernel; everywhere else the gather +
    einsum block attend (the dense path's own fallback, so numerics
    stay in one class)."""
    ps = entry["k"].shape[3]
    d = q.shape[3]
    from rlo_tpu.pallas.decode import can_paged_flash
    if jax.default_backend() == "tpu" and can_paged_flash(ps, d):
        from rlo_tpu.pallas.decode import paged_flash_decode
        # contiguous per-row positions: pos0 = first query position
        return paged_flash_decode(
            q, entry["k"], entry["v"], table, pos_q[:, 0], scale,
            entry.get("ks"), entry.get("vs"))
    kg, vg, ksg, vsg = paged_view(entry, table)
    return _attend_cache_block(q, kg, vg, pos_q, scale, k_scale=ksg,
                               v_scale=vsg, use_flash=False)


def paged_decode_step(params: dict, token, pos, pools, table, active,
                      cfg: TransformerConfig):
    """One token (b,) int32 per slot at per-slot positions ``pos``
    (b,) through all layers over the paged pool. ``table`` (b, mp)
    int32 maps logical page i of slot b to its physical page;
    ``active`` (b,) bool gates the cache writes (inactive slots — mid
    prefill, retired, idle — compute garbage that is never written or
    read, the dense server's masked-row discipline). Returns (logits
    (b, vocab) f32, new pools). The layer math IS apply_layer with the
    cache attend swapped in — the same single-source structure as
    models.generate.decode_step."""
    cfg = _decode_cfg(cfg)
    dt = cfg.act_dtype
    posv = jnp.asarray(pos, jnp.int32)
    b = token.shape[0]
    ps = pools[0]["k"].shape[3]
    mp = table.shape[1]
    page_i = jnp.clip(posv // ps, 0, mp - 1)
    page = jnp.take_along_axis(table, page_i[:, None], axis=1)[:, 0]
    ok = active & (posv >= 0) & (posv < mp * ps)
    page = jnp.where(ok, page, 0)
    off = jnp.where(ok, posv % ps, ps)     # ps = the drop sentinel
    pos_arr = posv[:, None]
    x = embed_tokens(params["embed"], token[:, None], pos_arr, cfg)
    scale = 1.0 / (cfg.head_dim ** 0.5)
    new_pools = []
    for layer, lc in zip(params["layers"], pools):
        def attend(q, k, v, lc=lc):
            quant = "ks" in lc
            k_row, v_row = k[:, 0], v[:, 0]          # (b, kvh, hd)
            ks_new = vs_new = None
            if quant:
                k_row, ks_new = _quantize_kv(k_row)
                v_row, vs_new = _quantize_kv(v_row)
            entry = paged_write_rows(lc, k_row, v_row, ks_new,
                                     vs_new, page, off)
            new_pools.append(entry)
            return _paged_attend(q, entry, table, pos_arr,
                                 scale).astype(dt)

        x, _ = apply_layer(x, layer, cfg, attention=attend,
                           pos=pos_arr)
    x = _rmsnorm(x, params["ln_f"]["g"])
    logits = (x[:, 0, :] @ params["embed"].T.astype(dt)) \
        .astype(jnp.float32)
    return logits, new_pools


def paged_prefill_chunk(params: dict, tokens, pos0, n_valid, pools,
                        table, cfg: TransformerConfig):
    """One slot's prompt chunk in one forward: ``tokens`` (1, T) int32
    (pad beyond ``n_valid`` with any valid id), token i at position
    pos0 + i, K/V written to page table[0, pos0 // ps] for the first
    ``n_valid`` tokens only. The chunk must not cross a page boundary:
    pos0 % page_size + n_valid <= page_size (the server schedules
    page-aligned chunks). Returns (logits at position
    pos0 + n_valid - 1, new pools) — the final chunk's logits seed the
    first generated token, earlier chunks' are discarded.

    Queries attend the table-mapped prefix [0, pos0 + i]: earlier
    chunks' pages (shared prefix pages included) plus this chunk's own
    just-written rows — write-then-attend, so in-chunk causality rides
    the same mask as models.generate.block_decode. MoE configs route
    drop-free (pads must be inert), the ragged-prefill rule."""
    cfg = _decode_cfg(cfg)
    dt = cfg.act_dtype
    b, T = tokens.shape
    ps = pools[0]["k"].shape[3]
    mp = table.shape[1]
    pos0 = jnp.asarray(pos0, jnp.int32)
    n_valid = jnp.asarray(n_valid, jnp.int32)
    page = table[0, jnp.clip(pos0 // ps, 0, mp - 1)]
    off0 = pos0 % ps
    pos_arr = pos0 + jnp.arange(T, dtype=jnp.int32)[None, :]  # (1, T)
    x = embed_tokens(params["embed"], tokens, pos_arr, cfg)
    scale = 1.0 / (cfg.head_dim ** 0.5)
    new_pools = []
    for layer, lc in zip(params["layers"], pools):
        def attend(q, k, v, lc=lc):
            quant = "ks" in lc
            kt = k[0].transpose(1, 2, 0)             # (kvh, hd, T)
            vt = v[0].transpose(1, 2, 0)
            ks_new = vs_new = None
            if quant:
                # quantize over hd per position BEFORE the seq-minor
                # flip (the block_decode ordering)
                kq, ks_new = _quantize_kv(k[0])      # (T, kvh, hd)
                vq, vs_new = _quantize_kv(v[0])
                kt = kq.transpose(1, 2, 0)
                vt = vq.transpose(1, 2, 0)
                ks_new = ks_new.transpose(1, 0)      # (kvh, T)
                vs_new = vs_new.transpose(1, 0)
            entry = paged_write_chunk(lc, kt, vt, ks_new, vs_new,
                                      page, off0, n_valid)
            new_pools.append(entry)
            return _paged_attend(q, entry, table, pos_arr,
                                 scale).astype(dt)

        x, _ = apply_layer(x, layer, cfg, attention=attend,
                           pos=pos_arr)
    x = _rmsnorm(x, params["ln_f"]["g"])
    idx = jnp.clip(n_valid - 1, 0, T - 1)[None, None, None]
    xl = jnp.take_along_axis(
        x, jnp.broadcast_to(idx, (b, 1, x.shape[-1])), axis=1)[:, 0]
    logits = (xl @ params["embed"].T.astype(dt)).astype(jnp.float32)
    return logits, new_pools


def copy_page(pools, src, dst):
    """The COW primitive: dst := src across every layer's pools (K, V
    and the int8 scale sidecars). Jit with donated pools so the copy
    is in-place at the XLA level."""
    out = []
    for entry in pools:
        e = {"k": entry["k"].at[dst].set(entry["k"][src]),
             "v": entry["v"].at[dst].set(entry["v"][src])}
        if "ks" in entry:
            e["ks"] = entry["ks"].at[dst].set(entry["ks"][src])
            e["vs"] = entry["vs"].at[dst].set(entry["vs"][src])
        out.append(e)
    return out
