"""Speculative decoding: a cheap draft proposes, the target verifies.

Serving-side latency lever (net-new; the reference has no model stack
at all, SURVEY.md §5): decode is HBM-bound — every step reads the full
weights for ONE token per row — so a small draft model proposes
``gamma`` tokens autoregressively and the big target model judges all
of them in ONE forward (models.generate.block_decode), reading its
weights once per round instead of once per token. Greedy speculative
decoding is LOSSLESS: the emitted tokens each round are the target's
own argmax predictions ``t_pred[0..j]`` (a draft token is accepted
exactly when it equals the target's prediction, so the accepted prefix
and the bonus token are all target predictions), hence the output
equals plain greedy decode token for token — the parity oracle
tests/test_speculative.py pins.

Numerics caveat on that claim: "the target's prediction" must mean
the SAME floating-point logits plain decode would compute, or a
near-tie argmax can flip between the two paths. On TPU both paths now
route through one kernel family — plain decode_step uses the Pallas
flash-decode kernel at T=1 and the verify block_decode uses the same
kernel at T=gamma (pallas.decode.flash_block_decode), with identical
tile shapes, accumulation order, and dot dtypes per query row — and on
CPU both take the einsum path, so the parity holds by shared numerics
on both backends. One carve-out: a gamma-wide block too large for
VMEM at the T=1 tiling (pallas.decode._block_fits_vmem; needs extreme
nkv*gamma*head_dim, far beyond any shipped config at gamma <= 8)
falls back to einsum with a RuntimeWarning and the parity degrades to
near-tie class there (pinned on-chip by benchmarks/tpu_parity_check.py —
run on the real TPU, outside the CPU-forced pytest conftest — and by
the CPU oracles in tests/test_speculative.py always).

Cache bookkeeping rides the same masking trick as ragged decode:
rejected drafts leave garbage cache entries BEYOND each row's valid
position, which are never attended (every attend masks at the row's
own position) and are overwritten by later rounds. Per-row acceptance
lengths make the whole loop ragged; positions, cache writes, and
output writes are all per-row. One `lax.while_loop` over rounds (the
trip count is data-dependent — rows finish at different speeds), each
round = gamma draft decode_steps + 1 target block_decode.

Speedup economics: a round emits j+1 in [1, gamma] tokens for the cost
of gamma draft steps + one gamma-wide target forward. With draft cost
c_d (fraction of a target step) and acceptance-driven yield E[j+1],
speedup = E[j+1] / (gamma * c_d + c_verify). benchmarks/spec_bench.py
measures the two cost terms on the chip and the realized yield.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from rlo_tpu.models.generate import (block_decode, decode_step,
                                     init_kv_cache, prefill)
from rlo_tpu.models.transformer import TransformerConfig


def speculative_generate(params: dict, draft_params: dict, prompt,
                         cfg: TransformerConfig,
                         draft_cfg: TransformerConfig, *,
                         max_new: int, gamma: int = 4,
                         max_len: Optional[int] = None,
                         temperature: float = 0.0,
                         rng=None, return_rounds: bool = False):
    """Speculative continuation of ``prompt`` (b, plen) int32: returns
    (b, max_new) int32. ``gamma`` = draft tokens proposed per round.
    Both configs must share the vocabulary; the draft is typically a
    much smaller model (fewer layers / narrower).

    temperature == 0 (default): greedy — IDENTICAL to
    ``generate(params, prompt, cfg, max_new=max_new)`` by the
    lossless-acceptance construction; the draft only changes how fast
    the tokens arrive.

    temperature > 0 (needs ``rng``): LOSSLESS speculative SAMPLING —
    the standard rejection scheme: the draft SAMPLES x_i ~ p_d, the
    target accepts x_i with probability min(1, p_t(x_i)/p_d(x_i)), and
    the first rejected position resamples from the residual
    norm(max(p_t - p_d, 0)). Each emitted token is distributed exactly
    as plain temperature sampling from the target — in DISTRIBUTION,
    not trajectory (the rejection scheme spends randomness differently
    than `generate`'s per-step categorical, so token-for-token equality
    is not defined; tests/test_speculative.py pins the distributional
    equality statistically and the all-accept behavior exactly).
    A round emits n_acc + 1 tokens (the accepted prefix + the
    adjustment sample), capped at gamma when every draft is accepted —
    the same [1, gamma] per-round yield as the greedy path.

    ``return_rounds``: also return the number of verify rounds taken
    (b-invariant scalar) — rounds * (gamma draft steps + 1 verify) is
    the realized cost, and max_new / rounds the realized per-round
    yield, which benchmarks/spec_bench.py turns into the measured
    acceptance-driven speedup.
    """
    if cfg.vocab != draft_cfg.vocab:
        raise ValueError(
            f"draft vocab {draft_cfg.vocab} != target vocab {cfg.vocab}")
    if gamma < 1:
        raise ValueError("gamma >= 1 required")
    if temperature > 0 and rng is None:
        raise ValueError("sampling (temperature > 0) needs rng")
    b, plen = prompt.shape
    # + gamma slack: the last round's block writes reach at most
    # position plen + max_new - 1 + gamma (garbage tail, never read)
    max_len = max_len or (plen + max_new + gamma)
    if plen + max_new + gamma > max_len:
        raise ValueError(f"max_len {max_len} < plen {plen} + max_new "
                         f"{max_new} + gamma {gamma}")

    # hoist the f32 -> act-dtype weight cast OUT of the round loop:
    # XLA's LICM does this for `generate`'s scan but NOT for the
    # while_loop here, so every round re-converted the full f32
    # weights (~1.1 ms/round at 134M params — measured as a 0.95x
    # "speedup" until hoisted; same values, same numerics, the cast
    # is exactly the one apply_layer would do)
    def _cast(tree, dt):
        # MoE router weights ('wr') deliberately compute in f32
        # (moe.moe_ffn) — downcasting them would let a bf16-rounded
        # top-1 flip diverge speculative output from plain generate
        def f(path, p):
            if p.dtype != jnp.float32:
                return p
            if any(getattr(k, "key", None) == "wr" for k in path):
                return p
            return p.astype(dt)
        return jax.tree_util.tree_map_with_path(f, tree)

    params = _cast(params, cfg.act_dtype)
    draft_params = _cast(draft_params, draft_cfg.act_dtype)

    t_cache = init_kv_cache(cfg, b, max_len)
    d_cache = init_kv_cache(draft_cfg, b, max_len)
    t_logits, t_cache = prefill(params, prompt, t_cache, cfg)
    _, d_cache = prefill(draft_params, prompt, d_cache, draft_cfg)

    sampling = temperature > 0
    if sampling:
        rng, k0 = jax.random.split(rng)
        first = jax.random.categorical(
            k0, t_logits / temperature, axis=-1).astype(jnp.int32)
        key0 = rng
    else:
        first = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)  # (b,)
        key0 = jnp.zeros((2,), jnp.uint32)  # unused carry slot

    # first token: the target's own prefill prediction (or sample).
    # Invariant from here on (per row): out[:n_out] emitted; both
    # caches are validly filled through position pos-1 and last_tok
    # has NOT been processed by either model yet; its position is pos.
    out = jnp.zeros((b, max_new), jnp.int32)
    out = out.at[:, 0].set(first)
    n_out = jnp.ones((b,), jnp.int32)
    pos = jnp.full((b,), plen, jnp.int32)
    last_tok = first
    rows = jnp.arange(b)

    def round_body(state):
        out, n_out, pos, last_tok, t_cache, d_cache, rounds, key = state
        done = n_out >= max_new
        # per-LANE liveness: under vmap the while_loop iterates until
        # every lane finishes and the body runs for finished lanes
        # too — an unconditional rounds+1 would report the batch MAX
        # instead of each lane's own round count (the acceptance
        # metric spec_bench records)
        live = jnp.any(n_out < max_new).astype(jnp.int32)
        if sampling:
            key, kd, ka, kr = jax.random.split(key, 4)
            dkeys = jax.random.split(kd, gamma)

        # --- draft rollout: gamma ragged decode steps as ONE lax.scan
        # (unrolled python steps measured ~0.13 ms EACH of pure
        # overhead inside the while body on the v5e chip; the same
        # step inside a scan — plain generate's structure — runs at
        # ~4 us for a 1-layer draft) ---------------------------------
        def droll(carry, xs):
            cur, dc = carry
            i, key = xs
            logits, dc = decode_step(draft_params, cur, pos + i, dc,
                                     draft_cfg)
            if sampling:
                probs = jax.nn.softmax(
                    logits.astype(jnp.float32) / temperature, axis=-1)
                nxt = jax.random.categorical(
                    key, logits / temperature,
                    axis=-1).astype(jnp.int32)
            else:
                probs = jnp.zeros((b, 0), jnp.float32)  # unused
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (nxt, dc), (nxt, probs)

        scan_keys = (dkeys if sampling
                     else jnp.zeros((gamma, 2), jnp.uint32))
        (_, dc), (d_seq, d_prob_seq) = lax.scan(
            droll, (last_tok, d_cache),
            (jnp.arange(gamma, dtype=jnp.int32), scan_keys))
        d_mat = jnp.transpose(d_seq)                       # (b, gamma)

        # --- verify: ONE target forward over [last_tok, d_1..d_{g-1}]
        block = jnp.concatenate([last_tok[:, None],
                                 d_mat[:, :gamma - 1]], axis=1)
        v_logits, tc = block_decode(params, block, pos, t_cache, cfg)

        if not sampling:
            t_pred = jnp.argmax(v_logits, axis=-1).astype(jnp.int32)
            # --- lossless greedy acceptance -------------------------
            acc = (d_mat == t_pred)                        # (b, gamma)
            n_acc = jnp.cumprod(acc, axis=1).sum(axis=1)   # in [0, g]
            j = jnp.minimum(n_acc, gamma - 1)              # (b,)
            # emitted tokens this round are t_pred[:, :j+1] — the
            # target's own predictions (accepted drafts EQUAL them;
            # the bonus IS one): the whole losslessness argument
            n_emit_raw = j + 1
            emit_at = lambda i: t_pred[:, i]  # noqa: E731
            emit_ok = lambda i: i <= j        # noqa: E731
            new_last_live = t_pred[rows, j]
        else:
            # --- lossless rejection sampling ------------------------
            # accept x_i with prob min(1, p_t(x_i) / p_d(x_i)); first
            # rejection resamples from norm(max(p_t - p_d, 0)) — each
            # emitted token is exactly target-temperature-distributed
            p_t = jax.nn.softmax(
                v_logits.astype(jnp.float32) / temperature, axis=-1)
            p_d = jnp.moveaxis(d_prob_seq, 0, 1)       # (b, g, V)
            idx = d_mat[..., None]
            pt_x = jnp.take_along_axis(p_t, idx, -1)[..., 0]  # (b, g)
            pd_x = jnp.take_along_axis(p_d, idx, -1)[..., 0]
            u = jax.random.uniform(ka, (b, gamma))
            accept = u * pd_x < pt_x       # u < pt/pd, division-free
            n_acc = jnp.cumprod(accept, axis=1).sum(axis=1)  # [0, g]
            j = jnp.minimum(n_acc, gamma - 1)
            # residual distribution at the first rejected position
            p_t_j = jnp.take_along_axis(p_t, j[:, None, None],
                                        1)[:, 0]          # (b, V)
            p_d_j = jnp.take_along_axis(p_d, j[:, None, None],
                                        1)[:, 0]
            resid = jnp.maximum(p_t_j - p_d_j, 0.0)
            s = resid.sum(-1, keepdims=True)
            res_logits = jnp.where(resid > 0,
                                   jnp.log(jnp.maximum(resid, 1e-38)),
                                   -1e30)
            # p_t == p_d exactly (s == 0): the residual is empty and
            # any sample from p_t is already correct — fall back
            fb_logits = jnp.log(jnp.maximum(p_t_j, 1e-38))
            y = jax.random.categorical(
                kr, jnp.where(s > 0, res_logits, fb_logits),
                axis=-1).astype(jnp.int32)                # (b,)
            # all gamma accepted -> emit them all (no bonus: the
            # target never processed x_{gamma-1}, same as greedy);
            # else the accepted prefix + the adjustment sample
            n_emit_raw = jnp.where(n_acc == gamma, gamma, n_acc + 1)
            emit_at = lambda i: jnp.where(  # noqa: E731
                i < n_acc, d_mat[:, i], y)
            emit_ok = lambda i: i < n_emit_raw  # noqa: E731
            new_last_live = jnp.where(n_acc == gamma,
                                      d_mat[:, gamma - 1], y)

        n_emit = jnp.where(done, 0, n_emit_raw)
        for i in range(gamma):
            idxw = jnp.minimum(n_out + i, max_new - 1)
            ok = emit_ok(i) & (n_out + i < max_new) & ~done
            old = out[rows, idxw]
            out = out.at[rows, idxw].set(
                jnp.where(ok, emit_at(i), old))
        new_last = jnp.where(done, last_tok, new_last_live)
        n_out = jnp.minimum(n_out + n_emit, max_new)
        pos = jnp.where(done, pos, pos + n_emit)
        return (out, n_out, pos, new_last, tc, dc, rounds + live, key)

    def cond(state):
        _, n_out, _, _, _, _, rounds, _ = state
        # every round emits >= 1 token per unfinished row, so max_new
        # rounds always suffice — the bound makes divergence impossible
        return jnp.any(n_out < max_new) & (rounds < max_new)

    state = (out, n_out, pos, last_tok, t_cache, d_cache,
             jnp.int32(0), key0)
    final = lax.while_loop(cond, round_body, state)
    if return_rounds:
        return final[0], final[6]
    return final[0]
