"""Speculative decoding: a cheap draft proposes, the target verifies.

Serving-side latency lever (net-new; the reference has no model stack
at all, SURVEY.md §5): decode is HBM-bound — every step reads the full
weights for ONE token per row — so a small draft model proposes
``gamma`` tokens autoregressively and the big target model judges all
of them in ONE forward (models.generate.block_decode), reading its
weights once per round instead of once per token. Greedy speculative
decoding is LOSSLESS: the emitted tokens each round are the target's
own argmax predictions ``t_pred[0..j]`` (a draft token is accepted
exactly when it equals the target's prediction, so the accepted prefix
and the bonus token are all target predictions), hence the output
equals plain greedy decode token for token — the parity oracle
tests/test_speculative.py pins.

Numerics caveat on that claim: "the target's prediction" must mean
the SAME floating-point logits plain decode would compute, or a
near-tie argmax can flip between the two paths. On TPU both paths now
route through one kernel family — plain decode_step uses the Pallas
flash-decode kernel at T=1 and the verify block_decode uses the same
kernel at T=gamma (pallas.decode.flash_block_decode), with identical
tile shapes, accumulation order, and dot dtypes per query row — and on
CPU both take the einsum path, so the parity holds by shared numerics
on both backends (pinned on-chip by benchmarks/tpu_parity_check.py —
run on the real TPU, outside the CPU-forced pytest conftest — and by
the CPU oracles in tests/test_speculative.py always).

Cache bookkeeping rides the same masking trick as ragged decode:
rejected drafts leave garbage cache entries BEYOND each row's valid
position, which are never attended (every attend masks at the row's
own position) and are overwritten by later rounds. Per-row acceptance
lengths make the whole loop ragged; positions, cache writes, and
output writes are all per-row. One `lax.while_loop` over rounds (the
trip count is data-dependent — rows finish at different speeds), each
round = gamma draft decode_steps + 1 target block_decode.

Speedup economics: a round emits j+1 in [1, gamma] tokens for the cost
of gamma draft steps + one gamma-wide target forward. With draft cost
c_d (fraction of a target step) and acceptance-driven yield E[j+1],
speedup = E[j+1] / (gamma * c_d + c_verify). benchmarks/spec_bench.py
measures the two cost terms on the chip and the realized yield.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

from rlo_tpu.models.generate import (block_decode, decode_step,
                                     init_kv_cache, prefill)
from rlo_tpu.models.transformer import TransformerConfig


def speculative_generate(params: dict, draft_params: dict, prompt,
                         cfg: TransformerConfig,
                         draft_cfg: TransformerConfig, *,
                         max_new: int, gamma: int = 4,
                         max_len: Optional[int] = None):
    """Greedy speculative continuation of ``prompt`` (b, plen) int32:
    returns (b, max_new) int32 — IDENTICAL to
    ``generate(params, prompt, cfg, max_new=max_new)`` by the
    lossless-acceptance construction; the draft only changes how fast
    the tokens arrive. ``gamma`` = draft tokens proposed per round.
    Both configs must share the vocabulary; the draft is typically a
    much smaller model (fewer layers / narrower).
    """
    if cfg.vocab != draft_cfg.vocab:
        raise ValueError(
            f"draft vocab {draft_cfg.vocab} != target vocab {cfg.vocab}")
    if gamma < 1:
        raise ValueError("gamma >= 1 required")
    b, plen = prompt.shape
    # + gamma slack: the last round's block writes reach at most
    # position plen + max_new - 1 + gamma (garbage tail, never read)
    max_len = max_len or (plen + max_new + gamma)
    if plen + max_new + gamma > max_len:
        raise ValueError(f"max_len {max_len} < plen {plen} + max_new "
                         f"{max_new} + gamma {gamma}")

    t_cache = init_kv_cache(cfg, b, max_len)
    d_cache = init_kv_cache(draft_cfg, b, max_len)
    t_logits, t_cache = prefill(params, prompt, t_cache, cfg)
    _, d_cache = prefill(draft_params, prompt, d_cache, draft_cfg)

    # first token: the target's own prefill prediction. Invariant from
    # here on (per row): out[:n_out] emitted; last_tok = out[n_out-1]
    # sits at sequence position pos-? — precisely, both caches are
    # validly filled through position pos-1 and last_tok has NOT been
    # processed by either model yet; last_tok's position is pos.
    first = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)     # (b,)
    out = jnp.zeros((b, max_new), jnp.int32)
    out = out.at[:, 0].set(first)
    n_out = jnp.ones((b,), jnp.int32)
    pos = jnp.full((b,), plen, jnp.int32)
    last_tok = first
    rows = jnp.arange(b)

    def round_body(state):
        out, n_out, pos, last_tok, t_cache, d_cache, rounds = state
        done = n_out >= max_new

        # --- draft rollout: gamma ragged decode steps ---------------
        cur = last_tok
        dc = d_cache
        d_toks = []
        for i in range(gamma):
            logits, dc = decode_step(draft_params, cur, pos + i, dc,
                                     draft_cfg)
            cur = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            d_toks.append(cur)
        d_mat = jnp.stack(d_toks, axis=1)                  # (b, gamma)

        # --- verify: ONE target forward over [last_tok, d_1..d_{g-1}]
        block = jnp.concatenate([last_tok[:, None],
                                 d_mat[:, :gamma - 1]], axis=1)
        v_logits, tc = block_decode(params, block, pos, t_cache, cfg)
        t_pred = jnp.argmax(v_logits, axis=-1).astype(jnp.int32)

        # --- lossless acceptance ------------------------------------
        acc = (d_mat == t_pred)                            # (b, gamma)
        n_acc = jnp.cumprod(acc, axis=1).sum(axis=1)       # in [0, g]
        j = jnp.minimum(n_acc, gamma - 1)                  # (b,)
        # emitted tokens this round are t_pred[:, :j+1] — the target's
        # own predictions (accepted drafts EQUAL them; the bonus IS
        # one), which is the whole losslessness argument
        n_emit = jnp.where(done, 0, j + 1)
        for i in range(gamma):
            idx = jnp.minimum(n_out + i, max_new - 1)
            ok = (i <= j) & (n_out + i < max_new) & ~done
            old = out[rows, idx]
            out = out.at[rows, idx].set(
                jnp.where(ok, t_pred[:, i], old))
        new_last = jnp.where(done, last_tok, t_pred[rows, j])
        n_out = jnp.minimum(n_out + n_emit, max_new)
        pos = jnp.where(done, pos, pos + n_emit)
        return (out, n_out, pos, new_last, tc, dc, rounds + 1)

    def cond(state):
        _, n_out, _, _, _, _, rounds = state
        # every round emits >= 1 token per unfinished row, so max_new
        # rounds always suffice — the bound makes divergence impossible
        return jnp.any(n_out < max_new) & (rounds < max_new)

    state = (out, n_out, pos, last_tok, t_cache, d_cache,
             jnp.int32(0))
    out = lax.while_loop(cond, round_body, state)[0]
    return out
