"""Continuous batching: a persistent decode loop with slot admission.

The serving shape after ragged prompts (round-5 VERDICT item 6): a
fixed pool of ``n_slots`` batch rows decodes forever; when a row
finishes its request, the slot is re-filled by the next pending request
without restarting the batch — the reference-side analogue is the
engine manager multiplexing independent engines over one progress loop
(/root/reference/rootless_ops.c:33-47: many engines, one
`RLO_make_progress_all`), here it is many REQUESTS multiplexing one
jitted decode program.

TPU-shaped design decisions:
  - The decode program is ONE jit over the whole slot pool — static
    shapes (n_slots, max_len), per-row positions/masks from the ragged
    machinery (models.generate decode_step with a (b,) pos vector), so
    admission never recompiles.
  - Admission granularity is a ROUND of ``round_len`` decode steps
    (one lax.scan inside one jit): the tunneled chip's ~110 ms
    dispatch floor makes per-token host round-trips absurd; round_len
    amortizes it. Iteration-level batching a la Orca.
  - DENSE mode (the original): a fresh request prefills into its slot
    with the blockwise prefill (one forward at a padded prompt bucket),
    then the row's cache is scattered into the pool cache at the slot
    index. Prompts longer than the largest bucket extend past it in
    jitted ``block_decode`` chunks — admission never rejects a prompt
    that fits ``max_len - max_new``.
  - PAGED mode (``paged=True``, docs/DESIGN.md §12): the per-slot
    dense cache becomes a global pool of ``page_size``-token seq-minor
    pages plus a per-slot int32 page table
    (models.paged / serving.pages). Prompts stream through CHUNKED
    prefill (page-aligned ≤ page_size-token forwards interleaved with
    decode rounds — no prompt buckets, no padding waste), shared
    prompt prefixes map the same physical pages copy-on-write through
    a radix trie, and rounds clip to the shortest active budget so
    finished rows never burn slot-steps.
  - Finished rows keep decoding masked garbage until the round ends
    (their budget exhausted); outputs are truncated to the request's
    max_new, and slot reuse is safe because every attend masks at the
    row's own position and cache writes overwrite in order.

Oracle (tests/test_serve.py, tests/test_paged.py): any stream of
requests produces, per request, EXACTLY the tokens of its dense
`generate` — continuous batching, chunked prefill, and page
indirection are scheduling/layout changes, not numerics changes.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from rlo_tpu.models.generate import (block_decode, decode_step,
                                     init_kv_cache, prefill,
                                     _decode_cfg)
from rlo_tpu.models.transformer import TransformerConfig
from rlo_tpu.observe.spans import Stage
from rlo_tpu.utils.metrics import Registry, SERVING, hist_summary


@dataclasses.dataclass
class Request:
    """One generation request: ``prompt`` (plen,) int32, ``max_new``
    tokens to generate. ``eos_id`` optionally ends the row early (the
    emitted tokens still include the eos)."""
    prompt: np.ndarray
    max_new: int
    eos_id: Optional[int] = None


def _bucket(plen: int, buckets: Tuple[int, ...]) -> int:
    for b in buckets:
        if plen <= b:
            return b
    raise ValueError(f"prompt length {plen} exceeds the largest "
                     f"bucket {buckets[-1]}")


class DecodeServer:
    """Continuous-batching server over ``n_slots`` rows.

    submit() queues requests; run() drives rounds until every request
    completes and returns the per-request token arrays in submission
    order. step_round() is the unit the throughput bench times.

    Serving telemetry (docs/DESIGN.md §7) records into ``metrics``
    (default: the process-wide ``metrics.SERVING`` registry, shared
    with ``generate_timed``): TTFT (submit -> first token,
    ``serve.ttft_usec``), admission-queue wait
    (``serve.queue_wait_usec``), per-request end-to-end latency
    (submit -> last token, ``serve.e2e_usec``), per-round and
    per-token decode latency (``serve.round_usec`` /
    ``serve.tok_usec``), batch occupancy per round
    (``serve.occupancy_pct``), request/token counters, and live
    queue-depth gauges. ``stats()`` snapshots it.

    PAGED mode adds the page-pool telemetry (docs/DESIGN.md §12):
    ``serve.pages_in_use`` / ``serve.pages_free`` gauges, prefix-cache
    counters (``serve.prefix_hits``, ``serve.prefix_tokens_shared``,
    ``serve.cow_copies``, ``serve.trie_evictions``), chunked-prefill
    counters (``serve.prefill_chunks``), and
    ``serve.admission_stalls`` (allocator backpressure).

    Paged knobs: ``page_size`` (128 on TPU — one lane block; smaller
    is legal off-TPU for tests), ``n_pages`` (pool size; default fits
    every slot at max_len plus the null page), ``prefill_budget``
    (max prompt tokens prefilled per slot per round — None finishes a
    prompt's prefill in its admission round; a finite budget
    interleaves long prompts' chunks with decode rounds, bounding
    their latency interference), ``prefix_cache`` (the radix trie),
    and ``clip_rounds`` (clip each round to the shortest active
    budget so finished rows never decode garbage; defaults on in
    paged mode, the dense scheduler is left byte-for-byte alone).
    """

    def __init__(self, params, cfg: TransformerConfig, *,
                 n_slots: int, max_len: int, round_len: int = 32,
                 prompt_buckets: Tuple[int, ...] = (64, 256, 1024),
                 metrics: Optional[Registry] = None,
                 # rlo-prover: lane-pinned (one 128-lane cache block)
                 paged: bool = False, page_size: int = 128,
                 n_pages: Optional[int] = None,
                 prefill_budget: Optional[int] = None,
                 prefix_cache: bool = True,
                 clip_rounds: Optional[bool] = None):
        self.metrics = SERVING if metrics is None else metrics
        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len
        self.round_len = round_len
        self.paged = paged
        self.pos = np.zeros((n_slots,), np.int32)
        self.last_tok = np.zeros((n_slots,), np.int32)
        self.budget = np.zeros((n_slots,), np.int64)  # tokens still due
        self.req_of_slot: List[Optional[int]] = [None] * n_slots
        self._queue: List[Tuple[int, Request]] = []
        self._out: List[Optional[List[int]]] = []
        self._eos: List[Optional[int]] = []
        self._submit_ts: dict = {}  # rid -> submit time (perf_counter)
        # rid -> submit time, RETAINED until completion (the e2e
        # latency stamp; _submit_ts is popped at admission for the
        # queue-wait/TTFT numbers)
        self._accept_ts: dict = {}
        self._canceled: set = set()
        # newly completed (rid, tokens) pairs awaiting poll_completed()
        # — the serving fabric's incremental face (docs/DESIGN.md §11)
        self._completed_log: List[Tuple[int, np.ndarray]] = []
        self.rounds_run = 0
        self.steps_run = 0
        # optional rlo-trace hooks (docs/DESIGN.md §19): a SpanRecorder
        # plus a server-rid -> fabric-rid resolver, attached by
        # ModelBackend when the owning fabric traces. None => the
        # scheduler runs zero span code (one is-None test per chunk).
        self.spans = None
        self.span_rid_of = None

        cfg_d = _decode_cfg(cfg)
        if paged:
            self._init_paged(cfg_d, page_size, n_pages,
                             prefill_budget, prefix_cache,
                             True if clip_rounds is None
                             else clip_rounds)
            return
        self.clip_rounds = bool(clip_rounds)
        self.buckets = tuple(b for b in sorted(prompt_buckets)
                             if b <= max_len)
        if not self.buckets:
            raise ValueError(
                f"no prompt bucket fits max_len {max_len} "
                f"(buckets {tuple(sorted(prompt_buckets))})")
        self.cache = init_kv_cache(cfg, n_slots, max_len)

        def round_fn(params, cache, last_tok, pos, kk):
            def body(carry, _):
                tok, pos, cache = carry
                logits, cache = decode_step(params, tok, pos, cache,
                                            cfg_d)
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (tok, pos + 1, cache), tok

            (tok, pos, cache), toks = lax.scan(
                body, (last_tok, pos, cache), None, length=kk)
            return tok, pos, cache, jnp.transpose(toks)  # (b, kk)

        # donate the pool cache: without aliasing, every round would
        # double-buffer the full n_slots x max_len cache in HBM
        self._round = jax.jit(round_fn, static_argnames=("kk",),
                              donate_argnums=(1,))

        def prefill_slot(params, prompt, length):
            # one padded row through the blockwise prefill; returns the
            # row cache + the first generated token
            row = init_kv_cache(cfg, 1, max_len)
            logits, row = prefill(params, prompt, row, cfg,
                                  last_index=length - 1)
            first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return row, first

        self._prefill = jax.jit(prefill_slot)

        # long prompts (plen > the largest bucket) extend the
        # bucket-prefilled row cache through jitted block_decode
        # chunks — the chunked-prefill unit on the dense path, so
        # admission never rejects a prompt that fits max_len - max_new
        self._chunk_w = min(128, self.buckets[-1])

        def extend_chunk(params, row, toks, pos0, n_valid):
            logits, row = block_decode(params, toks,
                                       pos0[None], row, cfg)
            idx = jnp.clip(n_valid - 1, 0,
                           toks.shape[1] - 1)[None, None, None]
            xl = jnp.take_along_axis(
                logits, jnp.broadcast_to(
                    idx, (1, 1, logits.shape[-1])), axis=1)[:, 0]
            first = jnp.argmax(xl, axis=-1).astype(jnp.int32)
            return first, row

        self._extend = jax.jit(extend_chunk, donate_argnums=(1,))

        def scatter_slot(cache, row, slot):
            def put(big, small):
                return lax.dynamic_update_slice(
                    big, small.astype(big.dtype),
                    (slot,) + (0,) * (big.ndim - 1))
            return jax.tree.map(put, cache, row)

        self._scatter = jax.jit(scatter_slot, donate_argnums=(0,))

    # ---- paged mode (docs/DESIGN.md §12) -----------------------------
    def _init_paged(self, cfg_d, page_size, n_pages, prefill_budget,
                    prefix_cache, clip_rounds):
        from rlo_tpu.models.paged import (copy_page, init_page_pool,
                                          paged_decode_step,
                                          paged_prefill_chunk)
        from rlo_tpu.serving.pages import PageAllocator, PrefixTrie
        if jax.default_backend() == "tpu" and page_size % 128:
            raise ValueError(
                f"TPU pages must be 128-lane multiples, got "
                f"{page_size}")
        self.page_size = page_size
        self.max_pages = -(-self.max_len // page_size)
        if n_pages is None:
            n_pages = self.n_slots * self.max_pages + 1
        self.n_pages = n_pages
        self.clip_rounds = clip_rounds
        self.prefill_budget = prefill_budget
        self.pools = init_page_pool(self.cfg, n_pages, page_size)
        self.allocator = PageAllocator(n_pages, page_size)
        self.trie = PrefixTrie(page_size) if prefix_cache else None
        self.table = np.zeros((self.n_slots, self.max_pages), np.int32)
        self.active = np.zeros((self.n_slots,), bool)
        #: pages owned (one reference each) per slot, table order
        self._slot_pages: List[List[int]] = \
            [[] for _ in range(self.n_slots)]
        #: slot -> in-flight chunked prefill state
        self._prefilling: Dict[int, dict] = {}

        def round_fn(params, pools, table, last_tok, pos, active, kk):
            def body(carry, _):
                tok, pos, pools = carry
                logits, pools = paged_decode_step(
                    params, tok, pos, pools, table, active, cfg_d)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                tok = jnp.where(active, nxt, tok)
                pos = pos + active.astype(pos.dtype)
                return (tok, pos, pools), tok

            (tok, pos, pools), toks = lax.scan(
                body, (last_tok, pos, pools), None, length=kk)
            return tok, pos, pools, jnp.transpose(toks)  # (b, kk)

        self._round_paged = jax.jit(round_fn, static_argnames=("kk",),
                                    donate_argnums=(1,))

        def chunk_fn(params, pools, table_row, toks, pos0, n_valid):
            return paged_prefill_chunk(params, toks, pos0, n_valid,
                                       pools, table_row, self.cfg)

        self._chunk = jax.jit(chunk_fn, donate_argnums=(1,))
        self._copy = jax.jit(copy_page, donate_argnums=(0,))

    # ---- request lifecycle ------------------------------------------
    def submit(self, prompt, max_new: int,
               eos_id: Optional[int] = None) -> int:
        """Queue a request; returns its id (position in results).
        Any prompt with plen + max_new <= max_len is admissible (long
        prompts stream through chunked prefill); only truly oversized
        requests are rejected."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) == 0:
            # an empty prompt has no last token to take logits from —
            # the paged prefill would wedge at next=-1 and the dense
            # prefill would index position -1; reject it cleanly
            raise ValueError("empty prompt")
        if len(prompt) + max_new > self.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new} exceeds "
                f"max_len {self.max_len}")
        if self.paged:
            need = -(-(len(prompt) + max_new) // self.page_size)
            if need > self.n_pages - 1:
                raise ValueError(
                    f"request spans {need} pages but the pool holds "
                    f"only {self.n_pages - 1} allocatable pages")
        rid = len(self._out)
        self._queue.append((rid, Request(prompt, max_new, eos_id)))
        self._out.append(None)
        self._eos.append(eos_id)
        now = time.perf_counter()
        self._submit_ts[rid] = now
        self._accept_ts[rid] = now
        self.metrics.counter("serve.requests_submitted").inc()
        self.metrics.gauge("serve.queue_depth").set(len(self._queue))
        return rid

    def _admit(self) -> int:
        """Fill every free slot from the queue; returns the number of
        requests that COMPLETED during admission (max_new=1 or an
        immediate eos retires the slot at once — the freed slot is
        re-offered to the queue in the same pass, and the completion
        count keeps step_round truthful about progress)."""
        if self.paged:
            return self._admit_paged()
        completed = 0
        slot = 0
        while slot < self.n_slots:
            if self.req_of_slot[slot] is not None or not self._queue:
                slot += 1
                continue
            rid, req = self._queue.pop(0)
            t_sub = self._submit_ts.pop(rid, None)
            now = time.perf_counter()
            if t_sub is not None:
                self.metrics.histogram("serve.queue_wait_usec").observe(
                    (now - t_sub) * 1e6)
            plen = len(req.prompt)
            head = min(plen, self.buckets[-1])
            bucket = _bucket(head, self.buckets)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :head] = req.prompt[:head]
            row, first = self._prefill(
                self.params, jnp.asarray(padded),
                jnp.asarray([head], jnp.int32))
            # long prompt: extend the row past the bucket in jitted
            # block_decode chunks (write-then-attend; the final
            # chunk's last-position logits seed the first token)
            off = head
            while off < plen:
                n = min(self._chunk_w, plen - off)
                toks = np.zeros((1, self._chunk_w), np.int32)
                toks[0, :n] = req.prompt[off:off + n]
                first, row = self._extend(
                    self.params, row, jnp.asarray(toks),
                    jnp.int32(off), jnp.int32(n))
                off += n
            self.cache = self._scatter(self.cache, row,
                                       jnp.int32(slot))
            first = int(np.asarray(first).reshape(-1)[0])
            if t_sub is not None:
                # first token is materialized on the host here: TTFT
                # = submit -> first token (queue wait included)
                self.metrics.histogram("serve.ttft_usec").observe(
                    (time.perf_counter() - t_sub) * 1e6)
            self.metrics.counter("serve.tokens_out").inc()
            self.metrics.gauge("serve.queue_depth").set(len(self._queue))
            self.req_of_slot[slot] = rid
            self._out[rid] = [first]
            self.pos[slot] = plen
            self.last_tok[slot] = first
            self.budget[slot] = req.max_new - 1
            if req.eos_id is not None and first == req.eos_id:
                self.budget[slot] = 0
            self._retire_if_done(slot)
            if self.req_of_slot[slot] is None:
                completed += 1  # retired at admission: re-offer slot
            else:
                slot += 1
        return completed

    # ---- paged admission / chunked prefill ---------------------------
    def _try_map(self, slot: int, req: Request) -> bool:
        """Reserve and map every page the request will ever touch
        (positions 0..plen+max_new-1) into the slot's table row:
        trie-shared leading pages are retained in place, the one
        shared page the request must write into is copied-on-write,
        the rest come fresh off the free list. All-at-admission
        reservation means a mapped request can never stall mid-decode
        on an empty pool — backpressure is an admission-time-only
        phenomenon. Returns False (nothing mapped) when the pool
        cannot cover it even after trie eviction."""
        ps = self.page_size
        plen = len(req.prompt)
        need_pages = -(-(plen + req.max_new) // ps)
        shared: List[int] = []
        covered = 0
        if self.trie is not None:
            shared, covered = self.trie.match(req.prompt)
        # always recompute at least the last prompt token (the first
        # generated token needs its logits; the cache alone has none)
        prefill_from = min(covered, plen - 1)
        n_keep = min(len(shared), prefill_from // ps)
        n_cow = len(shared) - n_keep      # 0 or 1 by construction
        n_new = need_pages - n_keep       # COW copies + fresh pages
        # pin every matched page across the eviction call: un-retained
        # refcount-1 trie pages are exactly what evict() frees
        for p in shared:
            self.allocator.retain(p)
        if not self.allocator.can_alloc(n_new):
            if self.trie is not None:
                ev = self.trie.evict(
                    self.allocator,
                    n_new - self.allocator.free_pages)
                if ev:
                    self.metrics.counter(
                        "serve.trie_evictions").inc(ev)
            if not self.allocator.can_alloc(n_new):
                for p in shared:
                    self.allocator.release(p)
                return False
        pages: List[int] = list(shared[:n_keep])
        for src in shared[n_keep:]:
            dst = self.allocator.alloc()
            self.pools = self._copy(self.pools, jnp.int32(src),
                                    jnp.int32(dst))
            self.allocator.release(src)   # drop the COW pin
            pages.append(dst)
            self.metrics.counter("serve.cow_copies").inc()
        for _ in range(need_pages - len(pages)):
            pages.append(self.allocator.alloc())
        self.table[slot, :] = 0
        self.table[slot, :need_pages] = pages
        self._slot_pages[slot] = pages
        if covered > 0:
            self.metrics.counter("serve.prefix_hits").inc()
            self.metrics.counter("serve.prefix_tokens_shared").inc(
                prefill_from)
        self._prefilling[slot] = {
            "req": req, "next": prefill_from, "plen": plen}
        return True

    def _release_slot_pages(self, slot: int) -> None:
        for p in self._slot_pages[slot]:
            self.allocator.release(p)
        self._slot_pages[slot] = []
        self.table[slot, :] = 0
        self.active[slot] = False
        self._prefilling.pop(slot, None)

    def _admit_paged(self) -> int:
        """Paged admission + the chunked-prefill tick. Head-of-line
        FIFO: when the queue head cannot reserve its pages the whole
        admission stalls (deterministic backpressure — decode rounds
        keep draining, retirements free pages, the head admits next
        round)."""
        for slot in range(self.n_slots):
            if self.req_of_slot[slot] is not None or not self._queue:
                continue
            rid, req = self._queue[0]
            if not self._try_map(slot, req):
                self.metrics.counter("serve.admission_stalls").inc()
                break
            self._queue.pop(0)
            t_sub = self._submit_ts.pop(rid, None)
            if t_sub is not None:
                self.metrics.histogram(
                    "serve.queue_wait_usec").observe(
                    (time.perf_counter() - t_sub) * 1e6)
            self.metrics.gauge("serve.queue_depth").set(
                len(self._queue))
            self.req_of_slot[slot] = rid
            self._out[rid] = []
        completed, _ = self._prefill_tick()
        self._page_gauges()
        return completed

    def _prefill_tick(self) -> Tuple[int, bool]:
        """Advance every prefilling slot by up to ``prefill_budget``
        prompt tokens (None = finish it now) in page-aligned chunks.
        Returns (requests completed at prefill time, any progress)."""
        completed = 0
        progressed = False
        ps = self.page_size
        for slot in list(self._prefilling):
            st = self._prefilling[slot]
            req, plen = st["req"], st["plen"]
            budget = (plen if self.prefill_budget is None
                      else self.prefill_budget)
            logits = None
            while st["next"] < plen and budget > 0:
                a = st["next"]
                end = min(plen, (a // ps + 1) * ps, a + budget)
                n = end - a
                toks = np.zeros((1, ps), np.int32)
                toks[0, :n] = req.prompt[a:end]
                t_chunk = (time.perf_counter()
                           if self.spans is not None else 0.0)
                logits, self.pools = self._chunk(
                    self.params, self.pools,
                    jnp.asarray(self.table[slot:slot + 1]),
                    jnp.asarray(toks), jnp.int32(a), jnp.int32(n))
                st["next"] = end
                budget -= n
                progressed = True
                self.metrics.counter("serve.prefill_chunks").inc()
                if self.spans is not None:
                    self._span(self.req_of_slot[slot],
                               Stage.PREFILL_CHUNK, t_chunk,
                               time.perf_counter())
            if st["next"] < plen:
                continue  # budget spent; more chunks next round
            # prefill complete: seed the first token, open decoding
            first = int(np.asarray(
                jnp.argmax(logits, axis=-1)).reshape(-1)[0])
            rid = self.req_of_slot[slot]
            t_sub = self._accept_ts.get(rid)
            if t_sub is not None:
                self.metrics.histogram("serve.ttft_usec").observe(
                    (time.perf_counter() - t_sub) * 1e6)
            self.metrics.counter("serve.tokens_out").inc()
            self._out[rid] = [first]
            self.pos[slot] = plen
            self.last_tok[slot] = first
            self.budget[slot] = req.max_new - 1
            if req.eos_id is not None and first == req.eos_id:
                self.budget[slot] = 0
            self.active[slot] = True
            del self._prefilling[slot]
            if self.trie is not None:
                self.trie.register(req.prompt, plen,
                                   self.table[slot], self.allocator)
            self._retire_if_done(slot)
            if self.req_of_slot[slot] is None:
                completed += 1
        return completed, progressed

    def _page_gauges(self) -> None:
        self.metrics.gauge("serve.pages_in_use").set(
            self.allocator.pages_in_use)
        self.metrics.gauge("serve.pages_free").set(
            self.allocator.free_pages)

    def _span(self, rid: Optional[int], stage: int, t0: float,
              t1: float) -> None:
        """Emit a scheduler-stage span for server rid ``rid`` when the
        fabric attached a recorder AND the fabric-level request is
        sampled. Off the traced path this method is never called."""
        if rid is None or self.span_rid_of is None:
            return
        frid = self.span_rid_of(rid)
        if frid is not None and self.spans.sampled(frid):
            self.spans.emit(frid, stage, t0, t1)

    def _retire_if_done(self, slot: int):
        rid = self.req_of_slot[slot]
        if rid is None:
            return
        if self.budget[slot] <= 0:
            self.req_of_slot[slot] = None
            if self.paged:
                self._release_slot_pages(slot)
            self.metrics.counter("serve.requests_completed").inc()
            self._completed_log.append(
                (rid, np.asarray(self._out[rid], np.int32)))
            t_sub = self._accept_ts.pop(rid, None)
            if t_sub is not None:
                # end-to-end latency: submit -> last token, queue wait
                # and every decode round included (the fail-over-aware
                # fleet twin is fabric.e2e_usec, docs/DESIGN.md §11)
                self.metrics.histogram("serve.e2e_usec").observe(
                    (time.perf_counter() - t_sub) * 1e6)

    # ---- fabric-facing hooks (docs/DESIGN.md §11) --------------------
    def poll_completed(self) -> List[Tuple[int, np.ndarray]]:
        """Drain the (rid, tokens) pairs completed since the last
        poll — the incremental completion face the serving fabric
        consumes round by round (``run()`` remains the drive-to-empty
        batch face)."""
        out, self._completed_log = self._completed_log, []
        return out

    def cancel(self, rid: int) -> bool:
        """Withdraw a request: de-queue it, or free its slot mid-
        decode (the fabric's re-queue/ownership-move hook). Returns
        False when the rid is unknown or already completed. A canceled
        request's ``run()`` output is its partial prefix — the caller
        owns whatever exactly-once story spans the re-queue (the
        fabric dedups by its own request id)."""
        if not 0 <= rid < len(self._out) or rid in self._canceled:
            return False
        for i, (qrid, _) in enumerate(self._queue):
            if qrid == rid:
                del self._queue[i]
                self._canceled.add(rid)
                self._submit_ts.pop(rid, None)
                self._accept_ts.pop(rid, None)
                self.metrics.counter("serve.requests_canceled").inc()
                self.metrics.gauge("serve.queue_depth").set(
                    len(self._queue))
                return True
        for slot in range(self.n_slots):
            if self.req_of_slot[slot] == rid:
                self.req_of_slot[slot] = None
                self.budget[slot] = 0
                if self.paged:
                    self._release_slot_pages(slot)
                self._canceled.add(rid)
                self._accept_ts.pop(rid, None)
                self.metrics.counter("serve.requests_canceled").inc()
                return True
        return False

    def has_work(self) -> bool:
        """Queued or in-flight requests remain."""
        return bool(self._queue) or any(
            r is not None for r in self.req_of_slot)

    def free_slots(self) -> int:
        return sum(1 for r in self.req_of_slot if r is None)

    def queue_depth(self) -> int:
        return len(self._queue)

    def slot_ownership(self) -> Tuple[Optional[int], ...]:
        """Which rid occupies each slot (None = free) — the
        slot-ownership view the fabric's placement records reason
        about."""
        return tuple(self.req_of_slot)

    # ---- the decode loop --------------------------------------------
    def step_round(self):
        """Admit pending requests, run one jitted round of ragged
        decode steps (``round_len`` of them; paged mode clips the
        round to the shortest active budget), distribute tokens."""
        if self.paged:
            return self._step_round_paged()
        completed = self._admit()
        if all(r is None for r in self.req_of_slot):
            return completed > 0
        active = sum(1 for r in self.req_of_slot if r is not None)
        kk = self.round_len
        if self.clip_rounds:
            kk = max(1, min(kk, int(min(
                self.budget[s] for s in range(self.n_slots)
                if self.req_of_slot[s] is not None))))
        t0 = time.perf_counter()
        tok, pos, cache, toks = self._round(
            self.params, self.cache, jnp.asarray(self.last_tok),
            jnp.asarray(self.pos), kk)
        self.cache = cache
        toks = np.asarray(toks)
        self.last_tok = np.asarray(tok).copy()
        self.pos = np.asarray(pos).copy()
        dt = time.perf_counter() - t0  # toks materialized: round done
        self._observe_round(dt, kk, active)
        self._distribute(toks, kk)
        return True

    def _step_round_paged(self):
        """The paged round: admission + chunked-prefill tick, then a
        budget-clipped decode round over the active slots, then token
        distribution and page release."""
        completed = self._admit()
        if not self.active.any():
            return completed > 0 or bool(self._prefilling)
        active_slots = [s for s in range(self.n_slots)
                        if self.active[s]]
        kk = self.round_len
        if self.clip_rounds:
            kk = max(1, min(kk, int(min(self.budget[s]
                                        for s in active_slots))))
        t0 = time.perf_counter()
        tok, pos, pools, toks = self._round_paged(
            self.params, self.pools, jnp.asarray(self.table),
            jnp.asarray(self.last_tok), jnp.asarray(self.pos),
            jnp.asarray(self.active), kk)
        self.pools = pools
        toks = np.asarray(toks)
        self.last_tok = np.asarray(tok).copy()
        self.pos = np.asarray(pos).copy()
        dt = time.perf_counter() - t0
        self._observe_round(dt, kk, len(active_slots))
        self._distribute(toks, kk, only_active=True)
        self._page_gauges()
        return True

    def _observe_round(self, dt: float, kk: int, active: int) -> None:
        self.metrics.histogram("serve.round_usec").observe(dt * 1e6)
        self.metrics.histogram("serve.tok_usec").observe(
            dt * 1e6 / kk)
        self.metrics.histogram("serve.occupancy_pct").observe(
            100.0 * active / self.n_slots)
        self.metrics.counter("serve.rounds").inc()
        self.metrics.counter("serve.steps").inc(kk)
        self.rounds_run += 1
        self.steps_run += kk

    def _distribute(self, toks, kk: int,
                    only_active: bool = False) -> None:
        tokens_out = self.metrics.counter("serve.tokens_out")
        for slot in range(self.n_slots):
            rid = self.req_of_slot[slot]
            if rid is None:
                continue
            if only_active and not self.active[slot]:
                continue  # mid-prefill: nothing decoded this round
            take = int(min(self.budget[slot], kk))
            seq = toks[slot, :take].tolist()
            eos = self._eos[rid]
            if eos is not None and eos in seq:
                seq = seq[:seq.index(eos) + 1]
                self.budget[slot] = 0
            else:
                self.budget[slot] -= take
            self._out[rid].extend(seq)
            tokens_out.inc(len(seq))
            self._retire_if_done(slot)

    def run(self) -> List[np.ndarray]:
        """Drive rounds until every submitted request completes."""
        while self._queue or any(r is not None
                                 for r in self.req_of_slot):
            progressed = self.step_round()
            if not progressed and self._queue:  # pragma: no cover
                raise RuntimeError("queue stuck with no free slots")
        # a request canceled before admission never produced tokens
        return [np.asarray(o if o is not None else [], np.int32)
                for o in self._out]

    def stats(self) -> dict:
        """Serving-telemetry snapshot: counters and gauges verbatim,
        histograms as percentile SUMMARIES (count/mean/min/max +
        p50/p90/p99 estimated from the log2 buckets,
        metrics.hist_summary) — dashboards read quantiles, not raw
        28-bucket dumps. The bucket layout stays available through
        ``self.metrics.snapshot()`` for anyone who wants it. Paged
        servers add the allocator's own counters under ``pages``."""
        snap = self.metrics.snapshot()
        snap["histograms"] = {k: hist_summary(h)
                              for k, h in snap["histograms"].items()}
        if self.paged:
            snap["pages"] = self.allocator.stats()
            if self.trie is not None:
                snap["pages"]["trie_entries"] = self.trie.entries
        return snap
