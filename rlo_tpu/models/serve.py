"""Continuous batching: a persistent decode loop with slot admission.

The serving shape after ragged prompts (round-5 VERDICT item 6): a
fixed pool of ``n_slots`` batch rows decodes forever; when a row
finishes its request, the slot is re-filled by the next pending request
without restarting the batch — the reference-side analogue is the
engine manager multiplexing independent engines over one progress loop
(/root/reference/rootless_ops.c:33-47: many engines, one
`RLO_make_progress_all`), here it is many REQUESTS multiplexing one
jitted decode program.

TPU-shaped design decisions:
  - The decode program is ONE jit over the whole slot pool — static
    shapes (n_slots, max_len), per-row positions/masks from the ragged
    machinery (models.generate decode_step with a (b,) pos vector), so
    admission never recompiles.
  - Admission granularity is a ROUND of ``round_len`` decode steps
    (one lax.scan inside one jit): the tunneled chip's ~110 ms
    dispatch floor makes per-token host round-trips absurd; round_len
    amortizes it. Iteration-level batching a la Orca.
  - A fresh request prefills into its slot with the blockwise prefill
    (one forward at a padded prompt bucket — a handful of distinct
    bucket lengths keeps the compile cache small), then the row's
    cache is scattered into the pool cache at the slot index.
  - Finished rows keep decoding masked garbage until the round ends
    (their budget exhausted); outputs are truncated to the request's
    max_new, and slot reuse is safe because every attend masks at the
    row's own position and cache writes overwrite in order.

Oracle (tests/test_serve.py): any stream of requests produces, per
request, EXACTLY the tokens of its dense `generate` — continuous
batching is a scheduling change, not a numerics change.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from rlo_tpu.models.generate import (decode_step, init_kv_cache,
                                     prefill, _decode_cfg)
from rlo_tpu.models.transformer import TransformerConfig
from rlo_tpu.utils.metrics import Registry, SERVING, hist_summary


@dataclasses.dataclass
class Request:
    """One generation request: ``prompt`` (plen,) int32, ``max_new``
    tokens to generate. ``eos_id`` optionally ends the row early (the
    emitted tokens still include the eos)."""
    prompt: np.ndarray
    max_new: int
    eos_id: Optional[int] = None


def _bucket(plen: int, buckets: Tuple[int, ...]) -> int:
    for b in buckets:
        if plen <= b:
            return b
    raise ValueError(f"prompt length {plen} exceeds the largest "
                     f"bucket {buckets[-1]}")


class DecodeServer:
    """Continuous-batching server over ``n_slots`` rows.

    submit() queues requests; run() drives rounds until every request
    completes and returns the per-request token arrays in submission
    order. step_round() is the unit the throughput bench times.

    Serving telemetry (docs/DESIGN.md §7) records into ``metrics``
    (default: the process-wide ``metrics.SERVING`` registry, shared
    with ``generate_timed``): TTFT (submit -> first token,
    ``serve.ttft_usec``), admission-queue wait
    (``serve.queue_wait_usec``), per-request end-to-end latency
    (submit -> last token, ``serve.e2e_usec``), per-round and
    per-token decode latency (``serve.round_usec`` /
    ``serve.tok_usec``), batch occupancy per round
    (``serve.occupancy_pct``), request/token counters, and live
    queue-depth gauges. ``stats()`` snapshots it.
    """

    def __init__(self, params, cfg: TransformerConfig, *,
                 n_slots: int, max_len: int, round_len: int = 32,
                 prompt_buckets: Tuple[int, ...] = (64, 256, 1024),
                 metrics: Optional[Registry] = None):
        self.metrics = SERVING if metrics is None else metrics
        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len
        self.round_len = round_len
        self.buckets = tuple(b for b in sorted(prompt_buckets)
                             if b <= max_len)
        if not self.buckets:
            raise ValueError(
                f"no prompt bucket fits max_len {max_len} "
                f"(buckets {tuple(sorted(prompt_buckets))})")
        self.cache = init_kv_cache(cfg, n_slots, max_len)
        self.pos = np.zeros((n_slots,), np.int32)
        self.last_tok = np.zeros((n_slots,), np.int32)
        self.budget = np.zeros((n_slots,), np.int64)  # tokens still due
        self.req_of_slot: List[Optional[int]] = [None] * n_slots
        self._queue: List[Tuple[int, Request]] = []
        self._out: List[Optional[List[int]]] = []
        self._eos: List[Optional[int]] = []
        self._submit_ts: dict = {}  # rid -> submit time (perf_counter)
        # rid -> submit time, RETAINED until completion (the e2e
        # latency stamp; _submit_ts is popped at admission for the
        # queue-wait/TTFT numbers)
        self._accept_ts: dict = {}
        self._canceled: set = set()
        # newly completed (rid, tokens) pairs awaiting poll_completed()
        # — the serving fabric's incremental face (docs/DESIGN.md §11)
        self._completed_log: List[Tuple[int, np.ndarray]] = []
        self.rounds_run = 0
        self.steps_run = 0

        cfg_d = _decode_cfg(cfg)

        def round_fn(params, cache, last_tok, pos, kk):
            def body(carry, _):
                tok, pos, cache = carry
                logits, cache = decode_step(params, tok, pos, cache,
                                            cfg_d)
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (tok, pos + 1, cache), tok

            (tok, pos, cache), toks = lax.scan(
                body, (last_tok, pos, cache), None, length=kk)
            return tok, pos, cache, jnp.transpose(toks)  # (b, kk)

        # donate the pool cache: without aliasing, every round would
        # double-buffer the full n_slots x max_len cache in HBM
        self._round = jax.jit(round_fn, static_argnames=("kk",),
                              donate_argnums=(1,))

        def prefill_slot(params, prompt, length):
            # one padded row through the blockwise prefill; returns the
            # row cache + the first generated token
            row = init_kv_cache(cfg, 1, max_len)
            logits, row = prefill(params, prompt, row, cfg,
                                  last_index=length - 1)
            first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return row, first

        self._prefill = jax.jit(prefill_slot)

        def scatter_slot(cache, row, slot):
            def put(big, small):
                return lax.dynamic_update_slice(
                    big, small.astype(big.dtype),
                    (slot,) + (0,) * (big.ndim - 1))
            return jax.tree.map(put, cache, row)

        self._scatter = jax.jit(scatter_slot, donate_argnums=(0,))

    # ---- request lifecycle ------------------------------------------
    def submit(self, prompt, max_new: int,
               eos_id: Optional[int] = None) -> int:
        """Queue a request; returns its id (position in results)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) + max_new > self.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new} exceeds "
                f"max_len {self.max_len}")
        if len(prompt) > self.buckets[-1]:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the largest "
                f"prompt bucket {self.buckets[-1]}")
        rid = len(self._out)
        self._queue.append((rid, Request(prompt, max_new, eos_id)))
        self._out.append(None)
        self._eos.append(eos_id)
        now = time.perf_counter()
        self._submit_ts[rid] = now
        self._accept_ts[rid] = now
        self.metrics.counter("serve.requests_submitted").inc()
        self.metrics.gauge("serve.queue_depth").set(len(self._queue))
        return rid

    def _admit(self) -> int:
        """Fill every free slot from the queue; returns the number of
        requests that COMPLETED during admission (max_new=1 or an
        immediate eos retires the slot at once — the freed slot is
        re-offered to the queue in the same pass, and the completion
        count keeps step_round truthful about progress)."""
        completed = 0
        slot = 0
        while slot < self.n_slots:
            if self.req_of_slot[slot] is not None or not self._queue:
                slot += 1
                continue
            rid, req = self._queue.pop(0)
            t_sub = self._submit_ts.pop(rid, None)
            now = time.perf_counter()
            if t_sub is not None:
                self.metrics.histogram("serve.queue_wait_usec").observe(
                    (now - t_sub) * 1e6)
            plen = len(req.prompt)
            bucket = _bucket(plen, self.buckets)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :plen] = req.prompt
            row, first = self._prefill(
                self.params, jnp.asarray(padded),
                jnp.asarray([plen], jnp.int32))
            self.cache = self._scatter(self.cache, row,
                                       jnp.int32(slot))
            first = int(np.asarray(first)[0])
            if t_sub is not None:
                # first token is materialized on the host here: TTFT
                # = submit -> first token (queue wait included)
                self.metrics.histogram("serve.ttft_usec").observe(
                    (time.perf_counter() - t_sub) * 1e6)
            self.metrics.counter("serve.tokens_out").inc()
            self.metrics.gauge("serve.queue_depth").set(len(self._queue))
            self.req_of_slot[slot] = rid
            self._out[rid] = [first]
            self.pos[slot] = plen
            self.last_tok[slot] = first
            self.budget[slot] = req.max_new - 1
            if req.eos_id is not None and first == req.eos_id:
                self.budget[slot] = 0
            self._retire_if_done(slot)
            if self.req_of_slot[slot] is None:
                completed += 1  # retired at admission: re-offer slot
            else:
                slot += 1
        return completed

    def _retire_if_done(self, slot: int):
        rid = self.req_of_slot[slot]
        if rid is None:
            return
        if self.budget[slot] <= 0:
            self.req_of_slot[slot] = None
            self.metrics.counter("serve.requests_completed").inc()
            self._completed_log.append(
                (rid, np.asarray(self._out[rid], np.int32)))
            t_sub = self._accept_ts.pop(rid, None)
            if t_sub is not None:
                # end-to-end latency: submit -> last token, queue wait
                # and every decode round included (the fail-over-aware
                # fleet twin is fabric.e2e_usec, docs/DESIGN.md §11)
                self.metrics.histogram("serve.e2e_usec").observe(
                    (time.perf_counter() - t_sub) * 1e6)

    # ---- fabric-facing hooks (docs/DESIGN.md §11) --------------------
    def poll_completed(self) -> List[Tuple[int, np.ndarray]]:
        """Drain the (rid, tokens) pairs completed since the last
        poll — the incremental completion face the serving fabric
        consumes round by round (``run()`` remains the drive-to-empty
        batch face)."""
        out, self._completed_log = self._completed_log, []
        return out

    def cancel(self, rid: int) -> bool:
        """Withdraw a request: de-queue it, or free its slot mid-
        decode (the fabric's re-queue/ownership-move hook). Returns
        False when the rid is unknown or already completed. A canceled
        request's ``run()`` output is its partial prefix — the caller
        owns whatever exactly-once story spans the re-queue (the
        fabric dedups by its own request id)."""
        if not 0 <= rid < len(self._out) or rid in self._canceled:
            return False
        for i, (qrid, _) in enumerate(self._queue):
            if qrid == rid:
                del self._queue[i]
                self._canceled.add(rid)
                self._submit_ts.pop(rid, None)
                self._accept_ts.pop(rid, None)
                self.metrics.counter("serve.requests_canceled").inc()
                self.metrics.gauge("serve.queue_depth").set(
                    len(self._queue))
                return True
        for slot in range(self.n_slots):
            if self.req_of_slot[slot] == rid:
                self.req_of_slot[slot] = None
                self.budget[slot] = 0
                self._canceled.add(rid)
                self._accept_ts.pop(rid, None)
                self.metrics.counter("serve.requests_canceled").inc()
                return True
        return False

    def has_work(self) -> bool:
        """Queued or in-flight requests remain."""
        return bool(self._queue) or any(
            r is not None for r in self.req_of_slot)

    def free_slots(self) -> int:
        return sum(1 for r in self.req_of_slot if r is None)

    def queue_depth(self) -> int:
        return len(self._queue)

    def slot_ownership(self) -> Tuple[Optional[int], ...]:
        """Which rid occupies each slot (None = free) — the
        slot-ownership view the fabric's placement records reason
        about."""
        return tuple(self.req_of_slot)

    # ---- the decode loop --------------------------------------------
    def step_round(self):
        """Admit pending requests, run one jitted round of
        ``round_len`` ragged decode steps, distribute tokens."""
        completed = self._admit()
        if all(r is None for r in self.req_of_slot):
            return completed > 0
        active = sum(1 for r in self.req_of_slot if r is not None)
        t0 = time.perf_counter()
        tok, pos, cache, toks = self._round(
            self.params, self.cache, jnp.asarray(self.last_tok),
            jnp.asarray(self.pos), self.round_len)
        self.cache = cache
        toks = np.asarray(toks)
        self.last_tok = np.asarray(tok).copy()
        self.pos = np.asarray(pos).copy()
        dt = time.perf_counter() - t0  # toks materialized: round done
        self.metrics.histogram("serve.round_usec").observe(dt * 1e6)
        self.metrics.histogram("serve.tok_usec").observe(
            dt * 1e6 / self.round_len)
        self.metrics.histogram("serve.occupancy_pct").observe(
            100.0 * active / self.n_slots)
        self.metrics.counter("serve.rounds").inc()
        self.metrics.counter("serve.steps").inc(self.round_len)
        self.rounds_run += 1
        self.steps_run += self.round_len
        tokens_out = self.metrics.counter("serve.tokens_out")
        for slot in range(self.n_slots):
            rid = self.req_of_slot[slot]
            if rid is None:
                continue
            take = int(min(self.budget[slot], self.round_len))
            seq = toks[slot, :take].tolist()
            eos = self._eos[rid]
            if eos is not None and eos in seq:
                seq = seq[:seq.index(eos) + 1]
                self.budget[slot] = 0
            else:
                self.budget[slot] -= take
            self._out[rid].extend(seq)
            tokens_out.inc(len(seq))
            self._retire_if_done(slot)
        return True

    def run(self) -> List[np.ndarray]:
        """Drive rounds until every submitted request completes."""
        while self._queue or any(r is not None
                                 for r in self.req_of_slot):
            progressed = self.step_round()
            if not progressed and self._queue:  # pragma: no cover
                raise RuntimeError("queue stuck with no free slots")
        # a request canceled before admission never produced tokens
        return [np.asarray(o if o is not None else [], np.int32)
                for o in self._out]

    def stats(self) -> dict:
        """Serving-telemetry snapshot: counters and gauges verbatim,
        histograms as percentile SUMMARIES (count/mean/min/max +
        p50/p90/p99 estimated from the log2 buckets,
        metrics.hist_summary) — dashboards read quantiles, not raw
        28-bucket dumps. The bucket layout stays available through
        ``self.metrics.snapshot()`` for anyone who wants it."""
        snap = self.metrics.snapshot()
        snap["histograms"] = {k: hist_summary(h)
                              for k, h in snap["histograms"].items()}
        return snap
