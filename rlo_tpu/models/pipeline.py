"""Pipeline parallelism: transformer layers sharded over a `pp` mesh
axis, microbatches streamed stage-to-stage with `lax.ppermute`.

Net-new capability completing the strategy set (dp/sp/tp/ep/pp; the
reference has none — SURVEY.md §5). GPipe-style schedule expressed the
TPU way: one SPMD program under shard_map where every stage runs the
same `lax.scan` over M + pp - 1 pipeline ticks; at each tick a stage
applies its local layer block and hands the activation to its successor
through a single CollectivePermute (the chain permutation
[(0,1), (1,2), ...] — no wraparound, so stage 0's inbound edge is the
zeros the schedule expects during fill). Stage 0 injects a fresh
microbatch each tick; the last stage collects finished activations and
computes logits + loss; the per-stage work is itself a `lax.scan` over
the stage's stacked layer parameters. No data-dependent control flow —
bubbles are masked arithmetic, so XLA overlaps the ppermute with the
next tick's matmuls.

Parameters: `stack_layers` converts the flagship model's per-layer list
(models.transformer.init_params) into leaves stacked over a leading
layer axis, which `pipeline_pspecs` shards over `pp` (each stage owns
n_layers/pp layers); embed and final-norm are replicated (the embedding
is used by stage 0 to embed and by the last stage to unembed — its
gradient contributions from both ends combine through vma's automatic
psum over pp).

Gradients flow through the scan + ppermute chain by ordinary reverse AD
(the transpose of a chain ppermute is the reverse chain), so stage-local
layer grads stay local and `train_step`-style SGD applies shard-wise.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from rlo_tpu.models.transformer import (TransformerConfig, _rmsnorm,
                                        embed_tokens, _vma_active, apply_layer,
                                        next_token_targets, nll_sum)


def stack_layers(params: dict) -> dict:
    """Convert init_params' per-layer list into stacked (L, ...) leaves
    (scan-able; the leading axis is what `pp` shards)."""
    layers = params["layers"]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
    return {"embed": params["embed"], "ln_f": params["ln_f"],
            "stacked": stacked}


def unstack_layers(pparams: dict, n_layers: int) -> dict:
    """Inverse of `stack_layers` (global view)."""
    layers = [jax.tree.map(lambda x: x[i], pparams["stacked"])
              for i in range(n_layers)]
    return {"embed": pparams["embed"], "ln_f": pparams["ln_f"],
            "layers": layers}


def pipeline_pspecs(pp_axis: Optional[str] = None,
                    cfg: Optional[TransformerConfig] = None):
    """PartitionSpec tree for `stack_layers` output: stacked layer
    leaves sharded over `pp` on the layer axis, embed/ln_f replicated.
    Pass ``cfg`` so the attention-projection leaves match it (GQA
    configs carry wq/wkv instead of the fused wqkv) — omitting it
    assumes MHA, like param_pspecs' default tree."""
    from jax.sharding import PartitionSpec as P
    layer = {
        "ln1": {"g": P(pp_axis, None)},
        "wo": P(pp_axis, None, None),
        "ln2": {"g": P(pp_axis, None)},
        "w1": P(pp_axis, None, None),
        "w2": P(pp_axis, None, None),
    }
    if cfg is not None and cfg.kv_heads != cfg.n_heads:
        layer["wq"] = P(pp_axis, None, None)
        layer["wkv"] = P(pp_axis, None, None, None)
    else:
        layer["wqkv"] = P(pp_axis, None, None, None)
    return {"embed": P(), "ln_f": {"g": P()}, "stacked": layer}


def _make_stage_fn(cfg: TransformerConfig):
    """Apply this stage's local stacked layers to an activation block —
    a lax.scan over transformer.apply_layer, THE layer math (shared with
    forward, so the block cannot diverge between the two)."""
    def one_layer(x, lp):
        pos = jnp.arange(x.shape[1])  # full sequence per microbatch
        x, _aux = apply_layer(x, lp, cfg, pos=pos)
        return x, None

    def stage(stacked_local, x):
        out, _ = lax.scan(one_layer, x, stacked_local)
        return out

    return stage


def pipeline_loss(pparams: dict, tokens, cfg: TransformerConfig,
                  pp_axis: str, n_micro: int):
    """Mean next-token cross-entropy, computed through the pipeline.

    tokens: (batch, blk), replicated across pp (batch % n_micro == 0).
    Equals models.transformer.loss_fn on the same params/tokens exactly
    (microbatching only reorders batch-independent work).
    """
    if cfg.n_experts > 0:
        raise NotImplementedError(
            "pipeline parallelism currently supports dense layers only; "
            "MoE (n_experts > 0) composes with dp/sp/ep via "
            "models.transformer.train_step instead")
    pp = lax.axis_size(pp_axis)
    stage_idx = lax.axis_index(pp_axis)
    b, blk = tokens.shape
    assert b % n_micro == 0, f"batch {b} % n_micro {n_micro} != 0"
    mb = b // n_micro
    dt = cfg.act_dtype
    stage_fn = _make_stage_fn(cfg)
    tokens_mb = tokens.reshape(n_micro, mb, blk)
    pos = jnp.arange(blk)
    chain = [(i, i + 1) for i in range(pp - 1)]  # no wraparound

    def embed_mb(tok):
        return embed_tokens(pparams["embed"], tok, pos, cfg)

    state0 = jnp.zeros((mb, blk, cfg.d_model), dt)
    try:
        # the chain ppermute makes the carry varying over pp, and
        # dp-sharded tokens make it varying over dp — pre-vary the init
        # over both so the scan carry type is stable
        need = ({pp_axis} | set(jax.typeof(tokens).vma)) \
            - set(jax.typeof(state0).vma)
        if need:
            state0 = lax.pcast(state0, tuple(sorted(need)), to="varying")
    except (AttributeError, TypeError):
        pass

    def tick(state, t):
        m = jnp.clip(t, 0, n_micro - 1)
        fresh = embed_mb(lax.dynamic_index_in_dim(tokens_mb, m, 0,
                                                  keepdims=False))
        inp = jnp.where(stage_idx == 0, fresh, state)
        out = stage_fn(pparams["stacked"], inp)
        send = lax.ppermute(out, pp_axis, chain)
        return send, out

    _, outs = lax.scan(tick, state0, jnp.arange(n_micro + pp - 1))
    # the last stage finished microbatch m at tick m + pp - 1
    finished = lax.dynamic_slice_in_dim(outs, pp - 1, n_micro, 0)

    def mb_loss(x, tok):
        x = _rmsnorm(x, pparams["ln_f"]["g"])
        logits = (x @ pparams["embed"].T.astype(dt)).astype(jnp.float32)
        targets, valid = next_token_targets(tok)
        return nll_sum(logits, targets, valid)

    sums, counts = jax.vmap(mb_loss)(finished, tokens_mb)
    local = jnp.sum(sums) / jnp.sum(counts)
    # only the last stage computed real losses; psum of the masked value
    # broadcasts it (and types the result invariant over pp)
    return lax.psum(jnp.where(stage_idx == pp - 1, local, 0.0), pp_axis)


def pipeline_cost(schedule: str, pp: int, n_micro: int) -> dict:
    """Analytic schedule model (round-5 VERDICT item 8), same role as
    tpu_collectives.allreduce_cost: the numbers the lowered program
    must exhibit, pinned by jaxpr inspection in
    tests/test_pipeline_parallel.py.

    GPipe here = forward scan of M + pp - 1 ticks, backward derived by
    reverse AD (its transpose runs the mirrored schedule), one chain
    ppermute per tick each way. Peak boundary-activation storage is
    the scan's stacked carry history: M + pp - 1 microbatch blocks per
    stage (plus AD's per-tick layer residuals unless remat).

    1F1B = ONE explicit scan of M + 2(pp - 1) ticks doing a masked
    forward AND a masked backward sub-step per tick (two ppermutes:
    activations down the chain, cotangents back up). Stage backward
    recomputes its block (remat) from a ring buffer of saved INPUTS,
    so peak boundary storage is the ring: 2*pp - 1 blocks regardless
    of M — the point of 1F1B. Same bubble fraction class as GPipe
    (2(pp-1) idle of M + 2(pp-1) combined ticks vs GPipe's 2(pp-1) of
    2(M + pp - 1)); the win is memory, not bubbles.
    """
    if pp < 1 or n_micro < 1:
        raise ValueError("pp >= 1 and n_micro >= 1 required")
    if schedule == "gpipe":
        fwd = n_micro + pp - 1
        return {"fwd_ticks": fwd, "total_ticks": 2 * fwd,
                "permutes_per_tick": 1,
                "bubble_fraction": (pp - 1) / fwd,
                "peak_boundary_blocks": fwd}
    if schedule == "1f1b":
        ticks = n_micro + 2 * (pp - 1)
        return {"fwd_ticks": ticks, "total_ticks": ticks,
                "permutes_per_tick": 2,
                "bubble_fraction": 2 * (pp - 1) / ticks,
                "peak_boundary_blocks": min(2 * pp - 1, n_micro + pp - 1)}
    raise ValueError(f"no cost model for schedule {schedule!r}")


def pipeline_1f1b_train_step(pparams: dict, tokens,
                             cfg: TransformerConfig, pp_axis: str,
                             n_micro: int, lr: float = 1e-2,
                             dp_axis: Optional[str] = None
                             ) -> Tuple[dict, jax.Array]:
    """One SGD step on the 1F1B schedule — gradients EQUAL the GPipe
    step's (tests pin it): same math, different schedule.

    One lax.scan over M + 2(pp-1) ticks; tick t at stage s runs
      forward  of microbatch m_f = t - s            (masked in-range)
      backward of microbatch m_b = t - 2(pp-1) + s  (masked in-range)
    The backward sub-step recomputes the stage block from the saved
    stage INPUT (a (2pp-1)-slot ring buffer — the only boundary
    storage) and pulls the successor's cotangent through jax.vjp;
    cotangents ride the REVERSE chain ppermute. At the last stage
    m_b == m_f every tick, so the loss seed is computed in place.
    Per-microbatch loss seeds are UNNORMALIZED (d nll_sum); all grads
    scale by 1/total_valid_count at the end (grads are linear in the
    seed), which makes the step exactly the mean-loss gradient without
    knowing the total count up front.
    """
    assert _vma_active(pp_axis), (
        "pipeline training requires shard_jit's vma typing "
        "(check_vma=True)")
    if cfg.n_experts > 0:
        raise NotImplementedError("dense layers only (as pipeline_loss)")
    pp = lax.axis_size(pp_axis)
    stage_idx = lax.axis_index(pp_axis)
    b, blk = tokens.shape
    assert b % n_micro == 0, f"batch {b} % n_micro {n_micro} != 0"
    mb = b // n_micro
    dt = cfg.act_dtype
    stage_fn = _make_stage_fn(cfg)
    tokens_mb = tokens.reshape(n_micro, mb, blk)
    pos = jnp.arange(blk)
    chain = [(i, i + 1) for i in range(pp - 1)]
    rchain = [(i + 1, i) for i in range(pp - 1)]
    S = min(2 * pp - 1, n_micro + pp - 1)      # ring slots
    T = n_micro + 2 * (pp - 1)                 # ticks
    W = pparams["stacked"]

    def embed_mb(e, tok):
        return embed_tokens(e, tok, pos, cfg)

    def mb_loss_sum(x, lnf_g, e, tok):
        xn = _rmsnorm(x, lnf_g)
        logits = (xn @ e.T.astype(dt)).astype(jnp.float32)
        targets, valid = next_token_targets(tok)
        s, c = nll_sum(logits, targets, valid)
        return s, c

    def _vary(x):
        # every carry leaf must be varying over pp (and dp when tokens
        # are) from tick 0, or the scan carry type flips mid-loop
        try:
            need = ({pp_axis} | set(jax.typeof(tokens).vma)) \
                - set(jax.typeof(x).vma)
            if need:
                return lax.pcast(x, tuple(sorted(need)), to="varying")
        except (AttributeError, TypeError):
            pass
        return x

    zeros_x = _vary(jnp.zeros((mb, blk, cfg.d_model), dt))
    ring0 = jnp.zeros((S,) + zeros_x.shape, dt) + zeros_x  # varying too
    g0 = jax.tree.map(jnp.zeros_like, pparams)
    # embed/ln_f are REPLICATED (vma-invariant over pp) — a vjp wrt an
    # invariant input auto-psums the cotangent across stages, which
    # would leak every stage's masked-out garbage into the last
    # stage's loss-head grads. Differentiate VARYING copies instead:
    # each stage gets its own cotangent, masked locally, psummed ONCE
    # at the end.
    emb_v = _vary(pparams["embed"])
    lnf_v = _vary(pparams["ln_f"]["g"])
    # same trap on the stacked weights when composing with dp: they
    # are pp-sharded (varying over pp) but dp-REPLICATED, so a vjp wrt
    # them auto-psums dW over dp inside every tick — double-counting
    # once the final pmean runs. Differentiate a dp-varying copy.
    W_v = jax.tree.map(_vary, W)

    def tick(carry, t):
        recv_f, recv_b, ring, g, loss_s, loss_c = carry
        # ---- forward sub-step -------------------------------------
        m_f = t - stage_idx
        ok_f = (m_f >= 0) & (m_f < n_micro)
        mf_c = jnp.clip(m_f, 0, n_micro - 1)
        tok_f = lax.dynamic_index_in_dim(tokens_mb, mf_c, 0,
                                         keepdims=False)
        fresh = embed_mb(emb_v, tok_f)
        inp = jnp.where(stage_idx == 0, fresh, recv_f)
        inp = jnp.where(ok_f, inp, zeros_x)
        out = stage_fn(W, inp)
        # invalid ticks must NOT write: the clipped slot index would
        # clobber a LIVE slot with zeros (stage 0's last backwards
        # would then recompute from zeros — rmsnorm blows them up)
        prev = lax.dynamic_index_in_dim(ring, mf_c % S, 0,
                                        keepdims=False)
        ring = lax.dynamic_update_index_in_dim(
            ring, jnp.where(ok_f, inp, prev), mf_c % S, 0)
        send_f = lax.ppermute(out, pp_axis, chain)

        # ---- backward sub-step ------------------------------------
        m_b = t - 2 * (pp - 1) + stage_idx
        ok_b = (m_b >= 0) & (m_b < n_micro)
        mb_c = jnp.clip(m_b, 0, n_micro - 1)
        xin = lax.dynamic_index_in_dim(ring, mb_c % S, 0,
                                       keepdims=False)
        tok_b = lax.dynamic_index_in_dim(tokens_mb, mb_c, 0,
                                         keepdims=False)
        # recompute the block (remat) + pullback
        out_b, pull = jax.vjp(lambda w, x: stage_fn(w, x), W_v, xin)
        # cotangent seed: last stage = d(nll_sum)/d(out) in place;
        # other stages = the successor's cotangent off the wire
        (l_s, l_c), pull_loss = jax.vjp(
            lambda x, lg, e: mb_loss_sum(x, lg, e, tok_b),
            out_b, lnf_v, emb_v)
        from rlo_tpu.parallel.mesh import vary_like
        dx_loss, d_lnf, d_emb_un = pull_loss(
            (vary_like(jnp.float32(1.0), l_s),
             vary_like(jnp.float32(0.0), l_c)))
        is_last = stage_idx == pp - 1
        cot = jnp.where(is_last, dx_loss.astype(dt), recv_b)
        cot = jnp.where(ok_b, cot, zeros_x)
        dW, dx_in = pull(cot)
        # stage 0: pull the input cotangent through the embedding
        _, pull_embed = jax.vjp(lambda e: embed_mb(e, tok_b),
                                emb_v)
        (d_emb_in,) = pull_embed(dx_in)
        okb_f = ok_b.astype(jnp.float32)
        okl = (ok_b & is_last).astype(jnp.float32)
        ok0 = (ok_b & (stage_idx == 0)).astype(jnp.float32)
        g = {
            "stacked": jax.tree.map(
                lambda a, d: a + okb_f * d.astype(a.dtype),
                g["stacked"], dW),
            "ln_f": {"g": g["ln_f"]["g"]
                     + okl * d_lnf.astype(g["ln_f"]["g"].dtype)},
            "embed": (g["embed"]
                      + okl * d_emb_un.astype(g["embed"].dtype)
                      + ok0 * d_emb_in.astype(g["embed"].dtype)),
        }
        loss_s = loss_s + jnp.where(ok_b & is_last, l_s, 0.0)
        loss_c = loss_c + jnp.where(ok_b & is_last, l_c, 0.0)
        # the predecessor needs dL/d(my INPUT) — the pullback's dx_in,
        # masked so bubble garbage never rides the reverse chain
        send_b = lax.ppermute(
            jnp.where(ok_b, dx_in.astype(dt), zeros_x), pp_axis,
            rchain)
        return (send_f, send_b, ring, g, loss_s, loss_c), None

    carry0 = jax.tree.map(_vary, (zeros_x, zeros_x, ring0, g0,
                                  jnp.float32(0.0), jnp.float32(0.0)))
    (_, _, _, g, loss_s, loss_c), _ = lax.scan(
        tick, carry0, jnp.arange(T))
    # embed/ln_f contributions live on different stages — combine
    total_c = lax.psum(jnp.where(stage_idx == pp - 1, loss_c, 0.0),
                       pp_axis)
    scale = 1.0 / jnp.maximum(total_c, 1.0)
    grads = {
        "stacked": jax.tree.map(lambda x: x * scale, g["stacked"]),
        "ln_f": {"g": lax.psum(g["ln_f"]["g"], pp_axis) * scale},
        "embed": lax.psum(g["embed"], pp_axis) * scale,
    }
    loss = lax.psum(jnp.where(stage_idx == pp - 1, loss_s, 0.0),
                    pp_axis) / jnp.maximum(total_c, 1.0)
    if dp_axis is not None:
        # manual grads carry no vma auto-psum over dp — combine
        # explicitly (pmean == the GPipe step's AD psum + /n)
        grads = jax.tree.map(lambda gg: lax.pmean(gg, dp_axis), grads)
        loss = lax.pmean(loss, dp_axis)
    new_params = jax.tree.map(lambda p, gg: p - lr * gg.astype(p.dtype),
                              pparams, grads)
    return new_params, loss


def pipeline_train_step(pparams: dict, tokens, cfg: TransformerConfig,
                        pp_axis: str, n_micro: int, lr: float = 1e-2,
                        dp_axis: Optional[str] = None
                        ) -> Tuple[dict, jax.Array]:
    """One SGD step through the pipeline; composes with dp (tokens
    additionally sharded over `dp_axis`). Stage-local layer grads stay
    on their stage; embed/ln_f grads combine over pp via vma's automatic
    psum."""
    # without vma typing, the cross-stage psum of embed/ln_f cotangents
    # never happens and every stage silently takes a different step
    assert _vma_active(pp_axis), (
        "pipeline training requires shard_jit's vma typing "
        "(check_vma=True)")
    loss, grads = jax.value_and_grad(pipeline_loss)(pparams, tokens, cfg,
                                                    pp_axis, n_micro)
    if dp_axis is not None:
        n = lax.axis_size(dp_axis)
        grads = jax.tree.map(lambda g: g / n, grads)
        loss = lax.pmean(loss, dp_axis)
    new_params = jax.tree.map(lambda p, g: p - lr * g, pparams, grads)
    return new_params, loss
