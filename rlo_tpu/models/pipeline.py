"""Pipeline parallelism: transformer layers sharded over a `pp` mesh
axis, microbatches streamed stage-to-stage with `lax.ppermute`.

Net-new capability completing the strategy set (dp/sp/tp/ep/pp; the
reference has none — SURVEY.md §5). GPipe-style schedule expressed the
TPU way: one SPMD program under shard_map where every stage runs the
same `lax.scan` over M + pp - 1 pipeline ticks; at each tick a stage
applies its local layer block and hands the activation to its successor
through a single CollectivePermute (the chain permutation
[(0,1), (1,2), ...] — no wraparound, so stage 0's inbound edge is the
zeros the schedule expects during fill). Stage 0 injects a fresh
microbatch each tick; the last stage collects finished activations and
computes logits + loss; the per-stage work is itself a `lax.scan` over
the stage's stacked layer parameters. No data-dependent control flow —
bubbles are masked arithmetic, so XLA overlaps the ppermute with the
next tick's matmuls.

Parameters: `stack_layers` converts the flagship model's per-layer list
(models.transformer.init_params) into leaves stacked over a leading
layer axis, which `pipeline_pspecs` shards over `pp` (each stage owns
n_layers/pp layers); embed and final-norm are replicated (the embedding
is used by stage 0 to embed and by the last stage to unembed — its
gradient contributions from both ends combine through vma's automatic
psum over pp).

Gradients flow through the scan + ppermute chain by ordinary reverse AD
(the transpose of a chain ppermute is the reverse chain), so stage-local
layer grads stay local and `train_step`-style SGD applies shard-wise.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from rlo_tpu.models.transformer import (TransformerConfig, _rmsnorm,
                                        embed_tokens, _vma_active, apply_layer,
                                        next_token_targets, nll_sum)


def stack_layers(params: dict) -> dict:
    """Convert init_params' per-layer list into stacked (L, ...) leaves
    (scan-able; the leading axis is what `pp` shards)."""
    layers = params["layers"]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
    return {"embed": params["embed"], "ln_f": params["ln_f"],
            "stacked": stacked}


def unstack_layers(pparams: dict, n_layers: int) -> dict:
    """Inverse of `stack_layers` (global view)."""
    layers = [jax.tree.map(lambda x: x[i], pparams["stacked"])
              for i in range(n_layers)]
    return {"embed": pparams["embed"], "ln_f": pparams["ln_f"],
            "layers": layers}


def pipeline_pspecs(pp_axis: Optional[str] = None,
                    cfg: Optional[TransformerConfig] = None):
    """PartitionSpec tree for `stack_layers` output: stacked layer
    leaves sharded over `pp` on the layer axis, embed/ln_f replicated.
    Pass ``cfg`` so the attention-projection leaves match it (GQA
    configs carry wq/wkv instead of the fused wqkv) — omitting it
    assumes MHA, like param_pspecs' default tree."""
    from jax.sharding import PartitionSpec as P
    layer = {
        "ln1": {"g": P(pp_axis, None)},
        "wo": P(pp_axis, None, None),
        "ln2": {"g": P(pp_axis, None)},
        "w1": P(pp_axis, None, None),
        "w2": P(pp_axis, None, None),
    }
    if cfg is not None and cfg.kv_heads != cfg.n_heads:
        layer["wq"] = P(pp_axis, None, None)
        layer["wkv"] = P(pp_axis, None, None, None)
    else:
        layer["wqkv"] = P(pp_axis, None, None, None)
    return {"embed": P(), "ln_f": {"g": P()}, "stacked": layer}


def _make_stage_fn(cfg: TransformerConfig):
    """Apply this stage's local stacked layers to an activation block —
    a lax.scan over transformer.apply_layer, THE layer math (shared with
    forward, so the block cannot diverge between the two)."""
    def one_layer(x, lp):
        pos = jnp.arange(x.shape[1])  # full sequence per microbatch
        x, _aux = apply_layer(x, lp, cfg, pos=pos)
        return x, None

    def stage(stacked_local, x):
        out, _ = lax.scan(one_layer, x, stacked_local)
        return out

    return stage


def pipeline_loss(pparams: dict, tokens, cfg: TransformerConfig,
                  pp_axis: str, n_micro: int):
    """Mean next-token cross-entropy, computed through the pipeline.

    tokens: (batch, blk), replicated across pp (batch % n_micro == 0).
    Equals models.transformer.loss_fn on the same params/tokens exactly
    (microbatching only reorders batch-independent work).
    """
    if cfg.n_experts > 0:
        raise NotImplementedError(
            "pipeline parallelism currently supports dense layers only; "
            "MoE (n_experts > 0) composes with dp/sp/ep via "
            "models.transformer.train_step instead")
    pp = lax.axis_size(pp_axis)
    stage_idx = lax.axis_index(pp_axis)
    b, blk = tokens.shape
    assert b % n_micro == 0, f"batch {b} % n_micro {n_micro} != 0"
    mb = b // n_micro
    dt = cfg.act_dtype
    stage_fn = _make_stage_fn(cfg)
    tokens_mb = tokens.reshape(n_micro, mb, blk)
    pos = jnp.arange(blk)
    chain = [(i, i + 1) for i in range(pp - 1)]  # no wraparound

    def embed_mb(tok):
        return embed_tokens(pparams["embed"], tok, pos, cfg)

    state0 = jnp.zeros((mb, blk, cfg.d_model), dt)
    try:
        # the chain ppermute makes the carry varying over pp, and
        # dp-sharded tokens make it varying over dp — pre-vary the init
        # over both so the scan carry type is stable
        need = ({pp_axis} | set(jax.typeof(tokens).vma)) \
            - set(jax.typeof(state0).vma)
        if need:
            state0 = lax.pcast(state0, tuple(sorted(need)), to="varying")
    except (AttributeError, TypeError):
        pass

    def tick(state, t):
        m = jnp.clip(t, 0, n_micro - 1)
        fresh = embed_mb(lax.dynamic_index_in_dim(tokens_mb, m, 0,
                                                  keepdims=False))
        inp = jnp.where(stage_idx == 0, fresh, state)
        out = stage_fn(pparams["stacked"], inp)
        send = lax.ppermute(out, pp_axis, chain)
        return send, out

    _, outs = lax.scan(tick, state0, jnp.arange(n_micro + pp - 1))
    # the last stage finished microbatch m at tick m + pp - 1
    finished = lax.dynamic_slice_in_dim(outs, pp - 1, n_micro, 0)

    def mb_loss(x, tok):
        x = _rmsnorm(x, pparams["ln_f"]["g"])
        logits = (x @ pparams["embed"].T.astype(dt)).astype(jnp.float32)
        targets, valid = next_token_targets(tok)
        return nll_sum(logits, targets, valid)

    sums, counts = jax.vmap(mb_loss)(finished, tokens_mb)
    local = jnp.sum(sums) / jnp.sum(counts)
    # only the last stage computed real losses; psum of the masked value
    # broadcasts it (and types the result invariant over pp)
    return lax.psum(jnp.where(stage_idx == pp - 1, local, 0.0), pp_axis)


def pipeline_train_step(pparams: dict, tokens, cfg: TransformerConfig,
                        pp_axis: str, n_micro: int, lr: float = 1e-2,
                        dp_axis: Optional[str] = None
                        ) -> Tuple[dict, jax.Array]:
    """One SGD step through the pipeline; composes with dp (tokens
    additionally sharded over `dp_axis`). Stage-local layer grads stay
    on their stage; embed/ln_f grads combine over pp via vma's automatic
    psum."""
    # without vma typing, the cross-stage psum of embed/ln_f cotangents
    # never happens and every stage silently takes a different step
    assert _vma_active(pp_axis), (
        "pipeline training requires shard_jit's vma typing "
        "(check_vma=True)")
    loss, grads = jax.value_and_grad(pipeline_loss)(pparams, tokens, cfg,
                                                    pp_axis, n_micro)
    if dp_axis is not None:
        n = lax.axis_size(dp_axis)
        grads = jax.tree.map(lambda g: g / n, grads)
        loss = lax.pmean(loss, dp_axis)
    new_params = jax.tree.map(lambda p, g: p - lr * g, pparams, grads)
    return new_params, loss
