"""Flagship model: decoder-only transformer, sequence-parallel by ring
attention, data-parallel by the framework's ring allreduce.

The reference ships no model code (SURVEY.md §5 records the absence);
this is the net-new capability demonstrating the substrate end-to-end on
a 2-D mesh (dp, sp):

  - the sequence axis is sharded over `sp`: attention runs as
    rlo_tpu.ops.ring_attention (K/V streaming over the ppermute ring),
    every other sublayer is position-local and needs no communication;
  - the batch axis is sharded over `dp`: gradients are combined with the
    framework's ring allreduce + Pallas fused combine
    (rlo_tpu.ops.tpu_collectives.allreduce), the data-collective path the
    BASELINE.json configs benchmark;
  - cross-shard label shift (next-token prediction across the sp
    boundary) is one ppermute of the first token column.

Pure-functional JAX: params are a pytree, `train_step` is jit/shard_map
compatible, bfloat16 activations with float32 params and accumulation.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from rlo_tpu import topology
from rlo_tpu.ops import tpu_collectives as tc
from rlo_tpu.ops.ring_attention import full_attention, ring_attention


@dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    dtype: str = "bfloat16"  # activation dtype; params stay float32

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def act_dtype(self):
        return jnp.dtype(self.dtype)


def init_params(rng: jax.Array, cfg: TransformerConfig) -> dict:
    """Scaled-normal init; embedding tied with the output head."""
    keys = jax.random.split(rng, 2 + 6 * cfg.n_layers)
    d, f = cfg.d_model, cfg.d_ff

    def norm(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale)

    params = {
        "embed": norm(keys[0], (cfg.vocab, d), 0.02),
        "ln_f": {"g": jnp.ones((d,), jnp.float32)},
        "layers": [],
    }
    k = 2
    for _ in range(cfg.n_layers):
        params["layers"].append({
            "ln1": {"g": jnp.ones((d,), jnp.float32)},
            "wqkv": norm(keys[k], (d, 3 * d), d ** -0.5),
            "wo": norm(keys[k + 1], (d, d), (2 * d * cfg.n_layers) ** -0.5),
            "ln2": {"g": jnp.ones((d,), jnp.float32)},
            "w1": norm(keys[k + 2], (d, f), d ** -0.5),
            "w2": norm(keys[k + 3], (f, d), (2 * f * cfg.n_layers) ** -0.5),
        })
        k += 6
    return params


def _rmsnorm(x, g):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                   keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * g.astype(
        x.dtype)


def _sincos(pos, d_model, dtype):
    """Sinusoidal positions for GLOBAL token positions (works sharded)."""
    half = d_model // 2
    freqs = jnp.exp(-np.log(10000.0) * jnp.arange(half) / half)
    ang = pos[:, None].astype(jnp.float32) * freqs[None, :]
    return jnp.concatenate([jnp.sin(ang), jnp.cos(ang)],
                           axis=-1).astype(dtype)


def forward(params: dict, tokens: jax.Array, cfg: TransformerConfig,
            sp_axis: Optional[str] = None) -> jax.Array:
    """Logits for next-token prediction; causal.

    tokens: (batch, block) int32 — `block` is the LOCAL sequence slice
    when sp_axis is set (shard r holds tokens [r*block, (r+1)*block)).
    """
    b, blk = tokens.shape
    dt = cfg.act_dtype
    if sp_axis is not None:
        pos0 = lax.axis_index(sp_axis) * blk
    else:
        pos0 = 0
    pos = pos0 + jnp.arange(blk)

    x = params["embed"][tokens].astype(dt) + _sincos(pos, cfg.d_model, dt)

    for layer in params["layers"]:
        h = _rmsnorm(x, layer["ln1"]["g"])
        qkv = h @ layer["wqkv"].astype(dt)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(b, blk, cfg.n_heads, cfg.head_dim)

        q, k, v = heads(q), heads(k), heads(v)
        if sp_axis is None:
            att = jax.vmap(lambda q_, k_, v_: full_attention(
                q_, k_, v_, causal=True))(q, k, v)
        else:
            att = jax.vmap(lambda q_, k_, v_: ring_attention(
                q_, k_, v_, sp_axis, causal=True), in_axes=0)(q, k, v)
        att = att.reshape(b, blk, cfg.d_model)
        x = x + att @ layer["wo"].astype(dt)

        h = _rmsnorm(x, layer["ln2"]["g"])
        h = jax.nn.gelu(h @ layer["w1"].astype(dt))
        x = x + h @ layer["w2"].astype(dt)

    x = _rmsnorm(x, params["ln_f"]["g"])
    return (x @ params["embed"].T.astype(dt)).astype(jnp.float32)


def loss_fn(params: dict, tokens: jax.Array, cfg: TransformerConfig,
            sp_axis: Optional[str] = None) -> jax.Array:
    """Mean next-token cross-entropy. With sp sharding, the label for a
    shard's last position is the next shard's first token — one ppermute
    — and the final global position is masked out."""
    logits = forward(params, tokens, cfg, sp_axis)
    b, blk = tokens.shape
    if sp_axis is None:
        targets = jnp.concatenate(
            [tokens[:, 1:], jnp.zeros((b, 1), tokens.dtype)], axis=1)
        valid = jnp.concatenate(
            [jnp.ones((b, blk - 1), jnp.float32),
             jnp.zeros((b, 1), jnp.float32)], axis=1)
    else:
        ws = lax.axis_size(sp_axis)
        idx = lax.axis_index(sp_axis)
        # shard r receives shard (r+1)'s first column: ppermute r+1 -> r
        nxt_first = lax.ppermute(tokens[:, :1], sp_axis,
                                 list(topology.ring_perm(ws, -1)))
        targets = jnp.concatenate([tokens[:, 1:], nxt_first], axis=1)
        is_last_shard = (idx == ws - 1)
        valid = jnp.concatenate(
            [jnp.ones((b, blk - 1), jnp.float32),
             jnp.where(is_last_shard, 0.0, 1.0) * jnp.ones(
                 (b, 1), jnp.float32)], axis=1)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    local = jnp.sum(nll * valid)
    count = jnp.sum(valid)
    if sp_axis is not None:
        local = lax.psum(local, sp_axis)
        count = lax.psum(count, sp_axis)
    return local / count


def train_step(params: dict, tokens: jax.Array, cfg: TransformerConfig,
               lr: float = 1e-2, sp_axis: Optional[str] = None,
               dp_axis: Optional[str] = None,
               grad_algorithm: str = "psum"):
    """One SGD step; returns (new_params, loss).

    Gradients combine over `dp_axis` with the framework's allreduce —
    grad_algorithm='ring' uses the explicit ppermute ring with the Pallas
    fused per-step combine (the BASELINE benchmark path), 'psum' the XLA
    collective.
    """
    loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg, sp_axis)
    if sp_axis is not None:
        # params are replicated over sp: sum the per-shard grad shards
        grads = jax.tree.map(lambda g: lax.psum(g, sp_axis), grads)
    if dp_axis is not None:
        n = lax.axis_size(dp_axis)
        grads = jax.tree.map(
            lambda g: tc.allreduce(g, dp_axis, algorithm=grad_algorithm)
            / n, grads)
        loss = lax.pmean(loss, dp_axis)
    new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    return new_params, loss
