"""Flagship model: decoder-only transformer, sequence-parallel by ring
attention, tensor-parallel Megatron-style, data-parallel by the
framework's ring allreduce.

The reference ships no model code (SURVEY.md §5 records the absence);
this is the net-new capability demonstrating the substrate end-to-end on
a (dp, sp, tp) mesh:

  - the sequence axis is sharded over `sp`: attention runs as
    rlo_tpu.ops.ring_attention (K/V streaming over the ppermute ring),
    every other sublayer is position-local and needs no communication;
  - attention heads and FFN hidden units are sharded over `tp`
    (column-parallel wqkv/w1, row-parallel wo/w2): each device computes
    its local heads/hidden slice and the partial output projections are
    summed with the framework's allreduce — the two classic
    tensor-parallel collectives per layer (`param_pspecs` gives the
    matching PartitionSpec tree);
  - the batch axis is sharded over `dp`: gradients are combined with the
    framework's ring allreduce + Pallas fused combine
    (rlo_tpu.ops.tpu_collectives.allreduce), the data-collective path the
    BASELINE.json configs benchmark;
  - cross-shard label shift (next-token prediction across the sp
    boundary) is one ppermute of the first token column.

Pure-functional JAX: params are a pytree, `train_step` is jit/shard_map
compatible, bfloat16 activations with float32 params and accumulation.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from rlo_tpu import topology
from rlo_tpu.models import moe
from rlo_tpu.ops import tpu_collectives as tc
from rlo_tpu.ops.ring_attention import full_attention, ring_attention


@dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    dtype: str = "bfloat16"  # activation dtype; params stay float32
    # mixture-of-experts FFN (0 = dense). Experts shard over `ep_axis`
    # with all_to_all dispatch/return — see rlo_tpu.models.moe.
    n_experts: int = 0
    capacity_factor: float = 2.0
    moe_aux_coef: float = 1e-2
    # sequence-parallel attention strategy: 'ring' (K/V streaming over
    # the ppermute ring) or 'ulysses' (all_to_all head-scatter; needs
    # local heads divisible by the sp size) — rlo_tpu.ops.{ring_attention,
    # ulysses}
    sp_attention: str = "ring"
    # grouped-query attention: number of K/V heads (must divide
    # n_heads); None = n_heads (MHA). Each group of
    # n_heads/n_kv_heads query heads shares one K/V head — smaller
    # projections and an n_heads/n_kv_heads-times smaller decode
    # KV cache (models.generate stores only the K/V heads).
    n_kv_heads: Optional[int] = None
    # position encoding: 'sincos' (additive at the embedding) or
    # 'rope' (rotary: q/k rotated per position inside every layer —
    # relative-position attention; composes with sp sharding because
    # the rotation uses GLOBAL positions, and with the KV cache
    # because keys are cached rotated)
    pos_encoding: str = "sincos"
    # RoPE context extension (rope configs only). rope_scaling:
    #   None      — plain rotary at base 10000
    #   'linear'  — position interpolation: positions divided by
    #               rope_scale, squeezing a rope_scale-times longer
    #               context into the trained rotation range
    #   'ntk'     — NTK-aware base rescale: base *= scale^(d/(d-2)),
    #               extending low-frequency dims' range while keeping
    #               high-frequency (local-order) resolution
    # Both are inference-time levers for running a model past its
    # training length; rope_scale is the extension factor.
    rope_scaling: Optional[str] = None
    rope_scale: float = 1.0
    # KV-cache storage dtype for generation (models.generate):
    #   None   — cache in the activation dtype (exact decode)
    #   'int8' — per-(position, head) symmetric quantization: HALF the
    #            cache memory and HBM bytes of bf16, error one
    #            quantization half-step per read. With the flash-decode
    #            kernel (pallas/decode.py) dequantizing tiles in VMEM,
    #            measured 1.17-1.43x decode tok/s (across windows)
    #            at batch 32 / plen 1024
    #            on v5e (interleaved paired ratio,
    #            benchmarks/decode_bench.py --compare-kv); also 2x the
    #            servable batch x context per chip.
    kv_cache_dtype: Optional[str] = None
    # rematerialize each layer in the backward pass (jax.checkpoint):
    # trades ~one extra forward of FLOPs for O(layers) less activation
    # HBM — the standard long-context memory lever
    remat: bool = False
    # cross-entropy vocab chunking (MEMORY lever, off by default): the
    # plain loss materializes two (batch, block, vocab) fp32 tensors
    # (logits + log-probs) plus backward residuals; N > 0 streams the
    # vocab axis through an online logsumexp (flash attention's
    # softmax trick applied to the LM head) in N-wide chunks and never
    # materializes either — O(batch*block*N) instead of
    # O(batch*block*vocab). Use when the loss working set OOMs (huge
    # vocab / long sequence). NOT a speed lever on v5e: measured
    # 10-15% SLOWER at vocab 32k (the scan serializes the head matmul
    # and the checkpointed backward recomputes it), so 0/None = off.
    loss_vocab_chunk: Optional[int] = None

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        if self.n_kv_heads is None:
            return self.n_heads
        assert self.n_heads % self.n_kv_heads == 0, \
            f"n_kv_heads {self.n_kv_heads} must divide n_heads " \
            f"{self.n_heads}"
        return self.n_kv_heads

    @property
    def act_dtype(self):
        return jnp.dtype(self.dtype)


def init_params(rng: jax.Array, cfg: TransformerConfig) -> dict:
    """Scaled-normal init; embedding tied with the output head.

    ``wqkv`` has shape (d, 3, d): axis 1 selects q/k/v and axis 2 is
    (heads x head_dim) flattened, so sharding axis 2 over `tp` splits
    each of q, k, v by head (the memory layout equals the fused
    (d, 3*d) [q|k|v] matrix)."""
    keys = jax.random.split(rng, 2 + 6 * cfg.n_layers)
    d, f = cfg.d_model, cfg.d_ff

    def norm(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale)

    params = {
        "embed": norm(keys[0], (cfg.vocab, d), 0.02),
        "ln_f": {"g": jnp.ones((d,), jnp.float32)},
        "layers": [],
    }
    k = 2
    for _ in range(cfg.n_layers):
        layer = {
            "ln1": {"g": jnp.ones((d,), jnp.float32)},
            "wo": norm(keys[k + 1], (d, d), (2 * d * cfg.n_layers) ** -0.5),
            "ln2": {"g": jnp.ones((d,), jnp.float32)},
        }
        if cfg.kv_heads == cfg.n_heads:
            layer["wqkv"] = norm(keys[k], (d, 3, d), d ** -0.5)
        else:  # GQA: smaller K/V projections, separate q
            dkv = cfg.kv_heads * cfg.head_dim
            kq, kkv = jax.random.split(keys[k])
            layer["wq"] = norm(kq, (d, d), d ** -0.5)
            layer["wkv"] = norm(kkv, (d, 2, dkv), d ** -0.5)
        if cfg.n_experts > 0:
            layer["moe"] = moe.init_moe_params(keys[k + 2], d, f,
                                               cfg.n_experts)
        else:
            layer["w1"] = norm(keys[k + 2], (d, f), d ** -0.5)
            layer["w2"] = norm(keys[k + 3], (f, d),
                               (2 * f * cfg.n_layers) ** -0.5)
        params["layers"].append(layer)
        k += 6
    return params


def param_pspecs(cfg: TransformerConfig, tp_axis: Optional[str] = None,
                 ep_axis: Optional[str] = None):
    """PartitionSpec tree matching `init_params` output.

    With ``tp_axis``: wqkv and w1 are column-parallel (outputs sharded by
    head / hidden unit), wo and w2 row-parallel (inputs sharded). With
    ``ep_axis`` (MoE configs): the expert-indexed leading axis of the
    per-expert FFN weights is sharded; the router is replicated.
    Everything else is replicated. Pass as shard_map in/out specs for the
    params argument."""
    from jax.sharding import PartitionSpec as P
    t = tp_axis
    layer = {
        "ln1": {"g": P()},
        "wo": P(t, None),
        "ln2": {"g": P()},
    }
    if cfg.kv_heads == cfg.n_heads:
        layer["wqkv"] = P(None, None, t)
    else:  # GQA: q and kv column-parallel by (kv-)head
        layer["wq"] = P(None, t)
        layer["wkv"] = P(None, None, t)
    if cfg.n_experts > 0:
        layer["moe"] = {"wr": P(), "w1": P(ep_axis, None, None),
                        "w2": P(ep_axis, None, None)}
    else:
        layer["w1"] = P(None, t)
        layer["w2"] = P(t, None)
    return {"embed": P(), "ln_f": {"g": P()},
            "layers": [dict(layer, ln1={"g": P()}, ln2={"g": P()})
                       for _ in range(cfg.n_layers)]}


def _rmsnorm(x, g):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                   keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * g.astype(
        x.dtype)


def _sincos(pos, d_model, dtype):
    """Sinusoidal positions for GLOBAL token positions (works sharded).
    ``pos`` is (blk,) shared across the batch, or (b, blk) per-row
    (ragged decode); returns (blk, d) or (b, blk, d) accordingly."""
    half = d_model // 2
    freqs = jnp.exp(-np.log(10000.0) * jnp.arange(half) / half)
    ang = pos[..., None].astype(jnp.float32) * freqs
    return jnp.concatenate([jnp.sin(ang), jnp.cos(ang)],
                           axis=-1).astype(dtype)


def embed_tokens(embed, tokens, pos, cfg: TransformerConfig):
    """THE token-embedding path — training (_features), pipeline
    microbatches, and decode all call it, so the position-encoding
    guard lives exactly once. 'sincos' adds the absolute encoding
    here; 'rope' embeds plain (the rotation happens on q/k inside
    every apply_layer)."""
    if cfg.pos_encoding not in ("sincos", "rope"):
        raise ValueError(
            f"unknown pos_encoding {cfg.pos_encoding!r}; "
            f"known: 'sincos', 'rope'")
    if cfg.pos_encoding == "rope" and cfg.head_dim % 2:
        raise ValueError(
            f"rope rotates (i, i+head_dim/2) dim pairs and needs an "
            f"even head_dim; got head_dim={cfg.head_dim} "
            f"(d_model={cfg.d_model}, n_heads={cfg.n_heads})")
    if cfg.rope_scaling is not None:
        if cfg.pos_encoding != "rope":
            raise ValueError(
                f"rope_scaling={cfg.rope_scaling!r} requires "
                f"pos_encoding='rope' (got {cfg.pos_encoding!r})")
        if cfg.rope_scaling not in ("linear", "ntk"):
            raise ValueError(
                f"unknown rope_scaling {cfg.rope_scaling!r}; "
                f"known: 'linear', 'ntk'")
        if cfg.rope_scale < 1.0:
            raise ValueError(
                f"rope_scale must be >= 1 (an extension factor); got "
                f"{cfg.rope_scale}")
    x = embed[tokens].astype(cfg.act_dtype)
    if cfg.pos_encoding == "sincos":
        x = x + _sincos(pos, cfg.d_model, cfg.act_dtype)
    return x


def _rope(t, pos, scaling: Optional[str] = None, scale: float = 1.0):
    """Rotary position embedding: rotate dim pairs (i, i+hd/2) of
    ``t`` (b, blk, heads, head_dim) by position-dependent angles
    (pos (blk,) GLOBAL token positions — sp shards pass their own
    slice, decode passes the single position). Attention scores then
    depend only on RELATIVE positions (the rotation of q·kᵀ composes
    to pos_q − pos_k).

    ``pos`` is (blk,) shared across the batch, or (b, blk) PER-ROW
    (ragged decode: each row sits at its own global position).

    ``scaling``/``scale`` extend the context window (cfg.rope_scaling):
    'linear' divides positions by ``scale`` (position interpolation —
    identical to evaluating the unscaled rotation at pos/scale); 'ntk'
    rescales the base by scale^(hd/(hd-2)) so the lowest frequency's
    period grows ~scale-fold while the highest stays ~unchanged."""
    hd = t.shape[-1]
    half = hd // 2
    base = 10000.0
    posf = pos.astype(jnp.float32)
    if scaling == "linear":
        posf = posf / scale
    elif scaling == "ntk":
        base = base * float(scale) ** (hd / (hd - 2))
    elif scaling is not None:
        raise ValueError(
            f"unknown rope_scaling {scaling!r}; known: 'linear', 'ntk'")
    freqs = jnp.exp(-np.log(base) * jnp.arange(half) / half)
    ang = posf[..., None] * freqs          # (blk, half) | (b, blk, half)
    if ang.ndim == 2:
        cos = jnp.cos(ang)[None, :, None, :]
        sin = jnp.sin(ang)[None, :, None, :]
    else:                                  # per-row positions
        cos = jnp.cos(ang)[:, :, None, :]
        sin = jnp.sin(ang)[:, :, None, :]
    t32 = t.astype(jnp.float32)
    t1, t2 = t32[..., :half], t32[..., half:]
    return jnp.concatenate([t1 * cos - t2 * sin,
                            t1 * sin + t2 * cos], -1).astype(t.dtype)


def _local_attention(q, k, v, use_flash=None, interpret=None):
    """Unsharded causal attention: q (b, L, H, D); k/v (b, L, Hkv, D)
    with Hkv ≤ H (grouped-query attention — query head h attends K/V
    head h // (H/Hkv)).

    On TPU this is the fused flash kernel (pallas/flash.py — trainable
    since the custom_vjp landed): the batch folds into the head axis
    (attention is per-head independent; the causal mask is purely
    position-driven, identical for every batch row), so the whole batch
    is ONE kernel launch instead of a vmapped per-row program — and the
    batch-folded head indices keep the GQA group mapping intact
    (b·H + h ↦ b·Hkv + h//G), so compact K/V streams from HBM. Falls
    back to the unfused oracle off-TPU or for shapes the kernel
    rejects. ``use_flash`` overrides the gate; ``interpret`` passes
    through to the kernel unchanged (interpret=True also enables flash
    off-TPU, where the compiled kernel cannot run)."""
    b, L, nh, hd = q.shape
    nkv = k.shape[2]
    g = nh // nkv
    from rlo_tpu.pallas.flash import auto_block_q, can_flash
    # adaptive Q tile: the batch folds into the kernel's head grid, so
    # large batches mean many programs — bigger tiles claw back the
    # per-program overhead (the round-4 MFU-cliff mechanism; measured
    # bq 1024 = 1.14x bq 256 at 128 folded heads)
    bq = auto_block_q(g * L, L, hd)
    if use_flash is None:
        use_flash = (jax.default_backend() == "tpu"
                     or bool(interpret)) and can_flash(L, L, hd,
                                                       block_q=bq,
                                                       groups=g)
    if not use_flash:
        if g > 1:
            k = jnp.repeat(k, g, axis=2)
            v = jnp.repeat(v, g, axis=2)
        return jax.vmap(lambda q_, k_, v_: full_attention(
            q_, k_, v_, causal=True))(q, k, v)
    from rlo_tpu.pallas.flash import flash_attention

    def fold(t):
        n = t.shape[2]
        return t.transpose(1, 0, 2, 3).reshape(L, b * n, hd)

    out = flash_attention(fold(q), fold(k), fold(v), causal=True,
                          block_q=bq, interpret=interpret)
    return out.reshape(L, b, nh, hd).transpose(1, 0, 2, 3)


def apply_layer(x, layer: dict, cfg: TransformerConfig, *,
                sp_axis: Optional[str] = None,
                tp_axis: Optional[str] = None,
                tp_algorithm: str = "psum",
                ep_axis: Optional[str] = None,
                attention=None,
                pos: Optional[jax.Array] = None):
    """One transformer layer (attention + FFN sublayers) on activation
    ``x`` (b, blk, d). Returns (x, aux). The single source of the layer
    math — `forward` iterates it, the pipeline stage (models.pipeline)
    scans it, and the KV-cache decode (models.generate) calls it with a
    custom ``attention`` callable — so the block cannot silently
    diverge between them. ``attention(q, k, v)`` receives q as
    (b, blk, heads, head_dim) and k/v as (b, blk, KV_heads, head_dim)
    — fewer heads than q on GQA configs (the hook owns the grouping,
    so e.g. the decode cache stays compact) — and returns the q shape;
    None selects the training dispatch (local flash / ring / ulysses),
    which also attends the compact grouped K/V directly — no repeat
    is materialized anywhere on the training path."""
    b, blk, _ = x.shape
    dt = x.dtype
    ntp = lax.axis_size(tp_axis) if tp_axis is not None else 1
    assert cfg.n_heads % ntp == 0 and cfg.d_ff % ntp == 0, \
        f"tp={ntp} must divide n_heads {cfg.n_heads} and d_ff {cfg.d_ff}"
    nh_local = cfg.n_heads // ntp

    def tp_sum(t):
        if tp_axis is None:
            return t
        return tc.allreduce(t, tp_axis, algorithm=tp_algorithm).astype(
            t.dtype)

    h = _rmsnorm(x, layer["ln1"]["g"])
    if cfg.kv_heads == cfg.n_heads:
        w = layer["wqkv"].astype(dt)   # (d, 3, local heads x hd)
        qkv = h @ w.reshape(w.shape[0], -1)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        nkv_local = nh_local
    else:  # GQA
        assert cfg.kv_heads % ntp == 0, \
            f"tp={ntp} must divide n_kv_heads {cfg.kv_heads}"
        nkv_local = cfg.kv_heads // ntp
        q = h @ layer["wq"].astype(dt)
        wkv = layer["wkv"].astype(dt)
        kv = h @ wkv.reshape(wkv.shape[0], -1)
        k, v = jnp.split(kv, 2, axis=-1)

    def heads(t, n):
        return t.reshape(b, blk, n, cfg.head_dim)

    q = heads(q, nh_local)
    k, v = heads(k, nkv_local), heads(v, nkv_local)
    if cfg.pos_encoding == "rope":
        assert pos is not None, "rope needs per-layer positions"
        q = _rope(q, pos, cfg.rope_scaling, cfg.rope_scale)
        k = _rope(k, pos, cfg.rope_scaling, cfg.rope_scale)  # compact
        # k: pre-grouping (the hook/caches see rotated compact keys)

    # GQA K/V stay COMPACT on every dispatch path: the attention ops
    # attend grouped heads natively (the flash kernel folds the group
    # dim into its Q axis; ring rotates and ulysses all_to_alls only
    # kv_heads worth of bytes — the ICI/HBM reduction GQA exists for),
    # and a custom ``attention`` hook receives the compact heads so
    # the decode cache stores only kv_heads
    if attention is not None:
        att = attention(q, k, v)
    elif sp_axis is None:
        att = _local_attention(q, k, v)
    elif cfg.sp_attention == "ulysses":
        from rlo_tpu.ops.ulysses import ulysses_attention
        att = jax.vmap(lambda q_, k_, v_: ulysses_attention(
            q_, k_, v_, sp_axis, causal=True), in_axes=0)(q, k, v)
    elif cfg.sp_attention == "ring":
        att = jax.vmap(lambda q_, k_, v_: ring_attention(
            q_, k_, v_, sp_axis, causal=True), in_axes=0)(q, k, v)
    else:
        raise ValueError(
            f"unknown sp_attention {cfg.sp_attention!r}; "
            f"known: 'ring', 'ulysses'")
    att = att.reshape(b, blk, nh_local * cfg.head_dim)
    x = x + tp_sum(att @ layer["wo"].astype(dt))

    h = _rmsnorm(x, layer["ln2"]["g"])
    if cfg.n_experts > 0:
        ffn_out, aux = moe.moe_ffn(
            layer["moe"], h, cfg.n_experts,
            capacity_factor=cfg.capacity_factor, ep_axis=ep_axis)
        x = x + ffn_out
        return x, aux
    h = jax.nn.gelu(h @ layer["w1"].astype(dt))
    x = x + tp_sum(h @ layer["w2"].astype(dt))
    return x, jnp.zeros((), jnp.float32)


def next_token_targets(tokens):
    """Dense (non-sp) next-token labels: shift left, zero-pad, and mask
    each row's final position. Shared by loss_fn and the pipeline's
    last-stage loss."""
    b, blk = tokens.shape
    targets = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros((b, 1), tokens.dtype)], axis=1)
    valid = jnp.concatenate(
        [jnp.ones((b, blk - 1), jnp.float32),
         jnp.zeros((b, 1), jnp.float32)], axis=1)
    return targets, valid


def nll_sum(logits, targets, valid):
    """Summed masked next-token NLL and the valid-token count."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.sum(nll * valid), jnp.sum(valid)


def nll_sum_chunked(x, embed, targets, valid, chunk: int):
    """nll_sum computed from the PRE-HEAD activations with the vocab
    axis streamed in ``chunk``-wide slices: nll = logsumexp(x·Eᵀ) −
    x·E[target], with the logsumexp accumulated online (running
    max/sumexp — flash attention's softmax trick applied to the LM
    head). Neither the (b, blk, vocab) logits nor log-probs ever
    exist; jax.checkpoint on the chunk step makes the backward
    recompute each chunk's logits instead of saving them. Exact (same
    value as nll_sum up to fp accumulation order)."""
    v, d = embed.shape
    # operands in the activation dtype, f32 accumulation — the same
    # mixed precision as the unfused head matmul (bf16 on the MXU)
    xd = x
    ed = embed.astype(x.dtype)
    tgt_logit = jnp.einsum("btd,btd->bt", xd, ed[targets],
                           preferred_element_type=jnp.float32)
    n_chunks = -(-v // chunk)
    pad = n_chunks * chunk - v
    epad = jnp.pad(ed, ((0, pad), (0, 0)))
    echunks = epad.reshape(n_chunks, chunk, d)
    # padded rows would contribute exp(0·x)=1 to the sumexp: mask them
    row_ok = (jnp.arange(n_chunks * chunk) < v).reshape(n_chunks, chunk)
    b, blk = targets.shape
    m0 = jnp.full((b, blk), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((b, blk), jnp.float32)

    @jax.checkpoint
    def step(carry, ch):
        m, s = carry
        emb, ok = ch
        lg = jnp.einsum("btd,cd->btc", xd, emb,
                        preferred_element_type=jnp.float32)
        lg = jnp.where(ok[None, None, :], lg, -jnp.inf)
        m2 = jnp.maximum(m, lg.max(axis=-1))
        s = s * jnp.exp(m - m2) + jnp.exp(
            lg - m2[..., None]).sum(axis=-1)
        return (m2, s), None

    (m, s), _ = lax.scan(step, (m0, s0), (echunks, row_ok))
    lse = m + jnp.log(s)
    nll = lse - tgt_logit
    return jnp.sum(nll * valid), jnp.sum(valid)


def opt_state_pspecs(opt_state, params: dict, param_specs):
    """PartitionSpec tree for an optax optimizer state: subtrees shaped
    like the param tree (Adam moments etc.) inherit the params' specs —
    so tp/ep-sharded weights get sharded moments — and every other leaf
    (step counts, scalars) is replicated. Pass as the opt_state in/out
    spec for shard_jit alongside `param_pspecs`."""
    from jax.sharding import PartitionSpec as P
    pdef = jax.tree_util.tree_structure(params)

    def params_like(node):
        try:
            return jax.tree_util.tree_structure(node) == pdef
        except Exception:
            return False

    return jax.tree_util.tree_map(
        lambda n: param_specs if params_like(n) else P(),
        opt_state, is_leaf=params_like)


def forward(params: dict, tokens: jax.Array, cfg: TransformerConfig,
            sp_axis: Optional[str] = None,
            tp_axis: Optional[str] = None,
            tp_algorithm: str = "psum",
            ep_axis: Optional[str] = None,
            with_aux: bool = False):
    """Logits for next-token prediction; causal. Returns logits, or
    (logits, aux_loss) when ``with_aux`` (MoE load-balancing term; 0 for
    dense configs).

    tokens: (batch, block) int32 — `block` is the LOCAL sequence slice
    when sp_axis is set (shard r holds tokens [r*block, (r+1)*block)).

    With ``tp_axis`` the layer weights arrive sharded per `param_pspecs`:
    this device computes its n_heads/tp heads and d_ff/tp hidden units,
    and the row-parallel output projections produce partial sums that
    are combined with the framework allreduce (``tp_algorithm`` picks
    psum / ring / recursive_doubling / halving_doubling). With
    ``ep_axis`` (MoE configs) the per-expert FFN weights arrive sharded
    by expert, and tokens cross shards via all_to_all (models.moe).
    """
    x, aux_total = _features(params, tokens, cfg, sp_axis, tp_axis,
                             tp_algorithm, ep_axis)
    dt = cfg.act_dtype
    logits = (x @ params["embed"].T.astype(dt)).astype(jnp.float32)
    if with_aux:
        return logits, aux_total
    return logits


def _features(params: dict, tokens: jax.Array, cfg: TransformerConfig,
              sp_axis: Optional[str] = None,
              tp_axis: Optional[str] = None,
              tp_algorithm: str = "psum",
              ep_axis: Optional[str] = None):
    """The transformer body up to (and including) the final norm:
    (b, blk, d) pre-head activations + the MoE aux loss. Split out of
    `forward` so the chunked loss can apply the LM head per vocab
    slice (nll_sum_chunked) instead of materializing full logits."""
    b, blk = tokens.shape
    dt = cfg.act_dtype
    if sp_axis is not None:
        pos0 = lax.axis_index(sp_axis) * blk
    else:
        pos0 = 0
    pos = pos0 + jnp.arange(blk)

    x = embed_tokens(params["embed"], tokens, pos, cfg)
    aux_total = jnp.zeros((), jnp.float32)

    def block(x, layer):
        return apply_layer(x, layer, cfg, sp_axis=sp_axis,
                           tp_axis=tp_axis, tp_algorithm=tp_algorithm,
                           ep_axis=ep_axis, pos=pos)

    if cfg.remat:
        # recompute each layer's activations in the backward pass
        block = jax.checkpoint(block)
    for layer in params["layers"]:
        x, aux = block(x, layer)
        aux_total = aux_total + aux

    return _rmsnorm(x, params["ln_f"]["g"]), aux_total


def loss_fn(params: dict, tokens: jax.Array, cfg: TransformerConfig,
            sp_axis: Optional[str] = None,
            tp_axis: Optional[str] = None,
            ep_axis: Optional[str] = None) -> jax.Array:
    """Mean next-token cross-entropy (+ the MoE load-balancing aux term
    for expert configs). With sp sharding, the label for a shard's last
    position is the next shard's first token — one ppermute — and the
    final global position is masked out."""
    x, aux = _features(params, tokens, cfg, sp_axis, tp_axis,
                       ep_axis=ep_axis)
    b, blk = tokens.shape
    if sp_axis is None:
        targets, valid = next_token_targets(tokens)
    else:
        ws = lax.axis_size(sp_axis)
        idx = lax.axis_index(sp_axis)
        # shard r receives shard (r+1)'s first column: ppermute r+1 -> r
        nxt_first = lax.ppermute(tokens[:, :1], sp_axis,
                                 list(topology.ring_perm(ws, -1)))
        targets = jnp.concatenate([tokens[:, 1:], nxt_first], axis=1)
        is_last_shard = (idx == ws - 1)
        valid = jnp.concatenate(
            [jnp.ones((b, blk - 1), jnp.float32),
             jnp.where(is_last_shard, 0.0, 1.0) * jnp.ones(
                 (b, 1), jnp.float32)], axis=1)
    chunk = cfg.loss_vocab_chunk or 0
    if chunk:
        local, count = nll_sum_chunked(x, params["embed"], targets,
                                       valid, chunk)
    else:
        logits = (x @ params["embed"].T.astype(cfg.act_dtype)) \
            .astype(jnp.float32)
        local, count = nll_sum(logits, targets, valid)
    if sp_axis is not None:
        local = lax.psum(local, sp_axis)
        count = lax.psum(count, sp_axis)
    loss = local / count
    if cfg.n_experts > 0:
        if sp_axis is not None:
            # each sp shard routed its own token slice: average the
            # local aux terms so the total loss is sp-invariant like
            # the cross-entropy term
            aux = lax.pmean(aux, sp_axis)
        loss = loss + cfg.moe_aux_coef * aux
    return loss


def _vma_active(axis: str) -> bool:
    """Whether varying-manual-axes typing is live for ``axis``.

    Probed by pcasting a fresh scalar to varying: under check_vma=True
    the result's vma contains the axis; under check_vma=False `.vma` is
    an empty frozenset for EVERYTHING — which must not be mistaken for
    'already reduced'."""
    try:
        probe = lax.pcast(jnp.zeros(()), (axis,), to="varying")
        return axis in jax.typeof(probe).vma
    except (AttributeError, TypeError, ValueError):
        return False


def train_step(params: dict, tokens: jax.Array, cfg: TransformerConfig,
               lr: float = 1e-2, sp_axis: Optional[str] = None,
               dp_axis: Optional[str] = None,
               tp_axis: Optional[str] = None,
               ep_axis: Optional[str] = None,
               grad_algorithm: str = "psum",
               dcn_axis: Optional[str] = None,
               dcn_algorithm: str = "psum"):
    """One SGD step; returns (new_params, loss). Run under shard_jit
    (check_vma=True by default).

    Gradient synchronization. Under varying-manual-axes typing, the
    reductions of replicated-param grads over sp, tp, AND dp are
    inserted by shard_map's AD itself (lowering to XLA AllReduce — the
    optimal 2(n-1)/n schedule; grads of tp-sharded matrices stay local,
    as they must); this function then only rescales by the dp size.
    The EXPLICIT framework combine — grad_algorithm='ring': ppermute
    ring with the Pallas fused per-step combine, the BASELINE benchmark
    path — engages on a pure-dp mesh under shard_jit(...,
    check_vma=False), where per-shard grads are well-defined without vma
    bookkeeping (no collective appears in the forward). A manual-ring
    result cannot be typed invariant under vma (only psum is), so vma
    runs route dp through the automatic path regardless of
    grad_algorithm.
    """
    loss, grads = grads_and_loss(params, tokens, cfg, sp_axis=sp_axis,
                                 dp_axis=dp_axis, tp_axis=tp_axis,
                                 ep_axis=ep_axis,
                                 grad_algorithm=grad_algorithm,
                                 dcn_axis=dcn_axis,
                                 dcn_algorithm=dcn_algorithm)
    new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    return new_params, loss


def grads_and_loss(params: dict, tokens: jax.Array,
                   cfg: TransformerConfig,
                   sp_axis: Optional[str] = None,
                   dp_axis: Optional[str] = None,
                   tp_axis: Optional[str] = None,
                   ep_axis: Optional[str] = None,
                   grad_algorithm: str = "psum",
                   dcn_axis: Optional[str] = None,
                   dcn_algorithm: str = "psum"):
    """(loss, fully-synchronized grads) — the shared gradient pipeline
    behind train_step (plain SGD) and train_step_optax.

    ``dcn_axis``: second, slower data-parallel tier (multi-slice DP,
    one mesh axis per make_multislice_mesh). On the explicit combine
    path the dp gradient sync becomes
    tpu_collectives.hierarchical_allreduce — reduce-scatter in-slice,
    cross-slice allreduce on only the scattered shard, all-gather
    in-slice — so per-chip DCN bytes shrink by the in-slice dp size.
    Under vma typing, AD inserts the (already hierarchical-aware) XLA
    AllReduce over both axes and only the rescale differs."""
    if sp_axis is not None or tp_axis is not None or ep_axis is not None:
        # without vma typing the sp/tp/ep cotangent reductions never
        # happen and every shard would silently take a different step
        assert _vma_active(sp_axis or tp_axis or ep_axis), (
            "sp/tp/ep training requires shard_jit's vma typing "
            "(check_vma=True); only the pure-dp explicit-ring path may "
            "run with check_vma=False")
    loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg, sp_axis,
                                              tp_axis, ep_axis)
    if dcn_axis is not None and dp_axis is None:
        raise ValueError("dcn_axis requires dp_axis (it is the second, "
                         "cross-slice tier of data parallelism)")
    if dp_axis is not None:
        n = lax.axis_size(dp_axis)
        if dcn_axis is not None:
            n *= lax.axis_size(dcn_axis)
        if _vma_active(dp_axis):
            if dcn_axis is not None and dcn_algorithm != "psum":
                # unlike grad_algorithm (whose vma fallback is also
                # psum-shaped, just XLA's own), a silently-dropped
                # int8 request means the user believes DCN traffic is
                # compressed when it is not — refuse instead
                raise ValueError(
                    f"dcn_algorithm={dcn_algorithm!r} requires the "
                    f"explicit combine path: run under "
                    f"shard_jit(..., check_vma=False); the vma path's "
                    f"AD-inserted AllReduce cannot be compressed")
            # vma AD already summed grads over dp (and dcn); rescale
            grads = jax.tree.map(lambda g: g / n, grads)
        elif dcn_axis is not None:
            # two-tier explicit combine: in-slice RS, DCN allreduce of
            # the scattered shard only, in-slice AG
            grads = jax.tree.map(
                lambda g: tc.hierarchical_allreduce(
                    g, dp_axis, dcn_axis,
                    dcn_algorithm=dcn_algorithm) / n,
                grads)
        else:
            # explicit framework combine of per-shard grads
            grads = jax.tree.map(
                lambda g: tc.allreduce(g, dp_axis,
                                       algorithm=grad_algorithm) / n,
                grads)
        loss = lax.pmean(loss, dp_axis)
        if dcn_axis is not None:
            loss = lax.pmean(loss, dcn_axis)
    if ep_axis is not None:
        # ep is a second data axis: tokens are sharded over it, so the
        # (vma-inserted) cross-shard grad sums — psum for replicated
        # params, the all_to_all transpose for expert weights — need the
        # same 1/n rescale as dp, and the local losses average
        nep = lax.axis_size(ep_axis)
        grads = jax.tree.map(lambda g: g / nep, grads)
        loss = lax.pmean(loss, ep_axis)
    return loss, grads


def train_step_optax(params: dict, opt_state, tokens: jax.Array,
                     cfg: TransformerConfig, optimizer,
                     sp_axis: Optional[str] = None,
                     dp_axis: Optional[str] = None,
                     tp_axis: Optional[str] = None,
                     ep_axis: Optional[str] = None,
                     grad_algorithm: str = "psum",
                     dcn_axis: Optional[str] = None,
                     dcn_algorithm: str = "psum"):
    """One optimizer step with any optax GradientTransformation
    (`optimizer.init(params)` builds opt_state); returns
    (new_params, new_opt_state, loss). Optimizer state mirrors the
    param tree, so tp/ep-sharded leaves carry sharded moments — the
    update math is elementwise and runs shard-local.
    """
    import optax

    loss, grads = grads_and_loss(params, tokens, cfg, sp_axis=sp_axis,
                                 dp_axis=dp_axis, tp_axis=tp_axis,
                                 ep_axis=ep_axis,
                                 grad_algorithm=grad_algorithm,
                                 dcn_axis=dcn_axis,
                                 dcn_algorithm=dcn_algorithm)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, loss
