"""KV-cache autoregressive generation for the flagship transformer.

The training side (models.transformer) recomputes full attention every
step; generation wants O(1) work per new token: each layer's keys and
values are cached HEAD-LEADING, SEQ-MINOR at (batch, kv_heads,
head_dim, max_len) — kv_heads < n_heads for GQA configs, and the
sequence-minor trailing dim streams HBM tiles at full 128-lane width
(see init_kv_cache; head_dim-minor measured half the bandwidth) — and
a decode step attends the
single new query against the cache prefix (grouped, never repeated).
Shapes stay STATIC (the cache is allocated at max_len up front and
masked by the traced position) so the whole generate loop is one
`lax.scan` inside one jit — XLA-friendly control flow, no per-token
retrace.

Scope: dense and MoE decode, single-device or tensor-parallel
(decode_step/generate take tp_axis inside shard_map: sharded params
per param_pspecs, sharded cache per kv_cache_pspecs; MoE experts can
shard over ep_axis). Sampling is greedy or temperature-softmax. The
math mirrors apply_layer exactly — rmsnorm/qkv/attention/wo/ffn with
the same weights — pinned by a logits-parity test against the training
`forward` at every generated position (tests/test_generate.py).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from rlo_tpu.models.transformer import (TransformerConfig, apply_layer,
                                        embed_tokens, _rmsnorm)
from rlo_tpu.ops.ring_attention import _NEG


def init_kv_cache(cfg: TransformerConfig, batch: int, max_len: int,
                  tp_axis: Optional[str] = None):
    """Zeroed per-layer K/V cache: a list of {"k","v"} arrays shaped
    (batch, kv_heads, head_dim, max_len) in the activation dtype —
    SEQUENCE-MINOR. The minor dimension is what HBM tiles pad to the
    128-lane width: the previous (…, max_len, head_dim) layout put
    head_dim=64 in the lanes and measured HALF the deliverable cache
    bandwidth (365 vs 703 GB/s at identical bytes,
    benchmarks/attend_sweep.py, 2026-07-31) because every (16, 128)
    bf16 tile was half padding. max_len is >= 128 in any real serving
    config, so the seq-minor layout streams at full width; the
    flash-decode kernel's dots contract head_dim as the sublane axis,
    which is the MXU-native (d, L) matmul orientation anyway. GQA
    configs (n_kv_heads < n_heads) store only the K/V heads, the
    n_heads/kv_heads memory win that motivates GQA. Inside shard_map
    with ``tp_axis``, each shard allocates only its kv_heads/tp local
    heads (matching apply_layer's column-parallel K/V projections).

    ``cfg.kv_cache_dtype='int8'``: entries are int8 with per-(batch,
    head, position) f32 scale sidecars ``ks``/``vs`` — half the bf16
    cache's bytes in HBM; the dequant folds into the attend's score /
    probability tensors so the cache reads stay int8 on the wire."""
    ntp = lax.axis_size(tp_axis) if tp_axis is not None else 1
    assert cfg.kv_heads % ntp == 0
    kvh = cfg.kv_heads // ntp
    if jax.default_backend() == "tpu":
        # round the seq axis up to the 128-lane tile: a non-multiple
        # max_len makes EVERY pallas call pad the whole cache (16
        # materialized pad ops per step at plen 1024 — measured); the
        # tail is position-masked everywhere, so +<=127 slots is
        # semantics-free and removes the pads
        max_len = -(-max_len // 128) * 128
    shape = (batch, kvh, cfg.head_dim, max_len)
    # DISTINCT buffers per entry: sharing one zeros array across k/v/
    # layers breaks donation ("attempt to donate the same buffer
    # twice") for any jit that takes the cache donated (serve.py's
    # round, capacity probes)
    if cfg.kv_cache_dtype == "int8":
        return [{"k": jnp.zeros(shape, jnp.int8),
                 "v": jnp.zeros(shape, jnp.int8),
                 "ks": jnp.zeros((batch, kvh, max_len), jnp.float32),
                 "vs": jnp.zeros((batch, kvh, max_len), jnp.float32)}
                for _ in range(cfg.n_layers)]
    if cfg.kv_cache_dtype is not None:
        raise ValueError(
            f"unknown kv_cache_dtype {cfg.kv_cache_dtype!r}")
    return [{"k": jnp.zeros(shape, cfg.act_dtype),
             "v": jnp.zeros(shape, cfg.act_dtype)}
            for _ in range(cfg.n_layers)]


def kv_cache_pspecs(cfg: TransformerConfig,
                    tp_axis: Optional[str] = None):
    """PartitionSpec tree matching init_kv_cache output: the K/V head
    axis shards over ``tp_axis`` (like the wkv projections in
    param_pspecs); batch/positions replicated. Pass as the cache
    in/out spec for shard_jit'd decode."""
    from jax.sharding import PartitionSpec as P
    spec = P(None, tp_axis, None, None)
    if cfg.kv_cache_dtype == "int8":
        sspec = P(None, tp_axis, None)
        return [{"k": spec, "v": spec, "ks": sspec, "vs": sspec}
                for _ in range(cfg.n_layers)]
    return [{"k": spec, "v": spec} for _ in range(cfg.n_layers)]


def _quantize_kv(x):
    """(..., head_dim) -> (int8 values, f32 scale over the last axis).
    Symmetric per-(batch, position, head) quantization: scale =
    amax/127, so dequant error is at most scale/2 per element."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, jnp.float32(1e-30)) / 127.0
    q = jnp.round(xf / scale[..., None]).astype(jnp.int8)
    return q, scale


def _decode_cfg(cfg: TransformerConfig) -> TransformerConfig:
    """Decode-time config: MoE routing is DROP-FREE (capacity >= the
    tokens in one step). Training-time capacity dropping is inherently
    order-dependent across the flattened token axis (moe.moe_ffn's
    cumsum queue), i.e. not causal — so decode routes every token to
    its argmax expert and parity with the training forward holds
    exactly when the forward drops nothing (capacity_factor >=
    n_experts guarantees that)."""
    if cfg.n_experts == 0:
        return cfg
    return dataclasses.replace(
        cfg, capacity_factor=max(cfg.capacity_factor,
                                 float(cfg.n_experts)))


def _attend_cache(q, k_cache, v_cache, pos, scale,
                  k_scale=None, v_scale=None, use_flash=None):
    """q (b, 1, H, hd) against the cache prefix [0, pos]: full-length
    matmul over the static cache, masked beyond the position. ``pos``
    is a scalar (all rows at the same position) or a (b,) vector
    (ragged decode: each row masks at its own position). The cache
    may hold fewer (grouped) K/V heads: each group of H/kv_heads
    query heads attends its shared K/V head directly — no repeat is
    ever materialized.

    Quantized caches (cfg.kv_cache_dtype='int8') pass per-(batch,
    head, position) ``k_scale``/``v_scale`` (b, kv_heads, max_len):
    the dequant is FOLDED into the score and probability tensors —
    scores scale per key position, probabilities pre-multiply the
    value scale — so the (b, kv, hd, max_len) cache operands enter
    their matmuls as stored int8 and the big HBM reads stay 1
    byte/element."""
    b, one, nh, hd = q.shape
    nkv, max_len = k_cache.shape[1], k_cache.shape[3]
    if use_flash is None:
        from rlo_tpu.pallas.decode import can_flash_decode
        use_flash = (jax.default_backend() == "tpu"
                     and can_flash_decode(max_len, hd))
    if use_flash:
        # fused decode attention: cache tiles stream through VMEM
        # (int8 tiles dequantize there — the einsum path measured XLA
        # materializing the dequant at batch 32), online softmax, one
        # pass — rlo_tpu.pallas.decode
        from rlo_tpu.pallas.decode import flash_decode
        return flash_decode(q, k_cache, v_cache, pos, scale,
                            k_scale, v_scale)
    # the einsum path IS the T=1 case of the block attend — one
    # implementation, so a dequant/mask/dtype fix can never diverge
    # decode_step from block_decode (speculative decoding's
    # losslessness rides on their agreement)
    posv = jnp.asarray(pos, jnp.int32)
    pos_q = (jnp.full((b, 1), posv) if posv.ndim == 0
             else posv.reshape(b, 1))
    return _attend_cache_block(q, k_cache, v_cache, pos_q, scale,
                               k_scale=k_scale, v_scale=v_scale)


def _attend_cache_block(q, k_cache, v_cache, pos_q, scale,
                        k_scale=None, v_scale=None, pos0=None,
                        use_flash=None):
    """Block variant of the cache attend: q (b, T, nh, hd) where query
    i of row b sits at position pos_q[b, i] and attends cache
    positions <= pos_q[b, i]. Because the block's own K/V rows are
    written into the cache BEFORE attending (write-then-attend, as in
    decode_step), that single mask covers in-block causality too.
    Used by the speculative-decoding verify step (T = gamma tokens
    through the target in ONE forward); T=1 recovers decode_step's
    attend shape.

    ``pos0`` (b,) asserts the positions are CONTIGUOUS per row
    (pos_q[b, i] == pos0[b] + i) — a static property of the caller,
    not checkable on traced values — which enables the fused
    flash-block path on TPU: the SAME kernel family decode_step's
    attend uses (T=1), so speculative verify logits and plain decode
    logits share numerics (losslessness of greedy speculative decoding
    needs their argmaxes to agree)."""
    b, T, nh, hd = q.shape
    nkv, max_len = k_cache.shape[1], k_cache.shape[3]
    if use_flash is None:
        from rlo_tpu.pallas.decode import (_block_fits_vmem,
                                           can_flash_decode)
        itemsize = 4 if k_cache.dtype == jnp.float32 else 2
        gate = (pos0 is not None
                and jax.default_backend() == "tpu"
                and can_flash_decode(max_len, hd))
        fits = gate and _block_fits_vmem(max_len, hd, nkv, nh // nkv,
                                         T, itemsize)
        if gate and not fits:
            # T=1 would flash but this block cannot share its tiling:
            # the einsum fallback DIVERGES numerically from the flash
            # decode step, so speculative greedy parity degrades to
            # near-tie class in this regime — warn, don't hide it
            import warnings
            warnings.warn(
                f"block attend T={T} exceeds the VMEM budget at the "
                f"T=1 flash tiling (nkv={nkv}, head_dim={hd}, "
                f"max_len={max_len}); falling back to einsum — verify "
                f"numerics will NOT match the flash decode step "
                f"(use a smaller gamma for exact speculative parity)",
                RuntimeWarning, stacklevel=2)
        use_flash = fits
    if use_flash:
        from rlo_tpu.pallas.decode import flash_block_decode
        return flash_block_decode(q, k_cache, v_cache, pos0, scale,
                                  k_scale, v_scale)
    rep = nh // nkv
    qg = q.reshape(b, T, nkv, rep, hd)
    cache_dt = jnp.bfloat16 if (k_scale is not None and
                                jax.default_backend() == "tpu") \
        else jnp.float32
    s = jnp.einsum("bqgrd,bgdk->bgrqk", qg.astype(cache_dt),
                   k_cache.astype(cache_dt),
                   preferred_element_type=jnp.float32) * scale
    s = s.astype(jnp.float32)
    if k_scale is not None:
        s = s * k_scale[:, :, None, None, :]
    mask = jnp.arange(max_len)[None, None, :] <= pos_q[:, :, None]
    s = jnp.where(mask[:, None, None, :, :], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    if v_scale is not None:
        p = p * v_scale[:, :, None, None, :]
    out = jnp.einsum("bgrqk,bgdk->bqgrd", p.astype(cache_dt),
                     v_cache.astype(cache_dt),
                     preferred_element_type=jnp.float32)
    return out.astype(jnp.float32).reshape(b, T, nh, hd)


def decode_step(params: dict, token, pos, cache, cfg: TransformerConfig,
                tp_axis: Optional[str] = None,
                ep_axis: Optional[str] = None) -> Tuple[jax.Array, list]:
    """One token (b,) int32 at position ``pos`` through all layers
    using the K/V cache. Returns (logits (b, vocab) f32, new cache).
    The layer math IS apply_layer (single source); only the attention
    is swapped for the cache-attend via its ``attention`` hook.

    ``pos`` is a scalar (every row at the same position) or a (b,)
    int32 vector — RAGGED decode: each row writes its cache slot and
    masks its attention at its own position (per-row rotary/sincos
    positions included).

    ``tp_axis`` (inside shard_map): tensor-parallel decode — params
    arrive sharded per param_pspecs, the cache per kv_cache_pspecs;
    each shard attends its local (kv-)heads and the row-parallel
    output projections combine with the framework allreduce, exactly
    like training. MoE configs route drop-free (see _decode_cfg);
    ``ep_axis`` shards the experts with all_to_all dispatch."""
    cfg = _decode_cfg(cfg)
    dt = cfg.act_dtype
    posv = jnp.asarray(pos)
    ragged = posv.ndim == 1
    b = token.shape[0]
    # (1,) shared positions, or (b, 1) per-row, for embed/rope
    pos_arr = posv[:, None] if ragged else posv[None]
    x = embed_tokens(params["embed"], token[:, None], pos_arr, cfg)
    scale = 1.0 / (cfg.head_dim ** 0.5)
    new_cache = []
    for layer, lc in zip(params["layers"], cache):
        def attend(q, k, v, lc=lc):
            # rope configs: q/k arrive rotated from apply_layer; keys
            # are cached rotated (standard RoPE decode). k/v arrive
            # (b, 1, kvh, hd); the cache is head-leading — transpose
            # the new entry to (b, kvh, hd) rows
            quant = "ks" in lc
            k_row, v_row = k[:, 0], v[:, 0]          # (b, kvh, hd)
            if quant:  # int8 cache: quantize the new entry at append
                k_row, ks_new = _quantize_kv(k_row)
                v_row, vs_new = _quantize_kv(v_row)
                store_dt = jnp.int8
            else:
                store_dt = dt
            from rlo_tpu.pallas.decode import (can_write_row,
                                               write_kv_row)
            max_len_c = lc["k"].shape[3]
            if (jax.default_backend() == "tpu"
                    and can_write_row(max_len_c)):
                # aliased pallas write: an XLA lane-offset DUS makes
                # layout assignment transpose the cache and copy it
                # back for the flash kernel every step (~2 ms/step at
                # plen 1024 — see write_kv_row)
                kc = write_kv_row(lc["k"], k_row, posv)
                vc = write_kv_row(lc["v"], v_row, posv)
            elif ragged:
                rows = jnp.arange(b)
                heads = jnp.arange(lc["k"].shape[1])
                # seq-minor: the new row lands in ONE lane per
                # (b, head, dim) — idx over the last axis
                dims = jnp.arange(lc["k"].shape[2])
                idx = (rows[:, None, None], heads[None, :, None],
                       dims[None, None, :], posv[:, None, None])
                kc = lc["k"].at[idx].set(k_row.astype(store_dt))
                vc = lc["v"].at[idx].set(v_row.astype(store_dt))
            else:
                kc = lax.dynamic_update_slice(
                    lc["k"], k_row[..., None].astype(store_dt),
                    (0, 0, 0, pos))
                vc = lax.dynamic_update_slice(
                    lc["v"], v_row[..., None].astype(store_dt),
                    (0, 0, 0, pos))
            entry = {"k": kc, "v": vc}
            ks = vs = None
            if quant:
                if (jax.default_backend() == "tpu"
                        and can_write_row(max_len_c)):
                    # the scale sidecars are seq-minor too — a lane-
                    # offset DUS would reintroduce the layout-war
                    # copies; view (b, kvh, L) as (b, kvh, 1, L) (a
                    # free reshape) and ride the same aliased kernel
                    ks = write_kv_row(lc["ks"][:, :, None, :],
                                      ks_new[:, :, None],
                                      posv)[:, :, 0, :]
                    vs = write_kv_row(lc["vs"][:, :, None, :],
                                      vs_new[:, :, None],
                                      posv)[:, :, 0, :]
                elif ragged:
                    rows = jnp.arange(b)
                    heads = jnp.arange(lc["k"].shape[1])
                    sidx = (rows[:, None], heads[None, :],
                            posv[:, None])
                    ks = lc["ks"].at[sidx].set(ks_new)
                    vs = lc["vs"].at[sidx].set(vs_new)
                else:
                    ks = lax.dynamic_update_slice(
                        lc["ks"], ks_new[:, :, None], (0, 0, pos))
                    vs = lax.dynamic_update_slice(
                        lc["vs"], vs_new[:, :, None], (0, 0, pos))
                entry.update(ks=ks, vs=vs)
            new_cache.append(entry)
            return _attend_cache(q, kc, vc, posv, scale,
                                 k_scale=ks, v_scale=vs).astype(dt)

        x, _ = apply_layer(x, layer, cfg, attention=attend,
                           tp_axis=tp_axis, ep_axis=ep_axis,
                           pos=pos_arr)
    x = _rmsnorm(x, params["ln_f"]["g"])
    logits = (x[:, 0, :] @ params["embed"].T.astype(dt)) \
        .astype(jnp.float32)
    return logits, new_cache


def block_decode(params: dict, tokens, pos0, cache,
                 cfg: TransformerConfig,
                 tp_axis: Optional[str] = None,
                 ep_axis: Optional[str] = None):
    """Process T tokens (b, T) through the cache in ONE forward: row
    b's token i sits at position pos0[b] + i. Returns
    (logits (b, T, vocab) f32, cache). The verify step of speculative
    decoding (the target judges all gamma draft tokens at once); also
    a building block for chunked cache extension. Write-then-attend
    with per-(row, i) masks, so rejected drafts' cache entries are
    simply garbage beyond the accepted position — masked out and
    overwritten by later writes, exactly like ragged decode."""
    cfg = _decode_cfg(cfg)
    dt = cfg.act_dtype
    b, T = tokens.shape
    pos0 = jnp.asarray(pos0, jnp.int32).reshape(b)
    pos_arr = pos0[:, None] + jnp.arange(T, dtype=jnp.int32)  # (b, T)
    x = embed_tokens(params["embed"], tokens, pos_arr, cfg)
    scale = 1.0 / (cfg.head_dim ** 0.5)
    new_cache = []
    for layer, lc in zip(params["layers"], cache):
        def attend(q, k, v, lc=lc):
            quant = "ks" in lc
            kt = k.transpose(0, 2, 1, 3)           # (b, kvh, T, hd)
            vt = v.transpose(0, 2, 1, 3)
            if quant:  # quantize over hd BEFORE the seq-minor flip
                kt, ks_new = _quantize_kv(kt)
                vt, vs_new = _quantize_kv(vt)
                store_dt = jnp.int8
            else:
                store_dt = dt
            kt = kt.transpose(0, 1, 3, 2)          # (b, kvh, hd, T)
            vt = vt.transpose(0, 1, 3, 2)
            kvh = lc["k"].shape[1]
            from rlo_tpu.pallas.decode import (can_write_block,
                                               write_kv_block)
            use_wb = (jax.default_backend() == "tpu"
                      and can_write_block(lc["k"].shape[3])
                      and T <= 128)
            if use_wb:
                # the XLA lane-index scatter lowers to a generic
                # scatter measured ~1.2 ms PER VERIFY at batch 1
                # (block_decode 1.65 ms vs 0.46 ms decode step) —
                # the aliased pallas block write replaces it
                kc = write_kv_block(lc["k"], kt.astype(store_dt),
                                    pos0)
                vc = write_kv_block(lc["v"], vt.astype(store_dt),
                                    pos0)
            else:
                rows = jnp.arange(b)[:, None, None, None]
                heads = jnp.arange(kvh)[None, :, None, None]
                dims = jnp.arange(lc["k"].shape[2])[None, None, :,
                                                    None]
                posw = pos_arr[:, None, None, :]   # (b, 1, 1, T)
                kc = lc["k"].at[rows, heads, dims, posw].set(
                    kt.astype(store_dt))
                vc = lc["v"].at[rows, heads, dims, posw].set(
                    vt.astype(store_dt))
            entry = {"k": kc, "v": vc}
            ks = vs = None
            if quant:
                if use_wb:
                    # sidecars (b, kvh, L) ride the same kernel via
                    # the free (b, kvh, 1, L) view
                    ks = write_kv_block(lc["ks"][:, :, None, :],
                                        ks_new[:, :, None, :],
                                        pos0)[:, :, 0, :]
                    vs = write_kv_block(lc["vs"][:, :, None, :],
                                        vs_new[:, :, None, :],
                                        pos0)[:, :, 0, :]
                else:
                    # scale sidecars stay (b, kvh, L): 3-D scatter
                    r3 = jnp.arange(b)[:, None, None]
                    h3 = jnp.arange(kvh)[None, :, None]
                    p3 = pos_arr[:, None, :]       # (b, 1, T)
                    ks = lc["ks"].at[r3, h3, p3].set(ks_new)
                    vs = lc["vs"].at[r3, h3, p3].set(vs_new)
                entry.update(ks=ks, vs=vs)
            new_cache.append(entry)
            return _attend_cache_block(q, kc, vc, pos_arr, scale,
                                       k_scale=ks, v_scale=vs,
                                       pos0=pos0).astype(dt)

        x, _ = apply_layer(x, layer, cfg, attention=attend,
                           tp_axis=tp_axis, ep_axis=ep_axis,
                           pos=pos_arr)
    x = _rmsnorm(x, params["ln_f"]["g"])
    logits = jnp.einsum("btd,vd->btv", x,
                        params["embed"].astype(dt)
                        ).astype(jnp.float32)
    return logits, new_cache


def prefill(params: dict, tokens, cache, cfg: TransformerConfig,
            tp_axis: Optional[str] = None,
            ep_axis: Optional[str] = None,
            last_index=None):
    """Fill the cache with the whole prompt in ONE forward pass.
    Returns (logits of the last prompt position, filled cache).
    ``last_index`` (b,) selects a PER-ROW logits position instead of
    the final one (ragged prompts: row i's prompt ends at
    last_index[i]; positions beyond it hold padding whose cache
    entries are never attended — decode masks at the row's own
    position and overwrites them in order).
    MoE prompts route with the TRAINING capacity semantics (the whole
    prompt is one token set — exact forward parity); decode steps then
    route drop-free (_decode_cfg). RAGGED MoE prompts instead route
    DROP-FREE too: the training-capacity cumsum queue runs over the
    whole flattened padded token set, so padding would consume expert
    capacity and displace real tokens — drop-free routing makes
    padding inert, and per-row parity with the dense generate then
    holds exactly when the dense forward drops nothing (the same
    capacity_factor >= n_experts condition as decode).

    The prompt is a causal prefix, so causal attention over the prompt
    block IS attention against the (empty-beyond-it) cache — one
    batched forward through the flash kernel (apply_layer's training
    dispatch) replaces plen serial decode steps. The attention hook
    stashes each layer's COMPACT K/V block into the cache on the way
    through (rope keys are cached rotated, exactly like decode_step).
    Logits-parity with the one-token-at-a-time scan is pinned in
    tests/test_generate.py (exactly for plain caches; quantized
    caches attend the DEQUANTIZED block — the same values decode
    reads back — so the parity is within matmul association error,
    not the quantization envelope); measured ~two orders of magnitude
    faster at plen 1024 on the v5e chip (decode_bench.py --ttft).
    """
    b, plen = tokens.shape
    if last_index is not None:
        cfg = _decode_cfg(cfg)  # ragged MoE: padding must be inert
    dt = cfg.act_dtype
    pos = jnp.arange(plen)
    x = embed_tokens(params["embed"], tokens, pos, cfg)
    new_cache = []
    for layer, lc in zip(params["layers"], cache):
        def attend(q, k, v, lc=lc):
            # k/v arrive (b, plen, kvh, hd); the cache is head-leading
            # and SEQ-MINOR: (b, kvh, hd, plen)
            kt = k.transpose(0, 2, 1, 3)             # (b, kvh, plen, hd)
            vt = v.transpose(0, 2, 1, 3)
            if "ks" in lc:  # int8 cache: quantize the whole block
                qk, ks = _quantize_kv(kt)
                qv, vs = _quantize_kv(vt)
                new_cache.append({
                    "k": lax.dynamic_update_slice(
                        lc["k"], qk.transpose(0, 1, 3, 2),
                        (0, 0, 0, 0)),
                    "v": lax.dynamic_update_slice(
                        lc["v"], qv.transpose(0, 1, 3, 2),
                        (0, 0, 0, 0)),
                    "ks": lax.dynamic_update_slice(lc["ks"], ks,
                                                   (0, 0, 0)),
                    "vs": lax.dynamic_update_slice(lc["vs"], vs,
                                                   (0, 0, 0))})
                # attend the DEQUANTIZED block: the prompt K/V the
                # prefill logits see must be the values decode will
                # read back from the cache, or the blockwise prefill
                # and the decode-step scan diverge by the quantization
                # envelope on quantized configs
                k = (qk.astype(jnp.float32) * ks[..., None]) \
                    .transpose(0, 2, 1, 3).astype(dt)
                v = (qv.astype(jnp.float32) * vs[..., None]) \
                    .transpose(0, 2, 1, 3).astype(dt)
            else:
                new_cache.append({
                    "k": lax.dynamic_update_slice(
                        lc["k"], kt.transpose(0, 1, 3, 2).astype(dt),
                        (0, 0, 0, 0)),
                    "v": lax.dynamic_update_slice(
                        lc["v"], vt.transpose(0, 1, 3, 2).astype(dt),
                        (0, 0, 0, 0))})
            from rlo_tpu.models.transformer import _local_attention
            return _local_attention(q, k, v).astype(dt)

        x, _ = apply_layer(x, layer, cfg, attention=attend,
                           tp_axis=tp_axis, ep_axis=ep_axis, pos=pos)
    x = _rmsnorm(x, params["ln_f"]["g"])
    if last_index is None:
        xl = x[:, -1, :]
    else:
        idx = jnp.asarray(last_index, jnp.int32)[:, None, None]
        xl = jnp.take_along_axis(
            x, jnp.broadcast_to(idx, (b, 1, x.shape[-1])), axis=1)[:, 0]
    logits = (xl @ params["embed"].T.astype(dt)).astype(jnp.float32)
    return logits, new_cache


def prefill_scan(params: dict, tokens, cache, cfg: TransformerConfig,
                 tp_axis: Optional[str] = None,
                 ep_axis: Optional[str] = None):
    """One-token-at-a-time prefill (scan over decode_step) — the
    parity oracle for `prefill` and a fallback exercising exactly the
    decode path."""
    b, plen = tokens.shape

    def step(carry, t):
        cache, pos, _ = carry
        logits, cache = decode_step(params, t, pos, cache, cfg,
                                    tp_axis=tp_axis, ep_axis=ep_axis)
        return (cache, pos + 1, logits), None

    z = jnp.zeros((b, cfg.vocab), jnp.float32)
    (cache, _, logits), _ = lax.scan(step, (cache, 0, z),
                                     jnp.transpose(tokens))
    return logits, cache


def generate(params: dict, prompt, cfg: TransformerConfig, *,
             max_new: int, max_len: Optional[int] = None,
             temperature: float = 0.0,
             rng: Optional[jax.Array] = None,
             tp_axis: Optional[str] = None,
             ep_axis: Optional[str] = None,
             prompt_lengths=None):
    """Autoregressive continuation of ``prompt`` (b, plen) int32:
    returns (b, max_new) int32 new tokens. temperature 0 = greedy;
    > 0 samples from softmax(logits/T) (needs ``rng``). Jittable as a
    whole (static shapes; one lax.scan over the new positions).
    With ``tp_axis`` (inside shard_map): tensor-parallel decode over
    sharded params + cache (see decode_step).

    ``prompt_lengths`` (b,) int32 enables RAGGED prompts (the serving
    shape: one batch, different prompt lengths): row i's prompt is
    prompt[i, :prompt_lengths[i]], the rest is padding (any valid
    token id). Row i's continuation starts right after its own last
    prompt token — per-row positions, cache slots, and attention
    masks throughout — and equals the dense generate of the truncated
    row exactly (the padded positions' cache entries are never
    attended: decode masks at the row's position and overwrites them
    in order). MoE configs: the ragged prefill routes drop-free so
    padding cannot consume expert capacity (see prefill); per-row
    parity then holds under the same capacity_factor >= n_experts
    condition as MoE decode."""
    logits, cache, pos0 = _generate_prefill(
        params, prompt, cfg, max_new=max_new, max_len=max_len,
        temperature=temperature, rng=rng, tp_axis=tp_axis,
        ep_axis=ep_axis, prompt_lengths=prompt_lengths)
    keys = (jax.random.split(rng, max_new) if rng is not None
            else jnp.zeros((max_new, 2), jnp.uint32))
    return _generate_decode(params, logits, cache, pos0, cfg, keys,
                            temperature, tp_axis, ep_axis)


def _generate_prefill(params, prompt, cfg, *, max_new, max_len,
                      temperature, rng, tp_axis, ep_axis,
                      prompt_lengths):
    """generate()'s argument checks + cache init + prefill; returns
    (logits, cache, pos0). Shared with generate_timed so the timed
    variant can never drift from the jitted one."""
    b, plen = prompt.shape
    max_len = max_len or (plen + max_new)
    if plen + max_new > max_len:
        raise ValueError(f"prompt {plen} + max_new {max_new} exceeds "
                         f"max_len {max_len}")
    if temperature > 0 and rng is None:
        # argument error: raise before any cache/prefill work is spent
        raise ValueError("sampling (temperature > 0) needs rng")
    cache = init_kv_cache(cfg, b, max_len, tp_axis=tp_axis)
    if prompt_lengths is None:
        pos0 = plen
        logits, cache = prefill(params, prompt, cache, cfg,
                                tp_axis=tp_axis, ep_axis=ep_axis)
    else:
        lengths = jnp.asarray(prompt_lengths, jnp.int32)
        pos0 = lengths                                   # (b,) ragged
        logits, cache = prefill(params, prompt, cache, cfg,
                                tp_axis=tp_axis, ep_axis=ep_axis,
                                last_index=lengths - 1)
    return logits, cache, pos0


def _pick_token(logits, key, temperature: float):
    if temperature == 0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        key, logits / temperature, axis=-1).astype(jnp.int32)


def _generate_decode(params, logits, cache, pos0, cfg, keys,
                     temperature, tp_axis, ep_axis):
    """generate()'s decode loop: one lax.scan over the new positions."""
    def step(carry, key):
        logits, cache, pos = carry
        tok = _pick_token(logits, key, temperature)
        logits, cache = decode_step(params, tok, pos, cache, cfg,
                                    tp_axis=tp_axis, ep_axis=ep_axis)
        return (logits, cache, pos + 1), tok

    (_, _, _), toks = lax.scan(step, (logits, cache, pos0), keys)
    return jnp.transpose(toks)  # (b, max_new)


def generate_timed(params: dict, prompt, cfg: TransformerConfig, *,
                   max_new: int, max_len: Optional[int] = None,
                   temperature: float = 0.0,
                   rng: Optional[jax.Array] = None,
                   prompt_lengths=None, metrics=None):
    """`generate` with serving telemetry: identical tokens, plus TTFT
    (call -> first token materialized on the host, ``serve.ttft_usec``)
    and per-token decode latency (``serve.tok_usec``) recorded into
    ``metrics`` — default the process-wide ``metrics.SERVING``
    registry, the same one ``DecodeServer`` records into, so one
    snapshot covers both serving paths.

    Eager by design (the host round-trips after prefill and after the
    scan are the measurement points); inside jit use plain
    ``generate``. The first token is computed once here for the TTFT
    stamp and recomputed inside the scan — picks are deterministic
    functions of (logits, key), so outputs equal ``generate`` exactly
    (pinned by test)."""
    from rlo_tpu.utils.metrics import SERVING
    reg = SERVING if metrics is None else metrics
    t0 = time.perf_counter()
    logits, cache, pos0 = _generate_prefill(
        params, prompt, cfg, max_new=max_new, max_len=max_len,
        temperature=temperature, rng=rng, tp_axis=None, ep_axis=None,
        prompt_lengths=prompt_lengths)
    keys = (jax.random.split(rng, max_new) if rng is not None
            else jnp.zeros((max_new, 2), jnp.uint32))
    if max_new > 0:  # max_new=0: no first token exists to stamp
        jax.block_until_ready(
            _pick_token(logits, keys[0], temperature))
        t1 = time.perf_counter()
        reg.histogram("serve.ttft_usec").observe((t1 - t0) * 1e6)
    else:
        t1 = time.perf_counter()
    toks = _generate_decode(params, logits, cache, pos0, cfg, keys,
                            temperature, None, None)
    jax.block_until_ready(toks)
    if max_new > 0:
        t2 = time.perf_counter()
        reg.histogram("serve.tok_usec").observe(
            (t2 - t1) * 1e6 / max_new)
        reg.counter("serve.tokens_out").inc(int(toks.shape[0]) * max_new)
    return toks
