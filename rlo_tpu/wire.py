"""Message types and wire format.

Reference parity: the PBuf layout (`/root/reference/rootless_ops.c:64-73,
1369-1410`) is ``[origin:int][pid:int][vote:int][data_len:size_t][data]`` —
a 4-byte origin prefix written by RLO_msg_new_bc (rootless_ops.c:307) followed
by the serialized proposal buffer. We keep the same logical fields in one
little-endian header and send **variable-size frames** — the reference always
ships the full 32 KB buffer regardless of payload (rootless_ops.c:1588), a
known perf flaw SURVEY.md §7 says not to replicate.

The ``vote`` field doubles as a type discriminator in the reference
(0/1 vote, -1 proposal, -2 decision — rootless_ops.h:88); here message kind
travels out-of-band as the transport tag (mirroring MPI_TAG dispatch in
make_progress_gen, rootless_ops.c:582-621), and ``vote`` only carries votes
and decisions.
"""

from __future__ import annotations

import enum
import struct
from dataclasses import dataclass, field


class Tag(enum.IntEnum):
    """Transport-level message tags (reference RLO_COMM_TAGS,
    rootless_ops.h:50-61). Values 0-8 match the reference enum order;
    DATA/BARRIER are net-new for the data-carrying collectives."""
    BCAST = 0
    JOB_DONE = 1
    IAR_PROPOSAL = 2
    IAR_VOTE = 3
    IAR_DECISION = 4
    BC_TEARDOWN = 5
    IAR_TEARDOWN = 6
    P2P = 7
    SYS = 8
    DATA = 9
    BARRIER = 10
    HEARTBEAT = 11   # point-to-point ring liveness probe (net-new)
    FAILURE = 12     # rootless failure notification; pid = failed rank


#: Tags that are store-and-forward broadcast over the skip-ring overlay.
BCAST_TAGS = frozenset({Tag.BCAST, Tag.IAR_PROPOSAL, Tag.IAR_DECISION,
                        Tag.FAILURE})

_HEADER = struct.Struct("<iiiQ")  # origin, pid, vote, data_len
HEADER_SIZE = _HEADER.size

#: Default engine cap, matching RLO_MSG_SIZE_MAX (rootless_ops.h:49). Frames
#: themselves are variable-size; this only bounds a single message payload.
MSG_SIZE_MAX = 32768


@dataclass
class Frame:
    """One wire message. ``origin`` is the broadcast initiator (not the
    immediate sender — that is transport metadata, like MPI_SOURCE)."""
    origin: int
    pid: int = -1
    vote: int = -1
    payload: bytes = b""

    def encode(self) -> bytes:
        return _HEADER.pack(self.origin, self.pid, self.vote,
                            len(self.payload)) + self.payload

    @classmethod
    def decode(cls, raw: bytes) -> "Frame":
        if len(raw) < HEADER_SIZE:
            raise ValueError(f"frame too short: {len(raw)} < {HEADER_SIZE}")
        origin, pid, vote, n = _HEADER.unpack_from(raw)
        payload = bytes(raw[HEADER_SIZE:HEADER_SIZE + n])
        if len(payload) != n:
            raise ValueError(f"truncated frame: want {n} payload bytes, "
                             f"have {len(raw) - HEADER_SIZE}")
        return cls(origin, pid, vote, payload)
