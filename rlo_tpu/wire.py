"""Message types and wire format.

Reference parity: the PBuf layout (`/root/reference/rootless_ops.c:64-73,
1369-1410`) is ``[origin:int][pid:int][vote:int][data_len:size_t][data]`` —
a 4-byte origin prefix written by RLO_msg_new_bc (rootless_ops.c:307) followed
by the serialized proposal buffer. We keep the same logical fields in one
little-endian header and send **variable-size frames** — the reference always
ships the full 32 KB buffer regardless of payload (rootless_ops.c:1588), a
known perf flaw SURVEY.md §7 says not to replicate.

The ``vote`` field doubles as a type discriminator in the reference
(0/1 vote, -1 proposal, -2 decision — rootless_ops.h:88); here message kind
travels out-of-band as the transport tag (mirroring MPI_TAG dispatch in
make_progress_gen, rootless_ops.c:582-621), and ``vote`` only carries votes
and decisions.

``seq`` is the reliable-delivery layer's per-(sender, receiver) link
sequence number (net-new: the reference has no loss recovery at all,
SURVEY.md §5). It is stamped by the sending engine's ARQ machinery at
isend time — NOT by the application — and is -1 on frames outside the
ARQ path (heartbeats, ACKs, engines without ARQ enabled). Receivers
dedup on (immediate sender, seq) before any tag dispatch, which makes
retransmits idempotent even through the store-and-forward broadcast
path; cumulative acknowledgements travel back as ``Tag.ACK`` frames
(and piggybacked on heartbeats) carrying the highest-contiguous
received seq in the ``vote`` field.

``epoch`` is the membership subsystem's link-level view stamp
(docs/DESIGN.md §8): the sending engine stamps its current membership
epoch into every frame at transmission time (retransmits are restamped
with the CURRENT epoch — the seq, not the epoch, is the frame's
identity). Receivers quarantine frames from senders they consider
failed, and frames whose epoch is below the per-sender floor set when
that sender was last declared failed or readmitted — this is what
makes stale frames from a "dead" peer distinguishable from its
post-rejoin traffic. ``Tag.JOIN`` / ``Tag.JOIN_WELCOME`` are exempt
(they are the frames that cross membership boundaries to heal them).
"""

from __future__ import annotations

import enum
import struct
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

from rlo_tpu.utils.metrics import ENGINE_COUNTER_KEYS


class Tag(enum.IntEnum):
    """Transport-level message tags (reference RLO_COMM_TAGS,
    rootless_ops.h:50-61). Values 0-8 match the reference enum order;
    DATA/BARRIER are net-new for the data-carrying collectives.

    Values are paired with the C ``enum rlo_tag`` (rlo_core.h) and
    checked by rlo-lint R1. Members without their own branch in the
    engine dispatch are delivered through the ``_on_other`` catch-all
    and carry the ``rlo-lint: default-route`` annotation (R4)."""
    BCAST = 0
    JOB_DONE = 1      # rlo-lint: default-route
    IAR_PROPOSAL = 2
    IAR_VOTE = 3
    IAR_DECISION = 4
    BC_TEARDOWN = 5   # rlo-lint: default-route
    IAR_TEARDOWN = 6  # rlo-lint: default-route
    P2P = 7           # rlo-lint: default-route
    SYS = 8           # rlo-lint: default-route
    DATA = 9          # rlo-lint: default-route
    BARRIER = 10      # rlo-lint: default-route
    HEARTBEAT = 11   # point-to-point ring liveness probe (net-new)
    FAILURE = 12     # rootless failure notification; pid = failed rank
    ACK = 13         # cumulative link ACK; vote = highest contiguous seq
    ABORT = 14       # rootless op-abort notification (deadline expiry);
                     # pid = aborted pid, payload = round generation
    JOIN = 15        # membership probe/petition; payload = 5 x le32
                     # (incarnation, epoch, min-alive-rank, petition,
                     # member) of the sender's view — petition=1 marks
                     # a joiner's plea vs a survivor's heal probe;
                     # member=1 tells the DESTINATION it is alive in
                     # the sender's view, steering a losing-view
                     # receiver to a Tag.MSYNC catch-up instead of a
                     # full rejoin (old 4-field probes parse member=0)
    JOIN_WELCOME = 16  # admission notice from the admitting proposer:
                     # payload = (epoch, incarnation echo, member list);
                     # followed by a point-to-point replay of the
                     # recent-broadcast log
    SERVE = 17       # rlo-lint: default-route
                     # serving-fabric point-to-point frame (load
                     # reports, docs/DESIGN.md §11): reliable (ARQ-
                     # stamped), epoch-gated, delivered via pickup —
                     # the payload is a fabric record, not engine state
    TELEM = 18       # rlo-lint: default-route
                     # in-band telemetry digest (docs/DESIGN.md §17):
                     # reliable (ARQ-stamped), epoch-gated, delivered
                     # via pickup to the telemetry plane
                     # (rlo_tpu/observe/), which store-and-forwards it
                     # along the broadcast overlay — the payload is a
                     # delta-encoded digest (encode_telem below), not
                     # engine state
    MSYNC = 19       # membership view-state sync (docs/DESIGN.md §18):
                     # a kind byte discriminates REQ (epoch-lagging
                     # member asks an up-to-date peer for its view),
                     # RSP (epoch + member admission records + a
                     # recent-log advert), AD (view-change re-flood
                     # advert: log-entry identities, not payloads) and
                     # WANT (the advert entries the receiver provably
                     # misses). ARQ- and epoch-exempt like JOIN: it
                     # crosses the membership boundaries it heals, and
                     # REQs repeat at join_interval until answered


#: Tags that are store-and-forward broadcast over the skip-ring overlay.
BCAST_TAGS = frozenset({Tag.BCAST, Tag.IAR_PROPOSAL, Tag.IAR_DECISION,
                        Tag.FAILURE, Tag.ABORT})

#: Tags the ARQ layer neither stamps nor retransmits: heartbeats are
#: periodic by construction (a lost one is replaced by the next) and
#: ACKs ack themselves by effect (a lost ACK just triggers one more
#: retransmit, which the dedup layer absorbs and re-acks). JOIN probes
#: repeat at their own cadence until answered, and a lost WELCOME is
#: replaced when the joiner's next probe arrives — both must also work
#: across the membership boundary where ARQ link state is being reset.
#: MSYNC shares the JOIN rationale: sync REQs repeat at join_interval
#: until the view catches up, and an ARQ-stamped frame into a
#: quarantining receiver would never be acked (a retransmit-then-give-
#: up loop that itself declares failures).
ARQ_EXEMPT_TAGS = frozenset({Tag.HEARTBEAT, Tag.ACK, Tag.JOIN,
                             Tag.JOIN_WELCOME, Tag.MSYNC})

#: Tags exempt from the stale-epoch quarantine: the membership frames
#: themselves must cross partition/incarnation boundaries to heal them.
EPOCH_EXEMPT_TAGS = frozenset({Tag.JOIN, Tag.JOIN_WELCOME, Tag.MSYNC})

# origin, pid, vote, seq, epoch, data_len
# rlo-lint: paired-with rlo_core.h:RLO_HEADER_SIZE
_HEADER = struct.Struct("<iiiiiQ")
HEADER_SIZE = _HEADER.size
#: byte offset of the seq field — the ARQ send path re-stamps encoded
#: frames in place (one encode per broadcast, one patch per edge)
SEQ_OFFSET = 12  # rlo-lint: paired-with rlo_core.h:RLO_SEQ_OFFSET
#: byte offset of the epoch field — stamped by the engine send gate at
#: every transmission (including retransmits) with the CURRENT epoch
EPOCH_OFFSET = 16  # rlo-lint: paired-with rlo_core.h:RLO_EPOCH_OFFSET

#: Default engine cap, matching RLO_MSG_SIZE_MAX (rootless_ops.h:49). Frames
#: themselves are variable-size; this only bounds a single message payload.
MSG_SIZE_MAX = 32768  # rlo-lint: paired-with rlo_core.h:RLO_MSG_SIZE_MAX


@dataclass
class Frame:
    """One wire message. ``origin`` is the broadcast initiator (not the
    immediate sender — that is transport metadata, like MPI_SOURCE).
    ``seq`` is per-(immediate sender, receiver) link state owned by the
    ARQ layer and ``epoch`` is the sender's membership epoch at
    transmission time (stamped by the engine send gate); neither is an
    application field."""
    origin: int
    pid: int = -1
    vote: int = -1
    payload: bytes = b""
    seq: int = -1
    epoch: int = 0

    def encode(self) -> bytes:
        return _HEADER.pack(self.origin, self.pid, self.vote, self.seq,
                            self.epoch, len(self.payload)) + self.payload

    @classmethod
    def decode(cls, raw: bytes) -> "Frame":
        if len(raw) < HEADER_SIZE:
            raise ValueError(f"frame too short: {len(raw)} < {HEADER_SIZE}")
        origin, pid, vote, seq, epoch, n = _HEADER.unpack_from(raw)
        payload = bytes(raw[HEADER_SIZE:HEADER_SIZE + n])
        if len(payload) != n:
            raise ValueError(f"truncated frame: want {n} payload bytes, "
                             f"have {len(raw) - HEADER_SIZE}")
        return cls(origin, pid, vote, payload, seq, epoch)


def restamp_seq(raw: bytes, seq: int) -> bytes:
    """Return ``raw`` with its header seq field replaced — the ARQ send
    path's per-edge stamp (avoids re-encoding the payload per edge)."""
    buf = bytearray(raw)
    struct.pack_into("<i", buf, SEQ_OFFSET, seq)
    return bytes(buf)


def restamp_epoch(raw: bytes, epoch: int) -> bytes:
    """Return ``raw`` with its header epoch field replaced — the send
    gate's per-transmission membership stamp (re-flooded and
    retransmitted frames carry the CURRENT epoch, so a live sender's
    old traffic is never mistaken for a dead incarnation's). Returns
    ``raw`` itself when the stamp already matches (the common case —
    all link epochs 0 — never copies; mirror of the C send gate)."""
    # rlo-sentinel: trusted — send-path helper: `raw` is a frame THIS
    # engine just encoded (>= HEADER_SIZE by construction), not wire
    # input from a peer
    if struct.unpack_from("<i", raw, EPOCH_OFFSET)[0] == epoch:
        return raw
    buf = bytearray(raw)
    struct.pack_into("<i", buf, EPOCH_OFFSET, epoch)
    return bytes(buf)


def restamp_link(raw: bytes, seq: int, epoch: int) -> bytes:
    """One-copy combined seq + epoch stamp for the ARQ send path."""
    buf = bytearray(raw)
    struct.pack_into("<ii", buf, SEQ_OFFSET, seq, epoch)
    return bytes(buf)


# ---------------------------------------------------------------------------
# Telemetry digest codec (docs/DESIGN.md §17). One digest = one rank's
# compact, delta-encoded telemetry sample, carried in a Tag.TELEM
# frame payload and store-and-forwarded by the telemetry plane
# (rlo_tpu/observe/telemetry.py) so any rank converges on an
# eventually-consistent fleet view. The byte layout is PINNED so the C
# engine can originate byte-identical digests (rlo_wire.c
# rlo_telem_encode / rlo_engine.c rlo_engine_telem_digest; parity
# asserted by tests/test_observe.py):
#
#   offset 0   magic  "RLOT\x01"                      (5 bytes)
#   offset 5   flags  u8    bit0 = FULL snapshot (deltas vs zero)
#   offset 6   rank   i32le origin rank of the sample
#   offset 10  epoch  i32le origin's membership epoch at emit time
#   offset 14  seq    u32le per-origin digest sequence (0, 1, 2, ...)
#   offset 18  mask   u64le bit i set => TELEM_KEYS[i] delta present
#   offset 26  deltas       one unsigned LEB128 varint per set mask
#                           bit (ascending bit order), zigzag-encoded
#                           (value - previous emitted value; a FULL
#                           digest encodes the absolute values, i.e.
#                           deltas vs zero, with every bit set)
#
# Receivers apply a digest only when it is FULL or exactly one seq
# past the last applied one — a gap (lost delta) parks the rank's
# view entry as stale until the origin's next full snapshot heals it.
# ---------------------------------------------------------------------------

#: digest magic prefix (the Tag.TELEM payload discriminator)
# rlo-lint: paired-with rlo_core.h:RLO_TELEM_MAGIC
TELEM_MAGIC = b"RLOT\x01"

#: fixed header size before the varint delta section
# rlo-lint: paired-with rlo_core.h:RLO_TELEM_HEADER_SIZE
TELEM_HEADER_SIZE = 26

#: digest keys beyond the engine-counter schema: per-link rollups
#: (frames both ways, the worst ack-measured RTT EWMA in usec), live
#: queue depth and pickup backlog, the serving layer's paged-pool
#: occupancy, and the fabric latency block (in-flight requests plus
#: p50/p99 TTFT and e2e from the fabric's log2-bucket histograms —
#: docs/DESIGN.md §19). All serving keys are zero on ranks without an
#: attached fabric — the C engine always emits 0 here. The trailing
#: collective data-plane rollups (cumulative schedule steps executed
#: and payload bytes sent by the engine-substrate collectives —
#: docs/DESIGN.md §21) are likewise zero on the C engine: tensor
#: collectives are Python-side.
# rlo-lint: paired-with rlo_wire.c:k_telem_keys
TELEM_EXTRA_KEYS = (
    "tx_frames", "rx_frames", "rtt_ewma_max_usec",
    "q_wait", "pickup_backlog", "pages_in_use", "pages_free",
    "serve_inflight", "ttft_p50_usec", "ttft_p99_usec",
    "e2e_p50_usec", "e2e_p99_usec",
    "coll_steps", "coll_bytes",
    "remedies_proposed", "remedies_executed",
    "quarantined", "backpressure_level",
)

#: The full digest schema, in mask-bit order: the engine-counter
#: schema (so every rlo-lint R2-pinned counter rides the digest — the
#: heal-cost counters included) followed by the extras. Bounded at 64
#: keys by the u64 mask; rlo-lint R2 pins this tuple against the C
#: codec's key-name table (rlo_wire.c k_telem_keys).
TELEM_KEYS = ENGINE_COUNTER_KEYS + TELEM_EXTRA_KEYS
assert len(TELEM_KEYS) <= 64, "TELEM mask is a u64: at most 64 keys"

_TELEM_HDR = struct.Struct("<BiiIQ")  # flags, rank, epoch, seq, mask


def _zigzag(n: int) -> int:
    return (n << 1) if n >= 0 else ((-n << 1) - 1)


def _unzigzag(u: int) -> int:
    return (u >> 1) if (u & 1) == 0 else -((u + 1) >> 1)


def _varint(out: bytearray, u: int) -> None:
    while u >= 0x80:
        out.append((u & 0x7F) | 0x80)
        u >>= 7
    out.append(u)


def encode_telem(rank: int, epoch: int, seq: int,
                 values: Sequence[int],
                 prev: Optional[Sequence[int]] = None,
                 full: bool = False) -> bytes:
    """Encode one telemetry digest. ``values`` are the CURRENT sample
    in TELEM_KEYS order; ``prev`` the previously EMITTED sample (the
    delta base). ``full`` (or ``prev=None``) emits a full snapshot —
    absolute values, every mask bit set — which is what heals a
    receiver that lost a delta."""
    if len(values) != len(TELEM_KEYS):
        raise ValueError(f"need {len(TELEM_KEYS)} values in TELEM_KEYS "
                         f"order, got {len(values)}")
    if prev is None:
        full = True
    out = bytearray(TELEM_MAGIC)
    mask = 0
    deltas = bytearray()
    for i, v in enumerate(values):
        d = int(v) - (0 if full else int(prev[i]))
        if full or d != 0:
            mask |= 1 << i
            _varint(deltas, _zigzag(d))
    out += _TELEM_HDR.pack(1 if full else 0, rank, epoch,
                           seq & 0xFFFFFFFF, mask)
    out += deltas
    return bytes(out)


def decode_telem(raw: bytes) -> Tuple[int, int, int, bool,
                                      Dict[str, int]]:
    """Decode one digest: ``(rank, epoch, seq, full, {key: delta})``.
    Raises ValueError on a malformed payload (bad magic, truncated
    header or varint section, mask bits beyond the schema)."""
    if len(raw) < TELEM_HEADER_SIZE or \
            raw[:len(TELEM_MAGIC)] != TELEM_MAGIC:
        raise ValueError("not a telemetry digest")
    flags, rank, epoch, seq, mask = _TELEM_HDR.unpack_from(
        raw, len(TELEM_MAGIC))
    if mask >> len(TELEM_KEYS):
        raise ValueError(f"digest mask {mask:#x} has bits beyond the "
                         f"{len(TELEM_KEYS)}-key schema")
    deltas: Dict[str, int] = {}
    pos = TELEM_HEADER_SIZE
    for i, key in enumerate(TELEM_KEYS):
        if not mask & (1 << i):
            continue
        u = 0
        shift = 0
        while True:
            # same validity bound as the C decoder (rlo_wire.c):
            # a varint past 64 payload bits is malformed, not a bigint
            if pos >= len(raw) or shift > 63:
                raise ValueError("truncated/overlong digest varint")
            b = raw[pos]
            pos += 1
            u |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        deltas[key] = _unzigzag(u)
    return rank, epoch, seq, bool(flags & 1), deltas


# ---------------------------------------------------------------------------
# Span context codec (docs/DESIGN.md §19). One span context = the
# compact causal stamp a traced request carries in-band: appended as a
# TRAILER to existing fabric record payloads (ADMIT / DONE / PLACE —
# never a new record kind, never a header change), so every rank the
# record reaches can emit a stage-boundary span into the PR-2 tracer
# rings without any side channel. The byte layout is PINNED so the C
# engine can recognise and decode the trailer on its wire-hop path
# (rlo_wire.c rlo_span_encode / rlo_span_decode; parity asserted by
# tests/test_spans.py):
#
#   offset 0   magic   "RLOS\x01"                     (5 bytes)
#   offset 5   flags   u8    bit0 = sampled (emit spans for this rid)
#   offset 6   stage   u8    observe.spans.Stage of the record boundary
#   offset 7   gateway i32le rid gateway rank (-1 on fleet-level spans,
#                            e.g. placement rounds keyed by version)
#   offset 11  seq     i32le rid sequence (low 31 bits — the trailer
#                            identifies, the full-width rid lives in
#                            the record body)
#   offset 15  t_usec  u64le stage START on the ORIGIN's engine clock
#
# Discrimination is structural: every fabric record body is its fixed
# header plus a whole number of i32 words, so (len - base) % 4 == 0 on
# a clean record and == SPAN_CTX_SIZE % 4 == 3 with a trailer — the
# magic check then confirms. Records without a trailer are
# byte-identical to the pre-span wire format (the zero-overhead
# contract the bench gates pin).
# ---------------------------------------------------------------------------

#: span-context trailer magic
# rlo-lint: paired-with rlo_core.h:RLO_SPAN_MAGIC
SPAN_MAGIC = b"RLOS\x01"

#: fixed trailer size; % 4 == 3 is what makes the trailer structurally
#: unambiguous against i32-word record payloads
# rlo-lint: paired-with rlo_core.h:RLO_SPAN_CTX_SIZE
SPAN_CTX_SIZE = 23

_SPAN_CTX = struct.Struct("<BBiiQ")  # flags, stage, gateway, seq, t_usec

#: flags bit0 — this rid was selected by the deterministic sampler
SPAN_F_SAMPLED = 1


def encode_span_ctx(gateway: int, seq: int, stage: int, t_usec: int,
                    flags: int = SPAN_F_SAMPLED) -> bytes:
    """Encode one span-context trailer (SPAN_CTX_SIZE bytes)."""
    return SPAN_MAGIC + _SPAN_CTX.pack(
        flags & 0xFF, stage & 0xFF, gateway, seq & 0x7FFFFFFF,
        t_usec & 0xFFFFFFFFFFFFFFFF)


def decode_span_ctx(raw: bytes, off: int = 0) \
        -> Optional[Tuple[int, int, int, int, int]]:
    """Decode a span context at ``raw[off:]``: ``(flags, stage,
    gateway, seq, t_usec)``, or None when the bytes there are not a
    span context (wrong magic / too short) — absence is the common
    case, not an error."""
    if len(raw) - off < SPAN_CTX_SIZE or \
            raw[off:off + len(SPAN_MAGIC)] != SPAN_MAGIC:
        return None
    flags, stage, gateway, seq, t_usec = _SPAN_CTX.unpack_from(
        raw, off + len(SPAN_MAGIC))
    return flags, stage, gateway, seq, t_usec


def split_span_ctx(body: bytes, base: int) \
        -> Tuple[int, Optional[Tuple[int, int, int, int, int]]]:
    """Split a fabric record body into ``(payload_end, ctx)`` where
    ``base`` is the record kind's fixed-header size and the payload
    after it is a whole number of i32 words. Returns ``(len(body),
    None)`` for clean records — one modulo and one compare on the hot
    path, nothing else."""
    if len(body) >= base + SPAN_CTX_SIZE and \
            (len(body) - base) % 4 == SPAN_CTX_SIZE % 4:
        ctx = decode_span_ctx(body, len(body) - SPAN_CTX_SIZE)
        if ctx is not None:
            return len(body) - SPAN_CTX_SIZE, ctx
    return len(body), None
