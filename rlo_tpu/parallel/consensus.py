"""Host-side IAR consensus protocol over the TPU collective backend.

The reference's consensus is host-reactive: arbitrary C judgement callbacks
run in the middle of the vote tree (rootless_ops.c:698, 773) and the action
callback fires on decision (:842). On TPU the vote aggregation is one
device-side min-reduction (rlo_tpu.ops.tpu_collectives.consensus); the
callbacks stay on the host around that sync point — the host/device split
SURVEY.md §7 calls the "hard part" of this mapping.

Protocol per submit() (mirrors RLO_submit_proposal -> judge -> vote merge ->
decision -> action, rootless_ops.c:876-932):
  1. host: judge_cb(proposal, app_ctx) -> my vote in {0,1}
  2. device: pmin over every shard's vote on the mesh axis
  3. host: if approved, action_cb(proposal, app_ctx)
"""

from __future__ import annotations

import weakref
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from rlo_tpu.ops import tpu_collectives


class JudgeWrapperCache:
    """One stable wrapper per user judge function.

    The sharded-decision caches key compiled programs on the wrapper's
    id(); a wrapper recreated per call therefore recompiles the
    shard_map program and permanently pins a fresh cache entry every
    round (round-2 advisor finding). Identity rules:

    - bound methods are keyed on (id(__self__), __func__): accessing
      ``obj.judge`` mints a NEW ephemeral method object per round, so
      keying on the object itself would evaporate between rounds and
      reintroduce the per-round recompile. The entry dies with
      __self__ (weakref callback), so a recycled id can never hit a
      stale wrapper.
    - other callables are keyed weakly so user judges are not pinned;
      the wrapper closes over a weakref for the same reason (a strong
      closure would keep the WeakKeyDictionary entry alive forever).
    - judges that don't support weakrefs fall back to a strong
      id-keyed map — they recompile once, never per call."""

    def __init__(self):
        self._weak = weakref.WeakKeyDictionary()
        self._methods: dict = {}
        self._strong: dict = {}

    def get(self, judge, make):
        """Return the cached wrapper for ``judge``, building it with
        ``make(get_judge)`` on first use (``get_judge`` is a zero-arg
        callable resolving to the live judge)."""
        import types

        if isinstance(judge, types.MethodType):
            k = (id(judge.__self__), judge.__func__)
            if k not in self._methods:
                func = judge.__func__
                ref_self = weakref.ref(
                    judge.__self__,
                    lambda _ref: self._methods.pop(k, None))
                self._methods[k] = make(
                    lambda: types.MethodType(func, ref_self()))
            return self._methods[k]
        try:
            return self._weak[judge]
        except KeyError:
            ref = weakref.ref(judge)
            wrapper = make(ref)
            self._weak[judge] = wrapper
            return wrapper
        except TypeError:  # judge not weakref-able: pin it
            k = id(judge)
            if k not in self._strong:
                self._strong[k] = (judge, make(lambda: judge))
            return self._strong[k][1]


class TpuConsensus:
    """Leaderless consensus context bound to one mesh axis.

    In multi-controller deployments every host process judges its own
    proposal copy and contributes the votes of its local shards; in
    single-controller tests per-shard votes can be injected directly via
    ``decide_votes`` to model heterogeneous judges.
    """

    def __init__(self, mesh: Mesh, axis: str,
                 judge_cb: Optional[Callable[[bytes, object], int]] = None,
                 app_ctx: object = None,
                 action_cb: Optional[Callable[[bytes, object], object]] = None):
        self.mesh = mesh
        self.axis = axis
        self.judge_cb = judge_cb
        self.app_ctx = app_ctx
        self.action_cb = action_cb
        self.axis_size = mesh.shape[axis]
        self._sharded_cache: dict = {}
        self._io_wrappers = JudgeWrapperCache()
        self._decide = jax.jit(jax.shard_map(
            lambda v: tpu_collectives.consensus(v, axis),
            mesh=mesh, in_specs=P(axis), out_specs=P(axis)))

    def decide_votes(self, votes) -> int:
        """Device-side AND over per-shard votes; returns the decision."""
        votes = jnp.asarray(votes, jnp.int32).reshape(self.axis_size)
        out = np.asarray(self._decide(votes))
        return int(out[0])

    def submit(self, proposal: bytes) -> int:
        """Full propose/judge/decide/act round; returns 1 approved, 0
        declined. The single controller judges once and replicates its
        vote — use submit_sharded for genuinely per-shard judgment."""
        my_vote = 1 if self.judge_cb is None else \
            int(self.judge_cb(proposal, self.app_ctx))
        votes = np.full((self.axis_size,), my_vote, np.int32)
        decision = self.decide_votes(votes)
        if decision and self.action_cb is not None:
            self.action_cb(proposal, self.app_ctx)
        return decision

    # -- per-shard judgment (the reference's essence: EVERY rank judges
    # its own local state, rootless_ops.c:698 — not one controller
    # replicating its vote) ---------------------------------------------

    def _sharded_decide(self, device_judge, key):
        if key not in self._sharded_cache:
            axis = self.axis

            def step(v):
                vote = jnp.asarray(device_judge(v), jnp.int32).reshape(1)
                return tpu_collectives.consensus(vote, axis)
            # pin the judge alongside the program: the key carries the
            # judge's id(), and pinning prevents id reuse after GC
            self._sharded_cache[key] = (device_judge, jax.jit(
                jax.shard_map(step, mesh=self.mesh, in_specs=P(self.axis),
                              out_specs=P(self.axis))))
        return self._sharded_cache[key][1]

    def shard_votes(self, x, device_judge, key=None):
        """Every shard's OWN verdict on its slice of ``x``, computed on
        device inside shard_map — no reduction. Returns an int32 array
        of axis_size votes (feed these into an engine-substrate vote
        tree, e.g. the hybrid bridge's C IAR round). ``key`` names a
        stable cache identity for closures recreated per call; the
        judge's id() is always part of the key, so a different judge
        can never hit a stale compiled program."""
        axis = self.axis
        key = ("votes", key, id(device_judge),
               np.asarray(x).shape, str(np.asarray(x).dtype))
        if key not in self._sharded_cache:
            def step(v):
                return jnp.asarray(device_judge(v),
                                   jnp.int32).reshape(1)
            self._sharded_cache[key] = (device_judge, jax.jit(
                jax.shard_map(step, mesh=self.mesh, in_specs=P(axis),
                              out_specs=P(axis))))
        return np.asarray(self._sharded_cache[key][1](x))

    def submit_sharded(self, proposal: bytes, x, device_judge,
                       key=None) -> int:
        """Consensus where every shard judges ITS OWN device-resident
        slice: ``device_judge(local_shard) -> {0,1}`` is traced per
        shard inside shard_map, the votes pmin-merge on device (one
        fused program: judge + vote tree), and the replicated decision
        returns to the host. The host-side judge_cb (the controller's
        own structural vote) ANDs in; action_cb fires on approval.

        A shard whose device data fails the predicate vetoes the round
        even though a single controller process drives the mesh — the
        device-side analogue of rootless_ops.c:698."""
        host_vote = 1 if self.judge_cb is None else \
            int(self.judge_cb(proposal, self.app_ctx))
        if not host_vote:
            return 0
        key = (key, id(device_judge), np.asarray(x).shape,
               str(np.asarray(x).dtype))
        out = np.asarray(self._sharded_decide(device_judge, key)(x))
        decision = int(out.reshape(-1)[0])
        if decision and self.action_cb is not None:
            self.action_cb(proposal, self.app_ctx)
        return decision

    def submit_host_sharded(self, proposal: bytes, x, shard_judge) -> int:
        """Like submit_sharded but the per-shard judge is a HOST
        callback: each shard's slice round-trips through
        jax.experimental.io_callback(shard_judge) — the escape hatch
        for judgement logic that cannot be traced (arbitrary Python,
        like the reference's arbitrary C callbacks, rootless_ops.h:77).
        Slower (one host callback per shard per round); same veto
        semantics."""
        from jax.experimental import io_callback

        def make(get_judge):
            def device_judge(v):
                return io_callback(
                    lambda blk: np.int32(1 if get_judge()(blk) else 0),
                    jax.ShapeDtypeStruct((), jnp.int32), v)
            return device_judge

        # stable wrapper per shard_judge: repeated rounds with the same
        # judge reuse one compiled program instead of recompiling and
        # leaking a cache entry per call (round-2 advisor finding). The
        # wrapper's id() carries the judge identity in the program
        # cache key — never the raw judge's id(), which is ephemeral
        # for bound methods (obj.judge mints a new object per access)
        device_judge = self._io_wrappers.get(shard_judge, make)
        return self.submit_sharded(proposal, x, device_judge,
                                   key="io")
