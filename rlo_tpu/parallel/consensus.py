"""Host-side IAR consensus protocol over the TPU collective backend.

The reference's consensus is host-reactive: arbitrary C judgement callbacks
run in the middle of the vote tree (rootless_ops.c:698, 773) and the action
callback fires on decision (:842). On TPU the vote aggregation is one
device-side min-reduction (rlo_tpu.ops.tpu_collectives.consensus); the
callbacks stay on the host around that sync point — the host/device split
SURVEY.md §7 calls the "hard part" of this mapping.

Protocol per submit() (mirrors RLO_submit_proposal -> judge -> vote merge ->
decision -> action, rootless_ops.c:876-932):
  1. host: judge_cb(proposal, app_ctx) -> my vote in {0,1}
  2. device: pmin over every shard's vote on the mesh axis
  3. host: if approved, action_cb(proposal, app_ctx)
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from rlo_tpu.ops import tpu_collectives


class TpuConsensus:
    """Leaderless consensus context bound to one mesh axis.

    In multi-controller deployments every host process judges its own
    proposal copy and contributes the votes of its local shards; in
    single-controller tests per-shard votes can be injected directly via
    ``decide_votes`` to model heterogeneous judges.
    """

    def __init__(self, mesh: Mesh, axis: str,
                 judge_cb: Optional[Callable[[bytes, object], int]] = None,
                 app_ctx: object = None,
                 action_cb: Optional[Callable[[bytes, object], object]] = None):
        self.mesh = mesh
        self.axis = axis
        self.judge_cb = judge_cb
        self.app_ctx = app_ctx
        self.action_cb = action_cb
        self.axis_size = mesh.shape[axis]
        self._decide = jax.jit(jax.shard_map(
            lambda v: tpu_collectives.consensus(v, axis),
            mesh=mesh, in_specs=P(axis), out_specs=P(axis)))

    def decide_votes(self, votes) -> int:
        """Device-side AND over per-shard votes; returns the decision."""
        votes = jnp.asarray(votes, jnp.int32).reshape(self.axis_size)
        out = np.asarray(self._decide(votes))
        return int(out[0])

    def submit(self, proposal: bytes) -> int:
        """Full propose/judge/decide/act round; returns 1 approved, 0
        declined."""
        my_vote = 1 if self.judge_cb is None else \
            int(self.judge_cb(proposal, self.app_ctx))
        votes = np.full((self.axis_size,), my_vote, np.int32)
        decision = self.decide_votes(votes)
        if decision and self.action_cb is not None:
            self.action_cb(proposal, self.app_ctx)
        return decision
