"""Device mesh discovery and shard_map helpers.

TPU equivalent of the reference's communicator setup (MPI_Comm_dup +
size/rank discovery in bcomm_init, /root/reference/rootless_ops.c:1461-1468):
on TPU the "communicator" is a `jax.sharding.Mesh` over the ICI topology and
"ranks" are mesh axis indices.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def make_mesh(shape: Optional[Sequence[int]] = None,
              axis_names: Optional[Sequence[str]] = None) -> Mesh:
    """Build a mesh over the available devices.

    Default: 1-D mesh named 'x' over all devices. Pass e.g.
    shape=(2, 4), axis_names=('dp', 'tp') for multi-axis layouts.
    """
    devices = np.asarray(jax.devices())
    if shape is None:
        shape = (len(devices),)
    if axis_names is None:
        axis_names = ("x",) if len(shape) == 1 else \
            tuple(f"axis{i}" for i in range(len(shape)))
    need = math.prod(shape)
    if need > len(devices):
        raise ValueError(f"mesh shape {tuple(shape)} needs {need} devices, "
                         f"have {len(devices)}")
    return Mesh(devices[:need].reshape(shape), tuple(axis_names))


def make_multislice_mesh(ici_shape: Sequence[int],
                         ici_axis_names: Sequence[str],
                         dcn_axis_name: str = "dcn") -> Mesh:
    """Mesh for multi-slice TPU jobs: a leading data-center-network axis
    over slices, then the per-slice ICI axes.

    On a multi-slice platform (devices carry distinct ``slice_index``),
    devices are grouped so that the ICI axes stay INSIDE a slice — the
    bandwidth-heavy collectives (tp/sp/ep, ring allreduce) ride ICI,
    while only the ``dcn`` axis (put your dp/gradient averaging there)
    crosses the slower cross-slice network. On single-slice or CPU
    platforms the dcn axis degrades to size 1, so programs written
    against the (dcn, *ici) layout run unchanged anywhere.
    """
    import warnings

    devices = jax.devices()
    slices: dict = {}
    for d in devices:
        slices.setdefault(getattr(d, "slice_index", 0), []).append(d)
    n_slices = len(slices)
    per = math.prod(ici_shape)
    for idx, devs in slices.items():
        if len(devs) < per:
            raise ValueError(
                f"slice {idx} has {len(devs)} devices, ICI shape "
                f"{tuple(ici_shape)} needs {per}")
        if len(devs) > per:
            warnings.warn(
                f"slice {idx}: ICI shape {tuple(ici_shape)} uses {per} "
                f"of {len(devs)} devices; the rest sit idle",
                stacklevel=2)
    arr = np.empty((n_slices,) + tuple(ici_shape), dtype=object)
    for i, idx in enumerate(sorted(slices)):
        arr[i] = np.asarray(slices[idx][:per]).reshape(ici_shape)
    return Mesh(arr, (dcn_axis_name,) + tuple(ici_axis_names))


def shard_jit(fn, mesh: Mesh, in_specs, out_specs, check_vma: bool = True):
    """jit(shard_map(fn)) — one SPMD program over the mesh.

    check_vma (varying-manual-axes typing) is ON by default: it makes
    jax.grad correct under shard_map by auto-inserting the cotangent
    psums for replicated params (without it, the transpose of psum is
    psum and per-shard grads of replicated params are wrong). Code that
    wants explicit control of a gradient collective (e.g. the dp ring
    allreduce) opts out per-param with `vary_over` instead of disabling
    the typing."""
    return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=check_vma))


def vary_like(x, like):
    """Mark ``x`` varying over the mesh axes ``like`` varies over.

    Under check_vma, fori_loop carries must keep a constant vma type:
    zeros-initialized accumulators start unvarying while the loop body
    makes them varying — cast the inits up front. No-op when vma typing
    is off or ``like`` carries no vma."""
    try:
        need = set(jax.typeof(like).vma) - set(jax.typeof(x).vma)
    except (AttributeError, TypeError):
        return x
    if not need:
        return x
    return jax.lax.pcast(x, tuple(sorted(need)), to="varying")


