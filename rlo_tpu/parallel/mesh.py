"""Device mesh discovery and shard_map helpers.

TPU equivalent of the reference's communicator setup (MPI_Comm_dup +
size/rank discovery in bcomm_init, /root/reference/rootless_ops.c:1461-1468):
on TPU the "communicator" is a `jax.sharding.Mesh` over the ICI topology and
"ranks" are mesh axis indices.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def make_mesh(shape: Optional[Sequence[int]] = None,
              axis_names: Optional[Sequence[str]] = None) -> Mesh:
    """Build a mesh over the available devices.

    Default: 1-D mesh named 'x' over all devices. Pass e.g.
    shape=(2, 4), axis_names=('dp', 'tp') for multi-axis layouts.
    """
    devices = np.asarray(jax.devices())
    if shape is None:
        shape = (len(devices),)
    if axis_names is None:
        axis_names = ("x",) if len(shape) == 1 else \
            tuple(f"axis{i}" for i in range(len(shape)))
    need = math.prod(shape)
    if need > len(devices):
        raise ValueError(f"mesh shape {tuple(shape)} needs {need} devices, "
                         f"have {len(devices)}")
    return Mesh(devices[:need].reshape(shape), tuple(axis_names))


def shard_jit(fn, mesh: Mesh, in_specs, out_specs):
    """jit(shard_map(fn)) — one SPMD program over the mesh.

    check_vma is disabled: the Pallas interpreter used on non-TPU backends
    loses varying-mesh-axes annotations in its internal grid loop, which
    would spuriously reject kernels that are correct on TPU.
    """
    return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))
