"""Multi-controller deployment: one OS process per host, real everywhere.

The reference's ranks are arbitrary MPI processes — including across
machines (`RLO_progress_engine_new` dup's any communicator,
/root/reference/rootless_ops.c:467, 1461; nothing in the library assumes
one host). The round-2 rebuild's TPU data plane was a single JAX
controller *simulating* ranks; this module is the real deployment shape
(round-2 VERDICT "What's missing" #1). Each OS process runs

  - its own ENGINE rank over the MPI transport — femtompi shared-memory
    rings between processes on one host (rlo_tpu/native/femtompi), the
    same `rlo_mpi.c` against a real MPI library across hosts; and
  - its own JAX controller, federated by `jax.distributed.initialize`
    into ONE global device mesh (CPU devices locally, the host's TPU
    chips in production — docs/DEPLOY.md maps a v5e-16 pod).

The consensus-gated collective is then genuinely distributed end to end:
the proposal/vote/decision frames are real cross-process engine traffic
(any process may initiate — rootless), each process judges its OWN local
state, and the approved action is one XLA AllReduce over the global mesh
(cross-process CPU collectives locally; ICI/DCN on a pod). A veto by any
single process blocks the device collective on every process.

Launch (single host, 4 "hosts" as processes):

    rlo_tpu/native/femtompirun -n 4 python your_prog.py

with `PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu` in the environment and a
free coordinator port in `RLO_COORDINATOR` (see
tests/test_multihost.py / benchmarks/multihost_demo.py).
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Tuple

import numpy as np

#: default coordination-service endpoint (process 0 binds it)
_DEFAULT_COORD = "127.0.0.1:28741"


class MultiHostContext:
    """Engine control plane + federated JAX data plane for one process.

    Construction order matters: `jax.distributed.initialize` must run
    before the first JAX backend touch, and needs (rank, world_size),
    which come from the engine world — so the engine backend comes up
    first (pure ctypes, no JAX).
    """

    def __init__(self, coordinator: Optional[str] = None,
                 transport: Optional[str] = None):
        """``transport``: 'mpi' (femtompi shm rings locally, a real MPI
        across hosts) or 'tcp' (the socket-mesh transport, rlo_tcp.c —
        crosses hosts with no MPI installed; launch via tcprun or with
        RLO_TCP_HOSTS). Default: $RLO_TRANSPORT, else autodetect from
        the launcher's env (RLO_TCP_RANK -> tcp)."""
        from rlo_tpu.backend import MpiBackend, TcpBackend

        transport = (transport or os.environ.get("RLO_TRANSPORT")
                     or ("tcp" if os.environ.get("RLO_TCP_RANK")
                         else "mpi"))
        if transport not in ("mpi", "tcp"):
            raise ValueError(f"unknown transport {transport!r}")
        self.backend = (TcpBackend if transport == "tcp"
                        else MpiBackend)()
        self.rank = self.backend.rank
        self.world_size = self.backend.world_size

        import jax

        coordinator = (coordinator
                       or os.environ.get("RLO_COORDINATOR")
                       or _DEFAULT_COORD)
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=self.world_size,
                                   process_id=self.rank)
        self._jax = jax
        # one mesh row per PROCESS: the first local device of each
        # process, in process order — every shard of a mesh-sharded
        # array then lives in a different OS process
        by_proc = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, []).append(d)
        if sorted(by_proc) != list(range(self.world_size)):
            raise RuntimeError(
                f"jax.distributed federated {sorted(by_proc)} processes; "
                f"expected {self.world_size} (is JAX_PLATFORMS=cpu set "
                f"in the environment, before python starts?)")
        from jax.sharding import Mesh

        self.mesh_devices = [by_proc[p][0]
                             for p in range(self.world_size)]
        self.mesh = Mesh(np.array(self.mesh_devices), ("hosts",))
        self._psum_cache: dict = {}

    # -- data plane ----------------------------------------------------
    def _global_array(self, local: np.ndarray):
        """Assemble the (ws, *local.shape) global array whose row r is
        process r's local tensor, sharded one row per process."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        jax = self._jax
        local = np.asarray(local)
        sharding = NamedSharding(self.mesh, P("hosts"))
        shard = jax.device_put(local[None],
                               self.mesh_devices[self.rank])
        return jax.make_array_from_single_device_arrays(
            (self.world_size, *local.shape), sharding, [shard])

    def device_allreduce(self, local: np.ndarray,
                         op: str = "sum") -> np.ndarray:
        """One XLA AllReduce across all processes' device memories;
        returns this process's (replicated) result. This is the real
        cross-process data plane — not a host gather."""
        from jax.sharding import PartitionSpec as P

        jax = self._jax
        key = (op, np.asarray(local).shape, str(np.asarray(local).dtype))
        if key not in self._psum_cache:
            from rlo_tpu.ops import tpu_collectives as tc

            def step(v):
                return tc.allreduce(v[0], "hosts", op=op,
                                    use_pallas=False)[None]

            self._psum_cache[key] = jax.jit(jax.shard_map(
                step, mesh=self.mesh, in_specs=P("hosts"),
                out_specs=P("hosts")))
        out = self._psum_cache[key](self._global_array(local))
        return np.asarray(out.addressable_shards[0].data[0])

    # -- the bridge ----------------------------------------------------
    def propose_collective(self, local: np.ndarray, *,
                           proposer: int = 0,
                           judge: Optional[Callable] = None,
                           op: str = "sum") -> Tuple[int, Optional[np.ndarray]]:
        """Leaderless-consensus-gated cross-process collective.

        Process ``proposer`` (ANY process — rootless) initiates; every
        process runs ``judge(local)`` on its OWN tensor and votes; the
        votes AND-merge up the engine's skip-ring tree as real
        cross-process frames; the decision broadcasts. Only on approval
        does the device collective run — a veto on one process blocks
        it on all (the distributed form of HybridBackend
        .propose_collective, which simulated ranks in one controller).

        Returns (decision, result): (1, summed array) on approval,
        (0, None) when any process vetoed.
        """
        vote = 1 if judge is None else int(bool(judge(local)))
        decision = self.backend.consensus(vote, proposer=proposer)
        if not decision:
            return 0, None
        return 1, self.device_allreduce(local, op=op)

    def sub_context(self, members) -> Optional["MultiHostContext"]:
        """Scoped context over a subset of the hosts (round-4 VERDICT:
        consensus over a rank subset on the REAL-process path).
        Collective — every process must call it with the same members.
        Member processes get a context whose control plane is the
        engine sub-communicator (backend.sub_group: subset frames on
        their own comm, demuxed on the same transport) and whose data
        plane is the sub-mesh of the members' devices; a veto by any
        member blocks the subset collective on every member, while
        non-members (who get None) keep using the parent. Matches the
        reference's engine-on-any-communicator (rootless_ops.c:467,
        1461)."""
        sub = self.backend.sub_group(members)
        if sub is None:
            return None
        return _SubContext(self, sub, sorted(set(int(m)
                                                 for m in members)))

    def close(self) -> None:
        self.backend.close()


class _SubContext(MultiHostContext):
    """Member-scoped MultiHostContext: ``rank`` is the SUBSET POSITION
    and the mesh spans only the members' devices. Ops are inherited —
    the indexing contract (positions everywhere) is what changes."""

    def __init__(self, parent: MultiHostContext, sub_backend, members):
        from jax.sharding import Mesh

        self.backend = sub_backend
        self.rank = sub_backend.pos
        self.world_size = sub_backend.world_size
        self._jax = parent._jax
        self.mesh_devices = [parent.mesh_devices[m] for m in members]
        self.mesh = Mesh(np.array(self.mesh_devices), ("hosts",))
        self._psum_cache: dict = {}

    def sub_context(self, members):
        raise NotImplementedError("nested sub-contexts are not supported")
