"""Progress engine: cooperative-polling state machine driving all ops.

Reference parity: `struct progress_engine` + `make_progress_gen`
(/root/reference/rootless_ops.c:202-253, 551-658), the EngineManager global
registry (:33-47, 407-466), pickup/recycle delivery (:938-992), the rootless
broadcast initiation/forwarding (:1581-1604, 1104-1225) and the IAR
leaderless-consensus handlers (:668-932). Same control-flow inversion as the
reference: **no background thread** — every public call turns the gears via
``progress_all()``, which steps every live engine so engines co-progress each
other (multi-engine multiplexing, testcases.c:110-241).

Deliberate departures from the reference (SURVEY.md §7 "quirks not to
replicate"):
  - votes are sent nonblocking (the reference uses blocking MPI_Send at
    rootless_ops.c:735 — a latent deadlock at scale);
  - frames are variable-size (reference always ships 32 KB, :1588);
  - explicit state enums instead of flag soup (the abandoned
    progress_engine.h design the reference never landed);
  - messages are plain GC'd objects — pickup/recycle keeps the reference's
    delivery *semantics* (a message can be picked up while still
    forwarding) without manual buffer ownership;
  - reliable delivery and bounded ops (net-new; the reference has no
    timeouts, retries, or loss recovery — SURVEY.md §5): opt-in ARQ
    (``arq_rto``) retransmits unacked frames with per-link sequence
    numbers and receive-side dedup, and op deadlines (``op_deadline`` /
    per-call ``deadline=``) make every bcast/proposal complete or FAIL
    deterministically, with a rootless ABORT unparking relays
    (docs/DESIGN.md §6).
"""

from __future__ import annotations

import enum
import itertools
import logging
import struct
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Set

from rlo_tpu import topology
from rlo_tpu.transport.base import SendHandle, Transport
from rlo_tpu.utils.metrics import Histogram, LinkStats
from rlo_tpu.utils.tracing import TRACER, Ev
from rlo_tpu.wire import (ARQ_EXEMPT_TAGS, BCAST_TAGS, Frame, MSG_SIZE_MAX,
                          Tag, restamp_seq)

logger = logging.getLogger("rlo_tpu.engine")


def _trace_ident(tag: int, frame: Frame) -> int:
    """Correlation identity a trace event carries in its c field: the
    per-origin exactly-once seq for Tag.BCAST (it travels in the vote
    field), the pid for everything else (proposals/decisions/aborts
    carry the round pid there; FAILURE notices the failed rank)."""
    return frame.vote if tag == Tag.BCAST else frame.pid


class ReqState(enum.IntEnum):
    """Reference RLO_Req_stat (rootless_ops.h:63-68)."""
    COMPLETED = 0
    IN_PROGRESS = 1
    FAILED = 2
    INVALID = 3


# judge/action callbacks: (payload: bytes, app_ctx) -> int / None
# (reference iar_cb_func_t, rootless_ops.h:77)
JudgeCb = Callable[[bytes, object], int]
ActionCb = Callable[[bytes, object], object]


@dataclass
class UserMsg:
    """What pickup_next hands the application (~RLO_user_msg,
    rootless_ops.h:84-91, decoded as in _user_msg_mock :920-932)."""
    type: int          # Tag value
    origin: int        # broadcast initiator rank
    pid: int = -1
    vote: int = -1
    data: bytes = b""


@dataclass
class ProposalState:
    """Per-proposal consensus bookkeeping (~Proposal_state,
    rootless_ops.c:184-194)."""
    pid: int = -1
    gen: int = -1                # round generation (disambiguates pid reuse)
    recv_from: int = -1          # parent in the vote tree
    vote: int = 1
    votes_needed: int = 0
    votes_recved: int = 0
    state: ReqState = ReqState.INVALID
    proposal_payload: bytes = b""
    decision_handles: List[SendHandle] = field(default_factory=list)
    decision_pending: bool = False
    # direct children whose (subtree-merged) votes are still outstanding;
    # lets the failure detector discount a dead child so consensus
    # completes instead of waiting forever (net-new vs the reference)
    await_from: List[int] = field(default_factory=list)
    # additional vote-tree parents acquired from duplicate proposals
    # (re-formed overlay trees during view changes); they receive the
    # SAME merged vote as recv_from when the round resolves — voting an
    # interim verdict to them could lose a subtree veto still in flight
    # (round-2 advisor finding)
    dup_parents: List[int] = field(default_factory=list)
    # the merged vote has been determined and sent up — a later
    # duplicate's parent can safely receive it immediately
    resolved: bool = False
    # absolute clock time by which the round must resolve, else the
    # proposer transitions to FAILED and broadcasts a rootless ABORT
    # (op-deadline machinery; None = no deadline)
    deadline: Optional[float] = None


@dataclass
class _Msg:
    """Internal in-flight message (~RLO_msg_t, rootless_ops.h:93-146)."""
    frame: Frame
    tag: int
    src: int = -1                       # immediate sender (~MPI_SOURCE)
    send_handles: List[SendHandle] = field(default_factory=list)
    pickup_done: bool = False
    fwd_done: bool = False
    prop_state: Optional[ProposalState] = None
    # op-deadline bookkeeping (net-new): absolute clock time by which
    # this op's outbound work must complete, else it transitions to
    # FAILED and is abandoned instead of tracked forever
    deadline: Optional[float] = None
    state: ReqState = ReqState.IN_PROGRESS
    # metrics stamps (None = metrics were off at the event — a None
    # sentinel, not 0.0, so an injectable simulated clock starting at
    # t=0 still records): initiation time of a locally-initiated bcast
    # (op-latency histogram) and receipt time of a deliverable message
    # (pickup-wait histogram)
    born: Optional[float] = None
    arrived: Optional[float] = None

    def sends_done(self) -> bool:
        return all(h.done() for h in self.send_handles)


@dataclass
class _ArqEntry:
    """One unacknowledged reliable frame awaiting its cumulative ACK
    (the sender half of the ARQ state machine)."""
    tag: int
    raw: bytes            # encoded frame, seq already stamped
    due: float            # next retransmit time
    retries: int = 0
    sent: float = 0.0     # first-transmission time (RTT sampling)


class EngineManager:
    """Global registry of live engines (~EngineManager/Active_Engines,
    rootless_ops.c:33-47). progress_all steps every engine one turn."""

    def __init__(self):
        self.engines: List["ProgressEngine"] = []
        self._ids = itertools.count()
        self._stepping = False

    def append(self, eng: "ProgressEngine") -> int:
        self.engines.append(eng)
        return next(self._ids)

    def remove(self, eng: "ProgressEngine") -> None:
        if eng in self.engines:
            self.engines.remove(eng)

    def progress_all(self) -> None:
        # handlers may initiate broadcasts (e.g. the decision bcast inside
        # the vote handler), which call back into progress_all — make
        # re-entrant turns no-ops instead of recursing
        if self._stepping:
            return
        self._stepping = True
        try:
            for eng in list(self.engines):
                eng._progress_once()
        finally:
            self._stepping = False


MANAGER = EngineManager()


def progress_all() -> None:
    """Turn every live engine's gears one step (~RLO_make_progress_all,
    rootless_ops.c:538-549)."""
    MANAGER.progress_all()


class ProgressEngine:
    """One rank's engine instance over a transport endpoint.

    ~RLO_progress_engine_new (rootless_ops.c:467-522). Multiple engines may
    coexist (each over its own transport, the analogue of the reference's
    dup'ed communicator per engine).
    """

    def __init__(self, transport: Transport,
                 judge_cb: Optional[JudgeCb] = None,
                 app_ctx: object = None,
                 action_cb: Optional[ActionCb] = None,
                 msg_size_max: int = MSG_SIZE_MAX,
                 manager: EngineManager = MANAGER,
                 failure_timeout: Optional[float] = None,
                 heartbeat_interval: Optional[float] = None,
                 failure_cb: Optional[Callable[[int, bool], None]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 members: Optional[Sequence[int]] = None,
                 fanout: Optional[str] = None,
                 arq_rto: Optional[float] = None,
                 arq_max_retries: int = 8,
                 op_deadline: Optional[float] = None):
        """``failure_timeout`` (seconds) enables the net-new failure
        detector (the reference defines RLO_FAILED but never assigns it,
        SURVEY.md §5): ranks heartbeat their ring successor every
        ``heartbeat_interval`` (default timeout/4) and declare their
        predecessor failed after ``failure_timeout`` of silence, then
        notify the world with a rootless FAILURE broadcast. Survivors
        elastically re-form the overlay (topology recomputed over the
        alive set) so broadcasts and consensus keep working.
        ``failure_cb(rank, detected_locally)`` fires once per learned
        failure. ``clock`` is injectable for deterministic tests.

        ``members`` restricts the engine to a RANK SUBSET — the
        reference's engines-over-sub-communicators capability
        (RLO_progress_engine_new on any MPI_Comm,
        rootless_ops.c:467, 1461). The overlay topology is computed
        over virtual ranks 0..len(members)-1 (the same translation the
        elastic re-forming uses), so bcast/IAR span exactly the member
        set; non-members never see this engine's traffic. This rank
        must be a member; create the subset engine only on member
        ranks.

        ``fanout`` selects the spanning-tree shape (mirror of the C
        engine's rlo_engine_set_fanout / RLO_FANOUT): 'skip_ring'
        (default — the reference overlay) or 'flat' (depth-1: the
        origin sends to every live member, receivers are leaves — the
        right shape when scheduling latency dominates). Rootlessness,
        dedup, and IAR vote accounting are schedule-independent.
        Default from $RLO_FANOUT, else 'skip_ring'.

        ``arq_rto`` (seconds) enables the reliable-delivery layer (the
        reference is fire-and-forget: no timeouts, retries, or loss
        recovery, SURVEY.md §5): every engine frame except heartbeats
        and ACKs is stamped with a per-(src, dst) link sequence number
        and kept in a retransmit queue until the destination's
        cumulative ACK covers it; unacked frames retransmit after
        ``arq_rto`` with exponential backoff, giving up after
        ``arq_max_retries`` (liveness of a persistently silent peer is
        the failure detector's job, not ARQ's). Receivers dedup on
        (sender, seq) BEFORE tag dispatch, so retransmits are
        idempotent through the store-and-forward broadcast path.

        ``op_deadline`` (seconds, relative) is the default deadline for
        bcast/submit_proposal ops; per-call ``deadline=`` overrides. A
        proposal that has not resolved by its deadline transitions to
        ReqState.FAILED (finally assigning the reference's dead enum
        value) and the proposer broadcasts a rootless Tag.ABORT so
        relays unpark the round and deliver the failure to the app via
        pickup instead of waiting forever; the pid is then free to
        resubmit on the (possibly re-formed) survivor topology."""
        ws = transport.world_size
        if ws < 2:  # bcomm_init rejects this (rootless_ops.c:1464)
            raise ValueError(f"world_size must be >= 2, got {ws}")
        if fanout is None:
            import os
            fanout = ("flat" if os.environ.get("RLO_FANOUT") == "flat"
                      else "skip_ring")
        if fanout not in ("skip_ring", "flat"):
            raise ValueError(
                f"unknown fanout {fanout!r}; known: 'skip_ring', 'flat'")
        self.fanout = fanout
        self.transport = transport
        self.rank = transport.rank
        self.world_size = ws
        self.msg_size_max = msg_size_max
        self.judge_cb = judge_cb
        self.app_ctx = app_ctx
        self.action_cb = action_cb

        # topology snapshot (~bcomm fields)
        self.my_level = topology.level(ws, self.rank)
        self.initiator_targets = topology.initiator_targets(ws, self.rank)

        # queues (~rootless_ops.c:206-211); recv queue is implicit in
        # transport.poll()
        self.queue_wait: List[_Msg] = []
        self.queue_pickup: deque = deque()
        self.queue_wait_and_pickup: List[_Msg] = []
        self.queue_iar_pending: List[_Msg] = []

        # counters (~rootless_ops.c:217-219 and header total_pickup)
        self.sent_bcast_cnt = 0
        self.recved_bcast_cnt = 0
        self.total_pickup = 0

        self.my_own_proposal = ProposalState()
        self.my_proposal_payload: bytes = b""
        # per-engine round counter: a proposer may reuse a pid across
        # sequential rounds; the generation travels in the proposal
        # frame's vote field and is echoed by every vote and decision,
        # so a stale message from an earlier same-pid round can never
        # be merged into a later one. Persisted by engine snapshots so
        # a restored engine never reissues a pre-snapshot generation.
        self._gen_next = 1

        # exactly-once broadcast bookkeeping: every Tag.BCAST frame this
        # rank initiates is stamped with a monotone sequence number (in
        # the frame's otherwise-unused vote field); receivers dedup on
        # (origin, seq) so a broadcast whose forwarding crosses a
        # membership change can never deliver twice, and survivors
        # re-flood their recent-broadcast log on every view change so it
        # cannot be lost either (see _mark_failed)
        self._bcast_seq = 0
        # origin -> [contig, set(seqs > contig)]: all seqs <= contig seen
        self._seen_bcast: dict = {}
        # ring log of recently initiated/forwarded BCAST frames (raw
        # bytes), flooded point-to-point on view changes
        self._recent_bcasts: deque = deque(maxlen=64)
        # settled consensus rounds: decisions forwarded by a mix of
        # old- and new-topology trees during a view change can reach a
        # rank twice; a settled (pid, gen) is delivered exactly once
        # (the IAR analogue of the (origin, seq) broadcast dedup)
        self._settled_rounds: deque = deque(maxlen=256)
        self._settled_set: Set = set()

        # failure detection (net-new; SURVEY.md §5 "failure detection:
        # none" in the reference)
        self.failure_timeout = failure_timeout
        self.heartbeat_interval = heartbeat_interval or (
            failure_timeout / 4 if failure_timeout else None)
        self.failure_cb = failure_cb
        self.clock = clock
        self.failed: Set[int] = set()
        self.suspected_self = False
        self._alive: List[int] = list(range(ws))
        self._v = {r: r for r in range(ws)}  # real rank -> virtual rank
        self._hb_last_sent = float("-inf")
        self._hb_seen: dict = {}  # sender rank -> last heartbeat clock

        # reliable delivery (ARQ; net-new — SURVEY.md §5 "no timeouts,
        # retries, or loss recovery" in the reference)
        if arq_rto is not None and arq_rto <= 0:
            raise ValueError(f"arq_rto must be positive, got {arq_rto}")
        self.arq_rto = arq_rto
        self.arq_max_retries = arq_max_retries
        self._tx_seq: dict = {}       # dst -> next link seq
        self._tx_unacked: dict = {}   # dst -> {seq: _ArqEntry}
        self._tx_skip: dict = {}      # dst -> [given-up seq, next send]
        self._rx_seen: dict = {}      # src -> [contig, set(seqs > contig)]
        self._ack_due: Set[int] = set()  # srcs owed a cumulative ACK
        # ARQ counters — part of the metrics registry snapshot
        # (metrics()["counters"]); the attributes are the canonical
        # storage and remain the public aliases PR-1 tests read
        self.arq_retransmits = 0
        self.arq_dup_drops = 0
        self.arq_gave_up = 0

        # op deadlines (net-new): ops complete or FAIL deterministically
        self.op_deadline = op_deadline
        self.ops_failed = 0

        # metrics registry (docs/DESIGN.md §7): per-link frame/byte/
        # retransmit/RTT accounting + op-latency histograms, snapshot
        # via metrics(). Disabled by default — the hot-path cost of
        # the disabled state is ONE branch per send/receive (the
        # overhead contract); counters above are plain ints and always
        # live. _mx_on gates everything that needs a clock read or a
        # per-link dict access.
        self._mx_on = False
        self._mx_link: dict = {}          # peer -> LinkStats
        self._h_bcast = Histogram()       # bcast init -> sends complete
        self._h_prop = Histogram()        # proposal submit -> decision
        self._h_pickup = Histogram()      # frame receipt -> pickup
        self._prop_born: Optional[float] = None

        if members is not None:
            group = sorted(set(int(r) for r in members))
            if len(group) < 2:
                raise ValueError(
                    f"a sub-communicator needs >= 2 members, got "
                    f"{group}")
            if any(r < 0 or r >= ws for r in group):
                raise ValueError(
                    f"members {group} out of range [0, {ws})")
            if self.rank not in group:
                raise ValueError(
                    f"rank {self.rank} is not in members {group}")
            # subset = the translated-topology machinery with the
            # non-members permanently excluded: every routed path
            # (_cur_initiator_targets, _fwd_targets, _ring_neighbors,
            # re-flood, discounting) already consults the alive view
            self.failed = set(range(ws)) - set(group)
            self._alive = group
            self._v = {r: i for i, r in enumerate(group)}
        self.group = list(self._alive)

        self.manager = manager
        self.engine_id = manager.append(self)

    # ------------------------------------------------------------------
    # Reliable delivery: ARQ send/receive (net-new — the reference has
    # no loss recovery at all, SURVEY.md §5). Sender half: every
    # non-exempt frame gets a per-(src, dst) link seq and sits in a
    # retransmit queue until the cumulative ACK covers it. Receiver
    # half: dedup on (immediate sender, seq) before tag dispatch —
    # retransmits are idempotent everywhere, including mid-forward in
    # the store-and-forward bcast path — then schedule a cumulative
    # ACK back (one per sender per progress turn, plus a piggyback on
    # every heartbeat). Exactly-once composes by layers: link-level
    # (src, seq) dedup absorbs ARQ retransmits; app-level (origin,
    # seq) / settled-(pid, gen) dedup absorbs view-change re-floods,
    # which travel with FRESH link seqs.
    # ------------------------------------------------------------------
    def _link(self, peer: int) -> LinkStats:
        ls = self._mx_link.get(peer)
        if ls is None:
            ls = self._mx_link[peer] = LinkStats()
        return ls

    def _isend_counted(self, dst: int, tag: int, raw: bytes) -> SendHandle:
        """tx-accounted isend for the out-of-band paths (heartbeats,
        ACKs, retransmits); fresh frames go through _send_raw, which
        inlines the same accounting to keep the hot path one branch."""
        if self._mx_on:
            ls = self._link(dst)
            ls.tx_frames += 1
            ls.tx_bytes += len(raw)
        return self.transport.isend(dst, int(tag), raw)

    def _send_raw(self, dst: int, tag: int, raw: bytes) -> SendHandle:
        """The one gate every fresh engine frame leaves through: stamps
        the link seq and registers the retransmit entry when ARQ is
        on; per-link tx accounting when metrics are on (one branch
        when off — the §7 overhead contract)."""
        if self._mx_on:
            ls = self._link(dst)
            ls.tx_frames += 1
            ls.tx_bytes += len(raw)
        if self.arq_rto is None or tag in ARQ_EXEMPT_TAGS:
            return self.transport.isend(dst, int(tag), raw)
        seq = self._tx_seq.get(dst, 0)
        self._tx_seq[dst] = seq + 1
        raw = restamp_seq(raw, seq)
        due = self.clock() + self.arq_rto
        self._tx_unacked.setdefault(dst, {})[seq] = _ArqEntry(
            tag=int(tag), raw=raw, due=due, sent=due - self.arq_rto)
        return self.transport.isend(dst, int(tag), raw)

    def _send(self, dst: int, tag: int, frame: Frame) -> SendHandle:
        return self._send_raw(dst, tag, frame.encode())

    @staticmethod
    def _window_record(ent: list, seq: int) -> bool:
        """Record ``seq`` in a [contig, set(seqs > contig)] watermark+
        window dedup entry; True when already seen. ONE implementation
        for both key spaces — the link-level (sender, seq) ARQ dedup
        and the broadcast-level (origin, seq) dedup (mirror of the C
        side's window_record). The 4096 compaction bounds out-of-order
        state by assuming the oldest half's gaps are lost, not late —
        see the at-least-once bound note in docs/DESIGN.md §6."""
        if seq <= ent[0] or seq in ent[1]:
            return True
        ent[1].add(seq)
        while ent[0] + 1 in ent[1]:
            ent[0] += 1
            ent[1].remove(ent[0])
        if len(ent[1]) > 4096:
            ent[0] = sorted(ent[1])[len(ent[1]) // 2]
            ent[1] = {s for s in ent[1] if s > ent[0]}
        return False

    def _rx_is_dup(self, src: int, seq: int) -> bool:
        """Link-level exactly-once receipt check, keyed on (immediate
        sender, seq)."""
        return self._window_record(
            self._rx_seen.setdefault(src, [-1, set()]), seq)

    def _rx_cum(self, src: int) -> int:
        return self._rx_seen.get(src, [-1, set()])[0]

    def _rx_skip(self, src: int, upto: int) -> None:
        """Sender-side skip notice: ``src`` gave up retransmitting
        everything <= ``upto``; advance the watermark so the hole can
        never block cumulative ACKs for later frames (without this,
        one given-up frame would force every subsequent frame on the
        link through the full retransmit-to-exhaustion cycle)."""
        ent = self._rx_seen.setdefault(src, [-1, set()])
        if upto > ent[0]:
            ent[0] = upto
            ent[1] = {s for s in ent[1] if s > upto}
            while ent[0] + 1 in ent[1]:  # holes below may now close
                ent[0] += 1
                ent[1].remove(ent[0])
            self._ack_due.add(src)  # tell the sender the new cum

    def _on_ack(self, src: int, cum: int) -> None:
        """Cumulative ACK from ``src``: everything <= cum is delivered;
        drop it from the retransmit queue (and retire a pending SKIP
        notice the ACK proves was absorbed)."""
        sk = self._tx_skip.get(src)
        if sk is not None and cum >= sk[0]:
            del self._tx_skip[src]
        q = self._tx_unacked.get(src)
        if not q:
            return
        now = self.clock() if self._mx_on else 0.0
        for seq in [s for s in q if s <= cum]:
            ent = q.pop(seq)
            if self._mx_on and ent.retries == 0:
                # RTT sample from ack timing — never-retransmitted
                # frames only (Karn's rule: a retransmitted frame's
                # ack is ambiguous about which copy it answers)
                self._link(src).rtt_sample((now - ent.sent) * 1e6)

    def _arq_tick(self) -> None:
        """Retransmit sweep: resend overdue unacked frames with
        exponential backoff; give up after arq_max_retries (a peer
        that silent is the failure detector's problem).

        Every give-up arms a SKIP notice (an ACK frame with the
        vote=-2 sentinel, pid = abandoned seq) telling the receiver to
        advance its watermark over the permanent hole — otherwise one
        given-up frame would pin the cumulative ACK below every later
        seq on the link, forcing each of them through the full
        retransmit-to-exhaustion cycle. The notice is only SENT once
        no lower seq is still being retried (the receiver's advanced
        watermark would misread those retransmits as duplicates), and
        it repeats at rto cadence until an ACK at or past the skipped
        seq proves the watermark moved."""
        now = self.clock()
        for dst, q in self._tx_unacked.items():
            if dst in self.failed:
                if q:
                    q.clear()
                self._tx_skip.pop(dst, None)
                continue
            for seq, ent in list(q.items()):
                if now < ent.due:
                    continue
                if ent.retries >= self.arq_max_retries:
                    del q[seq]
                    self.arq_gave_up += 1
                    sk = self._tx_skip.setdefault(dst, [-1, now])
                    if seq > sk[0]:
                        sk[0] = seq
                        sk[1] = now  # send immediately
                    continue
                ent.retries += 1
                ent.due = now + self.arq_rto * (2 ** ent.retries)
                self.arq_retransmits += 1
                if self._mx_on:
                    self._link(dst).retransmits += 1
                # same raw bytes, same seq: the receiver dedups
                self._isend_counted(dst, ent.tag, ent.raw)
            sk = self._tx_skip.get(dst)
            if sk is not None and now >= sk[1] and \
                    all(s > sk[0] for s in q):
                self._isend_counted(
                    dst, int(Tag.ACK),
                    Frame(origin=self.rank, pid=sk[0], vote=-2).encode())
                sk[1] = now + self.arq_rto

    def _flush_acks(self) -> None:
        """Send the owed cumulative ACKs (at most one per sender per
        progress turn; ACKs are themselves unreliable — a lost one
        just costs one more retransmit+dedup round trip)."""
        for src in self._ack_due:
            if src in self.failed or src == self.rank:
                continue
            self._isend_counted(
                src, int(Tag.ACK),
                Frame(origin=self.rank, vote=self._rx_cum(src)).encode())
        self._ack_due.clear()

    def arq_unacked(self) -> int:
        """Outstanding reliable frames not yet covered by an ACK."""
        return sum(len(q) for q in self._tx_unacked.values())

    # ------------------------------------------------------------------
    # Metrics registry (docs/DESIGN.md §7). Counter keys, nesting, and
    # histogram layout are IDENTICAL to the C engine's rlo_engine_stats
    # (bindings.NativeEngine.metrics()) — asserted by the metrics-parity
    # test — so dashboards and tests consume one schema.
    # ------------------------------------------------------------------
    def enable_metrics(self, on: bool = True) -> None:
        """Turn on per-link frame/byte/RTT accounting and op-latency
        histograms. Off (the default), the residual cost is one branch
        per send/receive; counters (ARQ, bcast/pickup totals) are plain
        int increments and always live."""
        self._mx_on = bool(on)

    def metrics(self) -> dict:
        """Snapshot the engine's metrics as a nested dict (JSON-ready):
        ``counters`` (monotone totals incl. the ARQ counters),
        ``queues`` (live depths; ``pickup`` + ``wait_and_pickup`` is
        the pickup backlog), ``links`` (per-peer tx/rx frames+bytes,
        retransmits, dup drops, ack-measured RTT EWMA; all peers
        present, zeros when metrics are off), and ``op_latency_usec``
        (bcast init->fan-out-complete, proposal submit->decision,
        frame receipt->pickup)."""
        links = {}
        for peer in range(self.world_size):
            if peer == self.rank:
                continue
            ls = self._mx_link.get(peer)
            # string peer keys: the in-memory dict and its JSON
            # round-trip (benchmarks emit snapshots) share one schema
            links[str(peer)] = ls.snapshot() if ls is not None \
                else LinkStats().snapshot()
        return {
            "counters": {
                "sent_bcast": self.sent_bcast_cnt,
                "recved_bcast": self.recved_bcast_cnt,
                "total_pickup": self.total_pickup,
                "ops_failed": self.ops_failed,
                "arq_retransmits": self.arq_retransmits,
                "arq_dup_drops": self.arq_dup_drops,
                "arq_gave_up": self.arq_gave_up,
                "arq_unacked": self.arq_unacked(),
            },
            "queues": {
                "wait": len(self.queue_wait),
                "pickup": len(self.queue_pickup),
                "wait_and_pickup": len(self.queue_wait_and_pickup),
                "iar_pending": len(self.queue_iar_pending),
            },
            "links": links,
            "op_latency_usec": {
                "bcast_complete": self._h_bcast.snapshot(),
                "proposal_resolve": self._h_prop.snapshot(),
                "pickup_wait": self._h_pickup.snapshot(),
            },
        }

    # ------------------------------------------------------------------
    # Rootless broadcast (~RLO_bcast_gen, rootless_ops.c:1581-1604)
    # ------------------------------------------------------------------
    def bcast(self, payload: bytes, tag: Tag = Tag.BCAST,
              pid: int = -1, vote: int = -1,
              deadline: Optional[float] = None) -> _Msg:
        """Initiate a broadcast from this rank — no pre-designated root."""
        if Tag(tag) not in BCAST_TAGS:
            raise ValueError(
                f"tag {Tag(tag).name} is not store-and-forward; only "
                f"{sorted(t.name for t in BCAST_TAGS)} may be broadcast")
        if len(payload) > self.msg_size_max:
            raise ValueError(
                f"payload {len(payload)}B exceeds msg_size_max "
                f"{self.msg_size_max}B")
        if Tag(tag) == Tag.BCAST:
            # the vote field of plain broadcasts belongs to the
            # exactly-once sequence stamp now; a caller-supplied value
            # would be misread by receivers as a (likely already-seen)
            # seq and silently dropped cluster-wide
            if vote != -1:
                raise ValueError(
                    "Tag.BCAST frames carry the exactly-once sequence "
                    "number in the vote field; pass payload data in the "
                    "payload, not vote")
            vote = self._bcast_seq
            self._bcast_seq += 1
        frame = Frame(origin=self.rank, pid=pid, vote=vote, payload=payload)
        raw = frame.encode()
        if Tag(tag) in (Tag.BCAST, Tag.IAR_DECISION, Tag.ABORT):
            # decisions join the re-flood log: a decision lost in a
            # view-change window would otherwise leave relayed rounds
            # parked forever (blocking checkpoint) — the settled-set
            # dedup absorbs the flood exactly like (origin, seq) does
            # for broadcasts. Aborts ride the same log for the same
            # reason: an abort lost with a dead relay would leave the
            # aborted round parked at its descendants.
            self._recent_bcasts.append((int(tag), raw))
        msg = _Msg(frame=frame, tag=int(tag))
        if deadline is None:
            deadline = self.op_deadline
        if deadline is not None:
            msg.deadline = self.clock() + deadline
        if self._mx_on and Tag(tag) == Tag.BCAST:
            msg.born = self.clock()
        for dst in self._cur_initiator_targets():  # furthest-first
            msg.send_handles.append(self._send_raw(dst, int(tag), raw))
        self.queue_wait.append(msg)
        self.sent_bcast_cnt += 1
        TRACER.emit(self.rank, Ev.BCAST_INIT, int(tag), len(payload),
                    _trace_ident(Tag(tag), frame))
        self.manager.progress_all()
        return msg

    # ------------------------------------------------------------------
    # IAR leaderless consensus (~rootless_ops.c:668-932)
    # ------------------------------------------------------------------
    def submit_proposal(self, proposal: bytes, pid: int,
                        deadline: Optional[float] = None) -> int:
        """Propose; every rank judges; AND-aggregated votes come back up the
        reverse broadcast tree; we then broadcast the decision
        (~RLO_submit_proposal, rootless_ops.c:876-906).

        Returns the decision if it completed within this call's progress
        turn, else -1 (poll with check_proposal_state / vote_my_proposal).

        ``deadline`` (seconds, relative; default ``op_deadline``): if the
        round has not resolved by then, the proposal transitions to
        ReqState.FAILED and a rootless Tag.ABORT broadcast unparks the
        round at every relay — the op completes or fails
        deterministically instead of hanging on a lost vote.
        """
        p = self.my_own_proposal
        if p.state == ReqState.IN_PROGRESS:
            raise RuntimeError(
                f"rank {self.rank}: proposal pid={p.pid} is still in "
                f"progress; wait for completion before submitting another")
        p.pid = pid
        if deadline is None:
            deadline = self.op_deadline
        p.deadline = None if deadline is None else self.clock() + deadline
        # rank-qualified (counter * world_size + rank) so two proposers
        # reusing one pid can never collide on generation either, with
        # no overflow for any realistic rank count or round count
        p.gen = self._gen_next * self.world_size + self.rank
        self._gen_next += 1
        p.vote = 1
        p.await_from = list(self._cur_initiator_targets())
        p.votes_needed = len(p.await_from)
        p.votes_recved = 0
        p.state = ReqState.IN_PROGRESS
        p.decision_handles = []
        p.decision_pending = False
        self.my_proposal_payload = bytes(proposal)
        if self._mx_on:
            self._prop_born = self.clock()
        TRACER.emit(self.rank, Ev.PROPOSAL_SUBMIT, pid, 0, p.gen)
        # the proposal frame's vote field carries the round generation
        # (the reference leaves it at the initial vote 1, :888)
        self.bcast(proposal, tag=Tag.IAR_PROPOSAL, pid=pid, vote=p.gen)
        if p.votes_needed == 0 and p.state == ReqState.IN_PROGRESS \
                and not p.decision_pending:
            # no awaited voters (sole survivor after elastic
            # re-forming): nothing will ever call _on_vote
            self._complete_own_proposal(p)
            self.manager.progress_all()
        if p.state == ReqState.COMPLETED:
            return p.vote
        return -1

    def check_proposal_state(self) -> ReqState:
        """~RLO_check_proposal_state (rootless_ops.c:869-872)."""
        self.manager.progress_all()
        return self.my_own_proposal.state

    def vote_my_proposal(self) -> int:
        """Decision for my own proposal: -1 incomplete, 0 declined,
        1 approved (~RLO_get_vote_my_proposal, rootless_ops.c:1666-1673)."""
        self.manager.progress_all()
        if self.my_own_proposal.state != ReqState.COMPLETED:
            return -1
        return self.my_own_proposal.vote

    # ------------------------------------------------------------------
    # Delivery (~RLO_user_pickup_next / RLO_user_msg_recycle,
    # rootless_ops.c:938-992)
    # ------------------------------------------------------------------
    def pickup_next(self) -> Optional[UserMsg]:
        """Next delivered message, or None. Messages still forwarding are
        eligible (wait_and_pickup first, then pickup — reference order)."""
        if self.queue_wait_and_pickup:
            msg = self.queue_wait_and_pickup.pop(0)
            msg.pickup_done = True
            self.queue_wait.append(msg)  # keep tracking its forwards
            return self._deliver(msg)
        if self.queue_pickup:
            msg = self.queue_pickup.popleft()
            msg.pickup_done = True
            return self._deliver(msg)
        return None

    def _deliver(self, msg: _Msg) -> UserMsg:
        self.total_pickup += 1
        if msg.arrived is not None:
            self._h_pickup.observe((self.clock() - msg.arrived) * 1e6)
        if TRACER.enabled:
            TRACER.emit(self.rank, Ev.DELIVER, msg.tag, msg.frame.origin,
                        _trace_ident(msg.tag, msg.frame), msg.src)
        return self._to_user(msg)

    @staticmethod
    def _to_user(msg: _Msg) -> UserMsg:
        f = msg.frame
        return UserMsg(type=msg.tag, origin=f.origin, pid=f.pid,
                       vote=f.vote, data=f.payload)

    # ------------------------------------------------------------------
    # The gear (~make_progress_gen, rootless_ops.c:551-641)
    # ------------------------------------------------------------------
    def _progress_once(self) -> None:
        # (a) my own decision broadcast completion -> proposal COMPLETED;
        # deadline expiry -> FAILED + rootless ABORT (op-deadline
        # machinery: the op terminates deterministically either way)
        p = self.my_own_proposal
        if p.state == ReqState.IN_PROGRESS and p.decision_pending:
            if all(h.done() for h in p.decision_handles):
                p.state = ReqState.COMPLETED
                p.decision_pending = False
                if self._prop_born is not None:
                    self._h_prop.observe(
                        (self.clock() - self._prop_born) * 1e6)
                    self._prop_born = None
        if (p.state == ReqState.IN_PROGRESS and not p.decision_pending
                and p.deadline is not None
                and self.clock() > p.deadline):
            self._abort_own_proposal(p)

        # (b) drain the transport, dispatch on tag
        while True:
            item = self.transport.poll()
            if item is None:
                break
            src, tag, raw = item
            if self.failure_timeout is not None and 0 <= src < \
                    self.world_size:
                # ANY frame proves the sender alive — under heavy
                # traffic this prevents heartbeat starvation when
                # membership views transiently diverge (each view picks
                # different ring successors)
                self._hb_seen[src] = self.clock()
            msg = _Msg(frame=Frame.decode(raw), tag=tag, src=src)
            if self._mx_on:
                if 0 <= src < self.world_size:
                    ls = self._link(src)
                    ls.rx_frames += 1
                    ls.rx_bytes += len(raw)
                msg.arrived = self.clock()
            if tag == Tag.ACK:
                if msg.frame.vote == -2 and msg.frame.pid >= 0:
                    # SKIP notice: the sender gave up on everything
                    # <= pid; advance the watermark over the hole
                    self._rx_skip(src, msg.frame.pid)
                else:
                    self._on_ack(src, msg.frame.vote)
                continue
            if self.arq_rto is not None and tag not in ARQ_EXEMPT_TAGS \
                    and msg.frame.seq >= 0:  # IntEnum: raw ints hash in
                # link-level exactly-once BEFORE tag dispatch: a
                # retransmitted frame must be idempotent everywhere
                # (dup suppression), and its receipt owes the sender a
                # cumulative ACK either way
                self._ack_due.add(src)
                if self._rx_is_dup(src, msg.frame.seq):
                    self.arq_dup_drops += 1
                    if self._mx_on:
                        self._link(src).dup_drops += 1
                    continue
            if tag == Tag.BCAST:
                self.recved_bcast_cnt += 1
                if self._bcast_is_dup(msg):
                    continue  # exactly-once: drop, don't re-forward
                self._recent_bcasts.append((int(tag), raw))
                self._bc_forward(msg)
            elif tag == Tag.IAR_PROPOSAL:
                self._on_proposal(msg)
            elif tag == Tag.IAR_VOTE:
                self._on_vote(msg)
            elif tag == Tag.IAR_DECISION:
                self.recved_bcast_cnt += 1
                self._on_decision(msg)
            elif tag == Tag.HEARTBEAT:
                # liveness already refreshed above for any frame; a
                # piggybacked cumulative ACK rides the payload
                if self.arq_rto is not None and \
                        len(msg.frame.payload) >= 4:
                    self._on_ack(src, struct.unpack_from(
                        "<i", msg.frame.payload)[0])
            elif tag == Tag.FAILURE:
                self._on_failure(msg)
            elif tag == Tag.ABORT:
                self._on_abort(msg)
            else:
                self._on_other(msg)

        # (b2) liveness: heartbeat my ring successor, watch my predecessor
        if self.failure_timeout is not None:
            self._failure_tick()

        # (b3) reliable delivery: retransmit overdue unacked frames,
        # then flush the cumulative ACKs this turn's receipts owe
        if self.arq_rto is not None:
            self._arq_tick()
            self._flush_acks()

        # (c) wait_and_pickup sweep (~_wait_and_pickup_queue_process :995).
        # Messages here are never picked up (pickup_next moves them to
        # queue_wait when it claims them), so completion always delivers.
        for msg in list(self.queue_wait_and_pickup):
            if msg.sends_done():
                msg.fwd_done = True
                if msg.state == ReqState.IN_PROGRESS:
                    msg.state = ReqState.COMPLETED
                self.queue_wait_and_pickup.remove(msg)
                self.queue_pickup.append(msg)
            elif msg.deadline is not None and self.clock() > msg.deadline:
                # op deadline: abandon the forwards but still deliver
                # locally (the payload arrived here; only the fan-out
                # is past deadline)
                msg.state = ReqState.FAILED
                self.ops_failed += 1
                msg.fwd_done = True
                self.queue_wait_and_pickup.remove(msg)
                self.queue_pickup.append(msg)

        # (d) wait-only sweep (~_wait_only_queue_cleanup :1015)
        for msg in list(self.queue_wait):
            if msg.sends_done():
                msg.fwd_done = True
                if msg.state == ReqState.IN_PROGRESS:
                    msg.state = ReqState.COMPLETED
                if msg.born is not None:
                    # locally-initiated bcast: init -> fan-out complete
                    self._h_bcast.observe(
                        (self.clock() - msg.born) * 1e6)
                self.queue_wait.remove(msg)
            elif msg.deadline is not None and self.clock() > msg.deadline:
                # op deadline: stop tracking — the op FAILED
                # deterministically instead of parking forever on a
                # handle that will never complete
                msg.state = ReqState.FAILED
                self.ops_failed += 1
                msg.fwd_done = True
                self.queue_wait.remove(msg)

    def _bc_forward_only(self, msg: _Msg) -> None:
        """Forward a duplicate store-and-forward frame along the overlay
        without any local processing/delivery; the wait-only queue frees
        it once the sends complete."""
        origin = msg.frame.origin
        raw = None
        for dst in self._fwd_targets(origin, msg.src):
            if raw is None:
                raw = msg.frame.encode()
            msg.send_handles.append(self._send_raw(dst, msg.tag, raw))
        self.queue_wait.append(msg)

    def _bcast_is_dup(self, msg: _Msg) -> bool:
        """Exactly-once receipt check for Tag.BCAST frames, keyed on
        (origin, seq). The initiator never delivers its own broadcast,
        so a re-flooded copy of my own frame is also a duplicate."""
        origin, seq = msg.frame.origin, msg.frame.vote
        if origin == self.rank:
            return True
        if seq < 0:
            return False  # unstamped (foreign/legacy frame): best-effort
        return self._window_record(
            self._seen_bcast.setdefault(origin, [-1, set()]), seq)

    # -- broadcast forwarding (~_bc_forward, rootless_ops.c:1104-1225) ----
    def _bc_forward(self, msg: _Msg) -> int:
        origin = msg.frame.origin
        targets = self._fwd_targets(origin, msg.src)
        raw = None
        for dst in targets:
            if raw is None:
                raw = msg.frame.encode()
            msg.send_handles.append(self._send_raw(dst, msg.tag, raw))
        # receipt+forward step — emitted even for leaf receipts (zero
        # targets) so the timeline merger always has a receive-side
        # anchor carrying (origin, identity, immediate sender)
        if TRACER.enabled:
            TRACER.emit(self.rank, Ev.BCAST_FWD, msg.tag, origin,
                        _trace_ident(msg.tag, msg.frame), msg.src)

        if msg.tag == Tag.IAR_PROPOSAL:
            # proposals are engine-internal: parked for the decision, never
            # user-visible (make_progress_gen :591-596)
            self.queue_iar_pending.append(msg)
        elif msg.tag == Tag.IAR_DECISION:
            # decision delivery handled by _on_decision
            pass
        else:
            if targets:
                self.queue_wait_and_pickup.append(msg)
            else:
                msg.fwd_done = True
                self.queue_pickup.append(msg)
        return len(targets)

    # -- IAR handlers (~rootless_ops.c:668-859) ---------------------------
    def _judge(self, payload: bytes, pid: int) -> int:
        if self.judge_cb is None:
            verdict = 1
        else:
            verdict = int(self.judge_cb(payload, self.app_ctx))
        TRACER.emit(self.rank, Ev.JUDGE, pid, verdict)
        return verdict

    def _vote_back(self, ps: ProposalState, vote: int) -> None:
        """Send my (merged) vote to the rank I got the proposal from
        (~_vote_back :728-741, nonblocking here). The payload echoes the
        round generation so a stale vote from an earlier same-pid round
        can never be counted into a later one."""
        frame = Frame(origin=self.rank, pid=ps.pid, vote=int(vote),
                      payload=struct.pack("<i", ps.gen))
        self._send(ps.recv_from, int(Tag.IAR_VOTE), frame)
        TRACER.emit(self.rank, Ev.VOTE, ps.pid, int(vote), ps.gen)

    def _resolve_relay(self, ps: ProposalState) -> None:
        """The relay's merged vote is final: send it to the vote-tree
        parent AND to every duplicate parent acquired from re-formed
        overlay trees. Sending one merged verdict everywhere (instead
        of an interim verdict at duplicate-arrival time) is what
        guarantees a subtree veto can never be lost when the original
        parent is the dead rank that triggered the view change
        (round-2 advisor finding: the optimistic interim vote approved
        a round whose veto went to a blackhole)."""
        ps.resolved = True
        self._vote_back(ps, ps.vote)
        for dp in ps.dup_parents:
            self._vote_back(ProposalState(pid=ps.pid, gen=ps.gen,
                                          recv_from=dp), ps.vote)
        ps.dup_parents.clear()

    def _on_proposal(self, msg: _Msg) -> None:
        """~_iar_proposal_handler (:668-726)."""
        origin = msg.frame.origin
        # duplicate across a view change (mixed old/new overlay trees):
        # never re-judge or re-park — a second ProposalState voting to a
        # second parent would corrupt the vote accounting. Forward for
        # coverage (a descendant may be reachable only via this tree).
        # A PENDING duplicate's sender is a live relay awaiting my vote
        # (its await_from was built from its own forward list), so it
        # must eventually hear from me — but my subtree's veto may
        # still be in flight, so an interim verdict could approve a
        # round a live rank vetoed. Resolved round: the merged vote is
        # final, send it now. Unresolved: record the sender as a
        # duplicate parent; _resolve_relay sends it the merged vote.
        # A SETTLED duplicate needs no vote (the decision already
        # broadcast; on_decision frees the sender's pending state).
        gen = msg.frame.vote
        pending = self._find_proposal_msg(msg.frame.pid, gen)
        if pending is not None or (msg.frame.pid, gen) in \
                self._settled_set:
            if pending is not None:
                ps = pending.prop_state
                if msg.src != ps.recv_from and \
                        msg.src not in ps.dup_parents:
                    if ps.resolved:
                        self._vote_back(
                            ProposalState(pid=ps.pid, gen=gen,
                                          recv_from=msg.src), ps.vote)
                    else:
                        ps.dup_parents.append(msg.src)
            self._bc_forward_only(msg)
            return
        if (self.my_own_proposal.state == ReqState.IN_PROGRESS
                and msg.frame.pid == self.my_own_proposal.pid):
            # pid collision with my active proposal — the reference only
            # printf-warns here (rootless_ops.c:690-692) and then corrupts
            # vote accounting; fail loudly instead
            raise RuntimeError(
                f"rank {self.rank}: received a proposal with the pid of my "
                f"own active proposal ({msg.frame.pid}); pids must be "
                f"unique across concurrent proposers")
        # equal to _bc_forward's target list by construction, including
        # after elastic re-forming (~fwd_send_cnt :1559)
        children = list(self._fwd_targets(origin, msg.src))
        ps = ProposalState(
            pid=msg.frame.pid,
            gen=msg.frame.vote,  # round generation (see submit_proposal)
            recv_from=msg.src,
            state=ReqState.IN_PROGRESS,
            proposal_payload=msg.frame.payload,
            votes_needed=len(children),
            await_from=children,
        )
        msg.prop_state = ps
        judgment = self._judge(msg.frame.payload, ps.pid)
        if judgment == 0:
            # decline: vote NO to parent immediately, do not forward —
            # the subtree below never sees the proposal, only the
            # decision. Parked anyway (resolved, vote 0) so duplicates
            # from re-formed trees find the verdict instead of
            # re-judging, and an approved decision (possible when this
            # veto was discounted with a dead subtree) still fires the
            # action callback here like everywhere else. The children
            # never saw the proposal: clear the await list so a later
            # child failure cannot re-trigger resolution (C mirror
            # zeroes n_await the same way)
            ps.vote = 0
            ps.votes_needed = 0
            ps.await_from = []
            self._resolve_relay(ps)
            self.queue_iar_pending.append(msg)
        else:
            sent = self._bc_forward(msg)  # parks msg in queue_iar_pending
            if sent == 0:
                self._resolve_relay(ps)  # leaf: merged vote == my own

    def _on_vote(self, msg: _Msg) -> None:
        """~_iar_vote_handler (:743-812). Votes AND-merge upward."""
        pid, vote = msg.frame.pid, msg.frame.vote
        gen = struct.unpack_from("<i", msg.frame.payload)[0] \
            if len(msg.frame.payload) >= 4 else -1
        p = self.my_own_proposal
        # claim the vote for my own proposal ONLY while it is in
        # progress AND the generations match: a later proposer may
        # legitimately reuse this pid (collisions are only forbidden
        # between CONCURRENT proposals), and a stale vote from an
        # earlier same-pid round must never merge into a newer one
        if pid == p.pid and p.state == ReqState.IN_PROGRESS \
                and gen == p.gen:
            # only votes from children still awaited count: a vote from
            # a discounted (suspected-dead) child must not advance the
            # count past a live child's pending veto
            if msg.src not in p.await_from:
                return
            p.await_from.remove(msg.src)
            p.votes_recved += 1
            p.vote &= vote
            if p.votes_recved == p.votes_needed:
                self._complete_own_proposal(p)
            return
        # vote for a proposal I'm relaying — matched on (pid, gen) so
        # two queued rounds reusing one pid can never shadow each other
        pm = self._find_proposal_msg(pid, gen)
        if pm is None:
            if (pid == p.pid and p.state != ReqState.INVALID) or \
                    (pid, gen) in self._settled_set or \
                    self.failure_timeout is not None or self.failed:
                # stale round / settled-or-aborted round / view change
                return
            raise RuntimeError(
                f"rank {self.rank}: vote for unknown proposal pid={pid}")
        ps = pm.prop_state
        if msg.src not in ps.await_from:
            return  # late/duplicate vote from a discounted child
        ps.await_from.remove(msg.src)
        ps.vote &= vote
        ps.votes_recved += 1
        if ps.votes_recved == ps.votes_needed:
            self._resolve_relay(ps)

    def _complete_own_proposal(self, p: ProposalState) -> None:
        if p.vote:
            # re-judge own proposal: a competing proposal may have
            # changed the app state since submission (:773)
            p.vote = self._judge(self.my_proposal_payload, p.pid)
        self._decision_bcast(p)

    def _decision_bcast(self, p: ProposalState) -> None:
        """Proposer broadcasts the final decision (~_iar_decision_bcast
        :908-917) — a regular rootless broadcast with the decision in the
        vote field and the round generation in the payload."""
        msg = self.bcast(struct.pack("<i", p.gen), tag=Tag.IAR_DECISION,
                         pid=p.pid, vote=p.vote)
        p.decision_handles = list(msg.send_handles)
        p.decision_pending = True
        TRACER.emit(self.rank, Ev.DECISION, p.pid, p.vote, p.gen)

    def _abort_own_proposal(self, p: ProposalState) -> None:
        """Deadline expired with votes still outstanding: the round
        FAILS deterministically. Mark FAILED (finally assigning the
        reference's dead RLO_FAILED for timeouts, not only dead
        proposers), then broadcast a rootless ABORT over the overlay so
        every relay unparks the round and the app learns the failure
        from pickup instead of hanging. Composes with elastic re-form:
        the pid is immediately free to resubmit on the survivor
        topology."""
        p.state = ReqState.FAILED
        self.ops_failed += 1
        self._prop_born = None  # resolve latency tracks successes only
        TRACER.emit(self.rank, Ev.DECISION, p.pid, -1, p.gen)
        self.bcast(struct.pack("<i", p.gen), tag=Tag.ABORT, pid=p.pid)

    def _on_abort(self, msg: _Msg) -> None:
        """A proposer gave up on a round (deadline expiry): unpark the
        relayed proposal as FAILED, settle the (pid, gen) so late
        duplicates of the proposal are never re-parked, forward along
        the overlay, and deliver the abort notice to the user (pid =
        aborted pid) — the failure is delivered, not hung on."""
        pid = msg.frame.pid
        if msg.frame.origin == self.rank:
            return  # re-flooded copy of my own abort
        gen = struct.unpack_from("<i", msg.frame.payload)[0] \
            if len(msg.frame.payload) >= 4 else -1
        if gen >= 0:
            if (pid, gen) in self._settled_set:
                # duplicate (view-change trees / re-flood): forward for
                # coverage, deliver exactly once
                self._bc_forward_only(msg)
                return
            if len(self._settled_rounds) == self._settled_rounds.maxlen:
                self._settled_set.discard(self._settled_rounds[0])
            self._settled_rounds.append((pid, gen))
            self._settled_set.add((pid, gen))
            self._recent_bcasts.append((int(Tag.ABORT),
                                        msg.frame.encode()))
        pm = self._find_proposal_msg(pid, gen)
        self._bc_forward(msg)  # forwards AND queues the notice for pickup
        if pm is not None:
            pm.prop_state.state = ReqState.FAILED
            self.queue_iar_pending.remove(pm)

    def _on_decision(self, msg: _Msg) -> None:
        """~_iar_decision_handler (:814-859) + forward along the overlay."""
        pid, vote = msg.frame.pid, msg.frame.vote
        if msg.frame.origin == self.rank:
            # a re-flooded copy of my own decision (the proposer learns
            # its decision from the vote merge, never from the wire)
            return
        gen = struct.unpack_from("<i", msg.frame.payload)[0] \
            if len(msg.frame.payload) >= 4 else -1
        if gen >= 0:  # ungenerated (foreign/legacy) frames: best-effort
            if (pid, gen) in self._settled_set:
                # duplicate across a view change: deliver exactly once,
                # but STILL forward — a descendant reachable only
                # through this second tree (its old-view parent died)
                # has no other way to learn the decision
                self._bc_forward_only(msg)
                return
            if len(self._settled_rounds) == self._settled_rounds.maxlen:
                self._settled_set.discard(self._settled_rounds[0])
            self._settled_rounds.append((pid, gen))
            self._settled_set.add((pid, gen))
            # log for view-change re-flooding (decisions must survive
            # the loss of any one relay — parked rounds depend on it)
            self._recent_bcasts.append((int(Tag.IAR_DECISION),
                                        msg.frame.encode()))
        pm = self._find_proposal_msg(pid, gen)
        self._bc_forward(msg)  # forward first; delivery below
        if pm is not None:
            if vote:
                # approved: execute the user action (:842) — on every
                # rank, including one that voted no (its veto may have
                # been discounted along with a dead subtree; agreement
                # means everyone follows the decision)
                if self.action_cb is not None:
                    self.action_cb(pm.prop_state.proposal_payload,
                                   self.app_ctx)
                pm.prop_state.state = ReqState.COMPLETED
            self.queue_iar_pending.remove(pm)
        # deliver the decision to the user either way (:852-854)
        self.queue_pickup.append(msg)

    # ------------------------------------------------------------------
    # Failure detection + elastic re-forming (net-new; the reference
    # defines RLO_FAILED, rootless_ops.h:66, but never assigns it and has
    # no timeouts/retry/rank-failure handling — SURVEY.md §5)
    #
    # Consistency contract: membership changes are NOT view-synchronous,
    # but Tag.BCAST delivery is **exactly-once** across them for any
    # broadcast whose initiator survives:
    #   - at-most-once by construction: every initiated frame carries a
    #     per-origin sequence number and receivers dedup on (origin,
    #     seq) before forwarding or delivering (_bcast_is_dup), so a
    #     broadcast forwarded by a mix of old- and new-topology trees
    #     can never deliver twice;
    #   - at-least-once by re-flooding: on every adopted view change,
    #     each survivor re-sends its recent-broadcast log point-to-point
    #     to every alive rank (_reflood_recent_bcasts), plugging the
    #     forwarding holes a dead relay left; the dedup layer absorbs
    #     the duplication this creates.
    # Bounds on the at-least-once leg (at-most-once is unconditional):
    #   - the re-flood log keeps the most recent 64 frames per rank
    #     (_recent_bcasts maxlen); a broadcast older than that at every
    #     survivor when the view change lands cannot be re-flooded —
    #     with >64 broadcasts outstanding per rank across a failure,
    #     delivery degrades to at-most-once for the evicted ones;
    #   - broadcasts whose *initiator* died mid-send are at-most-once
    #     (a frame the origin never handed any survivor is gone).
    # Consensus traffic is exactly-once too: duplicate proposals are
    # never re-judged (a pending duplicate's new parent receives the
    # accumulated verdict so its round stays live), duplicate
    # decisions deliver/act once per (pid, gen) while still forwarding
    # for coverage, and vote accounting uses (pid, generation)
    # matching + failure discounting throughout.
    # ------------------------------------------------------------------
    def _cur_initiator_targets(self):
        """Initiator send list over the current alive set. Identity to the
        static topology while nothing has failed."""
        if self.fanout == "flat":
            # depth-1 tree: everyone alive, directly (see __init__)
            return tuple(r for r in self._alive if r != self.rank)
        if not self.failed:
            return self.initiator_targets
        alive = self._alive
        if len(alive) < 2:
            return ()
        vt = topology.initiator_targets(len(alive), self._v[self.rank])
        return tuple(alive[v] for v in vt)

    def _fwd_targets(self, origin: int, src: int):
        """Forward targets over the current alive set. Messages routed by
        a pre-failure view (dead origin/sender) are delivered locally but
        not re-forwarded — survivors re-broadcast if they need fan-out."""
        if self.fanout == "flat":
            return ()  # the origin reached everyone; deliver only
        if not self.failed:
            return topology.fwd_targets(self.world_size, self.rank,
                                        origin, src)
        if origin in self.failed or src in self.failed:
            return ()
        alive = self._alive
        if len(alive) < 2:
            return ()
        vt = topology.fwd_targets(len(alive), self._v[self.rank],
                                  self._v[origin], self._v[src])
        return tuple(alive[v] for v in vt)

    def _ring_neighbors(self):
        alive = self._alive
        i = alive.index(self.rank)
        return alive[(i + 1) % len(alive)], alive[(i - 1) % len(alive)]

    def _failure_tick(self) -> None:
        if len(self._alive) < 2:
            return
        now = self.clock()
        succ, pred = self._ring_neighbors()
        if now - self._hb_last_sent >= self.heartbeat_interval:
            # piggyback the cumulative link ACK for the successor: even
            # with no reverse data traffic, its retransmit queue to us
            # drains at heartbeat cadence
            hb_payload = (struct.pack("<i", self._rx_cum(succ))
                          if self.arq_rto is not None else b"")
            frame = Frame(origin=self.rank, payload=hb_payload)
            self._isend_counted(succ, int(Tag.HEARTBEAT), frame.encode())
            self._hb_last_sent = now
            TRACER.emit(self.rank, Ev.HEARTBEAT, succ)
        seen = self._hb_seen.setdefault(pred, now)  # grace on first watch
        if now - seen > self.failure_timeout:
            self._declare_failed(pred)

    def _declare_failed(self, rank: int) -> None:
        """Local detection: mark, then tell the world — the failure notice
        rides the rootless broadcast overlay AND goes point-to-point to
        every alive rank (belt and braces: overlay forwarding can have
        holes while membership views are still converging; duplicate
        notices are suppressed at the receiver)."""
        # capture the evidence BEFORE _mark_failed clears the slot: the
        # last-seen heartbeat age is what makes a false-positive
        # declaration diagnosable after the fact
        seen = self._hb_seen.get(rank)
        age = (self.clock() - seen) if seen is not None else float("inf")
        if not self._mark_failed(rank):
            return
        age_usec = (min(int(age * 1e6), 2**31 - 1)
                    if age != float("inf") else 2**31 - 1)
        logger.warning(
            "rank %d declaring rank %d FAILED: no heartbeat for "
            "%.1f ms (timeout %.1f ms, interval %.1f ms, alive now %s)",
            self.rank, rank, age * 1e3, self.failure_timeout * 1e3,
            self.heartbeat_interval * 1e3, self._alive)
        TRACER.emit(self.rank, Ev.FAILURE, rank, 1, age_usec)
        self.bcast(b"", tag=Tag.FAILURE, pid=rank)
        frame = Frame(origin=self.rank, pid=rank)
        raw = frame.encode()
        for dst in self._alive:
            if dst != self.rank:
                self._send_raw(dst, int(Tag.FAILURE), raw)
        if self.failure_cb is not None:
            self.failure_cb(rank, True)

    def _on_failure(self, msg: _Msg) -> None:
        """A FAILURE notification arrived: adopt the new membership BEFORE
        forwarding so the whole propagation runs on the survivor overlay,
        then deliver the notice to the user (pid = failed rank).
        Duplicates (the notice floods: overlay + direct sends) are
        dropped entirely — each failure is delivered exactly once."""
        rank = msg.frame.pid
        if rank == self.rank:
            # somebody suspects me — a false positive from delays; there
            # is no un-fail protocol (matching the reference's absence of
            # recovery), so just record it for the application
            if not self.suspected_self:
                self.suspected_self = True
                self._bc_forward(msg)
            return
        fresh = self._mark_failed(rank)
        if not fresh:
            return  # already known: suppress the duplicate
        TRACER.emit(self.rank, Ev.FAILURE, rank, 0)
        self._bc_forward(msg)
        if self.failure_cb is not None:
            self.failure_cb(rank, False)

    def _mark_failed(self, rank: int) -> bool:
        """Adopt a failure into the membership view; returns False if it
        was already known (idempotent). Re-forms the virtual topology over
        the survivors — the elastic-recovery step."""
        if rank in self.failed or rank == self.rank or not (
                0 <= rank < self.world_size):
            return False
        old_pred = (self._ring_neighbors()[1]
                    if self.failure_timeout is not None
                    and len(self._alive) >= 2 else None)
        self.failed.add(rank)
        self._alive = [r for r in self._alive if r != rank]
        self._v = {r: v for v, r in enumerate(self._alive)}
        self._hb_seen.pop(rank, None)
        # ARQ: a dead peer will never ack — stop retransmitting at it
        # (and stop owing it acks or skip notices)
        self._tx_unacked.pop(rank, None)
        self._tx_skip.pop(rank, None)
        self._ack_due.discard(rank)
        if self.failure_timeout is not None and len(self._alive) >= 2:
            # fresh grace period — but only when my predecessor actually
            # changed; re-arming an unchanged predecessor's timer on every
            # learned failure would let a correlated multi-failure defer
            # detection of an already-silent peer indefinitely
            _, pred = self._ring_neighbors()
            if pred != old_pred:
                self._hb_seen[pred] = self.clock()
        self._discount_failed_voter(rank)
        self._abort_orphaned_proposals(rank)
        self._reflood_recent_bcasts()
        return True

    def _reflood_recent_bcasts(self) -> None:
        """Plug forwarding holes a dead relay left: re-send every recent
        BCAST and IAR_DECISION frame this rank initiated or forwarded,
        point-to-point to every alive rank. Receivers drop the
        duplicates ((origin, seq) for broadcasts, the settled (pid,
        gen) ring for decisions) — together the flood + dedup upgrade
        delivery across view changes to exactly-once for any initiator
        that survived. Covering decisions is what lets parent-died
        relayed rounds stay parked (see _abort_orphaned_proposals): the
        decision that clears them survives the loss of any one relay."""
        for tag, raw in list(self._recent_bcasts):
            for dst in self._alive:
                if dst != self.rank:
                    # through the ARQ gate: the re-flood gets FRESH
                    # link seqs (it is a new transmission, not a
                    # retransmit); app-level dedup absorbs the copies
                    self._send_raw(dst, tag, raw)

    def _discount_failed_voter(self, rank: int) -> None:
        """A consensus participant died mid-round: its subtree's merged
        vote will never arrive (sends to it blackhole). Discount it from
        every pending proposal — a dead rank cannot veto — and complete
        rounds that were only waiting on it."""
        p = self.my_own_proposal
        if (p.state == ReqState.IN_PROGRESS and rank in p.await_from
                and not p.decision_pending):
            p.await_from.remove(rank)
            p.votes_needed -= 1
            if p.votes_recved == p.votes_needed:
                self._complete_own_proposal(p)
        for pm in list(self.queue_iar_pending):
            ps = pm.prop_state
            if ps is not None and rank in ps.await_from:
                ps.await_from.remove(rank)
                ps.votes_needed -= 1
                if ps.votes_recved == ps.votes_needed:
                    self._resolve_relay(ps)

    def _abort_orphaned_proposals(self, rank: int) -> None:
        """Relayed proposals whose PROPOSER is the dead rank can never
        resolve (the decision will never be broadcast): mark them FAILED
        and unpark them, so the engine is checkpointable again and the
        pid is freed. This is the one place the rebuild assigns the
        reference's otherwise-dead RLO_FAILED state (rootless_ops.h:66).

        Rounds whose vote-tree PARENT died stay parked: the surviving
        proposer discounts the dead subtree and still broadcasts a
        decision, which reaches this rank through the re-formed overlay
        and clears the round (with the action callback) exactly like a
        healthy one. Keeping the round alive also preserves the child
        votes already merged into it, so a duplicate proposal from the
        new tree collects the true subtree verdict instead of a vote
        reconstructed from partial state (round-2 advisor finding)."""
        for pm in list(self.queue_iar_pending):
            ps = pm.prop_state
            if ps is None:
                continue
            if pm.frame.origin == rank:
                ps.state = ReqState.FAILED
                self.queue_iar_pending.remove(pm)

    def _on_other(self, msg: _Msg) -> None:
        """Unknown/aux tags go straight to pickup (reference prints and
        drops, :617-620; delivering is strictly more useful)."""
        msg.fwd_done = True
        self.queue_pickup.append(msg)

    def _find_proposal_msg(self, pid: int, gen: int) -> Optional[_Msg]:
        """~_find_proposal_msg (:1036-1053), extended to match on
        (pid, generation) so rounds reusing a pid never shadow each
        other in the pending queue."""
        for m in self.queue_iar_pending:
            if m.prop_state is not None and m.prop_state.pid == pid \
                    and m.prop_state.gen == gen:
                return m
        return None

    # ------------------------------------------------------------------
    # Teardown (~RLO_progress_engine_cleanup, rootless_ops.c:1606-1647)
    # ------------------------------------------------------------------
    def idle(self) -> bool:
        """No pending forwards or undelivered internal work on this
        engine. With ARQ enabled, unacked reliable frames count as
        outstanding work: an idle engine's sends are not just handed to
        the transport but acknowledged delivered (or given up on)."""
        return (not self.queue_wait and not self.queue_wait_and_pickup
                and not self.my_own_proposal.decision_pending
                and (self.arq_rto is None or self.arq_unacked() == 0))

    def cleanup(self) -> None:
        self.manager.remove(self)


def drain(worlds, engines, max_spins: int = 100_000) -> None:
    """Progress until every transport world is quiescent and every engine's
    outbound work is complete — the loopback analogue of the reference's
    termination-detection drain (MPI_Iallreduce over bcast counts + spin,
    rootless_ops.c:1613-1625)."""
    managers = []
    for e in engines:
        if e.manager not in managers:
            managers.append(e.manager)
    for _ in range(max_spins):
        # drive through the managers so the re-entrancy guard covers
        # handler-initiated broadcasts (e.g. the decision bcast)
        for m in managers:
            m.progress_all()
        if all(w.quiescent() for w in worlds) and all(
                e.idle() for e in engines):
            return
    raise RuntimeError("drain did not reach quiescence")
